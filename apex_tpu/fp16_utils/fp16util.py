"""Legacy fp16 helpers — ref: apex/fp16_utils/fp16util.py.

These pre-amp utilities are aliases over the single master-weights engine
(SURVEY.md §3.3: "provide ONE master-weights engine and alias both API styles
onto it"). Trees replace module/parameter lists.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.amp.policy import default_keep_fp32_predicate
from apex_tpu.utils.pytree import path_str, tree_cast, tree_cast_where


def network_to_half(params, half_dtype=jnp.float16):
    """Cast floating params to half, keeping batchnorm-looking leaves fp32
    (ref: network_to_half + BN_convert_float)."""
    return tree_cast_where(params, half_dtype, default_keep_fp32_predicate)


def BN_convert_float(params):
    """Force batchnorm-looking leaves back to fp32 (ref: BN_convert_float)."""

    def _conv(path, x):
        x = jnp.asarray(x)
        if jnp.issubdtype(x.dtype, jnp.floating) and default_keep_fp32_predicate(
            path_str(path)
        ):
            return x.astype(jnp.float32)
        return x

    return jax.tree_util.tree_map_with_path(_conv, params)


def prep_param_lists(params):
    """Returns (model_params, master_params): the fp32 master copy of a half
    tree (ref: prep_param_lists, flat_master unsupported — XLA has no use for
    a flat buffer)."""
    return params, tree_cast(params, jnp.float32)


def master_params_to_model_params(model_params, master_params):
    """Cast master values into the model tree's dtypes (ref name preserved)."""
    return jax.tree.map(
        lambda p, m: m.astype(jnp.asarray(p).dtype), model_params, master_params
    )


def model_grads_to_master_grads(model_grads):
    """Upcast half grads to fp32 masters (ref name preserved)."""
    return tree_cast(model_grads, jnp.float32)
