"""FP16_Optimizer — ref: apex/fp16_utils/fp16_optimizer.py.

The pre-amp master-weight wrapper (``backward(loss)`` + ``step()`` with
static or dynamic loss scale). Aliased onto the amp engine: this class wraps
an apex_tpu stateful optimizer with an :class:`apex_tpu.amp.AmpOptimizer`
configured for O2-style master weights.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.amp.frontend import AmpOptimizer
from apex_tpu.amp.policy import Policy
from apex_tpu.amp.scaler import LossScaler


class FP16_Optimizer:
    """Legacy API: ``opt = FP16_Optimizer(inner, static_loss_scale=128)``;
    ``scaled = opt.scale_loss(loss)``; ``opt.step(grads)``.

    ``inner`` is an apex_tpu stateful optimizer (e.g. ``FusedAdam``) holding
    half params; this wrapper owns fp32 masters + the scaler.
    """

    def __init__(
        self,
        init_optimizer,
        static_loss_scale=1.0,
        dynamic_loss_scale=False,
        dynamic_loss_args=None,
        verbose=False,
    ):
        self.inner = init_optimizer
        if dynamic_loss_scale:
            # translate legacy kwarg names (scale_factor/scale_window) onto
            # the engine's (growth_factor, backoff_factor, growth_interval)
            legacy = dict(dynamic_loss_args or {})
            kwargs = {}
            if "init_scale" in legacy:
                kwargs["init_scale"] = float(legacy.pop("init_scale"))
            if "scale_factor" in legacy:
                f = float(legacy.pop("scale_factor"))
                kwargs["growth_factor"] = f
                kwargs["backoff_factor"] = 1.0 / f
            if "scale_window" in legacy:
                kwargs["growth_interval"] = int(legacy.pop("scale_window"))
            kwargs.update(legacy)  # engine-native names pass through
            scaler = LossScaler(dynamic=True, **kwargs)
            loss_scale = "dynamic"
        else:
            scaler = LossScaler(init_scale=float(static_loss_scale), dynamic=False)
            loss_scale = float(static_loss_scale)
        policy = Policy.from_opt_level("O2", loss_scale=loss_scale)
        self._amp = AmpOptimizer(tx=init_optimizer.tx, policy=policy, scaler=scaler)
        self.state = self._amp.init(self.inner.params)
        if verbose:
            print(f"FP16_Optimizer: loss_scale={loss_scale}")

        @jax.jit
        def _apply(grads, state, params):
            return self._amp.apply_gradients(grads, state, params)

        self._apply = _apply

    @property
    def loss_scale(self):
        return float(self.state.scaler.scale)

    def scale_loss(self, loss):
        return (loss.astype(jnp.float32) * self.state.scaler.scale).astype(loss.dtype)

    # legacy name: backward(loss) computed grads; functional JAX computes
    # grads outside, so step takes them directly.
    def step(self, grads):
        self.inner.params, self.state = self._apply(
            grads, self.state, self.inner.params
        )
        return self.inner.params

    def zero_grad(self):
        pass

    def state_dict(self):
        """Full resume state: fp32 masters, live inner optax state, scaler,
        and the half params (ref FP16_Optimizer.state_dict saves the same
        set: optimizer state + fp32_from_fp16 groups + scaler fields)."""
        return {
            "amp_state": self.state,          # AmpOptState: inner/master/scaler
            "params": self.inner.params,      # half model params
        }

    def load_state_dict(self, d):
        self.state = d["amp_state"]
        self.inner.params = d["params"]
