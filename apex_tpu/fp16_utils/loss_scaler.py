"""Legacy loss scalers — ref: apex/fp16_utils/loss_scaler.py.

Aliases onto the single scaler engine (apex_tpu.amp.scaler): ``LossScaler``
is the static variant, ``DynamicLossScaler`` the dynamic one, with the
legacy attribute names preserved.
"""

from __future__ import annotations

from apex_tpu.amp.scaler import LossScaler as _Engine
from apex_tpu.utils.pytree import tree_all_finite


class LossScaler:
    """Static loss scaler (legacy API: .loss_scale, .scale_gradient)."""

    def __init__(self, scale=1.0):
        self._engine = _Engine(init_scale=float(scale), dynamic=False)
        self.state = self._engine.init()

    @property
    def loss_scale(self):
        return float(self.state.scale)

    def scale_loss(self, loss):
        return self._engine.scale_loss(self.state, loss)

    def unscale(self, grads):
        g32, _ = self._engine.unscale(self.state, grads)
        return g32

    @staticmethod
    def has_inf_or_nan(tree) -> bool:
        return not bool(tree_all_finite(tree))

    def update_scale(self, overflow: bool) -> None:
        pass  # static


class DynamicLossScaler(LossScaler):
    """Dynamic loss scaler (legacy API; 2x growth / 0.5x backoff)."""

    def __init__(self, init_scale=2.0 ** 32, scale_factor=2.0, scale_window=1000):
        self._engine = _Engine(
            init_scale=float(init_scale),
            growth_factor=float(scale_factor),
            backoff_factor=1.0 / float(scale_factor),
            growth_interval=int(scale_window),
            dynamic=True,
        )
        self.state = self._engine.init()

    def update_scale(self, overflow: bool) -> None:
        import jax.numpy as jnp

        self.state = self._engine.update(self.state, jnp.bool_(overflow))
