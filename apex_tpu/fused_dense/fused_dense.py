"""FusedDense / FusedDenseGeluDense — ref: apex/fused_dense/fused_dense.py
(+ csrc/fused_dense_cuda.cu using cublasLt GELU_AUX epilogues).

On TPU, bias and GELU epilogues fuse into the MXU matmul under XLA; the value
of these wrappers is API parity with the reference while letting the compiler
do the scheduling.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

try:
    import flax.linen as nn

    _HAVE_FLAX = True
except ImportError:  # pragma: no cover
    _HAVE_FLAX = False


def fused_dense(x, kernel, bias=None):
    """y = x @ kernel + bias (bias fused into the matmul epilogue by XLA)."""
    y = x @ kernel
    if bias is not None:
        y = y + bias
    return y


def fused_dense_gelu_dense(x, kernel1, bias1, kernel2, bias2):
    """linear+bias+gelu+linear+bias, the reference's cublasLt-epilogue chain.

    GELU uses the tanh approximation, matching the reference's CUDA epilogue.
    """
    h = x @ kernel1 + bias1
    h = jax.nn.gelu(h, approximate=True)
    return h @ kernel2 + bias2


if _HAVE_FLAX:

    class FusedDense(nn.Module):
        """Drop-in Dense with fused bias epilogue (ref: FusedDense)."""

        features: int
        use_bias: bool = True
        dtype: object = jnp.float32

        @nn.compact
        def __call__(self, x):
            return nn.Dense(
                self.features, use_bias=self.use_bias, dtype=self.dtype
            )(x)

    class FusedDenseGeluDense(nn.Module):
        """linear+gelu+linear chain (ref: FusedDenseGeluDense)."""

        intermediate_features: int
        out_features: int
        use_bias: bool = True
        dtype: object = jnp.float32

        @nn.compact
        def __call__(self, x):
            h = nn.Dense(
                self.intermediate_features, use_bias=self.use_bias, dtype=self.dtype
            )(x)
            h = jax.nn.gelu(h, approximate=True)
            return nn.Dense(
                self.out_features, use_bias=self.use_bias, dtype=self.dtype
            )(h)
