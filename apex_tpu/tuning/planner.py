"""Whole-run auto-parallelism planner (ROADMAP open item 4).

The tuning stack picks kernel block sizes; this module picks the RUN
configuration. Given a model shape and a device count it searches every
valid factorization of the devices into (dp x tp x pp x ep), each ZeRO
stage, and each comm-gate setting (``APEX_TPU_QUANTIZED_COMMS`` /
``APEX_TPU_OVERLAP_TP`` / ``APEX_TPU_ZERO_PREFETCH``), scores each
candidate with a per-config step-time projection, filters the ranked
list through the static per-device peak-HBM estimator, and emits
:class:`Plan` records (mesh axes, PartitionSpecs, env-gate dict,
projected step time + breakdown, projected peak HBM). Grounded in
"AMP: Automatically Finding Model Parallel Strategies" (PAPERS.md).

The projection composes three existing models — nothing here invents a
second definition of anything:

* **compute** — the FLOP/byte roofline of ``tuning/cost_model.py``
  (``device_spec`` peak + ``flash_flops``), per microbatch per stage,
  times the microbatch count, times the 1F1B bubble term
  ``1 + (pp-1)/M``;
* **comm** — ``tuning/comm_model.py``: DP gradient allreduce (exact vs
  int8-quantized, the PR-5 ``quantized_wire_bytes`` formulas verbatim),
  TP sequence-parallel layer collectives (overlapped vs monolithic per
  the overlap gate, chunk count from
  ``cost_model.overlap_chunks_default``), EP all_to_alls, ZeRO
  scatter/gather (+ prefetch overlap credit), and the pipeline p2p
  ring hops;
* **memory** — ``cost_model.estimate_peak_hbm`` (= analysis/memory.py)
  over a traced per-device microbatch train step built from the SAME
  per-device parameter tree the wire-byte formulas count, plus a
  min(pp, M)-deep in-flight activation buffer (the 1F1B residency cap).
  The budget reuses ``APEX_TPU_ANALYSIS_HBM_GB`` semantics, defaulting
  to the device kind's HBM capacity.

``python -m apex_tpu.tuning.planner`` is the CLI (JSON output;
``--execute`` runs the dryrun leg). :func:`execute_plan` EXECUTES a
plan on a host mesh: builds the mesh, applies the gates, runs real
steps, checks loss/grad parity against the unplanned single-device
reference — including the numeric pp path, driving
``fwd_bwd_pipelining_without_interleaving`` (+ the interleaved
schedule) against ``fwd_bwd_no_pipelining`` — and refuses to report a
plan valid before its traced entry point passes the APX2xx/4xx/5xx
auditors. Projected vs measured step times land on the
``tuning/plan_*`` gauges.

Like every perf claim in this repo, the model is structured to
re-measure the day a TPU shows up: the cost constants live in ONE
table (cost_model.DEVICE_SPECS), the wire bytes are the observability
formulas, and the executed leg reports projected-vs-measured so drift
is a number, not a vibe.
"""

from __future__ import annotations

import dataclasses
import json
import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from apex_tpu.tuning import comm_model, cost_model
from apex_tpu.utils.envvars import env_float

__all__ = [
    "ModelShape", "Plan", "PlanConfig", "enumerate_configs",
    "estimate_config_peak", "execute_plan", "local_param_elems",
    "plan", "project", "shape_by_name", "transformer_config",
]

GiB = float(2 ** 30)

# fwd + bwd cost multiple of one forward pass (bwd ~ 2x fwd)
_FWD_BWD = 3.0
# sequence-parallel layer collectives per transformer block per
# microbatch, forward AND backward: 2 all_gathers + 2 reduce_scatters
# forward (attention + MLP column inputs / row outputs), mirrored by
# the backward's transposes
_TP_COLLS_PER_LAYER = 8
# EP all_to_alls per MoE block per microbatch (dispatch + return,
# forward and backward)
_EP_A2A_PER_LAYER = 4


# ---------------------------------------------------------------------------
# model shapes
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ModelShape:
    """The planner's view of a training run: transformer geometry +
    global batch (sequences) + compute itemsize. ``ffn=None`` means the
    standard 4*hidden; ``experts=0`` is a dense model."""

    name: str
    vocab: int
    seq: int
    hidden: int
    layers: int
    heads: int
    global_batch: int
    ffn: Optional[int] = None
    experts: int = 0
    top_k: int = 2
    dtype_bytes: int = 2  # bf16 compute

    @property
    def ffn_width(self) -> int:
        return self.ffn if self.ffn else 4 * self.hidden

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads


# the bench flagships (models/configs.py geometry) + the CPU-mesh toy
# every dryrun/test leg plans and executes
_SHAPES = {
    "toy": ModelShape("toy", vocab=128, seq=32, hidden=32, layers=4,
                      heads=4, global_batch=8),
    "bert-large": ModelShape("bert-large", vocab=30528, seq=512,
                             hidden=1024, layers=24, heads=16,
                             global_batch=128),
    "gpt-medium": ModelShape("gpt-medium", vocab=50304, seq=1024,
                             hidden=1024, layers=24, heads=16,
                             global_batch=64),
}


def shape_by_name(name: str) -> ModelShape:
    if name not in _SHAPES:
        raise ValueError(
            f"unknown model shape {name!r} (known: {sorted(_SHAPES)})")
    return _SHAPES[name]


def transformer_config(shape: ModelShape, *, tp: int = 1, dtype=None):
    """The testing-flagship TransformerConfig matching a shape — the
    executed leg's model (apex_tpu.testing.standalone_transformer)."""
    import jax.numpy as jnp

    from apex_tpu.testing import TransformerConfig

    return TransformerConfig(
        vocab_size=shape.vocab, seq_len=shape.seq, hidden=shape.hidden,
        layers=shape.layers, heads=shape.heads, causal=True,
        sequence_parallel=tp > 1, dtype=dtype or jnp.float32,
    )


# ---------------------------------------------------------------------------
# configurations
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class PlanConfig:
    """One point of the search space: the mesh factorization, the ZeRO
    stage, the microbatch count, and the comm-gate settings."""

    dp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1
    zero: int = 0            # 0 = DDP, 2 = ZeRO-2 (sharded grads+opt)
    microbatches: int = 1
    quantized_comms: bool = False
    overlap_tp: bool = False
    zero_prefetch: bool = False

    @property
    def devices(self) -> int:
        return self.dp * self.tp * self.pp * self.ep

    @property
    def tag(self) -> str:
        gates = "".join(
            f"+{g}" for g, on in (
                ("qcomm", self.quantized_comms),
                ("overlap", self.overlap_tp),
                ("zprefetch", self.zero_prefetch)) if on)
        return (f"dp{self.dp}_tp{self.tp}_pp{self.pp}_ep{self.ep}"
                f"_z{self.zero}_m{self.microbatches}{gates}")

    @property
    def env_gates(self) -> Dict[str, str]:
        """The env dict the executed leg applies — the same levers
        bench.py's +overlap/+qcomm/+zprefetch rungs flip."""
        return {
            "APEX_TPU_QUANTIZED_COMMS":
                "1" if self.quantized_comms else "0",
            "APEX_TPU_OVERLAP_TP": "1" if self.overlap_tp else "0",
            "APEX_TPU_ZERO_PREFETCH": "1" if self.zero_prefetch else "0",
        }


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def _auto_microbatches(b_dp: int, pp: int) -> int:
    """Largest per-dp-rank microbatch count <= 4*pp (the point past
    which the 1F1B bubble credit flattens but the per-tick overhead
    keeps growing) that divides the per-rank batch."""
    cands = [d for d in _divisors(b_dp) if d <= 4 * pp]
    return max(cands) if cands else 1


def enumerate_configs(shape: ModelShape, n_devices: int, *,
                      microbatches: Optional[int] = None
                      ) -> List[PlanConfig]:
    """Every valid (dp, tp, pp, ep, zero, gates) factorization of the
    device count for this shape. Validity = divisibility: tp divides
    heads/hidden/ffn/vocab/seq (SP shards the sequence), pp divides
    layers, ep divides experts (dense models pin ep=1), dp divides the
    global batch, and the microbatch count divides the per-rank
    batch."""
    out: List[PlanConfig] = []
    n = int(n_devices)
    for dp in _divisors(n):
        if shape.global_batch % dp:
            continue
        b_dp = shape.global_batch // dp
        for tp in _divisors(n // dp):
            if (shape.heads % tp or shape.hidden % tp
                    or shape.ffn_width % tp or shape.vocab % tp
                    or shape.seq % tp):
                continue
            for pp in _divisors(n // (dp * tp)):
                if shape.layers % pp:
                    continue
                ep = n // (dp * tp * pp)
                if shape.experts:
                    if shape.experts % ep:
                        continue
                elif ep != 1:
                    continue
                if microbatches is not None:
                    m = int(microbatches)
                    if b_dp % m:
                        continue
                else:
                    m = _auto_microbatches(b_dp, pp)
                if pp > 1 and m < pp:
                    continue  # a pipeline shorter than its depth
                for zero in (0, 2) if dp > 1 else (0,):
                    for qc in (False, True) if dp > 1 else (False,):
                        for ov in (False, True) if tp > 1 else (False,):
                            for zp in ((False, True) if zero else
                                       (False,)):
                                out.append(PlanConfig(
                                    dp=dp, tp=tp, pp=pp, ep=ep,
                                    zero=zero, microbatches=m,
                                    quantized_comms=qc, overlap_tp=ov,
                                    zero_prefetch=zp))
    return out


# ---------------------------------------------------------------------------
# the per-device parameter tree — ONE source of truth for both the
# wire-byte counts and the memory-step trace
# ---------------------------------------------------------------------------

def _param_tree(shape: ModelShape, cfg: PlanConfig, float_dtype=None):
    """Per-device parameter avals (ShapeDtypeStructs — nothing is
    allocated) for one (tp, pp, ep) placement: embedding vocab-split
    over tp, layer stack depth-split over pp, attention/MLP kernels
    column/row-split over tp, experts split over ep."""
    import jax
    import jax.numpy as jnp

    dt = float_dtype or (jnp.bfloat16 if shape.dtype_bytes == 2
                         else jnp.float32)
    H, F = shape.hidden, shape.ffn_width
    L = shape.layers // cfg.pp
    sds = jax.ShapeDtypeStruct
    tree = {
        "emb": sds((shape.vocab // cfg.tp, H), dt),
        "pos": sds((shape.seq, H), dt),
        "ln": sds((L, 4, H), dt),          # ln1/ln2 gamma+beta
        "qkv": sds((L, H, 3 * H // cfg.tp), dt),
        "proj": sds((L, H // cfg.tp, H), dt),
    }
    if shape.experts:
        e_local = shape.experts // cfg.ep
        tree.update({
            "router": sds((L, H, shape.experts), dt),
            "w1": sds((L, e_local, H, F), dt),
            "w2": sds((L, e_local, F, H), dt),
        })
    else:
        tree.update({
            "fc1": sds((L, H, F // cfg.tp), dt),
            "fc2": sds((L, F // cfg.tp, H), dt),
        })
    return tree


def local_param_elems(shape: ModelShape, cfg: PlanConfig) -> int:
    """Per-device parameter count — the payload every DP-path wire
    formula and the ZeRO shard size are computed from."""
    return sum(int(math.prod(s.shape))
               for s in _param_tree(shape, cfg).values())


# ---------------------------------------------------------------------------
# step-time projection
# ---------------------------------------------------------------------------

def project(shape: ModelShape, cfg: PlanConfig,
            device: str = "cpu") -> dict:
    """Projected step time (ms) + breakdown for one configuration.

    Returns ``{"projected_ms", "compute_ms", "tp_ms", "dp_ms",
    "ep_ms", "pp_ms", "bubble_fraction", "wire_bytes": {...}}``. The
    ``wire_bytes`` entries for the DP/ZeRO paths are EXACTLY the PR-5
    observability formulas (comm_model delegations) — pinned by
    tests/L0/test_planner.py."""
    peak, hbm_bw, _ = cost_model.device_spec(device)
    M = cfg.microbatches
    b_dp = shape.global_batch // cfg.dp
    mb = max(1, b_dp // M)
    tokens_mb = mb * shape.seq
    L_local = shape.layers // cfg.pp
    heads_local = max(1, shape.heads // cfg.tp)
    H, F, V = shape.hidden, shape.ffn_width, shape.vocab

    # -- compute: roofline per microbatch per stage --------------------
    attn_lin = 2.0 * tokens_mb * 4 * H * H / cfg.tp
    # causal halves the flash work; one instance per (sequence, head)
    flash = (cost_model.flash_flops(shape.seq, shape.seq, shape.head_dim)
             * heads_local * mb / 2.0)
    if shape.experts:
        mlp = (2.0 * (tokens_mb * shape.top_k / cfg.ep) * 2 * H * F
               + 2.0 * tokens_mb * H * shape.experts)
    else:
        mlp = 2.0 * tokens_mb * 2 * H * F / cfg.tp
    head_f = 2.0 * tokens_mb * H * V / cfg.tp
    stage_flops = (attn_lin + flash + mlp) * L_local + head_f
    n_local = local_param_elems(shape, cfg)
    stage_param_bytes = n_local * shape.dtype_bytes
    t_mb = max(_FWD_BWD * stage_flops / peak,
               _FWD_BWD * stage_param_bytes / hbm_bw)
    bubble = (cfg.pp - 1) / M
    compute_s = t_mb * M * (1.0 + bubble)

    wire: Dict[str, int] = {}

    # -- TP sequence-parallel layer collectives ------------------------
    tp_s = 0.0
    wire["tp"] = 0
    if cfg.tp > 1:
        act_elems = tokens_mb * H
        one = comm_model.all_gather_wire_bytes(act_elems,
                                               shape.dtype_bytes)
        t_one = comm_model.collective_seconds("all_gather", one, cfg.tp,
                                              device)
        if cfg.overlap_tp:
            # decomposed collective matmul: the ring chunks pipeline
            # behind the partial matmuls; exposed time ~ one chunk hop
            chunks = cost_model.overlap_chunks_default(
                max(1, tokens_mb // cfg.tp), cfg.tp)
            t_one = t_one / max(1, chunks)
        tp_s = _TP_COLLS_PER_LAYER * L_local * M * t_one
        wire["tp"] = _TP_COLLS_PER_LAYER * L_local * M * one

    # -- DP gradient sync (DDP psum or ZeRO scatter/gather) ------------
    dp_s = 0.0
    wire["dp_grad"] = 0
    wire["zero_gather"] = 0
    if cfg.dp > 1:
        if cfg.zero:
            rs = comm_model.zero_scatter_wire_bytes(
                n_local, 4, cfg.dp, quantized=cfg.quantized_comms)
            dp_s += comm_model.collective_seconds(
                "reduce_scatter", rs, cfg.dp, device)
            wire["dp_grad"] = rs
            shard = -(-n_local // cfg.dp)
            ag = comm_model.zero_allgather_wire_bytes(shard, 4, cfg.dp)
            # place-in-zeros + psum: lowered as ONE allreduce
            t_ag = comm_model.collective_seconds("psum", ag, cfg.dp,
                                                 device)
            if cfg.zero_prefetch:
                # gather overlapped with the first microbatch forward
                t_ag = max(0.0, t_ag - t_mb / _FWD_BWD)
            dp_s += t_ag
            wire["zero_gather"] = ag
        else:
            ar = comm_model.ddp_psum_wire_bytes(
                n_local, 4, quantized=cfg.quantized_comms)
            dp_s += comm_model.collective_seconds("psum", ar, cfg.dp,
                                                  device)
            wire["dp_grad"] = ar

    # -- EP all_to_alls ------------------------------------------------
    ep_s = 0.0
    wire["ep"] = 0
    if shape.experts and cfg.ep > 1:
        a2a = comm_model.all_to_all_wire_bytes(
            tokens_mb * shape.top_k * H, shape.dtype_bytes)
        ep_s = (_EP_A2A_PER_LAYER * L_local * M
                * comm_model.collective_seconds("all_to_all", a2a,
                                                cfg.ep, device))
        wire["ep"] = _EP_A2A_PER_LAYER * L_local * M * a2a

    # -- pipeline p2p ring hops ---------------------------------------
    pp_s = 0.0
    wire["pp"] = 0
    if cfg.pp > 1:
        hop = comm_model.ppermute_step_wire_bytes(tokens_mb * H,
                                                  shape.dtype_bytes)
        ticks = -(-M // cfg.pp) * cfg.pp + cfg.pp - 1
        pp_s = 2 * ticks * comm_model.collective_seconds(
            "ppermute", hop, cfg.pp, device)
        wire["pp"] = 2 * ticks * hop

    total_ms = (compute_s + tp_s + dp_s + ep_s + pp_s) * 1e3
    return {
        "projected_ms": total_ms,
        "compute_ms": compute_s * 1e3,
        "tp_ms": tp_s * 1e3,
        "dp_ms": dp_s * 1e3,
        "ep_ms": ep_s * 1e3,
        "pp_ms": pp_s * 1e3,
        "bubble_fraction": bubble,
        "wire_bytes": wire,
    }


# ---------------------------------------------------------------------------
# memory feasibility
# ---------------------------------------------------------------------------

def _memory_step(shape: ModelShape, cfg: PlanConfig):
    """(fn, args, donate_argnums) of the per-device microbatch train
    step the static estimator walks: real matmuls + a materialized
    attention score tile + per-layer remat scan + an Adam-shaped
    update over the (ZeRO-sharded) optimizer state, plus a
    min(pp, M)-deep in-flight activation buffer standing in for the
    1F1B residency cap. ShapeDtypeStructs only — nothing allocates."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    params = _param_tree(shape, cfg)
    n_local = local_param_elems(shape, cfg)
    n_opt = -(-n_local // cfg.dp) if cfg.zero else n_local
    sds = jax.ShapeDtypeStruct
    opt = {
        "master": sds((n_opt,), jnp.float32),
        "m": sds((n_opt,), jnp.float32),
        "v": sds((n_opt,), jnp.float32),
    }
    b_dp = shape.global_batch // cfg.dp
    mb = max(1, b_dp // cfg.microbatches)
    resident = max(0, min(cfg.pp, cfg.microbatches) - 1)
    dt = next(iter(params.values())).dtype
    inflight = sds((resident, mb * shape.seq, shape.hidden), dt)
    tokens = sds((mb, shape.seq), jnp.int32)

    H = shape.hidden
    heads_local = max(1, shape.heads // cfg.tp)
    hd = shape.head_dim

    def layer(x, lp):
        # attention: column-split qkv, row-split proj, fp32 score tile
        qkv = x @ lp["qkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads_view(a):
            return a.reshape(a.shape[0], a.shape[1], heads_local, hd)

        q, k, v = heads_view(q), heads_view(k), heads_view(v)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                            preferred_element_type=jnp.float32)
        p = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        o = o.reshape(x.shape[0], x.shape[1], H // cfg.tp)
        x = x + o @ lp["proj"]
        # MLP (dense column/row split) or the local expert slab
        if shape.experts:
            cap = max(1, x.shape[0] * x.shape[1] * shape.top_k
                      // max(1, cfg.ep))
            e_local = shape.experts // cfg.ep
            rows = -(-cap // e_local)
            xe = jnp.zeros((e_local, rows, H), x.dtype)
            h1 = jnp.einsum("erh,ehf->erf", xe, lp["w1"])
            h2 = jnp.einsum("erf,efh->erh", jax.nn.gelu(h1), lp["w2"])
            x = x + jnp.mean(h2) * x
        else:
            h1 = jax.nn.gelu(x @ lp["fc1"])
            x = x + h1 @ lp["fc2"]
        return x, None

    def step(params, opt, inflight, tokens):
        del inflight  # resident for the whole step (non-donated input)

        def loss_fn(params):
            x = jnp.take(params["emb"],
                         jnp.clip(tokens, 0,
                                  params["emb"].shape[0] - 1), axis=0)
            x = (x + params["pos"][None]).astype(dt)
            stacked = {k_: v_ for k_, v_ in params.items()
                       if k_ not in ("emb", "pos")}
            x, _ = lax.scan(
                jax.checkpoint(lambda c, lp: layer(c, lp)), x, stacked)
            logits = jnp.einsum(
                "bsh,vh->bsv", x, params["emb"],
                preferred_element_type=jnp.float32)
            z = jax.nn.logsumexp(logits, axis=-1)
            return jnp.mean(z) - jnp.mean(logits)

        grads = jax.grad(loss_fn)(params)
        gflat = jnp.concatenate(
            [grads[k_].astype(jnp.float32).reshape(-1)
             for k_ in sorted(grads)])
        gshard = lax.dynamic_slice_in_dim(
            gflat, 0, opt["m"].shape[0], 0) \
            if opt["m"].shape[0] < gflat.shape[0] else gflat
        m = 0.9 * opt["m"] + 0.1 * gshard
        v = 0.99 * opt["v"] + 0.01 * gshard * gshard
        master = opt["master"] - 1e-3 * m / (jnp.sqrt(v) + 1e-8)
        new_params = jax.tree.map(
            lambda p_, g_: (p_.astype(jnp.float32)
                            - 1e-3 * g_.astype(jnp.float32)).astype(dt),
            params, grads)
        return new_params, {"master": master, "m": m, "v": v}

    return step, (params, opt, inflight, tokens), (0, 1)


def estimate_config_peak(shape: ModelShape, cfg: PlanConfig):
    """Static per-device peak-HBM of one configuration — the
    feasibility filter (cost_model.estimate_peak_hbm over the traced
    microbatch step). Trace-only; no devices, no compile."""
    fn, args, donate = _memory_step(shape, cfg)
    return cost_model.estimate_peak_hbm(fn, args,
                                        donate_argnums=donate)


# ---------------------------------------------------------------------------
# the Plan record + the search loop
# ---------------------------------------------------------------------------

@dataclass
class Plan:
    """One ranked, memory-feasible configuration: everything a run
    needs to configure itself."""

    config: PlanConfig
    shape: ModelShape
    device: str
    projected_ms: float
    breakdown: dict
    peak_bytes: int
    peak_site: str
    budget_bytes: float
    rank: int = 0

    @property
    def feasible(self) -> bool:
        """Derived, not stored: a Plan is feasible iff its projected
        peak fits the budget (plan() only ever emits such Plans; the
        property keeps that invariant checkable instead of a stored
        always-True flag)."""
        return self.peak_bytes <= self.budget_bytes

    @property
    def mesh_axes(self) -> Dict[str, int]:
        return {"data": self.config.dp, "stage": self.config.pp,
                "model": self.config.tp, "expert": self.config.ep}

    @property
    def env_gates(self) -> Dict[str, str]:
        return self.config.env_gates

    def partition_specs(self) -> dict:
        """The placement recipe: PartitionSpecs per parameter role
        (the tensor_parallel/pipeline layout the executed leg and any
        consumer shards by)."""
        from jax.sharding import PartitionSpec as P

        return {
            "batch": P("data"),
            "stage_stack": P("stage"),
            "vocab_embedding": P("model", None),
            "column_parallel_kernel": P(None, "model"),
            "row_parallel_kernel": P("model", None),
            "expert_stack": P("expert"),
        }

    def to_json(self) -> dict:
        return {
            "config": dataclasses.asdict(self.config),
            "tag": self.config.tag,
            "mesh_axes": self.mesh_axes,
            "env_gates": self.env_gates,
            "partition_specs": {k: str(v) for k, v in
                                self.partition_specs().items()},
            "projected_ms": round(self.projected_ms, 4),
            "breakdown": {
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in self.breakdown.items()},
            "projected_peak_gib": round(self.peak_bytes / GiB, 4),
            "peak_site": self.peak_site,
            "budget_gib": round(self.budget_bytes / GiB, 4),
            "rank": self.rank,
            "feasible": self.feasible,
        }


def plan(shape: ModelShape, n_devices: int, *, device: str = "cpu",
         hbm_budget_gb: Optional[float] = None,
         microbatches: Optional[int] = None, top_k: int = 5,
         max_memory_traces: int = 64, log=None) -> List[Plan]:
    """Rank the search space and return the top feasible Plans.

    Projection is cheap, tracing is not: every candidate is projected,
    the ranked list is walked in projected order, and each candidate
    is memory-checked (``estimate_peak_hbm``, memoized per
    (mesh, zero, M) — the gates cannot change residency) until
    ``top_k`` feasible plans are found or ``max_memory_traces`` traces
    are spent. Budget: ``hbm_budget_gb`` arg >
    ``APEX_TPU_ANALYSIS_HBM_GB`` > the device kind's HBM capacity."""
    from apex_tpu.observability.tracing import trace_span

    if hbm_budget_gb is None:
        hbm_budget_gb = env_float("APEX_TPU_ANALYSIS_HBM_GB")
    budget = (float(hbm_budget_gb) * GiB if hbm_budget_gb is not None
              else cost_model.device_hbm_bytes(device))
    with trace_span("tuning.plan_search", shape=shape.name,
                    devices=n_devices, device=device):
        return _plan_ranked(shape, n_devices, device, budget,
                            microbatches, top_k, max_memory_traces, log)


def _plan_ranked(shape: ModelShape, n_devices: int, device: str,
                 budget: float, microbatches: Optional[int], top_k: int,
                 max_memory_traces: int, log) -> List[Plan]:
    cands = enumerate_configs(shape, n_devices,
                              microbatches=microbatches)
    if not cands:
        raise ValueError(
            f"no valid configuration for shape {shape.name!r} on "
            f"{n_devices} device(s)")
    scored = sorted(
        ((project(shape, c, device), c) for c in cands),
        key=lambda bc: bc[0]["projected_ms"])
    if log:
        log(f"planner: {len(scored)} candidate configs for "
            f"{shape.name} on {n_devices}x {device}")

    mem_cache: Dict[Tuple, object] = {}
    plans: List[Plan] = []
    traces = 0
    for breakdown, cfg in scored:
        if len(plans) >= top_k or traces >= max_memory_traces:
            break
        key = (cfg.dp, cfg.tp, cfg.pp, cfg.ep, cfg.zero,
               cfg.microbatches)
        est = mem_cache.get(key)
        if est is None:
            traces += 1
            est = estimate_config_peak(shape, cfg)
            mem_cache[key] = est
        if est.peak_bytes > budget:
            if log:
                log(f"planner: {cfg.tag} infeasible "
                    f"({est.peak_bytes / GiB:.3f} GiB > "
                    f"{budget / GiB:.2f} GiB)")
            continue
        plans.append(Plan(
            config=cfg, shape=shape, device=device,
            projected_ms=breakdown["projected_ms"],
            breakdown=breakdown, peak_bytes=est.peak_bytes,
            peak_site=est.peak_site, budget_bytes=budget,
            rank=len(plans)))
    if not plans:
        raise ValueError(
            f"no memory-feasible configuration for {shape.name!r} "
            f"under a {budget / GiB:.2f} GiB budget "
            f"({traces} candidates traced)")
    _record_plan_gauges(plans)
    return plans


def _record_plan_gauges(plans: List[Plan]) -> None:
    from apex_tpu.observability import set_gauge

    for p in plans:
        set_gauge("tuning/plan_projected_ms", p.projected_ms,
                  config=p.config.tag, model=p.shape.name)
        set_gauge("tuning/plan_peak_gib", p.peak_bytes / GiB,
                  config=p.config.tag, model=p.shape.name)


# ---------------------------------------------------------------------------
# the executed-plan leg
# ---------------------------------------------------------------------------

def _audit_plan_step(fn, args, axis_sizes: Dict[str, int],
                     tag: str) -> int:
    """The chosen plan's entry point must pass the APX2xx (donation /
    drift / collective), APX4xx (memory) and APX5xx (spmd) auditors
    before the planner reports it valid. Returns the traced equation
    count; raises on any error finding."""
    import jax

    from apex_tpu.analysis.auditors import EntryPoint, audit_entry_point
    from apex_tpu.analysis.memory import audit_memory
    from apex_tpu.analysis.spmd import audit_spmd

    closed = jax.make_jaxpr(fn)(*args)
    ep = EntryPoint(name=tag, fn=fn, args=lambda: args,
                    axis_sizes=dict(axis_sizes))
    findings = list(audit_entry_point(ep, closed=closed, args0=args))
    mfind, _mrow = audit_memory(closed, ep.tag)
    findings.extend(mfind)
    sfind, srow = audit_spmd(closed, dict(axis_sizes), ep.tag)
    findings.extend(sfind)
    errors = [f for f in findings
              if f.severity == "error" and not f.suppressed]
    if errors or not srow.get("ok", False):
        raise AssertionError(
            f"plan step {tag} failed the auditors: "
            + "; ".join(f.format() for f in errors[:5]))
    return len(closed.jaxpr.eqns)


def _scoped_env(gates: Dict[str, str]):
    import contextlib
    import os

    @contextlib.contextmanager
    def ctx():
        saved = {k: os.environ.get(k) for k in gates}
        try:
            os.environ.update(gates)
            yield
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    return ctx()


def execute_plan(p: Plan, *, devices=None, steps: int = 2,
                 rtol: float = 1e-4, atol: float = 1e-5) -> dict:
    """EXECUTE a plan on a host mesh and validate it end to end.

    Builds the plan's mesh over ``devices``, applies its env gates
    (scoped + restored), runs ``steps`` real loss+grad steps of the
    shape's standalone-transformer model, and checks loss AND gradient
    parity against the unplanned single-device reference (gates off,
    no mesh). ``pp > 1`` plans run the REAL pipeline schedules —
    ``fwd_bwd_pipelining_without_interleaving`` and (when a stage
    holds >= 2 layers) the interleaved schedule — against
    ``fwd_bwd_no_pipelining`` as the numeric oracle; that leg executes
    the plan's pp-ring SLICE (one dp rank, tp=1 — the dp/tp gates are
    no-ops on it), so its drift gauge compares against the slice's own
    projection (``projected_executed_ms`` / ``executed_slice`` in the
    result), never the full plan's. The step is
    auditor-validated (APX2xx/4xx/5xx) before any parity claim.
    Returns measured/projected timings + parity verdicts and lands
    them on the ``tuning/plan_measured_ms`` /
    ``tuning/plan_projected_vs_measured`` gauges."""
    import jax

    from apex_tpu.observability import set_gauge

    cfg = p.config
    if devices is None:
        devices = jax.devices("cpu")
    need = cfg.devices
    if len(devices) < need:
        raise ValueError(
            f"plan {cfg.tag} needs {need} devices, have {len(devices)}")
    if p.shape.experts and cfg.ep > 1:
        raise NotImplementedError(
            "the executed leg drives dense dp x tp x pp plans; EP "
            "execution rides the MoE dryrun leg")

    from apex_tpu.observability.tracing import trace_span

    with trace_span("tuning.plan_execute", config=cfg.tag,
                    model=p.shape.name), _scoped_env(cfg.env_gates):
        if cfg.pp > 1:
            result = _execute_pipeline(p, devices, steps=steps,
                                       rtol=rtol, atol=atol)
        else:
            result = _execute_dp_tp(p, devices, steps=steps, rtol=rtol,
                                    atol=atol)

    measured_ms = result["measured_ms"]
    # like-for-like drift ratio: the pipeline leg executes only the
    # plan's pp-ring SLICE (one dp rank, tp=1 — the dp/tp gates are
    # no-ops on it), so the gauge compares the measured run against
    # the projection of that slice at the executed microbatch count,
    # never the full plan's projection
    if result["mode"] == "pipeline":
        m_exec = result["microbatches"]
        exec_shape = dataclasses.replace(p.shape, global_batch=m_exec)
        exec_cfg = PlanConfig(pp=cfg.pp, microbatches=m_exec)
        projected_exec = project(exec_shape, exec_cfg,
                                 p.device)["projected_ms"]
        result["executed_slice"] = exec_cfg.tag
    else:
        projected_exec = p.projected_ms
    set_gauge("tuning/plan_measured_ms", measured_ms,
              config=cfg.tag, model=p.shape.name)
    if measured_ms > 0:
        set_gauge("tuning/plan_projected_vs_measured",
                  projected_exec / measured_ms,
                  config=cfg.tag, model=p.shape.name)
    result.update({
        "tag": cfg.tag,
        "projected_ms": p.projected_ms,
        "projected_executed_ms": projected_exec,
        "projected_vs_measured":
            (projected_exec / measured_ms) if measured_ms > 0 else None,
    })
    return result


def _timed_steps(step, args, steps: int):
    """(median wall ms over ``steps`` executions, last output) — the
    first call compiles separately; returning the output saves callers
    a redundant extra step."""
    import time

    import jax

    out = step(*args)
    jax.block_until_ready(jax.tree.leaves(out)[0])
    times = []
    for _ in range(max(1, steps)):
        t0 = time.perf_counter()
        out = step(*args)
        jax.block_until_ready(jax.tree.leaves(out)[0])
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    return times[len(times) // 2], out


def _execute_dp_tp(p: Plan, devices, *, steps: int, rtol: float,
                   atol: float) -> dict:
    """pp=1 execution: dp x tp loss+grads with the plan's gates, DDP
    or ZeRO-2 gradient sync, parity vs the single-device reference."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.contrib.optimizers._sharding import (
        all_gather_flat,
        reduce_scatter_flat,
    )
    from apex_tpu.testing import (gpt_loss, param_specs, sp_grad_sync,
                                  transformer_init)
    from apex_tpu.testing.commons import smap

    cfg = p.config
    shape = p.shape
    tcfg = transformer_config(shape, tp=cfg.tp)
    params = transformer_init(jax.random.PRNGKey(0), tcfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (shape.global_batch, shape.seq), 0,
        tcfg.vocab_size)

    mesh = Mesh(
        np.array(devices[:cfg.dp * cfg.tp]).reshape(cfg.dp, cfg.tp),
        ("data", "model"))

    def body(params, tokens):
        loss, grads = jax.value_and_grad(
            lambda pr: gpt_loss(pr, tokens, tcfg))(params)
        if cfg.dp > 1:
            if cfg.zero:
                # the ZeRO-2 comm path: flat reduce-scatter of the
                # grads + allgather of the (here: unmodified) shards —
                # mathematically the mean the DDP psum computes
                leaves, treedef = jax.tree.flatten(grads)
                sizes = [leaf.size for leaf in leaves]
                flat = jnp.concatenate(
                    [leaf.reshape(-1) for leaf in leaves])
                orig = flat.shape[0]
                pad = (-orig) % cfg.dp
                if pad:
                    flat = jnp.concatenate(
                        [flat, jnp.zeros((pad,), flat.dtype)])
                shard = reduce_scatter_flat(flat, "data", mean=True)
                full = all_gather_flat(shard, "data")[:orig]
                out, off = [], 0
                for leaf, sz in zip(leaves, sizes):
                    out.append(full[off:off + sz].reshape(leaf.shape))
                    off += sz
                grads = jax.tree.unflatten(treedef, out)
            else:
                from apex_tpu.parallel.ddp import (
                    DistributedDataParallel,
                )

                ddp = DistributedDataParallel(axis_name="data")
                grads = ddp.allreduce_gradients(grads)
            loss = jax.lax.pmean(loss, "data")
        grads = sp_grad_sync(grads, tcfg)
        return loss, grads

    pspec = param_specs(tcfg)
    fn = smap(body, mesh, (pspec, P("data")), (P(), pspec))
    args = (params, tokens)
    n_eqns = _audit_plan_step(
        fn, args, {"data": cfg.dp, "model": cfg.tp},
        f"plan:{cfg.tag}")
    step = jax.jit(fn)
    measured_ms, (loss, grads) = _timed_steps(step, args, steps)

    # unplanned single-device reference: tp=1, no SP, gates off
    ref_cfg = transformer_config(shape, tp=1)
    ref_mesh = Mesh(np.array(devices[:1]), ("model",))
    ref_fn = smap(
        lambda pr, t: jax.value_and_grad(
            lambda q: gpt_loss(q, t, ref_cfg))(pr),
        ref_mesh, (param_specs(ref_cfg), P()),
        (P(), param_specs(ref_cfg)))
    with _scoped_env({"APEX_TPU_QUANTIZED_COMMS": "0",
                      "APEX_TPU_OVERLAP_TP": "0",
                      "APEX_TPU_ZERO_PREFETCH": "0"}):
        ref_loss, ref_grads = jax.jit(ref_fn)(params, tokens)

    np.testing.assert_allclose(float(loss), float(ref_loss),
                               rtol=rtol, atol=atol)
    for a, b in zip(jax.tree.leaves(grads),
                    jax.tree.leaves(ref_grads)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=10 * rtol, atol=10 * atol)
    return {"measured_ms": measured_ms, "parity_ok": True,
            "audited_eqns": n_eqns, "mode": "dp_tp",
            "loss": float(loss)}


def _execute_pipeline(p: Plan, devices, *, steps: int, rtol: float,
                      atol: float) -> dict:
    """pp>1 execution: the shape's transformer blocks staged over a
    real pp ring, 1F1B AND (when a stage holds >= 2 layers) the
    interleaved schedule, numerically pinned against
    fwd_bwd_no_pipelining — the pipeline engine's first end-to-end
    numeric run outside the test suite.

    Chunk layout convention: every chunk stack is ``[n_chunks, per,
    ...]`` (per = layers per chunk), so the SAME chunk_fn serves the
    no-pipelining oracle (scans dim 0) and the schedules (local stack
    after the stage shard)."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.ops.layer_norm import layer_norm
    from apex_tpu.testing import transformer_init
    from apex_tpu.testing.commons import smap
    from apex_tpu.testing.standalone_transformer import _attention, _mlp
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_no_pipelining,
        forward_backward_pipelining_with_interleaving,
        forward_backward_pipelining_without_interleaving,
    )

    cfg = p.config
    shape = p.shape
    pp = cfg.pp
    M = max(pp, min(cfg.microbatches, 8))
    mb = 1
    tcfg = transformer_config(shape, tp=1)
    params = transformer_init(jax.random.PRNGKey(0), tcfg)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (M * mb, shape.seq), 0, tcfg.vocab_size)

    layer_stack = jax.tree.map(lambda *xs: jnp.stack(xs),
                               *params["layers"])
    lp = {"final_ln": params["final_ln"], "emb": params["embedding"]}

    def block(lpj, x):
        x = x + _attention(
            lpj, layer_norm(x, lpj["ln1"]["gamma"], lpj["ln1"]["beta"]),
            tcfg, None)
        return x + _mlp(
            lpj, layer_norm(x, lpj["ln2"]["gamma"], lpj["ln2"]["beta"]),
            tcfg, None)

    def loss_fn(lp, y, target):
        y = layer_norm(y, lp["final_ln"]["gamma"],
                       lp["final_ln"]["beta"])
        logits = y.astype(jnp.float32) @ lp["emb"].astype(
            jnp.float32).T
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(
            jnp.take_along_axis(logp, target[..., None], axis=-1))

    # embed outside the schedules (shared by pipeline and oracle)
    emb = jnp.take(params["embedding"], tokens, axis=0)
    x_full = (emb + params["pos_embedding"][None, :shape.seq]).astype(
        tcfg.dtype).transpose(1, 0, 2)                  # [s, M*mb, h]
    xs = x_full.reshape(shape.seq, M, mb,
                        shape.hidden).transpose(1, 0, 2, 3)
    ys = jnp.roll(tokens, -1, axis=1).reshape(
        M, mb, shape.seq).transpose(0, 2, 1)            # [m, s, mb]

    # the transformer blocks issue TP collectives over "model", so the
    # stage ring carries a size-1 model axis (test_model_pipeline.py's
    # mesh shape); a tp>1 x pp>1 execution would widen it
    mesh = Mesh(np.array(devices[:pp]).reshape(1, pp),
                ("model", "stage"))
    ref_mesh = Mesh(np.array(devices[:1]), ("model",))
    n_layers = shape.layers

    def make_chunk_fn(per):
        def chunk_fn(cp, x):                  # cp: [per, ...] leaves
            for j in range(per):
                x = block(jax.tree.map(lambda a: a[j], cp), x)
            return x

        return chunk_fn

    def ref_run(chunk_fn, all_chunks):
        def body(chunks, lp, xs, ys):
            res = forward_backward_no_pipelining(
                chunk_fn, loss_fn, chunks, lp, xs, ys)
            return res.losses, res.stage_grads, res.loss_grads

        return jax.jit(smap(
            body, ref_mesh, (P(), P(), P(), P()), (P(), P(), P())))(
            all_chunks, lp, xs, ys)

    def pipelined(schedule, chunk_fn, all_chunks, vp):
        one_f1b = schedule is \
            forward_backward_pipelining_without_interleaving

        def body(chunks, lp, xs, ys):
            local = jax.tree.map(lambda a: a[0], chunks)  # [V, per, .]
            if one_f1b:
                local = jax.tree.map(lambda a: a[0], local)
            res = schedule(chunk_fn, loss_fn, local, lp, xs, ys,
                           axis="stage")
            g = res.stage_grads
            if one_f1b:
                g = jax.tree.map(lambda a: a[None], g)
            return (res.losses, jax.tree.map(lambda a: a[None], g),
                    res.loss_grads)

        fn = smap(body, mesh, (P("stage"), P(), P(), P()),
                  (P(), P("stage"), P()))
        # [n_chunks, per, ...] -> stage-local order [pp, V, per, ...]
        # (global chunk g lives on stage g % pp as local chunk g // pp)
        perm = np.argsort(
            [g % pp * vp + g // pp for g in range(pp * vp)])
        staged = jax.tree.map(
            lambda a: a[perm].reshape((pp, vp) + a.shape[1:]),
            all_chunks)
        args = (staged, lp, xs, ys)
        n_eqns = _audit_plan_step(fn, args, {"model": 1, "stage": pp},
                                  f"plan:{cfg.tag}:{schedule.__name__}")
        step = jax.jit(fn)
        ms, (losses, sg, lg) = _timed_steps(step, args, steps)
        # grads back to global chunk order [n_chunks, per, ...]
        inv = np.argsort(perm)
        sg = jax.tree.map(
            lambda a: a.reshape((pp * vp,) + a.shape[2:])[inv], sg)
        return (losses, sg, lg), n_eqns, ms

    def check(got, ref):
        losses, sg, lg = got
        ref_l, ref_g, ref_lg = ref
        np.testing.assert_allclose(np.asarray(losses),
                                   np.asarray(ref_l), rtol=rtol,
                                   atol=atol)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=10 * rtol,
                atol=10 * atol), sg, ref_g)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=10 * rtol,
                atol=10 * atol), lg, ref_lg)

    # -- 1F1B: pp chunks of layers/pp ----------------------------------
    per_stage = n_layers // pp
    chunks_1f1b = jax.tree.map(
        lambda a: a.reshape((pp, per_stage) + a.shape[1:]), layer_stack)
    fn_1f1b = make_chunk_fn(per_stage)
    ref = ref_run(fn_1f1b, chunks_1f1b)
    got, n_eqns, ms_1f1b = pipelined(
        forward_backward_pipelining_without_interleaving, fn_1f1b,
        chunks_1f1b, 1)
    check(got, ref)
    losses = got[0]

    # -- interleaved: n_layers chunks of 1 layer -----------------------
    interleaved_ok = None
    if per_stage >= 2:
        vp = per_stage
        chunks_v = jax.tree.map(lambda a: a[:, None], layer_stack)
        fn_v = make_chunk_fn(1)
        ref_v = ref_run(fn_v, chunks_v)
        got_v, _n, _ms = pipelined(
            forward_backward_pipelining_with_interleaving, fn_v,
            chunks_v, vp)
        check(got_v, ref_v)
        interleaved_ok = True

    return {"measured_ms": ms_1f1b, "parity_ok": True,
            "interleaved_ok": interleaved_ok, "audited_eqns": n_eqns,
            "mode": "pipeline", "microbatches": M,
            "loss": float(jnp.mean(losses))}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _host_devices(n: int):
    """Pin the platform to cpu BEFORE any backend touch (the
    tests/conftest.py discipline — this container's remote-TPU plugin
    can hang during init), then hand back
    ``parallel.mesh.cpu_devices(n)`` (the one definition of the
    count check)."""
    import os

    import jax

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    jax.config.update("jax_platforms", "cpu")
    from apex_tpu.parallel.mesh import cpu_devices

    return cpu_devices(n)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.tuning.planner",
        description="whole-run auto-parallelism planner: rank "
                    "(dp x tp x pp x ep x ZeRO x gate) configs by "
                    "projected step time under a peak-HBM budget; "
                    "--execute runs the winner on a host mesh with "
                    "loss/grad parity vs the unplanned reference")
    ap.add_argument("--model", default="toy",
                    help=f"shape preset ({sorted(_SHAPES)})")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--device-kind", default="cpu",
                    help="device kind for the cost tables (v5e, v5p, "
                         "v4, v6, cpu)")
    ap.add_argument("--hbm-gb", type=float, default=None,
                    help="per-device HBM budget (default: "
                         "APEX_TPU_ANALYSIS_HBM_GB, else the device "
                         "kind's capacity)")
    ap.add_argument("--microbatches", type=int, default=None)
    ap.add_argument("--top", type=int, default=5)
    ap.add_argument("--execute", action="store_true",
                    help="execute the top plan on a CPU host mesh "
                         "(the dryrun leg)")
    ap.add_argument("--steps", type=int, default=2)
    args = ap.parse_args(argv)

    shape = shape_by_name(args.model)
    plans = plan(shape, args.devices, device=args.device_kind,
                 hbm_budget_gb=args.hbm_gb,
                 microbatches=args.microbatches, top_k=args.top)
    report = {
        "model": shape.name,
        "devices": args.devices,
        "device_kind": args.device_kind,
        "plans": [p.to_json() for p in plans],
    }
    if args.execute:
        devs = _host_devices(max(args.devices, plans[0].config.devices))
        report["executed"] = execute_plan(plans[0], devices=devs,
                                          steps=args.steps)
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
