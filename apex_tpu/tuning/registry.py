"""Registry of tunable kernel parameters — the autotuner's search space.

One table replaces the knowledge that used to live scattered across
per-kernel heuristics: which parameters each kernel family exposes, the
candidate values worth sweeping, and the validity constraints a candidate
must satisfy before it may be timed or cached. The autotune driver sweeps
exactly this space; the fuzz suite (tests/L0/test_tuning_fuzz.py) samples
the same space against the jnp oracles — so any entry the tuner can emit
is a configuration the test suite has proven numerically correct.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass(frozen=True)
class Tunable:
    """One kernel family's tunable surface."""

    kernel: str
    params: Dict[str, List]            # name -> candidate values
    # validity check: (params, features) -> error string | None
    check: Optional[Callable[[dict, dict], Optional[str]]] = None
    doc: str = ""
    defaults_from: str = ""            # cost_model symbol providing defaults
    env: Dict[str, str] = field(default_factory=dict)  # param -> env override


def _mult(name: str, quantum: int):
    def chk(params: dict, _features: dict) -> Optional[str]:
        v = params.get(name)
        if v is not None and (v <= 0 or v % quantum):
            return f"{name}={v} must be a positive multiple of {quantum}"
        return None
    return chk


def _flash_check(params: dict, features: dict) -> Optional[str]:
    for p in ("block_q", "block_k"):
        err = _mult(p, 128)(params, features)
        if err:
            return err
    backend = params.get("backend", "pallas")
    if backend not in ("pallas", "jnp"):
        return f"backend={backend!r} not in ('pallas', 'jnp')"
    return None


def _rows_check(params: dict, features: dict) -> Optional[str]:
    # Mosaic sublane quantum: LN partial-reduction outputs are (8, h)
    return _mult("block_rows", 8)(params, features)


def _moe_check(params: dict, features: dict) -> Optional[str]:
    err = _mult("tile_t", 8)(params, features)
    if err:
        return err
    err = _mult("tile_f", 128)(params, features)
    if err:
        return err
    backend = params.get("backend", "pallas")
    if backend not in ("pallas", "jnp"):
        return f"backend={backend!r} not in ('pallas', 'jnp')"
    return None


def _quant_check(params: dict, features: dict) -> Optional[str]:
    err = _mult("tile_m", 8)(params, features)
    if err:
        return err
    err = _mult("tile_n", 128)(params, features)
    if err:
        return err
    err = _mult("tile_k", 128)(params, features)
    if err:
        return err
    backend = params.get("backend", "pallas")
    if backend not in ("pallas", "jnp"):
        return f"backend={backend!r} not in ('pallas', 'jnp')"
    return None


def _softmax_check(params: dict, _features: dict) -> Optional[str]:
    c = params.get("row_chunk", 0)
    if c < 0:
        return f"row_chunk={c} must be >= 0 (0 = untiled)"
    return None


def _overlap_check(params: dict, _features: dict) -> Optional[str]:
    c = params.get("chunks")
    if c is not None and c < 1:
        return f"chunks={c} must be >= 1"
    return None


def _paged_check(params: dict, features: dict) -> Optional[str]:
    err = _mult("block_rows", 8)(params, features)
    if err:
        return err
    err = _mult("q_tile", 8)(params, features)
    if err:
        return err
    f = params.get("kv_fetch")
    if f is not None and f < 1:
        return f"kv_fetch={f} must be >= 1"
    backend = params.get("backend", "pallas")
    if backend not in ("pallas", "jnp"):
        return f"backend={backend!r} not in ('pallas', 'jnp')"
    return None


TUNABLES: Dict[str, Tunable] = {
    t.kernel: t
    for t in (
        Tunable(
            kernel="flash",
            params={
                "block_q": [128, 256, 512, 1024],
                "block_k": [128, 256, 512, 1024],
                "backend": ["pallas", "jnp"],
            },
            check=_flash_check,
            doc="Flash attention fwd/bwd, resident + streaming families "
                "(class features carry pass/family/causal/GQA).",
            defaults_from="cost_model.flash_block_default / "
                          "flash_backend_default",
            env={"block_q": "APEX_TPU_FLASH_BLOCK",
                 "block_k": "APEX_TPU_FLASH_BLOCK",
                 "backend": "APEX_TPU_USE_PALLAS"},
        ),
        Tunable(
            kernel="layer_norm",
            params={"block_rows": [8, 16, 32, 64, 128, 256, 512]},
            check=_rows_check,
            doc="Rows per grid step of the LN fwd/bwd kernels.",
            defaults_from="cost_model.ln_block_rows_default",
            env={"block_rows": "APEX_TPU_LN_BLOCK_ROWS"},
        ),
        Tunable(
            kernel="rms_norm",
            params={"block_rows": [8, 16, 32, 64, 128, 256, 512]},
            check=_rows_check,
            doc="Rows per grid step of the RMSNorm fwd/bwd kernels.",
            defaults_from="cost_model.ln_block_rows_default",
            env={"block_rows": "APEX_TPU_LN_BLOCK_ROWS"},
        ),
        Tunable(
            kernel="optim_flat",
            params={"block_rows": [256, 512, 1024, 2048, 4096]},
            check=_mult("block_rows", 8),
            doc="128-lane rows per grid step of the flat optimizer "
                "kernels (adam/lamb/l2norm); class carries the live tile "
                "count.",
            defaults_from="cost_model.optim_block_rows_default",
            env={"block_rows": "APEX_TPU_OPTIM_BLOCK_ROWS"},
        ),
        Tunable(
            kernel="overlap_tp",
            params={"chunks": [1, 2, 4, 8]},
            check=_overlap_check,
            doc="Ring chunk count of the decomposed collective matmul "
                "(parallel/overlap.py): pieces of the local block that "
                "circulate independently, alternating ring direction "
                "(2 = classic bidirectional). Class carries local rows, "
                "ring size and dtype.",
            defaults_from="cost_model.overlap_chunks_default",
            env={"chunks": "APEX_TPU_OVERLAP_TP_CHUNKS"},
        ),
        Tunable(
            kernel="paged_decode",
            params={
                "block_rows": [8, 16, 32],
                "kv_fetch": [1, 2, 4, 8],
                "q_tile": [8, 16, 32, 64],
                "backend": ["pallas", "jnp"],
            },
            check=_paged_check,
            doc="Ragged multi-query paged-attention kernel "
                "(ops/paged_attention.py — prefill chunks + decode in one "
                "program): block_rows = sublane floor of the per-(work "
                "item, kv-head) q tile; q_tile = query tokens per work "
                "item (the tile is q_tile x GQA group rows); kv_fetch = "
                "KV pages pulled per grid step (staggered index maps "
                "pipeline the page DMAs). Class carries slots, packed "
                "query rows, total paged KV span, page size, GQA group, "
                "head dim and dtype.",
            defaults_from="cost_model.paged_block_rows_default / "
                          "paged_kv_fetch_default / paged_q_tile_default",
            env={"block_rows": "APEX_TPU_PAGED_BLOCK_ROWS",
                 "kv_fetch": "APEX_TPU_PAGED_KV_FETCH",
                 "q_tile": "APEX_TPU_PAGED_Q_TILE",
                 "backend": "APEX_TPU_USE_PALLAS"},
        ),
        Tunable(
            kernel="moe_grouped",
            params={
                "tile_t": [128, 256, 512],
                "tile_f": [128, 256, 512],
                "backend": ["pallas", "jnp"],
            },
            check=_moe_check,
            doc="Ragged grouped matmul (ops/grouped_matmul.py, the "
                "dropless-MoE expert FFN): tile_t = rows per work tile "
                "(sublane multiple of 8), tile_f = output columns per grid "
                "step (lane multiple of 128). The cost model also owns the "
                "oracle-fallback row threshold behind the backend default "
                "(cost_model.MOE_FALLBACK_ROWS). Class carries routed rows, "
                "expert count, hidden, ffn and dtype.",
            defaults_from="cost_model.moe_tile_t_default / "
                          "moe_tile_f_default / moe_backend_default",
            env={"tile_t": "APEX_TPU_MOE_TILE_T",
                 "tile_f": "APEX_TPU_MOE_TILE_F",
                 "backend": "APEX_TPU_USE_PALLAS"},
        ),
        Tunable(
            kernel="quant_matmul",
            params={
                "tile_m": [32, 128, 256, 512],
                "tile_n": [128, 256, 512],
                "tile_k": [128, 256, 512],
                "backend": ["pallas", "jnp"],
            },
            check=_quant_check,
            doc="Blockwise-scaled low-precision matmul (quantization/"
                "scaled_matmul.py, int8 + fp8-layout operands with "
                "per-tile fp32 scale sidecars): tile_m = output rows per "
                "grid step (sublane multiple of 8; int8 tiles natively "
                "want 32), tile_n = output columns (lane multiple of "
                "128), tile_k = contraction elements per k-step AND the "
                "quantization block size (scale resolution vs occupancy "
                "trade). The cost model also owns the oracle-fallback "
                "row threshold (cost_model.QUANT_FALLBACK_ROWS) behind "
                "the backend default. Class carries rows, contraction, "
                "output width, source dtype and payload width.",
            defaults_from="cost_model.quant_tile_m_default / "
                          "quant_tile_n_default / quant_tile_k_default / "
                          "quant_backend_default",
            env={"tile_m": "APEX_TPU_QUANT_TILE_M",
                 "tile_n": "APEX_TPU_QUANT_TILE_N",
                 "tile_k": "APEX_TPU_QUANT_TILE_K",
                 "backend": "APEX_TPU_USE_PALLAS"},
        ),
        Tunable(
            kernel="softmax",
            params={"row_chunk": [0, 1024, 2048, 4096, 8192]},
            check=_softmax_check,
            doc="Row tiling of the fused scale/mask softmax family "
                "(0 = single XLA-fused pass, today's default).",
            defaults_from="cost_model.softmax_row_chunk_default",
            env={"row_chunk": "APEX_TPU_SOFTMAX_CHUNK"},
        ),
    )
}


def validate_entry(kernel: str, params: dict,
                   features: Optional[dict] = None) -> None:
    """Raise ValueError if (kernel, params) is not a legal cache entry.
    The autotune driver calls this before writing; the cache consumer
    side stays permissive (unknown keys are ignored, wrong values are
    clamped) so a hand-edited file degrades, never crashes."""
    t = TUNABLES.get(kernel)
    if t is None:
        raise ValueError(
            f"unknown kernel family {kernel!r} (known: {sorted(TUNABLES)})"
        )
    unknown = set(params) - set(t.params)
    if unknown:
        raise ValueError(
            f"{kernel}: unknown tunable(s) {sorted(unknown)} "
            f"(known: {sorted(t.params)})"
        )
    if t.check is not None:
        err = t.check(params, features or {})
        if err:
            raise ValueError(f"{kernel}: {err}")
