"""Autotune driver: sweep tunable kernel configs per shape class.

Two modes, chosen automatically (or forced with ``--interpret``):

- **hardware** (a TPU is attached): each candidate config is compiled and
  timed (median of ``--reps`` f+b steps); the best per shape class is
  written to the tune cache with its measured milliseconds. This is how
  tunnel minutes become a durable artifact instead of a one-off number —
  the ladder that used to be hand-run env-var experiments
  (``APEX_TPU_FLASH_BLOCK_BWD`` sweeps, wide-hidden LN A/B) is one CLI.
- **interpret** (CPU, or forced): candidates are *verified* against the
  jnp oracles in Pallas interpret mode at small shapes, then *ranked* by
  the cost model's roofline projection; entries record
  ``source: "interpret+cost_model"``. Large benched classes additionally
  get projection-only entries (``source: "cost_model_projection"``) so a
  dark round still ships a complete, valid tunedb for the next window.

Usage::

    python -m apex_tpu.tuning.autotune --interpret           # CPU-safe
    python -m apex_tpu.tuning.autotune --out benchmarks/tunedb/v5e.json
    python bench.py --autotune                               # same, after
                                                             # preflight

The sweep space is registry.TUNABLES — the same space the fuzz suite
(tests/L0/test_tuning_fuzz.py) proves correct, so nothing this driver can
emit is an untested configuration.
"""

from __future__ import annotations

import argparse
import contextlib
import os
import sys
import time
from pathlib import Path
from typing import Iterable, Optional

from apex_tpu.tuning import cache, cost_model, registry, shape_class

# env overrides that would defeat a sweep — cleared (not just ignored)
# around every candidate run so the pinned entry is what executes
_SWEEP_ENV = (
    "APEX_TPU_FLASH_BLOCK",
    "APEX_TPU_FLASH_BLOCK_BWD",
    "APEX_TPU_FLASH_STREAM",
    "APEX_TPU_LN_BLOCK_ROWS",
    "APEX_TPU_MOE_TILE_T",
    "APEX_TPU_MOE_TILE_F",
    "APEX_TPU_OPTIM_BLOCK_ROWS",
    "APEX_TPU_PAGED_BLOCK_ROWS",
    "APEX_TPU_PAGED_KV_FETCH",
    "APEX_TPU_PAGED_Q_TILE",
    "APEX_TPU_QUANT_TILE_M",
    "APEX_TPU_QUANT_TILE_N",
    "APEX_TPU_QUANT_TILE_K",
    "APEX_TPU_SOFTMAX_CHUNK",
    "APEX_TPU_USE_PALLAS",
)


@contextlib.contextmanager
def _sweep_env(**pins):
    """Clear every sweep-relevant env var, then apply explicit pins."""
    saved = {k: os.environ.pop(k, None) for k in _SWEEP_ENV}
    try:
        for k, v in pins.items():
            if v is not None:
                os.environ[k] = v
        yield
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v


def _maxdiff(a, b) -> float:
    import jax.numpy as jnp

    return float(
        jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))


# ------------------------------------------------------------------
# flash attention
# ------------------------------------------------------------------

def _flash_case(sq: int, sk: int, d: int, dtype, causal: bool, group: int):
    import jax
    import jax.numpy as jnp

    hq, hkv = 2 * group, 2
    q = jax.random.normal(jax.random.PRNGKey(0), (1, hq, sq, d), dtype)
    k = jax.random.normal(jax.random.PRNGKey(1), (1, hkv, sk, d), dtype)
    v = jax.random.normal(jax.random.PRNGKey(2), (1, hkv, sk, d), dtype)
    do = jax.random.normal(jax.random.PRNGKey(3), q.shape, dtype)

    def loss(q, k, v, use):
        from apex_tpu.ops.attention import flash_attention

        y = flash_attention(q, k, v, causal=causal, use_pallas=use)
        return jnp.vdot(y.astype(jnp.float32), do.astype(jnp.float32))

    return q, k, v, loss


def _verify_flash(sq, sk, d, dtype, causal, group, params, streaming) -> \
        Optional[str]:
    """Interpret-mode parity of one candidate vs the jnp oracle (fwd via
    the loss value, bwd via all three input grads)."""
    import jax

    db = cache.TuneDB()
    for bwd in (False, True):
        db.record(
            shape_class.flash_key(sq, sk, d, dtype, causal, group,
                                  streaming, bwd),
            {k: v for k, v in params.items() if k != "backend"},
            source="sweep-candidate")
    q, k, v, loss = _flash_case(sq, sk, d, dtype, causal, group)
    stream_pin = "1" if streaming else "0"
    try:
        with _sweep_env(APEX_TPU_FLASH_STREAM=stream_pin), cache.pinned(db):
            gp = jax.grad(lambda q, k, v: loss(q, k, v, True),
                          argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: loss(q, k, v, False),
                      argnums=(0, 1, 2))(q, k, v)
        for a, c in zip(gp, gr):
            if _maxdiff(a, c) > 0.1:
                return f"grad mismatch {_maxdiff(a, c):.3f} vs oracle"
    except Exception as e:  # noqa: BLE001 — a failing candidate is data
        return f"{type(e).__name__}: {str(e).splitlines()[0][:200]}"
    return None


def _time_flash(sq, sk, d, dtype, causal, group, params, streaming,
                reps: int) -> float:
    """Median f+b milliseconds of one candidate on the attached device."""
    import jax

    db = cache.TuneDB()
    for bwd in (False, True):
        db.record(
            shape_class.flash_key(sq, sk, d, dtype, causal, group,
                                  streaming, bwd),
            {k: v for k, v in params.items() if k != "backend"},
            source="sweep-candidate")
    q, k, v, loss = _flash_case(sq, sk, d, dtype, causal, group)
    stream_pin = "1" if streaming else "0"
    with _sweep_env(APEX_TPU_FLASH_STREAM=stream_pin), cache.pinned(db):
        g = jax.jit(jax.grad(lambda q, k, v: loss(q, k, v, True),
                             argnums=(0, 1, 2)))
        out = g(q, k, v)  # compile + warmup
        jax.block_until_ready(out)
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(g(q, k, v))
            times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2] * 1e3


def _flash_candidates(sq: int, sk: int, streaming: bool) -> Iterable[dict]:
    space = registry.TUNABLES["flash"].params
    for bq in space["block_q"]:
        for bk in space["block_k"]:
            if bq > cost_model._ceil128(sq) or bk > cost_model._ceil128(sk):
                continue
            if streaming and (bq > 512 or bk > 512):
                continue  # streaming scratch is O(block); huge tiles OOM
            yield {"block_q": bq, "block_k": bk}


def sweep_flash(db: cache.TuneDB, *, seqs, dtype, hardware: bool,
                reps: int, log=print) -> None:
    import jax.numpy as jnp

    dt = jnp.bfloat16 if dtype == "bf16" else jnp.float32
    for s in seqs:
        streaming = s > cost_model.STREAM_SEQ  # attention's routing
        causal = True
        group = 1
        d = 64
        rows = []
        src = "hardware" if hardware else "interpret+cost_model"
        for params in _flash_candidates(s, s, streaming):
            if hardware:
                try:
                    score = _time_flash(s, s, d, dt, causal, group, params,
                                        streaming, reps)
                except Exception as e:  # noqa: BLE001 — OOM class is data
                    log(f"autotune: flash s={s} {params}: FAILED "
                        f"{type(e).__name__}: {str(e).splitlines()[0][:120]}")
                    continue
            else:
                err = _verify_flash(s, s, d, dt, causal, group, params,
                                    streaming)
                if err:
                    log(f"autotune: flash s={s} {params}: REJECTED ({err})")
                    continue
                proj = cost_model.flash_projection(
                    s, s, d, dtype, params["block_q"], params["block_k"],
                    streaming=streaming, bwd=True,
                    device=shape_class.device_kind())
                score = proj["flash_ms"]
            rows.append((params, score))
            log(f"autotune: flash s={s} {params}: {score:.3f} ms "
                f"({'measured' if hardware else 'projected'})")
        best = best_score = None
        if rows:
            # among candidates within 5% of the best score, prefer the one
            # matching the cost-model (measured) default — projections lack
            # the resolution to overturn a measured rule on a near-tie
            floor = min(sc for _, sc in rows)
            default_b = cost_model.flash_block_default(s, streaming)
            best, best_score = min(
                ((p, sc) for p, sc in rows if sc <= 1.05 * floor),
                key=lambda r: (r[0]["block_q"] != default_b
                               or r[0]["block_k"] != default_b, r[1]),
            )
        if best is None:
            log(f"autotune: flash s={s}: no viable candidate; class keeps "
                f"its cost-model default")
            continue
        for bwd in (False, True):
            key = shape_class.flash_key(s, s, d, dt, causal, group,
                                        streaming, bwd)
            registry.validate_entry("flash", best)
            db.record(key, best, source=src, ms=best_score,
                      note=f"swept {len(rows)} candidates")
        log(f"autotune: flash s={s} -> {best} ({best_score:.3f} ms, {src})")


def project_flash_ladder(db: cache.TuneDB, *, log=print) -> None:
    """Projection-only entries for the full benched ladder (no execution):
    the cost model's pick per class, so a dark round still ships a
    complete tunedb for the next hardware window to refine."""
    import jax.numpy as jnp

    dev = shape_class.device_kind()
    for rung in cost_model.iter_flash_ladder():
        sq, d, causal = rung["sq"], rung["d"], rung["causal"]
        streaming = sq > cost_model.STREAM_SEQ
        for bwd in (False, True):
            bq = cost_model.flash_block_default(sq, streaming, bwd)
            key = shape_class.flash_key(sq, sq, d, jnp.bfloat16, causal, 1,
                                        streaming, bwd)
            if db.get(key):  # never downgrade a measured/verified entry
                continue
            proj = cost_model.flash_projection(
                sq, sq, d, "bf16", bq, bq, streaming=streaming, bwd=bwd,
                device=dev)
            db.record(key, {"block_q": bq, "block_k": bq},
                      source="cost_model_projection", ms=proj["flash_ms"])
    log("autotune: flash ladder projection entries recorded")


# ------------------------------------------------------------------
# layer norm / rms norm
# ------------------------------------------------------------------

def sweep_ln(db: cache.TuneDB, *, hiddens, dtype, hardware: bool,
             reps: int, kernels=("layer_norm", "rms_norm"),
             log=print) -> None:
    import jax
    import jax.numpy as jnp

    dt = jnp.bfloat16 if dtype == "bf16" else jnp.float32
    for kernel in kernels:
        for h in hiddens:
            best, best_score = None, None
            rows_shape = (4, 96, h)
            x = jax.random.normal(jax.random.PRNGKey(0), rows_shape, dt)
            g = jnp.ones((h,), jnp.float32)
            b = jnp.zeros((h,), jnp.float32)
            dy = jax.random.normal(jax.random.PRNGKey(1), x.shape, dt)

            def loss(x, g, b, use, kernel=kernel, dy=dy):
                from apex_tpu.ops.layer_norm import (
                    layer_norm_affine, rms_norm_affine)

                if kernel == "layer_norm":
                    y = layer_norm_affine(x, g, b, 1e-5, use)
                else:
                    y = rms_norm_affine(x, g, 1e-5, use)
                return jnp.vdot(y.astype(jnp.float32),
                                dy.astype(jnp.float32))

            for rows in registry.TUNABLES[kernel].params["block_rows"]:
                db_c = cache.TuneDB()
                db_c.record(shape_class.ln_key(kernel, h, dt),
                            {"block_rows": rows}, source="sweep-candidate")
                try:
                    with _sweep_env(), cache.pinned(db_c):
                        if hardware:
                            f = jax.jit(jax.grad(
                                lambda x, g, b: loss(x, g, b, True),
                                argnums=(0, 1)))
                            jax.block_until_ready(f(x, g, b))
                            times = []
                            for _ in range(reps):
                                t0 = time.perf_counter()
                                jax.block_until_ready(f(x, g, b))
                                times.append(time.perf_counter() - t0)
                            times.sort()
                            score = times[len(times) // 2] * 1e3
                        else:
                            gp = jax.grad(lambda x, g, b: loss(x, g, b, True),
                                          argnums=(0, 1))(x, g, b)
                            gr = jax.grad(
                                lambda x, g, b: loss(x, g, b, False),
                                argnums=(0, 1))(x, g, b)
                            for a, c in zip(gp, gr):
                                assert _maxdiff(a, c) < 0.1
                            # interpret runs prove correctness, not speed:
                            # rank by distance from the measured default
                            # so the emitted entry reproduces it
                            default = cost_model.ln_block_rows_default(
                                h, device=shape_class.device_kind())
                            score = abs(rows - default)
                except Exception as e:  # noqa: BLE001
                    log(f"autotune: {kernel} h={h} rows={rows}: REJECTED "
                        f"({type(e).__name__}: "
                        f"{str(e).splitlines()[0][:120]})")
                    continue
                if best_score is None or score < best_score:
                    best, best_score = rows, score
            if best is None:
                continue
            db.record(shape_class.ln_key(kernel, h, dt),
                      {"block_rows": best},
                      source="hardware" if hardware
                      else "interpret+cost_model",
                      ms=best_score if hardware else None)
            log(f"autotune: {kernel} h={h} -> block_rows={best}")


# ------------------------------------------------------------------
# optimizer flat kernels
# ------------------------------------------------------------------

def sweep_optim(db: cache.TuneDB, *, hardware: bool, reps: int,
                log=print) -> None:
    import jax
    import jax.numpy as jnp

    n = 4099 if not hardware else 8 * 1024 * 1024
    g = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.float32)
    p = jax.random.normal(jax.random.PRNGKey(1), (n,), jnp.float32)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    for tiles, runner in ((7, "adam"), (2, "l2norm")):
        best, best_score = None, None
        for rows in registry.TUNABLES["optim_flat"].params["block_rows"]:
            db_c = cache.TuneDB()
            db_c.record(shape_class.optim_key(tiles), {"block_rows": rows},
                        source="sweep-candidate")
            try:
                with _sweep_env(), cache.pinned(db_c):
                    from apex_tpu.ops.pallas_optim import adam_flat, \
                        l2norm_flat

                    # the flat kernels are module-level jits: the block
                    # choice binds at trace time, so each candidate needs
                    # a fresh trace
                    for f in (adam_flat, l2norm_flat):
                        try:
                            f.clear_cache()
                        except Exception:  # noqa: BLE001 — older jax
                            jax.clear_caches()

                    def run():
                        if runner == "adam":
                            return adam_flat(
                                g, p, m, v, lr=1e-3, beta1=0.9, beta2=0.999,
                                eps=1e-8, step=1, weight_decay=0.01)
                        return l2norm_flat(g)

                    out = run()
                    jax.block_until_ready(out)
                    if hardware:
                        times = []
                        for _ in range(reps):
                            t0 = time.perf_counter()
                            jax.block_until_ready(run())
                            times.append(time.perf_counter() - t0)
                        times.sort()
                        score = times[len(times) // 2] * 1e3
                    else:
                        # interpret: verify vs oracle, then rank by
                        # distance from the OOM-measured default
                        if runner == "l2norm":
                            ref = jnp.sqrt(jnp.sum(g.astype(jnp.float32)**2))
                            assert abs(float(out) - float(ref)) < 1e-2
                        default = cost_model.optim_block_rows_default(
                            tiles, device=shape_class.device_kind())
                        score = abs(rows - default)
            except Exception as e:  # noqa: BLE001
                log(f"autotune: optim tiles={tiles} rows={rows}: REJECTED "
                    f"({type(e).__name__})")
                continue
            if best_score is None or score < best_score:
                best, best_score = rows, score
        if best is None:
            continue
        db.record(shape_class.optim_key(tiles), {"block_rows": best},
                  source="hardware" if hardware else "interpret+cost_model",
                  ms=best_score if hardware else None)
        log(f"autotune: optim_flat tiles={tiles} -> block_rows={best}")


def sweep_paged(db: cache.TuneDB, *, hardware: bool, reps: int,
                log=print) -> None:
    """(block_rows, kv_fetch, q_tile) sweep for the ragged multi-query
    paged-attention kernel (ops/paged_attention.py, registry family
    ``paged_decode``), run over a MIXED ragged layout (prefill chunks +
    decode steps + an idle slot) so every candidate is exercised on the
    shape the unified serving step actually dispatches.

    Hardware sessions time the kernel per (slots, packed rows, kv span,
    page size, group, d) class — median of ``reps`` calls per candidate,
    winner recorded with milliseconds. Interpret sessions VERIFY each
    candidate against the generalized gather oracle and record the
    cost-model defaults (projections lack the resolution to overturn
    the measured rule — same policy as the flash sweep)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.paged_attention import (
        _ragged_pallas,
        ragged_paged_attention_ref,
    )

    space = registry.TUNABLES["paged_decode"].params
    ladder = (
        # (slots, hq, hkv, d, block_size, max_blocks, total_q)
        (8, 8, 8, 128, 16, 64, 8),       # dense MHA pure decode
        (8, 8, 2, 128, 16, 64, 8),       # GQA group 4 pure decode
        (8, 8, 2, 128, 16, 64, 256),     # chunked prefill + decode mix
    ) if hardware else ((4, 4, 2, 64, 8, 4, 20),)
    for slots, hq, hkv, d, bs, maxb, total_q in ladder:
        nb = slots * maxb + 8
        group = hq // hkv
        keys = jax.random.split(jax.random.PRNGKey(slots + d + total_q), 4)
        k_pool = jax.random.normal(keys[0], (nb, bs, hkv, d), jnp.bfloat16)
        v_pool = jax.random.normal(keys[1], (nb, bs, hkv, d), jnp.bfloat16)
        q = jax.random.normal(keys[2], (total_q, hq, d), jnp.bfloat16)
        tables = jax.random.permutation(keys[3], nb)[: slots * maxb
                                                     ].reshape(slots, maxb)
        # mixed layout in slot order: one big chunk takes the spare rows,
        # one idle slot, the rest single-token decodes
        span = bs * maxb
        ql = [1] * slots
        ql[1] = 0
        ql[0] = total_q - sum(ql[1:])
        qs, off = [], 0
        for n in ql:
            qs.append(off)
            off += n
        kl = [min(span - 3, max(n, span // 2 + i)) for i, n in enumerate(ql)]
        kl[1] = 0
        kl[0] = max(kl[0], ql[0])
        qs = jnp.asarray(qs, jnp.int32)
        qlj = jnp.asarray(ql, jnp.int32)
        klj = jnp.asarray(kl, jnp.int32)
        ref = ragged_paged_attention_ref(q, k_pool, v_pool, tables, qs,
                                         qlj, klj)
        scale = 1.0 / (d ** 0.5)
        best = None
        for rows in space["block_rows"]:
            for fetch in space["kv_fetch"]:
                if fetch > maxb:
                    continue
                for q_tile in space["q_tile"]:

                    def f(q, kp, vp, t, a, b, c, rows=rows, fetch=fetch,
                          q_tile=q_tile):
                        return _ragged_pallas(q, kp, vp, t, a, b, c,
                                              scale, rows, fetch, q_tile)

                    try:
                        fn = jax.jit(f)
                        got = fn(q, k_pool, v_pool, tables, qs, qlj, klj)
                        got.block_until_ready()
                        err = float(jnp.max(jnp.abs(
                            got.astype(jnp.float32)
                            - ref.astype(jnp.float32))))
                        if err > 5e-2:
                            raise AssertionError(f"oracle mismatch {err}")
                        times = []
                        for _ in range(max(1, reps)):
                            t0 = time.perf_counter()
                            fn(q, k_pool, v_pool, tables, qs, qlj,
                               klj).block_until_ready()
                            times.append(time.perf_counter() - t0)
                        ms = sorted(times)[len(times) // 2] * 1e3
                    except Exception as e:  # noqa: BLE001 — failing cand.
                        log(f"autotune: paged_decode rows={rows} "
                            f"fetch={fetch} q_tile={q_tile} failed: "
                            f"{type(e).__name__}: {e}")
                        continue
                    if best is None or ms < best[3]:
                        best = (rows, fetch, q_tile, ms)
        if best is None:
            continue
        if hardware:
            entry = {"block_rows": best[0], "kv_fetch": best[1],
                     "q_tile": best[2]}
        else:  # verified, but keep the measured-rule defaults
            entry = {
                "block_rows": cost_model.paged_block_rows_default(group),
                "kv_fetch": cost_model.paged_kv_fetch_default(bs, d),
                "q_tile": cost_model.paged_q_tile_default(group),
            }
        registry.validate_entry("paged_decode", entry)
        key = shape_class.paged_key(slots, maxb, bs, group, d,
                                    jnp.bfloat16, total_q=total_q)
        db.record(key, entry,
                  source="hardware" if hardware else "interpret+cost_model",
                  ms=best[3] if hardware else None,
                  note=f"swept {len(space['block_rows'])}x"
                       f"{len(space['kv_fetch'])}x"
                       f"{len(space['q_tile'])} candidates")
        log(f"autotune: paged_decode slots={slots} g={group} d={d} "
            f"tq={total_q} -> rows={entry['block_rows']} "
            f"fetch={entry['kv_fetch']} q_tile={entry['q_tile']}"
            + (f" ({best[3]:.3f} ms)" if hardware else " (verified)"))


def sweep_moe(db: cache.TuneDB, *, hardware: bool, reps: int,
              log=print) -> None:
    """(tile_t, tile_f) sweep for the ragged grouped matmul
    (ops/grouped_matmul.py, registry family ``moe_grouped``).

    Hardware sessions time a full gmm f+b step per (rows, E, h, f) class
    — median of ``reps`` value_and_grad calls per candidate, winner
    recorded with milliseconds. Interpret sessions VERIFY each candidate
    against the segment oracle (fwd + both grads, skewed ragged groups)
    and record the cost-model default (projections lack the resolution
    to overturn the measured rule — same policy as the flash sweep)."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.grouped_matmul import gmm, gmm_ref

    space = registry.TUNABLES["moe_grouped"].params
    ladder = (
        # (rows = tokens * top_k, E, hidden, ffn)
        (4096, 8, 1024, 4096),     # GPT-medium-class MoE FFN
        (16384, 8, 1024, 4096),    # the long-batch class
    ) if hardware else ((96, 4, 64, 128),)
    for t, e, h, f in ladder:
        keys = jax.random.split(jax.random.PRNGKey(t + e), 4)
        lhs = jax.random.normal(keys[0], (t, h), jnp.bfloat16)
        rhs = jax.random.normal(keys[1], (e, h, f), jnp.bfloat16)
        do = jax.random.normal(keys[2], (t, f), jnp.bfloat16)
        # skewed ragged split (one heavy group, one empty) + remainder
        heavy = t // 2
        rest = (t - heavy) // max(e - 2, 1)
        sizes = [heavy, 0] + [rest] * (e - 2)
        sizes[-1] += t - sum(sizes)
        group_sizes = jnp.array(sizes, jnp.int32)

        def loss(lhs, rhs, use):
            y = gmm(lhs, rhs, group_sizes, use_pallas=use)
            return jnp.vdot(y.astype(jnp.float32), do.astype(jnp.float32))

        gr = None
        if not hardware:  # candidate-independent oracle grads, once
            gr = jax.grad(
                lambda lhs, rhs: jnp.vdot(
                    gmm_ref(lhs, rhs, group_sizes).astype(jnp.float32),
                    do.astype(jnp.float32)),
                argnums=(0, 1))(lhs, rhs)
        best = None
        src = "hardware" if hardware else "interpret+cost_model"
        for tt in space["tile_t"]:
            for tf in space["tile_f"]:
                db_c = cache.TuneDB()
                db_c.record(shape_class.moe_key(t, e, h, f, jnp.bfloat16),
                            {"tile_t": tt, "tile_f": tf},
                            source="sweep-candidate")
                try:
                    with _sweep_env(), cache.pinned(db_c):
                        g = jax.jit(jax.grad(
                            lambda lhs, rhs: loss(lhs, rhs, True),
                            argnums=(0, 1)))
                        gp = g(lhs, rhs)
                        jax.block_until_ready(gp)
                        if hardware:
                            times = []
                            for _ in range(max(1, reps)):
                                t0 = time.perf_counter()
                                jax.block_until_ready(g(lhs, rhs))
                                times.append(time.perf_counter() - t0)
                            times.sort()
                            score = times[len(times) // 2] * 1e3
                        else:
                            for a, c in zip(gp, gr):
                                assert _maxdiff(a, c) < 0.1, \
                                    f"grad mismatch {_maxdiff(a, c)}"
                            # interpret runs prove correctness, not speed:
                            # rank by distance from the measured defaults
                            score = (abs(tt - cost_model.moe_tile_t_default(
                                h, f, device=shape_class.device_kind()))
                                + abs(tf - cost_model.moe_tile_f_default(f)))
                except Exception as err:  # noqa: BLE001 — failing candidate
                    log(f"autotune: moe_grouped t={t} tile_t={tt} "
                        f"tile_f={tf}: REJECTED ({type(err).__name__}: "
                        f"{str(err).splitlines()[0][:120]})")
                    continue
                if best is None or score < best[2]:
                    best = (tt, tf, score)
        if best is None:
            log(f"autotune: moe_grouped t={t}: no viable candidate; class "
                f"keeps its cost-model default")
            continue
        entry = {"tile_t": best[0], "tile_f": best[1]}
        registry.validate_entry("moe_grouped", entry)
        db.record(shape_class.moe_key(t, e, h, f, jnp.bfloat16), entry,
                  source=src, ms=best[2] if hardware else None,
                  note=f"swept {len(space['tile_t'])}x"
                       f"{len(space['tile_f'])} candidates")
        log(f"autotune: moe_grouped t={t} e={e} h={h} f={f} -> "
            f"tile_t={best[0]} tile_f={best[1]}"
            + (f" ({best[2]:.3f} ms)" if hardware else " (verified)"))


def sweep_quant(db: cache.TuneDB, *, hardware: bool, reps: int,
                log=print) -> None:
    """(tile_m, tile_n, tile_k) sweep for the blockwise-scaled
    quantized matmul (quantization/scaled_matmul.py, registry family
    ``quant_matmul``), int8 and fp8 payload widths.

    Hardware sessions time a full quant_matmul f+b step per (m, k, n)
    class — median of ``reps`` value_and_grad calls per candidate,
    winner recorded with milliseconds. Interpret sessions VERIFY each
    candidate against the dequantize-einsum oracle over the SAME
    quantized payloads (fwd + both fp32-policy grads) and record the
    cost-model default — the moe sweep's policy."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.quantization import quant_matmul

    space = registry.TUNABLES["quant_matmul"].params
    ladder = (
        (4096, 1024, 4096),       # GPT-medium MLP up-projection class
        (8192, 4096, 1024),       # ...and its down-projection
    ) if hardware else ((96, 200, 160),)
    for m, k, n in ladder:
        for qdtype in ("int8", "fp8"):
            keys = jax.random.split(jax.random.PRNGKey(m + n), 3)
            lhs = jax.random.normal(keys[0], (m, k), jnp.float32)
            rhs = jax.random.normal(keys[1], (k, n), jnp.float32)
            do = jax.random.normal(keys[2], (m, n), jnp.float32)

            def loss(lhs, rhs, use):
                y = quant_matmul(lhs, rhs, dtype=qdtype, use_pallas=use)
                return jnp.vdot(y, do)

            best = None
            for tm in space["tile_m"]:
                for tn in space["tile_n"]:
                    for tk in space["tile_k"]:
                        entry = {"tile_m": tm, "tile_n": tn, "tile_k": tk}
                        db_c = cache.TuneDB()
                        db_c.record(
                            shape_class.quant_key(m, k, n, jnp.float32,
                                                  qdtype),
                            entry, source="sweep-candidate")
                        try:
                            with _sweep_env(), cache.pinned(db_c):
                                g = jax.jit(jax.grad(
                                    lambda lhs, rhs: loss(lhs, rhs, True),
                                    argnums=(0, 1)))
                                gp = g(lhs, rhs)
                                jax.block_until_ready(gp)
                                if hardware:
                                    times = []
                                    for _ in range(max(1, reps)):
                                        t0 = time.perf_counter()
                                        jax.block_until_ready(g(lhs, rhs))
                                        times.append(
                                            time.perf_counter() - t0)
                                    times.sort()
                                    score = times[len(times) // 2] * 1e3
                                else:
                                    go = jax.grad(
                                        lambda lhs, rhs: loss(lhs, rhs,
                                                              False),
                                        argnums=(0, 1))(lhs, rhs)
                                    for a, c in zip(gp, go):
                                        assert _maxdiff(a, c) < 0.1, \
                                            f"grad mismatch {_maxdiff(a, c)}"
                                    score = (
                                        abs(tm
                                            - cost_model.quant_tile_m_default(
                                                k, n))
                                        + abs(tn
                                              - cost_model.quant_tile_n_default(
                                                  n))
                                        + abs(tk
                                              - cost_model.quant_tile_k_default(
                                                  k)))
                        except Exception as err:  # noqa: BLE001
                            log(f"autotune: quant_matmul m={m} "
                                f"tiles=({tm},{tn},{tk}) {qdtype}: "
                                f"REJECTED ({type(err).__name__}: "
                                f"{str(err).splitlines()[0][:120]})")
                            continue
                        if best is None or score < best[3]:
                            best = (tm, tn, tk, score)
            if best is None:
                log(f"autotune: quant_matmul m={m} {qdtype}: no viable "
                    f"candidate; class keeps its cost-model default")
                continue
            if hardware:
                entry = {"tile_m": best[0], "tile_n": best[1],
                         "tile_k": best[2]}
            else:  # verified, but keep the measured-rule defaults
                entry = {
                    "tile_m": cost_model.quant_tile_m_default(k, n),
                    "tile_n": cost_model.quant_tile_n_default(n),
                    "tile_k": cost_model.quant_tile_k_default(k),
                }
            registry.validate_entry("quant_matmul", entry)
            db.record(
                shape_class.quant_key(m, k, n, jnp.float32, qdtype), entry,
                source="hardware" if hardware else "interpret+cost_model",
                ms=best[3] if hardware else None,
                note=f"swept {len(space['tile_m'])}x{len(space['tile_n'])}"
                     f"x{len(space['tile_k'])} candidates")
            log(f"autotune: quant_matmul m={m} k={k} n={n} {qdtype} -> "
                f"tile_m={entry['tile_m']} tile_n={entry['tile_n']} "
                f"tile_k={entry['tile_k']}"
                + (f" ({best[3]:.3f} ms)" if hardware else " (verified)"))


# ------------------------------------------------------------------
# BASELINE.md projection table
# ------------------------------------------------------------------

def sweep_overlap(db: cache.TuneDB, *, hardware: bool, reps: int,
                  log=print) -> None:
    """Chunk-count sweep for the decomposed collective matmul
    (parallel/overlap.py, registry family ``overlap_tp``).

    With >= 2 devices of the default backend a real ppermute ring is
    timed per (rows, ring, dtype) class — median of ``reps`` fused
    allgather->matmul steps per candidate chunk count, winner recorded
    with its milliseconds. Single-device sessions (the common 1-chip
    tunnel) record the cost-model default instead
    (``source: "cost_model_projection"``), which a later multi-chip
    session's measured entries overwrite — never the other way around."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices()
    ring = len(devs)
    # rank-local rows per class; interpret/CPU sessions sweep one small
    # class (ring mechanics verified, timings meaningless there anyway)
    ladder = (64, 512, 2048) if hardware else (64,)
    if ring < 2:
        for rows in ladder:
            key = shape_class.overlap_key(rows, 2, jnp.bfloat16)
            if db.get(key):
                continue
            db.record(
                key,
                {"chunks": cost_model.overlap_chunks_default(rows, 2)},
                source="cost_model_projection",
                note="single-device session; ring not timeable")
        log("autotune: overlap_tp projection entries recorded (1 device)")
        return

    from apex_tpu.parallel import overlap as ov

    mesh = Mesh(np.array(devs), ("ring",))
    hidden = 512
    for rows in ladder:
        x = jax.random.normal(jax.random.PRNGKey(0), (rows * ring, hidden),
                              jnp.bfloat16)
        w = jax.random.normal(jax.random.PRNGKey(1), (hidden, hidden),
                              jnp.bfloat16)
        best = None
        for chunks in registry.TUNABLES["overlap_tp"].params["chunks"]:
            if chunks > rows:
                continue

            def body(xl, wl, chunks=chunks):
                return ov.all_gather_matmul(xl, wl, "ring", 0, chunks)

            try:
                fn = jax.jit(jax.shard_map(
                    body, mesh=mesh, in_specs=(P("ring"), P()),
                    out_specs=P(), check_vma=False))
                fn(x, w).block_until_ready()  # compile + warm
                times = []
                for _ in range(max(1, reps)):
                    t0 = time.perf_counter()
                    fn(x, w).block_until_ready()
                    times.append(time.perf_counter() - t0)
                ms = sorted(times)[len(times) // 2] * 1e3
            except Exception as e:  # noqa: BLE001 — a failing candidate
                log(f"autotune: overlap_tp rows={rows} chunks={chunks} "
                    f"failed: {type(e).__name__}: {e}")
                continue
            if best is None or ms < best[1]:
                best = (chunks, ms)
        if best is None:
            continue
        key = shape_class.overlap_key(rows, ring, jnp.bfloat16)
        entry = {"chunks": best[0]}
        registry.validate_entry("overlap_tp", entry)
        db.record(key, entry,
                  source="hardware" if hardware else "interpret+cost_model",
                  ms=best[1], note=f"ring={ring} swept")
        log(f"autotune: overlap_tp rows={rows} ring={ring} -> "
            f"chunks={best[0]} ({best[1]:.3f} ms)")


def projection_table_md(device: Optional[str] = None) -> str:
    """Markdown FLOP/byte projection table over the benched ladder — the
    written per-rung plan VERDICT Next #8b asked for."""
    dev = device or shape_class.device_kind()
    lines = [
        "| rung (sq=sk, d) | pass | family | block | FLOPs | F/B fused | "
        "F/B unfused | flash ms (proj) | jnp ms (proj) |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for rung in cost_model.iter_flash_ladder():
        sq, d = rung["sq"], rung["d"]
        streaming = sq > cost_model.STREAM_SEQ
        for bwd in (False, True):
            b = cost_model.flash_block_default(sq, streaming, bwd)
            proj = cost_model.flash_projection(
                sq, sq, d, "bf16", b, b, streaming=streaming, bwd=bwd,
                device=dev)
            lines.append(
                f"| s={sq}, d={d} | {'bwd' if bwd else 'fwd'} | "
                f"{'stream' if streaming else 'res'} | {b} | "
                f"{proj['flops'] / 1e9:.1f} G | "
                f"{proj['flop_per_byte_fused']} | "
                f"{proj['flop_per_byte_unfused']} | "
                f"{proj['flash_ms']} | {proj['jnp_ms']} |")
    return "\n".join(lines)


# ------------------------------------------------------------------
# CLI
# ------------------------------------------------------------------

def run(*, out: Optional[str] = None, interpret: bool = False,
        kernels: Optional[list] = None, seqs: Optional[list] = None,
        hiddens: Optional[list] = None, dtype: str = "bf16", reps: int = 5,
        quick: bool = False, log=print) -> "cache.TuneDB":
    """Programmatic entry (bench.py --autotune calls this)."""
    from apex_tpu.ops._utils import on_tpu

    hardware = on_tpu() and not interpret
    saved_interp = os.environ.get("APEX_TPU_PALLAS_INTERPRET")
    if not hardware:
        # interpret verification must actually run interpret kernels even
        # if a TPU plugin initialized in this process; restored on exit so
        # a TPU caller's later kernels don't silently stay interpreted
        os.environ["APEX_TPU_PALLAS_INTERPRET"] = "1"
    try:
        return _run_inner(out=out, kernels=kernels, seqs=seqs,
                          hiddens=hiddens, dtype=dtype, reps=reps,
                          quick=quick, hardware=hardware, log=log)
    finally:
        if not hardware:
            if saved_interp is None:
                os.environ.pop("APEX_TPU_PALLAS_INTERPRET", None)
            else:
                os.environ["APEX_TPU_PALLAS_INTERPRET"] = saved_interp


def _run_inner(*, out, kernels, seqs, hiddens, dtype, reps, quick,
               hardware, log) -> "cache.TuneDB":
    kernels = kernels or ["flash", "layer_norm", "rms_norm", "optim_flat",
                          "overlap_tp", "paged_decode", "moe_grouped",
                          "quant_matmul"]
    seqs = seqs or ([256] if quick else [256, 512])
    hiddens = hiddens or ([256] if quick else [256, 1024])
    out_path = Path(out) if out else cache.cache_path()
    db = cache._load_quietly(out_path)  # merge into an existing file
    mode = "hardware" if hardware else "interpret"
    log(f"autotune: mode={mode} device={shape_class.device_kind()} "
        f"kernels={kernels} -> {out_path}")
    if "flash" in kernels:
        sweep_flash(db, seqs=seqs, dtype=dtype, hardware=hardware,
                    reps=reps, log=log)
        if not quick:
            project_flash_ladder(db, log=log)
    ln_kernels = [k for k in ("layer_norm", "rms_norm") if k in kernels]
    if ln_kernels:
        sweep_ln(db, kernels=ln_kernels, hiddens=hiddens, dtype=dtype,
                 hardware=hardware, reps=reps, log=log)
    if "optim_flat" in kernels:
        sweep_optim(db, hardware=hardware, reps=reps, log=log)
    if "overlap_tp" in kernels:
        sweep_overlap(db, hardware=hardware, reps=reps, log=log)
    if "paged_decode" in kernels:
        sweep_paged(db, hardware=hardware, reps=reps, log=log)
    if "moe_grouped" in kernels:
        sweep_moe(db, hardware=hardware, reps=reps, log=log)
    if "quant_matmul" in kernels:
        sweep_quant(db, hardware=hardware, reps=reps, log=log)
    path = db.save(out_path)
    cache.invalidate()  # the freshly-written file is live immediately
    log(f"autotune: wrote {len(db.entries)} entries to {path}")
    return db


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.tuning.autotune",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--interpret", action="store_true",
                    help="force interpret mode (CPU-safe; verifies + "
                         "projects instead of timing)")
    ap.add_argument("--out", default=None,
                    help=f"output tunedb path (default {cache.cache_path()})")
    ap.add_argument("--kernels",
                    default="flash,layer_norm,rms_norm,optim_flat,"
                            "overlap_tp,paged_decode,moe_grouped,"
                            "quant_matmul",
                    help="comma list: flash,layer_norm,rms_norm,"
                         "optim_flat,overlap_tp,paged_decode,moe_grouped,"
                         "quant_matmul")
    ap.add_argument("--seqs", default=None,
                    help="flash seq classes to sweep, comma list")
    ap.add_argument("--hiddens", default=None,
                    help="LN hidden classes to sweep, comma list")
    ap.add_argument("--dtype", default="bf16", choices=["bf16", "f32"])
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--quick", action="store_true",
                    help="smallest sweep (smoke/test hook)")
    args = ap.parse_args(argv)
    run(
        out=args.out,
        interpret=args.interpret,
        kernels=[k.strip() for k in args.kernels.split(",") if k.strip()],
        seqs=[int(s) for s in args.seqs.split(",")] if args.seqs else None,
        hiddens=[int(h) for h in args.hiddens.split(",")]
        if args.hiddens else None,
        dtype=args.dtype,
        reps=args.reps,
        quick=args.quick,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
