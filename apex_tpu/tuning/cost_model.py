"""Cost-model defaults: FLOP/byte + VMEM-footprint projection per shape class.

This is tier 0 of the tuning stack — what every kernel uses when neither
an env override nor a cache entry exists. Two jobs:

1. **Defaults.** Reproduce the measured v5e block choices (BASELINE.md
   variants + long-context tables) for every benched shape class, with ONE
   deliberate change: the resident flash family at ``s >= 2048`` now gets
   block 256 instead of 512. The old ``s <= 2048 -> 512`` rule shipped a
   measured ~1.6x regression at seq 2048 (VERDICT round 5, Weak #3): at
   2048 the whole K/V row (2048 x d) is VMEM-resident *on top of* the
   512-wide fp32 score tile and its bwd accumulators, which pushes the
   fused backward past the comfortable scoped-VMEM point — the same
   footprint cliff that made 256 the measured winner at s=4096 (8.9 ms vs
   15.1 ms). 2048 sits on the same side of the cliff as 4096, not 512.

2. **Projection.** A roofline estimate (``projected_ms``) of flash vs the
   unfused jnp path per shape class: compute time = FLOPs / peak, memory
   time = HBM bytes / bandwidth, projected = max of the two plus a
   per-grid-step overhead. The autotune driver uses it to rank candidates
   when no hardware answers (interpret mode), and ``flash_backend_default``
   uses it for the documented fallback-to-jnp rule:

   **Fallback threshold:** auto mode routes a shape class to the unfused
   jnp path when ``projected_flash_ms > FALLBACK_RATIO * projected_unfused_ms``
   (FALLBACK_RATIO = 1.1 — flash must not be projected >10% slower) or
   when the resident family's VMEM residency exceeds ``vmem_budget`` with
   the streaming family unavailable. A pinned cache entry
   (``{"backend": "jnp"}``) forces the fallback for a class regardless of
   projection; ``APEX_TPU_USE_PALLAS`` beats both (env > cache > model).

All numbers are per-chip and intentionally coarse — the model only has to
order candidates correctly, not predict milliseconds.
"""

from __future__ import annotations

from typing import Iterable

# Per device-kind substring: (peak bf16 matmul FLOP/s, HBM GB/s, VMEM MiB,
# HBM GiB, ICI link bytes/s per direction, ICI per-hop latency s).
# Same normalization as bench.peak_flops; VMEM is the scoped budget Mosaic
# enforces, not the raw SRAM size. The last three columns feed the
# whole-run planner: HBM capacity is the default feasibility budget
# (APEX_TPU_ANALYSIS_HBM_GB overrides), and the link columns are the
# per-device-kind interconnect model tuning/comm_model.py layers its
# collective times on (aggregate ICI bandwidth per chip divided across
# links; microsecond-class per-hop latency — coarse, like everything
# here: the model only has to ORDER configurations, not predict
# microseconds, and every number re-measures the day a TPU shows up).
DEVICE_SPECS = (
    ("v5lite", 197e12, 819e9, 16.0, 16.0, 186e9, 1e-6),
    ("v5e", 197e12, 819e9, 16.0, 16.0, 186e9, 1e-6),
    ("v5p", 459e12, 2765e9, 16.0, 95.0, 600e9, 1e-6),
    ("v6", 918e12, 1640e9, 32.0, 32.0, 448e9, 1e-6),
    ("v4", 275e12, 1228e9, 16.0, 32.0, 300e9, 1e-6),
    # nominal: interpret-mode ranking only; the link row keeps CPU-mesh
    # planner demos ordered the same way a pod would be
    ("cpu", 1e12, 50e9, 16.0, 16.0, 10e9, 5e-6),
)

# Per-grid-step launch/DMA-setup overhead (seconds). Coarse, but it is
# what penalizes absurdly small blocks (grid explosion) in the projection.
GRID_STEP_OVERHEAD_S = 2e-6

FALLBACK_RATIO = 1.1  # flash must not be projected >10% slower than jnp

# The s >= 2048 resident classes take block 256 (see module doc).
RESIDENT_SMALL_SEQ = 2048

# Resident -> streaming routing switch: max(sq, sk) strictly greater goes
# to the streaming family. MUST match ops/attention._STREAM_SEQ (pinned by
# tests/L0/test_tuning.py); duplicated here so the cost model stays
# importable without the kernel layer.
STREAM_SEQ = 4096


def device_spec(kind: str):
    kind = (kind or "cpu").lower().replace(" ", "")
    for sub, flops, bw, vmem, _hbm, _link, _lat in DEVICE_SPECS:
        if sub in kind:
            return flops, bw, vmem * 2**20
    return 197e12, 819e9, 16.0 * 2**20  # unknown TPU: assume v5e


def link_spec(kind: str):
    """(ICI bytes/s per direction, per-hop latency s) for a device kind —
    the planner's interconnect model (see the DEVICE_SPECS doc)."""
    kind = (kind or "cpu").lower().replace(" ", "")
    for sub, _fl, _bw, _vm, _hbm, link, lat in DEVICE_SPECS:
        if sub in kind:
            return link, lat
    return 186e9, 1e-6  # unknown TPU: assume v5e


def device_hbm_bytes(kind: str) -> float:
    """Per-device HBM capacity in bytes — the planner's default
    feasibility budget (APEX_TPU_ANALYSIS_HBM_GB beats it)."""
    kind = (kind or "cpu").lower().replace(" ", "")
    for sub, _fl, _bw, _vm, hbm, _link, _lat in DEVICE_SPECS:
        if sub in kind:
            return hbm * 2**30
    return 16.0 * 2**30  # unknown TPU: assume v5e


def _ceil128(s: int) -> int:
    return max(128, -(-int(s) // 128) * 128)


def _dtype_bytes(dt_token: str) -> int:
    return {"bf16": 2, "f16": 2, "f32": 4, "f64": 8}.get(dt_token, 2)


# ------------------------------------------------------------------
# flash attention
# ------------------------------------------------------------------

def flash_block_default(s: int, streaming: bool = False,
                        bwd: bool = False) -> int:
    """Default block for one sequence axis — the single source of truth
    behind ops/attention._block_size. Measured provenance:

    - streaming: 512 (v5e bench_long_context 2026-07-31 — 2.1-2.2x over
      256 at s=16k/32k; bigger tiles amortize the per-step scratch DMA)
    - resident s < 2048: min(512, padded) (v5e BASELINE.md variants —
      512 beats 256 by 1.12x at BERT-large b128 s512, 128 loses)
    - resident s >= 2048: 256 (s=4096 measured 8.9 ms vs 15.1 at 512;
      s=2048 moved into this class — the VERDICT Weak #3 regression fix,
      see module doc)

    ``bwd`` currently shares the forward's optimum — the knob exists so a
    tuned cache entry (or APEX_TPU_FLASH_BLOCK_BWD) can split them.
    """
    del bwd  # same default; the cache/env layers differentiate
    if streaming:
        return min(512, _ceil128(s))
    if s < RESIDENT_SMALL_SEQ:
        return min(512, _ceil128(s))
    return 256


def flash_flops(sq: int, sk: int, d: int, bwd: bool = False) -> float:
    """Matmul FLOPs of one attention instance ([sq,d]x[sk,d] scores +
    [sq,sk]x[sk,d] PV; backward re-does scores and adds dP/ds/dq/dk/dv —
    5 block matmuls vs the forward's 2)."""
    fwd = 2.0 * sq * sk * d * 2
    return fwd * 2.5 if bwd else fwd


def flash_hbm_bytes(sq: int, sk: int, d: int, bytes_el: int,
                    bwd: bool = False) -> float:
    """HBM traffic of the FUSED kernel: operands + outputs once (the
    score matrix never leaves VMEM)."""
    fwd = (sq + 2 * sk) * d * bytes_el + sq * d * bytes_el + sq * 4  # +lse
    if not bwd:
        return fwd
    # bwd re-reads q/k/v/o/do/lse and writes dq/dk/dv
    return (5 * (sq + sk) * d + sq) * bytes_el + sq * 4


def unfused_hbm_bytes(sq: int, sk: int, d: int, bytes_el: int,
                      bwd: bool = False) -> float:
    """HBM traffic of the unfused jnp path, which materializes the
    [sq, sk] fp32 score/probability matrix. XLA fuses the elementwise
    chain, so the matrix crosses HBM ~twice in the forward (scores out of
    the first dot, probabilities into the second) and ~three more times
    in the backward (p, dp, ds)."""
    operands = (sq + 2 * sk) * d * bytes_el + sq * d * bytes_el
    score_passes = 2 if not bwd else 5
    if bwd:
        operands = (5 * (sq + sk) * d + sq) * bytes_el
    return operands + score_passes * sq * sk * 4.0


def grid_steps(sq: int, sk: int, bq: int, bk: int, streaming: bool) -> int:
    nq = -(-_ceil128(sq) // bq)
    nk = -(-_ceil128(sk) // bk)
    return nq * nk if streaming else nq


def projected_ms(flops: float, hbm_bytes: float, n_grid_steps: int,
                 device: str) -> float:
    peak, bw, _ = device_spec(device)
    t = max(flops / peak, hbm_bytes / bw)
    return (t + n_grid_steps * GRID_STEP_OVERHEAD_S) * 1e3


def flash_projection(sq: int, sk: int, d: int, dt_token: str, bq: int,
                     bk: int, *, streaming: bool, bwd: bool,
                     device: str) -> dict:
    """Roofline rows for one candidate config — consumed by the autotune
    ranking and the BASELINE.md projection table."""
    b = _dtype_bytes(dt_token)
    fl = flash_flops(sq, sk, d, bwd)
    fused = flash_hbm_bytes(sq, sk, d, b, bwd)
    unfused = unfused_hbm_bytes(sq, sk, d, b, bwd)
    steps = grid_steps(sq, sk, bq, bk, streaming)
    return {
        "flops": fl,
        "fused_bytes": fused,
        "unfused_bytes": unfused,
        "flop_per_byte_fused": round(fl / fused, 1),
        "flop_per_byte_unfused": round(fl / unfused, 1),
        "grid_steps": steps,
        "flash_ms": round(projected_ms(fl, fused, steps, device), 4),
        "jnp_ms": round(projected_ms(fl, unfused, 0, device), 4),
    }


def flash_vmem_bytes(sq: int, sk: int, d: int, bytes_el: int, bq: int,
                     bk: int, *, streaming: bool, bwd: bool) -> int:
    """Projected peak VMEM residency of one kernel instance (the quantity
    the scoped-VMEM compile failures at s=8192 were about)."""
    skp, sqp = _ceil128(sk), _ceil128(sq)
    score = bq * bk * 4
    if streaming:
        # O(block) residency: q/k/v tiles + (acc, m, l) scratch
        base = (bq + 2 * bk) * d * bytes_el + bq * d * 4 + score
        return int(base * (3 if bwd else 1))
    if not bwd:
        # whole K/V row resident + q tile + fp32 acc
        return int(2 * skp * d * bytes_el + bq * d * (bytes_el + 4) + score)
    # fused bwd: whole q/do/dq rows + kv tile + dk/dv accumulators + score
    return int(
        3 * sqp * d * (bytes_el + 1)  # q, do (bf16) + fp32 dq out block
        + 2 * bk * d * bytes_el + 2 * bk * d * 4 + score
    )


def flash_backend_default(sq: int, sk: int, d: int, dt_token: str, *,
                          causal: bool, streaming: bool,
                          streaming_available: bool, device: str) -> str:
    """"pallas" or "jnp" — the documented auto-fallback rule (module doc).

    Applied per shape class at trace time; cheap (pure arithmetic)."""
    del causal  # causal halves both paths' work — ratio unchanged
    bq = flash_block_default(sq, streaming)
    bk = flash_block_default(sk, streaming)
    proj = flash_projection(sq, sk, d, dt_token, bq, bk,
                            streaming=streaming, bwd=True, device=device)
    if proj["flash_ms"] > FALLBACK_RATIO * proj["jnp_ms"]:
        return "jnp"
    if not streaming and not streaming_available:
        _, _, vmem = device_spec(device)
        need = flash_vmem_bytes(sq, sk, d, _dtype_bytes(dt_token), bq, bk,
                                streaming=False, bwd=True)
        if need > 0.75 * vmem:  # leave headroom for stack + double-buffer
            return "jnp"
    return "pallas"


# ------------------------------------------------------------------
# layer norm / rms norm
# ------------------------------------------------------------------

LN_BLOCK_ROWS_DEFAULT = 256  # today's measured choice (v5e-green, round 4)
# live fp32 row tiles per block in the LN bwd kernel (x, dy, dx)
_LN_LIVE_TILES = 3


def ln_block_rows_default(hidden: int, dtype_bytes: int = 4,
                          device: str = "cpu") -> int:
    """256 everywhere benched (v5e-green through h=4096-class shapes);
    only genuinely wide hidden shrinks the block, to keep the bwd
    kernel's 3 live fp32 row tiles inside the full scoped-VMEM budget
    (the wide-hidden LN A/B from VERDICT Next #3 sweeps this knob on
    hardware; until then the footprint guard is the default)."""
    del dtype_bytes  # kernels compute in fp32 regardless of input dtype
    _, _, vmem = device_spec(device)
    rows = LN_BLOCK_ROWS_DEFAULT
    while rows > 8 and rows * hidden * 4 * _LN_LIVE_TILES > vmem:
        rows //= 2
    return rows


# ------------------------------------------------------------------
# optimizer flat kernels
# ------------------------------------------------------------------

def optim_block_rows_default(n_tiles: int, device: str = "cpu") -> int:
    """Largest power-of-two row count (cap 2048, today's measured top)
    whose n_tiles double-buffered 128-lane fp32 tiles fit 75% of the VMEM
    budget (the measured v5e OOM was "17.03M vs limit 16.00M" — double
    buffering plus stack overshoots a naive 2x model, hence the margin).
    Reproduces the measured split exactly: 2 tiles (l2norm) -> 2048,
    7 tiles (adam/lamb) -> 1024 (pallas_optim.py's _BLOCK_ROWS vs
    _BLOCK_ROWS_WIDE). Anything above 2048 is autotune's to prove."""
    _, _, vmem = device_spec(device)
    rows = 2048
    while rows > 128 and rows * 128 * 4 * n_tiles * 2 > 0.75 * vmem:
        rows //= 2
    return rows


# ------------------------------------------------------------------
# decomposed collective matmul (parallel/overlap.py)
# ------------------------------------------------------------------

def overlap_chunks_default(rows_local: int, n_ranks: int) -> int:
    """Ring chunk count for the decomposed collective matmul. 2 (the
    bidirectional ring — both ICI link directions busy, per-hop latency
    halved) whenever the local block can split; 4 for large blocks where
    finer pieces pipeline the DMA behind the partial matmuls without the
    per-ppermute overhead dominating. 1 (plain unidirectional) when the
    block is a single row or there is no ring. Anything finer is
    autotune's to prove."""
    if n_ranks <= 1 or rows_local < 2:
        return 1
    return 4 if rows_local >= 512 else 2


# ------------------------------------------------------------------
# ragged paged-attention decode (ops/paged_attention.py)
# ------------------------------------------------------------------

def paged_block_rows_default(group: int) -> int:
    """Sublane padding of the decode q tile ([group, d] per (slot,
    kv-head) instance). The fp32 tile quantum is 8 sublanes, so anything
    below 8 pads to 8 anyway; pad dense-MHA groups of 1 straight to 8 and
    otherwise round the group up. Capped at 32 — beyond that the q tile's
    dead rows outweigh the MXU occupancy win on every projected shape;
    larger is autotune's to prove."""
    return max(8, min(32, -(-int(group) // 8) * 8))


def paged_q_tile_default(group: int) -> int:
    """Query tokens per work item of the ragged multi-query kernel. The
    q tile is ``q_tile x group`` rows, so the knob trades MXU occupancy
    (taller score tiles amortize the per-page dot setup for prefill
    chunks) against dead rows on decode-heavy mixes (a decode run is one
    token — everything past row ``group`` is masked). 16 tokens keeps
    dense/small-group tiles at the measured flash sweet spot; GQA groups
    >= 4 already fill the sublanes per token, so they drop to 8. Larger
    is autotune's to prove on chunk-heavy workloads."""
    return 8 if int(group) >= 4 else 16


# Oracle-fallback threshold for the paged family: below this much work
# the unfused gather oracle beats the ragged grid's per-step overhead.
# Work proxy = slots x paged KV span x GQA group — the group FOLDS IN
# because the oracle's score tensor ([S, Hkv, group, T]) and the
# kernel's useful MXU rows both scale with it, so a grouped class
# amortizes the grid sooner than a dense one with the same span. A
# pinned cache entry ({"backend": ...}) overrides per class;
# APEX_TPU_USE_PALLAS=1 beats both (env > cache > model, as everywhere).
PAGED_FALLBACK_WORK = 4096


def paged_backend_default(n_slots: int, max_blocks: int, block_size: int,
                          group: int) -> str:
    """"pallas" or "jnp" — the documented oracle-fallback rule for the
    ragged paged family (see PAGED_FALLBACK_WORK)."""
    span = max(1, int(max_blocks)) * int(block_size)
    work = int(n_slots) * span * max(1, int(group))
    return "jnp" if work < PAGED_FALLBACK_WORK else "pallas"


def paged_kv_fetch_default(block_size: int, d: int,
                           dtype_bytes: int = 2) -> int:
    """Pages pulled per grid step. More pages per step amortize the
    per-step overhead (the dominant cost at decode's tiny arithmetic
    intensity) and give the pipeline independent DMAs to overlap; the
    bound is the K+V page tiles resident per step staying comfortably
    inside scoped VMEM (1 MiB budget — decode shares VMEM with nothing
    else, but double buffering doubles the footprint)."""
    budget = 2**20
    fetch = 8
    while fetch > 1 and fetch * block_size * d * dtype_bytes * 2 > budget:
        fetch //= 2
    return fetch


# ------------------------------------------------------------------
# ragged grouped matmul (ops/grouped_matmul.py)
# ------------------------------------------------------------------

# Oracle-fallback threshold: below this many routed rows the grouped
# kernel's grid overhead (t_pad/tile_t + E work steps, each a masked
# partial matmul) exceeds what the dense one-hot segment einsum costs,
# so auto mode routes the class to the jnp oracle. A pinned cache entry
# ({"backend": ...}) overrides per class; APEX_TPU_USE_PALLAS=1 beats
# both (env > cache > model, as everywhere).
MOE_FALLBACK_ROWS = 256


def moe_tile_t_default(h: int, f: int, dtype_bytes: int = 2,
                       device: str = "cpu") -> int:
    """Rows per work tile. 512 (the MXU-occupancy sweet spot measured for
    the flash q tiles) shrunk by powers of two while the per-step
    resident tiles — lhs [tile_t, h] + rhs [h, tile_f] + out
    [tile_t, tile_f] double-buffered, plus the fp32 accumulator — push
    past 75% of scoped VMEM (wide-expert shapes: h=8192 bf16 drops to
    128). Anything finer is autotune's to prove."""
    _, _, vmem = device_spec(device)
    tf = moe_tile_f_default(f)
    tm = 512
    while tm > 128 and (
        2 * (tm * h + h * tf + tm * tf) * dtype_bytes + tm * tf * 4
    ) > 0.75 * vmem:
        tm //= 2
    return tm


def moe_tile_f_default(f: int) -> int:
    """Output columns per grid step: 256 (two MXU lanes' worth — enough
    reuse of the resident lhs tile without blowing the rhs block up),
    clamped to the padded output width for narrow experts."""
    return min(256, _ceil128(f))


def moe_backend_default(t: int, e: int, h: int, f: int,
                        device: str = "cpu") -> str:
    """"pallas" or "jnp" — the documented oracle-fallback rule: tiny
    routed-row counts can't amortize the ragged grid (MOE_FALLBACK_ROWS),
    so the dense segment oracle wins there."""
    del e, h, f, device  # row count dominates; the rest is autotune's
    return "jnp" if t < MOE_FALLBACK_ROWS else "pallas"


# ------------------------------------------------------------------
# blockwise-scaled low-precision matmul (quantization/scaled_matmul.py)
# ------------------------------------------------------------------

# Oracle-fallback threshold: below this many output rows the quantize
# prologue + grid overhead exceed what the dequantize-einsum oracle
# costs, so auto mode routes the class to the oracle. A pinned cache
# entry ({"backend": ...}) overrides per class; APEX_TPU_USE_PALLAS=1
# beats both (env > cache > model, as everywhere).
QUANT_FALLBACK_ROWS = 256


def quant_tile_m_default(k: int, n: int, device: str = "cpu") -> int:
    """Output rows per grid step. 256 (eight int8-native 32-sublane
    tiles — the narrow payload keeps the resident footprint small, so
    taller tiles than the bf16 gmm default are affordable) shrunk by
    powers of two while the per-step residents — int8 lhs/rhs tiles +
    fp32 accumulator + output, double-buffered inputs — push past 75%
    of scoped VMEM. Anything finer is autotune's to prove."""
    _, _, vmem = device_spec(device)
    tn = quant_tile_n_default(n)
    tk = quant_tile_k_default(k)
    tm = 256
    while tm > 32 and (
        2 * (tm * tk + tk * tn) * 1 + tm * tn * (4 + 4)
    ) > 0.75 * vmem:
        tm //= 2
    return tm


def quant_tile_n_default(n: int) -> int:
    """Output columns per grid step: 256 (two MXU lanes' worth, the
    moe_tile_f rationale), clamped to the padded width for narrow
    outputs."""
    return min(256, _ceil128(n))


def quant_tile_k_default(k: int) -> int:
    """Contraction elements per k-step — ALSO the quantization block,
    so this knob trades scale resolution (smaller blocks isolate
    outliers better) against MXU occupancy and sidecar bytes. 256
    matches the quantized-collectives chunk that the comms fuzz proved,
    clamped to the padded contraction for narrow k."""
    return min(256, _ceil128(k))


def quant_backend_default(m: int, k: int, n: int,
                          device: str = "cpu") -> str:
    """"pallas" or "jnp" — the documented oracle-fallback rule: tiny
    row counts can't amortize the quantize prologue + grid
    (QUANT_FALLBACK_ROWS)."""
    del k, n, device  # row count dominates; the rest is autotune's
    return "jnp" if m < QUANT_FALLBACK_ROWS else "pallas"


# ------------------------------------------------------------------
# softmax tiling
# ------------------------------------------------------------------

def softmax_row_chunk_default() -> int:
    """0 = no tiling (today's behavior: XLA fuses the whole pass). The
    knob exists for the autotuner: giant [rows, cols] score tensors can
    be streamed in row chunks to cap the fp32 intermediate."""
    return 0


# ------------------------------------------------------------------
# whole-program memory model (the planner's input)
# ------------------------------------------------------------------

# The static peak-HBM estimator lives with the other jaxpr walkers in
# apex_tpu.analysis.memory but is re-exported here because it is a COST
# MODEL: the whole-run auto-parallelism planner (ROADMAP open item 4)
# scores candidate (dp x tp x pp x ZeRO) configurations by calling
# estimate_peak_hbm(step_fn, args, mesh, specs) per candidate — a
# trace-only, per-device projection — and rejecting the ones whose peak
# exceeds device_spec()'s HBM before any timing happens. Import is lazy
# at module level only in the sense that analysis.memory itself imports
# jax lazily, so this module stays importable without the kernel layer.
from apex_tpu.analysis.memory import (  # noqa: E402,F401
    MemoryEstimate,
    estimate_peak_hbm,
)


def iter_flash_ladder() -> Iterable[dict]:
    """The benched shape-class ladder (BASELINE.md rungs) — shared by the
    projection table generator and the autotune default sweep."""
    for sq, d, causal in (
        (512, 64, False),    # BERT-large
        (1024, 64, True),    # GPT-medium
        (2048, 64, True),    # the regression class
        (4096, 128, True),   # long-context resident boundary
        (8192, 128, True),   # streaming
        (16384, 128, True),  # streaming
    ):
        yield {"sq": sq, "sk": sq, "d": d, "causal": causal}
