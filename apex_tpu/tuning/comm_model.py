"""Collective cost model: analytic bytes-on-wire + a link-time layer.

The whole-run planner (tuning/planner.py) scores candidate
(dp x tp x pp x ep x ZeRO x gate) configurations by composing compute
time (cost_model.py's FLOP/byte machinery) with COMMUNICATION time.
This module is the comm half, built from two layers that must never
disagree with the rest of the repo:

1. **Bytes on wire.** One analytic byte count per collective. For the
   DDP / ZeRO gradient paths these are *delegations to the PR-5
   formulas* — ``parallel/quantized_collectives.py``'s
   ``quantized_wire_bytes`` / ``quantized_scatter_wire_bytes`` for the
   int8 paths and the same ``n * itemsize`` payload count the
   ``comms/bytes_on_wire`` counters record for the exact paths
   (parallel/ddp.py, contrib/optimizers/_sharding.py) — so the planner
   and the observability counters share ONE definition of wire bytes
   (pinned by tests/L0/test_planner.py). The remaining collectives
   (all_gather, reduce_scatter, all_to_all, the ppermute ring step)
   follow the same convention: count the logical payload once.

2. **Link time.** A per-device-kind interconnect model
   (``cost_model.link_spec``: ICI bytes/s per direction + per-hop
   latency) with the standard ring algorithmics layered on top:
   a psum moves ``2*(w-1)/w`` of its payload per device over ``2*(w-1)``
   hops, reduce_scatter / all_gather half that, an all_to_all moves the
   ``(w-1)/w`` remote fraction, a ppermute step is one neighbor hop.
   Quantized collectives time their own (already pass- and
   scale-inclusive) wire formula over the same ring.

Like every cost model here the numbers are deliberately coarse — they
only have to order configurations, and they re-measure the day a TPU
shows up (BENCH_r01-r05 are all "tpu backend unavailable").
"""

from __future__ import annotations

__all__ = [
    "all_gather_wire_bytes",
    "all_to_all_wire_bytes",
    "collective_seconds",
    "ddp_psum_wire_bytes",
    "ppermute_step_wire_bytes",
    "reduce_scatter_wire_bytes",
    "zero_allgather_wire_bytes",
    "zero_scatter_wire_bytes",
]

# ring passes over the payload per device / hop counts per collective
# kind (w = axis size): the classic bidirectional-ring algorithmics the
# XLA collectives lower to on ICI
_RING = {
    # kind: (payload_fraction(w), hops(w))
    "psum": (lambda w: 2.0 * (w - 1) / w, lambda w: 2 * (w - 1)),
    "all_gather": (lambda w: (w - 1) / w, lambda w: w - 1),
    "reduce_scatter": (lambda w: (w - 1) / w, lambda w: w - 1),
    "all_to_all": (lambda w: (w - 1) / w, lambda w: w - 1),
    "ppermute": (lambda w: 1.0, lambda w: 1),
}


# ---------------------------------------------------------------------------
# bytes on wire — the counted payload, ONE definition per path
# ---------------------------------------------------------------------------

def ddp_psum_wire_bytes(n_elems: int, itemsize: int, *,
                        quantized: bool = False,
                        chunk: int | None = None) -> int:
    """Counted wire bytes of one DDP gradient all-reduce over an
    ``n_elems`` flat bucket — EXACTLY what parallel/ddp.py records on
    ``comms/bytes_on_wire``: ``n * itemsize`` for the exact psum,
    ``quantized_wire_bytes(n)`` for the int8 path."""
    n = int(n_elems)
    if not quantized:
        return n * int(itemsize)
    from apex_tpu.parallel.quantized_collectives import (
        DEFAULT_CHUNK,
        quantized_wire_bytes,
    )

    return quantized_wire_bytes(n, chunk or DEFAULT_CHUNK)


def zero_scatter_wire_bytes(n_elems: int, itemsize: int, world: int, *,
                            quantized: bool = False,
                            chunk: int | None = None) -> int:
    """Counted wire bytes of the ZeRO-2 gradient reduce-scatter —
    EXACTLY what contrib/optimizers/_sharding.py records:
    ``n * itemsize`` exact, ``quantized_scatter_wire_bytes(n, world)``
    int8."""
    n = int(n_elems)
    if not quantized:
        return n * int(itemsize)
    from apex_tpu.parallel.quantized_collectives import (
        DEFAULT_CHUNK,
        quantized_scatter_wire_bytes,
    )

    return quantized_scatter_wire_bytes(n, int(world),
                                        chunk or DEFAULT_CHUNK)


def zero_allgather_wire_bytes(shard_elems: int, itemsize: int,
                              world: int) -> int:
    """Counted wire bytes of the ZeRO updated-param gather — EXACTLY
    the ``world * shard * itemsize`` allreduce-sized payload
    _sharding.all_gather_flat records (place-in-zeros + psum)."""
    return int(world) * int(shard_elems) * int(itemsize)


def all_gather_wire_bytes(gathered_elems: int, itemsize: int) -> int:
    """Payload count of an all_gather whose OUTPUT is
    ``gathered_elems`` (each device contributes 1/w of it)."""
    return int(gathered_elems) * int(itemsize)


def reduce_scatter_wire_bytes(full_elems: int, itemsize: int) -> int:
    """Payload count of a reduce_scatter whose INPUT is
    ``full_elems`` per device."""
    return int(full_elems) * int(itemsize)


def all_to_all_wire_bytes(local_elems: int, itemsize: int) -> int:
    """Payload count of an all_to_all over a ``local_elems`` per-device
    buffer (the EP dispatch/return unit)."""
    return int(local_elems) * int(itemsize)


def ppermute_step_wire_bytes(local_elems: int, itemsize: int) -> int:
    """Payload of one ring hop (the pipeline p2p / decomposed-matmul
    chunk unit)."""
    return int(local_elems) * int(itemsize)


# ---------------------------------------------------------------------------
# link time
# ---------------------------------------------------------------------------

def collective_seconds(kind: str, payload_bytes: float, world: int,
                       device: str = "cpu") -> float:
    """Projected seconds of one collective: the counted payload run
    through the ring algorithmics over the device kind's link model.

    ``kind``: psum | all_gather | reduce_scatter | all_to_all |
    ppermute. ``payload_bytes`` is the COUNTED payload (the wire-bytes
    functions above); the ring fraction/hops are applied here, so a
    quantized payload (whose formula already folds in its passes and
    scale sidecars) rides the same ring as the exact one. world <= 1 is
    free."""
    if kind not in _RING:
        # validated BEFORE the degenerate-world early return: a typo'd
        # kind must fail loudly even on a size-1 axis
        raise ValueError(
            f"unknown collective kind {kind!r} (known: {sorted(_RING)})")
    w = int(world)
    if w <= 1 or payload_bytes <= 0:
        return 0.0
    from apex_tpu.tuning.cost_model import link_spec

    frac, hops = _RING[kind]
    bw, lat = link_spec(device)
    return hops(w) * lat + frac(w) * float(payload_bytes) / bw
