"""Shape-class keys for the kernel autotuner.

A *shape class* is the equivalence class of call shapes that share one
tuned kernel configuration. Exact shapes would fragment the cache into
thousands of entries that can never be swept on real hardware; raw kernel
names would collapse shapes with very different roofline positions into
one. The classes here bucket the axes that move the optimum:

- sequence / row counts  -> next power of two (floor 128, the Mosaic lane
  quantum every block is padded to anyway)
- hidden / head dim      -> next power of two (floor 8)
- dtype                  -> canonical short name (bf16 / f16 / f32 / ...)
- boolean structure      -> causal, GQA (group > 1), streaming family,
  fwd vs bwd pass
- device kind            -> normalized jax device_kind ("tpuv5lite",
  "cpu", ...), so one cache file can carry several generations

The key is a flat, order-stable string — the JSON cache's dict key and
the unit the autotune driver sweeps::

    flash|tpuv5lite|pass=fwd|family=res|sq=2048|sk=2048|d=128|dt=bf16|causal=1|gqa=0

Everything here is pure string/arithmetic work (no jax imports beyond the
lazy device probe) so it is safe at trace time inside jitted code.
"""

from __future__ import annotations

from typing import Mapping

import jax


def pow2_bucket(n: int, floor: int = 128) -> int:
    """Smallest power of two >= max(n, 1), clamped below by ``floor``."""
    n = max(int(n), 1)
    b = floor
    while b < n:
        b *= 2
    return b


def seq_bucket(s: int) -> int:
    return pow2_bucket(s, floor=128)


def hidden_bucket(h: int) -> int:
    return pow2_bucket(h, floor=8)


def dtype_token(dtype) -> str:
    """Canonical short dtype name ("bfloat16" -> "bf16")."""
    import jax.numpy as jnp

    name = jnp.dtype(dtype).name if dtype is not None else "f32"
    return {
        "bfloat16": "bf16",
        "float16": "f16",
        "float32": "f32",
        "float64": "f64",
        "float8_e4m3fn": "f8e4m3",
        "float8_e5m2": "f8e5m2",
    }.get(name, name)


def device_kind() -> str:
    """Normalized device kind of the default backend ("tpuv5lite", "cpu").

    Never raises: before backend init (or when init fails) it reports
    "cpu", matching ops/_utils.on_tpu's conservatism.
    """
    try:
        kind = getattr(jax.devices()[0], "device_kind", "cpu")
    except Exception:  # pragma: no cover — backend init failure
        kind = "cpu"
    return str(kind).lower().replace(" ", "")


def class_key(kernel: str, features: Mapping[str, object],
              device: str | None = None) -> str:
    """Build the canonical cache key for (kernel, shape class).

    ``features`` values are rendered as ``k=v`` tokens in sorted key
    order; booleans render as 0/1 so keys are diff-stable across python
    versions. ``device`` defaults to the current backend's kind.
    """
    dev = device if device is not None else device_kind()
    toks = []
    for k in sorted(features):
        v = features[k]
        if isinstance(v, bool):
            v = int(v)
        toks.append(f"{k}={v}")
    return "|".join([kernel, dev] + toks)


# ------------------------------------------------------------------
# per-kernel feature builders — ONE place defines what each kernel's
# shape class looks like, shared by the ops layer, the autotune driver
# and the committed snapshots (a key built anywhere matches everywhere)
# ------------------------------------------------------------------

def flash_features(sq: int, sk: int, d: int, dtype, causal: bool,
                   group: int, streaming: bool, bwd: bool) -> dict:
    return {
        "pass": "bwd" if bwd else "fwd",
        "family": "stream" if streaming else "res",
        "sq": seq_bucket(sq),
        "sk": seq_bucket(sk),
        "d": hidden_bucket(d),
        "dt": dtype_token(dtype),
        "causal": bool(causal),
        "gqa": group > 1,
    }


def flash_key(sq, sk, d, dtype, causal, group, streaming, bwd,
              device=None) -> str:
    return class_key(
        "flash",
        flash_features(sq, sk, d, dtype, causal, group, streaming, bwd),
        device,
    )


def ln_features(hidden: int, dtype) -> dict:
    return {"h": hidden_bucket(hidden), "dt": dtype_token(dtype)}


def ln_key(kernel: str, hidden: int, dtype, device=None) -> str:
    """kernel is "layer_norm" or "rms_norm" (separate families: the bwd
    tile counts differ — LN carries dbeta, RMS does not)."""
    return class_key(kernel, ln_features(hidden, dtype), device)


def optim_features(n_tiles: int) -> dict:
    """Optimizer flat kernels are shape-oblivious (1-D streams); what
    moves the block optimum is the LIVE TILE COUNT (operands + outputs,
    double-buffered) against scoped VMEM — the exact quantity behind the
    measured _BLOCK_ROWS_WIDE split (pallas_optim.py)."""
    return {"tiles": int(n_tiles)}


def optim_key(n_tiles: int, device=None) -> str:
    return class_key("optim_flat", optim_features(n_tiles), device)


def overlap_features(rows_local: int, n_ranks: int, dtype) -> dict:
    """Decomposed-collective-matmul chunking (parallel/overlap.py): the
    optimum moves with the rank-local row count (how finely the block can
    split), the ring size (hop count) and the payload dtype. Rows bucket
    with floor 8 — SP blocks can be tiny on big meshes."""
    return {
        "rows": pow2_bucket(rows_local, floor=8),
        "ring": int(n_ranks),
        "dt": dtype_token(dtype),
    }


def overlap_key(rows_local: int, n_ranks: int, dtype, device=None) -> str:
    return class_key(
        "overlap_tp", overlap_features(rows_local, n_ranks, dtype), device)


def paged_features(n_slots: int, max_blocks: int, block_size: int,
                   group: int, d: int, dtype,
                   total_q: int | None = None) -> dict:
    """Ragged multi-query paged attention (ops/paged_attention.py): the
    optimum moves with the batch width (slots), the packed query rows
    (total_q — what separates decode-only calls from chunked-prefill
    mixes; defaults to one query per slot, the decode entry's shape),
    the paged KV span a slot can reach (max_blocks * block_size — what
    the fetch loop walks), the page size (DMA granule), the GQA group
    (q tile rows per token) and head dim."""
    return {
        "slots": pow2_bucket(n_slots, floor=8),
        "tq": pow2_bucket(total_q if total_q else n_slots, floor=8),
        "kv": seq_bucket(max_blocks * block_size),
        "bs": int(block_size),
        "g": int(group),
        "d": hidden_bucket(d),
        "dt": dtype_token(dtype),
    }


def paged_key(n_slots: int, max_blocks: int, block_size: int, group: int,
              d: int, dtype, device=None, total_q: int | None = None) -> str:
    return class_key(
        "paged_decode",
        paged_features(n_slots, max_blocks, block_size, group, d, dtype,
                       total_q),
        device,
    )


def moe_features(t: int, e: int, h: int, f: int, dtype) -> dict:
    """Ragged grouped matmul (ops/grouped_matmul.py): the optimum moves
    with the routed row count (t = tokens x top_k — seq bucket, so one
    tuned entry covers a batch-size neighborhood), the expert count (work
    items per grid, rhs block count), hidden and ffn widths (the resident
    lhs/rhs tile footprint) and the payload dtype."""
    return {
        "t": seq_bucket(t),
        "e": int(e),
        "h": hidden_bucket(h),
        "f": hidden_bucket(f),
        "dt": dtype_token(dtype),
    }


def moe_key(t: int, e: int, h: int, f: int, dtype, device=None) -> str:
    return class_key("moe_grouped", moe_features(t, e, h, f, dtype), device)


def quant_features(m: int, k: int, n: int, dtype, qdtype: str) -> dict:
    """Blockwise-scaled low-precision matmul (quantization/
    scaled_matmul.py): the optimum moves with the row count (m — seq
    bucket, batch dims collapse into it), the contraction and output
    widths (the resident tile footprint AND the k-tile = quantization
    block trade), the ORIGINAL operand dtype (what the narrow payload
    is saving against) and the payload width ("int8" | "fp8")."""
    return {
        "m": seq_bucket(m),
        "k": hidden_bucket(k),
        "n": hidden_bucket(n),
        "dt": dtype_token(dtype),
        "q": str(qdtype),
    }


def quant_key(m: int, k: int, n: int, dtype, qdtype: str,
              device=None) -> str:
    return class_key("quant_matmul",
                     quant_features(m, k, n, dtype, qdtype), device)


def softmax_features(rows: int, cols: int, dtype) -> dict:
    return {
        "rows": seq_bucket(rows),
        "cols": seq_bucket(cols),
        "dt": dtype_token(dtype),
    }


def softmax_key(rows: int, cols: int, dtype, device=None) -> str:
    return class_key("softmax", softmax_features(rows, cols, dtype), device)
