"""Persistent tune cache: JSON entries keyed by shape class.

Resolution order at a kernel call site (highest wins):

1. **Env var** — ``APEX_TPU_FLASH_BLOCK[_BWD]``, ``APEX_TPU_LN_BLOCK_ROWS``,
   ``APEX_TPU_OPTIM_BLOCK_ROWS``, ``APEX_TPU_SOFTMAX_CHUNK``,
   ``APEX_TPU_USE_PALLAS``. Enforced at the op layer (ops/attention.py
   etc.), NOT here — the cache never sees a call the env already decided,
   so A/B sweeps keep working unchanged on a tuned machine.
2. **Pinned DB** — a ``pinned(db)`` context (preflight probes pin the
   resolved DB so a mid-probe cache reload can't skew results; tests pin
   synthetic DBs).
3. **User cache file** — ``$APEX_TPU_TUNEDB`` or
   ``~/.cache/apex_tpu/tunedb.json`` (what the autotune driver writes).
4. **Committed snapshot** — ``benchmarks/tunedb/*.json`` in a repo
   checkout (the v5e sweep results ride the repo, so a fresh container
   starts from measured configs, not from scratch).
5. **Cost model** — ``cost_model.py`` defaults (handled by callers when
   ``lookup`` returns None).

``APEX_TPU_TUNE=0`` disables layers 2-4 entirely (pure cost-model
defaults — the knob preflight and A/B baselines use).

File schema (version 1)::

    {"version": 1,
     "entries": {"<class key>": {"params": {...}, "source": "...",
                                 "ms": 1.23, "note": "..."}}}

Class keys embed the device kind (shape_class.class_key), so one file may
safely carry several generations' entries.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
from pathlib import Path
from typing import Dict, Optional

from apex_tpu.utils.envvars import env_flag, env_str

SCHEMA_VERSION = 1

_lock = threading.RLock()
_pinned_db: Optional["TuneDB"] = None
_active_db: Optional["TuneDB"] = None  # lazy singleton (snapshot + user file)


class TuneDB:
    """In-memory view of a tune database; persists as JSON."""

    def __init__(self, entries: Optional[Dict[str, dict]] = None):
        self.entries: Dict[str, dict] = dict(entries or {})

    # -- access -----------------------------------------------------
    def get(self, key: str) -> Optional[dict]:
        e = self.entries.get(key)
        return dict(e["params"]) if e and isinstance(e.get("params"), dict) \
            else None

    def record(self, key: str, params: dict, *, source: str,
               ms: Optional[float] = None, note: Optional[str] = None):
        entry: dict = {"params": dict(params), "source": source}
        if ms is not None:
            entry["ms"] = round(float(ms), 4)
        if note:
            entry["note"] = note
        self.entries[key] = entry

    def merge(self, other: "TuneDB") -> "TuneDB":
        """Entries in ``other`` override same-key entries here."""
        merged = dict(self.entries)
        merged.update(other.entries)
        return TuneDB(merged)

    # -- persistence ------------------------------------------------
    def to_json(self) -> dict:
        return {"version": SCHEMA_VERSION, "entries": self.entries}

    def save(self, path: os.PathLike | str) -> Path:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(path.suffix + ".tmp")
        tmp.write_text(json.dumps(self.to_json(), indent=1, sort_keys=True))
        tmp.replace(path)  # atomic: concurrent readers see old or new
        return path

    @classmethod
    def load(cls, path: os.PathLike | str) -> "TuneDB":
        data = json.loads(Path(path).read_text())
        if data.get("version") != SCHEMA_VERSION:
            raise ValueError(
                f"tunedb {path}: schema version {data.get('version')!r} "
                f"(this build reads {SCHEMA_VERSION})"
            )
        entries = data.get("entries")
        if not isinstance(entries, dict):
            raise ValueError(f"tunedb {path}: 'entries' must be an object")
        for k, e in entries.items():
            if not isinstance(e, dict) or not isinstance(e.get("params"), dict):
                raise ValueError(f"tunedb {path}: entry {k!r} lacks 'params'")
        return cls(entries)


def cache_path() -> Path:
    env = env_str("APEX_TPU_TUNEDB")
    if env:
        return Path(env)
    return Path.home() / ".cache" / "apex_tpu" / "tunedb.json"


def snapshot_dir() -> Path:
    """benchmarks/tunedb/ next to the apex_tpu package (repo checkouts);
    may not exist in an installed wheel — callers must tolerate that."""
    return Path(__file__).resolve().parents[2] / "benchmarks" / "tunedb"


def _load_quietly(path: Path) -> TuneDB:
    try:
        return TuneDB.load(path)
    except FileNotFoundError:
        return TuneDB()
    except Exception as e:  # noqa: BLE001 — a corrupt cache must never
        # take down training; it costs a warning and the defaults
        import warnings

        warnings.warn(f"apex_tpu.tuning: ignoring unreadable tunedb "
                      f"{path}: {e}", stacklevel=3)
        return TuneDB()


def _build_active() -> TuneDB:
    db = TuneDB()
    snap = snapshot_dir()
    if snap.is_dir():
        for f in sorted(snap.glob("*.json")):
            db = db.merge(_load_quietly(f))
    db = db.merge(_load_quietly(cache_path()))  # user cache wins over snapshot
    return db


def tuning_enabled() -> bool:
    return env_flag("APEX_TPU_TUNE", default=True)


def active_db() -> TuneDB:
    """The resolved runtime DB (snapshot + user cache), loaded once per
    process; ``invalidate()`` forces a reload (tests, post-autotune)."""
    global _active_db
    with _lock:
        if _pinned_db is not None:
            return _pinned_db
        if _active_db is None:
            _active_db = _build_active()
        return _active_db


def invalidate() -> None:
    global _active_db
    with _lock:
        _active_db = None


@contextlib.contextmanager
def pinned(db: Optional[TuneDB]):
    """Pin the tune DB for the context's duration. ``pinned(TuneDB())``
    pins pure cost-model defaults; ``pinned(active_db())`` freezes the
    current resolution (what preflight does around its probes)."""
    global _pinned_db
    with _lock:
        prev = _pinned_db
        _pinned_db = db if db is not None else TuneDB()
    try:
        yield
    finally:
        with _lock:
            _pinned_db = prev


def lookup(key: str) -> Optional[dict]:
    """Tuned params for a class key, or None (-> cost-model default).
    Respects pinning and APEX_TPU_TUNE=0.

    Every resolution lands a hit/miss sample in the observability
    registry (``tuning/lookups``, labels ``result`` + ``source``) —
    lookups happen at TRACE time, so the counts answer "which shape
    classes ran on cost-model defaults this build" without touching the
    compiled program."""
    from apex_tpu.observability.registry import inc_counter

    if _pinned_db is not None:
        params = _pinned_db.get(key)
        inc_counter("tuning/lookups", 1, source="pinned",
                    result="hit" if params is not None else "miss")
        return params
    if not tuning_enabled():
        inc_counter("tuning/lookups", 1, source="disabled", result="miss")
        return None
    params = active_db().get(key)
    inc_counter("tuning/lookups", 1, source="cache",
                result="hit" if params is not None else "miss")
    return params
