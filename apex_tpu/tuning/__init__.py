"""Kernel autotuning subsystem.

One registry of tunable parameters per Pallas kernel family (registry.py),
keyed by shape class (shape_class.py), resolved through three layers:

    env var  >  tune cache (pinned / user file / committed snapshot)
             >  cost-model default (cost_model.py)

The ops layer calls the ``*_config`` helpers below at trace time; the
autotune driver (``python -m apex_tpu.tuning.autotune``) sweeps the
registry's candidate space per shape class and writes the cache
(cache.py — ``~/.cache/apex_tpu/tunedb.json`` by default, snapshots
committed under ``benchmarks/tunedb/``). See docs/tuning.md.

Helpers here never raise on cache weirdness: an out-of-range cached value
is clamped or ignored (cost of a wrong entry = a slow kernel, never a
crash); env-var validation stays at the op layer where it always lived.
"""

from __future__ import annotations

from apex_tpu.tuning import comm_model, cost_model, registry, shape_class
from apex_tpu.tuning.cache import (
    TuneDB,
    active_db,
    cache_path,
    invalidate,
    lookup,
    pinned,
    snapshot_dir,
    tuning_enabled,
)
from apex_tpu.tuning.shape_class import (
    class_key,
    device_kind,
    dtype_token,
    flash_key,
    ln_key,
    moe_key,
    optim_key,
    paged_key,
    quant_key,
    softmax_key,
)

__all__ = [
    "TuneDB", "active_db", "cache_path", "invalidate", "lookup", "pinned",
    "snapshot_dir", "tuning_enabled", "class_key", "device_kind",
    "dtype_token", "flash_key", "ln_key", "moe_key", "optim_key",
    "paged_key", "quant_key", "softmax_key", "flash_config",
    "ln_block_rows", "moe_grouped_config", "optim_block_rows",
    "paged_decode_config", "quant_matmul_config", "softmax_row_chunk",
    "comm_model", "cost_model", "registry", "shape_class",
]


def _ceil128(s: int) -> int:
    return max(128, -(-int(s) // 128) * 128)


def _clamp_block(b, s: int, default: int) -> int:
    """A cached block must be a positive multiple of 128; clamp to the
    padded sequence (same rule as the env override) and fall back to the
    default on anything malformed."""
    try:
        b = int(b)
    except (TypeError, ValueError):
        return default
    if b <= 0 or b % 128:
        return default
    return min(b, _ceil128(s))


def flash_config(sq: int, sk: int, d: int, dtype, causal: bool, group: int,
                 streaming: bool, bwd: bool) -> dict:
    """Resolved flash config for one shape class:
    ``{"block_q", "block_k", "backend"}``. Cache entry wins where present
    (field-wise); cost model fills the rest. Env overrides are applied by
    ops/attention.py BEFORE consulting this.

    The ops layer consumes the blocks here (attention._flash_blocks) but
    routes the backend decision through ``flash_backend_auto`` — that one
    reads the pin bwd-key-first so fwd and bwd can never split backends;
    the ``backend`` field in this resolved view reports the per-pass
    entry for introspection/tooling."""
    dq = cost_model.flash_block_default(sq, streaming, bwd)
    dk = cost_model.flash_block_default(sk, streaming, bwd)
    dq, dk = min(dq, _ceil128(sq)), min(dk, _ceil128(sk))
    cfg = {"block_q": dq, "block_k": dk, "backend": "pallas"}
    entry = lookup(flash_key(sq, sk, d, dtype, causal, group, streaming, bwd))
    if entry:
        cfg["block_q"] = _clamp_block(entry.get("block_q"), sq, dq)
        cfg["block_k"] = _clamp_block(entry.get("block_k"), sk, dk)
        if entry.get("backend") in ("pallas", "jnp"):
            cfg["backend"] = entry["backend"]
    return cfg


def flash_backend_auto(sq: int, sk: int, d: int, dtype, causal: bool,
                       group: int, streaming: bool,
                       streaming_available: bool) -> str:
    """"pallas" or "jnp" for auto mode (use_pallas=None, no env override):
    a cached ``backend`` pin wins; otherwise the documented cost-model
    fallback rule (cost_model.flash_backend_default).

    The decision is made ONCE per shape class for forward and backward
    together (a split backend would recompute residuals inconsistently),
    so the pin is read from the bwd-pass key first — the pass that
    dominates cost and VMEM pressure — falling back to the fwd-pass key;
    the autotune driver writes both."""
    for bwd in (True, False):
        entry = lookup(
            flash_key(sq, sk, d, dtype, causal, group, streaming, bwd))
        if entry and entry.get("backend") in ("pallas", "jnp"):
            return entry["backend"]
    return cost_model.flash_backend_default(
        sq, sk, d, dtype_token(dtype), causal=causal, streaming=streaming,
        streaming_available=streaming_available, device=device_kind())


def _clamp_rows(v, default: int, quantum: int = 8, lo: int = 8,
                hi: int = 65536) -> int:
    try:
        v = int(v)
    except (TypeError, ValueError):
        return default
    if v < lo or v > hi or v % quantum:
        return default
    return v


def ln_block_rows(kernel: str, hidden: int, dtype) -> int:
    """Rows per grid step for the LN/RMS kernels (kernel is "layer_norm"
    or "rms_norm"). APEX_TPU_LN_BLOCK_ROWS is applied by the op layer."""
    default = cost_model.ln_block_rows_default(hidden, device=device_kind())
    entry = lookup(ln_key(kernel, hidden, dtype))
    if entry:
        return _clamp_rows(entry.get("block_rows"), default)
    return default


def optim_block_rows(n_tiles: int) -> int:
    """128-lane rows per grid step for the flat optimizer kernels;
    ``n_tiles`` = live operand+output tiles (see shape_class.optim_key)."""
    default = cost_model.optim_block_rows_default(n_tiles,
                                                  device=device_kind())
    entry = lookup(optim_key(n_tiles))
    if entry:
        return _clamp_rows(entry.get("block_rows"), default, lo=128)
    return default


def paged_decode_config(n_slots: int, max_blocks: int, block_size: int,
                        group: int, d: int, dtype,
                        total_q: int | None = None) -> dict:
    """Resolved config for one ragged paged-attention shape class:
    ``{"block_rows", "kv_fetch", "q_tile", "backend"}``. Cache entry wins
    field-wise where present (clamped to legal values); the cost model
    fills the rest — including the group-aware oracle-fallback backend
    rule (cost_model.paged_backend_default). Env overrides
    (APEX_TPU_PAGED_BLOCK_ROWS / APEX_TPU_PAGED_KV_FETCH /
    APEX_TPU_PAGED_Q_TILE) are applied by ops/paged_attention.py BEFORE
    consulting this — the standard env > cache > model order."""
    rows_d = cost_model.paged_block_rows_default(group)
    fetch_d = cost_model.paged_kv_fetch_default(
        block_size, d, {"bf16": 2, "f16": 2}.get(dtype_token(dtype), 4))
    cfg = {
        "block_rows": rows_d,
        "kv_fetch": fetch_d,
        "q_tile": cost_model.paged_q_tile_default(group),
        "backend": cost_model.paged_backend_default(
            n_slots, max_blocks, block_size, group),
    }
    entry = lookup(paged_key(n_slots, max_blocks, block_size, group, d,
                             dtype, total_q=total_q))
    if entry:
        cfg["block_rows"] = _clamp_rows(entry.get("block_rows"), rows_d,
                                        quantum=8, lo=8, hi=512)
        cfg["q_tile"] = _clamp_rows(entry.get("q_tile"), cfg["q_tile"],
                                    quantum=8, lo=8, hi=512)
        try:
            f = int(entry.get("kv_fetch"))
            if 1 <= f <= max(1, max_blocks):
                cfg["kv_fetch"] = f
        except (TypeError, ValueError):
            pass
        if entry.get("backend") in ("pallas", "jnp"):
            cfg["backend"] = entry["backend"]
    return cfg


def moe_grouped_config(t: int, e: int, h: int, f: int, dtype) -> dict:
    """Resolved grouped-matmul config for one shape class:
    ``{"tile_t", "tile_f", "backend"}``. Cache entry wins field-wise
    where present (clamped to legal values); the cost model fills the
    rest. Env overrides (APEX_TPU_MOE_TILE_T / APEX_TPU_MOE_TILE_F) are
    applied by ops/grouped_matmul.py BEFORE consulting this — the
    standard env > cache > model order."""
    b = {"bf16": 2, "f16": 2}.get(dtype_token(dtype), 4)
    tt_d = cost_model.moe_tile_t_default(h, f, b, device=device_kind())
    tf_d = cost_model.moe_tile_f_default(f)
    cfg = {
        "tile_t": tt_d,
        "tile_f": tf_d,
        "backend": cost_model.moe_backend_default(t, e, h, f,
                                                  device=device_kind()),
    }
    entry = lookup(moe_key(t, e, h, f, dtype))
    if entry:
        cfg["tile_t"] = _clamp_rows(entry.get("tile_t"), tt_d, quantum=8,
                                    lo=8, hi=4096)
        cfg["tile_f"] = _clamp_rows(entry.get("tile_f"), tf_d, quantum=128,
                                    lo=128, hi=4096)
        if entry.get("backend") in ("pallas", "jnp"):
            cfg["backend"] = entry["backend"]
    return cfg


def quant_matmul_config(m: int, k: int, n: int, dtype,
                        qdtype: str = "int8") -> dict:
    """Resolved config for one blockwise-scaled matmul shape class:
    ``{"tile_m", "tile_n", "tile_k", "backend"}``. Cache entry wins
    field-wise where present (clamped to legal values); the cost model
    fills the rest — including the oracle-fallback backend rule
    (cost_model.quant_backend_default). Env overrides
    (APEX_TPU_QUANT_TILE_M / _N / _K) are applied by
    quantization/scaled_matmul.py BEFORE consulting this — the standard
    env > cache > model order."""
    tm_d = cost_model.quant_tile_m_default(k, n, device=device_kind())
    tn_d = cost_model.quant_tile_n_default(n)
    tk_d = cost_model.quant_tile_k_default(k)
    cfg = {
        "tile_m": tm_d,
        "tile_n": tn_d,
        "tile_k": tk_d,
        "backend": cost_model.quant_backend_default(m, k, n,
                                                    device=device_kind()),
    }
    entry = lookup(quant_key(m, k, n, dtype, qdtype))
    if entry:
        cfg["tile_m"] = _clamp_rows(entry.get("tile_m"), tm_d, quantum=8,
                                    lo=8, hi=4096)
        cfg["tile_n"] = _clamp_rows(entry.get("tile_n"), tn_d, quantum=128,
                                    lo=128, hi=4096)
        cfg["tile_k"] = _clamp_rows(entry.get("tile_k"), tk_d, quantum=128,
                                    lo=128, hi=4096)
        if entry.get("backend") in ("pallas", "jnp"):
            cfg["backend"] = entry["backend"]
    return cfg


def softmax_row_chunk(rows: int, cols: int, dtype) -> int:
    """Row-tile size for the fused softmax family (0 = untiled)."""
    entry = lookup(softmax_key(rows, cols, dtype))
    if entry:
        try:
            c = int(entry.get("row_chunk", 0))
            return max(0, c)
        except (TypeError, ValueError):
            pass
    return cost_model.softmax_row_chunk_default()
