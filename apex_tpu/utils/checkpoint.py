"""Checkpoint/resume helpers (ref: SURVEY.md §6 — amp.state_dict scaler
checkpointing + examples/imagenet save_checkpoint; TPU idiom: the whole
train state is one pytree, saved async via orbax when available).

The amp/optimizer states in this library are already pytrees (scaler scale,
growth counters, master weights, moments), so "checkpointable" is the
default; these helpers add the IO.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp

    _HAVE_ORBAX = True
except ImportError:  # pragma: no cover
    _HAVE_ORBAX = False


def save_checkpoint(path: str, state: Any, *, async_save: bool = False):
    """Save a train-state pytree. Uses orbax (async-capable, TPU-friendly
    sharded IO) when importable, else a host-side pickle of numpy leaves.

    Returns the async save handle (orbax) or None.
    """
    if _HAVE_ORBAX:
        ckptr = (ocp.AsyncCheckpointer if async_save else ocp.Checkpointer)(
            ocp.PyTreeCheckpointHandler()
        )
        ckptr.save(os.path.abspath(path), state, force=True)
        return ckptr if async_save else None
    host_state = jax.tree.map(np.asarray, jax.device_get(state))
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    with open(tmp, "wb") as f:
        pickle.dump(host_state, f)
    os.replace(tmp, path)
    return None


def load_checkpoint(path: str, target: Optional[Any] = None):
    """Restore a pytree saved by :func:`save_checkpoint`. ``target`` (an
    abstract/like-typed pytree) restores dtypes/shardings under orbax."""
    if _HAVE_ORBAX:
        ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
        restored = ckptr.restore(os.path.abspath(path), item=target)
        return restored
    with open(path, "rb") as f:
        return pickle.load(f)
