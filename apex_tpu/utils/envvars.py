"""Validated ``APEX_TPU_*`` environment-knob parsing — the ONE place raw
``os.environ`` values become ints and flags.

Every runtime knob in the library is an env var read **at call/trace
time** (never cached at import — the PR-3 ``profiling.py`` bug class,
now machine-checked by ``apex_tpu.analysis`` rule APX101). Before this
module each consumer parsed its own string: a bad ``APEX_TPU_MOE_TILE_T``
surfaced as a bare ``invalid literal for int()`` five frames deep in
kernel code, and a typo'd flag value silently meant "off". The contract
here:

* unset / empty  -> the caller's ``default`` (``None`` means "no
  override" in the resolution chains: env > tune cache > cost model)
* well-formed    -> the parsed value, validated (positive multiple of
  ``quantum`` for ints, ``"1"``/``"0"`` for flags)
* malformed      -> ``ValueError`` naming the VARIABLE and the offending
  value, raised at the read site (= the first trace that consults the
  knob), never deeper

``apex_tpu.analysis`` rule APX102 forbids raw ``int(os.environ...)`` /
``== "1"`` parsing anywhere else in the package, so new knobs cannot
regress to ad-hoc parsing.
"""

from __future__ import annotations

import os

__all__ = ["env_int", "env_flag", "env_str", "env_float"]


def env_int(var: str, *, quantum: int = 1, default=None,
            allow_zero: bool = False):
    """Integer env knob: ``default`` when unset/empty, else a validated
    positive multiple of ``quantum`` (``allow_zero=True`` additionally
    admits 0 — the "disabled / untiled" convention, e.g.
    APEX_TPU_SOFTMAX_CHUNK). Malformed values raise naming ``var``."""
    raw = os.environ.get(var)
    if raw is None or raw == "":
        return default
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"{var}={raw!r} must be an integer"
            + (f" multiple of {quantum}" if quantum > 1 else "")
        ) from None
    if v == 0 and allow_zero:
        return 0
    if v <= 0 or v % quantum:
        zero = " (or 0)" if allow_zero else ""
        raise ValueError(
            f"{var}={v} must be a positive multiple of {quantum}{zero}")
    return v


def env_float(var: str, *, default=None):
    """Float env knob (budgets like APEX_TPU_ANALYSIS_HBM_GB, which may
    legitimately be fractional): ``default`` when unset/empty, else a
    validated positive float. Malformed values raise naming ``var``."""
    raw = os.environ.get(var)
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(f"{var}={raw!r} must be a number") from None
    if v <= 0:
        raise ValueError(f"{var}={v} must be positive")
    return v


def env_flag(var: str, *, default=None):
    """Boolean env gate: ``"1"`` -> True, ``"0"`` -> False, unset/empty ->
    ``default``. Anything else raises naming ``var`` — a typo'd gate
    value must fail loudly, not silently mean "off" (the pre-analysis
    behavior of every ``== "1"`` comparison)."""
    raw = os.environ.get(var)
    if raw is None or raw == "":
        return default
    if raw == "1":
        return True
    if raw == "0":
        return False
    raise ValueError(
        f"{var}={raw!r} must be '1' or '0' (unset = default)")


def env_str(var: str, *, default=None):
    """String env knob (paths, sink kinds): ``default`` when unset/empty.
    Exists so string knobs share the one read surface the linter
    allowlists — validation of the *values* stays with the consumer."""
    raw = os.environ.get(var)
    if raw is None or raw == "":
        return default
    return raw
