"""Tracing / profiling seams (ref: SURVEY.md §6 — the reference's nvtx
range_push/pop calls in DDP bucket ops and distributed_fused_adam, plus the
``prof`` ctor flag).

TPU equivalents: ``jax.profiler.TraceAnnotation`` ranges (visible in
TensorBoard/Perfetto traces) at the same seams — bucket flush, scaler
update, pipeline schedule phases — plus a capture helper. Annotation is
zero-cost when no trace is being captured.
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

import jax

# master switch mirroring the reference's DistributedDataParallel(prof=...)
_PROF_ENABLED = os.environ.get("APEX_TPU_PROF", "1") == "1"


def set_profiling_enabled(enabled: bool) -> None:
    global _PROF_ENABLED
    _PROF_ENABLED = enabled


@contextlib.contextmanager
def trace_range(name: str) -> Iterator[None]:
    """nvtx.range_push/pop analog. Two mechanisms, because jit splits the
    timeline: ``jax.named_scope`` names the *ops emitted during tracing* so
    the range survives into compiled device traces (the nvtx-in-kernel
    analog), and ``TraceAnnotation`` marks host-side eager execution."""
    if _PROF_ENABLED:
        with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
            yield
    else:
        yield


def annotate(name: str):
    """Decorator form of :func:`trace_range`."""
    def deco(fn):
        def wrapped(*a, **k):
            with trace_range(name):
                return fn(*a, **k)
        wrapped.__name__ = getattr(fn, "__name__", name)
        return wrapped
    return deco


@contextlib.contextmanager
def capture(logdir: str = "/tmp/apex_tpu_trace",
            host_tracer_level: Optional[int] = None) -> Iterator[str]:
    """Capture a device+host trace around a block; view in TensorBoard
    (`tensorboard --logdir ...`) or Perfetto. Returns the logdir."""
    if host_tracer_level is not None:
        opts = jax.profiler.ProfileOptions()
        opts.host_tracer_level = host_tracer_level
        jax.profiler.start_trace(logdir, profiler_options=opts)
    else:
        jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()
