"""Tracing / profiling seams (ref: SURVEY.md §6 — the reference's nvtx
range_push/pop calls in DDP bucket ops and distributed_fused_adam, plus the
``prof`` ctor flag).

TPU equivalents: ``jax.profiler.TraceAnnotation`` ranges (visible in
TensorBoard/Perfetto traces) at the same seams — bucket flush, scaler
update, pipeline schedule phases — plus a capture helper. Annotation is
zero-cost when no trace is being captured.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Iterator, Optional

import jax

from apex_tpu.utils.envvars import env_flag

# master switch mirroring the reference's DistributedDataParallel(prof=...).
# None = "no programmatic override": trace_range then follows the env var
# (default on). APEX_TPU_PROF is re-read at every trace_range call — the
# old import-time latch silently ignored an env var set after import (e.g.
# a harness enabling profiling around one benchmark phase) — and when SET
# it wins over set_profiling_enabled, so the operator's env always decides.
_PROF_OVERRIDE: bool | None = None


def set_profiling_enabled(enabled: bool) -> None:
    """Programmatic default for when APEX_TPU_PROF is unset; pass ``None``
    to clear. An explicit APEX_TPU_PROF env value beats this."""
    global _PROF_OVERRIDE
    _PROF_OVERRIDE = enabled


def profiling_enabled() -> bool:
    """The switch trace_range consults, resolved at CALL time:
    APEX_TPU_PROF env (when set) > set_profiling_enabled > default on."""
    env = env_flag("APEX_TPU_PROF")
    if env is not None:
        return env
    if _PROF_OVERRIDE is not None:
        return _PROF_OVERRIDE
    return True


@contextlib.contextmanager
def trace_range(name: str) -> Iterator[None]:
    """nvtx.range_push/pop analog. Two mechanisms, because jit splits the
    timeline: ``jax.named_scope`` names the *ops emitted during tracing* so
    the range survives into compiled device traces (the nvtx-in-kernel
    analog), and ``TraceAnnotation`` marks host-side eager execution."""
    if profiling_enabled():
        with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
            yield
    else:
        yield


@contextlib.contextmanager
def host_trace_range(name: str) -> Iterator[None]:
    """TraceAnnotation-only variant of :func:`trace_range` for host loops
    that dispatch into already-jitted functions. ``jax.named_scope``
    would leak into any tracing the block happens to trigger (the FIRST
    call of a jitted program traces inside the caller's context),
    renaming ops in the compiled HLO — so this marks the host timeline
    only, leaving every traced program bitwise-identical.

    This is also THE seam ``observability.tracing.Tracer.span`` enters
    around every tracer span: one instrumentation point feeds both the
    tracer ring (``APEX_TPU_TRACE``) and the jax profiler timeline
    (``APEX_TPU_PROF`` / an active capture) — instrument once, see it
    in the flight recorder, the Perfetto export AND TensorBoard."""
    if profiling_enabled():
        with jax.profiler.TraceAnnotation(name):
            yield
    else:
        yield


def annotate(name: str):
    """Decorator form of :func:`trace_range`. ``functools.wraps``
    preserves the full wrapped-function identity (docstring, signature,
    ``__wrapped__``) — a bare ``__name__`` copy dropped everything
    introspection and ``inspect.signature`` need on decorated hot-path
    fns."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*a, **k):
            with trace_range(name):
                return fn(*a, **k)
        return wrapped
    return deco


@contextlib.contextmanager
def capture(logdir: str = "/tmp/apex_tpu_trace",
            host_tracer_level: Optional[int] = None) -> Iterator[str]:
    """Capture a device+host trace around a block; view in TensorBoard
    (`tensorboard --logdir ...`) or Perfetto. Returns the logdir."""
    if host_tracer_level is not None:
        opts = jax.profiler.ProfileOptions()
        opts.host_tracer_level = host_tracer_level
        jax.profiler.start_trace(logdir, profiler_options=opts)
    else:
        jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()
