"""jit-safe numerics guards (NaN/Inf detection inside compiled steps).

Ref: SURVEY §6 "Race detection / sanitizers" — the reference has no
in-code sanitizer (CUDA stream discipline is enforced by design); the
TPU-native analog keeps the invariant TESTS (DDP ordering/aliasing) and
adds ``jax.debug``-based NaN guards, since under XLA the failure mode
users actually hit is a non-finite value appearing silently mid-step
(the amp loss scaler already catches grads — these guards cover
everything else: activations, optimizer state, custom losses).

Usage::

    x = check_numerics(x, "attn_out")            # identity + host report
    params = check_numerics(params, "params", abort=True)  # raise instead

Guards are host callbacks: cheap when values are finite (one all-finite
reduction per leaf on device; the callback fires either way but prints
only on failure), but they do serialize with the host — strip them from
production steps. ``find_nonfinite`` is the eager/post-mortem variant.
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp

from apex_tpu.utils.dtypes import is_float

__all__ = ["check_numerics", "find_nonfinite"]


def _leaf_name(path) -> str:
    return jax.tree_util.keystr(path) or "<leaf>"


def check_numerics(tree, label: str = "tree", *, abort: bool = False):
    """Return ``tree`` unchanged, with a non-finite check attached to every
    floating leaf. Works under ``jit``/``shard_map`` (the check is a
    ``jax.debug.callback``). ``abort=True`` raises ``FloatingPointError``
    from the callback (surfacing as an XLA callback error at the failing
    step) instead of printing to stderr."""

    def report(name, count, total):
        count = int(count)
        if not count:
            return
        msg = (f"apex_tpu.check_numerics[{label}]: {name} has "
               f"{count}/{int(total)} non-finite values")
        if abort:
            raise FloatingPointError(msg)
        print(msg, file=sys.stderr, flush=True)

    def guard(path, leaf):
        if not is_float(leaf):
            return leaf
        x = jnp.asarray(leaf)
        # isfinite natively supports every float dtype — no f32 cast (a
        # cast would copy bf16 trees and falsely flag finite f64 values
        # beyond f32 range, e.g. 1e100)
        bad = jnp.sum(~jnp.isfinite(x))
        jax.debug.callback(
            lambda count, name=_leaf_name(path), total=x.size:
            report(name, count, total),
            bad,
        )
        return leaf

    return jax.tree_util.tree_map_with_path(guard, tree)


def find_nonfinite(tree) -> dict:
    """Eager post-mortem: ``{leaf path: non-finite count}`` for every
    floating leaf that has any. Call OUTSIDE jit on concrete arrays."""
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if not is_float(leaf):
            continue
        n = int(jnp.sum(~jnp.isfinite(jnp.asarray(leaf))))
        if n:
            out[_leaf_name(path)] = n
    return out
