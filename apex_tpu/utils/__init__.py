from apex_tpu.utils import compat  # noqa: F401  — installs jax API shims
from apex_tpu.utils.pytree import (  # noqa: F401
    tree_all_finite,
    tree_cast,
    tree_cast_where,
    tree_global_norm,
    tree_select,
    tree_size,
    tree_zeros_like,
)
from apex_tpu.utils.debug import (  # noqa: F401
    check_numerics,
    find_nonfinite,
)
from apex_tpu.utils.dtypes import (  # noqa: F401
    canonical_half_dtype,
    is_float,
    default_half_dtype,
)
from apex_tpu.utils.metrics import (  # noqa: F401
    StepCounters,
    init_counters,
    step_metrics,
    update_counters,
)
