"""JAX version-compat shims, applied on ``import apex_tpu``.

The library targets the current jax API (``jax.shard_map`` with
``check_vma``, ``jax.lax.axis_size``); older runtimes (observed: 0.4.37 in
the benchmark container) still spell these ``jax.experimental.shard_map``
with ``check_rep`` and have no ``lax.axis_size``. Rather than sprinkling
try/except at ~30 call sites (library, tests, examples all call
``jax.shard_map`` directly), install the modern names once here when they
are missing. On a current jax this module is a no-op.
"""

from __future__ import annotations

import jax
from jax import lax


def _install_shard_map() -> None:
    try:
        jax.shard_map  # noqa: B018 — probe; removed names raise
        return
    except AttributeError:
        pass
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(*args, **kwargs):
        # the modern kwarg is check_vma; the experimental one is check_rep
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

    shard_map.__doc__ = _shard_map.__doc__
    jax.shard_map = shard_map


def _install_axis_size() -> None:
    if hasattr(lax, "axis_size"):
        return

    def axis_size(axis_name):
        """Size of a bound mesh axis (modern lax.axis_size): the count of
        participants, computed collectively."""
        return lax.psum(1, axis_name)

    lax.axis_size = axis_size


def install() -> None:
    _install_shard_map()
    _install_axis_size()


install()
