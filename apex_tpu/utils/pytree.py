"""Pytree utilities shared across the library.

These replace the reference's flat-buffer helpers (``csrc/flatten_unflatten.cpp``
``apex_C.flatten/unflatten``): under XLA there is no per-kernel launch overhead
to amortize, so trees are operated on directly and the compiler fuses the maps.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.utils.dtypes import is_float  # noqa: F401  (re-exported)


def tree_cast(tree, dtype):
    """Cast every floating leaf of ``tree`` to ``dtype`` (non-floats untouched)."""
    if dtype is None:
        return tree

    def _cast(x):
        x = jnp.asarray(x)
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(_cast, tree)


def tree_cast_where(tree, dtype, keep_fp32_predicate):
    """Cast floating leaves to ``dtype`` except where the path predicate holds.

    ``keep_fp32_predicate(path_str)`` receives a '/'-joined key path; leaves for
    which it returns True stay float32. This implements the reference's
    ``keep_batchnorm_fp32`` behavior (apex/amp/_initialize.py, O2 casts the
    model to half but leaves BatchNorm parameters in fp32) by parameter path
    rather than module type.
    """
    if dtype is None:
        return tree

    def _cast(path, x):
        x = jnp.asarray(x)
        if not jnp.issubdtype(x.dtype, jnp.floating):
            return x
        if keep_fp32_predicate(path_str(path)):
            return x.astype(jnp.float32)
        return x.astype(dtype)

    return jax.tree_util.tree_map_with_path(_cast, tree)


def path_str(path) -> str:
    """'/'-joined key path covering dict/sequence/attr-keyed pytree nodes."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):  # GetAttrKey
            parts.append(str(k.name))
        else:
            parts.append(str(k).strip("."))
    return "/".join(parts)


def tree_all_finite(tree):
    """Scalar bool array: True iff every element of every floating leaf is finite.

    The jit-compatible analog of the reference's inf/nan ``noop_flag`` produced
    by ``csrc/multi_tensor_scale_kernel.cu``.
    """
    leaves = [x for x in jax.tree.leaves(tree) if is_float(x)]
    if not leaves:
        return jnp.bool_(True)
    finite = [jnp.all(jnp.isfinite(x)) for x in leaves]
    return jnp.stack(finite).all()


def tree_global_norm(tree, *, per_leaf: bool = False):
    """Global L2 norm over all floating leaves (fp32 accumulation).

    Mirrors ``amp_C.multi_tensor_l2norm``: returns the global norm, and the
    per-tensor norms too when ``per_leaf`` is set (used by LAMB trust ratios).
    """
    leaves = [jnp.asarray(x) for x in jax.tree.leaves(tree) if is_float(x)]
    if not leaves:
        zero = jnp.float32(0.0)
        return (zero, []) if per_leaf else zero
    sq = [jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves]
    total = jnp.sqrt(jnp.stack(sq).sum())
    if per_leaf:
        return total, [jnp.sqrt(s) for s in sq]
    return total


def tree_select(pred, tree_true, tree_false):
    """Elementwise tree select on a scalar predicate; used for step-skipping."""
    return jax.tree.map(lambda t, f: jnp.where(pred, t, f), tree_true, tree_false)


def tree_zeros_like(tree, dtype=None):
    return jax.tree.map(
        lambda x: jnp.zeros(jnp.shape(x), dtype or jnp.asarray(x).dtype), tree
    )


def tree_size(tree) -> int:
    """Total number of elements across all leaves (python int, static)."""
    return sum(int(jnp.size(x)) for x in jax.tree.leaves(tree))


def is_stacked_path(path, stacked_key) -> bool:
    """True iff ``path`` (a jax key path) reaches a leaf stored DIRECTLY
    under dict key ``stacked_key`` — the ``testing.stack_layer_params``
    convention where a [L, ...] array stacks what the reference allocates
    as L separate per-layer tensors. A SequenceKey AFTER the marker means
    the UNSTACKED layout (``params["layers"][i][...]`` — a list of
    per-layer dicts), whose leaves are ordinary tensors; treating those as
    stacked would silently turn per-tensor optimizer statistics (LAMB
    trust ratios) into per-row ones."""
    if stacked_key is None:
        return False
    for i, k in enumerate(path):
        if isinstance(k, jax.tree_util.DictKey) and k.key == stacked_key:
            return not any(
                isinstance(rest, jax.tree_util.SequenceKey)
                for rest in path[i + 1:]
            )
    return False


def stacked_flags(tree, stacked_key):
    """Per-leaf stacked booleans for ``tree`` in ``jax.tree.flatten`` order
    (paths and plain flatten agree on ordering).

    Guards against structural false positives (the detection is by path,
    and a third-party tree may store ordinary tensors under the same
    name): within EACH stacked collection (each distinct subtree rooted
    at a ``stacked_key`` dict entry — a model may hold several, e.g.
    encoder and decoder stacks of different depths), leaves count as
    stacked only when the collection has at least TWO candidate leaves
    and ALL of them share the same leading dimension — the invariant
    ``stack_layer_params`` guarantees (every leaf is [L, ...] for one
    L). A single-array collection is structurally ambiguous and is
    demoted to per-tensor treatment with a warning. 0-d leaves are never
    stacked."""
    paths, _ = jax.tree_util.tree_flatten_with_path(tree)

    def group_of(path):
        for i, k in enumerate(path):
            if isinstance(k, jax.tree_util.DictKey) and k.key == stacked_key:
                return path[: i + 1]
        return None

    flags = []
    groups: dict = {}
    for idx, (path, leaf) in enumerate(paths):
        cand = jnp.ndim(leaf) > 0 and is_stacked_path(path, stacked_key)
        flags.append(cand)
        if cand:
            groups.setdefault(group_of(path), []).append(
                (idx, jnp.shape(leaf)[0])
            )
    for gpath, members in groups.items():
        dims = {d for _, d in members}
        if len(members) >= 2 and len(dims) == 1:
            continue
        import warnings

        if len(members) == 1:
            warnings.warn(
                f"collection at {jax.tree_util.keystr(gpath)} has a single "
                f"array under the stacked key {stacked_key!r} — structurally "
                "ambiguous, treating it as an ORDINARY tensor (per-tensor "
                "optimizer statistics). Restructure or pass "
                "stacked_key=None to silence.",
                stacklevel=3,
            )
        else:
            # >=2 leaves with DISAGREEING leading dims: a malformed stack
            # (e.g. one leaf transposed) must not silently flip LAMB/
            # NovoGrad/LARC from per-layer to whole-tensor statistics
            # (round-3 advisor item)
            warnings.warn(
                f"collection at {jax.tree_util.keystr(gpath)} has leaves "
                f"with mismatched leading dims {sorted(dims)} under the "
                f"stacked key {stacked_key!r} — not a lax.scan stack; "
                "treating ALL its leaves as ORDINARY tensors (per-tensor "
                "optimizer statistics). Check for a transposed/misshaped "
                "leaf, or pass stacked_key=None to silence.",
                stacklevel=3,
            )
        for idx, _ in members:
            flags[idx] = False
    return flags


def stacked_sq_sum(x, stacked: bool):
    """Sum of squares for per-tensor statistics: one scalar for a plain
    tensor, one value PER LEADING SLICE (keepdims, broadcastable back) for
    a lax.scan-stacked [L, ...] tensor. The shared reduction behind LAMB
    trust ratios, NovoGrad second moments, and LARC adaptive rates."""
    axes = tuple(range(1, jnp.ndim(x))) if stacked else None
    return jnp.sum(jnp.square(x), axis=axes, keepdims=stacked)
