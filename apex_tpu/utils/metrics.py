"""Per-step training observability — the reference's minimalist idiom.

Ref: apex keeps no metrics registry; observability is the loss-scale
printouts (`apex/amp/_amp_state.py::maybe_print` on scale changes) and
whatever the examples log per step (loss, grad norm —
`examples/imagenet/main_amp.py`). SURVEY §6 prescribes the same
minimalism for the rebuild: one optional per-step scalar dict, fully
device-side so it adds no host sync inside jit — the caller decides when
(or whether) to pull values to the host.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

from apex_tpu.utils.pytree import tree_global_norm


class StepCounters(NamedTuple):
    """Device-side cumulative counters (carry them in the train state).

    For amp training loops prefer passing the AmpOptState to
    ``step_metrics(opt_state=...)`` — it already carries the overflow
    count (``skipped_steps``, incremented from the axes-reduced flag), so
    a separate StepCounters would double-count state that can drift.
    StepCounters is for loops NOT using the amp optimizer wrapper."""

    steps: jnp.ndarray           # i32[] total optimizer steps attempted
    overflows: jnp.ndarray       # i32[] steps skipped on non-finite grads


def init_counters() -> StepCounters:
    return StepCounters(steps=jnp.int32(0), overflows=jnp.int32(0))


def update_counters(counters: StepCounters, found_inf) -> StepCounters:
    found_inf = jnp.asarray(found_inf)
    return StepCounters(
        steps=counters.steps + 1,
        overflows=counters.overflows + found_inf.astype(jnp.int32),
    )


def step_metrics(
    loss=None,
    grads=None,
    scaler_state=None,
    found_inf=None,
    counters: Optional[StepCounters] = None,
    opt_state=None,
    moe_aux=None,
) -> dict:
    """Build the per-step scalar dict (loss, grad_norm, loss_scale,
    found_inf, overflow/step counts, MoE router health). Every value is
    a device array; jit-safe. Pass only what you have — absent inputs
    are omitted.

    ``opt_state``: an ``amp.AmpOptState`` — reads its scaler scale and
    ``skipped_steps`` overflow count (single source of truth for amp
    loops; don't also pass ``counters``).

    ``moe_aux``: the aux dict ``transformer.moe.moe_apply`` returns (or
    a list of them, one per MoE layer — averaged). Surfaces the router
    health the dispatch already computed — ``moe_dropped_fraction``
    (scalar) and ``moe_expert_load`` (per-expert [E] assignment-fraction
    vector; a collapsing router shows one entry racing to 1) — so
    training loops can log router collapse without recomputing
    dispatch."""
    out = {}
    if loss is not None:
        out["loss"] = jnp.asarray(loss, jnp.float32)
    if grads is not None:
        out["grad_norm"] = tree_global_norm(grads)
    if scaler_state is not None:
        out["loss_scale"] = scaler_state.scale
    if found_inf is not None:
        out["found_inf"] = jnp.asarray(found_inf)
    if counters is not None:
        out["steps"] = counters.steps
        out["overflow_count"] = counters.overflows
    if opt_state is not None:
        from apex_tpu.amp.scaler import ScalerState

        if isinstance(opt_state.scaler, ScalerState):
            out["loss_scale"] = opt_state.scaler.scale
        else:  # amp.initialize(num_losses=N): one scale per loss
            for i, sc in enumerate(opt_state.scaler):
                out[f"loss_scale{i}"] = sc.scale
        out["overflow_count"] = opt_state.skipped_steps
    if moe_aux is not None:
        auxes = moe_aux if isinstance(moe_aux, (list, tuple)) else [moe_aux]
        for key in ("dropped_fraction", "expert_load"):
            vals = [jnp.asarray(a[key], jnp.float32)
                    for a in auxes if key in a]
            if not vals:
                continue
            if all(v.shape == vals[0].shape for v in vals):
                out[f"moe_{key}"] = sum(vals) / len(vals)
            else:
                # mixed expert counts can't share one averaged vector —
                # emit per-layer keys instead of silently dropping the
                # router-health signal
                for i, v in enumerate(vals):
                    out[f"moe_{key}/{i}"] = v
    return out
