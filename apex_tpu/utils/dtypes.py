"""Dtype policy helpers.

TPU-native half precision is bfloat16 (MXU-native, no loss scaling required in
the common path); float16 is fully supported as well to preserve the
reference's fp16 ladder (apex/amp opt levels were designed around fp16 +
dynamic loss scaling, and the tests exercise both dtypes).
"""

from __future__ import annotations

import jax.numpy as jnp


def default_half_dtype():
    """bfloat16 — the TPU-native 16-bit dtype."""
    return jnp.bfloat16


def canonical_half_dtype(dtype_or_name):
    """Accept 'float16'/'bfloat16'/jnp dtypes/None and canonicalize."""
    if dtype_or_name is None:
        return None
    if isinstance(dtype_or_name, str):
        name = dtype_or_name.lower()
        if name in ("fp16", "float16", "half"):
            return jnp.float16
        if name in ("bf16", "bfloat16"):
            return jnp.bfloat16
        if name in ("fp32", "float32", "float"):
            return jnp.float32
        raise ValueError(f"unknown dtype name {dtype_or_name!r}")
    return jnp.dtype(dtype_or_name)


def is_float(x) -> bool:
    # result_type is pure dtype metadata — jnp.asarray(x) would materialize
    # (and device-transfer) the value just to read its dtype
    return jnp.issubdtype(jnp.result_type(x), jnp.floating)
