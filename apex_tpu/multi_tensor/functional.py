"""Functional multi-tensor ops — the TPU analog of the ``amp_C`` kernel suite.

Reference kernels (csrc/): multi_tensor_scale_kernel.cu, multi_tensor_axpby_kernel.cu,
multi_tensor_l2norm_kernel.cu, multi_tensor_adam.cu, multi_tensor_adagrad.cu,
multi_tensor_novograd.cu, multi_tensor_sgd_kernel.cu, multi_tensor_lamb.cu and
update_scale_hysteresis.cu.

Semantics preserved:
  * all update math accumulates in float32 regardless of storage dtype
    (the reference's DISPATCH_FLOAT_HALF_AND_BFLOAT kernels upcast per element);
  * scale/axpby detect inf/nan and report it via the returned ``noop_flag``
    — the primitive the amp loss scaler is built on;
  * results are returned (functional) rather than written in place; jit buffer
    donation restores in-place behavior at the boundary.

Each op takes ``(noop_flag, tensor_lists, *args)`` to match the
``multi_tensor_applier`` calling convention and returns
``(*new_lists, noop_flag)``.
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp

from apex_tpu.utils.pytree import stacked_sq_sum, tree_global_norm

Tensors = Sequence[jnp.ndarray]


def _f32(x):
    return jnp.asarray(x, jnp.float32)


def _nonfinite_any(tensors: Tensors):
    if not tensors:
        return jnp.bool_(False)
    return jnp.stack([~jnp.all(jnp.isfinite(t)) for t in tensors]).any()


def multi_tensor_scale(noop_flag, tensor_lists, scale, out_dtype=None):
    """out = in * scale; flags inf/nan. Ref: csrc/multi_tensor_scale_kernel.cu.

    ``tensor_lists = [ins]`` (outputs are returned; the reference's [ins, outs]
    out-tensor dtype is selected by ``out_dtype`` — pass ``jnp.float32`` to get
    the fp16-model-grads → fp32-master-grads unscale used by amp
    (apex/amp/_process_optimizer.py::post_backward_with_master_weights);
    ``None`` preserves each input's dtype).
    """
    (ins,) = tensor_lists
    scale = _f32(scale)
    outs32 = [_f32(t) * scale for t in ins]
    outs = [o.astype(out_dtype or t.dtype) for o, t in zip(outs32, ins)]
    flag = noop_flag | _nonfinite_any(outs32)
    return outs, flag


def multi_tensor_axpby(noop_flag, tensor_lists, a, b):
    """out = a*x + b*y with inf/nan check. Ref: csrc/multi_tensor_axpby_kernel.cu."""
    xs, ys = tensor_lists
    a, b = _f32(a), _f32(b)
    outs32 = [a * _f32(x) + b * _f32(y) for x, y in zip(xs, ys)]
    outs = [o.astype(x.dtype) for o, x in zip(outs32, xs)]
    flag = noop_flag | _nonfinite_any(outs32)
    return outs, flag


def multi_tensor_l2norm(noop_flag, tensor_lists, per_tensor=False):
    """Global (and optionally per-tensor) L2 norms, fp32 accumulation.

    Ref: csrc/multi_tensor_l2norm_kernel.cu (+_mp). Used for LAMB trust ratios
    and clip_grad_norm. Single source of truth for the reduction is
    ``apex_tpu.utils.pytree.tree_global_norm``.
    """
    (xs,) = tensor_lists
    if not xs:
        z = jnp.float32(0.0)
        return (z, jnp.zeros((0,), jnp.float32)) if per_tensor else z
    if per_tensor:
        total, per = tree_global_norm(list(xs), per_leaf=True)
        return total, jnp.stack(per)
    return tree_global_norm(list(xs))


ADAM_MODE_ADAM = 0      # L2 regularization added to gradient (classic Adam)
ADAM_MODE_ADAMW = 1     # decoupled weight decay (AdamW)


def multi_tensor_adam(
    noop_flag,
    tensor_lists,
    lr,
    beta1,
    beta2,
    eps,
    step,
    mode,
    bias_correction,
    weight_decay,
):
    """Fused Adam/AdamW update. Ref: csrc/multi_tensor_adam.cu.

    tensor_lists = [grads, params, exp_avgs, exp_avg_sqs]; returns updated
    (params, exp_avgs, exp_avg_sqs, noop_flag). When ``noop_flag`` is set the
    update is suppressed (reference kernels early-exit on the flag).
    """
    grads, params, ms, vs = tensor_lists
    lr = _f32(lr)
    b1, b2, eps = _f32(beta1), _f32(beta2), _f32(eps)
    step = jnp.asarray(step, jnp.float32)
    if bias_correction:
        bc1 = 1.0 - b1 ** step
        bc2 = 1.0 - b2 ** step
    else:
        bc1 = bc2 = jnp.float32(1.0)

    skip = noop_flag
    new_p, new_m, new_v = [], [], []
    for g, p, m, v in zip(grads, params, ms, vs):
        g32, p32, m32, v32 = _f32(g), _f32(p), _f32(m), _f32(v)
        if mode == ADAM_MODE_ADAM:
            g32 = g32 + weight_decay * p32
        m_n = b1 * m32 + (1.0 - b1) * g32
        v_n = b2 * v32 + (1.0 - b2) * jnp.square(g32)
        update = (m_n / bc1) / (jnp.sqrt(v_n / bc2) + eps)
        if mode == ADAM_MODE_ADAMW:
            update = update + weight_decay * p32
        p_n = p32 - lr * update
        new_p.append(jnp.where(skip, p32, p_n).astype(p.dtype))
        new_m.append(jnp.where(skip, m32, m_n).astype(m.dtype))
        new_v.append(jnp.where(skip, v32, v_n).astype(v.dtype))
    return new_p, new_m, new_v, noop_flag


def multi_tensor_adagrad(noop_flag, tensor_lists, lr, epsilon, mode, weight_decay):
    """Fused Adagrad. Ref: csrc/multi_tensor_adagrad.cu (mode 0 = L2, 1 = decoupled)."""
    grads, params, hs = tensor_lists
    lr, eps = _f32(lr), _f32(epsilon)
    skip = noop_flag
    new_p, new_h = [], []
    for g, p, h in zip(grads, params, hs):
        g32, p32, h32 = _f32(g), _f32(p), _f32(h)
        if mode == 0:
            g32 = g32 + weight_decay * p32
        h_n = h32 + jnp.square(g32)
        p_n = p32 - lr * g32 / (jnp.sqrt(h_n) + eps)
        if mode == 1:
            p_n = p_n - lr * weight_decay * p32
        new_p.append(jnp.where(skip, p32, p_n).astype(p.dtype))
        new_h.append(jnp.where(skip, h32, h_n).astype(h.dtype))
    return new_p, new_h, noop_flag


def multi_tensor_sgd(
    noop_flag,
    tensor_lists,
    weight_decay,
    momentum,
    dampening,
    lr,
    nesterov,
    first_run,
    weight_decay_after_momentum,
    scale=1.0,
):
    """Fused momentum SGD. Ref: csrc/multi_tensor_sgd_kernel.cu.

    tensor_lists = [grads, params, momentum_buffers]. ``scale`` multiplies the
    gradient (used to fold grad unscaling into the update).
    """
    grads, params, bufs = tensor_lists
    lr = _f32(lr)
    skip = noop_flag
    new_p, new_b = [], []
    for g, p, b in zip(grads, params, bufs):
        g32, p32, b32 = _f32(g) * _f32(scale), _f32(p), _f32(b)
        if weight_decay != 0.0 and not weight_decay_after_momentum:
            g32 = g32 + weight_decay * p32
        if momentum != 0.0:
            b_n = jnp.where(
                jnp.bool_(first_run), g32, momentum * b32 + (1.0 - dampening) * g32
            )
            d = g32 + momentum * b_n if nesterov else b_n
        else:
            b_n = b32
            d = g32
        if weight_decay != 0.0 and weight_decay_after_momentum:
            d = d + weight_decay * p32
        p_n = p32 - lr * d
        new_p.append(jnp.where(skip, p32, p_n).astype(p.dtype))
        new_b.append(jnp.where(skip, b32, b_n).astype(b.dtype))
    return new_p, new_b, noop_flag


def multi_tensor_novograd(
    noop_flag,
    tensor_lists,
    lr,
    beta1,
    beta2,
    eps,
    step,
    bias_correction,
    weight_decay,
    grad_averaging,
    moment_mode,
    norm_type,
    stacked=None,
):
    """Fused NovoGrad: per-TENSOR second moment (a scalar per tensor).

    Ref: csrc/multi_tensor_novograd.cu; norms list is [per-tensor v scalars].
    tensor_lists = [grads, params, exp_avgs]; plus ``norms`` vector argument is
    carried in exp_avg_sq per-tensor scalars, here returned as a vector.

    ``stacked``: per-tensor bools; a True entry marks a lax.scan-stacked
    [L, ...] tensor whose slices are the reference's per-layer tensors —
    its second moment is a [L] vector (one scalar per layer slice), kept
    broadcastable against the slice.
    """
    grads, params, ms, v_scalars = tensor_lists
    lr, b1, b2, eps = _f32(lr), _f32(beta1), _f32(beta2), _f32(eps)
    step = jnp.asarray(step, jnp.float32)
    bc1 = 1.0 - b1 ** step if bias_correction else jnp.float32(1.0)
    bc2 = 1.0 - b2 ** step if bias_correction else jnp.float32(1.0)
    g_coef = (1.0 - b1) if grad_averaging else jnp.float32(1.0)
    skip = noop_flag
    if stacked is None:
        stacked = [False] * len(grads)
    new_p, new_m, new_v = [], [], []
    for g, p, m, v, stk in zip(grads, params, ms, v_scalars, stacked):
        g32, p32, m32, v32 = _f32(g), _f32(p), _f32(m), _f32(v)
        gnorm2 = stacked_sq_sum(g32, stk)
        if stk:
            v32 = v32.reshape(gnorm2.shape)
        v_n = jnp.where(
            jnp.bool_(step <= 1.0) if moment_mode == 0 else jnp.bool_(False),
            gnorm2,
            b2 * v32 + (1.0 - b2) * gnorm2,
        )
        denom = jnp.sqrt(v_n / bc2) + eps
        g_scaled = g32 / denom + weight_decay * p32
        m_n = b1 * m32 + g_coef * g_scaled
        p_n = p32 - lr * (m_n / bc1)
        new_p.append(jnp.where(skip, p32, p_n).astype(p.dtype))
        new_m.append(jnp.where(skip, m32, m_n).astype(m.dtype))
        new_v.append(jnp.where(skip, v32, v_n).reshape(jnp.shape(v)))
    return new_p, new_m, new_v, noop_flag


def multi_tensor_lamb(
    noop_flag,
    tensor_lists,
    lr,
    beta1,
    beta2,
    eps,
    step,
    bias_correction,
    weight_decay,
    grad_averaging,
    mode,
    global_grad_norm,
    max_grad_norm,
    use_nvlamb=False,
    stacked=None,
):
    """Fused LAMB (both phases + per-tensor trust ratios in one call).

    Ref: csrc/multi_tensor_lamb.cu. tensor_lists = [grads, params, m, v].
    Phase 1: Adam-style moment update with global gradient clipping by
    ``global_grad_norm``/``max_grad_norm``. Phase 2: per-tensor trust ratio
    ``phi(||w||)/||update||`` scales the learning rate. NVLAMB variant applies
    the trust ratio to weight-decay-free tensors too.

    ``stacked``: optional per-tensor bools. A True entry marks a tensor
    whose leading axis stacks what the reference allocates as SEPARATE
    per-layer tensors (apex_tpu's ``lax.scan``-over-layers layout,
    ``testing.stack_layer_params``). Its trust ratios are computed per
    leading-axis slice — one norm over all L layers would be a different
    optimizer from the reference's per-tensor LAMB.
    """
    grads, params, ms, vs = tensor_lists
    lr, b1, b2, eps = _f32(lr), _f32(beta1), _f32(beta2), _f32(eps)
    step = jnp.asarray(step, jnp.float32)
    bc1 = 1.0 - b1 ** step if bias_correction else jnp.float32(1.0)
    bc2 = 1.0 - b2 ** step if bias_correction else jnp.float32(1.0)
    beta3 = (1.0 - b1) if grad_averaging else jnp.float32(1.0)

    gnorm = _f32(global_grad_norm)
    if max_grad_norm is not None and max_grad_norm > 0:
        clip = jnp.maximum(gnorm / _f32(max_grad_norm), 1.0)
    else:
        clip = jnp.float32(1.0)

    skip = noop_flag
    if stacked is None:
        stacked = [False] * len(grads)
    new_p, new_m, new_v = [], [], []
    for g, p, m, v, stk in zip(grads, params, ms, vs, stacked):
        g32 = _f32(g) / clip
        p32, m32, v32 = _f32(p), _f32(m), _f32(v)
        if mode == 0:  # L2 mode: wd folded into gradient
            g32 = g32 + weight_decay * p32
        m_n = b1 * m32 + beta3 * g32
        v_n = b2 * v32 + (1.0 - b2) * jnp.square(g32)
        update = (m_n / bc1) / (jnp.sqrt(v_n / bc2) + eps)
        if mode == 1:  # AdamW-style decoupled decay joins the update
            update = update + weight_decay * p32
        # stacked [L, ...] leaf: one norm PER LAYER SLICE (broadcasts back
        # over the slice); plain leaf: one scalar norm for the whole tensor
        w_norm = jnp.sqrt(stacked_sq_sum(p32, stk))
        u_norm = jnp.sqrt(stacked_sq_sum(update, stk))
        if weight_decay != 0.0 or use_nvlamb:
            ratio = jnp.where(
                (w_norm > 0) & (u_norm > 0), w_norm / u_norm, jnp.float32(1.0)
            )
        else:
            ratio = jnp.float32(1.0)
        p_n = p32 - lr * ratio * update
        new_p.append(jnp.where(skip, p32, p_n).astype(p.dtype))
        new_m.append(jnp.where(skip, m32, m_n).astype(m.dtype))
        new_v.append(jnp.where(skip, v32, v_n).astype(v.dtype))
    return new_p, new_m, new_v, noop_flag


def update_scale_hysteresis(
    scale, growth_tracker, hysteresis_tracker, found_inf,
    growth_interval, growth_factor, backoff_factor, hysteresis,
):
    """Device-side dynamic loss-scale update with hysteresis.

    Ref: csrc/update_scale_hysteresis.cu. On overflow, the hysteresis counter
    must reach zero before the scale is actually backed off (absorbs isolated
    spikes); on ``growth_interval`` consecutive clean steps the scale grows.
    """
    scale = _f32(scale)
    found_inf = jnp.asarray(found_inf, jnp.bool_)

    hys_n = jnp.where(found_inf, hysteresis_tracker - 1, hysteresis)
    backoff = found_inf & (hys_n <= 0)
    growth_n = jnp.where(found_inf, 0, growth_tracker + 1)
    grow = (~found_inf) & (growth_n == growth_interval)

    new_scale = jnp.where(
        backoff, scale * backoff_factor, jnp.where(grow, scale * growth_factor, scale)
    )
    new_growth = jnp.where(grow, 0, growth_n)
    new_hys = jnp.where(backoff, hysteresis, hys_n).astype(jnp.int32)
    return new_scale, new_growth.astype(jnp.int32), new_hys
