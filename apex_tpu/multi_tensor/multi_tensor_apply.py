"""``multi_tensor_applier``-shaped dispatch engine.

Reference: apex/multi_tensor_apply/multi_tensor_apply.py::MultiTensorApply and
the CUDA chunking harness csrc/multi_tensor_apply.cuh. The reference exists
because CUDA kernel launches are per-tensor: it packs hundreds of tensors'
pointers into chunked kernel launches. Under XLA a jit'd tree-map is already a
single fused program, so the TPU engine keeps only the *semantics*:

  * one call covers an arbitrary list-of-tensor-lists,
  * an overflow ("noop") flag is computed alongside scaling ops,
  * the op implementations are swappable (fused-jit default, Pallas variants
    registered by apex_tpu.ops for the optimizer updates).

Ops here are functional: they RETURN new tensor lists and the updated flag
instead of writing in place (donation at the jit boundary recovers the
reference's in-place buffer reuse).
"""

from __future__ import annotations


class MultiTensorApply:
    """API-parity shim for ``apex.multi_tensor_apply.MultiTensorApply``.

    ``chunk_size`` is accepted and ignored: XLA tiles and fuses the work, so
    there is nothing to chunk on the host side.
    """

    available = True
    warned = False

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size

    def __call__(self, op, noop_flag, tensor_lists, *args, **kwargs):
        """Invoke ``op(noop_flag, tensor_lists, *args)`` and return its result.

        Contract mirrors the reference: ``op`` receives the current overflow
        flag and the list of tensor lists; functional ops return
        ``(new_tensor_lists..., new_noop_flag)``.
        """
        return op(noop_flag, tensor_lists, *args, **kwargs)


multi_tensor_applier = MultiTensorApply(2048 * 32)
