from apex_tpu.multi_tensor.multi_tensor_apply import (  # noqa: F401
    MultiTensorApply,
    multi_tensor_applier,
)
from apex_tpu.multi_tensor import functional  # noqa: F401
from apex_tpu.multi_tensor.functional import (  # noqa: F401
    multi_tensor_adagrad,
    multi_tensor_adam,
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_lamb,
    multi_tensor_novograd,
    multi_tensor_scale,
    multi_tensor_sgd,
)
