"""One-shot in-place build of the _apex_tpu_C extension via the system C
compiler (no pybind11 in the image — plain CPython C API; see
csrc/apex_tpu_C.c).

The built .so is a local cache, never committed: it is validated against a
content hash of the C source (sidecar ``.build_hash``), so a stale or
foreign binary is never loaded (round-1 advisor finding: mtime-based reuse
would execute an unauditable committed artifact on fresh checkouts).
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys
import sysconfig

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))


def _source_hash(src: str) -> str:
    with open(src, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def build(verbose: bool = False) -> str | None:
    """Compile csrc/apex_tpu_C.c into this package directory. Returns the
    built path or None on failure (callers fall back to numpy)."""
    src = os.path.join(_PKG_DIR, "csrc", "apex_tpu_C.c")
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    out = os.path.join(_PKG_DIR, "_apex_tpu_C" + suffix)
    stamp = os.path.join(_PKG_DIR, ".build_hash")
    try:
        want = _source_hash(src)
    except OSError as e:  # stripped checkout without csrc — numpy fallback
        if verbose:
            print(f"_apex_tpu_C source unavailable: {e}", file=sys.stderr)
        return None
    if os.path.exists(out) and os.path.exists(stamp):
        with open(stamp) as f:
            if f.read().strip() == want:
                return out
    cc = sysconfig.get_config_var("CC") or "cc"
    include = sysconfig.get_paths()["include"]
    cmd = cc.split() + [
        "-O3", "-shared", "-fPIC", f"-I{include}", src, "-o", out,
    ]
    try:
        subprocess.run(
            cmd, check=True,
            capture_output=not verbose,
        )
        with open(stamp, "w") as f:
            f.write(want)
        return out
    except (subprocess.CalledProcessError, OSError) as e:  # pragma: no cover
        if verbose:
            print(f"_apex_tpu_C build failed: {e}", file=sys.stderr)
        return None
