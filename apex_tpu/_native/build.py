"""One-shot in-place build of the _apex_tpu_C extension via setuptools
(no pybind11 in the image — plain CPython C API; see csrc/apex_tpu_C.c)."""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))


def build(verbose: bool = False) -> str | None:
    """Compile csrc/apex_tpu_C.c into this package directory. Returns the
    built path or None on failure (callers fall back to numpy)."""
    src = os.path.join(_PKG_DIR, "csrc", "apex_tpu_C.c")
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    out = os.path.join(_PKG_DIR, "_apex_tpu_C" + suffix)
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    cc = sysconfig.get_config_var("CC") or "cc"
    include = sysconfig.get_paths()["include"]
    cmd = cc.split() + [
        "-O3", "-shared", "-fPIC", f"-I{include}", src, "-o", out,
    ]
    try:
        subprocess.run(
            cmd, check=True,
            capture_output=not verbose,
        )
        return out
    except (subprocess.CalledProcessError, OSError) as e:  # pragma: no cover
        if verbose:
            print(f"_apex_tpu_C build failed: {e}", file=sys.stderr)
        return None
