/* _apex_tpu_C — native host-side helpers.
 *
 * Ref: csrc/flatten_unflatten.cpp (ext `apex_C`: flatten/unflatten used by
 * apex.parallel.DistributedDataParallel's flat buckets) and the host-side
 * inf/nan scan in apex/fp16_utils/loss_scaler.py::DynamicLossScaler.
 *
 * On TPU the *device-side* flattening is XLA's job (see parallel/ddp.py),
 * but host-side staging still shows up in checkpoint IO and data paths;
 * these helpers do GIL-released memcpy/scans over any objects exporting
 * the buffer protocol. Pure C (CPython API only — no pybind11 in the
 * image), built by apex_tpu/_native/build.py via setuptools.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <math.h>
#include <string.h>

/* flatten_into(dst, [src, ...]) -> bytes copied
 * dst: writable contiguous buffer; srcs are copied back-to-back. */
static PyObject *
flatten_into(PyObject *self, PyObject *args)
{
    PyObject *dst_obj, *src_list;
    if (!PyArg_ParseTuple(args, "OO!", &dst_obj, &PyList_Type, &src_list))
        return NULL;

    Py_buffer dst;
    if (PyObject_GetBuffer(dst_obj, &dst, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS))
        return NULL;

    Py_ssize_t n = PyList_GET_SIZE(src_list);
    Py_ssize_t total = 0;
    Py_buffer *srcs = PyMem_Malloc(sizeof(Py_buffer) * (n ? n : 1));
    if (!srcs) {
        PyBuffer_Release(&dst);
        return PyErr_NoMemory();
    }
    Py_ssize_t got = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        if (PyObject_GetBuffer(PyList_GET_ITEM(src_list, i), &srcs[i],
                               PyBUF_C_CONTIGUOUS)) {
            for (Py_ssize_t j = 0; j < got; j++)
                PyBuffer_Release(&srcs[j]);
            PyMem_Free(srcs);
            PyBuffer_Release(&dst);
            return NULL;
        }
        got++;
        total += srcs[i].len;
    }
    if (total > dst.len) {
        for (Py_ssize_t j = 0; j < got; j++)
            PyBuffer_Release(&srcs[j]);
        PyMem_Free(srcs);
        PyBuffer_Release(&dst);
        PyErr_SetString(PyExc_ValueError, "flatten_into: dst too small");
        return NULL;
    }

    char *out = (char *)dst.buf;
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t i = 0; i < n; i++) {
        memcpy(out, srcs[i].buf, (size_t)srcs[i].len);
        out += srcs[i].len;
    }
    Py_END_ALLOW_THREADS

    for (Py_ssize_t j = 0; j < got; j++)
        PyBuffer_Release(&srcs[j]);
    PyMem_Free(srcs);
    PyBuffer_Release(&dst);
    return PyLong_FromSsize_t(total);
}

/* unflatten_from(src, [dst, ...]) -> bytes copied */
static PyObject *
unflatten_from(PyObject *self, PyObject *args)
{
    PyObject *src_obj, *dst_list;
    if (!PyArg_ParseTuple(args, "OO!", &src_obj, &PyList_Type, &dst_list))
        return NULL;

    Py_buffer src;
    if (PyObject_GetBuffer(src_obj, &src, PyBUF_C_CONTIGUOUS))
        return NULL;

    Py_ssize_t n = PyList_GET_SIZE(dst_list);
    Py_ssize_t off = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        Py_buffer dst;
        if (PyObject_GetBuffer(PyList_GET_ITEM(dst_list, i), &dst,
                               PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS)) {
            PyBuffer_Release(&src);
            return NULL;
        }
        if (off + dst.len > src.len) {
            PyBuffer_Release(&dst);
            PyBuffer_Release(&src);
            PyErr_SetString(PyExc_ValueError, "unflatten_from: src too small");
            return NULL;
        }
        Py_BEGIN_ALLOW_THREADS
        memcpy(dst.buf, (char *)src.buf + off, (size_t)dst.len);
        Py_END_ALLOW_THREADS
        off += dst.len;
        PyBuffer_Release(&dst);
    }
    PyBuffer_Release(&src);
    return PyLong_FromSsize_t(off);
}

/* has_inf_or_nan_f32(buf) -> bool — GIL-released scan of float32 data */
static PyObject *
has_inf_or_nan_f32(PyObject *self, PyObject *args)
{
    PyObject *obj;
    if (!PyArg_ParseTuple(args, "O", &obj))
        return NULL;
    Py_buffer buf;
    if (PyObject_GetBuffer(obj, &buf, PyBUF_C_CONTIGUOUS))
        return NULL;
    const float *p = (const float *)buf.buf;
    Py_ssize_t count = buf.len / (Py_ssize_t)sizeof(float);
    int found = 0;
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t i = 0; i < count; i++) {
        if (!isfinite(p[i])) { found = 1; break; }
    }
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&buf);
    if (found) Py_RETURN_TRUE;
    Py_RETURN_FALSE;
}

static PyMethodDef Methods[] = {
    {"flatten_into", flatten_into, METH_VARARGS,
     "Copy a list of contiguous buffers back-to-back into dst."},
    {"unflatten_from", unflatten_from, METH_VARARGS,
     "Scatter a contiguous buffer into a list of writable buffers."},
    {"has_inf_or_nan_f32", has_inf_or_nan_f32, METH_VARARGS,
     "True if any float32 element is inf or NaN."},
    {NULL, NULL, 0, NULL}
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_apex_tpu_C", NULL, -1, Methods
};

PyMODINIT_FUNC
PyInit__apex_tpu_C(void)
{
    return PyModule_Create(&moduledef);
}
