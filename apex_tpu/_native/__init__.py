"""Native host-side helpers with numpy fallback (ref: ext ``apex_C``).

``flatten``/``unflatten`` mirror apex_C.flatten/unflatten for host arrays
(checkpoint staging, data paths); ``has_inf_or_nan`` is the loss-scaler
host scan. The C extension is built lazily on FIRST USE (cc -O3, ~1s), not
at import time, so importing apex_tpu stays side-effect-free in sandboxed /
no-toolchain environments; when the build fails, a one-line warning makes
the numpy-fallback activation observable (round-1 advisor finding).
"""

from __future__ import annotations

import logging

import numpy as np

_logger = logging.getLogger(__name__)

_C = None
_tried = False


def _native():
    """Build+load the C extension on first call; None => numpy fallback."""
    global _C, _tried
    if _tried:
        return _C
    _tried = True
    from apex_tpu._native.build import build as _build

    so = _build()
    if so is None:
        _logger.warning(
            "apex_tpu._native: C extension build failed; using numpy fallback"
        )
        return None
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location("_apex_tpu_C", so)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _C = mod
    except Exception as e:  # pragma: no cover
        _logger.warning(
            "apex_tpu._native: C extension load failed (%s); numpy fallback", e
        )
        _C = None
    return _C


def have_native() -> bool:
    """True when the C extension is (buildable and) loaded."""
    return _native() is not None


def flatten(arrays):
    """Concatenate host arrays into one flat array of the common dtype
    (ref: apex_C.flatten)."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    if not arrays:
        return np.empty((0,), np.float32)
    dtype = arrays[0].dtype
    if any(a.dtype != dtype for a in arrays):
        raise ValueError("flatten: arrays must share a dtype (ref asserts)")
    total = sum(a.size for a in arrays)
    out = np.empty((total,), dtype)
    C = _native()
    if C is not None:
        C.flatten_into(out, list(arrays))
    else:
        off = 0
        for a in arrays:
            out[off:off + a.size] = a.reshape(-1)
            off += a.size
    return out


def unflatten(flat, like):
    """Split a flat array back into arrays shaped like ``like``
    (ref: apex_C.unflatten)."""
    flat = np.ascontiguousarray(flat)
    outs = [np.empty(np.shape(a), flat.dtype) for a in like]
    C = _native()
    if C is not None:
        C.unflatten_from(flat, outs)
    else:
        off = 0
        for o in outs:
            o[...] = flat[off:off + o.size].reshape(o.shape)
            off += o.size
    return outs


def has_inf_or_nan(array) -> bool:
    """Host-side overflow check (ref: fp16_utils
    DynamicLossScaler.has_inf_or_nan)."""
    a = np.ascontiguousarray(array)
    C = _native()
    if C is not None and a.dtype == np.float32:
        return bool(C.has_inf_or_nan_f32(a))
    return not bool(np.isfinite(a).all())


def __getattr__(name):
    # HAVE_NATIVE was an eager module constant pre-round-2; keep it working
    # for callers/tests without forcing a build at import time.
    if name == "HAVE_NATIVE":
        return have_native()
    raise AttributeError(name)
