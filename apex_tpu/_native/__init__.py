"""Native host-side helpers with numpy fallback (ref: ext ``apex_C``).

``flatten``/``unflatten`` mirror apex_C.flatten/unflatten for host arrays
(checkpoint staging, data paths); ``has_inf_or_nan`` is the loss-scaler
host scan. The C extension is built on first import (cc -O3, ~1s) and the
pure-numpy fallback keeps everything working where no compiler exists.
"""

from __future__ import annotations

import numpy as np

from apex_tpu._native.build import build as _build

_C = None
_so = _build()
if _so is not None:
    try:
        import importlib.util

        _spec = importlib.util.spec_from_file_location("_apex_tpu_C", _so)
        _C = importlib.util.module_from_spec(_spec)
        _spec.loader.exec_module(_C)
    except Exception:  # pragma: no cover
        _C = None

HAVE_NATIVE = _C is not None


def flatten(arrays):
    """Concatenate host arrays into one flat array of the common dtype
    (ref: apex_C.flatten)."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    if not arrays:
        return np.empty((0,), np.float32)
    dtype = arrays[0].dtype
    if any(a.dtype != dtype for a in arrays):
        raise ValueError("flatten: arrays must share a dtype (ref asserts)")
    total = sum(a.size for a in arrays)
    out = np.empty((total,), dtype)
    if HAVE_NATIVE:
        _C.flatten_into(out, list(arrays))
    else:
        off = 0
        for a in arrays:
            out[off:off + a.size] = a.reshape(-1)
            off += a.size
    return out


def unflatten(flat, like):
    """Split a flat array back into arrays shaped like ``like``
    (ref: apex_C.unflatten)."""
    flat = np.ascontiguousarray(flat)
    outs = [np.empty(np.shape(a), flat.dtype) for a in like]
    if HAVE_NATIVE:
        _C.unflatten_from(flat, outs)
    else:
        off = 0
        for o in outs:
            o[...] = flat[off:off + o.size].reshape(o.shape)
            off += o.size
    return outs


def has_inf_or_nan(array) -> bool:
    """Host-side overflow check (ref: fp16_utils
    DynamicLossScaler.has_inf_or_nan)."""
    a = np.ascontiguousarray(array)
    if HAVE_NATIVE and a.dtype == np.float32:
        return bool(_C.has_inf_or_nan_f32(a))
    return not bool(np.isfinite(a).all())
