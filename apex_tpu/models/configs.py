"""Named TransformerConfig presets for the reference's benchmark models.

Ref: the model geometries NVIDIA's apex examples and MLPerf submissions
train (BERT-large is the DistributedFusedLAMB MLPerf model; GPT-2 medium
is the Megatron tensor-parallel example size). These are plain
dataclasses — override any field with dataclasses.replace.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from apex_tpu.testing.standalone_transformer import TransformerConfig


def _preset(**kw) -> TransformerConfig:
    base = dict(dtype=jnp.bfloat16, scan_layers=True, remat=True)
    base.update(kw)
    return TransformerConfig(**base)


def bert_base(**over) -> TransformerConfig:
    return dataclasses.replace(_preset(
        vocab_size=30528, seq_len=512, hidden=768, layers=12, heads=12,
        causal=False), **over)


def bert_large(**over) -> TransformerConfig:
    """The north-star benchmark model (bench.py / BASELINE config 3)."""
    return dataclasses.replace(_preset(
        vocab_size=30528, seq_len=512, hidden=1024, layers=24, heads=16,
        causal=False), **over)


def gpt2_small(**over) -> TransformerConfig:
    return dataclasses.replace(_preset(
        vocab_size=50304, seq_len=1024, hidden=768, layers=12, heads=12,
        causal=True), **over)


def gpt2_medium(**over) -> TransformerConfig:
    """BASELINE config 4 (tensor-parallel example)."""
    return dataclasses.replace(_preset(
        vocab_size=50304, seq_len=1024, hidden=1024, layers=24, heads=16,
        causal=True), **over)


def gpt2_large(**over) -> TransformerConfig:
    return dataclasses.replace(_preset(
        vocab_size=50304, seq_len=1024, hidden=1280, layers=36, heads=20,
        causal=True), **over)


def llama2_7b(**over) -> TransformerConfig:
    """Llama-2-7B geometry: RoPE + RMSNorm + SwiGLU, dense MHA.
    (Beyond the reference — apex has no decoder-LLM presets; the
    components are the framework's own rope/rms_norm/flash ops.)"""
    return dataclasses.replace(_preset(
        vocab_size=32000, seq_len=4096, hidden=4096, layers=32, heads=32,
        causal=True, rope=True, norm="rmsnorm", mlp_act="swiglu",
        ffn_mult=11008 / 4096), **over)


def llama3_8b(**over) -> TransformerConfig:
    """Llama-3-8B geometry: GQA (8 kv heads), RoPE, RMSNorm, SwiGLU."""
    return dataclasses.replace(_preset(
        vocab_size=128256, seq_len=8192, hidden=4096, layers=32, heads=32,
        kv_heads=8, causal=True, rope=True, norm="rmsnorm",
        mlp_act="swiglu", ffn_mult=14336 / 4096), **over)


def mixtral_8x7b(**over) -> TransformerConfig:
    """Mixtral-8x7B geometry: Llama-style body (GQA 8 kv heads, RoPE,
    RMSNorm) with 8 swiglu experts top-2 replacing the dense MLP
    (transformer/moe.py over the model axis)."""
    return dataclasses.replace(_preset(
        vocab_size=32000, seq_len=4096, hidden=4096, layers=32, heads=32,
        kv_heads=8, causal=True, rope=True, norm="rmsnorm",
        mlp_act="swiglu", ffn_mult=14336 / 4096, moe_experts=8,
        moe_top_k=2), **over)
