"""apex_tpu.models — reference model definitions for the benchmark configs.

The reference ships its model zoo via examples (apex/examples/imagenet) and
external DeepLearningExamples; here the models the BASELINE configs need are
first-class so examples and benches stay thin:

- resnet: functional NHWC ResNet-50 (bottleneck v1.5) with pluggable
  normalization — local BN, cross-replica SyncBN (psum over a mesh axis),
  or GroupNorm (the RetinaNet configuration).
- The transformer family (BERT/GPT with TP/SP/scan/remat) lives in
  apex_tpu.testing.standalone_transformer and is re-exported here.
"""

from apex_tpu.models.resnet import (  # noqa: F401
    resnet50_init,
    resnet50_apply,
    resnet_init,
    resnet_apply,
)
from apex_tpu.testing.standalone_transformer import (  # noqa: F401
    TransformerConfig,
    bert_loss,
    gpt_loss,
    transformer_init,
)
from apex_tpu.models.configs import (  # noqa: F401
    bert_base,
    bert_large,
    gpt2_large,
    gpt2_medium,
    gpt2_small,
    llama2_7b,
    llama3_8b,
    mixtral_8x7b,
)
