"""Functional NHWC ResNet (bottleneck v1.5) with pluggable normalization.

Ref: apex/examples/imagenet/main_amp.py trains torchvision resnet50 under
amp+DDP, and apex/parallel converts its BatchNorm to SyncBatchNorm; the
RetinaNet config swaps BN for GroupNorm (apex/contrib group_norm). This
module is the TPU-native model those configs exercise:

- NHWC layout (TPU conv native), 3x3 stride-2 in the bottleneck (v1.5).
- norm="bn" | "syncbn" | "gn": BN keeps running stats in a separate state
  pytree (functional — no module mutation); syncbn psums batch statistics
  over a named mesh axis via parallel.sync_batchnorm.sync_batch_stats;
  gn uses contrib.group_norm (32 groups, the RetinaNet setting).
- bf16-friendly: params fp32, compute dtype set by the caller's amp policy.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.contrib.group_norm import group_norm_nhwc
from apex_tpu.parallel.sync_batchnorm import sync_batch_stats

_DN = ("NHWC", "HWIO", "NHWC")
_STAGES50 = (3, 4, 6, 3)


def _conv(x, w, stride=1, padding="SAME"):
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), padding, dimension_numbers=_DN
    )


def _he(key, shape, dtype=jnp.float32):
    fan_in = shape[0] * shape[1] * shape[2]
    return (jax.random.normal(key, shape) * (2.0 / fan_in) ** 0.5).astype(dtype)


def _norm_init(ch):
    return {"gamma": jnp.ones((ch,), jnp.float32),
            "beta": jnp.zeros((ch,), jnp.float32)}


def _norm_state(ch):
    return {"mean": jnp.zeros((ch,), jnp.float32),
            "var": jnp.ones((ch,), jnp.float32)}


def _apply_norm(x, p, s, *, norm, training, axis_name, momentum=0.9, eps=1e-5):
    """Returns (y, new_state). GroupNorm has no state (s passes through)."""
    if norm == "gn":
        return group_norm_nhwc(x, p["gamma"], p["beta"], num_groups=32,
                               eps=eps), s
    if training:
        if norm == "syncbn":
            mean, var = sync_batch_stats(x, axis_name)
        else:
            mean, var = sync_batch_stats(x, None)
        new_s = {
            "mean": momentum * s["mean"] + (1 - momentum) * mean,
            "var": momentum * s["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    inv = jax.lax.rsqrt(var + eps) * p["gamma"]
    y = (x.astype(jnp.float32) - mean) * inv + p["beta"]
    return y.astype(x.dtype), new_s


def _block_init(key, in_ch, mid, out_ch):
    ks = jax.random.split(key, 4)
    p = {
        "conv1": _he(ks[0], (1, 1, in_ch, mid)), "n1": _norm_init(mid),
        "conv2": _he(ks[1], (3, 3, mid, mid)), "n2": _norm_init(mid),
        "conv3": _he(ks[2], (1, 1, mid, out_ch)), "n3": _norm_init(out_ch),
    }
    s = {"n1": _norm_state(mid), "n2": _norm_state(mid),
         "n3": _norm_state(out_ch)}
    if in_ch != out_ch:
        p["proj"] = _he(ks[3], (1, 1, in_ch, out_ch))
        p["np"] = _norm_init(out_ch)
        s["np"] = _norm_state(out_ch)
    return p, s


def _block_apply(p, s, x, *, stride, norm, training, axis_name):
    ns = {}
    y = _conv(x, p["conv1"])
    y, ns["n1"] = _apply_norm(y, p["n1"], s["n1"], norm=norm,
                              training=training, axis_name=axis_name)
    y = jax.nn.relu(y)
    y = _conv(y, p["conv2"], stride=stride)  # v1.5: stride on the 3x3
    y, ns["n2"] = _apply_norm(y, p["n2"], s["n2"], norm=norm,
                              training=training, axis_name=axis_name)
    y = jax.nn.relu(y)
    y = _conv(y, p["conv3"])
    y, ns["n3"] = _apply_norm(y, p["n3"], s["n3"], norm=norm,
                              training=training, axis_name=axis_name)
    if "proj" in p:
        sc = _conv(x, p["proj"], stride=stride)
        sc, ns["np"] = _apply_norm(sc, p["np"], s["np"], norm=norm,
                                   training=training, axis_name=axis_name)
    else:
        sc = x if stride == 1 else x[:, ::stride, ::stride, :]
    return jax.nn.relu(y + sc), ns


def resnet_init(key, *, stages=_STAGES50, width=64, num_classes=1000):
    """Returns (params, norm_state)."""
    ks = jax.random.split(key, 2 + sum(stages))
    params = {"stem": _he(ks[0], (7, 7, 3, width)), "stem_n": _norm_init(width)}
    state = {"stem_n": _norm_state(width)}
    in_ch, ki = width, 1
    for si, blocks in enumerate(stages):
        mid = width * (2 ** si)
        out_ch = mid * 4
        for bi in range(blocks):
            p, s = _block_init(ks[ki], in_ch, mid, out_ch)
            params[f"s{si}b{bi}"] = p
            state[f"s{si}b{bi}"] = s
            in_ch = out_ch
            ki += 1
    params["head"] = (jax.random.normal(ks[ki], (in_ch, num_classes))
                      * (1.0 / in_ch) ** 0.5).astype(jnp.float32)
    return params, state


def resnet_apply(params, state, x, *, stages=_STAGES50, norm="bn",
                 training=True, axis_name: Optional[str] = None,
                 return_features=False):
    """x: [N, H, W, 3]. Returns (logits, new_state) — or, with
    return_features, ((c3, c4, c5) pyramid features, new_state)."""
    ns = {}
    y = _conv(x, params["stem"], stride=2)
    y, ns["stem_n"] = _apply_norm(y, params["stem_n"], state["stem_n"],
                                  norm=norm, training=training,
                                  axis_name=axis_name)
    y = jax.nn.relu(y)
    y = jax.lax.reduce_window(y, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")
    feats = []
    for si, blocks in enumerate(stages):
        for bi in range(blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            y, ns[f"s{si}b{bi}"] = _block_apply(
                params[f"s{si}b{bi}"], state[f"s{si}b{bi}"], y, stride=stride,
                norm=norm, training=training, axis_name=axis_name)
        feats.append(y)
    if return_features:
        return tuple(feats[-3:]), ns
    y = y.mean(axis=(1, 2)).astype(jnp.float32)
    return y @ params["head"], ns


resnet50_init = functools.partial(resnet_init, stages=_STAGES50)
resnet50_apply = functools.partial(resnet_apply, stages=_STAGES50)
