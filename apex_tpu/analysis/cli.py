"""``python -m apex_tpu.analysis`` — run the five layers over a target.

Usage::

    python -m apex_tpu.analysis [PATHS...]        # default: the installed
                                                  # apex_tpu package
        --json                  machine-readable report on stdout
        --no-lint / --no-audit / --no-sanitize / --no-memory / --no-spmd
                                skip a layer (default: all five run)
        --memory-budget-gb G    per-device HBM budget for APX401 (also
                                via APEX_TPU_ANALYSIS_HBM_GB; unset =
                                info-level peak inventory only)
        --full-sweep            exhaustive tunable-space sanitize (the
                                `slow` CI lane; default is a seeded
                                subsample per family)
        --seed N                subsample seed (default 0)
        --sample N              subsample size per family (default 24)
        --strict                promote warn -> error (also via
                                APEX_TPU_ANALYSIS_STRICT=1)
        --show-suppressed       include pragma-suppressed findings in the
                                text report
        --list-rules            print the rule catalog and exit

Exit codes are per-rule-layer bits: 1 = lint findings (APX1xx), 2 =
auditor findings (APX2xx), 4 = sanitizer findings (APX3xx), 8 = memory
findings (APX4xx), 16 = spmd findings (APX5xx), OR-ed; 0 = clean. 64 =
internal error. Per-rule counts, the per-entry-point peak-HBM table
(``stats.memory``) and the collective-sequence verdicts (``stats.spmd``)
ride the JSON report.

The auditor, memory and spmd layers share one ``make_jaxpr`` trace per
registered entry point (``auditors.trace_entry``), so enabling all
three costs one trace pass, not three.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from apex_tpu.analysis.findings import (
    RULES,
    Finding,
    summarize,
)
from apex_tpu.utils.envvars import env_flag, env_float


def _default_target() -> List[str]:
    import apex_tpu

    return [os.path.dirname(os.path.abspath(apex_tpu.__file__))]


def run(paths: Optional[List[str]] = None, *, lint: bool = True,
        audit: bool = True, sanitize: bool = True, memory: bool = True,
        spmd: bool = True, full_sweep: bool = False, seed: int = 0,
        sample: int = 24, strict: Optional[bool] = None,
        memory_budget_gb: Optional[float] = None) -> dict:
    """Programmatic entry (the tier-1 self-run test and the graft leg
    call this): returns the full report dict incl. findings + exit
    code."""
    if strict is None:
        strict = bool(env_flag("APEX_TPU_ANALYSIS_STRICT", default=False))
    if memory_budget_gb is None:
        memory_budget_gb = env_float("APEX_TPU_ANALYSIS_HBM_GB")
    findings: List[Finding] = []
    stats: dict = {}
    root = None
    if lint:
        from apex_tpu.analysis.lint import iter_py_files, lint_paths

        targets = paths or _default_target()
        root = os.path.commonpath([os.path.abspath(p) for p in targets]) \
            if targets else None
        if root is not None and os.path.isfile(root):
            root = os.path.dirname(root)
        findings.extend(lint_paths(targets, root))
        stats["lint_files"] = len(iter_py_files(targets))
    if audit or memory or spmd:
        from apex_tpu.analysis.auditors import (audit_entry_point,
                                                default_entry_points,
                                                trace_entry)

        eps = default_entry_points()
        stats["entry_points"] = len(eps)
        if audit:
            # the APX2xx layer actually ran — --no-audit must not claim
            # donation/drift/collective coverage that did not happen
            stats["audited_entry_points"] = len(eps)
        mem_rows: List[dict] = []
        spmd_rows: List[dict] = []
        budget_bytes = None
        if memory_budget_gb is not None:
            from apex_tpu.analysis.memory import GiB

            budget_bytes = float(memory_budget_gb) * GiB
        for ep in eps:
            try:
                closed, args0 = trace_entry(ep)
            except Exception as e:  # noqa: BLE001 — broken entry = data
                findings.append(Finding(
                    "APX202", ep.tag, 0,
                    f"entry point failed to trace: "
                    f"{type(e).__name__}: {e}"))
                continue
            if audit:
                findings.extend(
                    audit_entry_point(ep, closed=closed, args0=args0))
            if memory:
                from apex_tpu.analysis.memory import (audit_memory,
                                                      leaf_factors)

                factors = None
                if ep.specs is not None:
                    factors = leaf_factors(args0, ep.specs, ep.axis_sizes)
                mfind, mrow = audit_memory(
                    closed, ep.tag, factors=factors,
                    budget_bytes=budget_bytes)
                findings.extend(mfind)
                mem_rows.append(mrow)
            if spmd:
                from apex_tpu.analysis.spmd import audit_spmd

                sfind, srow = audit_spmd(closed, ep.axis_sizes, ep.tag)
                findings.extend(sfind)
                spmd_rows.append(srow)
        if memory:
            stats["memory"] = mem_rows
            stats["memory_budget_gb"] = memory_budget_gb
        if spmd:
            stats["spmd"] = spmd_rows
    if sanitize:
        from apex_tpu.analysis.sanitizer import sanitize_families

        san_findings, san_stats = sanitize_families(
            full=full_sweep, seed=seed, sample=sample)
        findings.extend(san_findings)
        stats["sanitize"] = san_stats
    report = summarize(findings, strict=strict)
    report["strict"] = strict
    report["stats"] = stats
    report["findings"] = findings
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis",
        description="apex_tpu static analysis: trace-hygiene lint + "
                    "jaxpr auditors + Pallas kernel sanitizer + "
                    "peak-HBM estimator + SPMD deadlock checker")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the apex_tpu "
                         "package)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--no-lint", action="store_false", dest="lint")
    ap.add_argument("--no-audit", action="store_false", dest="audit")
    ap.add_argument("--no-sanitize", action="store_false", dest="sanitize")
    ap.add_argument("--no-memory", action="store_false", dest="memory")
    ap.add_argument("--no-spmd", action="store_false", dest="spmd")
    ap.add_argument("--memory-budget-gb", type=float, default=None,
                    help="per-device HBM budget for APX401 (default: "
                         "APEX_TPU_ANALYSIS_HBM_GB, else inventory only)")
    ap.add_argument("--full-sweep", action="store_true")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--sample", type=int, default=24)
    ap.add_argument("--strict", action="store_true", default=None)
    ap.add_argument("--show-suppressed", action="store_true")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in RULES.values():
            print(f"{rule.id}  {rule.name}  [{rule.severity}]")
            print(f"    {rule.doc}")
        return 0

    try:
        report = run(args.paths or None, lint=args.lint, audit=args.audit,
                     sanitize=args.sanitize, memory=args.memory,
                     spmd=args.spmd, full_sweep=args.full_sweep,
                     seed=args.seed, sample=args.sample,
                     strict=args.strict,
                     memory_budget_gb=args.memory_budget_gb)
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"apex_tpu.analysis: internal error: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 64

    findings = report.pop("findings")
    if args.as_json:
        report["findings"] = [f.to_json() for f in findings]
        print(json.dumps(report, indent=2, sort_keys=True))
        return report["exit_code"]

    shown = 0
    for f in findings:
        if f.suppressed and not args.show_suppressed:
            continue
        if f.severity == "info":
            continue
        print(f.format())
        shown += 1
    for row in report["stats"].get("memory", ()):
        over = " OVER BUDGET" if row.get("over_budget") else ""
        print(f"apex_tpu.analysis: memory {row['entry']}: peak "
              f"{row['peak_gib']:.4f} GiB/device at {row['peak_site']}"
              f"{over}")
    for row in report["stats"].get("spmd", ()):
        print(f"apex_tpu.analysis: spmd {row['entry']}: "
              f"{row['collectives']} collective(s), {row['paths']} "
              f"path(s), {row['loop_phases']} loop phase(s) — "
              f"{'ok' if row['ok'] else 'HAZARD'}")
    info = sum(1 for f in findings
               if f.severity == "info" and not f.suppressed)
    print(f"apex_tpu.analysis: {report['errors']} finding(s), "
          f"{report['suppressed']} suppressed, {info} info; "
          f"exit {report['exit_code']}"
          + (" [strict]" if report["strict"] else ""))
    return report["exit_code"]
