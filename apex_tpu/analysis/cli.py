"""``python -m apex_tpu.analysis`` — run the three layers over a target.

Usage::

    python -m apex_tpu.analysis [PATHS...]        # default: the installed
                                                  # apex_tpu package
        --json                  machine-readable report on stdout
        --no-lint / --no-audit / --no-sanitize
                                skip a layer (default: all three run)
        --full-sweep            exhaustive tunable-space sanitize (the
                                `slow` CI lane; default is a seeded
                                subsample per family)
        --seed N                subsample seed (default 0)
        --sample N              subsample size per family (default 24)
        --strict                promote warn -> error (also via
                                APEX_TPU_ANALYSIS_STRICT=1)
        --show-suppressed       include pragma-suppressed findings in the
                                text report
        --list-rules            print the rule catalog and exit

Exit codes are per-rule-layer bits: 1 = lint findings (APX1xx), 2 =
auditor findings (APX2xx), 4 = sanitizer findings (APX3xx), OR-ed; 0 =
clean. 64 = internal error. Per-rule counts ride the JSON report.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from apex_tpu.analysis.findings import (
    RULES,
    Finding,
    summarize,
)
from apex_tpu.utils.envvars import env_flag


def _default_target() -> List[str]:
    import apex_tpu

    return [os.path.dirname(os.path.abspath(apex_tpu.__file__))]


def run(paths: Optional[List[str]] = None, *, lint: bool = True,
        audit: bool = True, sanitize: bool = True, full_sweep: bool = False,
        seed: int = 0, sample: int = 24, strict: Optional[bool] = None
        ) -> dict:
    """Programmatic entry (the tier-1 self-run test and the graft leg
    call this): returns the full report dict incl. findings + exit
    code."""
    if strict is None:
        strict = bool(env_flag("APEX_TPU_ANALYSIS_STRICT", default=False))
    findings: List[Finding] = []
    stats: dict = {}
    root = None
    if lint:
        from apex_tpu.analysis.lint import iter_py_files, lint_paths

        targets = paths or _default_target()
        root = os.path.commonpath([os.path.abspath(p) for p in targets]) \
            if targets else None
        if root is not None and os.path.isfile(root):
            root = os.path.dirname(root)
        findings.extend(lint_paths(targets, root))
        stats["lint_files"] = len(iter_py_files(targets))
    if audit:
        from apex_tpu.analysis.auditors import (audit_entry_points,
                                                default_entry_points)

        eps = default_entry_points()
        findings.extend(audit_entry_points(eps))
        stats["audited_entry_points"] = len(eps)
    if sanitize:
        from apex_tpu.analysis.sanitizer import sanitize_families

        san_findings, san_stats = sanitize_families(
            full=full_sweep, seed=seed, sample=sample)
        findings.extend(san_findings)
        stats["sanitize"] = san_stats
    report = summarize(findings, strict=strict)
    report["strict"] = strict
    report["stats"] = stats
    report["findings"] = findings
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis",
        description="apex_tpu static analysis: trace-hygiene lint + "
                    "jaxpr auditors + Pallas kernel sanitizer")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the apex_tpu "
                         "package)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--no-lint", action="store_false", dest="lint")
    ap.add_argument("--no-audit", action="store_false", dest="audit")
    ap.add_argument("--no-sanitize", action="store_false", dest="sanitize")
    ap.add_argument("--full-sweep", action="store_true")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--sample", type=int, default=24)
    ap.add_argument("--strict", action="store_true", default=None)
    ap.add_argument("--show-suppressed", action="store_true")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in RULES.values():
            print(f"{rule.id}  {rule.name}  [{rule.severity}]")
            print(f"    {rule.doc}")
        return 0

    try:
        report = run(args.paths or None, lint=args.lint, audit=args.audit,
                     sanitize=args.sanitize, full_sweep=args.full_sweep,
                     seed=args.seed, sample=args.sample,
                     strict=args.strict)
    except Exception as e:  # noqa: BLE001 — CLI boundary
        print(f"apex_tpu.analysis: internal error: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 64

    findings = report.pop("findings")
    if args.as_json:
        report["findings"] = [f.to_json() for f in findings]
        print(json.dumps(report, indent=2, sort_keys=True))
        return report["exit_code"]

    shown = 0
    for f in findings:
        if f.suppressed and not args.show_suppressed:
            continue
        if f.severity == "info":
            continue
        print(f.format())
        shown += 1
    info = sum(1 for f in findings
               if f.severity == "info" and not f.suppressed)
    print(f"apex_tpu.analysis: {report['errors']} finding(s), "
          f"{report['suppressed']} suppressed, {info} info; "
          f"exit {report['exit_code']}"
          + (" [strict]" if report["strict"] else ""))
    return report["exit_code"]
