"""Jaxpr auditors (rules APX201-APX203): trace representative entry
points and check invariants the type system cannot.

Everything here works on ``jax.make_jaxpr`` output — tracing only, no
compile, no devices beyond what the trace itself needs — so the audits
run in seconds on CPU and are deterministic across backends.

Three checks:

* **APX201 use-after-donation** — walk a composite jaxpr (a host-level
  harness that calls a donating jitted step); for every ``pjit`` equation
  with ``donated_invars``, the donated operands must not be consumed by
  any later equation or escape as outputs. This is the
  ``observability/bridge.py`` double-buffer hazard class, checked
  statically: the drainer must hand the *replacement* buffer to the next
  donated step, never the one it kicked a transfer on.

* **APX202 signature-drift** — trace the same entry with the "step 0"
  and "step N" argument builders and require identical input avals
  (shape, dtype, **weak_type**). A python ``1.0`` where step 0 passed
  ``np.float32`` retraces every call — goodput.py catches it at runtime
  via trace counters; this is the static complement.

* **APX203 collective-consistency** — recursively walk every equation
  (descending into ``pjit``/``shard_map``/control-flow sub-jaxprs):
  collective primitives may only name axes the entry point declared
  (mesh axes + shard_map binds), and every ``ppermute`` permutation must
  be replica-consistent: sources unique, destinations unique, all ranks
  in range. On hardware an inconsistent permutation deadlocks or
  silently corrupts — it never raises.

Entry points are :class:`EntryPoint` records; :func:`default_entry_points`
builds the repo's representative set (train step, DDP bucket flush, ZeRO
scatter flush, decomposed TP matmul, serving paged decode, ragged
speculative verify, the unified serving step — full-width AND over the
int8 KV pool — and the pipeline-parallel 1F1B + interleaved train steps
on a pp=2 stage ring) sized to trace in well under a minute on CPU. The same traced jaxprs feed the memory
estimator (analysis/memory.py) and the SPMD checker (analysis/spmd.py)
— :func:`trace_entry` is the share point, so each entry traces once per
run however many layers consume it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from apex_tpu.analysis._jaxpr import axes_of as _axes_of
from apex_tpu.analysis._jaxpr import sub_jaxprs as _keyed_sub_jaxprs
from apex_tpu.analysis.findings import Finding

__all__ = ["EntryPoint", "audit_entry_point", "audit_entry_points",
           "audit_donation", "audit_signature_drift", "audit_collectives",
           "default_entry_points", "trace_entry"]

_COLLECTIVES = {"psum", "ppermute", "pbroadcast", "all_gather",
                "all_to_all", "reduce_scatter", "psum_scatter", "pmax",
                "pmin", "axis_index"}


@dataclass
class EntryPoint:
    """One auditable program: ``fn(*args())`` must trace under
    ``jax.make_jaxpr``. ``args_variant`` (optional) is the "step N"
    argument builder for the drift check; ``axis_sizes`` the mesh axes
    the program may legally name; ``specs`` (optional) a PartitionSpec
    tree for the arguments (prefix trees welcome) — the memory
    estimator divides the argument avals by their shard factors so its
    peak is a per-device number."""

    name: str
    fn: Callable
    args: Callable[[], tuple]
    args_variant: Optional[Callable[[], tuple]] = None
    axis_sizes: Dict[str, int] = field(default_factory=dict)
    specs: Optional[tuple] = None

    @property
    def tag(self) -> str:
        return f"<audit:{self.name}>"


def trace_entry(ep: EntryPoint):
    """Trace one entry point once: (ClosedJaxpr, the args it was traced
    with). The CLI calls this and hands the jaxpr to every enabled
    layer (auditors / memory / spmd) so an entry never re-traces."""
    import jax

    args0 = ep.args()
    return jax.make_jaxpr(ep.fn)(*args0), args0


# ---------------------------------------------------------------------------
# APX201 — donated operand referenced after the donating call
# ---------------------------------------------------------------------------

def _donating_eqns(jaxpr):
    for i, eqn in enumerate(jaxpr.eqns):
        donated = eqn.params.get("donated_invars")
        if donated and any(donated):
            yield i, eqn, donated


def audit_donation(closed_jaxpr, tag: str) -> List[Finding]:
    """Donated invars of inner pjit equations must be dead afterwards."""
    import jax.core as _core  # Literal lives here across 0.4.x

    findings: List[Finding] = []
    jaxpr = closed_jaxpr.jaxpr
    for i, eqn, donated in _donating_eqns(jaxpr):
        # scalar-prefetch style prefixes can offset donated_invars from
        # invars; align from the right, the way pjit binds them
        invars = eqn.invars[-len(donated):]
        for dflag, var in zip(donated, invars):
            if not dflag or isinstance(var, getattr(_core, "Literal", ())):
                continue
            used_later = any(
                var in later.invars for later in jaxpr.eqns[i + 1:])
            escapes = var in jaxpr.outvars
            if used_later or escapes:
                how = ("consumed by a later equation" if used_later
                       else "returned as an output")
                findings.append(Finding(
                    "APX201", tag, 0,
                    f"value donated to {eqn.params.get('name', '?')!r} is "
                    f"{how} — the buffer may alias the callee's outputs; "
                    f"carry the callee's replacement value instead "
                    f"(the bridge double-buffer discipline)"))
    return findings


# ---------------------------------------------------------------------------
# APX202 — argument-signature drift between "identical" steps
# ---------------------------------------------------------------------------

def _aval_token(aval) -> str:
    weak = getattr(aval, "weak_type", False)
    return f"{getattr(aval, 'str_short', lambda: str(aval))()}" + (
        "~weak" if weak else "")


def audit_signature_drift(fn, args0: tuple, args1: tuple, tag: str,
                          jaxpr0=None) -> List[Finding]:
    """``jaxpr0`` (optional) is a ClosedJaxpr already traced from
    ``args0`` — the entry-point driver passes the one it has so the
    expensive trace is not repeated."""
    import jax

    j0 = jaxpr0 if jaxpr0 is not None else jax.make_jaxpr(fn)(*args0)
    j1 = jax.make_jaxpr(fn)(*args1)
    a0 = [_aval_token(v.aval) for v in j0.jaxpr.invars]
    a1 = [_aval_token(v.aval) for v in j1.jaxpr.invars]
    findings: List[Finding] = []
    if a0 != a1:
        drift = [f"arg {i}: {x} -> {y}"
                 for i, (x, y) in enumerate(zip(a0, a1)) if x != y]
        if len(a0) != len(a1):
            drift.append(f"arity {len(a0)} -> {len(a1)}")
        findings.append(Finding(
            "APX202", tag, 0,
            "argument avals drift between step variants — every such "
            "call retraces and recompiles (" + "; ".join(drift) + ")"))
    return findings


# ---------------------------------------------------------------------------
# APX203 — collective consistency over shard_map jaxprs
# ---------------------------------------------------------------------------

def _sub_jaxprs(eqn):
    for _key, sub in _keyed_sub_jaxprs(eqn):
        yield sub


def _walk_eqns(jaxpr, axis_sizes: Dict[str, int], out: list):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _COLLECTIVES or name.startswith(("psum", "ppermute",
                                                    "all_gather",
                                                    "all_to_all",
                                                    "reduce_scatter")):
            out.append((eqn, dict(axis_sizes)))
        scope = dict(axis_sizes)
        mesh = eqn.params.get("mesh")
        if mesh is not None and hasattr(mesh, "shape"):
            try:
                scope.update({str(k): int(v)
                              for k, v in dict(mesh.shape).items()})
            except Exception:
                pass
        for sub in _sub_jaxprs(eqn):
            _walk_eqns(sub, scope, out)


def audit_collectives(closed_jaxpr, axis_sizes: Dict[str, int],
                      tag: str) -> List[Finding]:
    findings: List[Finding] = []
    eqns: list = []
    _walk_eqns(closed_jaxpr.jaxpr, dict(axis_sizes), eqns)
    for eqn, scope in eqns:
        prim = eqn.primitive.name
        for ax in _axes_of(eqn):
            if ax not in scope:
                findings.append(Finding(
                    "APX203", tag, 0,
                    f"{prim} names axis {ax!r} but the entry point "
                    f"declares only {sorted(scope) or '(no axes)'} — "
                    f"an unbound collective axis"))
        if prim == "ppermute":
            perm = eqn.params.get("perm") or ()
            axes = _axes_of(eqn)
            n = scope.get(axes[0]) if axes and axes[0] in scope else None
            srcs = [s for s, _ in perm]
            dsts = [d for _, d in perm]
            if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
                findings.append(Finding(
                    "APX203", tag, 0,
                    f"ppermute permutation {list(perm)} has duplicate "
                    f"sources or destinations — not replica-consistent "
                    f"(deadlocks or corrupts on hardware)"))
            elif n is not None and any(
                    not (0 <= r < n) for r in srcs + dsts):
                findings.append(Finding(
                    "APX203", tag, 0,
                    f"ppermute permutation {list(perm)} references ranks "
                    f"outside [0, {n}) on axis {axes[0]!r}"))
    return findings


# ---------------------------------------------------------------------------
# entry-point driver
# ---------------------------------------------------------------------------

def audit_entry_point(ep: EntryPoint, closed=None, args0=None
                      ) -> List[Finding]:
    """``closed``/``args0`` (optional) are a pre-traced jaxpr and the
    args it was traced with — pass :func:`trace_entry`'s result to skip
    the re-trace."""
    findings: List[Finding] = []
    if closed is None:
        try:
            closed, args0 = trace_entry(ep)
        except Exception as e:  # noqa: BLE001 — a broken entry point is data
            findings.append(Finding(
                "APX202", ep.tag, 0,
                f"entry point failed to trace: {type(e).__name__}: {e}"))
            return findings
    findings.extend(audit_donation(closed, ep.tag))
    findings.extend(audit_collectives(closed, ep.axis_sizes, ep.tag))
    if ep.args_variant is not None:
        findings.extend(audit_signature_drift(
            ep.fn, args0, ep.args_variant(), ep.tag, jaxpr0=closed))
    return findings


def audit_entry_points(eps: Optional[Sequence[EntryPoint]] = None
                       ) -> List[Finding]:
    if eps is None:
        eps = default_entry_points()
    findings: List[Finding] = []
    for ep in eps:
        findings.extend(audit_entry_point(ep))
    return findings


# ---------------------------------------------------------------------------
# the repo's representative entry points
# ---------------------------------------------------------------------------

def default_entry_points() -> List[EntryPoint]:
    """Small-but-real programs covering the subsystems the auditors were
    built for. Shapes are deliberately tiny: make_jaxpr cost only."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    import apex_tpu  # noqa: F401 — installs the jax.shard_map compat shim
    shard_map = jax.shard_map

    eps: List[EntryPoint] = []

    # -- 1. train step: toy transformer loss + grads + sgd, donated ----
    # the testing transformer is tensor-parallel by construction (vocab-
    # parallel embedding psums over "model"), so the loss runs under a
    # size-1 "model" shard_map exactly like the L0 model tests do
    from apex_tpu.parallel.mesh import cpu_mesh
    from apex_tpu.testing import (TransformerConfig, bert_loss,
                                  param_specs, smap, transformer_init)

    cfg = TransformerConfig(vocab_size=64, seq_len=16, hidden=32,
                            layers=1, heads=2, causal=False,
                            dtype=jnp.float32)
    params0 = transformer_init(jax.random.PRNGKey(0), cfg)
    tp_mesh1 = cpu_mesh({"model": 1})

    def _loss(p, tokens, labels, mask):
        return smap(
            lambda p_, t_, l_, m_: bert_loss(p_, t_, l_, m_, cfg),
            tp_mesh1, (param_specs(cfg), P(), P(), P()), P(),
        )(p, tokens, labels, mask)

    step = jax.jit(
        lambda p, tokens, labels, mask: jax.tree.map(
            lambda w, g: w - 1e-3 * g, p,
            jax.grad(_loss)(p, tokens, labels, mask)),
        donate_argnums=0)

    def train_harness(p, tokens, labels, mask):
        # the CORRECT protocol: carry the returned params, never touch
        # the donated operand again
        return step(p, tokens, labels, mask)

    def _train_args(label_dtype=np.int32):
        tokens = np.zeros((2, cfg.seq_len), np.int32)
        labels = np.zeros((2, cfg.seq_len), label_dtype)
        mask = np.ones((2, cfg.seq_len), bool)
        return (params0, tokens, labels, mask)

    eps.append(EntryPoint(
        name="train_step", fn=train_harness, args=_train_args,
        args_variant=_train_args, axis_sizes={"model": 1}))

    # -- 2. DDP bucket flush: psum mean over the data axis -------------
    n = max(1, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:n]), ("data",))

    def ddp_flush(g):
        f = shard_map(
            lambda x: jax.lax.psum(x, "data") / n,
            mesh=mesh, in_specs=(P("data"),), out_specs=P("data"))
        return f(g)

    eps.append(EntryPoint(
        name="ddp_bucket_flush", fn=ddp_flush,
        args=lambda: (np.ones((n * 2, 8), np.float32),),
        axis_sizes={"data": n}))

    # -- 3. ZeRO scatter flush: psum_scatter over the flat bucket ------
    def zero_flush(g):
        f = shard_map(
            lambda x: jax.lax.psum_scatter(x, "data", scatter_dimension=0,
                                           tiled=True),
            mesh=mesh, in_specs=(P(),), out_specs=P("data"))
        return f(g)

    eps.append(EntryPoint(
        name="zero_scatter_flush", fn=zero_flush,
        args=lambda: (np.ones((n * 4,), np.float32),),
        axis_sizes={"data": n}))

    # -- 4. decomposed TP collective matmul (the ppermute ring) --------
    from apex_tpu.parallel import overlap

    tp_mesh = Mesh(np.array(jax.devices()[:n]), ("tp",))

    def tp_ring(x, w):
        f = shard_map(
            lambda xs, ws: overlap.all_gather_matmul(xs, ws, "tp", 0, 2),
            mesh=tp_mesh, in_specs=(P("tp"), P()), out_specs=P("tp"))
        return f(x, w)

    eps.append(EntryPoint(
        name="overlap_tp_matmul", fn=tp_ring,
        args=lambda: (np.ones((n * 2, 8), np.float32),
                      np.ones((8, 8), np.float32)),
        axis_sizes={"tp": n}))

    # -- 5. serving paged decode (jnp oracle path; dtype-drift pinned) -
    from apex_tpu.ops.paged_attention import paged_attention_ref

    def decode(q, kp, vp, tables, lengths):
        return paged_attention_ref(q, kp, vp, tables, lengths)

    def _decode_args(len_dtype=np.int32):
        q = np.zeros((2, 4, 16), np.float32)
        kp = np.zeros((8, 4, 2, 16), np.float32)
        vp = np.zeros((8, 4, 2, 16), np.float32)
        tables = np.zeros((2, 3), np.int32)
        lengths = np.array([5, 0], len_dtype)
        return (q, kp, vp, tables, lengths)

    eps.append(EntryPoint(
        name="serving_paged_decode", fn=jax.jit(decode),
        args=_decode_args, args_variant=_decode_args))

    # -- 6. serving ragged verify (speculative K+1 windows over the
    #       multi-query oracle; dtype-drift pinned on the ragged lengths)
    from apex_tpu.ops.paged_attention import ragged_paged_attention_ref

    def verify(q, kp, vp, tables, qs, ql, kl):
        return ragged_paged_attention_ref(q, kp, vp, tables, qs, ql, kl)

    def _verify_args(len_dtype=np.int32):
        # a K=3 verify window, a plain decode row, an idle slot — the
        # packed layout the speculative engine hands the unified step
        q = np.zeros((5, 4, 16), np.float32)
        kp = np.zeros((8, 4, 2, 16), np.float32)
        vp = np.zeros((8, 4, 2, 16), np.float32)
        tables = np.zeros((3, 3), np.int32)
        qs = np.array([0, 4, 5], np.int32)
        ql = np.array([4, 1, 0], np.int32)
        kl = np.array([9, 6, 0], len_dtype)
        return (q, kp, vp, tables, qs, ql, kl)

    eps.append(EntryPoint(
        name="serving_ragged_verify", fn=jax.jit(verify),
        args=_verify_args, args_variant=_verify_args))

    # -- 7. the unified serving step: cow_append + extend_slots +
    #       per-layer KV append + ragged multi-query attention +
    #       vocab-parallel greedy, donated cache — the ONE compiled
    #       program the engine runs (prefill chunks, decodes and spec
    #       verify windows are all run metadata of this step)
    from apex_tpu.serving import kv_cache as kc
    from apex_tpu.serving.engine import _step_body

    sv_cfg = TransformerConfig(vocab_size=64, seq_len=32, hidden=32,
                               layers=1, heads=2, causal=True,
                               dtype=jnp.float32)
    sv_params = transformer_init(jax.random.PRNGKey(1), sv_cfg)
    sv_mesh = cpu_mesh({"model": 1})
    sv_specs = (param_specs(sv_cfg), kc.cache_pspecs("model"),
                P(), P(), P())
    sv_step = jax.jit(
        smap(lambda p, c, t, qs, ql: _step_body(
            p, c, t, qs, ql, cfg=sv_cfg, scfg={"tp": 1}),
            sv_mesh, sv_specs, (kc.cache_pspecs("model"), P())),
        donate_argnums=(1,))

    def _sv_args(tok_dtype=np.int32):
        # one 3-token prompt chunk + one decode row over a tiny pool
        cache = kc.paged_kv_cache(
            layers=sv_cfg.layers, num_blocks=8, block_size=4,
            n_kv_heads=sv_cfg.heads,
            head_dim=sv_cfg.hidden // sv_cfg.heads,
            max_slots=2, max_blocks_per_seq=8, dtype=jnp.float32)
        tokens = np.zeros((4,), tok_dtype)
        qs = np.array([0, 3], np.int32)
        ql = np.array([3, 1], np.int32)
        return (sv_params, cache, tokens, qs, ql)

    eps.append(EntryPoint(
        name="serving_unified_step", fn=sv_step, args=_sv_args,
        args_variant=_sv_args, axis_sizes={"model": 1}, specs=sv_specs))

    # -- 7b. the SAME unified step over the int8 KV pool (the
    #        APEX_TPU_SERVING_KV_INT8 program): quantized payload +
    #        scale-sidecar pools donated through the step, in-kernel
    #        dequantization at fetch time — donation, dtype-drift and
    #        the APX4xx/APX5xx layers all run over the quantized
    #        program too
    sv_qspecs = (param_specs(sv_cfg), kc.quant_cache_pspecs("model"),
                 P(), P(), P())
    sv_qstep = jax.jit(
        smap(lambda p, c, t, qs, ql: _step_body(
            p, c, t, qs, ql, cfg=sv_cfg, scfg={"tp": 1}),
            sv_mesh, sv_qspecs, (kc.quant_cache_pspecs("model"), P())),
        donate_argnums=(1,))

    def _svq_args(tok_dtype=np.int32):
        # same run layout as the full-width entry, over the DOUBLED
        # pool the int8 variant holds in the same bytes
        cache = kc.quantized_kv_cache(
            layers=sv_cfg.layers, num_blocks=16, block_size=4,
            n_kv_heads=sv_cfg.heads,
            head_dim=sv_cfg.hidden // sv_cfg.heads,
            max_slots=2, max_blocks_per_seq=8)
        tokens = np.zeros((4,), tok_dtype)
        qs = np.array([0, 3], np.int32)
        ql = np.array([3, 1], np.int32)
        return (sv_params, cache, tokens, qs, ql)

    eps.append(EntryPoint(
        name="serving_unified_step_int8", fn=sv_qstep, args=_svq_args,
        args_variant=_svq_args, axis_sizes={"model": 1},
        specs=sv_qspecs))

    # -- 8/9. pipeline-parallel train steps (1F1B + interleaved) on the
    #         circulating stage ring — pp=2 whenever the process has two
    #         host devices (tier-1 / battery9 / the graft leg do), pp=1
    #         as the single-device degenerate so the CLI still audits
    #         the schedule's structure anywhere
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_with_interleaving,
        forward_backward_pipelining_without_interleaving,
    )

    try:
        _cdevs = jax.devices("cpu")
    except Exception:  # no host platform registered: use what exists
        _cdevs = jax.devices()
    pp = 2 if len(_cdevs) >= 2 else 1
    pp_mesh = Mesh(np.array(_cdevs[:pp]), ("stage",))
    HID, MBS, HEAD = 8, 2, 4

    def _pp_stage(p, x):
        return jnp.tanh(x @ p["w"] + p["b"]) + x

    def _pp_loss(lp, y, t):
        return jnp.mean((y @ lp["head"] - t) ** 2)

    def _pp_fn(schedule, vp):
        def body(chunks, lp, xs, ys):
            local = jax.tree.map(lambda a: a[0], chunks)  # [1,V,..]->[V,..]
            if vp == 1:
                local = jax.tree.map(lambda a: a[0], local)
            res = schedule(_pp_stage, _pp_loss, local, lp, xs, ys,
                           axis="stage", checkpoint_activations=True)
            g = res.stage_grads
            if vp == 1:
                g = jax.tree.map(lambda a: a[None], g)
            return (res.losses, jax.tree.map(lambda a: a[None], g),
                    res.loss_grads)

        return jax.jit(shard_map(
            body, mesh=pp_mesh,
            in_specs=(P("stage"), P(), P(), P()),
            out_specs=(P(), P("stage"), P()), check_vma=False))

    def _pp_args_builder(vp):
        def build(x_dtype=np.float32):
            chunks = {"w": np.zeros((pp, vp, HID, HID), np.float32),
                      "b": np.zeros((pp, vp, HID), np.float32)}
            lp = {"head": np.zeros((HID, HEAD), np.float32)}
            xs = np.zeros((pp, MBS, HID), x_dtype)   # M = pp microbatches
            ys = np.zeros((pp, MBS, HEAD), np.float32)
            return (chunks, lp, xs, ys)

        return build

    for pname, sched, vp in (
            ("pp_1f1b_train_step",
             forward_backward_pipelining_without_interleaving, 1),
            ("pp_interleaved_train_step",
             forward_backward_pipelining_with_interleaving, 2)):
        eps.append(EntryPoint(
            name=pname, fn=_pp_fn(sched, vp), args=_pp_args_builder(vp),
            args_variant=_pp_args_builder(vp), axis_sizes={"stage": pp},
            specs=(P("stage"), P(), P(), P())))

    return eps
