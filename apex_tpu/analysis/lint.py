"""AST trace-hygiene linter (rules APX101-APX107).

Pure-stdlib static analysis over the package source — no jax import, no
tracing, so the whole-package self-run costs well under a second and can
gate every PR. Each rule encodes one bug class a previous PR shipped and
hand-fixed:

* APX101 — env values frozen at import time inside trace paths (the
  PR-3 ``utils/profiling.py`` fix).
* APX102 — ad-hoc ``int(os.environ...)`` / ``== "1"`` knob parsing
  (unified into ``utils/envvars.py`` by this PR).
* APX103 — host syncs (``.item()``, ``jax.device_get``, ``np.asarray``,
  ``float(arg)``) inside jitted functions / kernel bodies.
* APX104 — decorators whose wrapper closure lacks ``functools.wraps``
  (the PR-5 ``profiling.annotate`` fix).
* APX105 — Python truthiness on jnp expressions inside traced code.
* APX106 — ``pl.BlockSpec`` / ``index_map=`` lambdas defined inside a
  loop (or comprehension) that capture the loop variable by reference:
  python closures late-bind, so every index map the loop builds reads
  the LAST iteration's value when Pallas finally calls it. Bind it as
  a default (``lambda i, k=k: ...``) or build the map in a factory.
* APX107 — ``time.time()`` used for duration math: any subtraction
  with a wall-clock read (direct call or a name assigned from one) on
  either side. Wall clocks step under NTP; spans/latencies must use
  ``time.perf_counter()``. Pure timestamps (no arithmetic) stay legal
  — the registry's record timestamps, postmortem file names.

"Jitted" is decided statically: a function is **hot** when it is
decorated with ``jax.jit``/``pjit`` (bare or via ``functools.partial``),
passed to ``jax.jit(...)`` anywhere in the same module, passed to
``pl.pallas_call`` (directly or through ``functools.partial``), or named
in :data:`HOT_PATHS`. Everything else is host code, where syncs are the
point (the drainer's harvest, the engine's scheduler) — that scoping is
the triage the rule catalog promises: of the ~113 host-sync call sites
in the repo, the ones outside hot functions are the allowlist.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from apex_tpu.analysis.findings import Finding, Pragmas

__all__ = ["lint_source", "lint_file", "lint_paths", "iter_py_files",
           "HOT_PATHS"]

# Known-hot host functions that are not statically jit-detectable
# (qualified "<module suffix>:<function name>"). Kept deliberately short:
# the rule's value is precision, not recall-by-listing.
HOT_PATHS: Set[str] = set()

# modules allowed to touch os.environ int/flag parsing directly
_ENV_HELPER_MODULES = ("utils/envvars.py",)


def _is_env_helper_module(path: str, rel: str) -> bool:
    """True for utils/envvars.py however the lint target was rooted —
    the repo-relative path narrows when the CLI is pointed at a
    subdirectory (``apex_tpu/utils`` makes rel just ``envvars.py``), so
    the absolute path is consulted too."""
    posix = os.path.abspath(path).replace(os.sep, "/")
    rel_posix = rel.replace(os.sep, "/")
    return (rel_posix.endswith(_ENV_HELPER_MODULES)
            or any(posix.endswith("/" + m) for m in _ENV_HELPER_MODULES))


def _is_env_read(node: ast.AST) -> bool:
    """os.environ.get(...) / os.getenv(...) / os.environ[...] /
    environ.get(...) — any expression whose value comes from the
    process environment."""
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            # os.environ.get / environ.get
            if f.attr == "get" and _is_environ(f.value):
                return True
            # os.getenv
            if f.attr == "getenv" and isinstance(f.value, ast.Name) \
                    and f.value.id == "os":
                return True
        if isinstance(f, ast.Name) and f.id == "getenv":
            return True
    if isinstance(node, ast.Subscript) and _is_environ(node.value):
        return True
    return False


def _is_environ(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return True
    if isinstance(node, ast.Name) and node.id == "environ":
        return True
    return False


def _contains_env_read(node: ast.AST) -> Optional[ast.AST]:
    for sub in ast.walk(node):
        if _is_env_read(sub):
            return sub
    return None


def _module_scope_env_read(stmt: ast.AST) -> Optional[ast.AST]:
    """First env read evaluated AT MODULE SCOPE inside ``stmt`` — reads
    inside nested function/lambda bodies run at call time, not at
    import, so they are skipped (a function defined under a top-level
    try/if still reads at call time); class bodies DO execute at
    import and are descended into."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
        return None
    if _is_env_read(stmt):
        return stmt
    for child in ast.iter_child_nodes(stmt):
        hit = _module_scope_env_read(child)
        if hit is not None:
            return hit
    return None


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression ("jax.jit",
    "functools.partial", ...); "" when not a plain name chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_PALLAS_CALL_NAMES = {"pl.pallas_call", "pallas_call",
                      "pallas.pallas_call"}
_SYNC_ATTRS = {"item", "block_until_ready"}
_DEVICE_GET = {"jax.device_get", "device_get"}
_NP_SYNC = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
            "onp.asarray", "onp.array"}
_JNP_PREFIXES = ("jnp.", "jax.numpy.", "lax.", "jax.lax.")


def _first_arg_names(call: ast.Call) -> List[str]:
    """Names plausibly designating the function being wrapped: the first
    positional arg of jax.jit(...) / pl.pallas_call(...), looking
    through functools.partial."""
    if not call.args:
        return []
    a = call.args[0]
    if isinstance(a, ast.Name):
        return [a.id]
    if isinstance(a, ast.Call) and _dotted(a.func) in (
            "functools.partial", "partial") and a.args:
        inner = a.args[0]
        if isinstance(inner, ast.Name):
            return [inner.id]
    return []


def _collect_time_names(tree: ast.Module) -> tuple:
    """(module aliases of ``time``, function aliases of ``time.time``)
    — what an APX107 wall-clock read can look like in this module:
    ``time.time()`` / ``t.time()`` after ``import time as t`` /
    ``time()`` after ``from time import time`` (incl. ``as`` names)."""
    mods: Set[str] = set()
    funcs: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    mods.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "time":
                    funcs.add(a.asname or "time")
    return mods, funcs


def _collect_hot_names(tree: ast.Module) -> Set[str]:
    """Function names that are jitted or pallas-called anywhere in the
    module (assignment-style ``step = jax.jit(body, ...)`` and call-style
    ``pl.pallas_call(functools.partial(kernel, ...), ...)``)."""
    hot: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name in _JIT_NAMES or name in _PALLAS_CALL_NAMES:
                hot.update(_first_arg_names(node))
    return hot


def _is_hot_def(fn: ast.AST, hot_names: Set[str], module_tag: str) -> bool:
    assert isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
    for dec in fn.decorator_list:
        d = dec.func if isinstance(dec, ast.Call) else dec
        name = _dotted(d)
        if name in _JIT_NAMES:
            return True
        # functools.partial(jax.jit, ...) as a decorator
        if isinstance(dec, ast.Call) and name in ("functools.partial",
                                                  "partial"):
            if dec.args and _dotted(dec.args[0]) in _JIT_NAMES:
                return True
    if fn.name in hot_names:
        return True
    if f"{module_tag}:{fn.name}" in HOT_PATHS:
        return True
    return False


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel                 # repo-relative, for allowlists
        self.findings: List[Finding] = []
        self.tree = ast.parse(source, filename=path)
        self.hot_names = _collect_hot_names(self.tree)
        self._fn_stack: List[ast.AST] = []
        self._hot_depth = 0
        # loop-target names currently in scope (for/comprehension
        # frames) — what an APX106 late-binding lambda can capture
        self._loop_vars: List[Set[str]] = []
        # per-function-frame names assigned directly from an env read
        # ("env = os.environ.get(...)") — the aliases APX102 follows
        self._env_aliases: List[Set[str]] = []
        # names assigned from a wall-clock read ("t0 = time.time()") —
        # the aliases APX107 follows through a later subtraction; frame
        # 0 is module scope, functions push/pop their own
        self._time_mods, self._time_funcs = _collect_time_names(self.tree)
        self._time_aliases: List[Set[str]] = [set()]

    # -- helpers ----------------------------------------------------
    def _add(self, rule: str, node: ast.AST, msg: str) -> None:
        self.findings.append(
            Finding(rule, self.path, getattr(node, "lineno", 0), msg))

    def run(self) -> List[Finding]:
        self._module_scope_env_reads()
        self.visit(self.tree)
        return self.findings

    # -- APX101: env reads at module scope ---------------------------
    def _module_scope_env_reads(self) -> None:
        for stmt in self.tree.body:
            hit = _module_scope_env_read(stmt)
            if hit is not None:
                self._add(
                    "APX101", hit,
                    "environment read at module scope — the value is "
                    "frozen at import time; re-read it at call time "
                    "(utils/envvars.env_int / env_flag) or pragma an "
                    "intentionally import-time site")

    # -- function tracking -------------------------------------------
    def _visit_fn(self, node) -> None:
        hot = _is_hot_def(node, self.hot_names, self.rel)
        self._fn_stack.append(node)
        self._env_aliases.append(set())
        self._time_aliases.append(set())
        self._hot_depth += 1 if hot else 0
        self._check_missing_wraps(node)
        self.generic_visit(node)
        self._hot_depth -= 1 if hot else 0
        self._time_aliases.pop()
        self._env_aliases.pop()
        self._fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    @property
    def _in_hot(self) -> bool:
        return self._hot_depth > 0

    # -- APX104: decorator missing functools.wraps --------------------
    def _check_missing_wraps(self, fn) -> None:
        """Fire when ``fn`` returns an inner *args/**kwargs closure that
        calls one of ``fn``'s parameters and the closure carries no
        functools.wraps — the classic hand-rolled decorator shape. HOFs
        with explicit-signature inner functions (step builders,
        index-map factories) deliberately do not match."""
        params = {a.arg for a in fn.args.args + fn.args.posonlyargs
                  + fn.args.kwonlyargs}
        if not params:
            return
        inner_defs = {n.name: n for n in fn.body
                      if isinstance(n, ast.FunctionDef)}
        returned: List[ast.FunctionDef] = []
        for stmt in fn.body:
            if isinstance(stmt, ast.Return) and \
                    isinstance(stmt.value, ast.Name) and \
                    stmt.value.id in inner_defs:
                returned.append(inner_defs[stmt.value.id])
        for inner in returned:
            if not (inner.args.vararg and inner.args.kwarg):
                continue
            calls_param = any(
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id in params
                for sub in ast.walk(inner))
            if not calls_param:
                continue
            has_wraps = any(
                _dotted(d.func if isinstance(d, ast.Call) else d)
                in ("functools.wraps", "wraps")
                for d in inner.decorator_list)
            if not has_wraps:
                self._add(
                    "APX104", inner,
                    f"wrapper {inner.name!r} returned by {fn.name!r} "
                    f"calls the wrapped function but is not decorated "
                    f"with functools.wraps — name/docstring/signature "
                    f"of every wrapped function are lost")

    def _is_env_alias(self, node: ast.AST) -> bool:
        if _is_env_read(node):
            return True
        if isinstance(node, ast.Name):
            return any(node.id in frame for frame in self._env_aliases)
        return False

    # -- wall-clock tracking (APX107) ---------------------------------
    def _is_wallclock_call(self, node: ast.AST) -> bool:
        """A ``time.time()``-shaped expression under this module's
        imports (``time.time()``, ``t.time()`` after ``import time as
        t``, bare ``time()`` after ``from time import time``)."""
        if not isinstance(node, ast.Call):
            return False
        name = _dotted(node.func)
        if name in self._time_funcs:
            return True
        mod, _, attr = name.rpartition(".")
        return attr == "time" and mod in self._time_mods

    def _is_wallclock_operand(self, node: ast.AST) -> bool:
        if self._is_wallclock_call(node):
            return True
        if isinstance(node, ast.Name):
            return any(node.id in frame for frame in self._time_aliases)
        return False

    def _note_time_assign(self, value: ast.AST, target: ast.AST) -> None:
        """Track (or clear) a name's wall-clock provenance in the
        current frame: assigning ``time.time()`` marks it, reassigning
        anything else un-marks it (precision: a reused ``t0`` must not
        keep firing)."""
        if not isinstance(target, ast.Name):
            return
        frame = self._time_aliases[-1]
        if self._is_wallclock_call(value):
            frame.add(target.id)
        else:
            frame.discard(target.id)

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._env_aliases and _contains_env_read(node.value) is not None:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self._env_aliases[-1].add(tgt.id)
        for tgt in node.targets:
            self._note_time_assign(node.value, tgt)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if self._env_aliases and node.value is not None \
                and _contains_env_read(node.value) is not None \
                and isinstance(node.target, ast.Name):
            self._env_aliases[-1].add(node.target.id)
        if node.value is not None:
            self._note_time_assign(node.value, node.target)
        self.generic_visit(node)

    def visit_NamedExpr(self, node: ast.NamedExpr) -> None:
        # walrus: (v := os.environ.get(...)) aliases v for the frame
        if self._env_aliases and _contains_env_read(node.value) is not None \
                and isinstance(node.target, ast.Name):
            self._env_aliases[-1].add(node.target.id)
        self._note_time_assign(node.value, node.target)
        self.generic_visit(node)

    # APX107: wall-clock subtraction = duration math on time.time()
    def visit_BinOp(self, node: ast.BinOp) -> None:
        if isinstance(node.op, ast.Sub) and (
                self._is_wallclock_operand(node.left)
                or self._is_wallclock_operand(node.right)):
            self._add(
                "APX107", node,
                "duration computed from time.time() — the wall clock "
                "steps under NTP slew, so this span/latency can come "
                "out negative or wildly wrong; use "
                "time.perf_counter() (monotonic) for duration math "
                "(time.time() is fine for pure timestamps)")
        self.generic_visit(node)

    # -- loop tracking (APX106) ---------------------------------------
    @staticmethod
    def _target_names(target: ast.AST) -> Set[str]:
        return {n.id for n in ast.walk(target)
                if isinstance(n, ast.Name)}

    def _visit_loop(self, node) -> None:
        self._loop_vars.append(self._target_names(node.target))
        self.generic_visit(node)
        self._loop_vars.pop()

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop

    def _visit_comp(self, node) -> None:
        names: Set[str] = set()
        for gen in node.generators:
            names |= self._target_names(gen.target)
        self._loop_vars.append(names)
        self.generic_visit(node)
        self._loop_vars.pop()

    visit_ListComp = _visit_comp
    visit_SetComp = _visit_comp
    visit_GeneratorExp = _visit_comp
    visit_DictComp = _visit_comp

    # -- expression-level rules ---------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        self._check_raw_env_parse(node)
        self._check_late_binding(node)
        if self._in_hot:
            self._check_host_sync(node)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        self._check_env_flag_compare(node)
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        self._check_truthiness(node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_truthiness(node.test)
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_truthiness(node.test)
        self.generic_visit(node)

    def visit_BoolOp(self, node: ast.BoolOp) -> None:
        for v in node.values:
            self._check_truthiness(v, in_boolop=True)
        self.generic_visit(node)

    # APX102a: int()/float() directly over an env read
    def _check_raw_env_parse(self, node: ast.Call) -> None:
        if _is_env_helper_module(self.path, self.rel):
            return
        if isinstance(node.func, ast.Name) and node.func.id in ("int",
                                                                "float"):
            for a in node.args:
                if self._is_env_alias(a) or (
                        isinstance(a, (ast.BoolOp, ast.IfExp))
                        and any(self._is_env_alias(v)
                                for v in ast.walk(a)
                                if isinstance(v, (ast.Name, ast.Call,
                                                  ast.Subscript)))):
                    self._add(
                        "APX102", node,
                        f"raw {node.func.id}() over an environment read "
                        f"— use apex_tpu.utils.envvars.env_int so a "
                        f"malformed value raises an error naming the "
                        f"variable")

    # APX102b: env read compared against '0'/'1'
    def _check_env_flag_compare(self, node: ast.Compare) -> None:
        if _is_env_helper_module(self.path, self.rel):
            return
        sides = [node.left] + list(node.comparators)
        if not any(self._is_env_alias(s) for s in sides):
            return
        if any(isinstance(s, ast.Constant) and s.value in ("0", "1")
               for s in sides):
            self._add(
                "APX102", node,
                "flag parse by string comparison over an environment "
                "read — use apex_tpu.utils.envvars.env_flag so a typo'd "
                "gate value raises instead of silently meaning 'off'")

    # APX106: BlockSpec / index-map lambdas late-binding a loop variable
    def _check_late_binding(self, node: ast.Call) -> None:
        if not self._loop_vars:
            return
        name = _dotted(node.func)
        lambdas: List[ast.Lambda] = []
        if name.endswith("BlockSpec"):
            lambdas += [a for a in node.args if isinstance(a, ast.Lambda)]
            lambdas += [kw.value for kw in node.keywords
                        if isinstance(kw.value, ast.Lambda)]
        else:
            lambdas += [kw.value for kw in node.keywords
                        if kw.arg == "index_map"
                        and isinstance(kw.value, ast.Lambda)]
        if not lambdas:
            return
        loop_names = set().union(*self._loop_vars)
        for lam in lambdas:
            # parameters (incl. default-bound `k=k`) rebind the name —
            # that is exactly the sanctioned fix, so they never fire
            bound = {a.arg for a in lam.args.args + lam.args.posonlyargs
                     + lam.args.kwonlyargs}
            free = {n.id for n in ast.walk(lam.body)
                    if isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)} - bound
            captured = sorted(free & loop_names)
            if captured:
                self._add(
                    "APX106", lam,
                    f"index-map lambda captures loop "
                    f"variable{'s' if len(captured) > 1 else ''} "
                    f"{', '.join(captured)} by reference — closures "
                    f"late-bind, so every map built by this loop sees "
                    f"the last iteration's value; bind it as a default "
                    f"({', '.join(f'{c}={c}' for c in captured)}) or "
                    f"build the map in a factory function")

    # APX103: host syncs inside hot functions
    def _check_host_sync(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _SYNC_ATTRS:
            self._add(
                "APX103", node,
                f".{fn.attr}() inside a jitted function or kernel body "
                f"forces a device sync (or fails at trace time) — hoist "
                f"the readback to the host loop")
            return
        name = _dotted(fn)
        if name in _DEVICE_GET or name in _NP_SYNC:
            self._add(
                "APX103", node,
                f"{name}() inside a jitted function or kernel body "
                f"pulls the value to the host every step — accumulate "
                f"on device (observability.bridge) and drain "
                f"asynchronously instead")
            return
        if isinstance(fn, ast.Name) and fn.id == "float" and node.args:
            a = node.args[0]
            if isinstance(a, ast.Name) and self._is_param(a.id):
                self._add(
                    "APX103", node,
                    f"float({a.id}) of a traced argument inside a "
                    f"jitted function — a host conversion that syncs "
                    f"(or raises) at trace time")

    def _is_param(self, name: str) -> bool:
        for fn in reversed(self._fn_stack):
            args = fn.args
            for a in (args.args + args.posonlyargs + args.kwonlyargs):
                if a.arg == name:
                    return True
        return False

    # APX105: truthiness of jnp expressions in hot scope
    def _check_truthiness(self, test: ast.AST,
                          in_boolop: bool = False) -> None:
        if not self._in_hot:
            return
        node: Optional[ast.AST] = None
        if isinstance(test, ast.Call) and _dotted(test.func).startswith(
                _JNP_PREFIXES):
            node = test
        elif isinstance(test, ast.Compare):
            sides = [test.left] + list(test.comparators)
            if any(isinstance(s, ast.Call)
                   and _dotted(s.func).startswith(_JNP_PREFIXES)
                   for s in sides):
                node = test
        if node is not None:
            self._add(
                "APX105", node,
                "Python truthiness of a jnp expression inside a jitted "
                "function or kernel body — TracerBoolConversionError at "
                "trace time (or a silently frozen branch); use "
                "lax.cond / jnp.where / pl.when")


def lint_source(source: str, path: str, rel: Optional[str] = None
                ) -> List[Finding]:
    """Lint one source string; pragmas applied. ``rel`` is the
    repo-relative path used for allowlists (defaults to ``path``)."""
    linter = _Linter(path, rel or path, source)
    return Pragmas(source).apply(linter.run())


def lint_file(path: str, root: Optional[str] = None) -> List[Finding]:
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    rel = os.path.relpath(path, root) if root else path
    return lint_source(source, path, rel)


def iter_py_files(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames)
                           if f.endswith(".py"))
    return sorted(out)


def lint_paths(paths: List[str], root: Optional[str] = None
               ) -> List[Finding]:
    """Lint every .py under ``paths`` (dirs walked recursively)."""
    findings: List[Finding] = []
    for f in iter_py_files(paths):
        findings.extend(lint_file(f, root))
    return findings
