"""SPMD collective-consistency / deadlock checker (rules APX501-APX503).

Answers the second question that kills multi-chip runs — *can this
program hang* — statically: extract the ordered collective sequence
(psum / all_gather / psum_scatter / ppermute / all_to_all, with axes and
operand shapes) from a jaxpr **per control-flow path**, descending into
``cond`` / ``while`` / ``scan`` / inner ``pjit`` / ``shard_map`` /
remat, then check the three hazards SPMD lowering cannot:

* **APX501 branch-divergent collectives** — a ``lax.cond`` whose
  predicate is tainted by ``axis_index`` selects branches with different
  collective sequences over an axis the predicate varies along: replicas
  of that axis take different branches and issue mismatched collectives
  — the classic SPMD hang. Taint is tracked per axis name, so the
  pipeline engine's stage-varying loss cond around *model-axis*
  collectives (every tp peer of a stage shares the predicate) stays
  legal.

* **APX502 ppermute pairing** — a ``ppermute`` inside a loop body (the
  steady state of a schedule) must be a **total bijection** of its axis:
  a rank that never receives reads zeros every iteration, a rank that
  never sends has its value dropped — mismatched send/recv pairing
  across the cyclic schedule. (Replica-consistency — unique src/dst, in
  range — is APX203; this is the scheduling-level complement.)

* **APX503 pipeline-phase inconsistency** — the loop phases of one
  schedule (each innermost loop body containing ppermutes, per axis)
  must rotate the ring compatibly: every perm must be the schedule's
  base rotation or its inverse (forward wave / transposed backward wave
  / remat recompute). A phase permuting a different topology hands
  activations or grads to the wrong stage — the forward/backward
  permutes no longer compose to the identity across the schedule.

Like the auditors, everything here is ``make_jaxpr`` output only: no
compiles, no devices, deterministic across backends.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, FrozenSet, List, Optional, Tuple

from apex_tpu.analysis._jaxpr import align_right, axes_of, is_literal, \
    sub_jaxprs
from apex_tpu.analysis.findings import Finding

__all__ = ["CollectiveOp", "collective_paths", "audit_spmd"]

_axes_of = axes_of
_is_literal = is_literal
_sub_jaxprs_of = sub_jaxprs
_align_right = align_right

_COLLECTIVES = {"psum", "ppermute", "pbroadcast", "all_gather",
                "all_to_all", "reduce_scatter", "psum_scatter",
                "pmax", "pmin"}

# fork guard: a cond-heavy program multiplies paths; past this we keep
# the first MAX_PATHS and mark the verdict truncated (still sound for
# APX501-503, which fire during the walk, not on the path product)
MAX_PATHS = 64


@dataclass(frozen=True)
class CollectiveOp:
    """One collective in program order: primitive, axes, operand shape/
    dtype, the ppermute perm (if any), how many loop bodies deep it
    sits, and its site string."""

    prim: str
    axes: Tuple[str, ...]
    shape: Tuple[int, ...]
    dtype: str
    perm: Optional[Tuple[Tuple[int, int], ...]]
    loop_depth: int
    site: str

    @property
    def sig(self) -> Tuple[str, Tuple[str, ...]]:
        return (self.prim, self.axes)

    def to_json(self) -> dict:
        return {"prim": self.prim, "axes": list(self.axes),
                "shape": list(self.shape), "dtype": self.dtype,
                "loop_depth": self.loop_depth, "site": self.site}


def _perm_map(perm) -> Dict[int, int]:
    return {int(s): int(d) for s, d in perm}


def _is_total_bijection(perm, n: int) -> bool:
    m = _perm_map(perm)
    return (len(m) == n and set(m) == set(range(n))
            and set(m.values()) == set(range(n)))


def _same_or_inverse(a, b) -> bool:
    ma, mb = _perm_map(a), _perm_map(b)
    if ma == mb:
        return True
    return {(d, s) for s, d in ma.items()} == set(mb.items())


_Taint = FrozenSet[str]
_NO_TAINT: _Taint = frozenset()


class _Walker:
    """One pass over the jaxpr tree: collects collectives per path,
    propagates axis_index taint, records loop-body ppermute phases, and
    emits APX501/APX502 findings as it goes (APX503 is a post-pass over
    the phases)."""

    def __init__(self, axis_sizes: Dict[str, int], tag: str):
        self.axis_sizes = dict(axis_sizes)
        self.tag = tag
        self.findings: List[Finding] = []
        self.paths: List[List[CollectiveOp]] = [[]]
        self.truncated = False
        # (axis, perm, site) per in-loop ppermute, grouped per loop body
        self.phases: List[Tuple[str, List[Tuple[tuple, str]]]] = []
        self._frame_stack: List[Dict[str, List[Tuple[tuple, str]]]] = []
        self.n_collectives = 0

    # -- path bookkeeping ------------------------------------------------
    def _emit(self, op: CollectiveOp) -> None:
        self.n_collectives += 1
        for p in self.paths:
            p.append(op)
        if op.prim == "ppermute" and op.perm is not None \
                and op.loop_depth > 0:
            axis = op.axes[0] if op.axes else "?"
            if self._frame_stack:
                self._frame_stack[-1].setdefault(axis, []).append(
                    (op.perm, op.site))
            n = self.axis_sizes.get(axis)
            if n and n > 0 and not _is_total_bijection(op.perm, n):
                m = _perm_map(op.perm)
                silent_rx = sorted(set(range(n)) - set(m.values()))
                silent_tx = sorted(set(range(n)) - set(m))
                self.findings.append(Finding(
                    "APX502", self.tag, 0,
                    f"ppermute {list(op.perm)} at {op.site} sits inside "
                    f"a loop body but is not a total bijection of axis "
                    f"{axis!r} (size {n}): "
                    + (f"ranks {silent_rx} never receive (zeros every "
                       f"iteration)" if silent_rx else "")
                    + (" and " if silent_rx and silent_tx else "")
                    + (f"ranks {silent_tx} never send (their value is "
                       f"dropped)" if silent_tx else "")
                    + " — mismatched send/recv pairing across the "
                      "schedule"))

    def _fork(self, branch_walks: List["_Walker"]) -> None:
        """Cross-product this walker's paths with each branch's paths."""
        new_paths: List[List[CollectiveOp]] = []
        for base in self.paths:
            for bw in branch_walks:
                for suffix in bw.paths:
                    new_paths.append(base + suffix)
                    if len(new_paths) >= MAX_PATHS:
                        break
                if len(new_paths) >= MAX_PATHS:
                    break
            if len(new_paths) >= MAX_PATHS:
                self.truncated = True
                break
        self.paths = new_paths or [[]]

    def _branch_walker(self) -> "_Walker":
        w = _Walker(self.axis_sizes, self.tag)
        w._frame_stack = self._frame_stack      # shared phase frames
        return w

    # -- the walk --------------------------------------------------------
    def walk(self, jaxpr, in_taints: Optional[List[_Taint]],
             loop_depth: int, site_prefix: str) -> List[_Taint]:
        taint: Dict[Any, _Taint] = {}
        if in_taints is not None:
            for v, t in zip(jaxpr.invars, in_taints):
                if t:
                    taint[v] = t

        def t_of(v) -> _Taint:
            if _is_literal(v):
                return _NO_TAINT
            return taint.get(v, _NO_TAINT)

        for i, eqn in enumerate(jaxpr.eqns):
            prim = eqn.primitive.name
            site = f"{site_prefix}:eqn {i} ({prim})"
            in_taint = _NO_TAINT
            for v in eqn.invars:
                in_taint = in_taint | t_of(v)

            if prim == "axis_index":
                ax = eqn.params.get("axis_name")
                axes = tuple(ax) if isinstance(ax, (tuple, list)) \
                    else (str(ax),)
                out_t: List[_Taint] = [frozenset(str(a) for a in axes)
                                       | in_taint]
            elif prim == "cond":
                out_t = self._walk_cond(eqn, t_of, in_taint, loop_depth,
                                        site)
            else:
                if prim in _COLLECTIVES or prim.startswith(
                        ("psum", "ppermute", "all_gather", "all_to_all",
                         "reduce_scatter")):
                    op0 = eqn.invars[0]
                    perm = eqn.params.get("perm")
                    self._emit(CollectiveOp(
                        prim=prim, axes=_axes_of(eqn),
                        shape=tuple(getattr(op0.aval, "shape", ())),
                        dtype=str(getattr(op0.aval, "dtype", "?")),
                        perm=tuple((int(s), int(d)) for s, d in perm)
                        if perm else None,
                        loop_depth=loop_depth, site=site))
                is_loop = prim in ("scan", "while")
                for key, sub in _sub_jaxprs_of(eqn):
                    sub_taints = _align_right(
                        [t_of(v) for v in eqn.invars], len(sub.invars))
                    sub_taints = [t or _NO_TAINT for t in sub_taints]
                    if is_loop:
                        self._frame_stack.append({})
                    sub_out = self.walk(
                        sub, sub_taints, loop_depth + (1 if is_loop else 0),
                        f"{site}/{key}")
                    if is_loop:
                        frame = self._frame_stack.pop()
                        for axis, perms in frame.items():
                            self.phases.append((axis, perms))
                    if len(sub_out) == len(eqn.outvars):
                        for v, t in zip(eqn.outvars, sub_out):
                            if t:
                                taint[v] = taint.get(v, _NO_TAINT) | t
                out_t = [in_taint] * len(eqn.outvars)

            for v, t in zip(eqn.outvars, out_t):
                if t:
                    taint[v] = taint.get(v, _NO_TAINT) | t

        return [t_of(v) for v in jaxpr.outvars]

    def _walk_cond(self, eqn, t_of, in_taint: _Taint, loop_depth: int,
                   site: str) -> List[_Taint]:
        pred_taint = t_of(eqn.invars[0])
        branches = eqn.params.get("branches") or ()
        walks: List[_Walker] = []
        out_t = [in_taint | pred_taint] * len(eqn.outvars)
        for bi, br in enumerate(branches):
            sub = br.jaxpr if hasattr(br, "jaxpr") else br
            bw = self._branch_walker()
            sub_taints = _align_right(
                [t_of(v) for v in eqn.invars[1:]], len(sub.invars))
            br_out = bw.walk(sub, [t or _NO_TAINT for t in sub_taints],
                             loop_depth, f"{site}/branch{bi}")
            if len(br_out) == len(out_t):
                out_t = [a | b for a, b in zip(out_t, br_out)]
            walks.append(bw)
            self.findings.extend(bw.findings)
            self.phases.extend(bw.phases)   # loops nested in the branch
            self.truncated = self.truncated or bw.truncated
            self.n_collectives += bw.n_collectives

        # APX501: different collective sequences across branches, over
        # an axis the predicate varies along
        sigs = [tuple(op.sig for op in (bw.paths[0] if bw.paths else ()))
                for bw in walks]
        if pred_taint and len(set(sigs)) > 1:
            branch_axes = {ax for bw in walks for p in bw.paths
                           for op in p for ax in op.axes}
            hot = sorted(pred_taint & branch_axes)
            if hot:
                desc = "; ".join(
                    f"branch{bi}: " + (" -> ".join(
                        f"{p}[{','.join(a)}]" for p, a in sig) or "(none)")
                    for bi, sig in enumerate(sigs))
                self.findings.append(Finding(
                    "APX501", self.tag, 0,
                    f"cond at {site} has a predicate that can depend on "
                    f"axis_index over {hot} and branches with different "
                    f"collective sequences over {'that axis' if len(hot) == 1 else 'those axes'} "
                    f"({desc}) — replicas diverge and the mismatched "
                    f"collectives hang on hardware"))

        self._fork(walks)
        return out_t


def _check_phases(walker: _Walker) -> None:
    """APX503: all in-loop ppermute perms of one axis must share a base
    rotation (each equal to it or its inverse). Partial permutations are
    excluded from the comparison — totality against the REAL axis size
    is APX502's check, and comparing a partial map against the base
    rotation would only duplicate that finding."""
    by_axis: Dict[str, List[Tuple[tuple, str]]] = {}
    for axis, perms in walker.phases:
        by_axis.setdefault(axis, []).extend(perms)
    for axis, perms in by_axis.items():
        n = walker.axis_sizes.get(axis)
        if not n:
            continue   # unbound axis: APX203's finding, nothing to pair
        total = [(p, s) for p, s in perms if _is_total_bijection(p, n)]
        if len(total) < 2:
            continue
        base, base_site = total[0]
        for p, s in total[1:]:
            if not _same_or_inverse(base, p):
                walker.findings.append(Finding(
                    "APX503", walker.tag, 0,
                    f"pipeline phases over axis {axis!r} rotate with "
                    f"incompatible permutations: {list(base)} at "
                    f"{base_site} vs {list(p)} at {s} (neither equal "
                    f"nor inverse) — the forward/backward permutes do "
                    f"not compose back to the identity across the "
                    f"schedule, so activations/grads land on the wrong "
                    f"stage"))


def collective_paths(closed_jaxpr, axis_sizes: Dict[str, int],
                     tag: str = "<jaxpr>"
                     ) -> Tuple[List[List[CollectiveOp]], _Walker]:
    """Ordered collective sequence per control-flow path (capped at
    ``MAX_PATHS``), plus the walker carrying findings/phases/stats."""
    w = _Walker(axis_sizes, tag)
    w.walk(closed_jaxpr.jaxpr, None, 0, tag)
    _check_phases(w)
    return w.paths, w


def audit_spmd(closed_jaxpr, axis_sizes: Dict[str, int], tag: str
               ) -> Tuple[List[Finding], dict]:
    """The CLI layer over one traced entry point: APX501/502/503
    findings plus the per-entry verdict summary."""
    paths, w = collective_paths(closed_jaxpr, axis_sizes, tag)
    summary = {
        "entry": tag,
        "paths": len(paths),
        "collectives": w.n_collectives,
        "loop_phases": len(w.phases),
        "truncated": w.truncated,
        "sequence": [op.to_json() for op in paths[0][:32]] if paths else [],
        "ok": not any(f.severity == "error" for f in w.findings),
    }
    return w.findings, summary
