"""Static peak-HBM / liveness estimator (rules APX401-APX402).

Answers the first of the two questions that actually kill multi-chip
runs — *will this program fit in HBM* — without running (or even
compiling) anything: a liveness walk over ``jax.make_jaxpr`` output
computes per-equation live-set bytes and reports the projected
per-device peak with the top-K resident tensors and their def/use
sites.

The model (deliberately coarse — like ``tuning/cost_model.py``, it only
has to *order* configurations correctly, not predict megabytes):

* every value is ``prod(shape) * dtype.itemsize`` bytes, divided by its
  **shard factor** — the number of ways the mesh splits it;
* a jaxpr's inputs are resident from entry; non-donated inputs stay
  resident to the end (the caller holds the buffer), donated inputs die
  at their last real reference (donation frees them — that credit is
  exactly what APX402 revokes when the donated value escapes);
* an equation's outputs materialize while it runs and die after their
  last use; operands are still resident during the equation;
* equations with sub-jaxprs (``pjit`` / ``scan`` / ``cond`` / ``while``
  / ``shard_map`` / remat) contribute their inner peak *beyond* the
  operands already counted outside — computed recursively, so a wave of
  rematerialized pipeline ticks costs what the wave holds, not what the
  whole schedule holds.

Sharding awareness has two sources that compose: the entry point's
PartitionSpecs divide the top-level argument avals (``spec_factor``),
and descending into a ``shard_map`` equation switches to the body's
**per-shard avals** (factor 1 by construction). Factors propagate
forward through equations — ``shard_map`` outputs take their
``out_names`` factor, sub-jaxpr outputs return their inner factors, and
a simple equation whose output matches an operand's shape inherits that
operand's factor (the SGD update ``w - lr*g`` of sharded params stays
sharded). Everything is therefore *per-device* bytes.

Public API: :func:`estimate_peak_hbm` — re-exported by
``tuning/cost_model.py`` so the whole-run auto-parallelism planner
(ROADMAP open item 4, AMP-style search) can score candidate
(dp x tp x pp x ZeRO) configurations without running them.
:func:`audit_memory` is the CLI layer: APX401 when the peak exceeds the
per-device budget (``APEX_TPU_ANALYSIS_HBM_GB`` / ``--memory-budget-gb``;
info-severity inventory otherwise), APX402 when a declared donation
never frees its buffer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from apex_tpu.analysis._jaxpr import (align_right, is_literal,
                                      sub_jaxprs)
from apex_tpu.analysis.findings import Finding

__all__ = ["estimate_peak_hbm", "audit_memory", "MemoryEstimate",
           "spec_factor", "leaf_factors", "GiB"]

GiB = float(2 ** 30)


# ---------------------------------------------------------------------------
# shard factors: PartitionSpecs -> ways the mesh splits a value
# ---------------------------------------------------------------------------

def spec_factor(spec, axis_sizes: Dict[str, int]) -> int:
    """Number of shards a PartitionSpec splits an array into on the
    given mesh: the product of the extents of every mesh axis it names
    (``None`` entries replicate). ``spec=None`` -> 1."""
    if spec is None:
        return 1
    factor = 1
    for entry in spec:
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            factor *= int(axis_sizes.get(ax, 1))
    return factor


def leaf_factors(args, specs, axis_sizes: Dict[str, int]) -> List[int]:
    """Per-flat-leaf shard factors for ``args``, in ``jax.tree.leaves``
    order (= ``make_jaxpr`` invar order). ``specs`` may be a PREFIX tree
    of args' structure — a single PartitionSpec covering a whole subtree,
    the shard_map in_specs convention."""
    import jax
    from jax.sharding import PartitionSpec

    out: List[int] = []

    def is_spec(s):
        return s is None or isinstance(s, PartitionSpec)

    def rec(a, s):
        if is_spec(s):
            out.extend([spec_factor(s, axis_sizes)]
                       * len(jax.tree.leaves(a)))
            return
        if isinstance(a, dict):
            for k in sorted(a):
                rec(a[k], s[k])
        elif isinstance(a, (list, tuple)):
            if len(a) != len(s):
                raise ValueError(
                    f"specs tree does not match args: {len(s)} specs "
                    f"for {len(a)} children")
            for ai, si in zip(a, s):
                rec(ai, si)
        else:
            raise ValueError(
                f"specs tree does not match args at a {type(a).__name__} "
                f"leaf (got {type(s).__name__}, expected a PartitionSpec)")

    rec(args, specs)
    return out


# ---------------------------------------------------------------------------
# the liveness walk
# ---------------------------------------------------------------------------

def _aval_bytes(aval, factor: int = 1) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return -(-(n * dtype.itemsize) // max(1, int(factor)))


_is_literal = is_literal
_sub_jaxprs_of = sub_jaxprs
_align_right = align_right


def _is_dropvar(v) -> bool:
    return type(v).__name__ == "DropVar"


def _mesh_axis_sizes(mesh) -> Dict[str, int]:
    try:
        return {str(k): int(v) for k, v in dict(mesh.shape).items()}
    except Exception:
        return {}


def _shard_map_out_factors(eqn) -> Optional[List[int]]:
    """Per-output shard factors of a shard_map equation, read from its
    ``out_names`` ({dim: (axis, ...)} per output) and mesh."""
    mesh = eqn.params.get("mesh")
    out_names = eqn.params.get("out_names")
    if mesh is None or out_names is None:
        return None
    sizes = _mesh_axis_sizes(mesh)
    factors = []
    for names in out_names:
        f = 1
        try:
            for axes in dict(names).values():
                for ax in (axes if isinstance(axes, tuple) else (axes,)):
                    f *= sizes.get(str(ax), 1)
        except Exception:
            f = 1
        factors.append(f)
    return factors


@dataclass
class _Resident:
    bytes: int
    shape: tuple
    dtype: str
    defined: str
    last_use: str

    def to_json(self) -> dict:
        return {"bytes": int(self.bytes), "shape": list(self.shape),
                "dtype": self.dtype, "defined": self.defined,
                "last_use": self.last_use}


@dataclass
class _Hazard:
    site: str          # "path:eqn_i -> callee"
    how: str           # "consumed by a later equation" / "escapes ..."
    bytes: int


@dataclass
class MemoryEstimate:
    """What :func:`estimate_peak_hbm` returns: projected per-device peak
    bytes, where it happens, the top-K resident tensors there (def/use
    sites as equation indices), and any donation hazards found on the
    way (APX402 material)."""

    peak_bytes: int
    peak_site: str
    residents: List[_Resident]
    n_eqns: int
    hazards: List[_Hazard] = field(default_factory=list)

    @property
    def peak_gib(self) -> float:
        return self.peak_bytes / GiB

    def to_json(self) -> dict:
        return {
            "peak_bytes": int(self.peak_bytes),
            "peak_gib": round(self.peak_gib, 4),
            "peak_site": self.peak_site,
            "n_eqns": self.n_eqns,
            "residents": [r.to_json() for r in self.residents],
            "donation_hazards": len(self.hazards),
        }


class _Analyzer:
    def __init__(self, top_k: int = 8):
        self.top_k = top_k
        self.hazards: List[_Hazard] = []
        self.n_eqns = 0

    def analyze(self, jaxpr, in_factors: Optional[List[int]],
                donated: Optional[Sequence[bool]], path: str
                ) -> Tuple[int, List[int], str, List[_Resident]]:
        """Liveness walk of one (sub-)jaxpr. Returns (peak_bytes,
        out_factors, peak_site, residents_at_peak). ``in_factors`` /
        ``donated`` align with ``jaxpr.invars``."""
        eqns = jaxpr.eqns
        self.n_eqns += len(eqns)
        invars = [v for v in jaxpr.invars]
        if in_factors is None:
            in_factors = [1] * len(invars)
        if donated is None:
            donated = [False] * len(invars)

        factors: Dict[Any, int] = {}
        meta: Dict[Any, str] = {}
        for j, v in enumerate(invars):
            factors[v] = in_factors[j] or 1
            meta[v] = f"arg[{j}]"
        for v in jaxpr.constvars:
            factors[v] = 1
            meta[v] = "const"

        # last real reference of each var (equation index; len(eqns) =
        # "escapes as an output")
        end = len(eqns)
        last_ref: Dict[Any, int] = {}
        for i, eqn in enumerate(eqns):
            for v in eqn.invars:
                if not _is_literal(v):
                    last_ref[v] = i
        outset = {v for v in jaxpr.outvars if not _is_literal(v)}
        for v in outset:
            last_ref[v] = end

        # vars donated into an inner pjit die at their last REAL
        # reference (the donation frees them); everything else the
        # caller handed in stays resident to the end
        donated_inner: Dict[Any, int] = {}
        for i, eqn in enumerate(eqns):
            dflags = eqn.params.get("donated_invars")
            if not dflags or not any(dflags):
                continue
            for dflag, v in zip(dflags,
                                _align_right(eqn.invars, len(dflags))):
                if dflag and v is not None and not _is_literal(v):
                    donated_inner.setdefault(v, i)

        death: Dict[Any, int] = {}
        for j, v in enumerate(invars):
            if v in outset:
                death[v] = end
            elif donated[j] or v in donated_inner:
                death[v] = last_ref.get(v, -1)
            else:
                death[v] = end
        for v in jaxpr.constvars:
            death[v] = end

        # APX402: donation declared but the value never dies
        for v, i in donated_inner.items():
            ref = last_ref.get(v, i)
            if ref > i:
                eqn = eqns[i]
                how = ("escapes as an output" if v in outset
                       and ref == end else
                       f"consumed again by eqn {ref} "
                       f"({eqns[min(ref, end - 1)].primitive.name})")
                self.hazards.append(_Hazard(
                    site=(f"{path}:eqn {i} "
                          f"(pjit {eqn.params.get('name', '?')!r})"),
                    how=how,
                    bytes=_aval_bytes(v.aval, factors.get(v, 1))))

        live: Dict[Any, int] = {}
        for v in invars + list(jaxpr.constvars):
            if death.get(v, -1) >= 0:
                live[v] = _aval_bytes(v.aval, factors.get(v, 1))

        def _use_str(v) -> str:
            r = last_ref.get(v)
            if r is None:
                return "unused"
            if r >= end:
                return "output"
            return f"eqn {r} ({eqns[r].primitive.name})"

        def _snapshot(extra_entries) -> List[_Resident]:
            snap = [
                _Resident(b, tuple(getattr(v.aval, "shape", ())),
                          str(getattr(v.aval, "dtype", "?")),
                          meta.get(v, "?"), _use_str(v))
                for v, b in live.items()
            ] + list(extra_entries)
            snap.sort(key=lambda r: -r.bytes)
            return snap[:self.top_k]

        peak = sum(live.values())
        peak_site = f"{path}:entry"
        residents = _snapshot([])

        for i, eqn in enumerate(eqns):
            prim = eqn.primitive.name
            site = f"{path}:eqn {i} ({prim})"
            out_factors = self._eqn_out_factors(eqn, factors)

            # sub-jaxprs first: their returned output factors must land
            # in out_factors BEFORE any output bytes are computed, or
            # the live set would hold e.g. a sharded shard_map result
            # at its unsharded size for the rest of the walk
            subs = []
            for key, sub in _sub_jaxprs_of(eqn):
                sub_in = _align_right(
                    [factors.get(v, 1) if not _is_literal(v) else 1
                     for v in eqn.invars], len(sub.invars))
                if prim == "shard_map":
                    # body avals are already per-shard
                    sub_in = [1] * len(sub.invars)
                sub_don = None
                dflags = eqn.params.get("donated_invars")
                if dflags:
                    sub_don = _align_right(list(dflags), len(sub.invars))
                    sub_don = [bool(d) for d in sub_don]
                sub_peak, sub_out, _, sub_res = self.analyze(
                    sub, [f or 1 for f in sub_in], sub_don,
                    f"{site}/{key}")
                sub_base = sum(
                    _aval_bytes(v.aval, f or 1)
                    for v, f in zip(sub.invars, sub_in))
                subs.append((sub_peak, sub_base, sub_res))
                if len(sub_out) == len(eqn.outvars) and prim != "shard_map":
                    out_factors = [max(a, b) for a, b in
                                   zip(out_factors, sub_out)]

            out_entries = []
            out_bytes = 0
            for v, f in zip(eqn.outvars, out_factors):
                b = _aval_bytes(v.aval, f)
                out_bytes += b
                out_entries.append(_Resident(
                    b, tuple(getattr(v.aval, "shape", ())),
                    str(getattr(v.aval, "dtype", "?")), site,
                    "dropped" if _is_dropvar(v) else _use_str(v)))

            # transient of a sub-jaxpr equation beyond what the outer
            # scope already holds (operands + outputs)
            inner_extra = 0
            inner_residents: List[_Resident] = []
            for sub_peak, sub_base, sub_res in subs:
                extra = max(0, sub_peak - sub_base - out_bytes)
                if extra > inner_extra:
                    inner_extra = extra
                    inner_residents = sub_res

            during = sum(live.values()) + out_bytes + inner_extra
            if during > peak:
                peak = during
                peak_site = site
                residents = _snapshot(out_entries + inner_residents)

            # retire values dead after this equation, then land outputs
            for v in list(live):
                if death.get(v, end) <= i:
                    del live[v]
            for v, f, ent in zip(eqn.outvars, out_factors, out_entries):
                if _is_dropvar(v):
                    continue
                factors[v] = f
                meta[v] = site
                death[v] = end if v in outset else last_ref.get(v, i)
                if death[v] > i:
                    live[v] = ent.bytes

        return peak, [factors.get(v, 1) if not _is_literal(v) else 1
                      for v in jaxpr.outvars], peak_site, residents

    def _eqn_out_factors(self, eqn, factors: Dict[Any, int]) -> List[int]:
        sm = _shard_map_out_factors(eqn) \
            if eqn.primitive.name == "shard_map" else None
        if sm is not None and len(sm) == len(eqn.outvars):
            return sm
        out = []
        for v in eqn.outvars:
            shape = getattr(v.aval, "shape", None)
            f = 1
            for iv in eqn.invars:
                if _is_literal(iv):
                    continue
                if getattr(iv.aval, "shape", ()) == shape:
                    f = max(f, factors.get(iv, 1))
            out.append(f)
        return out


def _estimate(closed_jaxpr, factors: Optional[List[int]] = None,
              donated: Optional[Sequence[bool]] = None,
              top_k: int = 8, label: str = "jaxpr") -> MemoryEstimate:
    an = _Analyzer(top_k=top_k)
    peak, _, site, residents = an.analyze(
        closed_jaxpr.jaxpr, factors, donated, label)
    return MemoryEstimate(peak_bytes=peak, peak_site=site,
                          residents=residents, n_eqns=an.n_eqns,
                          hazards=an.hazards)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def estimate_peak_hbm(fn, args: tuple, mesh=None, specs=None, *,
                      donate_argnums: Sequence[int] = (),
                      top_k: int = 8) -> MemoryEstimate:
    """Project the per-device peak-HBM of ``fn(*args)`` statically.

    ``mesh`` is a ``jax.sharding.Mesh`` or a ``{axis: size}`` dict;
    ``specs`` a tree of PartitionSpecs for ``args`` (prefix trees in the
    shard_map in_specs convention are fine) — together they divide each
    argument's bytes by its shard count, which is what makes the
    estimate a *per-device* number the planner can compare across
    (dp x tp x pp x ZeRO) candidates. ``donate_argnums`` marks arguments
    whose buffers the caller releases (they die at their last use
    instead of surviving to program end). Trace-only: no compile, no
    devices beyond what ``make_jaxpr`` itself needs."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    axis_sizes: Dict[str, int] = {}
    if mesh is not None:
        axis_sizes = mesh if isinstance(mesh, dict) \
            else _mesh_axis_sizes(mesh)
    factors = None
    if specs is not None:
        factors = leaf_factors(args, specs, axis_sizes)
        if len(factors) != len(closed.jaxpr.invars):
            raise ValueError(
                f"specs flatten to {len(factors)} leaves but the traced "
                f"program has {len(closed.jaxpr.invars)} inputs")
    donated = None
    if donate_argnums:
        donate_argnums = set(
            int(d) for d in (donate_argnums if isinstance(
                donate_argnums, (tuple, list, set)) else (donate_argnums,)))
        # expand per-argument donation over each argument's flat leaves
        donated = []
        for j, a in enumerate(args):
            n = len(jax.tree.leaves(a))
            donated.extend([j in donate_argnums] * n)
        if len(donated) != len(closed.jaxpr.invars):
            donated = None  # static/capture mismatch: fall back
    return _estimate(closed, factors, donated, top_k=top_k)


def audit_memory(closed_jaxpr, tag: str, *,
                 factors: Optional[List[int]] = None,
                 budget_bytes: Optional[float] = None,
                 top_k: int = 5) -> Tuple[List[Finding], dict]:
    """The CLI layer over one traced entry point: APX402 per donation
    hazard, APX401 error when over ``budget_bytes`` (info inventory
    otherwise). Returns (findings, summary-for-the-report)."""
    est = _estimate(closed_jaxpr, factors, top_k=top_k, label=tag)
    findings: List[Finding] = []
    for hz in est.hazards:
        findings.append(Finding(
            "APX402", tag, 0,
            f"donated buffer ({hz.bytes} bytes) never dies — donation "
            f"at {hz.site} but the value {hz.how}; the estimator must "
            f"keep both it and the callee's outputs resident"))
    top = ", ".join(
        f"{r.bytes / GiB:.4f} GiB {r.dtype}{list(r.shape)} "
        f"(def {r.defined}, use {r.last_use})"
        for r in est.residents[:3])
    if budget_bytes is not None and est.peak_bytes > budget_bytes:
        findings.append(Finding(
            "APX401", tag, 0,
            f"projected per-device peak HBM {est.peak_gib:.4f} GiB "
            f"exceeds the {budget_bytes / GiB:.2f} GiB budget at "
            f"{est.peak_site}; top residents: {top}"))
    else:
        findings.append(Finding(
            "APX401", tag, 0,
            f"projected per-device peak HBM {est.peak_gib:.4f} GiB at "
            f"{est.peak_site}; top residents: {top}",
            severity="info"))
    summary = est.to_json()
    summary["entry"] = tag
    summary["over_budget"] = bool(
        budget_bytes is not None and est.peak_bytes > budget_bytes)
    return findings, summary
