"""Shared jaxpr-walk helpers for the auditor/memory/spmd layers.

Three walkers (auditors.py, memory.py, spmd.py) traverse the same
equation tree with the same binding conventions; the conventions encode
subtle jax facts, so they live in exactly one place:

* :func:`sub_jaxprs` — every sub-jaxpr riding an equation's params
  (ClosedJaxpr unwrapped, branch tuples flattened), keyed for site
  strings.
* :func:`align_right` — how outer operands map onto a sub-jaxpr's
  invars: positionally from the right, which is exact for ``pjit``
  (1:1), ``scan`` (consts+carry+xs), ``cond`` branches (the predicate
  is dropped from the left) and ``while`` body jaxprs (cond_nconsts
  dropped from the left); ``while`` *cond* jaxprs lose their
  cond_consts alignment — the documented approximation.
* :func:`axes_of` — the axis names of a collective equation, whichever
  param spelling the primitive uses.
* :func:`is_literal` — Literal operands (no buffer, no liveness).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

__all__ = ["axes_of", "is_literal", "sub_jaxprs", "align_right"]


def axes_of(eqn) -> Tuple[str, ...]:
    """Axis names a collective equation operates over (``axes`` /
    ``axis_name`` / ``axis``, scalar or tuple)."""
    for key in ("axes", "axis_name", "axis"):
        v = eqn.params.get(key)
        if v is None:
            continue
        if isinstance(v, (tuple, list)):
            return tuple(a for a in v if isinstance(a, str))
        if isinstance(v, str):
            return (v,)
    return ()


def is_literal(v) -> bool:
    import jax.core as _core  # Literal lives here across 0.4.x

    return isinstance(v, getattr(_core, "Literal", ()))


def sub_jaxprs(eqn):
    """(key, raw Jaxpr) for every sub-jaxpr riding the equation params —
    ClosedJaxpr unwrapped, tuple-valued params (cond branches) indexed."""
    for key, val in eqn.params.items():
        vals = val if isinstance(val, (list, tuple)) else (val,)
        for i, v in enumerate(vals):
            if hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                yield f"{key}[{i}]", v.jaxpr
            elif hasattr(v, "eqns"):
                yield f"{key}[{i}]", v


def align_right(outer: Sequence, inner_n: int) -> List:
    """Map per-operand values onto ``inner_n`` sub-jaxpr invars the way
    jax binds them (see module doc): right-aligned, padded with None."""
    outer = list(outer)
    if len(outer) >= inner_n:
        return outer[len(outer) - inner_n:]
    return [None] * (inner_n - len(outer)) + outer
