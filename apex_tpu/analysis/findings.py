"""Finding model + rule catalog + pragma handling for apex_tpu.analysis.

One vocabulary for all three layers (AST linter, jaxpr auditors, Pallas
kernel sanitizer): a :class:`Finding` is (rule, file, line, message,
severity), a :class:`Rule` is the catalog entry behind it, and pragmas
(``# apexlint: disable=APX101`` / ``disable=APX101,APX104`` /
``disable=all``, inline on the offending line) suppress findings without
deleting the evidence that a human looked.

Severities:

* ``error`` — a violated invariant; fails the CLI (exit-code bit of the
  rule's layer).
* ``warn``  — suspicious but sometimes legitimate; fails only under
  ``APEX_TPU_ANALYSIS_STRICT=1`` (or ``--strict``).
* ``info``  — inventory/telemetry (e.g. tunable-space candidates the
  cost model itself would reject); never fails.

Rule IDs are stable API: APX1xx = trace-hygiene lint, APX2xx = jaxpr
auditors, APX3xx = kernel sanitizer, APX4xx = peak-HBM/liveness
estimator, APX5xx = SPMD collective-consistency checker. The catalog is
the single source for ``--list-rules`` and docs/analysis.md.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = ["Finding", "Rule", "RULES", "Pragmas", "layer_bit"]

ERROR = "error"
WARN = "warn"
INFO = "info"


@dataclass(frozen=True)
class Rule:
    id: str
    name: str
    severity: str
    doc: str


RULES: Dict[str, Rule] = {
    r.id: r
    for r in (
        # ---- APX1xx: AST trace-hygiene lint --------------------------
        Rule("APX101", "env-read-at-import", ERROR,
             "os.environ / getenv read at module scope: the value is "
             "frozen at import time, so a knob flipped between imports "
             "and traces is silently ignored inside jitted/kernel code "
             "(the PR-3 utils/profiling.py bug class). Re-read at call "
             "time, or pragma a genuinely import-time site with a "
             "comment saying why."),
        Rule("APX102", "raw-env-parse", ERROR,
             "int()/float() over an env read, or comparison of an env "
             "read against '0'/'1', outside utils/envvars.py: use "
             "env_int / env_flag so a malformed APEX_TPU_* value raises "
             "an error naming the variable instead of a bare ValueError "
             "deep in kernel code (or a typo silently meaning 'off')."),
        Rule("APX103", "host-sync-in-jit", ERROR,
             ".item(), jax.device_get, np.asarray/np.array, or float() "
             "of a traced argument inside a jitted function or Pallas "
             "kernel body: forces a device sync (or a trace-time "
             "ConcretizationError) in a hot path. Move the readback to "
             "the host loop (observability.bridge drains asynchronously) "
             "or pragma a deliberate sync point."),
        Rule("APX104", "missing-functools-wraps", ERROR,
             "a decorator's inner wrapper (*args/**kwargs closure "
             "calling the wrapped callable) lacks functools.wraps: the "
             "wrapped function loses its name/docstring/signature (the "
             "PR-5 profiling.annotate bug class)."),
        Rule("APX106", "late-binding-index-map", ERROR,
             "a pl.BlockSpec / index-map lambda defined inside a loop "
             "captures the loop variable by reference: python closures "
             "late-bind, so every index map built by the loop sees the "
             "LAST iteration's value when Pallas finally calls it — "
             "bind it as a default (lambda i, k=k: ...) or build the "
             "map in a factory function."),
        Rule("APX105", "traced-truthiness", ERROR,
             "Python bool() of a jnp expression (if/while/assert/and/or "
             "directly on a jnp.* call or comparison) inside a jitted "
             "function or kernel body: raises TracerBoolConversionError "
             "at trace time, or silently freezes a data-dependent branch "
             "if the value is concrete during tracing. Use lax.cond / "
             "jnp.where / pl.when."),
        Rule("APX107", "wallclock-duration", ERROR,
             "time.time() used for duration math (a subtraction with a "
             "time.time() result — direct or via an assigned alias — on "
             "either side): the wall clock steps under NTP slew, so a "
             "span or latency measured with it can come out negative or "
             "wildly wrong — exactly the samples SLO verdicts, goodput "
             "EMAs and tracer spans are built on. Use "
             "time.perf_counter() (monotonic) for every duration; "
             "time.time() stays legitimate for timestamps that never "
             "enter arithmetic (log records, file names)."),
        # ---- APX2xx: jaxpr auditors ----------------------------------
        Rule("APX201", "use-after-donation", ERROR,
             "a value passed into a donated argument slot of a jitted "
             "call is referenced again afterwards (later equation or "
             "returned output): the buffer may already be aliased to the "
             "callee's outputs — the observability/bridge.py "
             "double-buffer hazard class."),
        Rule("APX202", "signature-drift-retrace", ERROR,
             "two argument sets that the caller treats as 'the same "
             "step' trace to different input avals (dtype / weak_type / "
             "shape drift): every such call retraces and recompiles, "
             "the compile-time leak goodput.py detects at runtime — "
             "this is the static pin."),
        Rule("APX203", "collective-inconsistency", ERROR,
             "a collective (psum / psum_scatter / ppermute / all_gather "
             "/ all_to_all) names an axis missing from the declared "
             "mesh, or a ppermute permutation is not replica-consistent "
             "(duplicate sources/destinations or out-of-range ranks) — "
             "the quantized_collectives/overlap invariant; on hardware "
             "this deadlocks or corrupts, it does not error."),
        # ---- APX3xx: Pallas kernel sanitizer -------------------------
        Rule("APX301", "blockspec-divisibility", ERROR,
             "grid x block does not tile the (padded) array exactly: "
             "uncovered trailing blocks are emitted as garbage, "
             "overhanging blocks read out of bounds. Every registered "
             "tunable candidate must tile exactly or be rejected by the "
             "registry's validity check."),
        Rule("APX302", "vmem-budget", ERROR,
             "the kernel's projected VMEM residency (block tiles + "
             "scratch, double-buffered) exceeds the device's scoped "
             "VMEM budget for a configuration the resolution chain "
             "would actually pick (cost-model default or env-reachable "
             "without rejection)."),
        Rule("APX303", "indexmap-bounds", ERROR,
             "a BlockSpec index map evaluated at a grid corner selects "
             "a block outside the (padded) operand: the DMA reads or "
             "writes out of bounds. Ragged index maps must clamp "
             "(jnp.minimum / jnp.clip) exactly like the shipped "
             "kernels do."),
        Rule("APX304", "revisit-chain-race", ERROR,
             "the revisit-chain accumulator protocol is violated for "
             "some group distribution: an accumulate lands on an "
             "uninitialized scratch (missed init), a tile's chain is "
             "never flushed (garbage out), a tile is revisited after "
             "its flush (write race), or a sentinel work item emits."),
        Rule("APX305", "candidate-rejected", INFO,
             "a tunable-space candidate is rejected by the registry "
             "check or projected over the VMEM budget — inventory of "
             "the space the autotuner must not sweep on this device; "
             "never fails the run."),
        # ---- APX4xx: peak-HBM / liveness estimator -------------------
        Rule("APX401", "hbm-over-budget", ERROR,
             "the entry point's projected per-device peak HBM (jaxpr "
             "liveness walk: donation-aware, sharding-aware via the "
             "entry's PartitionSpecs) exceeds the per-device budget "
             "(APEX_TPU_ANALYSIS_HBM_GB / --memory-budget-gb). With no "
             "budget set (or under it) the same finding is emitted at "
             "info severity — the peak inventory the auto-parallelism "
             "planner scores configs with."),
        Rule("APX402", "donation-never-frees", ERROR,
             "a buffer donated into a jitted call is still referenced "
             "afterwards (a later equation, or it escapes as an "
             "output), so the donation never frees it: the estimator "
             "must keep BOTH the donated operand and the callee's "
             "outputs resident — the memory-side complement of the "
             "APX201 correctness hazard."),
        # ---- APX5xx: SPMD collective-consistency checker -------------
        Rule("APX501", "branch-divergent-collectives", ERROR,
             "a lax.cond whose predicate can depend on axis_index "
             "selects branches with different collective sequences "
             "over an axis the predicate varies along: replicas on "
             "that axis take different branches and issue mismatched "
             "collectives — the classic SPMD hang. Divergence over a "
             "DISJOINT axis (a stage-varying predicate around "
             "model-axis collectives shared by all peers of a stage) "
             "is safe and not flagged."),
        Rule("APX502", "ppermute-pairing", ERROR,
             "a ppermute inside a steady-state loop body (scan/while "
             "pipeline schedule) is not a total bijection of the axis: "
             "some rank never receives (reads zeros every iteration) "
             "or never sends (its value is dropped) — mismatched "
             "send/recv pairing across the cyclic schedule; the "
             "circulating-ring engine requires total rotations."),
        Rule("APX503", "pipeline-phase-inconsistency", ERROR,
             "the loop phases of a pipeline schedule rotate the stage "
             "ring with incompatible permutations: every in-loop "
             "ppermute over an axis must be the schedule's base "
             "rotation or its inverse (forward wave / transposed "
             "backward wave); a phase permuting a different topology "
             "hands activations or grads to the wrong stage."),
    )
}


def layer_bit(rule_id: str) -> int:
    """Exit-code bit of a rule: lint (APX1xx) -> 1, auditors (APX2xx) ->
    2, sanitizer (APX3xx) -> 4, memory estimator (APX4xx) -> 8, spmd
    checker (APX5xx) -> 16. The CLI exit code is the OR of the bits of
    every rule with unsuppressed error-severity findings."""
    if rule_id.startswith("APX1"):
        return 1
    if rule_id.startswith("APX2"):
        return 2
    if rule_id.startswith("APX4"):
        return 8
    if rule_id.startswith("APX5"):
        return 16
    return 4


@dataclass
class Finding:
    rule: str
    path: str                       # file, or pseudo-path like "<audit:...>"
    line: int                       # 1-based; 0 = whole-file/entry finding
    message: str
    severity: str = ""              # defaults to the rule's catalog severity
    suppressed: bool = False

    def __post_init__(self):
        if not self.severity:
            self.severity = RULES[self.rule].severity

    def format(self) -> str:
        sup = " [suppressed]" if self.suppressed else ""
        return (f"{self.path}:{self.line}: {self.rule} "
                f"({RULES[self.rule].name}){sup}: {self.message}")

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "name": RULES[self.rule].name,
            "path": self.path,
            "line": self.line,
            "severity": self.severity,
            "suppressed": self.suppressed,
            "message": self.message,
        }


_PRAGMA_RE = re.compile(r"#\s*apexlint:\s*disable=([A-Za-z0-9_,\s]+)")


class Pragmas:
    """Per-file inline suppression table: line -> set of rule ids (or
    {"all"}). Built once per source file from the raw text, consulted by
    every layer that can attribute a finding to a line."""

    def __init__(self, source: str):
        self.by_line: Dict[int, set] = {}
        for i, text in enumerate(source.splitlines(), start=1):
            m = _PRAGMA_RE.search(text)
            if m:
                rules = {r.strip().upper() for r in m.group(1).split(",")
                         if r.strip()}
                self.by_line[i] = {"ALL" if r == "ALL" else r for r in rules}

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.by_line.get(line)
        if not rules:
            return False
        return "ALL" in rules or rule.upper() in rules

    def apply(self, findings: List[Finding]) -> List[Finding]:
        for f in findings:
            if self.suppressed(f.rule, f.line):
                f.suppressed = True
        return findings


def summarize(findings: List[Finding], *, strict: bool = False) -> dict:
    """Counts + exit code for a finding list. ``strict`` promotes warn ->
    error (the APEX_TPU_ANALYSIS_STRICT semantics)."""
    per_rule: Dict[str, int] = {}
    exit_code = 0
    n_err = n_sup = 0
    for f in findings:
        if f.suppressed:
            n_sup += 1
            continue
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        sev = f.severity
        if strict and sev == WARN:
            sev = ERROR
        if sev == ERROR:
            n_err += 1
            exit_code |= layer_bit(f.rule)
    return {
        "per_rule": dict(sorted(per_rule.items())),
        "errors": n_err,
        "suppressed": n_sup,
        "exit_code": exit_code,
    }
