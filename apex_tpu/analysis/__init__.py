"""apex_tpu.analysis — static correctness tooling for the library itself.

Five layers, one finding vocabulary, one CLI
(``python -m apex_tpu.analysis``):

* :mod:`apex_tpu.analysis.lint` — AST trace-hygiene linter (APX1xx):
  env reads frozen at import, ad-hoc env parsing, host syncs in jitted
  code, decorators without ``functools.wraps``, truthiness on traced
  values, late-binding index-map closures.
* :mod:`apex_tpu.analysis.auditors` — jaxpr auditors (APX2xx): donated
  buffers referenced after donation, argument-signature drift that
  retraces, collective/axis consistency over shard_map programs.
* :mod:`apex_tpu.analysis.sanitizer` — Pallas kernel sanitizer (APX3xx):
  BlockSpec/grid divisibility, VMEM budgets, index-map bounds at grid
  corners, and the grouped-matmul revisit-chain replay — over every
  registered tunable family's full candidate space.
* :mod:`apex_tpu.analysis.memory` — static peak-HBM/liveness estimator
  (APX4xx): donation- and sharding-aware per-equation live-set bytes
  over every entry point, with :func:`estimate_peak_hbm` as the public
  API the auto-parallelism planner scores configurations with
  (re-exported by ``tuning/cost_model.py``).
* :mod:`apex_tpu.analysis.spmd` — SPMD collective-consistency /
  deadlock checker (APX5xx): per-control-flow-path collective
  sequences, axis_index-divergent branches, ppermute pairing and
  pipeline-phase consistency over the stage ring.

The analyzer is **self-hosted**: a tier-1 test runs it over the package
and pins zero unsuppressed findings, so the suite lints every future PR.
Suppress a reviewed site inline with ``# apexlint: disable=APX101`` (and
a comment saying why). See docs/analysis.md for the rule catalog.
"""

from apex_tpu.analysis.findings import Finding, Rule, RULES  # noqa: F401
from apex_tpu.analysis.cli import run  # noqa: F401
from apex_tpu.analysis.memory import estimate_peak_hbm  # noqa: F401

__all__ = ["Finding", "Rule", "RULES", "run", "estimate_peak_hbm"]
