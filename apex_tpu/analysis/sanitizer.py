"""Pallas kernel sanitizer (rules APX301-APX305): statically validate
every registered tunable family over its whole candidate space.

The fuzz suites (test_tuning_fuzz, test_grouped_matmul_fuzz) prove
point-wise numerical correctness of *sampled* configurations; the
sanitizer closes the other half: for EVERY candidate the registry can
emit (the space the autotuner sweeps and the tune cache can pin), verify
the kernel *geometry* — before any of it runs on hardware:

* **APX301 blockspec-divisibility** — grid x block tiles the padded
  operand exactly (no uncovered trailing blocks = garbage out, no
  overhang = OOB DMA).
* **APX302 vmem-budget** — projected VMEM residency (block tiles +
  scratch, double-buffered where the pipeline does) against the device
  budget from ``tuning.cost_model.device_spec`` — but only for
  configurations the resolution chain would actually *select* (the
  cost-model default, or an env override the op layer accepts).
  Candidates that merely exist in the sweep space and bust the budget
  are APX305 inventory, not errors: the autotuner's probe rejects them.
* **APX303 indexmap-bounds** — the BlockSpec index maps, modeled as
  plain-integer functions, evaluated at every grid corner (and for the
  ragged families at adversarial scalar-prefetch contents): the selected
  block must stay inside the padded operand. The shipped kernels clamp
  (``jnp.minimum`` / ``jnp.clip``); a candidate geometry without the
  clamp fails here.
* **APX304 revisit-chain-race** — an instrumented replay of the
  grouped-matmul work schedule (``ops.grouped_matmul._group_metadata``,
  the real function, on the real adversarial group distributions): walk
  the grid in pipeline order and check the accumulator protocol — init
  by first visitor, flush by last, no accumulate-before-init
  (uninitialized read), no revisit-after-flush (write race), sentinels
  never emit.

Geometry is modeled, not introspected: each family's :class:`KernelGeom`
builder mirrors the corresponding kernel's grid/BlockSpec construction
(``_gmm_pallas``, ``_decode_pallas``, ``attention`` block rules). The
tier-1 suite pins the models against the kernels' own constructors where
they are importable, and the deliberately-broken-fixture test proves the
checks reject what they should.
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from apex_tpu.analysis.findings import Finding

__all__ = ["BlockGeom", "KernelGeom", "check_geometry", "FAMILIES",
           "sanitize_family", "sanitize_families", "replay_gmm_schedule",
           "replay_tgmm_schedule"]


def _ceil(a: int, b: int) -> int:
    return -(-a // b)


def _pad128(n: int) -> int:
    return max(128, _ceil(n, 128) * 128)


def _pad_to(n: int, q: int) -> int:
    return max(q, _ceil(max(n, 1), q) * q)


# ---------------------------------------------------------------------------
# geometry model + generic checks
# ---------------------------------------------------------------------------

@dataclass
class BlockGeom:
    """One operand's BlockSpec model: ``block`` element shape,
    ``array`` the padded operand shape, ``index_map`` a plain-int
    function of the grid indices returning BLOCK indices (exactly the
    BlockSpec contract). ``ragged_dims`` marks dims whose index comes
    from scalar-prefetch contents — those are checked against the
    adversarial tables the family supplies, not against corners only."""

    name: str
    block: Tuple[int, ...]
    array: Tuple[int, ...]
    index_map: Callable[..., Tuple[int, ...]]


@dataclass
class KernelGeom:
    """One kernel instance's geometry: grid + operand blocks + scratch."""

    family: str
    grid: Tuple[int, ...]
    blocks: List[BlockGeom]
    vmem_bytes: int = 0
    vmem_budget: int = 0
    # grid-index tuples beyond the corners worth probing (ragged probes)
    extra_probes: List[Tuple[int, ...]] = field(default_factory=list)
    tag: str = "<sanitize>"


def _grid_corners(grid: Tuple[int, ...]) -> Iterable[Tuple[int, ...]]:
    """First/last index along every grid axis — 2^rank corner probes,
    plus a mid point per axis when the axis is long enough."""
    axes = []
    for n in grid:
        pts = {0, n - 1}
        if n > 2:
            pts.add(n // 2)
        axes.append(sorted(pts))
    return itertools.product(*axes)


def check_geometry(geom: KernelGeom) -> List[Finding]:
    """The generic APX301/302/303 checks over one modeled kernel."""
    findings: List[Finding] = []
    tag = geom.tag

    for bg in geom.blocks:
        if len(bg.block) != len(bg.array):
            findings.append(Finding(
                "APX301", tag, 0,
                f"{geom.family}/{bg.name}: block rank {len(bg.block)} != "
                f"operand rank {len(bg.array)}"))
            continue
        for d, (b, a) in enumerate(zip(bg.block, bg.array)):
            if b <= 0:
                findings.append(Finding(
                    "APX301", tag, 0,
                    f"{geom.family}/{bg.name}: block dim {d} is {b}"))
            elif a % b:
                findings.append(Finding(
                    "APX301", tag, 0,
                    f"{geom.family}/{bg.name}: padded operand dim {d} "
                    f"({a}) is not a multiple of the block dim ({b}) — "
                    f"trailing elements are never covered by a whole "
                    f"block"))

    probes = list(_grid_corners(geom.grid)) + list(geom.extra_probes)
    for bg in geom.blocks:
        if len(bg.block) != len(bg.array):
            continue
        bad = None
        for idx in probes:
            try:
                bidx = bg.index_map(*idx)
            except Exception as e:  # noqa: BLE001 — a raising map is a bug
                findings.append(Finding(
                    "APX303", tag, 0,
                    f"{geom.family}/{bg.name}: index map raised at grid "
                    f"index {idx}: {type(e).__name__}: {e}"))
                bad = True
                break
            if len(bidx) != len(bg.block):
                findings.append(Finding(
                    "APX303", tag, 0,
                    f"{geom.family}/{bg.name}: index map at grid index "
                    f"{idx} returned {len(bidx)} block indices for a "
                    f"rank-{len(bg.block)} block — dims beyond the "
                    f"returned arity would go unchecked"))
                bad = True
                break
            for d, (bi, b, a) in enumerate(zip(bidx, bg.block, bg.array)):
                if bi < 0 or (bi + 1) * b > a:
                    bad = (idx, d, bi)
                    break
            if isinstance(bad, tuple):
                idx, d, bi = bad
                findings.append(Finding(
                    "APX303", tag, 0,
                    f"{geom.family}/{bg.name}: index map at grid index "
                    f"{idx} selects block {bi} on dim {d} — elements "
                    f"[{bi * bg.block[d]}, {(bi + 1) * bg.block[d]}) "
                    f"outside the padded operand dim of {bg.array[d]} "
                    f"(missing clamp?)"))
                break
        if bad:
            continue

    if geom.vmem_budget and geom.vmem_bytes > geom.vmem_budget:
        findings.append(Finding(
            "APX302", tag, 0,
            f"{geom.family}: projected VMEM residency "
            f"{geom.vmem_bytes / 2**20:.2f} MiB exceeds the device "
            f"budget {geom.vmem_budget / 2**20:.2f} MiB"))
    return findings


# ---------------------------------------------------------------------------
# revisit-chain replay (APX304) — grouped matmul work schedules
# ---------------------------------------------------------------------------

def _metadata_np(group_sizes: Sequence[int], t_pad: int, tile_t: int):
    """The REAL work-list builder (ops.grouped_matmul._group_metadata),
    evaluated to host ints — the replay instruments the exact schedule
    the kernel's index maps will see."""
    import jax.numpy as jnp
    import numpy as np

    from apex_tpu.ops.grouped_matmul import _group_metadata

    wt, wg, offs = _group_metadata(
        jnp.asarray(list(group_sizes), dtype=jnp.int32), t_pad, tile_t)
    return (np.asarray(wt).tolist(), np.asarray(wg).tolist(),
            np.asarray(offs).tolist())


def replay_gmm_schedule(group_sizes: Sequence[int], t: int, tile_t: int,
                        tag: str = "<sanitize:moe_grouped>"
                        ) -> List[Finding]:
    """Instrumented replay of the gmm kernel's accumulator protocol.

    Walks work items in grid order tracking per-OUT-TILE state
    (uninit -> accumulating -> flushed), mirroring ``_gmm_kernel``:
    init when ``prev_tile != tile``, accumulate every step, flush when
    ``next_tile != tile``. Violations are exactly the write-race /
    uninitialized-read classes the rule documents."""
    e = len(group_sizes)
    t_pad = _pad_to(t, tile_t)
    pt = t_pad // tile_t
    wt, wg, offs = _metadata_np(group_sizes, t_pad, tile_t)
    findings: List[Finding] = []

    def add(msg):
        findings.append(Finding("APX304", tag, 0, msg))

    n = len(wt) - 1                      # last entry is the sentinel
    if wt[n] != pt or wg[n] != e:
        add(f"sentinel work item is (tile={wt[n]}, group={wg[n]}), "
            f"expected ({pt}, {e}) — the kernels' i+1 peek reads junk")
    flushed = set()
    acc_tile = None                      # tile currently accumulating
    acc_init = False
    for i in range(n):
        tile = wt[i]
        prev_tile = wt[i - 1] if i > 0 else -1
        init = prev_tile != tile
        emit = wt[i + 1] != tile
        real = tile < pt
        if init:
            acc_tile, acc_init = tile, True
        else:
            if acc_tile != tile or not acc_init:
                add(f"work item {i} accumulates into tile {tile} without "
                    f"an init (scratch holds tile {acc_tile}) — "
                    f"uninitialized read")
        if real and tile in flushed and init:
            add(f"work item {i} re-opens tile {tile} after its flush — "
                f"write race on the output block")
        if emit:
            if real:
                if tile in flushed:
                    add(f"work item {i} flushes tile {tile} twice")
                flushed.add(tile)
            acc_init = False
        if not real and emit and wg[i] < e:
            add(f"work item {i} emits through the sentinel tile with a "
                f"real group {wg[i]}")
    missing = set(range(pt)) - flushed
    if missing:
        add(f"output tiles {sorted(missing)} are never flushed — they "
            f"would contain garbage (t={t}, tile_t={tile_t}, "
            f"groups={list(group_sizes)})")
    # masks must partition each tile's rows among its visiting groups
    for g in range(e):
        lo, hi = offs[g], offs[g + 1]
        if hi < lo:
            add(f"group {g} has negative extent [{lo}, {hi})")
    if offs[e] > t_pad:
        add(f"group offsets end at {offs[e]} > padded rows {t_pad}")
    return findings


def replay_tgmm_schedule(group_sizes: Sequence[int], t: int, tile_t: int,
                         tag: str = "<sanitize:moe_grouped>"
                         ) -> List[Finding]:
    """Same replay for the tgmm kernel, whose chain is keyed on the
    GROUP: init when ``prev_group != group``, flush when
    ``next_group != group`` and the group is real; empty groups are
    never visited (the wrapper zeroes their output blocks)."""
    e = len(group_sizes)
    t_pad = _pad_to(t, tile_t)
    wt, wg, offs = _metadata_np(group_sizes, t_pad, tile_t)
    findings: List[Finding] = []

    def add(msg):
        findings.append(Finding("APX304", tag, 0, msg))

    n = len(wg) - 1
    emitted = set()
    for i in range(n):
        g = wg[i]
        prev_g = wg[i - 1] if i > 0 else -1
        emit_now = (wg[i + 1] != g) and (g < e)
        if emit_now:
            if g in emitted:
                add(f"work item {i} emits group {g} twice — write race "
                    f"on the output block")
            emitted.add(g)
        if g < e and prev_g != g and g in emitted and not emit_now:
            add(f"work item {i} re-opens group {g} after its emit")
    expected = {g for g in range(e) if group_sizes[g] > 0}
    missing = expected - emitted
    if missing:
        add(f"nonempty groups {sorted(missing)} never emit their output "
            f"block (t={t}, tile_t={tile_t}, groups={list(group_sizes)})")
    extra = emitted - expected
    if extra:
        add(f"empty groups {sorted(extra)} emit — they would overwrite "
            f"the wrapper's zero contract")
    return findings


# the adversarial group distributions the fuzz suite established
def _group_distributions(e: int, t: int, rng: random.Random
                         ) -> List[List[int]]:
    dists = [
        [0] * e,                                   # nothing routed
        [t] + [0] * (e - 1),                       # one takes all
        [0] * (e - 1) + [t],                       # last takes all
        [t // e] * e,                              # uniform
    ]
    # ragged random split summing to <= t (exercises trailing tiles)
    cut = sorted(rng.randrange(t + 1) for _ in range(e - 1))
    rag = [b - a for a, b in zip([0] + cut, cut + [rng.randrange(t, t + 1)])]
    dists.append(rag)
    # non-tile-aligned boundaries; trim from the tail until the gmm
    # contract (sum(group_sizes) <= t) holds for ANY (t, e)
    odd = [max(0, t // e + (7 if i % 2 else -7)) for i in range(e)]
    over, i = sum(odd) - t, e - 1
    while over > 0 and i >= 0:
        take = min(over, odd[i])
        odd[i] -= take
        over -= take
        i -= 1
    dists.append(odd)
    return dists


# ---------------------------------------------------------------------------
# family models
# ---------------------------------------------------------------------------

def _vmem_budget(device: str = "cpu") -> int:
    from apex_tpu.tuning import cost_model

    _, _, vmem = cost_model.device_spec(device)
    return int(vmem)


@dataclass
class Family:
    name: str
    registry_key: str
    shapes: Callable[[], List[dict]]
    # (params, features) -> KernelGeom | None (None = no kernel, e.g.
    # jnp backend or a pure host-side knob) ; may raise for broken input
    build: Callable[[dict, dict], Optional[KernelGeom]]
    # features for which a params dict is the RESOLVED default
    # (cost-model output) rather than a swept candidate
    default_params: Optional[Callable[[dict], dict]] = None
    # extra family-specific checks: (params, features, tag) -> findings
    extra: Optional[Callable[[dict, dict, str], List[Finding]]] = None


def _tag(family: str, features: dict, params: dict) -> str:
    feat = ",".join(f"{k}={v}" for k, v in sorted(features.items()))
    par = ",".join(f"{k}={v}" for k, v in sorted(params.items()))
    return f"<sanitize:{family}|{feat}|{par}>"


# -- flash attention -------------------------------------------------------

def _flash_shapes() -> List[dict]:
    from apex_tpu.tuning import cost_model

    out = []
    for row in cost_model.iter_flash_ladder():
        for bwd in (False, True):
            out.append({"sq": row["sq"], "sk": row["sk"], "d": row["d"],
                        "dt": "bf16", "bwd": bwd})
    return out


def _flash_build(params: dict, features: dict) -> Optional[KernelGeom]:
    from apex_tpu.tuning import cost_model

    if params.get("backend") == "jnp":
        return None
    sq, sk, d = features["sq"], features["sk"], features["d"]
    bwd = features["bwd"]
    streaming = max(sq, sk) > cost_model.STREAM_SEQ
    sqp, skp = _pad128(sq), _pad128(sk)
    bq = min(params["block_q"], sqp)
    bk = min(params["block_k"], skp)
    # the op layer pads sequences up to block multiples
    sqp, skp = _pad_to(sqp, bq), _pad_to(skp, bk)
    nq, nk = sqp // bq, skp // bk
    bh = 4  # batch*heads instances — any positive count; geometry per-instance
    grid = (bh, nq, nk) if streaming else (bh, nq)
    blocks = [
        BlockGeom("q", (1, bq, d), (bh, sqp, d),
                  (lambda b, i, k=0: (b, i, 0)) if streaming
                  else (lambda b, i: (b, i, 0))),
        BlockGeom("out", (1, bq, d), (bh, sqp, d),
                  (lambda b, i, k=0: (b, i, 0)) if streaming
                  else (lambda b, i: (b, i, 0))),
    ]
    if streaming:
        blocks.append(BlockGeom("kv", (1, bk, d), (bh, skp, d),
                                lambda b, i, k: (b, k, 0)))
    else:
        # resident family: the whole padded K/V row is the block
        blocks.append(BlockGeom("kv", (1, skp, d), (bh, skp, d),
                                lambda b, i: (b, 0, 0)))
    bytes_el = 2 if features["dt"] in ("bf16", "f16") else 4
    vmem = cost_model.flash_vmem_bytes(sq, sk, d, bytes_el, bq, bk,
                                       streaming=streaming, bwd=bwd)
    return KernelGeom("flash", grid, blocks, vmem_bytes=int(vmem),
                      vmem_budget=_vmem_budget(),
                      tag=_tag("flash", features, params))


def _flash_defaults(features: dict) -> dict:
    from apex_tpu.tuning import cost_model

    streaming = max(features["sq"], features["sk"]) > cost_model.STREAM_SEQ
    return {
        "block_q": cost_model.flash_block_default(
            features["sq"], streaming, features["bwd"]),
        "block_k": cost_model.flash_block_default(
            features["sk"], streaming, features["bwd"]),
    }


# -- layer norm / rms norm -------------------------------------------------

def _ln_shapes() -> List[dict]:
    return [{"rows": r, "hidden": h}
            for r in (128, 4096) for h in (1024, 8192)]


def _ln_build(params: dict, features: dict) -> KernelGeom:
    rows_total = _pad_to(features["rows"], params["block_rows"])
    br, h = params["block_rows"], features["hidden"]
    n = rows_total // br
    vmem = br * h * 4 * 3            # bwd holds x, dy, dx fp32 row tiles
    return KernelGeom(
        "layer_norm", (n,),
        [BlockGeom("x", (br, h), (rows_total, h), lambda i: (i, 0)),
         BlockGeom("out", (br, h), (rows_total, h), lambda i: (i, 0))],
        vmem_bytes=vmem, vmem_budget=_vmem_budget(),
        tag=_tag("layer_norm", features, params))


def _ln_defaults(features: dict) -> dict:
    from apex_tpu.tuning import cost_model

    return {"block_rows": cost_model.ln_block_rows_default(
        features["hidden"])}


# -- optimizer flat kernels ------------------------------------------------

def _optim_shapes() -> List[dict]:
    return [{"n": n, "n_tiles": tiles}
            for n in (8192, 1 << 22) for tiles in (2, 7)]


def _optim_build(params: dict, features: dict) -> KernelGeom:
    br = params["block_rows"]
    rows = _pad_to(_ceil(features["n"], 128), br)
    n = rows // br
    vmem = br * 128 * 4 * features["n_tiles"] * 2   # double-buffered
    return KernelGeom(
        "optim_flat", (n,),
        [BlockGeom("flat", (br, 128), (rows, 128), lambda i: (i, 0))],
        vmem_bytes=vmem, vmem_budget=_vmem_budget(),
        tag=_tag("optim_flat", features, params))


def _optim_defaults(features: dict) -> dict:
    from apex_tpu.tuning import cost_model

    return {"block_rows": cost_model.optim_block_rows_default(
        features["n_tiles"])}


# -- softmax row tiling (host-side lax.map tiling — no Pallas kernel) ------

def _softmax_shapes() -> List[dict]:
    return [{"rows": r, "cols": c} for r in (512, 16384) for c in (128,)]


def _softmax_build(params: dict, features: dict) -> Optional[KernelGeom]:
    c = params["row_chunk"]
    if c <= 0:
        return None                   # untiled: one fused XLA pass
    rows = _pad_to(features["rows"], c)
    return KernelGeom(
        "softmax", (rows // c,),
        [BlockGeom("rows", (c, features["cols"]),
                   (rows, features["cols"]), lambda i: (i, 0))],
        vmem_bytes=0, vmem_budget=0,
        tag=_tag("softmax", features, params))


# -- overlap_tp ring chunking (collective schedule — no Pallas kernel) -----

def _overlap_shapes() -> List[dict]:
    return [{"rows_local": r, "n_ranks": n}
            for r in (1, 8, 512) for n in (1, 4, 8)]


def _overlap_build(params: dict, features: dict) -> None:
    return None


def _overlap_extra(params: dict, features: dict, tag: str
                   ) -> List[Finding]:
    """The ring schedule's own invariants: the split covers the local
    rows exactly and every hop's ppermute is a bijection (the APX203
    invariant, checked over the static schedule here)."""
    from apex_tpu.parallel.overlap import _perm, _split_points

    findings: List[Finding] = []
    rows, n = features["rows_local"], features["n_ranks"]
    chunks = params["chunks"]
    pieces = _split_points(rows, chunks)
    covered = sum(size for _, size in pieces)
    if rows and covered != rows:
        findings.append(Finding(
            "APX301", tag, 0,
            f"overlap_tp: ring pieces cover {covered} of {rows} local "
            f"rows (chunks={chunks})"))
    if rows and pieces:
        ends = [o + s for o, s in pieces]
        starts = [o for o, _ in pieces[1:]] + [rows]
        if ends != starts or pieces[0][0] != 0:
            findings.append(Finding(
                "APX301", tag, 0,
                f"overlap_tp: ring pieces {pieces} overlap or leave gaps "
                f"over {rows} rows"))
    for direction in (1, -1):
        perm = _perm(n, direction)
        srcs, dsts = [s for s, _ in perm], [d for _, d in perm]
        if sorted(srcs) != list(range(n)) or sorted(dsts) != list(range(n)):
            findings.append(Finding(
                "APX203", tag, 0,
                f"overlap_tp: ring permutation {perm} is not a bijection "
                f"over {n} ranks"))
    return findings


# -- paged decode (ragged multi-query) --------------------------------------

def _paged_shapes() -> List[dict]:
    shapes = [{"slots": 4, "max_blocks": mb, "bs": 16, "group": g, "d": 64,
               "nb": 32, "tq": tq}
              for mb in (1, 7) for g in (1, 4) for tq in (4, 24)]
    # the int8-KV variant (quant=True): same grid, each fetched page
    # adds a scale-sidecar block pair riding the same table-driven
    # index maps — two representative shapes keep the sweep bounded
    shapes += [dict(s, quant=True) for s in (shapes[1], shapes[-1])]
    return shapes


def _paged_layout(s_n: int, tq: int, q_tile: int) -> List[int]:
    """Adversarial per-slot query lengths for the work-list model: an
    idle slot, a single-token decode, a speculative K=3 verify window
    (query_len 4 — the serving engine's spec-on run shape) when tq
    allows, and one chunk taking every remaining row (crossing q_tile
    boundaries whenever tq allows)."""
    ql = [1] * s_n
    ql[1 % s_n] = 0
    if s_n > 2 and tq >= s_n + 6:
        ql[2] = 4
    ql[0] = max(1, tq - sum(ql[1:]))
    del q_tile  # the chunk crosses tiles for any q_tile < ql[0]
    return ql


def _paged_build(params: dict, features: dict) -> Optional[KernelGeom]:
    """Mirror of ops.paged_attention._ragged_pallas: grid
    (work item, kv head, fetch step) over the static (slot, q-tile) work
    list, whole-array q/out blocks, per-fetch KV page blocks selected by
    the table through clamped flat indices."""
    if params.get("backend") == "jnp":
        return None
    s_n, mb = features["slots"], features["max_blocks"]
    bs, group, d = features["bs"], features["group"], features["d"]
    nb, tq = features["nb"], features["tq"]
    hkv = 2
    hq = hkv * group
    fetch = min(params["kv_fetch"], max(1, mb))
    q_tile = params["q_tile"]
    rows = max(params["block_rows"], q_tile * group)
    nj = _ceil(mb, fetch)
    n_work = _ceil(tq, q_tile) + s_n
    tq_pad = tq + q_tile

    # the work list exactly as _work_metadata builds it (plain ints)
    ql = _paged_layout(s_n, tq, q_tile)
    work_slot: List[int] = []
    for s, n in enumerate(ql):
        work_slot.extend([s] * _ceil(n, q_tile))
    work_slot = (work_slot + [s_n] * n_work)[:n_work]  # sentinel pad

    # adversarial block table: first/last pool pages + the clamp target
    table = [(si * 7 + j * 3) % nb for si in range(s_n) for j in range(mb)]
    flat_len = len(table)

    def page_map(i):
        def index(w, h, j):
            s = min(work_slot[w], s_n - 1)
            flat = min(max(s * mb + j * fetch + i, 0), flat_len - 1)
            return (table[flat], 0, h, 0)
        return index

    def scale_map(i):
        # the quant variant's sidecar pages: same page selection, minus
        # the head_dim axis (ops/paged_attention.scale_map)
        def index(w, h, j):
            s = min(work_slot[w], s_n - 1)
            flat = min(max(s * mb + j * fetch + i, 0), flat_len - 1)
            return (table[flat], 0, h)
        return index

    blocks = [BlockGeom("q", (tq_pad, hq, d), (tq_pad, hq, d),
                        lambda w, h, j: (0, 0, 0)),
              BlockGeom("out", (tq_pad, hq, d), (tq_pad, hq, d),
                        lambda w, h, j: (0, 0, 0))]
    for i in range(fetch):
        blocks.append(BlockGeom(f"k{i}", (1, bs, 1, d), (nb, bs, hkv, d),
                                page_map(i)))
        blocks.append(BlockGeom(f"v{i}", (1, bs, 1, d), (nb, bs, hkv, d),
                                page_map(i)))
    quant = bool(features.get("quant"))
    if quant:
        for i in range(fetch):
            blocks.append(BlockGeom(f"ks{i}", (1, bs, 1), (nb, bs, hkv),
                                    scale_map(i)))
            blocks.append(BlockGeom(f"vs{i}", (1, bs, 1), (nb, bs, hkv),
                                    scale_map(i)))
    bytes_el = 1 if quant else 2
    vmem = (2 * tq_pad * hq * d * 2                 # resident q + out
            + fetch * 2 * bs * d * bytes_el * 2     # double-buffered pages
            + (fetch * 2 * bs * 4 * 2 if quant else 0)   # scale pages
            + rows * d * 4 + 2 * rows * 4)          # (acc, m, l) scratch
    return KernelGeom(
        "paged_decode", (n_work, hkv, nj), blocks,
        vmem_bytes=vmem, vmem_budget=_vmem_budget(),
        tag=_tag("paged_decode", features, params))


def _paged_defaults(features: dict) -> dict:
    from apex_tpu.tuning import cost_model

    return {
        "block_rows": cost_model.paged_block_rows_default(
            features["group"]),
        "kv_fetch": cost_model.paged_kv_fetch_default(
            features["bs"], features["d"]),
        "q_tile": cost_model.paged_q_tile_default(features["group"]),
    }


# -- blockwise-scaled quantized matmul (quantization/scaled_matmul.py) -----

def _quant_shapes() -> List[dict]:
    return [{"m": m, "k": k, "n": 384}
            for m in (48, 1024) for k in (200, 1024)]


def _quant_build(params: dict, features: dict) -> Optional[KernelGeom]:
    """Mirror of quantization.scaled_matmul._qmm_pallas: dense grid
    (m-tile, n-tile, k-block) with k minor (the revisit axis of the
    fp32 accumulator), int8/fp8 payload tiles plus their (rows, 1) /
    (1, cols) scale-sidecar blocks."""
    if params.get("backend") == "jnp":
        return None
    m, k, n = features["m"], features["k"], features["n"]
    tile_m, tile_k = params["tile_m"], params["tile_k"]
    k_pad = _ceil(max(_pad128(k), 1), tile_k) * tile_k
    n_pad128 = _pad128(n)
    tile_n = min(params["tile_n"], n_pad128)
    m_pad = _pad_to(m, tile_m)
    n_pad = _ceil(n_pad128, tile_n) * tile_n
    nm, nn, nk = m_pad // tile_m, n_pad // tile_n, k_pad // tile_k
    blocks = [
        BlockGeom("lq", (tile_m, tile_k), (m_pad, k_pad),
                  lambda i, j, kb: (i, kb)),
        BlockGeom("ls", (tile_m, 1), (m_pad, nk),
                  lambda i, j, kb: (i, kb)),
        BlockGeom("rq", (tile_k, tile_n), (k_pad, n_pad),
                  lambda i, j, kb: (kb, j)),
        BlockGeom("rs", (1, tile_n), (nk, n_pad),
                  lambda i, j, kb: (kb, j)),
        BlockGeom("out", (tile_m, tile_n), (m_pad, n_pad),
                  lambda i, j, kb: (i, j)),
    ]
    vmem = (2 * (tile_m * tile_k + tile_k * tile_n) * 1   # int8 payloads
            + 2 * (tile_m + tile_n) * 4                   # scale sidecars
            + tile_m * tile_n * (4 + 4))                  # fp32 acc + out
    return KernelGeom(
        "quant_matmul", (nm, nn, nk), blocks,
        vmem_bytes=vmem, vmem_budget=_vmem_budget(),
        tag=_tag("quant_matmul", features, params))


def _quant_defaults(features: dict) -> dict:
    from apex_tpu.tuning import cost_model

    return {
        "tile_m": cost_model.quant_tile_m_default(features["k"],
                                                  features["n"]),
        "tile_n": cost_model.quant_tile_n_default(features["n"]),
        "tile_k": cost_model.quant_tile_k_default(features["k"]),
    }


# -- grouped matmul (dropless MoE) -----------------------------------------

def _moe_shapes() -> List[dict]:
    return [{"t": t, "e": e, "h": 256, "f": 384}
            for t in (8, 1024) for e in (4, 8)]


def _moe_build(params: dict, features: dict) -> Optional[KernelGeom]:
    if params.get("backend") == "jnp":
        return None
    t, e = features["t"], features["e"]
    h, f = features["h"], features["f"]
    tile_t = params["tile_t"]
    tile_f = min(params["tile_f"], _pad128(f))
    k_pad = _pad128(h)
    f_pad = _ceil(_pad128(f), tile_f) * tile_f
    t_pad = _pad_to(t, tile_t)
    pt = t_pad // tile_t
    nf = f_pad // tile_f
    # adversarial work-list contents for the ragged index-map probes:
    # real tiles/groups up front, sentinel values (pt / e) behind — the
    # exact extremes _group_metadata emits
    work_tile = list(range(pt)) + [pt] * (e + 1)
    work_group = list(range(e)) + [e] * (pt + 1)
    # grid minor axis walks the work list; index maps CLAMP exactly like
    # _gmm_pallas (tile -> pt-1, group -> e-1)
    blocks = [
        BlockGeom("lhs", (tile_t, k_pad), (t_pad, k_pad),
                  lambda j, i: (min(work_tile[i], pt - 1), 0)),
        BlockGeom("rhs", (1, k_pad, tile_f), (e, k_pad, f_pad),
                  lambda j, i: (min(work_group[i], e - 1), 0, j)),
        BlockGeom("out", (tile_t, tile_f), (t_pad, f_pad),
                  lambda j, i: (min(work_tile[i], pt - 1), j)),
    ]
    dtype_bytes = 2
    vmem = (2 * (tile_t * k_pad + k_pad * tile_f + tile_t * tile_f)
            * dtype_bytes + tile_t * tile_f * 4)
    return KernelGeom(
        "moe_grouped", (nf, pt + e), blocks,
        vmem_bytes=vmem, vmem_budget=_vmem_budget(),
        tag=_tag("moe_grouped", features, params))


def _moe_defaults(features: dict) -> dict:
    from apex_tpu.tuning import cost_model

    return {
        "tile_t": cost_model.moe_tile_t_default(features["h"],
                                                features["f"]),
        "tile_f": cost_model.moe_tile_f_default(features["f"]),
    }


def _moe_extra(params: dict, features: dict, tag: str) -> List[Finding]:
    """The APX304 revisit-chain replay over the adversarial group
    distributions, for both gmm (tile-keyed) and tgmm (group-keyed)."""
    if params.get("backend") == "jnp":
        return []
    rng = random.Random(f"{features['t']}:{features['e']}:"
                        f"{params['tile_t']}")
    findings: List[Finding] = []
    for dist in _group_distributions(features["e"], features["t"], rng):
        findings.extend(replay_gmm_schedule(
            dist, features["t"], params["tile_t"], tag))
        findings.extend(replay_tgmm_schedule(
            dist, features["t"], params["tile_t"], tag))
    return findings


FAMILIES: Dict[str, Family] = {
    f.name: f
    for f in (
        Family("flash", "flash", _flash_shapes, _flash_build,
               _flash_defaults),
        Family("layer_norm", "layer_norm", _ln_shapes, _ln_build,
               _ln_defaults),
        Family("optim", "optim_flat", _optim_shapes, _optim_build,
               _optim_defaults),
        Family("softmax", "softmax", _softmax_shapes, _softmax_build),
        Family("paged_decode", "paged_decode", _paged_shapes,
               _paged_build, _paged_defaults),
        Family("moe_grouped", "moe_grouped", _moe_shapes, _moe_build,
               _moe_defaults, extra=_moe_extra),
        Family("quant_matmul", "quant_matmul", _quant_shapes,
               _quant_build, _quant_defaults),
        Family("overlap_tp", "overlap_tp", _overlap_shapes,
               _overlap_build, extra=_overlap_extra),
    )
}


# ---------------------------------------------------------------------------
# sweep driver
# ---------------------------------------------------------------------------

def _candidate_space(registry_key: str) -> List[dict]:
    from apex_tpu.tuning.registry import TUNABLES

    t = TUNABLES[registry_key]
    keys = sorted(t.params)
    out = []
    for combo in itertools.product(*(t.params[k] for k in keys)):
        out.append(dict(zip(keys, combo)))
    return out


def sanitize_family(name: str, *, full: bool = False, seed: int = 0,
                    sample: int = 24) -> Tuple[List[Finding], dict]:
    """Sweep one family: every (shape, candidate) pair when ``full``,
    else a seeded subsample of ``sample`` pairs (tier-1 budget). Returns
    (findings, stats)."""
    from apex_tpu.tuning.registry import TUNABLES

    fam = FAMILIES[name]
    reg = TUNABLES[fam.registry_key]
    shapes = fam.shapes()
    cands = _candidate_space(fam.registry_key)
    pairs = [(s, c) for s in shapes for c in cands]
    if fam.default_params is not None:
        pairs += [(s, fam.default_params(s)) for s in shapes]
    if not full and len(pairs) > sample:
        rng = random.Random((seed, name).__repr__())
        keep = rng.sample(range(len(pairs)), sample)
        # defaults always stay in the subsample
        n_def = len(shapes) if fam.default_params is not None else 0
        keep = sorted(set(keep) | set(range(len(pairs) - n_def,
                                            len(pairs))))
        pairs = [pairs[i] for i in keep]

    findings: List[Finding] = []
    stats = {"family": name, "checked": 0, "rejected": 0, "kernels": 0}
    n_def = len(shapes) if fam.default_params is not None else 0
    for k, (features, params) in enumerate(pairs):
        is_default = k >= len(pairs) - n_def
        tag = _tag(name, features, params)
        if reg.check is not None:
            err = reg.check({p: v for p, v in params.items()
                             if p in reg.params}, features)
            if err:
                findings.append(Finding(
                    "APX305", tag, 0,
                    f"candidate rejected by the registry check: {err}"))
                stats["rejected"] += 1
                continue
        stats["checked"] += 1
        geom = fam.build(params, features)
        if geom is not None:
            stats["kernels"] += 1
            geo_findings = check_geometry(geom)
            if not is_default:
                # swept candidates busting VMEM are inventory (APX305):
                # the autotune probe rejects them before any cache pin
                geo_findings = [
                    Finding("APX305", f.path, f.line,
                            "candidate over the VMEM budget (autotune "
                            "probe would reject): " + f.message)
                    if f.rule == "APX302" else f
                    for f in geo_findings
                ]
            findings.extend(geo_findings)
        if fam.extra is not None:
            findings.extend(fam.extra(params, features, tag))
    return findings, stats


def sanitize_families(names: Optional[Sequence[str]] = None, *,
                      full: bool = False, seed: int = 0,
                      sample: int = 24
                      ) -> Tuple[List[Finding], List[dict]]:
    if names is None:
        names = sorted(FAMILIES)
    findings: List[Finding] = []
    stats: List[dict] = []
    for n in names:
        f, s = sanitize_family(n, full=full, seed=seed, sample=sample)
        findings.extend(f)
        stats.append(s)
    return findings, stats
