"""Low-precision subsystem: blockwise-scaled quantization for compute
and memory (ROADMAP item 3 — the end-to-end story whose wire half is
``parallel/quantized_collectives.py``).

Three consumers of one scheme (narrow payload + per-block fp32 absmax
scale sidecar, qtensor.py):

* ``quant_matmul`` (scaled_matmul.py) — the Pallas blockwise-scaled
  int8/fp8 matmul family, registered as the ``quant_matmul`` tunable
  and routed into dense/MLP matmuls by the amp ``O2_INT8`` policy mode
  (amp/policy.py).
* the int8 paged KV cache (serving/kv_cache.py ``quantized_kv_cache``)
  — int8 K/V pools with per-(token, head) scales, dequantized in-kernel
  by ops/paged_attention.py, behind ``APEX_TPU_SERVING_KV_INT8=1``.
* the quantized collectives that came first (parallel/) — unchanged,
  already validated by tests/L0/test_quantized_comms_fuzz.py.

docs/quantization.md covers the error models, policy modes, KV layout
and tunables.
"""

from apex_tpu.quantization.qtensor import (
    FP8_MAX,
    INT8_QMAX,
    QTensor,
    dequantize,
    quant_itemsize,
    quantize,
)
from apex_tpu.quantization.scaled_matmul import (
    matmul_bytes_saved,
    quant_matmul,
    quant_matmul_ref,
    quantized_operands,
)

__all__ = [
    "FP8_MAX",
    "INT8_QMAX",
    "QTensor",
    "dequantize",
    "matmul_bytes_saved",
    "quant_itemsize",
    "quant_matmul",
    "quant_matmul_ref",
    "quantize",
    "quantized_operands",
]
