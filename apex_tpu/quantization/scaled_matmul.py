"""Blockwise-scaled low-precision matmul (``quant_matmul``) — Pallas
kernel family + dequantize-einsum oracle.

Apex's reason to exist is mixed precision; this is the compute half of
the end-to-end low-precision story (ROADMAP item 3 — the wire half
shipped as ``parallel/quantized_collectives.py``). The scheme is the
same one the collectives proved: quantize both operands BLOCKWISE along
the contraction axis (per-tile absmax scales held as a fp32 SIDECAR
array, qtensor.py), run the narrow matmul on the MXU, and apply the
scale outer product per k-block while accumulating in fp32:

    out[i, j] = sum_kb  ( lq[i, kb·K:...] · rq[kb·K:..., j] )    (int)
                * ls[i, kb] * rs[kb, j]                          (fp32)

which equals the dequantize-einsum exactly in real arithmetic (the
scales are constant within a block), so ``quant_matmul_ref`` — the jnp
dequantize-einsum over the SAME quantized payloads — is both the
fallback and the test oracle; kernel-vs-oracle differences are fp32
accumulation-order noise only, and the QUANTIZATION error itself is the
qtensor.py model (int8: elementwise <= absmax_block/254 per operand).

Two operand widths, one kernel body:

* ``int8`` — int8 x int8 MXU products accumulated in int32 per k-tile
  (exact), scaled into the fp32 accumulator.
* ``fp8`` — ``float8_e4m3fn`` payload; the kernel body upcasts the f8
  tiles to fp32 before the dot (CPU/interpret emulation; on an fp8-MXU
  generation the upcast drops out — the PAYLOAD layout and scale
  sidecar are already the native format).

Backward (``jax.custom_vjp``): dlhs = dout @ rhs^T and
drhs = lhs^T @ dout, computed either at the SAME quantized width
(``bwd_quant=True`` — both cotangents re-quantize along their own
contraction axes) or in plain fp32 (the default; amp policy
``matmul_quant_bwd`` picks, docs/quantization.md).

Tunables (``quant_matmul`` family, tuning/registry.py): ``tile_m``
(output rows per grid step, sublane multiple of 8 — int8 tiles
natively want 32), ``tile_n`` (output columns, lane multiple of 128)
and ``tile_k`` (contraction elements per k-step — ALSO the
quantization block size, so the tuner trades scale resolution against
MXU occupancy), resolved env (APEX_TPU_QUANT_TILE_M /
APEX_TPU_QUANT_TILE_N / APEX_TPU_QUANT_TILE_K) > tune cache > cost
model, the PR-1 order; ``autotune.sweep_quant`` sweeps exactly this
space and the sanitizer (analysis/sanitizer.py) validates every
candidate's geometry statically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.observability import inc_counter
from apex_tpu.ops._utils import default_use_pallas, env_flag, env_int, \
    pallas_interpret
from apex_tpu.quantization.qtensor import QTensor, quantize

try:
    from jax.experimental.pallas import tpu as _pltpu
except Exception:  # pragma: no cover
    _pltpu = None

_HIGHEST = jax.lax.Precision.HIGHEST

__all__ = ["quant_matmul", "quant_matmul_ref", "quantized_operands",
           "matmul_bytes_saved"]


def _ceil(a: int, b: int) -> int:
    return -(-a // b)


def _pad128(n: int) -> int:
    return max(128, _ceil(n, 128) * 128)


def _quant_params(m: int, k: int, n: int, dtype, qdtype: str) -> dict:
    """Resolved {"tile_m", "tile_n", "tile_k", "backend"} for one call:
    env wins outright, then the tune cache for this shape class, then
    the cost model — the same three-layer order as every PR-1 family."""
    from apex_tpu import tuning

    cfg = tuning.quant_matmul_config(m, k, n, dtype, qdtype)
    tm = env_int("APEX_TPU_QUANT_TILE_M", quantum=8)
    tn = env_int("APEX_TPU_QUANT_TILE_N", quantum=128)
    tk = env_int("APEX_TPU_QUANT_TILE_K", quantum=128)
    return {
        "tile_m": tm if tm is not None else cfg["tile_m"],
        "tile_n": tn if tn is not None else cfg["tile_n"],
        "tile_k": tk if tk is not None else cfg["tile_k"],
        "backend": cfg["backend"],
    }


def _auto_use_kernel(m: int, k: int, n: int, dtype, qdtype: str) -> bool:
    """Backend decision for auto mode (use_pallas=None): preflight
    registry and APEX_TPU_USE_PALLAS first (ops/_utils), then a pinned
    cache entry or the cost-model row threshold may route the class to
    the dequantize-einsum oracle; env=1 beats both (env > cache >
    model)."""
    if not default_use_pallas("quant_matmul"):
        return False
    if env_flag("APEX_TPU_USE_PALLAS"):
        return True
    return _quant_params(m, k, n, dtype, qdtype)["backend"] != "jnp"


def matmul_bytes_saved(m: int, k: int, n: int, itemsize: int,
                       tile_k: int) -> int:
    """Analytic operand-bytes saving of ONE quantized matmul vs reading
    both operands at their original width: narrow payloads cost 1 B/elt
    and the sidecar adds one fp32 scale per (row, k-block). The
    ``quant/matmul_bytes_saved`` counter and its test share this
    formula — one definition, no drift (the quantized_wire_bytes
    discipline)."""
    nk = _ceil(int(k), int(tile_k))
    full = (m * k + k * n) * itemsize
    quant = (m * k + k * n) * 1 + (m * nk + nk * n) * 4
    return max(0, full - quant)


# ---------------------------------------------------------------------------
# quantized-operand prologue (shared by kernel and oracle)
# ---------------------------------------------------------------------------

def quantized_operands(lhs, rhs, tile_k: int, qdtype: str):
    """Pad ``lhs [m, k]`` / ``rhs [k, n]`` to the k-tile grid and
    quantize both along k with block = tile_k. Kernel and oracle both
    consume THIS output, so the quantization error is identical on
    either path and parity tests measure only accumulation order.
    Returns (lhs_qt, rhs_qt, k_pad)."""
    m, k = lhs.shape
    _, n = rhs.shape
    k_pad = _ceil(max(_pad128(k), 1), tile_k) * tile_k
    lhs_p = jnp.pad(lhs.astype(jnp.float32), ((0, 0), (0, k_pad - k)))
    rhs_p = jnp.pad(rhs.astype(jnp.float32), ((0, k_pad - k), (0, 0)))
    lqt = quantize(lhs_p, block=tile_k, axis=1, dtype=qdtype)
    rqt = quantize(rhs_p, block=tile_k, axis=0, dtype=qdtype)
    return lqt, rqt, k_pad


# ---------------------------------------------------------------------------
# jnp reference (oracle + fallback)
# ---------------------------------------------------------------------------

def quant_matmul_ref(lqt: QTensor, rqt: QTensor, tile_k: int,
                     out_dtype=jnp.float32):
    """Dequantize-einsum oracle over the quantized payloads: per
    k-block, the integer partial products scale by the fp32 outer
    product of the block scales — the memory-bound unfused path the
    kernel exists to avoid, and the parity target of the fuzz suite."""
    m, k_pad = lqt.q.shape
    _, n = rqt.q.shape
    nk = k_pad // tile_k
    lq = lqt.q.astype(jnp.float32).reshape(m, nk, tile_k)
    rq = rqt.q.astype(jnp.float32).reshape(nk, tile_k, n)
    part = jnp.einsum("mbk,bkn->bmn", lq, rq, precision=_HIGHEST)
    out = jnp.einsum("bmn,mb,bn->mn", part, lqt.scale, rqt.scale,
                     precision=_HIGHEST)
    return out.astype(out_dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

def _qmm_kernel(lq_ref, ls_ref, rq_ref, rs_ref, out_ref, acc_ref, *, nk,
                int_payload: bool):
    """Grid (m-tile i, n-tile j, k-block kb) with kb minor: consecutive
    kb steps revisit one output tile, accumulating the scaled partial
    products in fp32 VMEM scratch; the last k-block flushes."""
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    if int_payload:
        part = jax.lax.dot_general(
            lq_ref[...], rq_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32).astype(jnp.float32)
    else:
        # fp8 emulation: upcast the f8 tiles; on an fp8-MXU device this
        # cast drops out of the lowering (the payload is already native)
        part = jax.lax.dot_general(
            lq_ref[...].astype(jnp.float32),
            rq_ref[...].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    acc_ref[...] += part * (ls_ref[...] * rs_ref[...])

    @pl.when(kb == nk - 1)
    def _emit():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def _qmm_pallas(lqt: QTensor, rqt: QTensor, m: int, n: int, tile_m: int,
                tile_n: int, tile_k: int, out_dtype, int_payload: bool):
    k_pad = lqt.q.shape[1]
    nk = k_pad // tile_k
    n_pad128 = _pad128(n)
    tile_n = min(tile_n, n_pad128)
    # the grid floor-divides: pad outputs to tile multiples or trailing
    # blocks would never be visited (= garbage out), same rule as gmm
    m_pad = _ceil(max(m, 1), tile_m) * tile_m
    n_pad = _ceil(n_pad128, tile_n) * tile_n
    nm, nn = m_pad // tile_m, n_pad // tile_n

    lq = jnp.pad(lqt.q, ((0, m_pad - m), (0, 0)))
    ls = jnp.pad(lqt.scale, ((0, m_pad - m), (0, 0)))       # [m_pad, nk]
    rq = jnp.pad(rqt.q, ((0, 0), (0, n_pad - n)))
    rs = jnp.pad(rqt.scale, ((0, 0), (0, n_pad - n)))       # [nk, n_pad]

    out = pl.pallas_call(
        functools.partial(_qmm_kernel, nk=nk, int_payload=int_payload),
        grid=(nm, nn, nk),
        in_specs=[
            pl.BlockSpec((tile_m, tile_k), lambda i, j, kb: (i, kb)),
            pl.BlockSpec((tile_m, 1), lambda i, j, kb: (i, kb)),
            pl.BlockSpec((tile_k, tile_n), lambda i, j, kb: (kb, j)),
            pl.BlockSpec((1, tile_n), lambda i, j, kb: (kb, j)),
        ],
        out_specs=pl.BlockSpec((tile_m, tile_n), lambda i, j, kb: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m_pad, n_pad), out_dtype),
        scratch_shapes=[_pltpu.VMEM((tile_m, tile_n), jnp.float32)],
        interpret=pallas_interpret(),
    )(lq, ls, rq, rs)
    return out[:m, :n]


# ---------------------------------------------------------------------------
# differentiable core (custom_vjp) + public API
# ---------------------------------------------------------------------------

def _qmm_dispatch(lhs, rhs, qdtype, out_dtype, use_pallas):
    m, k = lhs.shape
    _, n = rhs.shape
    p = _quant_params(m, k, n, lhs.dtype, qdtype)
    tile_k = p["tile_k"]
    use = use_pallas
    if use is None:
        use = _auto_use_kernel(m, k, n, lhs.dtype, qdtype)
    # trace-time analytic accounting, the comms/bytes_on_wire idiom:
    # counts once per trace, reporting the per-call operand saving
    inc_counter("quant/matmul_bytes_saved",
                matmul_bytes_saved(m, k, n,
                                   jnp.dtype(lhs.dtype).itemsize, tile_k),
                qdtype=qdtype)
    lqt, rqt, _ = quantized_operands(lhs, rhs, tile_k, qdtype)
    out_dtype = out_dtype or lhs.dtype
    if not use or _pltpu is None:
        return quant_matmul_ref(lqt, rqt, tile_k, out_dtype=out_dtype)
    return _qmm_pallas(lqt, rqt, m, n, p["tile_m"], p["tile_n"], tile_k,
                       out_dtype, int_payload=(qdtype == "int8"))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _qmm_core(lhs, rhs, qdtype, bwd_quant, out_dtype, use_pallas):
    return _qmm_dispatch(lhs, rhs, qdtype, out_dtype, use_pallas)


def _qmm_core_fwd(lhs, rhs, qdtype, bwd_quant, out_dtype, use_pallas):
    out = _qmm_dispatch(lhs, rhs, qdtype, out_dtype, use_pallas)
    return out, (lhs, rhs)


def _qmm_core_bwd(qdtype, bwd_quant, out_dtype, use_pallas, res, dout):
    lhs, rhs = res
    del out_dtype                    # cotangent dtypes follow the primals
    if bwd_quant:
        # bwd at the SAME quantized width: each cotangent re-quantizes
        # along its own contraction axis (n for dlhs, m for drhs)
        dlhs = _qmm_dispatch(dout, rhs.T, qdtype, lhs.dtype, use_pallas)
        drhs = _qmm_dispatch(lhs.T, dout, qdtype, rhs.dtype, use_pallas)
    else:
        d32 = dout.astype(jnp.float32)
        dlhs = jnp.matmul(d32, rhs.astype(jnp.float32).T,
                          precision=_HIGHEST).astype(lhs.dtype)
        drhs = jnp.matmul(lhs.astype(jnp.float32).T, d32,
                          precision=_HIGHEST).astype(rhs.dtype)
    return dlhs, drhs


_qmm_core.defvjp(_qmm_core_fwd, _qmm_core_bwd)


def quant_matmul(lhs, rhs, *, dtype: str = "int8", bwd_quant: bool = False,
                 out_dtype=None, use_pallas=None):
    """Blockwise-scaled low-precision matmul ``lhs @ rhs``.

    ``lhs``: ``[..., m, k]`` float (leading batch dims collapse into
    rows); ``rhs``: ``[k, n]`` float. Both operands quantize to
    ``dtype`` ("int8" | "fp8") with per-(row, k-tile) fp32 scales;
    accumulation is fp32 on the MXU. Returns ``[..., m, n]`` in
    ``out_dtype`` (default lhs.dtype). Differentiable in both operands
    (custom_vjp: cotangents at the same quantized width when
    ``bwd_quant``, plain fp32 otherwise). The quantization error is the
    qtensor.py model per operand; ``quant_matmul_ref`` over the same
    payloads is the oracle and the auto-mode fallback.
    """
    if lhs.ndim < 2 or rhs.ndim != 2:
        raise ValueError(f"quant_matmul expects lhs [..., m, k], "
                         f"rhs [k, n]: got {lhs.shape} / {rhs.shape}")
    if lhs.shape[-1] != rhs.shape[0]:
        raise ValueError(f"contraction mismatch: lhs k={lhs.shape[-1]} vs "
                         f"rhs k={rhs.shape[0]}")
    from apex_tpu.quantization.qtensor import _qdtype
    _qdtype(dtype)                             # validate the width token
    lead = lhs.shape[:-2]
    flat = lhs.reshape((-1, lhs.shape[-1])) if lead else lhs
    out = _qmm_core(flat, rhs, dtype, bool(bwd_quant), out_dtype,
                    use_pallas)
    return out.reshape(lead + (lhs.shape[-2], rhs.shape[1])) if lead \
        else out
