"""Blockwise quantize / dequantize — the scale-sidecar library under the
low-precision subsystem.

The wire format is the one ``parallel/quantized_collectives.py`` proved
for gradients (EQuARX, PAPERS.md), brought to compute and memory: a
narrow payload plus a PER-BLOCK fp32 absmax scale, so one outlier costs
its own block's resolution, never the tensor's. Blocks run along ONE
axis (the matmul contraction axis for ``scaled_matmul.quant_matmul``,
head_dim for the int8 KV cache), and the scales ride as a SIDECAR array
— ``QTensor(q, scale)`` is a plain pytree the jitted consumers carry
like any other operand pair.

Two payload widths:

* ``int8`` — symmetric round-to-nearest-even into [-127, 127]
  (``jnp.round`` is RNE; ties cannot bias a sum). Scale =
  absmax / 127 per block. **Error model** (fuzzed by
  tests/L0/test_quantization_fuzz.py the way
  test_quantized_comms_fuzz.py fuzzes the wire): the roundtrip error is
  elementwise bounded by half a quantization step,

      |x - dequant(quant(x))| <= scale / 2 = absmax_block / 254,

  i.e. worst-case ~0.4% of the block's absmax. Exact zeros survive
  exactly; a value equal to the block absmax maps to exactly ±127 (no
  clamping error).

* ``fp8`` (``float8_e4m3fn`` layout, emulated on CPU via XLA's f8
  casts) — scale = absmax / 448 (the e4m3 max normal), payload is the
  f8 cast of ``x / scale``. **Error model**: e4m3 carries 3 mantissa
  bits, so the roundtrip error is relative,

      |x - dequant(quant(x))| <= |x| * 2^-4 + scale * 2^-7,

  (half-ulp of the 3-bit mantissa, plus the subnormal floor near zero).
  fp8 trades the int8 format's uniform absolute error for wider dynamic
  range WITHIN a block — denormal-heavy blocks keep relative precision
  an int8 grid would flush to zero.

All-zero blocks take scale 1 (zeros quantize exactly, no 0/0), matching
the collectives' convention. Non-block-aligned trailing extents are
zero-padded internally — zeros quantize exactly, so a ragged tail costs
nothing — and the padding never leaves this module (``quantize``
returns the original extent; consumers that WANT the padded layout,
like the matmul kernel, pad first and quantize the padded operand so
kernel and oracle see byte-identical payloads).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = [
    "QTensor",
    "FP8_MAX",
    "INT8_QMAX",
    "dequantize",
    "quantize",
    "quant_itemsize",
]

INT8_QMAX = 127.0
FP8_MAX = 448.0          # float8_e4m3fn largest normal


def _qdtype(dtype: str):
    if dtype == "int8":
        return jnp.int8
    if dtype == "fp8":
        return jnp.float8_e4m3fn
    raise ValueError(f"quantized dtype {dtype!r} not in ('int8', 'fp8')")


def quant_itemsize(dtype: str) -> int:
    """Payload bytes per element — both formats are 1 byte; the sidecar
    adds 4 bytes per block (the capacity arithmetic the KV pool and the
    bytes-saved counters share)."""
    _qdtype(dtype)
    return 1


class QTensor(NamedTuple):
    """A quantized payload + its per-block fp32 scale sidecar.

    ``q`` has the source array's shape; ``scale`` has the same shape
    with the block axis divided by the block size (ceil). The block
    axis and size are CALL metadata (the consumer resolved them — e.g.
    the matmul kernel's ``tile_k``), not pytree state, exactly like the
    ragged run metadata of ops/paged_attention.py."""

    q: jax.Array
    scale: jax.Array


def quantize(x, *, block: int, axis: int = -1,
             dtype: str = "int8") -> QTensor:
    """Blockwise-quantize ``x`` along ``axis`` with per-block absmax
    scales (module doc for the error model). ``block`` need not divide
    the axis extent — the ragged tail is padded with exact zeros
    internally and the returned payload keeps ``x``'s shape."""
    qdt = _qdtype(dtype)
    qmax = INT8_QMAX if dtype == "int8" else FP8_MAX
    axis = axis % x.ndim
    n = x.shape[axis]
    block = max(1, min(int(block), n))
    xm = jnp.moveaxis(x.astype(jnp.float32), axis, -1)
    pad = (-n) % block
    if pad:
        xm = jnp.concatenate(
            [xm, jnp.zeros(xm.shape[:-1] + (pad,), jnp.float32)], axis=-1)
    nb = xm.shape[-1] // block
    rows = xm.reshape(xm.shape[:-1] + (nb, block))
    amax = jnp.max(jnp.abs(rows), axis=-1)
    scale = jnp.where(amax > 0, amax, 1.0) / qmax          # [..., nb]
    scaled = rows / scale[..., None]
    if dtype == "int8":
        q = jnp.clip(jnp.round(scaled), -INT8_QMAX, INT8_QMAX)
    else:
        q = jnp.clip(scaled, -FP8_MAX, FP8_MAX)
    q = q.astype(qdt).reshape(xm.shape)
    if pad:
        q = q[..., :n]
    return QTensor(q=jnp.moveaxis(q, -1, axis),
                   scale=jnp.moveaxis(scale, -1, axis))


def dequantize(qt: QTensor, *, block: int, axis: int = -1,
               out_dtype=jnp.float32):
    """Invert :func:`quantize` up to the documented roundtrip error:
    each payload element multiplies its block's scale. ``block``/
    ``axis`` must be the values the payload was quantized with (call
    metadata, not stored — the consumer that resolved the tile owns
    them)."""
    q, scale = qt
    axis = axis % q.ndim
    n = q.shape[axis]
    block = max(1, min(int(block), n))
    qm = jnp.moveaxis(q, axis, -1).astype(jnp.float32)
    sm = jnp.moveaxis(scale, axis, -1)
    idx = jnp.arange(n) // block                            # [n] -> block id
    out = qm * sm[..., idx]
    return jnp.moveaxis(out, -1, axis).astype(out_dtype)
