"""Logging minimalism for the transformer package.

Ref: apex/transformer/log_util.py — ``get_transformer_logger`` returns a
namespaced stdlib logger and ``set_logging_level`` adjusts the package
logger's threshold; apex deliberately has no metrics registry beyond
this (SURVEY §6). Per-step scalars live in ``apex_tpu.utils.metrics``.
"""

from __future__ import annotations

import logging

_PACKAGE = "apex_tpu.transformer"


def get_transformer_logger(name: str | None = None) -> logging.Logger:
    """Namespaced logger (``apex_tpu.transformer[.name]``)."""
    return logging.getLogger(f"{_PACKAGE}.{name}" if name else _PACKAGE)


def set_logging_level(verbosity) -> None:
    """Set the package logger's threshold. Accepts a stdlib level number
    or name ("DEBUG", "INFO", ...) — ref: set_logging_level(verbosity)."""
    if isinstance(verbosity, str):
        level = logging.getLevelName(verbosity.upper())
        if not isinstance(level, int):
            raise ValueError(f"unknown logging level name: {verbosity!r}")
        verbosity = level
    get_transformer_logger().setLevel(verbosity)
