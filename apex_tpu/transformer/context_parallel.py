"""Context parallelism for long sequences: ring attention + Ulysses all-to-all.

Ref: the reference scales long sequences with Megatron context parallelism
(ring exchange of KV over NCCL p2p, apex/transformer + TE integration) and
DeepSpeed-Ulysses-style head/sequence all-to-all. TPU mapping:

- ``ring_attention``: Q/K/V are sequence-sharded over a mesh axis; KV
  chunks circulate the ring with ``ppermute`` (neighbor DMA on ICI) inside
  ``lax.scan`` while each hop's flash partials (o_t, lse_t) merge via the
  online-softmax rule. The merge needs per-chunk logsumexps WITH exact
  gradients — ops/attention.py::flash_attention_with_lse provides them
  (the lse cotangent folds into the flash backward's delta term), so the
  whole ring is reverse-differentiable with plain autodiff: the scan
  transpose reverses the ring, which is exactly the backward KV pass the
  reference implements by hand.
- ``ulysses_attention``: two ``all_to_all``s re-shard [heads, seq_local] ->
  [heads_local, seq] around a normal full-sequence flash call. Cheaper
  than the ring when heads >= ring size (one collective pair instead of
  C-1 hops) but caps the parallelism at the head count.

Both run inside ``shard_map`` over a named axis (e.g. "context"). Causal
masking uses global positions, so results equal single-device causal
attention on the gathered sequence.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.ops.attention import flash_attention, flash_attention_with_lse

_NEG = -1e30


def _merge(o_a, lse_a, o_b, lse_b):
    """Online-softmax merge of two normalized partials (fp32)."""
    m = jnp.maximum(lse_a, lse_b)
    # guard fully-masked rows (both lse ~ -1e30): shift so exp() is finite
    m = jnp.maximum(m, _NEG)
    wa = jnp.exp(lse_a - m)
    wb = jnp.exp(lse_b - m)
    denom = wa + wb
    o = (o_a * wa[..., None] + o_b * wb[..., None]) / denom[..., None]
    return o, m + jnp.log(denom)


def ring_attention(q, k, v, axis: str, *, causal: bool = False,
                   scale: float | None = None, use_pallas: bool | None = None):
    """Exact attention over a sequence sharded along ``axis``.

    q, k, v: [..., s_local, d] — the LOCAL sequence chunk (global sequence
    = concatenation over ring ranks in axis order). Must be called inside
    ``shard_map``. Returns the local chunk of the attention output.

    Causal masking is positional per hop: the diagonal chunk masks
    in-kernel, below-diagonal chunks run unmasked, above-diagonal chunks
    skip the flash call entirely (lax.switch on the chunk index). The KV
    rotation is C-1 ``ppermute`` neighbor hops (the local chunk is
    processed before any communication), overlapped with compute by XLA's
    latency-hiding scheduler.
    """
    c = lax.axis_size(axis)
    r = lax.axis_index(axis)
    perm = [(i, (i + 1) % c) for i in range(c)]

    def attend(k_t, v_t, src):
        """(o_t, lse_t) for the KV chunk with global index ``src``. Causal
        masking is positional per chunk: the diagonal chunk uses the
        in-kernel causal mask (no bias materialization), chunks entirely
        below the diagonal are unmasked, chunks above contribute nothing
        (no flash call at all)."""
        if not causal:
            return flash_attention_with_lse(
                q, k_t, v_t, causal=False, scale=scale, use_pallas=use_pallas)

        def diag(_):
            return flash_attention_with_lse(
                q, k_t, v_t, causal=True, scale=scale, use_pallas=use_pallas)

        def below(_):
            return flash_attention_with_lse(
                q, k_t, v_t, causal=False, scale=scale, use_pallas=use_pallas)

        def above(_):  # fully masked — skip the compute entirely
            return (jnp.zeros(q.shape, q.dtype),
                    jnp.full(q.shape[:-1], _NEG, jnp.float32))

        idx = jnp.where(src == r, 0, jnp.where(src < r, 1, 2))
        return lax.switch(idx, [diag, below, above], None)

    # hop 0 is the LOCAL (diagonal) chunk — no communication
    o0, lse0 = attend(k, v, r)
    o0 = o0.astype(jnp.float32)

    def hop(carry, t):
        k_t, v_t, o_acc, lse_acc = carry
        # rotate FIRST: c-1 ppermutes total, none wasted
        k_n = lax.ppermute(k_t, axis, perm)
        v_n = lax.ppermute(v_t, axis, perm)
        src = (r - t) % c  # global KV chunk index after t rotations
        o_t, lse_t = attend(k_n, v_n, src)
        o_m, lse_m = _merge(o_acc, lse_acc, o_t.astype(jnp.float32), lse_t)
        return (k_n, v_n, o_m, lse_m), None

    if c > 1:
        (_, _, o, _), _ = lax.scan(
            hop, (k, v, o0, lse0), jnp.arange(1, c)
        )
    else:
        o = o0
    return o.astype(q.dtype)


def ulysses_attention(q, k, v, axis: str, *, causal: bool = False,
                      scale: float | None = None,
                      use_pallas: bool | None = None):
    """All-to-all context parallelism (DeepSpeed-Ulysses style).

    q, k, v: [b, h, s_local, d] inside ``shard_map`` with the sequence
    sharded over ``axis``; h must be divisible by the axis size. Re-shards
    to [b, h_local, s_global, d], runs normal (flash) attention, and
    re-shards back. Exact for causal and bidirectional.
    """
    c = lax.axis_size(axis)
    assert q.shape[1] % c == 0, (
        f"heads {q.shape[1]} not divisible by context axis size {c}")
    # GQA passes through (flash_attention shares KV across the group),
    # but the all-to-all must still split the KV head axis evenly. When
    # it can't (hkv < ring size, e.g. llama3 8 KV heads on cp=16), use
    # ring_attention, whose KV rotation never splits the head axis.
    assert k.shape[1] % c == 0, (
        f"kv heads {k.shape[1]} not divisible by context axis size {c}; "
        f"use ring_attention for GQA shapes with fewer kv heads than the "
        f"context axis")

    def to_seq(x):  # [b, h, s_loc, d] -> [b, h/c, s_glob, d]
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    def to_heads(x):  # [b, h/c, s_glob, d] -> [b, h, s_loc, d]
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    o = flash_attention(
        to_seq(q), to_seq(k), to_seq(v), causal=causal, scale=scale,
        use_pallas=use_pallas,
    )
    return to_heads(o)
