"""Model-parallel topology state — the mesh-backed analog of process groups.

Ref: apex/transformer/parallel_state.py::initialize_model_parallel and the
rank/world-size getters over _TENSOR_MODEL_PARALLEL_GROUP /
_PIPELINE_MODEL_PARALLEL_GROUP / _DATA_PARALLEL_GROUP etc.

The reference enumerates global ranks into NCCL communicators per parallel
dimension. Under single-controller SPMD none of that machinery exists: one
``jax.sharding.Mesh`` with axes ("stage", "data", "model") IS the 3D
decomposition, and "my rank in group G" is ``lax.axis_index(axis)`` inside a
mapped computation. This module keeps the reference's API shape so Megatron-
style model code ports mechanically:

  * world sizes are static mesh properties — callable anywhere;
  * ranks are *traced* values — callable only inside shard_map/pmap/pjit
    bodies (where an axis binding exists), mirroring how the reference's
    rank getters are only meaningful after torch.distributed init;
  * virtual-pipeline bookkeeping (used by the interleaved schedule) is plain
    host state, exactly like the reference's globals.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import jax
from jax import lax
from jax.sharding import Mesh

from apex_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    STAGE_AXIS,
    make_mesh,
)

_state: Optional["ParallelState"] = None


@dataclasses.dataclass
class ParallelState:
    """Everything initialize_model_parallel computed, mesh-ified."""

    mesh: Mesh
    tensor_axis: str = MODEL_AXIS
    pipeline_axis: str = STAGE_AXIS
    data_axis: str = DATA_AXIS
    virtual_pipeline_model_parallel_size: Optional[int] = None
    pipeline_model_parallel_split_rank: Optional[int] = None
    # Host-side cursor used by the interleaved schedule, mirroring the
    # reference's _VIRTUAL_PIPELINE_MODEL_PARALLEL_RANK global.
    virtual_pipeline_model_parallel_rank: Optional[int] = None

    def axis_size(self, axis: str) -> int:
        return self.mesh.shape[axis] if axis in self.mesh.axis_names else 1


def initialize_model_parallel(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_split_rank: Optional[int] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    mesh: Optional[Mesh] = None,
) -> ParallelState:
    """Build (or adopt) the mesh for a TPxPPxDP decomposition.

    Ref signature: parallel_state.py::initialize_model_parallel(
    tensor_model_parallel_size_, pipeline_model_parallel_size_, virtual...,
    pipeline_model_parallel_split_rank_). The reference's ``default_backend``/
    ``p2p_backend`` (nccl|ucc) selectors have no analog: XLA picks the
    transport (ICI/DCN) from the mesh layout.

    DP size is inferred as n_devices / (tp * pp), like the reference.
    """
    global _state
    if virtual_pipeline_model_parallel_size is not None:
        if pipeline_model_parallel_size < 2:
            raise ValueError(
                "virtual pipeline parallelism requires pipeline_model_parallel_size >= 2"
            )
    if mesh is None:
        mesh = make_mesh(
            {
                STAGE_AXIS: pipeline_model_parallel_size,
                DATA_AXIS: -1,
                MODEL_AXIS: tensor_model_parallel_size,
            },
            devices=devices,
        )
    _state = ParallelState(
        mesh=mesh,
        virtual_pipeline_model_parallel_size=virtual_pipeline_model_parallel_size,
        pipeline_model_parallel_split_rank=pipeline_model_parallel_split_rank,
        virtual_pipeline_model_parallel_rank=(
            0 if virtual_pipeline_model_parallel_size is not None else None
        ),
    )
    return _state


def model_parallel_is_initialized() -> bool:
    """Ref: parallel_state.py::model_parallel_is_initialized."""
    return _state is not None


def get_state() -> ParallelState:
    if _state is None:
        raise RuntimeError(
            "model parallel state is not initialized; call "
            "initialize_model_parallel() first"
        )
    return _state


def get_mesh() -> Mesh:
    return get_state().mesh


def destroy_model_parallel() -> None:
    """Ref: parallel_state.py::destroy_model_parallel."""
    global _state
    _state = None


# -- axis names (the "group" handles) ------------------------------------

def get_tensor_model_parallel_group() -> str:
    """The reference returns an NCCL communicator; we return the axis name —
    the thing every collective in this library takes in its place."""
    return get_state().tensor_axis


def get_pipeline_model_parallel_group() -> str:
    return get_state().pipeline_axis


def get_data_parallel_group() -> str:
    return get_state().data_axis


def get_model_parallel_group() -> tuple:
    """TP x PP combined (ref: _MODEL_PARALLEL_GROUP)."""
    s = get_state()
    return (s.pipeline_axis, s.tensor_axis)


# -- world sizes (static, callable anywhere) ------------------------------

def get_tensor_model_parallel_world_size() -> int:
    s = get_state()
    return s.axis_size(s.tensor_axis)


def get_pipeline_model_parallel_world_size() -> int:
    s = get_state()
    return s.axis_size(s.pipeline_axis)


def get_data_parallel_world_size() -> int:
    s = get_state()
    return s.axis_size(s.data_axis)


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return get_state().virtual_pipeline_model_parallel_size


# -- ranks (traced; inside mapped computations only) -----------------------

def get_tensor_model_parallel_rank():
    s = get_state()
    return lax.axis_index(s.tensor_axis)


def get_pipeline_model_parallel_rank():
    s = get_state()
    return lax.axis_index(s.pipeline_axis)


def get_data_parallel_rank():
    s = get_state()
    return lax.axis_index(s.data_axis)


def get_tensor_model_parallel_src_rank() -> int:
    """Ref: global rank of tp-rank-0 in my TP group. Under SPMD the src is
    simply index 0 along the tensor axis."""
    return 0


def is_pipeline_first_stage(ignore_virtual: bool = False):
    """Traced bool. Ref: parallel_state.py::is_pipeline_first_stage."""
    s = get_state()
    first = lax.axis_index(s.pipeline_axis) == 0
    if not ignore_virtual and s.virtual_pipeline_model_parallel_size is not None:
        if s.virtual_pipeline_model_parallel_rank != 0:
            return first & False
    return first


def is_pipeline_last_stage(ignore_virtual: bool = False):
    s = get_state()
    last = lax.axis_index(s.pipeline_axis) == s.axis_size(s.pipeline_axis) - 1
    if not ignore_virtual and s.virtual_pipeline_model_parallel_size is not None:
        vp = s.virtual_pipeline_model_parallel_size
        if s.virtual_pipeline_model_parallel_rank != vp - 1:
            return last & False
    return last


def set_virtual_pipeline_model_parallel_rank(rank: Optional[int]) -> None:
    get_state().virtual_pipeline_model_parallel_rank = rank


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    return get_state().virtual_pipeline_model_parallel_rank


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    return get_state().pipeline_model_parallel_split_rank
