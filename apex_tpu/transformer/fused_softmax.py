"""FusedScaleMaskSoftmax — the attention-softmax front door.

Ref: apex/transformer/functional/fused_softmax.py::FusedScaleMaskSoftmax —
routes to scaled_upper_triang_masked_softmax_cuda (causal) /
scaled_masked_softmax_cuda (padding) / scaled_softmax_cuda (no mask) when the
CUDA kernels' constraints hold, else a torch fallback.

On TPU there is no eligibility gate: the jnp softmax family
(apex_tpu.ops.softmax) fuses under XLA for any shape/dtype, so the
"kernel" path is always taken; ``is_kernel_available`` is kept (always True
for supported dtypes) so ported callers behave identically.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax.numpy as jnp

from apex_tpu.ops.softmax import (
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.transformer.enums import AttnMaskType


@dataclasses.dataclass
class FusedScaleMaskSoftmax:
    """fused operation: scaling + mask + softmax.

    Arguments mirror the reference ctor:
      input_in_fp16/bf16: declared activation dtype (validated at call)
      attn_mask_type: AttnMaskType.padding | AttnMaskType.causal
      scaled_masked_softmax_fusion: kept for parity; fusion is XLA's job
      mask_func: fallback mask function (applied when mask given and the
        generic path runs), e.g. lambda x, m: x.masked_fill(m, -10000)
      softmax_in_fp32: compute softmax in fp32 (the kernels always do)
      scale: logit scale factor
    """

    input_in_fp16: bool = False
    input_in_bf16: bool = False
    attn_mask_type: AttnMaskType = AttnMaskType.padding
    scaled_masked_softmax_fusion: bool = True
    mask_func: Optional[Callable] = None
    softmax_in_fp32: bool = True
    scale: Optional[float] = None

    def __post_init__(self):
        if self.input_in_fp16 and self.input_in_bf16:
            raise ValueError("both fp16 and bf16 flags cannot be active")
        if self.scale is not None and not self.softmax_in_fp32:
            raise ValueError("softmax should be in fp32 when scaled (ref asserts)")

    @property
    def input_in_float16(self) -> bool:
        return self.input_in_fp16 or self.input_in_bf16

    def is_kernel_available(self, mask, b, np_, sq, sk) -> bool:
        """The reference gates on dtype, 16 < sk <= 4096, sk % 4 == 0, etc.
        XLA has no such constraints; report True for float16/bfloat16 inputs
        (the only dtypes the CUDA kernels accept)."""
        return self.scaled_masked_softmax_fusion and self.input_in_float16

    def __call__(self, x, mask=None):
        scale = self.scale if self.scale is not None else 1.0
        orig_dtype = x.dtype
        if self.softmax_in_fp32:
            x = x.astype(jnp.float32)

        if self.attn_mask_type == AttnMaskType.causal:
            # the reference's causal kernel ignores the mask argument
            probs = scaled_upper_triang_masked_softmax(x, scale)
        elif mask is not None:
            if self.mask_func is not None and not self.input_in_float16:
                probs = scaled_softmax(self.mask_func(x * scale, mask), 1.0)
            else:
                probs = scaled_masked_softmax(x, mask, scale)
        else:
            probs = scaled_softmax(x, scale)

        if self.softmax_in_fp32 and self.input_in_float16:
            probs = probs.astype(orig_dtype)
        return probs


class GenericScaledMaskedSoftmax(FusedScaleMaskSoftmax):
    """Arbitrary-mask variant (ref: generic_scaled_masked_softmax_cuda) —
    identical math on TPU; exists for import parity."""
