"""Model-parallel-aware grad scaler.

Ref: apex/transformer/amp/grad_scaler.py::GradScaler — subclasses
torch.cuda.amp.GradScaler and all-reduces found_inf across the model-parallel
group so every TP/PP rank skips the same steps.

Here the same contract over apex_tpu.amp.LossScaler: ``unscale`` additionally
MAX-reduces found_inf over the model axes when called inside a mapped
computation. Under pure GSPMD/pjit the overflow flag is computed on global
arrays and is already consistent — the sync matters for shard_map training
loops where each model shard sees only its local grads.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import lax

from apex_tpu.amp.scaler import LossScaler, ScalerState

Axis = Union[str, Sequence[str]]


def sync_found_inf(found_inf, axes: Axis):
    """MAX-reduce the overflow flag over ``axes`` (ref: the all_reduce in
    GradScaler._unscale_grads_)."""
    return lax.pmax(found_inf.astype(jnp.float32), axes) > 0


@dataclasses.dataclass(frozen=True)
class GradScaler(LossScaler):
    """LossScaler whose overflow decision is agreed across model axes.

    ``model_parallel_axes`` defaults to ("stage", "model") — the reference's
    _MODEL_PARALLEL_GROUP (TP x PP).
    """

    model_parallel_axes: Tuple[str, ...] = ("stage", "model")

    def unscale(self, state: ScalerState, grads, *, in_mapped_context: bool = True):
        grads32, found_inf = super().unscale(state, grads)
        if in_mapped_context and self.model_parallel_axes:
            found_inf = sync_found_inf(found_inf, tuple(self.model_parallel_axes))
        return grads32, found_inf
