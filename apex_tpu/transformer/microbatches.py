"""Microbatch calculators.

Ref: apex/transformer/microbatches.py::build_num_microbatches_calculator,
::ConstantNumMicroBatches, ::RampupBatchsizeNumMicroBatches. Pure host-side
bookkeeping — ported semantics, no device code.
"""

from __future__ import annotations

from typing import Optional, Sequence


class NumMicroBatchesCalculator:
    def __init__(self):
        self.num_micro_batches: Optional[int] = None
        self.current_global_batch_size: Optional[int] = None

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check) -> None:
        raise NotImplementedError


class ConstantNumMicroBatchesCalculator(NumMicroBatchesCalculator):
    """Ref: microbatches.py::ConstantNumMicroBatches."""

    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        super().__init__()
        micro_batch_times_dp = micro_batch_size * data_parallel_size
        if global_batch_size % micro_batch_times_dp:
            raise ValueError(
                f"global batch size ({global_batch_size}) is not divisible by "
                f"micro batch size ({micro_batch_size}) times data parallel "
                f"size ({data_parallel_size})"
            )
        self.num_micro_batches = global_batch_size // micro_batch_times_dp
        if self.num_micro_batches < 1:
            raise ValueError("num_micro_batches must be >= 1")
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def update(self, consumed_samples, consistency_check) -> None:
        pass


class RampupBatchsizeNumMicroBatchesCalculator(NumMicroBatchesCalculator):
    """Linear global-batch-size ramp (ref: RampupBatchsizeNumMicroBatches).

    Batch size grows from ``start_batch_size`` by ``batch_size_increment``
    every ``ramup_samples / steps`` consumed samples, where
    steps = (global_batch_size - start_batch_size) / batch_size_increment.
    """

    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 ramup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int):
        super().__init__()
        if batch_size_increment <= 0:
            raise ValueError("batch_size_increment must be positive")
        if ramup_samples < 0:
            raise ValueError("ramup_samples must be non-negative")
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size
        )
        if start_batch_size % self.micro_batch_times_data_parallel_size:
            raise ValueError(
                "start batch size must be divisible by micro-batch * dp size"
            )

        diff = global_batch_size - start_batch_size
        if diff < 0:
            raise ValueError("global batch size must be >= start batch size")
        if diff % batch_size_increment:
            raise ValueError(
                f"expected global batch size interval ({diff}) to be divisible "
                f"by batch size increment ({batch_size_increment})"
            )
        num_increments = diff // batch_size_increment
        self.rampup_samples_per_increment = (
            self.ramup_samples / num_increments if num_increments > 0 else 0
        )
        self.update(0, False)

    def update(self, consumed_samples: int, consistency_check: bool) -> None:
        if (self.rampup_samples_per_increment == 0
                or consumed_samples > self.ramup_samples):
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment
            )
            self.current_global_batch_size = min(
                self.current_global_batch_size, self.global_batch_size
            )
        if consistency_check:
            if self.current_global_batch_size % \
                    self.micro_batch_times_data_parallel_size:
                raise ValueError(
                    f"current global batch size "
                    f"({self.current_global_batch_size}) is not divisible by "
                    "micro-batch-size * data-parallel-size"
                )
        self.num_micro_batches = (
            self.current_global_batch_size
            // self.micro_batch_times_data_parallel_size
        )


def build_num_microbatches_calculator(
    rank: int = 0,
    rampup_batch_size: Optional[Sequence[int]] = None,
    global_batch_size: int = 1,
    micro_batch_size: int = 1,
    data_parallel_size: int = 1,
) -> NumMicroBatchesCalculator:
    """Ref: microbatches.py::build_num_microbatches_calculator.

    ``rampup_batch_size`` is the Megatron triple
    [start_batch_size, increment, ramup_samples] or None for constant.
    """
    if rampup_batch_size is None:
        return ConstantNumMicroBatchesCalculator(
            global_batch_size, micro_batch_size, data_parallel_size
        )
    if len(rampup_batch_size) != 3:
        raise ValueError(
            "expected the following format: --rampup-batch-size <start batch "
            "size> <batch size increment> <ramp-up samples>"
        )
    return RampupBatchsizeNumMicroBatchesCalculator(
        int(rampup_batch_size[0]),
        int(rampup_batch_size[1]),
        int(rampup_batch_size[2]),
        global_batch_size,
        micro_batch_size,
        data_parallel_size,
    )
