"""Megatron-style model parallelism, TPU-native.

Ref: apex/transformer/* (SURVEY.md §3.9). The reference manages NCCL process
groups for a 3D (TP x PP x DP) decomposition; here a single named
``jax.sharding.Mesh`` plus SPMD collectives replaces all group bookkeeping.
"""

from apex_tpu.transformer import context_parallel
from apex_tpu.transformer import moe
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import pipeline_parallel
from apex_tpu.transformer import tensor_parallel
from apex_tpu.transformer.context_parallel import (
    ring_attention,
    ulysses_attention,
)
from apex_tpu.transformer.moe import (
    MoEConfig,
    moe_apply,
    moe_init,
)
from apex_tpu.transformer.enums import AttnType, AttnMaskType, LayerType, ModelType
from apex_tpu.transformer.fused_softmax import (
    FusedScaleMaskSoftmax,
    GenericScaledMaskedSoftmax,
)
from apex_tpu.transformer.grad_scaler import GradScaler
from apex_tpu.transformer.log_util import (  # noqa: F401
    get_transformer_logger,
    set_logging_level,
)
from apex_tpu.transformer.microbatches import (
    build_num_microbatches_calculator,
    ConstantNumMicroBatchesCalculator,
    RampupBatchsizeNumMicroBatchesCalculator,
)

__all__ = [
    "parallel_state",
    "pipeline_parallel",
    "tensor_parallel",
    "AttnType",
    "AttnMaskType",
    "LayerType",
    "ModelType",
    "FusedScaleMaskSoftmax",
    "GenericScaledMaskedSoftmax",
    "GradScaler",
    "build_num_microbatches_calculator",
    "ConstantNumMicroBatchesCalculator",
    "RampupBatchsizeNumMicroBatchesCalculator",
]
