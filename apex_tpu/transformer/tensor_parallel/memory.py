"""GlobalMemoryBuffer (ref: apex/transformer/tensor_parallel/memory.py).

The reference hand-recycles large activation buffers to dodge the CUDA
caching allocator. Under XLA, buffer reuse is the compiler's job (donation +
liveness analysis), so the TPU-correct implementation is an API shim that
returns freshly-traced zeros — inside jit these become XLA temporaries that
the compiler already aliases and reuses. Kept so Megatron-style ports run
unchanged.
"""

from __future__ import annotations

import jax.numpy as jnp


class GlobalMemoryBuffer:
    """Ref: memory.py::GlobalMemoryBuffer.get_tensor(shape, dtype, name)."""

    def get_tensor(self, tensor_shape, dtype, name):
        del name  # XLA names/aliases buffers itself
        return jnp.zeros(tensor_shape, dtype)


_GLOBAL_MEMORY_BUFFER = GlobalMemoryBuffer()


def get_global_memory_buffer() -> GlobalMemoryBuffer:
    return _GLOBAL_MEMORY_BUFFER
