"""Tensor-parallel layers.

Ref: apex/transformer/tensor_parallel/layers.py::VocabParallelEmbedding,
::ColumnParallelLinear, ::RowParallelLinear,
::LinearWithGradAccumulationAndAsyncCommunication.

Two API levels, both first-class:

1. **Functional, shard-local** (``column_parallel_linear`` & co.): run inside
   a ``shard_map`` body over the tensor axis with explicitly sharded weight
   shards — the direct analog of the reference's per-rank modules, and the
   form the parity tests pin down collective-by-collective.
2. **Flax modules** (``ColumnParallelLinear`` & co.): GSPMD-style modules
   whose params carry ``nn.with_partitioning`` metadata; under pjit on a
   mesh, XLA inserts the same collectives automatically.

Reference knobs with no TPU analog (documented, accepted, ignored):
  * ``async_tensor_model_parallel_allreduce`` / the side-stream overlap in
    LinearWithGradAccumulationAndAsyncCommunication — XLA's async
    collectives overlap comm with the wgrad matmul without manual streams.
  * ``gradient_accumulation_fusion`` (fused_weight_gradient_mlp_cuda's fp32
    main_grad accumulation) — weight-grad matmuls here always accumulate in
    fp32 on the MXU (``preferred_element_type``); cross-microbatch
    accumulation in fp32 is the optimizer/master-weights engine's job.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.mesh import MODEL_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.utils import divide

try:
    import flax.linen as nn

    _HAVE_FLAX = True
except ImportError:  # pragma: no cover
    _HAVE_FLAX = False


def _matmul(x, kernel):
    """Shard-local GEMM with fp32 MXU accumulation, result in input dtype.

    Under an active amp policy with the ``matmul_quant`` override
    (O2_INT8), the unambiguous ``[..., m, k] @ [k, n]`` projection routes
    through the blockwise-scaled ``quantization.quant_matmul`` instead —
    the explicit call site the autocast interceptor cannot reach (the
    ``preferred_element_type`` kwarg disqualifies generic interception),
    so the planner's quant gate applies to the TP column/row stack too.
    Gate off (no policy, or ``matmul_quant=None``) this lowers
    byte-identical HLO to the plain GEMM (pinned by
    tests/L0/run_transformer/test_layers.py)."""
    from apex_tpu.amp.autocast import active_matmul_quant, autocast

    quant = active_matmul_quant()
    if quant is not None and kernel.ndim == 2 and x.ndim >= 2 \
            and x.shape[-1] == kernel.shape[0]:
        from apex_tpu.quantization import quant_matmul

        # casts-disabled: the quant path's own jnp internals must not
        # re-enter the autocast interceptor (amp/autocast.py does the
        # same around its quant route)
        with autocast(enabled=False):
            return quant_matmul(x, kernel, dtype=quant[0],
                                bwd_quant=quant[1])
    return jnp.matmul(x, kernel, preferred_element_type=jnp.float32).astype(
        jnp.result_type(x, kernel)
    )


# -- functional (shard_map-local) forms -----------------------------------

def column_parallel_linear(
    x,
    kernel,
    bias=None,
    *,
    axis: str = MODEL_AXIS,
    gather_output: bool = True,
    sequence_parallel_enabled: bool = False,
):
    """Y = XA + b with A column-split: local ``kernel`` is [in, out/tp].

    Ref: layers.py::ColumnParallelLinear.forward. With
    ``sequence_parallel_enabled`` the input arrives seq-sharded [s/tp, b, in]
    and is all-gathered here (bwd: reduce-scatter) — Megatron SP.
    """
    if sequence_parallel_enabled:
        if gather_output:
            raise ValueError(
                "gather_output is incompatible with sequence parallelism (ref "
                "asserts the same)"
            )
        from apex_tpu.amp.autocast import active_matmul_quant
        from apex_tpu.parallel import overlap

        if overlap.overlap_tp_enabled() and active_matmul_quant() is None:
            # decomposed collective matmul: the seq-dim all-gather and the
            # GEMM become one ppermute-pipelined op (ring chunks each
            # overlapped with a partial matmul); its custom_vjp decomposes
            # the backward reduce-scatter symmetrically. The decomposed
            # ring computes at FULL width, so an active matmul_quant
            # policy (O2_INT8) takes precedence: monolithic collective +
            # quant_matmul via _matmul rather than silently dropping the
            # requested int8 compute — which combination wins on hardware
            # is an A/B to measure.
            y = overlap.all_gather_matmul(x, kernel, axis, 0, None)
        else:
            x = gather_from_sequence_parallel_region(
                x, axis, True  # tensor_parallel_output_grad
            )
            y = _matmul(x, kernel)
    else:
        x = copy_to_tensor_model_parallel_region(x, axis)
        y = _matmul(x, kernel)
    if bias is not None:
        y = y + bias
    if gather_output:
        y = gather_from_tensor_model_parallel_region(y, axis)
    return y


def row_parallel_linear(
    x,
    kernel,
    bias=None,
    *,
    axis: str = MODEL_AXIS,
    input_is_parallel: bool = True,
    sequence_parallel_enabled: bool = False,
):
    """Y = XA + b with A row-split: local ``kernel`` is [in/tp, out].

    Ref: layers.py::RowParallelLinear.forward. The local GEMM yields partial
    sums; they are all-reduced (or reduce-scattered along seq under SP).
    Bias is added *after* the reduction, once, like the reference.
    """
    if not input_is_parallel:
        if sequence_parallel_enabled:
            raise ValueError(
                "sequence parallelism requires input_is_parallel (ref asserts)"
            )
        x = scatter_to_tensor_model_parallel_region(x, axis)
    if sequence_parallel_enabled:
        from apex_tpu.amp.autocast import active_matmul_quant
        from apex_tpu.parallel import overlap

        if overlap.overlap_tp_enabled() and active_matmul_quant() is None:
            # decomposed collective matmul: only the destination slice of
            # the product is computed per ring step, pipelined against the
            # partial-sum ppermutes (see parallel/overlap.py). An active
            # matmul_quant policy wins over the full-width ring — see the
            # column path's rationale.
            y = overlap.matmul_reduce_scatter(x, kernel, axis, 0, None)
        else:
            y = reduce_scatter_to_sequence_parallel_region(
                _matmul(x, kernel), axis)
    else:
        y = reduce_from_tensor_model_parallel_region(_matmul(x, kernel), axis)
    if bias is not None:
        y = y + bias
    return y


def vocab_parallel_embedding(ids, table, *, axis: str = MODEL_AXIS,
                             reduce_output: bool = True):
    """Embedding lookup over a vocab-split table: local ``table`` is
    [vocab/tp, h]; out-of-range ids contribute zero and the partial
    embeddings are all-reduced.

    ``reduce_output=False`` returns the per-rank PARTIAL embeddings so a
    sequence-parallel caller can combine with a seq-dim reduce_scatter
    instead (Megatron SP: the combine IS the scatter; its backward
    all_gather hands every rank the full-sequence cotangent, keeping the
    vocab-shard grads complete).

    Ref: layers.py::VocabParallelEmbedding.forward (mask input, zero masked
    rows, reduce_from_tensor_model_parallel_region).
    """
    n_local = table.shape[0]
    start = lax.axis_index(axis) * n_local
    local = ids - start
    in_range = (local >= 0) & (local < n_local)
    safe = jnp.clip(local, 0, n_local - 1)
    emb = jnp.take(table, safe, axis=0)
    emb = jnp.where(in_range[..., None], emb, 0)
    if not reduce_output:
        return emb
    return reduce_from_tensor_model_parallel_region(emb, axis)


# -- flax/GSPMD modules ----------------------------------------------------

if _HAVE_FLAX:

    def _init(fn, spec):
        return nn.with_partitioning(fn, spec)

    class ColumnParallelLinear(nn.Module):
        """GSPMD ColumnParallelLinear: kernel sharded (None, "model").

        Under pjit over a mesh with a "model" axis, XLA derives the same
        collectives the functional form issues explicitly. ``gather_output``
        is expressed as an output sharding constraint.
        """

        features: int
        use_bias: bool = True
        gather_output: bool = True
        dtype: Any = None
        param_dtype: Any = jnp.float32
        kernel_init: Callable = nn.initializers.lecun_normal()
        bias_init: Callable = nn.initializers.zeros_init()
        axis: str = MODEL_AXIS

        @nn.compact
        def __call__(self, x):
            kernel = self.param(
                "kernel",
                _init(self.kernel_init, (None, self.axis)),
                (x.shape[-1], self.features),
                self.param_dtype,
            )
            bias = (
                self.param(
                    "bias",
                    _init(self.bias_init, (self.axis,)),
                    (self.features,),
                    self.param_dtype,
                )
                if self.use_bias
                else None
            )
            x, kernel = nn.dtypes.promote_dtype(x, kernel, dtype=self.dtype)[:2]
            y = _matmul(x, kernel)
            if bias is not None:
                y = y + bias.astype(y.dtype)
            # gather_output=False leaves y sharded (.., "model") — which GSPMD
            # already derives from the kernel sharding; gather_output=True is a
            # replication constraint so downstream non-parallel ops see full y.
            if self.gather_output:
                mesh = jax.sharding.get_abstract_mesh()
                if mesh is not None and not mesh.empty:
                    y = jax.lax.with_sharding_constraint(
                        y, jax.sharding.PartitionSpec()
                    )
            return y

    class RowParallelLinear(nn.Module):
        """GSPMD RowParallelLinear: kernel sharded ("model", None)."""

        features: int
        use_bias: bool = True
        input_is_parallel: bool = True
        dtype: Any = None
        param_dtype: Any = jnp.float32
        kernel_init: Callable = nn.initializers.lecun_normal()
        bias_init: Callable = nn.initializers.zeros_init()
        axis: str = MODEL_AXIS

        @nn.compact
        def __call__(self, x):
            kernel = self.param(
                "kernel",
                _init(self.kernel_init, (self.axis, None)),
                (x.shape[-1], self.features),
                self.param_dtype,
            )
            bias = (
                self.param(
                    "bias", self.bias_init, (self.features,), self.param_dtype
                )
                if self.use_bias
                else None
            )
            x, kernel = nn.dtypes.promote_dtype(x, kernel, dtype=self.dtype)[:2]
            y = _matmul(x, kernel)
            if bias is not None:
                y = y + bias.astype(y.dtype)
            return y

    class VocabParallelEmbedding(nn.Module):
        """GSPMD vocab-parallel embedding: table sharded ("model", None)."""

        num_embeddings: int
        features: int
        dtype: Any = None
        param_dtype: Any = jnp.float32
        embedding_init: Callable = nn.initializers.normal(stddev=1.0)
        axis: str = MODEL_AXIS

        @nn.compact
        def __call__(self, ids):
            table = self.param(
                "embedding",
                _init(self.embedding_init, (self.axis, None)),
                (self.num_embeddings, self.features),
                self.param_dtype,
            )
            (table,) = nn.dtypes.promote_dtype(table, dtype=self.dtype)
            return jnp.take(table, ids, axis=0)
