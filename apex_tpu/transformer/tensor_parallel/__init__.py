"""Tensor parallelism. Ref: apex/transformer/tensor_parallel/__init__.py."""

from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.data import broadcast_data
from apex_tpu.transformer.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.random import (
    RNGStatesTracker,
    checkpoint,
    get_cuda_rng_tracker,
    model_parallel_manual_seed,
    model_parallel_seed,
)
from apex_tpu.transformer.tensor_parallel.utils import (
    VocabUtility,
    divide,
    ensure_divisibility,
    gather_split_1d_tensor,
    split_tensor_along_last_dim,
    split_tensor_into_1d_equal_chunks,
)

try:
    from apex_tpu.transformer.tensor_parallel.layers import (
        ColumnParallelLinear,
        RowParallelLinear,
        VocabParallelEmbedding,
    )
except ImportError:  # pragma: no cover - flax not installed
    pass

__all__ = [
    "vocab_parallel_cross_entropy",
    "broadcast_data",
    "column_parallel_linear",
    "row_parallel_linear",
    "vocab_parallel_embedding",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "VocabParallelEmbedding",
    "copy_to_tensor_model_parallel_region",
    "gather_from_sequence_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
    "scatter_to_sequence_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "RNGStatesTracker",
    "checkpoint",
    "get_cuda_rng_tracker",
    "model_parallel_manual_seed",
    "model_parallel_seed",
    "VocabUtility",
    "divide",
    "ensure_divisibility",
    "split_tensor_along_last_dim",
    "split_tensor_into_1d_equal_chunks",
    "gather_split_1d_tensor",
]
