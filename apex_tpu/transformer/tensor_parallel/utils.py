"""TP shape utilities. Ref: apex/transformer/tensor_parallel/utils.py and
apex/transformer/utils.py (divide, split_tensor_along_last_dim, VocabUtility,
split_tensor_into_1d_equal_chunks / gather_split_1d_tensor)."""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
from jax import lax


def ensure_divisibility(numerator: int, denominator: int) -> None:
    """Ref: utils.py::ensure_divisibility."""
    if numerator % denominator != 0:
        raise ValueError(f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    """Ref: utils.py::divide."""
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(x, num_partitions: int) -> Sequence:
    """Ref: utils.py::split_tensor_along_last_dim (contiguous flag is a torch
    detail with no XLA analog)."""
    ensure_divisibility(x.shape[-1], num_partitions)
    return jnp.split(x, num_partitions, axis=-1)


def split_tensor_into_1d_equal_chunks(x, axis: str):
    """Ref: apex/transformer/utils.py::split_tensor_into_1d_equal_chunks —
    this rank's flat chunk (the p2p scatter-gather optimization)."""
    flat = x.reshape(-1)
    n = lax.axis_size(axis)
    chunk = divide(flat.shape[0], n)
    return lax.dynamic_slice_in_dim(flat, lax.axis_index(axis) * chunk, chunk)


def gather_split_1d_tensor(x, axis: str):
    """Ref: apex/transformer/utils.py::gather_split_1d_tensor."""
    return lax.all_gather(x, axis, axis=0, tiled=True)


class VocabUtility:
    """Ref: tensor_parallel/utils.py::VocabUtility — [first, last) vocab range
    owned by a partition."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
        per_partition_vocab_size: int, rank
    ) -> Tuple:
        first = rank * per_partition_vocab_size
        return first, first + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(
        global_vocab_size: int, rank, world_size: int
    ) -> Tuple:
        per_partition = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per_partition, rank
        )
