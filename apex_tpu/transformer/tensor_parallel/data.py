"""TP data broadcast. Ref: apex/transformer/tensor_parallel/data.py::broadcast_data.

The reference moves each batch from tp-rank-0 to the rest of the TP group
(other ranks pass dummy tensors). Under SPMD input batches are *already*
replicated (or sharded) by the sharding of the input arrays, so the common
case is the identity. ``broadcast_data`` exists for shard_map code that
constructs rank-divergent values and needs the reference's semantics.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
from jax import lax


def broadcast_data(keys: Sequence[str], data: Mapping[str, jax.Array], dtype=None,
                   axis: str = "model"):
    """Every rank gets tp-rank-0's value for each key.

    Shapes must match across ranks (the reference ships size metadata first
    for the same reason; under SPMD shapes are static so that step is free).
    """
    out = {}
    for k in keys:
        x = data[k]
        if dtype is not None:
            x = x.astype(dtype)
        idx = lax.axis_index(axis)
        out[k] = lax.psum(jnp.where(idx == 0, x, jnp.zeros_like(x)), axis)
    return out
