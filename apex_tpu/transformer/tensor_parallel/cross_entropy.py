"""Vocab-parallel cross entropy.

Ref: apex/transformer/tensor_parallel/cross_entropy.py::_VocabParallelCrossEntropy
— numerically-stable CE over a vocab-sharded logits tensor:

  1. all-reduce(max) for stability,
  2. each rank gathers target logits for targets in its vocab range (others
     contribute 0), all-reduce(sum) to assemble the predicted logit,
  3. all-reduce(sum of exp) for the partition function,
  4. backward is fully local: softmax - onehot (within this rank's range).

The custom_vjp both pins the reference backward (one local pass, no extra
collective — the incoming grad is replicated across the tensor axis) and
keeps the saved residual to the local softmax shard.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _fwd_core(vocab_parallel_logits, target, axis, label_smoothing):
    x = vocab_parallel_logits.astype(jnp.float32)
    # 1. global max for stability
    logits_max = lax.pmax(jnp.max(x, axis=-1), axis)
    x = x - logits_max[..., None]

    # this rank's [first, last) vocab slice
    partition_vocab_size = x.shape[-1]
    rank = lax.axis_index(axis)
    vocab_start = rank * partition_vocab_size

    # 2. predicted logit: local masked gather, then sum across ranks
    target_local = target - vocab_start
    in_range = (target_local >= 0) & (target_local < partition_vocab_size)
    safe_idx = jnp.clip(target_local, 0, partition_vocab_size - 1)
    picked = jnp.take_along_axis(x, safe_idx[..., None], axis=-1)[..., 0]
    predicted_logit = lax.psum(jnp.where(in_range, picked, 0.0), axis)

    # 3. partition function
    exp_logits = jnp.exp(x)
    sum_exp = lax.psum(jnp.sum(exp_logits, axis=-1), axis)
    log_sum_exp = jnp.log(sum_exp)
    loss = log_sum_exp - predicted_logit

    vocab_size = partition_vocab_size * lax.axis_size(axis)
    if label_smoothing > 0:
        # Ref: smoothing spreads (label_smoothing) mass uniformly over the
        # vocab: loss = (1-eps)*nll + eps * mean_v(-log p_v).
        log_probs = x - log_sum_exp[..., None]
        smoothed = -lax.psum(jnp.sum(log_probs, axis=-1), axis) / vocab_size
        loss = (1.0 - label_smoothing) * loss + label_smoothing * smoothed

    softmax_local = exp_logits / sum_exp[..., None]
    return loss, (softmax_local, in_range, safe_idx)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def vocab_parallel_cross_entropy(
    vocab_parallel_logits, target, axis: str = "model", label_smoothing: float = 0.0
):
    """Per-token CE loss [.., seq] from vocab-sharded logits [.., seq, v/tp].

    ``target`` holds *global* vocab ids. Must run inside a shard_map body
    with ``axis`` bound. Ref: cross_entropy.py::vocab_parallel_cross_entropy.
    """
    loss, _ = _fwd_core(vocab_parallel_logits, target, axis, label_smoothing)
    return loss


def _vce_fwd(vocab_parallel_logits, target, axis, label_smoothing):
    loss, res = _fwd_core(vocab_parallel_logits, target, axis, label_smoothing)
    # zero-size marker array carries the input dtype through the residuals
    # (a bare dtype is not a valid JAX residual type)
    dtype_marker = jnp.zeros((0,), vocab_parallel_logits.dtype)
    return loss, (res, dtype_marker)


def _vce_bwd(axis, label_smoothing, residuals, g):
    (softmax_local, in_range, safe_idx), dtype_marker = residuals
    in_dtype = dtype_marker.dtype
    partition_vocab_size = softmax_local.shape[-1]
    vocab_size = partition_vocab_size * lax.axis_size(axis)

    onehot = (
        jax.nn.one_hot(safe_idx, partition_vocab_size, dtype=jnp.float32)
        * in_range[..., None]
    )
    if label_smoothing > 0:
        grad = (
            softmax_local
            - (1.0 - label_smoothing) * onehot
            - label_smoothing / vocab_size
        )
    else:
        grad = softmax_local - onehot
    grad = grad * g[..., None]
    return grad.astype(in_dtype), None


vocab_parallel_cross_entropy.defvjp(_vce_fwd, _vce_bwd)
