"""Model-parallel RNG streams + activation checkpointing.

Ref: apex/transformer/tensor_parallel/random.py::CudaRNGStatesTracker,
::model_parallel_cuda_manual_seed, ::CheckpointFunction.

The reference juggles mutable per-device CUDA RNG states: a "default" state
shared across TP ranks (so e.g. data augmentations agree) and a
"model-parallel-rng" state offset by tp rank (so dropout masks *differ*
across TP ranks but match across DP). With JAX's counter-based PRNG the same
contract is a pure key-derivation spec — frozen here because checkpoint/
resume and dropout-parity tests depend on it:

  default key        = PRNGKey(seed)
  model-parallel key = fold_in(PRNGKey(seed + 2718), tp_rank)

(2718 mirrors the reference's ``offset = seed + 2718``.)

``checkpoint`` is ``jax.checkpoint``: XLA replays the *same* fold_in chain
during recomputation, so the RNG-replay machinery the reference needs
(fork/restore around recompute) is automatic.
"""

from __future__ import annotations

import contextlib
from typing import NamedTuple

import jax
from jax import lax

_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"
_MODEL_PARALLEL_SEED_OFFSET = 2718  # ref: model_parallel_cuda_manual_seed


class ModelParallelKeys(NamedTuple):
    """The two streams the reference tracks (see module docstring)."""

    default: jax.Array
    model_parallel: jax.Array


def model_parallel_seed(seed: int, axis: str = "model") -> ModelParallelKeys:
    """Derive the two PRNG streams for this rank. Must run where ``axis`` is
    bound (shard_map body). Ref: random.py::model_parallel_cuda_manual_seed."""
    default = jax.random.PRNGKey(seed)
    mp = jax.random.fold_in(
        jax.random.PRNGKey(seed + _MODEL_PARALLEL_SEED_OFFSET),
        lax.axis_index(axis),
    )
    return ModelParallelKeys(default=default, model_parallel=mp)


class RNGStatesTracker:
    """API-parity shim for CudaRNGStatesTracker.

    Holds named key streams; ``fork(name)`` yields a fresh subkey and
    advances the stream. This is trace-time Python bookkeeping over traced
    keys — deterministic, and replayed identically under ``jax.checkpoint``
    recomputation (which is exactly the fork/restore semantics the
    reference implements manually).
    """

    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_ = {}

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name: str, key) -> None:
        if name in self.states_:
            raise ValueError(f"rng state {name} already present")
        if isinstance(key, int):
            key = jax.random.PRNGKey(key)
        self.states_[name] = key

    @contextlib.contextmanager
    def fork(self, name: str = _MODEL_PARALLEL_RNG_TRACKER_NAME):
        if name not in self.states_:
            raise ValueError(f"rng state {name} is not added")
        self.states_[name], sub = jax.random.split(self.states_[name])
        yield sub


_tracker = RNGStatesTracker()


def get_cuda_rng_tracker() -> RNGStatesTracker:
    """Name kept for mechanical ports (ref: random.py::get_cuda_rng_tracker)."""
    return _tracker


def model_parallel_manual_seed(seed: int, axis: str = "model") -> ModelParallelKeys:
    """Seed the global tracker (ref: model_parallel_cuda_manual_seed)."""
    keys = model_parallel_seed(seed, axis)
    _tracker.reset()
    _tracker.add(_MODEL_PARALLEL_RNG_TRACKER_NAME, keys.model_parallel)
    return keys


# Activation recomputation. Ref: random.py::CheckpointFunction — fwd under
# no_grad + RNG snapshot, bwd replays with restored RNG. jax.checkpoint gives
# both (recompute on bwd; PRNG ops replay deterministically).
checkpoint = jax.checkpoint
