"""Autograd-transparent TP collectives.

Ref: apex/transformer/tensor_parallel/mappings.py — the _CopyToModelParallel
Region / _ReduceFromModelParallelRegion / _ScatterToModelParallelRegion /
_GatherFromModelParallelRegion autograd.Functions plus the three
sequence-parallel region functions.

Each mapping is a ``jax.custom_vjp`` whose forward and backward are the
conjugate collective pair the reference hand-writes:

  copy     : fwd identity      / bwd all-reduce
  reduce   : fwd all-reduce    / bwd identity
  scatter  : fwd split last dim/ bwd all-gather last dim
  gather   : fwd all-gather    / bwd split last dim
  SP scatter        : fwd split seq dim       / bwd all-gather seq dim
  SP gather         : fwd all-gather seq dim  / bwd reduce-scatter (or split)
  SP reduce-scatter : fwd reduce-scatter seq  / bwd all-gather seq dim

All functions take the mesh axis name where the reference takes an implicit
process group, and must run inside a shard_map/pmap body. The sequence
dimension is dim 0 ([s, b, h] layout), matching the reference.
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax

SEQ_DIM = 0  # reference uses sequence-first [s, b, h] activations


def _split_along(x, axis: str, dim: int):
    """This rank's equal chunk of ``x`` along ``dim``."""
    n = lax.axis_size(axis)
    if x.shape[dim] % n:
        raise ValueError(f"dim {dim} size {x.shape[dim]} not divisible by {n}")
    chunk = x.shape[dim] // n
    return lax.dynamic_slice_in_dim(x, lax.axis_index(axis) * chunk, chunk, dim)


def _all_gather(x, axis: str, dim: int):
    return lax.all_gather(x, axis, axis=dim, tiled=True)


def _reduce_scatter(x, axis: str, dim: int):
    return lax.psum_scatter(x, axis, scatter_dimension=dim, tiled=True)


# -- sequence-parallel collective routing ----------------------------------
# Behind APEX_TPU_OVERLAP_TP=1 the SP region ops issue their seq-dim
# collectives as chunked ppermute rings (parallel/overlap.py) instead of
# one monolithic all_gather/psum_scatter, so XLA's latency-hiding
# scheduler can interleave the chunk DMAs with neighboring compute. Gate
# off (the default) keeps the exact lax collectives above — bitwise
# identical to the pre-overlap behavior. The fully FUSED
# allgather->matmul / matmul->reduce-scatter decompositions live one
# level up in layers.py, where the matmul operand is in scope.

def _sp_all_gather(x, axis: str):
    from apex_tpu.parallel import overlap

    if overlap.overlap_tp_enabled():
        return overlap.ring_all_gather(x, axis, dim=SEQ_DIM)
    return _all_gather(x, axis, SEQ_DIM)


def _sp_reduce_scatter(x, axis: str):
    from apex_tpu.parallel import overlap

    if overlap.overlap_tp_enabled():
        return overlap.ring_reduce_scatter(x, axis, dim=SEQ_DIM)
    return _reduce_scatter(x, axis, SEQ_DIM)


# -- copy: identity fwd, all-reduce bwd -----------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_model_parallel_region(x, axis: str):
    """Ref: mappings.py::copy_to_tensor_model_parallel_region."""
    return x


def _copy_fwd(x, axis):
    return x, None


def _copy_bwd(axis, _, g):
    return (lax.psum(g, axis),)


copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


# -- reduce: all-reduce fwd, identity bwd ---------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_model_parallel_region(x, axis: str):
    """Ref: mappings.py::reduce_from_tensor_model_parallel_region."""
    return lax.psum(x, axis)


def _reduce_fwd(x, axis):
    return lax.psum(x, axis), None


def _reduce_bwd(axis, _, g):
    return (g,)


reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


# -- scatter/gather along the last (hidden) dim ---------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_tensor_model_parallel_region(x, axis: str):
    """Ref: mappings.py::scatter_to_tensor_model_parallel_region."""
    return _split_along(x, axis, x.ndim - 1)


def _scatter_fwd(x, axis):
    return _split_along(x, axis, x.ndim - 1), None


def _scatter_bwd(axis, _, g):
    return (_all_gather(g, axis, g.ndim - 1),)


scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_tensor_model_parallel_region(x, axis: str):
    """Ref: mappings.py::gather_from_tensor_model_parallel_region."""
    return _all_gather(x, axis, x.ndim - 1)


def _gather_fwd(x, axis):
    return _all_gather(x, axis, x.ndim - 1), None


def _gather_bwd(axis, _, g):
    return (_split_along(g, axis, g.ndim - 1),)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# -- sequence-parallel regions (seq dim 0) --------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_sequence_parallel_region(x, axis: str):
    """Ref: mappings.py::scatter_to_sequence_parallel_region."""
    return _split_along(x, axis, SEQ_DIM)


def _sp_scatter_fwd(x, axis):
    return _split_along(x, axis, SEQ_DIM), None


def _sp_scatter_bwd(axis, _, g):
    return (_sp_all_gather(g, axis),)


scatter_to_sequence_parallel_region.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_sequence_parallel_region(
    x, axis: str, tensor_parallel_output_grad: bool = True
):
    """Ref: mappings.py::gather_from_sequence_parallel_region.

    ``tensor_parallel_output_grad=True`` (the ColumnParallel input path):
    the gathered activation feeds a tensor-parallel matmul, so the incoming
    grad is a *partial sum* per rank and the backward is a reduce-scatter.
    False: the grad is replicated and the backward is a plain split.
    """
    return _sp_all_gather(x, axis)


def _sp_gather_fwd(x, axis, tensor_parallel_output_grad):
    return _sp_all_gather(x, axis), None


def _sp_gather_bwd(axis, tensor_parallel_output_grad, _, g):
    if tensor_parallel_output_grad:
        return (_sp_reduce_scatter(g, axis),)
    return (_split_along(g, axis, SEQ_DIM),)


gather_from_sequence_parallel_region.defvjp(_sp_gather_fwd, _sp_gather_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_scatter_to_sequence_parallel_region(x, axis: str):
    """Ref: mappings.py::reduce_scatter_to_sequence_parallel_region."""
    return _sp_reduce_scatter(x, axis)


def _sp_rs_fwd(x, axis):
    return _sp_reduce_scatter(x, axis), None


def _sp_rs_bwd(axis, _, g):
    return (_sp_all_gather(g, axis),)


reduce_scatter_to_sequence_parallel_region.defvjp(_sp_rs_fwd, _sp_rs_bwd)
