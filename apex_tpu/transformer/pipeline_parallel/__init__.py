"""Pipeline parallelism, TPU-native.

Ref: apex/transformer/pipeline_parallel/* (SURVEY.md §3.9): schedules
(no-pipelining / 1F1B / interleaved-virtual), p2p communication over
``batch_isend_irecv``, and microbatch bookkeeping.

The TPU design replaces per-rank divergent send/recv programs with a single
SPMD program over the mesh ``stage`` axis: activations circulate around the
stage ring via ``lax.ppermute`` inside a ``lax.scan`` of pipeline clock
ticks, and the backward pipeline is obtained by differentiating through the
scan (the transpose of a ``ppermute`` is the reverse rotation, so
``jax.grad`` *is* the reverse schedule). See schedules/common.py.
"""

from apex_tpu.transformer.pipeline_parallel.schedules import (
    get_forward_backward_func,
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    forward_backward_pipelining_with_interleaving,
)
from apex_tpu.transformer.pipeline_parallel import p2p_communication
from apex_tpu.transformer.pipeline_parallel.utils import (
    build_model,
    local_chunk_indices,
    setup_microbatch_calculator,
    get_num_microbatches,
    get_micro_batch_size,
    get_current_global_batch_size,
    update_num_microbatches,
    listify_model,
)

__all__ = [
    "build_model",
    "local_chunk_indices",
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "p2p_communication",
    "setup_microbatch_calculator",
    "get_num_microbatches",
    "get_micro_batch_size",
    "get_current_global_batch_size",
    "update_num_microbatches",
    "listify_model",
]
