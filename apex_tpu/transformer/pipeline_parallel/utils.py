"""Pipeline-parallel bookkeeping: the global microbatch calculator and
shape/model helpers.

Ref: apex/transformer/pipeline_parallel/utils.py — setup_microbatch_
calculator + _GLOBAL_NUM_MICROBATCHES_CALCULATOR global, get_num_
microbatches / get_current_global_batch_size / update_num_microbatches,
listify_model, and tensor-shape inference (seq divided by tp under the
scatter-gather optimization / sequence parallelism).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

from apex_tpu.transformer.microbatches import (
    NumMicroBatchesCalculator,
    build_num_microbatches_calculator,
)
from apex_tpu.transformer.tensor_parallel.utils import divide

_GLOBAL_NUM_MICROBATCHES_CALCULATOR: Optional[NumMicroBatchesCalculator] = None
_GLOBAL_MICRO_BATCH_SIZE: Optional[int] = None


def _ensure(name, value):
    if value is None:
        raise RuntimeError(f"{name} is not initialized; call "
                           "setup_microbatch_calculator() first")
    return value


def setup_microbatch_calculator(
    rank: int = 0,
    rampup_batch_size: Optional[Sequence[int]] = None,
    global_batch_size: int = 1,
    micro_batch_size: int = 1,
    data_parallel_size: int = 1,
) -> None:
    """Ref: pipeline_parallel/utils.py::setup_microbatch_calculator."""
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is not None:
        raise RuntimeError("microbatch calculator is already initialized")
    _reconfigure_microbatch_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size,
    )


def _reconfigure_microbatch_calculator(
    rank: int = 0,
    rampup_batch_size: Optional[Sequence[int]] = None,
    global_batch_size: int = 1,
    micro_batch_size: int = 1,
    data_parallel_size: int = 1,
) -> None:
    """Ref: ::_reconfigure_microbatch_calculator (tests/finetune resets)."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR, _GLOBAL_MICRO_BATCH_SIZE
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size,
    )
    _GLOBAL_MICRO_BATCH_SIZE = micro_batch_size


def destroy_microbatch_calculator() -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR, _GLOBAL_MICRO_BATCH_SIZE
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
    _GLOBAL_MICRO_BATCH_SIZE = None


def get_num_microbatches() -> int:
    """Ref: ::get_num_microbatches."""
    return _ensure("microbatch calculator",
                   _GLOBAL_NUM_MICROBATCHES_CALCULATOR).get()


def get_current_global_batch_size() -> int:
    """Ref: ::get_current_global_batch_size."""
    return _ensure(
        "microbatch calculator", _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    ).get_current_global_batch_size()


def get_micro_batch_size() -> int:
    return _ensure("micro batch size", _GLOBAL_MICRO_BATCH_SIZE)


def update_num_microbatches(consumed_samples: int,
                            consistency_check: bool = True) -> None:
    """Ref: ::update_num_microbatches."""
    _ensure("microbatch calculator", _GLOBAL_NUM_MICROBATCHES_CALCULATOR
            ).update(consumed_samples, consistency_check)


def listify_model(model: Any) -> List[Any]:
    """Ref: ::listify_model — interleaved schedules carry a list of chunks."""
    return model if isinstance(model, list) else [model]


def get_tensor_shapes(
    seq_length: int,
    micro_batch_size: int,
    hidden_size: int,
    *,
    tensor_model_parallel_size: int = 1,
    sequence_parallel_enabled: bool = False,
) -> Tuple[int, int, int]:
    """Inter-stage activation shape [s, b, h]. Ref: the shape bookkeeping in
    pipeline_parallel/utils.py — seq divided by tp world size under
    sequence parallelism (and under the scatter-gather p2p optimization)."""
    if sequence_parallel_enabled:
        seq_length = divide(seq_length, tensor_model_parallel_size)
    return (seq_length, micro_batch_size, hidden_size)


def local_chunk_indices(stage: int, pipeline_size: int,
                        virtual_size: int = 1) -> List[int]:
    """Global layer-chunk ids owned by ``stage``, in local slot order —
    the interleaved assignment (global chunk g -> stage g % pp, slot
    g // pp) the reference's build_model uses for virtual pipelining."""
    return [slot * pipeline_size + stage for slot in range(virtual_size)]


def build_model(chunk_init_fn, key, pipeline_size: int,
                virtual_size: int = 1):
    """SPMD analog of ref pipeline_parallel/schedules::build_model.

    The reference builds each rank's model chunks on that rank.  Under SPMD
    one process builds the GLOBAL chunk stack arranged [pp, V, ...] so that
    sharding dim 0 with ``P("stage")`` hands every stage exactly its
    interleaved local chunks (drop the leading dim inside shard_map; drop
    both for V == 1 with the non-interleaved schedule).

    ``chunk_init_fn(key, global_chunk_idx) -> params pytree`` is the
    model_provider; chunk g ends up at [g % pp, g // pp].
    """
    import jax as _jax
    import jax.numpy as _jnp

    n = pipeline_size * virtual_size
    keys = _jax.random.split(key, n)
    chunks = [chunk_init_fn(keys[g], g) for g in range(n)]
    stacked = _jax.tree.map(lambda *xs: _jnp.stack(xs), *chunks)
    perm = _jnp.array(
        [g for s in range(pipeline_size)
         for g in local_chunk_indices(s, pipeline_size, virtual_size)]
    )
    return _jax.tree.map(
        lambda a: a[perm].reshape(
            (pipeline_size, virtual_size) + a.shape[1:]
        ),
        stacked,
    )
