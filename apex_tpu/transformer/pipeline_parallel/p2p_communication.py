"""Stage-ring point-to-point communication.

Ref: apex/transformer/pipeline_parallel/p2p_communication.py::_communicate
and its helpers (send_forward, recv_forward, send_forward_recv_backward, …)
built on ``torch.distributed.batch_isend_irecv`` between pipeline neighbors.

Under SPMD there are no per-rank send/recv programs: a "send to next stage"
and a "receive from previous stage" are the *same* ``lax.ppermute`` viewed
from the two ends. Every helper therefore takes the value this stage is
sending and returns the value this stage receives; stages with no sender
(stage 0 for a forward recv, the last stage for a backward recv) receive
zeros, matching the reference where those ranks simply skip the recv.

All helpers must run inside a mapped computation where ``axis`` is bound.
The reference's scatter-gather p2p optimization (split activation across TP
ranks before send, all-gather after recv) lives in
apex_tpu/transformer/tensor_parallel/utils.py::split_tensor_into_1d_equal_chunks
/ gather_split_1d_tensor and composes with these helpers.
"""

from __future__ import annotations

from typing import Optional

from jax import lax

from apex_tpu.parallel.collectives import axis_size


def _fwd_perm(n: int, ring: bool):
    """(src, dst) pairs moving values to the next stage."""
    if ring:
        return [(i, (i + 1) % n) for i in range(n)]
    return [(i, i + 1) for i in range(n - 1)]


def _bwd_perm(n: int, ring: bool):
    if ring:
        return [(i, (i - 1) % n) for i in range(n)]
    return [(i, i - 1) for i in range(1, n)]


def _communicate(
    tensor_send_next=None,
    tensor_send_prev=None,
    *,
    axis: str,
    ring: bool = False,
):
    """Ref: p2p_communication.py::_communicate(tensor_send_next,
    tensor_send_prev, recv_prev, recv_next, …) -> (recv_prev, recv_next).

    One ``ppermute`` per direction (the SPMD analog of one
    ``batch_isend_irecv`` group). ``ring=True`` wraps last->first, used by
    the circulating-pipeline engine; the reference's schedules never wrap.
    """
    n = axis_size(axis)
    tensor_recv_prev = None
    tensor_recv_next = None
    if tensor_send_next is not None:
        tensor_recv_prev = lax.ppermute(tensor_send_next, axis, _fwd_perm(n, ring))
    if tensor_send_prev is not None:
        tensor_recv_next = lax.ppermute(tensor_send_prev, axis, _bwd_perm(n, ring))
    return tensor_recv_prev, tensor_recv_next


def send_forward_recv_forward(x, *, axis: str, ring: bool = False):
    """Send activation to the next stage; return the one arriving from the
    previous stage. Ref: p2p_communication.py::send_forward /
    ::recv_forward (one op seen from both ends)."""
    recv_prev, _ = _communicate(tensor_send_next=x, axis=axis, ring=ring)
    return recv_prev


def send_backward_recv_backward(g, *, axis: str, ring: bool = False):
    """Send grad to the previous stage; return the one arriving from the
    next stage. Ref: ::send_backward / ::recv_backward."""
    _, recv_next = _communicate(tensor_send_prev=g, axis=axis, ring=ring)
    return recv_next


# Reference-named aliases: in SPMD the send half and the recv half of each
# reference helper collapse into one value-rotation.
send_forward = send_forward_recv_forward
recv_forward = send_forward_recv_forward
send_backward = send_backward_recv_backward
recv_backward = send_backward_recv_backward


def send_forward_recv_backward(x, g, *, axis: str, ring: bool = False):
    """Ref: ::send_forward_recv_backward — steady-state 1F1B pair."""
    recv_prev, recv_next = _communicate(
        tensor_send_next=x, tensor_send_prev=g, axis=axis, ring=ring
    )
    return recv_prev, recv_next


send_backward_recv_forward = send_forward_recv_backward


def send_forward_backward_recv_forward_backward(
    x, g, *, axis: str, ring: bool = False
):
    """Ref: ::send_forward_backward_recv_forward_backward (interleaved)."""
    return _communicate(tensor_send_next=x, tensor_send_prev=g, axis=axis, ring=ring)
