"""No-pipelining schedule: sequential microbatch loop with grad accumulation.

Ref: apex/transformer/pipeline_parallel/schedules/fwd_bwd_no_pipelining.py::
forward_backward_no_pipelining — loops microbatches under a no-grad-sync
context, accumulating grads; the reference relies on torch grad accumulation,
here a ``lax.scan`` summing per-microbatch ``value_and_grad`` results (one
grad buffer live at a time, same memory shape as the reference).

Also the parity oracle for the pipelined schedules (SURVEY.md §5 pattern 3:
1F1B(loss) == nopipe(loss)).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    LossFn,
    PipelineResult,
    StageFn,
    _chunk,
)


def _compose_chunks(stage_fn, stage_params, x, checkpoint_activations):
    """Fold the [V, ...] chunk stack in order — the single-stage model."""
    f = jax.checkpoint(stage_fn) if checkpoint_activations else stage_fn

    def body(h, p):
        return f(p, h), None

    y, _ = lax.scan(body, x, stage_params)
    return y


def forward_backward_no_pipelining(
    stage_fn: StageFn,
    loss_fn: LossFn,
    stage_params: Any,
    loss_params: Any,
    xs: jax.Array,
    ys: Any,
    *,
    axis: str = None,  # unused; signature-compatible with the pipelined schedules
    forward_only: bool = False,
    checkpoint_activations: bool = False,
    collect_outputs: bool = False,
) -> PipelineResult:
    M = xs.shape[0]

    def mb_loss(params, lparams, m):
        y = _compose_chunks(stage_fn, params, xs[m], checkpoint_activations)
        return loss_fn(lparams, y, _chunk(ys, m)).astype(jnp.float32), y

    if forward_only:
        def fwd(m):
            loss, y = mb_loss(stage_params, loss_params, m)
            return loss, (y if collect_outputs else 0.0)

        losses, outs = lax.map(fwd, jnp.arange(M))
        return PipelineResult(losses, None, None, outs if collect_outputs else None)

    grad_fn = jax.value_and_grad(mb_loss, argnums=(0, 1), has_aux=True)

    def step(carry, m):
        gp, gl = carry
        (loss, y), (gpm, glm) = grad_fn(stage_params, loss_params, m)
        gp = jax.tree.map(jnp.add, gp, gpm)
        gl = jax.tree.map(jnp.add, gl, glm)
        return (gp, gl), (loss, y if collect_outputs else 0.0)

    zeros = lambda t: jax.tree.map(jnp.zeros_like, t)
    (gp, gl), (losses, outs) = lax.scan(
        step, (zeros(stage_params), zeros(loss_params)), jnp.arange(M)
    )
    return PipelineResult(losses, gp, gl, outs if collect_outputs else None)
