"""Non-interleaved pipeline schedule (the reference's 1F1B slot).

Ref: apex/transformer/pipeline_parallel/schedules/
fwd_bwd_pipelining_without_interleaving.py::forward_backward_pipelining_
without_interleaving — warmup ``pp_size - pp_rank - 1`` forwards, steady
1F1B send/recv pairs, cooldown backward drain.

TPU form: the V=1 instantiation of the circulating-ring engine
(schedules/common.py). The warmup/steady/cooldown phasing emerges from the
ring rotation plus autodiff — stage s sits idle (masked compute) for its
first s ticks (warmup bubble) and the transposed scan drains backwards
(cooldown) — rather than being three hand-written loops. Loss/grad parity
with the reference schedule is exact (same math, same microbatch order);
the schedule-parity invariant vs no-pipelining is tested in
tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py.
"""

from __future__ import annotations

from typing import Any

import jax

from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    LossFn,
    PipelineResult,
    StageFn,
    run_pipeline,
)


def forward_backward_pipelining_without_interleaving(
    stage_fn: StageFn,
    loss_fn: LossFn,
    stage_params: Any,
    loss_params: Any,
    xs: jax.Array,
    ys: Any,
    *,
    axis: str,
    forward_only: bool = False,
    checkpoint_activations: bool = False,
    collect_outputs: bool = False,
) -> PipelineResult:
    """stage_params: this stage's params, unstacked (single chunk per stage)."""
    stage_params = jax.tree.map(lambda a: a[None], stage_params)
    res = run_pipeline(
        stage_fn,
        loss_fn,
        stage_params,
        loss_params,
        xs,
        ys,
        axis=axis,
        forward_only=forward_only,
        checkpoint_activations=checkpoint_activations,
        collect_outputs=collect_outputs,
    )
    if res.stage_grads is not None:
        res = res._replace(
            stage_grads=jax.tree.map(lambda a: a[0], res.stage_grads)
        )
    return res
