"""The SPMD circulating-pipeline engine shared by all schedules.

Ref: apex/transformer/pipeline_parallel/schedules/common.py (forward_step /
backward_step / build_model) and the schedule bodies in
fwd_bwd_pipelining_without_interleaving.py / _with_interleaving.py.

Reference mechanism: each pipeline rank runs a *different* program — warmup
forwards, steady 1F1B send/recv pairs, cooldown backwards — with manual
``torch.autograd.backward`` calls stitching grads across ranks.

TPU mechanism (this module): one program on every stage. Time advances in
pipeline clock ticks inside a ``lax.scan``; each tick every stage

  1. takes the activation arriving on the stage ring (or injects a fresh
     microbatch at stage 0),
  2. applies its local model chunk (selected by a tick-derived chunk index,
     which makes the same loop serve the non-interleaved ``V=1`` and
     interleaved-virtual ``V>1`` schedules),
  3. computes the loss when a microbatch completes its final chunk on the
     last stage (masked elsewhere),
  4. rotates its output to the next stage with ``lax.ppermute``.

The backward schedule is not hand-written at all: differentiating through
the scan transposes every ``ppermute`` into the reverse rotation, so
``jax.value_and_grad`` materializes the cooldown/steady/warmup backward
phases automatically, with activation rematerialization
(``jax.checkpoint``) standing in for the reference's
tensor_parallel/random.py::CheckpointFunction.

Scheduling bookkeeping (derivation used throughout):

  P = stages, V = local chunks per stage, ring period ``rp = P*V``.
  Microbatch ``m`` enters stage 0 at tick ``e(m) = (m//P)*rp + m%P`` (a wave
  of P microbatches is injected per ring period — the ring holds at most P
  live activations). At tick ``t`` the activation residing on stage ``s``
  has ring offset ``r = (t - s) mod P``, hop ``h = (t - r) mod rp``, local
  chunk ``k = h // P``, and microbatch ``m = ((t - r)//rp)*P + r``; it is
  live iff ``m < M``. A microbatch finishes (hop ``rp-1``, necessarily on
  stage P-1 with chunk V-1) at tick ``e(m) + rp - 1``; total ticks
  ``T = ceil(M/P)*rp + P - 1``.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.collectives import axis_size
from apex_tpu.transformer.pipeline_parallel.p2p_communication import (
    send_forward_recv_forward,
)

StageFn = Callable[[Any, jax.Array], jax.Array]
LossFn = Callable[[Any, jax.Array, Any], jax.Array]


class PipelineResult(NamedTuple):
    """What a fwd-bwd schedule returns.

    losses: [M] per-microbatch losses, valid on every stage (psum'd over the
        stage axis), mirroring the reference's ``losses_reduced`` list.
    stage_grads: grads of this stage's chunk params, stacked [V, ...]
        (``None`` when forward_only).
    loss_grads: grads of the loss/head params, psum'd over the stage axis so
        replicated head params see a consistent grad (``None`` when
        forward_only or no loss params).
    outputs: [M, ...] final-chunk outputs (only when collect_outputs; valid
        on every stage via psum).
    """

    losses: jax.Array
    stage_grads: Any = None
    loss_grads: Any = None
    outputs: Optional[jax.Array] = None


def _chunk(tree, k):
    return jax.tree.map(
        lambda a: lax.dynamic_index_in_dim(a, k, 0, keepdims=False), tree
    )


def run_pipeline(
    stage_fn: StageFn,
    loss_fn: LossFn,
    stage_params: Any,
    loss_params: Any,
    xs: jax.Array,
    ys: Any,
    *,
    axis: str,
    forward_only: bool = False,
    checkpoint_activations: bool = False,
    collect_outputs: bool = False,
) -> PipelineResult:
    """Run the circulating pipeline over ``M = xs.shape[0]`` microbatches.

    stage_params is this stage's chunk stack [V, ...] (V=1 for the
    non-interleaved schedule). ``xs`` (stage-0 inputs, activation-shaped)
    and ``ys`` (last-stage targets) are replicated over the stage axis, the
    analog of the reference broadcasting data to all ranks
    (tensor_parallel/data.py::broadcast_data).

    stage_fn: (chunk_params, x) -> y with y.shape == x.shape (uniform
    transformer-block stack; embedding/head run outside or in loss_fn).
    loss_fn: (loss_params, y, target) -> scalar. Grads are of the *sum* of
    per-microbatch losses — fold any 1/M normalization into loss_fn.
    """
    P = axis_size(axis)
    V = jax.tree.leaves(stage_params)[0].shape[0]
    M = xs.shape[0]
    rp = P * V
    num_waves = -(-M // P)
    T = num_waves * rp + P - 1

    f = jax.checkpoint(stage_fn) if checkpoint_activations else stage_fn
    s = lax.axis_index(axis)
    on_last = lax.axis_index(axis) == P - 1
    # Microbatch m finishes (last chunk, last stage) at tick e(m) + rp - 1.
    finish = jnp.array(
        [(m // P) * rp + m % P + rp - 1 for m in range(M)], jnp.int32
    )

    def run(params, lparams):
        def tick(buf, t):
            # Stage-0 injection: wave w, slot r_in within the ring period.
            w_in = t // rp
            r_in = t % rp
            m_in = w_in * P + r_in
            inject = (s == 0) & (r_in < P) & (m_in < M)
            x = jnp.where(inject, xs[jnp.minimum(m_in, M - 1)], buf)
            # Which chunk this stage applies this tick (see module docstring).
            r = (t - s) % P
            k = ((t - r) % rp) // P
            y = f(_chunk(params, k), x)
            buf_next = send_forward_recv_forward(y, axis=axis, ring=True)
            return buf_next, y

        buf0 = jnp.zeros_like(xs[0])
        _, tick_y = lax.scan(tick, buf0, jnp.arange(T))
        finals = tick_y[finish]  # [M, ...] valid on the last stage only
        # Loss once per microbatch, not per tick (the vocab head is heavy).
        # Double-where: dead stages evaluate loss_fn at a benign point so
        # non-finite partials at garbage primals can't leak NaN into the
        # zero-masked cotangents.
        y_in = jnp.where(on_last, finals, jnp.ones_like(finals))
        losses_m = jax.vmap(
            lambda y, t: loss_fn(lparams, y, t).astype(jnp.float32)
        )(y_in, ys)
        losses_m = jnp.where(on_last, losses_m, 0.0)
        return losses_m.sum(), (losses_m, finals)

    if forward_only:
        _, (losses_m, finals) = run(stage_params, loss_params)
        stage_grads = loss_grads = None
    else:
        grad_fn = jax.value_and_grad(run, argnums=(0, 1), has_aux=True)
        (_, (losses_m, finals)), (stage_grads, loss_grads) = grad_fn(
            stage_params, loss_params
        )
        if loss_params is not None and jax.tree.leaves(loss_grads):
            loss_grads = jax.tree.map(lambda g: lax.psum(g, axis), loss_grads)

    # Replicate the per-microbatch losses (the reference's losses_reduced
    # list lives on the last stage; we hand every stage a copy).
    losses = lax.psum(losses_m, axis)

    outputs = None
    if collect_outputs:
        outputs = lax.psum(
            jnp.where(on_last, finals, jnp.zeros_like(finals)), axis
        )

    return PipelineResult(losses, stage_grads, loss_grads, outputs)
