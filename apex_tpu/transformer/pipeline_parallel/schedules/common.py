"""The SPMD circulating-pipeline engine shared by all schedules.

Ref: apex/transformer/pipeline_parallel/schedules/common.py (forward_step /
backward_step / build_model) and the schedule bodies in
fwd_bwd_pipelining_without_interleaving.py / _with_interleaving.py.

Reference mechanism: each pipeline rank runs a *different* program — warmup
forwards, steady 1F1B send/recv pairs, cooldown backwards — with manual
``torch.autograd.backward`` calls stitching grads across ranks. The whole
point of the 1F1B order is to cap in-flight activations at ~P per stage.

TPU mechanism (this module): one program on every stage. Time advances in
pipeline clock ticks; each tick every stage

  1. takes the activation arriving on the stage ring (or injects a fresh
     microbatch at stage 0),
  2. applies its local model chunk (selected by a tick-derived chunk index,
     which makes the same loop serve the non-interleaved ``V=1`` and
     interleaved-virtual ``V>1`` schedules),
  3. computes the loss when a microbatch completes its final chunk on the
     last stage (masked elsewhere), accumulating it into an [M] bucket,
  4. rotates its output to the next stage with ``lax.ppermute``.

The backward schedule is not hand-written at all: differentiating through
the scan transposes every ``ppermute`` into the reverse rotation, so
``jax.value_and_grad`` materializes the cooldown/steady/warmup backward
phases automatically.

**Memory contract (the analog of 1F1B's in-flight cap).** Ticks are grouped
into waves of ``rp = P*V`` ticks and the wave body is ``jax.checkpoint``ed
inside an outer ``lax.scan``: the forward saves only one ring-buffer
activation per wave (plus the [M] scalar loss bucket), and the backward
recomputes one wave at a time, holding at most ``rp`` tick activations
live — O(P*V), independent of the microbatch count M. With
``checkpoint_activations=True`` each tick is additionally remat'd (the
reference's tensor_parallel/random.py::CheckpointFunction), shrinking the
per-wave backward residency from rp x layer-internals to rp x one
activation.

Scheduling bookkeeping (derivation used throughout):

  P = stages, V = local chunks per stage, ring period ``rp = P*V``.
  Microbatch ``m`` enters stage 0 at tick ``e(m) = (m//P)*rp + m%P`` (a wave
  of P microbatches is injected per ring period — the ring holds at most P
  live activations). At tick ``t`` the activation residing on stage ``s``
  has ring offset ``r = (t - s) mod P``, hop ``h = (t - r) mod rp``, local
  chunk ``k = h // P``, and microbatch ``m = ((t - r)//rp)*P + r``; it is
  live iff ``m < M``. A microbatch finishes (hop ``rp-1``, necessarily on
  stage P-1 with chunk V-1) at tick ``e(m) + rp - 1``; total ticks
  ``T = ceil(M/P)*rp + P - 1`` (padded up to a whole number of waves).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.collectives import axis_size
from apex_tpu.transformer.pipeline_parallel.p2p_communication import (
    send_forward_recv_forward,
)

StageFn = Callable[[Any, jax.Array], jax.Array]
LossFn = Callable[[Any, jax.Array, Any], jax.Array]


class PipelineResult(NamedTuple):
    """What a fwd-bwd schedule returns.

    losses: [M] per-microbatch losses, valid on every stage (psum'd over the
        stage axis), mirroring the reference's ``losses_reduced`` list.
    stage_grads: grads of this stage's chunk params, stacked [V, ...]
        (``None`` when forward_only).
    loss_grads: grads of the loss/head params, psum'd over the stage axis so
        replicated head params see a consistent grad (``None`` when
        forward_only or no loss params).
    outputs: [M, ...] final-chunk outputs (only when collect_outputs; valid
        on every stage via psum).
    """

    losses: jax.Array
    stage_grads: Any = None
    loss_grads: Any = None
    outputs: Optional[jax.Array] = None


def _chunk(tree, k):
    return jax.tree.map(
        lambda a: lax.dynamic_index_in_dim(a, k, 0, keepdims=False), tree
    )


def run_pipeline(
    stage_fn: StageFn,
    loss_fn: LossFn,
    stage_params: Any,
    loss_params: Any,
    xs: jax.Array,
    ys: Any,
    *,
    axis: str,
    forward_only: bool = False,
    checkpoint_activations: bool = False,
    collect_outputs: bool = False,
) -> PipelineResult:
    """Run the circulating pipeline over ``M = xs.shape[0]`` microbatches.

    stage_params is this stage's chunk stack [V, ...] (V=1 for the
    non-interleaved schedule). ``xs`` (stage-0 inputs, activation-shaped)
    and ``ys`` (last-stage targets) are replicated over the stage axis, the
    analog of the reference broadcasting data to all ranks
    (tensor_parallel/data.py::broadcast_data).

    stage_fn: (chunk_params, x) -> y with y.shape == x.shape (uniform
    transformer-block stack; embedding/head run outside or in loss_fn).
    loss_fn: (loss_params, y, target) -> scalar. Grads are of the *sum* of
    per-microbatch losses — fold any 1/M normalization into loss_fn.
    """
    P = axis_size(axis)
    V = jax.tree.leaves(stage_params)[0].shape[0]
    M = xs.shape[0]
    rp = P * V
    num_waves = -(-M // P)
    T = num_waves * rp + P - 1
    num_outer = -(-T // rp)  # waves incl. the padded drain tail

    s = lax.axis_index(axis)
    on_last = s == P - 1

    def run(params, lparams):
        def tick(carry, t):
            buf, losses_acc, finals = carry
            # Stage-0 injection: wave w_in, slot r_in within the ring period.
            w_in = t // rp
            r_in = t % rp
            m_in = w_in * P + r_in
            inject = (s == 0) & (r_in < P) & (m_in < M)
            x = jnp.where(inject, xs[jnp.minimum(m_in, M - 1)], buf)
            # Which chunk this stage applies this tick (module docstring).
            r = (t - s) % P
            h = (t - r) % rp
            k = h // P
            m = ((t - r) // rp) * P + r
            y = stage_fn(_chunk(params, k), x)
            # Loss at the tick where a microbatch completes its final chunk
            # on the last stage. lax.cond (not a masked unconditional call)
            # so the heavy vocab head runs ONLY on finishing ticks — in
            # shard_map each device takes its own branch, and all tp peers
            # of a stage share the predicate, so loss_fn's model-axis
            # collectives stay collective-safe.
            # m >= 0 guards the pre-fill ticks: before its first activation
            # arrives, the last stage sees garbage slots with NEGATIVE m
            # (t < s), which also sit at hop rp-1 — without the guard their
            # losses wrap around (at[-3] => at[M-3]) into real microbatches.
            is_final = on_last & (h == rp - 1) & (m >= 0) & (m < M)
            m_idx = jnp.clip(m, 0, M - 1)
            target = jax.tree.map(lambda a: a[m_idx], ys)
            l = lax.cond(
                is_final,
                lambda y, t: loss_fn(lparams, y, t).astype(jnp.float32),
                lambda y, t: jnp.float32(0.0),
                y, target,
            )
            losses_acc = losses_acc.at[m_idx].add(l)
            if finals is not None:
                cur = lax.dynamic_index_in_dim(finals, m_idx, 0,
                                               keepdims=False)
                finals = lax.dynamic_update_index_in_dim(
                    finals,
                    jnp.where(is_final, lax.stop_gradient(y), cur),
                    m_idx, 0,
                )
            buf_next = send_forward_recv_forward(y, axis=axis, ring=True)
            return (buf_next, losses_acc, finals), None

        if checkpoint_activations:
            # rp x one activation live during a wave's backward
            tick_fn = jax.checkpoint(tick)
        else:
            # rp x layer-internals live — the reference's no-recompute 1F1B
            tick_fn = tick

        def wave(carry, t_row):
            carry, _ = lax.scan(tick_fn, carry, t_row)
            return carry, None

        buf0 = jnp.zeros_like(xs[0])
        losses0 = jnp.zeros((M,), jnp.float32)
        finals0 = (
            jnp.zeros((M,) + xs.shape[1:], xs.dtype)
            if collect_outputs else None
        )
        ts = jnp.arange(num_outer * rp).reshape(num_outer, rp)
        # checkpoint per wave: the fwd saves one ring carry per wave; the
        # bwd recomputes wave-by-wave — O(P*V) live ticks, not O(T)
        (buf, losses_m, finals), _ = lax.scan(
            jax.checkpoint(wave), (buf0, losses0, finals0), ts
        )
        return losses_m.sum(), (losses_m, finals)

    if forward_only:
        _, (losses_m, finals) = run(stage_params, loss_params)
        stage_grads = loss_grads = None
    else:
        grad_fn = jax.value_and_grad(run, argnums=(0, 1), has_aux=True)
        (_, (losses_m, finals)), (stage_grads, loss_grads) = grad_fn(
            stage_params, loss_params
        )
        if loss_params is not None and jax.tree.leaves(loss_grads):
            loss_grads = jax.tree.map(lambda g: lax.psum(g, axis), loss_grads)

    # Replicate the per-microbatch losses (the reference's losses_reduced
    # list lives on the last stage; we hand every stage a copy).
    losses = lax.psum(losses_m, axis)

    outputs = None
    if collect_outputs:
        outputs = lax.psum(
            jnp.where(on_last, finals, jnp.zeros_like(finals)), axis
        )

    return PipelineResult(losses, stage_grads, loss_grads, outputs)
