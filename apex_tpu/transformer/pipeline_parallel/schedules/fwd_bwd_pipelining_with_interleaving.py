"""Interleaved (virtual-pipeline) schedule.

Ref: apex/transformer/pipeline_parallel/schedules/
fwd_bwd_pipelining_with_interleaving.py::_forward_backward_pipelining_with_
interleaving — each rank holds ``V`` non-adjacent model chunks (global chunk
``g`` lives on stage ``g % P`` as local chunk ``g // P``), microbatches
visit every chunk in global order, and the tighter schedule shrinks the
pipeline bubble by ~V.

TPU form: the V>1 instantiation of the circulating-ring engine — the ring's
wrap-around (last stage -> stage 0) *is* the chunk transition, so the
interleaved dataflow needs no extra machinery beyond a tick-derived chunk
index (see schedules/common.py's derivation). The bubble shrinks identically:
total ticks ``ceil(M/P)*P*V + P - 1`` of 1/V-sized chunk steps, i.e. the
same ``(P-1)/V``-chunk bubble as the reference.
"""

from __future__ import annotations

from typing import Any

import jax

from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    LossFn,
    PipelineResult,
    StageFn,
    run_pipeline,
)


def forward_backward_pipelining_with_interleaving(
    stage_fn: StageFn,
    loss_fn: LossFn,
    stage_params: Any,
    loss_params: Any,
    xs: jax.Array,
    ys: Any,
    *,
    axis: str,
    forward_only: bool = False,
    checkpoint_activations: bool = False,
    collect_outputs: bool = False,
) -> PipelineResult:
    """stage_params: this stage's chunk stack [V, ...] in *local chunk
    order* (local chunk k is global chunk ``k*P + stage``)."""
    return run_pipeline(
        stage_fn,
        loss_fn,
        stage_params,
        loss_params,
        xs,
        ys,
        axis=axis,
        forward_only=forward_only,
        checkpoint_activations=checkpoint_activations,
        collect_outputs=collect_outputs,
    )
