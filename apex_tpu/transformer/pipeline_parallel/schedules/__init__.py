"""Schedule selection.

Ref: apex/transformer/pipeline_parallel/schedules/__init__.py::
get_forward_backward_func — picks no-pipelining / 1F1B / interleaved from
(virtual) pipeline sizes.
"""

from __future__ import annotations

from typing import Optional

from apex_tpu.transformer.pipeline_parallel.schedules.common import (
    PipelineResult,
    run_pipeline,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_no_pipelining import (
    forward_backward_no_pipelining,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_without_interleaving import (  # noqa: E501
    forward_backward_pipelining_without_interleaving,
)
from apex_tpu.transformer.pipeline_parallel.schedules.fwd_bwd_pipelining_with_interleaving import (  # noqa: E501
    forward_backward_pipelining_with_interleaving,
)


def get_forward_backward_func(
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_size: int = 1,
):
    """Ref: schedules/__init__.py::get_forward_backward_func."""
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining


__all__ = [
    "PipelineResult",
    "run_pipeline",
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
]
