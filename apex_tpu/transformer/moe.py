"""Mixture-of-Experts layer with expert parallelism (EP) over a mesh axis.

NOT in the reference — NVIDIA/apex has no MoE layer (SURVEY §3 lists
none); this is bonus surface completing the framework's parallelism set
(dp/tp/pp/sp/cp/**ep**), built the TPU way: deterministic capacity-based
token-choice routing with STATIC shapes (the GShard/Switch einsum
dispatch — no data-dependent shapes, so the whole layer jits), and the
dispatch/return exchanges ride two ``lax.all_to_all``s over the expert
axis (ICI-friendly, the same collective discipline as
context_parallel.ulysses_attention).

Layout (shard_map-local):
  x [t, h]           — this rank's tokens (t = local token count)
  router wg [h, E]   — replicated over the expert axis
  experts w1 [E_local, h, f], w2 [E_local, f, h] — each rank OWNS
                       E_local = E / ep_size experts (the EP sharding).
                       act="swiglu" doubles w1's last dim to 2f
                       ([gate|up] halves); w2 stays [E_local, f, h]

Per token the router picks top-k experts; a token occupies a slot in an
expert's fixed capacity C = ceil(t * k * capacity_factor / E) in router-
score order (priority dispatch); overflow tokens are DROPPED from that
expert — their combine weight is 0 and the caller's residual connection
carries them through unchanged (Switch-Transformer semantics).

**Grouped fast path** (``APEX_TPU_MOE_GROUPED=1`` or
``moe_apply(..., grouped=True)``): the dense [t, E, C] dispatch/combine
einsums — O(t·E·C·h) FLOPs and memory just to MOVE tokens — are replaced
by a sort-based dispatch over the ragged grouped-matmul kernel
(ops/grouped_matmul.py): argsort the token→expert assignments, gather
into expert-sorted order, run the expert FFN as two ``gmm``s over the
contiguous groups, scatter-add the results back weighted by the router
gates. Two modes:

- capacity mode (``capacity_factor`` a float): token-for-token identical
  drop set to the einsum path (the same priority-dispatch ``fits`` mask;
  dropped assignments keep their rows with combine weight 0), outputs
  equal to fp32-accumulation tolerance. Under EP the capacity slots ride
  the SAME two all_to_alls — the scatter/gather replaces the dispatch/
  combine einsums and the expert FFN runs as a gmm over the received
  slots.
- dropless mode (``capacity_factor=None``): every assignment is honored
  — expert FLOPs scale with the tokens actually routed, no phantom
  capacity padding. The einsum path cannot express this (it would need
  C = t·k); requires the grouped path and, for now, ep = 1
  (a dropless EP exchange needs data-dependent all_to_all splits).

With the gate off, ``moe_apply`` is bitwise identical to the pre-grouped
implementation.

Aux outputs: the Switch load-balance loss (E * Σ_e fraction_e * prob_e),
the router z-loss (mean log²Z), the dropped-token fraction, and
``expert_load`` — the per-expert fraction of the t·k routed assignments
(sums to 1; utils/metrics.step_metrics(moe_aux=...) surfaces it for
router-collapse monitoring without recomputing dispatch).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.observability import inc_counter
from apex_tpu.utils.envvars import env_flag
from apex_tpu.utils.profiling import trace_range


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _grad_scale(x, s: float):
    """Identity forward, cotangent scaled by ``s`` in the backward."""
    return x


def _grad_scale_fwd(x, s):
    return x, None


def _grad_scale_bwd(s, _, ct):
    return (ct * s,)


_grad_scale.defvjp(_grad_scale_fwd, _grad_scale_bwd)


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    hidden: int
    ffn: int
    num_experts: int
    top_k: int = 2
    capacity_factor: object = 1.25  # float, or None = dropless (grouped
                                    # path only: no per-expert cap, no
                                    # drops — the einsum path cannot
                                    # express it)
    expert_axis: object = None     # mesh axis name sharding experts, or
                                   # None = all experts local (ep = 1)
    act: str = "gelu"              # "gelu" | "swiglu" (Mixtral-style
                                   # gated experts: w1 carries [gate|up]
                                   # halves — experts are whole per rank,
                                   # so no TP interleaving needed)
    dtype: object = jnp.float32

    def __post_init__(self):
        assert 1 <= self.top_k <= self.num_experts
        assert self.act in ("gelu", "swiglu"), self.act

    def capacity(self, tokens: int) -> int:
        assert self.capacity_factor is not None, \
            "dropless MoE (capacity_factor=None) has no capacity"
        c = -(-tokens * self.top_k * self.capacity_factor // self.num_experts)
        return max(int(c), 1)


def moe_init(key, cfg: MoEConfig):
    """FULL-size params: router [h, E] fp32 (replicate), w1 [E, h, f]
    ([E, h, 2f] when act="swiglu" — gate|up halves) and
    w2 [E, f, h] in cfg.dtype. Under expert parallelism shard w1/w2 on
    the leading (expert) dim — P(expert_axis, ...) — and let shard_map
    hand each rank its E_local = E / ep_size slice."""
    k1, k2, k3 = jax.random.split(key, 3)
    e, h, f = cfg.num_experts, cfg.hidden, cfg.ffn
    f1 = f * (2 if cfg.act == "swiglu" else 1)
    scale = 0.02
    return {
        "router": (jax.random.normal(k1, (h, e)) * scale).astype(jnp.float32),
        "w1": (jax.random.normal(k2, (e, h, f1)) * scale).astype(cfg.dtype),
        "w2": (jax.random.normal(k3, (e, f, h)) * scale).astype(cfg.dtype),
    }


def _route(logits, cfg: MoEConfig, capacity):
    """Shared top-k routing (both dispatch paths).

    logits [t, E] fp32. Returns (top_idx [t, k] int32, sel [t, k, E]
    one-hot fp32, gate [t, k] fp32, pos [t, k] int32 capacity slot |
    None, fits [t, k] bool, aux).
    With a capacity, slots are taken in router-probability order
    (priority dispatch): within each expert, higher-prob tokens win the
    capacity race — deterministic and argsort-stable. ``capacity=None``
    (dropless) skips the slot race entirely (fits all-True)."""
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)                    # [t, E]
    _, top_idx = lax.top_k(probs, cfg.top_k)                   # [t, k]

    # kth-choice one-hots, flattened over (token, k): a token can occupy
    # at most one slot per expert (top_k indices are distinct)
    sel = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)        # [t, k, E]
    gate = jnp.take_along_axis(probs, top_idx, axis=-1)        # [t, k]

    if capacity is None:
        pos = None
        fits = jnp.ones((t, cfg.top_k), bool)
    else:
        # priority order: sort (expert, -prob) pairs implicitly by ranking
        # each selection within its expert by gate DESC. rank via argsort
        # of (-gate) per expert using a stable double-argsort over the
        # flat [t*k] selections.
        flat_sel = sel.reshape(t * cfg.top_k, e)               # [tk, E]
        flat_gate = gate.reshape(t * cfg.top_k)                # [tk]
        order = jnp.argsort(-flat_gate)                        # high first
        sel_sorted = flat_sel[order]
        pos_sorted = jnp.cumsum(sel_sorted, axis=0) - sel_sorted  # slot idx
        inv = jnp.argsort(order)
        pos = jnp.take_along_axis(
            pos_sorted, inv[:, None], axis=0
        )                                                      # [tk, E]
        pos = jnp.sum(pos * flat_sel, axis=-1).reshape(t, cfg.top_k)
        pos = pos.astype(jnp.int32)
        fits = pos < capacity                                  # [t, k]

    # Switch aux losses (computed pre-capacity so the signal pushes the
    # router toward balance, not toward whatever fit)
    frac_tokens = jnp.mean(sel[:, 0], axis=0)   # top-1 assignment fraction
    frac_probs = jnp.mean(probs, axis=0)
    aux = {
        "load_balance": e * jnp.sum(frac_tokens * frac_probs),
        "router_z": jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2),
        # router-health vector: fraction of the t*k assignments routed to
        # each expert (sums to 1) — metrics.step_metrics(moe_aux=...)
        "expert_load": jnp.mean(sel, axis=(0, 1)),
    }
    return top_idx, sel, gate, pos, fits, aux


def _dispatch_masks(logits, cfg: MoEConfig, capacity: int):
    """Static-shape top-k capacity dispatch (the einsum path's masks).

    logits [t, E] fp32. Returns (dispatch [t, E, C] bool,
    combine [t, E, C] fp32, aux dict)."""
    t, _ = logits.shape
    _, sel, gate, pos, fits, aux = _route(logits, cfg, capacity)

    slot = jax.nn.one_hot(
        jnp.where(fits, pos, capacity), capacity + 1, dtype=jnp.float32
    )[..., :capacity]                                          # [t, k, C]
    # dispatch[t, e, c] = 1 iff token t sits in slot c of expert e
    dispatch = jnp.einsum("tke,tkc->tec", sel, slot)
    combine = jnp.einsum("tke,tkc,tk->tec", sel, slot,
                         jnp.where(fits, gate, 0.0))
    aux = dict(aux)
    aux["dropped_fraction"] = \
        1.0 - jnp.sum(combine > 0) / (t * cfg.top_k)
    return dispatch, combine, aux


def _grouped_enabled() -> bool:
    """The trace-time gate (same discipline as parallel/overlap.py)."""
    return env_flag("APEX_TPU_MOE_GROUPED", default=False)


def moe_apply(params, x, cfg: MoEConfig, *,
              tokens_replicated_over_axis: bool = False, grouped=None):
    """x [t, h] -> ([t, h], aux). Inside shard_map when expert_axis is
    set: params["w1"/"w2"] are the rank-LOCAL [E_local, ...] shards and
    two all_to_alls move token slots between expert owners.

    ``grouped``: None (default) reads APEX_TPU_MOE_GROUPED at trace
    time; True/False force the sort-based grouped-matmul dispatch or the
    einsum dispatch (see module doc). Gate off = bitwise the pre-grouped
    implementation.

    ``tokens_replicated_over_axis``: set True when x is the SAME tokens on
    every expert-axis rank (e.g. MoE riding a TP group without sequence
    parallelism). The forward is then p-fold redundant but correct; the
    BACKWARD however hands each expert owner p identical cotangent copies
    through the all_to_all transpose, so the local expert grads come out
    p x the true gradient — corrected here by scaling the w1/w2
    cotangents by 1/p (the router's grads flow only through this rank's
    own combine weights and are already 1x). With genuinely sharded
    tokens (SP, or one shard per rank) leave it False: each expert's grad
    sums DISJOINT token slices and is already complete."""
    t, h = x.shape
    if grouped is None:
        grouped = _grouped_enabled()
    if cfg.capacity_factor is None:
        if not grouped:
            raise ValueError(
                "dropless MoE (capacity_factor=None) needs the grouped "
                "dispatch: set APEX_TPU_MOE_GROUPED=1 or pass grouped=True "
                "(the einsum path would need capacity = t * top_k)")
        if cfg.expert_axis is not None:
            raise NotImplementedError(
                "dropless MoE under expert parallelism needs data-dependent "
                "all_to_all splits; use a capacity_factor with EP, or "
                "ep = 1 for dropless")
    w1, w2 = params["w1"], params["w2"]
    if tokens_replicated_over_axis and cfg.expert_axis is not None:
        inv_p = 1.0 / lax.axis_size(cfg.expert_axis)
        w1 = _grad_scale(w1, inv_p)
        w2 = _grad_scale(w2, inv_p)
    params = dict(params, w1=w1, w2=w2)
    logits = x.astype(jnp.float32) @ params["router"].astype(jnp.float32)
    if grouped:
        return _moe_grouped(params, x, logits, cfg)

    cap = cfg.capacity(t)
    dispatch, combine, aux = _dispatch_masks(logits, cfg, cap)
    # dispatch is one-hot, so this gather-einsum is exact in any dtype;
    # cast to the compute dtype BEFORE the exchange (halves ICI bytes)
    xin = jnp.einsum("tec,th->ech", dispatch.astype(cfg.dtype),
                     x.astype(cfg.dtype))

    if cfg.expert_axis is not None:
        p = lax.axis_size(cfg.expert_axis)
        assert cfg.num_experts % p == 0, (
            f"num_experts={cfg.num_experts} not divisible by "
            f"|{cfg.expert_axis}|={p}")
        e_local = cfg.num_experts // p
        # [E, C, h] -> [p, E_local, C, h] -> exchange expert-major for
        # source-rank-major: each rank ends with ITS experts' slots from
        # every source rank, concatenated on the slot dim
        xin = xin.reshape(p, e_local, cap, h)
        xin = lax.all_to_all(xin, cfg.expert_axis, split_axis=0,
                             concat_axis=0, tiled=False)       # [p, eL, C, h]
        xin = xin.transpose(1, 0, 2, 3).reshape(e_local, p * cap, h)
    # expert FFN — one batched einsum over the local experts; operands in
    # the compute dtype at full MXU rate, fp32 MXU accumulation
    hmid = jnp.einsum("ech,ehf->ecf", xin, params["w1"],
                      preferred_element_type=jnp.float32)
    hmid = _moe_act(hmid, cfg)
    out = jnp.einsum(
        "ecf,efh->ech", hmid.astype(cfg.dtype), params["w2"],
        preferred_element_type=jnp.float32)
    # same cast on BOTH the EP and ep=1 paths (keeps them bitwise equal)
    # so the return all_to_all also moves compute-dtype bytes
    out = out.astype(cfg.dtype)
    if cfg.expert_axis is not None:
        p = lax.axis_size(cfg.expert_axis)
        e_local = cfg.num_experts // p
        out = out.reshape(e_local, p, cap, h).transpose(1, 0, 2, 3)
        out = lax.all_to_all(out, cfg.expert_axis, split_axis=0,
                             concat_axis=0, tiled=False)
        out = out.reshape(cfg.num_experts, cap, h)
    y = jnp.einsum("tec,ech->th", combine, out.astype(jnp.float32))
    return y.astype(x.dtype), aux


def _moe_act(hmid, cfg: MoEConfig):
    """Expert activation on the fp32 accumulator (shared by both paths;
    hmid's leading dims are free — [e, c, f1] or [rows, f1])."""
    if cfg.act == "swiglu":
        return jax.nn.silu(hmid[..., :cfg.ffn]) * hmid[..., cfg.ffn:]
    return jax.nn.gelu(hmid)


def _moe_grouped(params, x, logits, cfg: MoEConfig):
    """Sort-based dispatch over the ragged grouped matmul.

    ep = 1: argsort the [t*k] token->expert assignments (stable, so equal
    experts keep token order), gather tokens into expert-sorted order,
    FFN = two gmms over the contiguous groups, scatter-add combine
    weighted by the router gates. Dropped assignments (capacity mode)
    keep their rows with weight 0 — identical drop sets, identical
    per-token math to the einsum path at fp32-accumulation tolerance.

    EP: the capacity slots are built by SCATTER (no [t, E, C] one-hot
    einsum), ride the same two all_to_alls as the einsum path, the local
    expert FFN runs as a gmm over the received slot rows (uniform groups
    of p*C), and the combine is a gather + weighted sum."""
    with trace_range("moe_grouped_dispatch"):
        return _moe_grouped_body(params, x, logits, cfg)


def _moe_grouped_body(params, x, logits, cfg: MoEConfig):
    from apex_tpu.ops.grouped_matmul import gmm

    t, h = x.shape
    # trace-time dispatch accounting (static routing geometry): how many
    # grouped-dispatch programs exist per traced step, and their shape
    inc_counter("moe/grouped_dispatch", 1,
                mode="dropless" if cfg.capacity_factor is None
                else "capacity",
                ep="1" if cfg.expert_axis is None
                else str(lax.axis_size(cfg.expert_axis)))
    k, e = cfg.top_k, cfg.num_experts
    dropless = cfg.capacity_factor is None
    cap = None if dropless else cfg.capacity(t)
    top_idx, sel, gate, pos, fits, aux = _route(logits, cfg, cap)
    w_flat = jnp.where(fits, gate, 0.0).reshape(t * k)         # fp32
    aux = dict(aux)
    # dropless honors every assignment by construction — pin the exact 0
    # rather than letting XLA's reassociated 1 - n/n wobble around it
    aux["dropped_fraction"] = jnp.float32(0.0) if dropless else \
        1.0 - jnp.sum(w_flat > 0) / (t * k)
    e_flat = top_idx.reshape(t * k).astype(jnp.int32)

    if cfg.expert_axis is not None:
        p = lax.axis_size(cfg.expert_axis)
        assert cfg.num_experts % p == 0, (
            f"num_experts={cfg.num_experts} not divisible by "
            f"|{cfg.expert_axis}|={p}")
        e_local = cfg.num_experts // p
        # dispatch: scatter each fitting assignment into its (expert,
        # capacity-slot) row — the relayout the dispatch einsum used to
        # pay O(t*E*C*h) for; collisions are impossible (distinct experts
        # per token, distinct slots per expert)
        slot = e_flat * cap + pos.reshape(t * k)               # [tk]
        slot = jnp.where(fits.reshape(t * k), slot, e * cap)   # OOB = drop
        x_rep = jnp.repeat(x.astype(cfg.dtype), k, axis=0)     # [tk, h]
        xin = jnp.zeros((e * cap, h), cfg.dtype).at[slot].set(
            x_rep, mode="drop")
        xin = xin.reshape(p, e_local, cap, h)
        xin = lax.all_to_all(xin, cfg.expert_axis, split_axis=0,
                             concat_axis=0, tiled=False)       # [p, eL, C, h]
        rows = xin.transpose(1, 0, 2, 3).reshape(e_local * p * cap, h)
        sizes = jnp.full((e_local,), p * cap, jnp.int32)
        hmid = gmm(rows, params["w1"], sizes, out_dtype=jnp.float32)
        hmid = _moe_act(hmid, cfg)
        out = gmm(hmid.astype(cfg.dtype), params["w2"], sizes,
                  out_dtype=jnp.float32).astype(cfg.dtype)
        out = out.reshape(e_local, p, cap, h).transpose(1, 0, 2, 3)
        out = lax.all_to_all(out, cfg.expert_axis, split_axis=0,
                             concat_axis=0, tiled=False)
        out = out.reshape(e * cap, h)
        # combine: gather each assignment's slot row, weight by its gate
        taken = out[jnp.clip(slot, 0, e * cap - 1)].astype(jnp.float32)
        y = jnp.sum((taken * w_flat[:, None]).reshape(t, k, h), axis=1)
        return y.astype(x.dtype), aux

    # ep = 1: expert-sorted ragged groups, no capacity padding at all
    order = jnp.argsort(e_flat, stable=True)                   # [tk]
    tok = order // k                                           # source token
    xs = jnp.take(x.astype(cfg.dtype), tok, axis=0)            # [tk, h]
    group_sizes = jnp.bincount(e_flat, length=e).astype(jnp.int32)
    hmid = gmm(xs, params["w1"], group_sizes, out_dtype=jnp.float32)
    hmid = _moe_act(hmid, cfg)
    ys = gmm(hmid.astype(cfg.dtype), params["w2"], group_sizes,
             out_dtype=jnp.float32).astype(cfg.dtype)
    w_sorted = w_flat[order]
    y = jnp.zeros((t, h), jnp.float32).at[tok].add(
        ys.astype(jnp.float32) * w_sorted[:, None])
    return y.astype(x.dtype), aux


def moe_reference(params, x, cfg: MoEConfig):
    """ep=1 oracle: identical math with all experts local (used by tests
    to pin the all_to_all exchange). Always the einsum path."""
    cfg1 = dataclasses.replace(cfg, expert_axis=None)
    return moe_apply(params, x, cfg1, grouped=False)
