"""NHWC BatchNorm with fused ReLU/add and cross-chip bn_group (ref:
apex/contrib/groupbn, ext ``bnp``; also covers apex/contrib/cudnn_gbn's
``GroupBatchNorm2d`` — same capability over cuDNN).

The reference computes BN statistics across a ``bn_group`` of GPUs through
CUDA-IPC peer memory. On TPU the group is a named mesh axis (or sub-axis):
statistics are fp32 batch moments reduced with ``lax.psum`` when running
under ``shard_map``. Fused epilogues (relu / residual add+relu) mirror the
``bn_relu`` / ``bn_add_relu`` kernel variants.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def batch_norm_nhwc(x, params, state, *, training: bool, momentum: float = 0.9,
                    eps: float = 1e-5, axis_name: Optional[str] = None,
                    fuse_add=None, fuse_relu: bool = False):
    """x: [N, H, W, C]; params: {gamma, beta}; state: {mean, var} running.

    Returns (y, new_state). ``axis_name`` reduces stats over that mesh axis
    (the bn_group). ``fuse_add`` is an optional residual added before the
    (optionally fused) ReLU — the reference's bn_add_relu.
    """
    x32 = x.astype(jnp.float32)
    if training:
        # two-pass moments (centered-square form): stable for large-mean
        # inputs where E[x^2]-E[x]^2 cancels; with a bn_group the second
        # pass reuses the group mean, so the result is still exact
        mean = jnp.mean(x32, axis=(0, 1, 2))
        if axis_name is not None:
            mean = lax.pmean(mean, axis_name)
        var = jnp.mean(jnp.square(x32 - mean), axis=(0, 1, 2))
        if axis_name is not None:
            var = lax.pmean(var, axis_name)
        new_state = {
            "mean": momentum * state["mean"] + (1 - momentum) * mean,
            "var": momentum * state["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = state["mean"], state["var"]
        new_state = state
    y = (x32 - mean) * lax.rsqrt(var + eps)
    y = y * params["gamma"].astype(jnp.float32) + params["beta"].astype(
        jnp.float32
    )
    if fuse_add is not None:
        y = y + fuse_add.astype(jnp.float32)
    if fuse_relu:
        y = jax.nn.relu(y)
    return y.astype(x.dtype), new_state


class BatchNorm2d_NHWC:
    """Veneer with the reference constructor (ref: groupbn/batch_norm.py::
    BatchNorm2d_NHWC(planes, fuse_relu, bn_group))."""

    def __init__(self, num_features: int, fuse_relu: bool = False,
                 bn_group: Optional[str] = None, momentum: float = 0.9,
                 eps: float = 1e-5, dtype=jnp.float32):
        self.fuse_relu = fuse_relu
        self.bn_group = bn_group
        self.momentum = momentum
        self.eps = eps
        self.params = {
            "gamma": jnp.ones((num_features,), dtype),
            "beta": jnp.zeros((num_features,), dtype),
        }
        self.state = {
            "mean": jnp.zeros((num_features,), jnp.float32),
            "var": jnp.ones((num_features,), jnp.float32),
        }

    def __call__(self, x, z=None, *, training: bool = True, params=None,
                 state=None):
        y, new_state = batch_norm_nhwc(
            x, self.params if params is None else params,
            self.state if state is None else state,
            training=training, momentum=self.momentum, eps=self.eps,
            axis_name=self.bn_group, fuse_add=z, fuse_relu=self.fuse_relu,
        )
        if state is None and not isinstance(new_state["mean"], jax.core.Tracer):
            # only persist concrete stats: under jit, silently storing a
            # tracer would poison the module (use the functional
            # batch_norm_nhwc + explicit state inside train steps)
            self.state = new_state
        return y


# cudnn_gbn parity name
GroupBatchNorm2d = BatchNorm2d_NHWC
