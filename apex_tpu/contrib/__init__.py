"""apex_tpu.contrib — optional extensions (ref: apex/contrib).

Each submodule mirrors one reference contrib package; all compute paths are
jnp/XLA + the Pallas kernels in :mod:`apex_tpu.ops` (the reference's CUDA
extension modules are listed per-file). Imported lazily.
"""

_SUBMODULES = (
    "multihead_attn",
    "fmha",
    "xentropy",
    "focal_loss",
    "group_norm",
    "groupbn",
    "cudnn_gbn",
    "gpu_specific",
    "layer_norm",
    "clip_grad",
    "sparsity",
    "transducer",
    "index_mul_2d",
    "conv_bias_relu",
    "bottleneck",
    "peer_memory",
    "optimizers",
    "openfold",
)


def __getattr__(name):
    if name in _SUBMODULES:
        import importlib

        mod = importlib.import_module(f"apex_tpu.contrib.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'apex_tpu.contrib' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals().keys()) + list(_SUBMODULES))
