"""Encoder-decoder multihead attention.

Ref: apex/contrib/multihead_attn/encdec_multihead_attn.py::EncdecMultiheadAttn
(q projected from the decoder stream, k/v from the encoder stream with a
single fused [h, 2h] projection; optional fused pre-LN + residual on the
query stream only, like the reference's encdec_*_norm_add kernels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import flash_attention
from apex_tpu.ops.layer_norm import layer_norm


def encdec_attn_init(key, hidden_dim: int, heads: int, *, bias: bool = False,
                     include_norm_add: bool = False, dtype=jnp.float32):
    if hidden_dim % heads:
        raise ValueError("hidden_dim must be divisible by heads")
    k_q, k_kv, k_out = jax.random.split(key, 3)
    bound_q = (6.0 / (2 * hidden_dim)) ** 0.5 / (2.0 ** 0.5)
    bound_kv = (6.0 / (3 * hidden_dim)) ** 0.5 / (2.0 ** 0.5)
    bound_out = (6.0 / (2 * hidden_dim)) ** 0.5
    params = {
        "q_kernel": jax.random.uniform(
            k_q, (hidden_dim, hidden_dim), dtype, -bound_q, bound_q
        ),
        "kv_kernel": jax.random.uniform(
            k_kv, (hidden_dim, 2 * hidden_dim), dtype, -bound_kv, bound_kv
        ),
        "out_kernel": jax.random.uniform(
            k_out, (hidden_dim, hidden_dim), dtype, -bound_out, bound_out
        ),
    }
    if bias:
        params["q_bias"] = jnp.zeros((hidden_dim,), dtype)
        params["kv_bias"] = jnp.zeros((2 * hidden_dim,), dtype)
        params["out_bias"] = jnp.zeros((hidden_dim,), dtype)
    if include_norm_add:
        params["ln_gamma"] = jnp.ones((hidden_dim,), dtype)
        params["ln_beta"] = jnp.zeros((hidden_dim,), dtype)
    return params


def encdec_attn_apply(
    params,
    query,
    key_value,
    heads: int,
    *,
    key_padding_mask=None,
    attn_mask=None,
    is_training: bool = True,
    dropout_p: float = 0.0,
    dropout_rng=None,
    include_norm_add: bool = False,
    use_pallas: bool | None = None,
):
    """query: [sq, batch, hidden] (decoder); key_value: [sk, batch, hidden]
    (encoder). Masks follow the reference conventions (True = masked)."""
    sq, b, h = query.shape
    sk = key_value.shape[0]
    d = h // heads
    qin = query
    if include_norm_add:
        query = layer_norm(query, params["ln_gamma"], params["ln_beta"],
                           use_pallas=use_pallas)
    q = query @ params["q_kernel"]
    if "q_bias" in params:
        q = q + params["q_bias"]
    kv = key_value @ params["kv_kernel"]
    if "kv_bias" in params:
        kv = kv + params["kv_bias"]
    k, v = jnp.split(kv, 2, axis=-1)

    def split_heads(t, s):
        return t.reshape(s, b, heads, d).transpose(1, 2, 0, 3)

    q = split_heads(q, sq)
    k = split_heads(k, sk)
    v = split_heads(v, sk)

    mask = None
    if attn_mask is not None:
        mask = jnp.asarray(attn_mask, bool)[None, None]
    if key_padding_mask is not None:
        kp = jnp.asarray(key_padding_mask, bool)[:, None, None, :]
        mask = kp if mask is None else (mask | kp)

    p = dropout_p if is_training else 0.0
    o = flash_attention(
        q, k, v, mask=mask, dropout_p=p, dropout_rng=dropout_rng,
        use_pallas=use_pallas,
    )
    o = o.transpose(2, 0, 1, 3).reshape(sq, b, h)
    o = o @ params["out_kernel"]
    if "out_bias" in params:
        o = o + params["out_bias"]
    if include_norm_add:
        o = o + qin
    return o


class EncdecMultiheadAttn:
    """Stateful-looking veneer with the reference constructor signature."""

    def __init__(self, embed_dim: int, num_heads: int, *, dropout: float = 0.0,
                 bias: bool = False, include_norm_add: bool = False,
                 impl: str = "fast", dtype=jnp.float32, key=None):
        if impl not in ("fast", "default"):
            raise ValueError(f"unknown impl {impl!r}")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.include_norm_add = include_norm_add
        self.use_pallas = None if impl == "fast" else False
        key = jax.random.PRNGKey(0) if key is None else key
        self.params = encdec_attn_init(
            key, embed_dim, num_heads, bias=bias,
            include_norm_add=include_norm_add, dtype=dtype,
        )

    def __call__(self, query, key_value, *, key_padding_mask=None,
                 attn_mask=None, is_training=True, dropout_rng=None,
                 params=None):
        return encdec_attn_apply(
            self.params if params is None else params,
            query, key_value, self.num_heads,
            key_padding_mask=key_padding_mask, attn_mask=attn_mask,
            is_training=is_training, dropout_p=self.dropout,
            dropout_rng=dropout_rng,
            include_norm_add=self.include_norm_add,
            use_pallas=self.use_pallas,
        )
