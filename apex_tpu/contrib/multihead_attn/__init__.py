"""Fused multihead attention (ref: apex/contrib/multihead_attn)."""

from apex_tpu.contrib.multihead_attn.self_multihead_attn import (  # noqa: F401
    SelfMultiheadAttn,
    self_attn_apply,
    self_attn_init,
)
from apex_tpu.contrib.multihead_attn.encdec_multihead_attn import (  # noqa: F401
    EncdecMultiheadAttn,
    encdec_attn_apply,
    encdec_attn_init,
)
