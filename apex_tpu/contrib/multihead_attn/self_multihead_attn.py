"""Self multihead attention with optional fused pre-LN + residual.

Ref: apex/contrib/multihead_attn/self_multihead_attn.py::SelfMultiheadAttn
and its ``fast_multihead_attn`` kernels (self_attn_*, *_norm_add_*,
*_bias_*, mask_softmax_dropout_*). The reference fuses qkv GEMM + scaled
masked softmax + dropout + out GEMM in one autograd Function; here the
attention core is the Pallas flash kernel (:func:`apex_tpu.ops.flash_attention`)
and XLA fuses the projections — same capability, no score-matrix
materialization (stronger than the reference, which materializes probs for
dropout).

Layout follows the reference: inputs are [seq, batch, hidden] (torch MHA
convention). ``include_norm_add`` applies LayerNorm to the input before the
qkv projection and adds the *raw* input as a residual to the output, exactly
like the reference's norm_add variants.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import flash_attention
from apex_tpu.ops.layer_norm import layer_norm


def self_attn_init(key, hidden_dim: int, heads: int, *, bias: bool = False,
                   include_norm_add: bool = False, dtype=jnp.float32):
    """Parameters matching the reference's reset_parameters: qkv weight
    xavier-uniform with gain 1/sqrt(2) (the torch MHA trick), out weight
    xavier-uniform."""
    if hidden_dim % heads:
        raise ValueError("hidden_dim must be divisible by heads")
    k_qkv, k_out = jax.random.split(key)
    # xavier_uniform bound for a [h, 3h] matrix, with the 1/sqrt(2) gain
    bound_qkv = (6.0 / (hidden_dim + 3 * hidden_dim)) ** 0.5 / (2.0 ** 0.5)
    bound_out = (6.0 / (hidden_dim + hidden_dim)) ** 0.5
    params = {
        "qkv_kernel": jax.random.uniform(
            k_qkv, (hidden_dim, 3 * hidden_dim), dtype, -bound_qkv, bound_qkv
        ),
        "out_kernel": jax.random.uniform(
            k_out, (hidden_dim, hidden_dim), dtype, -bound_out, bound_out
        ),
    }
    if bias:
        params["qkv_bias"] = jnp.zeros((3 * hidden_dim,), dtype)
        params["out_bias"] = jnp.zeros((hidden_dim,), dtype)
    if include_norm_add:
        params["ln_gamma"] = jnp.ones((hidden_dim,), dtype)
        params["ln_beta"] = jnp.zeros((hidden_dim,), dtype)
    return params


def self_attn_apply(
    params,
    x,
    heads: int,
    *,
    key_padding_mask=None,
    attn_mask=None,
    is_training: bool = True,
    dropout_p: float = 0.0,
    dropout_rng=None,
    include_norm_add: bool = False,
    use_pallas: bool | None = None,
):
    """x: [seq, batch, hidden]. ``key_padding_mask``: [batch, seq] bool,
    True = masked (reference convention). ``attn_mask`` True => causal
    time mask (reference passes a precomputed upper-triangular mask; any
    explicit [sq, sk] bool array is also accepted)."""
    s, b, h = x.shape
    d = h // heads
    xin = x
    if include_norm_add:
        x = layer_norm(x, params["ln_gamma"], params["ln_beta"],
                       use_pallas=use_pallas)
    qkv = x @ params["qkv_kernel"]
    if "qkv_bias" in params:
        qkv = qkv + params["qkv_bias"]
    q, k, v = jnp.split(qkv, 3, axis=-1)

    # [seq, batch, hidden] -> [batch, heads, seq, d]
    def split_heads(t):
        return t.reshape(s, b, heads, d).transpose(1, 2, 0, 3)

    q, k, v = split_heads(q), split_heads(k), split_heads(v)

    # attn_mask=True (any scalar bool) selects the causal time mask; an
    # explicit [sq, sk] bool array is applied as-is (True = masked)
    causal = False
    mask = None
    if attn_mask is not None:
        if isinstance(attn_mask, bool) or (
            hasattr(attn_mask, "ndim") and attn_mask.ndim == 0
        ):
            causal = bool(attn_mask)
        else:
            mask = jnp.asarray(attn_mask, bool)[None, None]
    if key_padding_mask is not None:
        kp = jnp.asarray(key_padding_mask, bool)[:, None, None, :]
        mask = kp if mask is None else (mask | kp)

    p = dropout_p if is_training else 0.0
    o = flash_attention(
        q, k, v, mask=mask, causal=causal, dropout_p=p,
        dropout_rng=dropout_rng, use_pallas=use_pallas,
    )
    # [batch, heads, seq, d] -> [seq, batch, hidden]
    o = o.transpose(2, 0, 1, 3).reshape(s, b, h)
    o = o @ params["out_kernel"]
    if "out_bias" in params:
        o = o + params["out_bias"]
    if include_norm_add:
        o = o + xin
    return o


class SelfMultiheadAttn:
    """Stateful-looking veneer with the reference constructor signature."""

    def __init__(self, embed_dim: int, num_heads: int, *, dropout: float = 0.0,
                 bias: bool = False, include_norm_add: bool = False,
                 impl: str = "fast", dtype=jnp.float32, key=None):
        if impl not in ("fast", "default"):
            raise ValueError(f"unknown impl {impl!r}")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.include_norm_add = include_norm_add
        # 'fast' = Pallas kernel, 'default' = jnp reference (same numerics)
        self.use_pallas = None if impl == "fast" else False
        key = jax.random.PRNGKey(0) if key is None else key
        self.params = self_attn_init(
            key, embed_dim, num_heads, bias=bias,
            include_norm_add=include_norm_add, dtype=dtype,
        )

    def __call__(self, query, *, key_padding_mask=None, attn_mask=None,
                 is_training=True, dropout_rng=None, params=None):
        return self_attn_apply(
            self.params if params is None else params,
            query, self.num_heads,
            key_padding_mask=key_padding_mask, attn_mask=attn_mask,
            is_training=is_training, dropout_p=self.dropout,
            dropout_rng=dropout_rng,
            include_norm_add=self.include_norm_add,
            use_pallas=self.use_pallas,
        )
