"""NHWC GroupNorm with optional fused SiLU (ref: apex/contrib/group_norm,
ext ``group_norm_cuda`` — the diffusion-UNet-tuned kernels).

The reference ships two-pass and one-pass CUDA kernels over NHWC because
cuDNN GroupNorm wants NCHW. On TPU, NHWC is already the native layout and
XLA fuses (reduce → normalize → silu) into two HBM passes — the same IO as
the reference's two-pass kernel — so the implementation is jnp with fp32
statistics; the module surface (channel lists, act="silu") matches the
reference's ``GroupNorm``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# channel counts the reference's CUDA kernels support (group_norm.py::SUPPORTED_
# CHANNELS analog); on TPU any channel count works, kept for API parity checks
def group_norm_nhwc(x, gamma, beta, num_groups: int, eps: float = 1e-5,
                    act: str = "none"):
    """x: [N, H, W, C] (NHWC, TPU-native); gamma/beta: [C].

    Statistics are computed in fp32 over (H, W, C/G) per sample per group,
    matching the reference's Welford accumulation.
    """
    n, h, w, c = x.shape
    if c % num_groups:
        raise ValueError(f"channels {c} not divisible by groups {num_groups}")
    xg = x.reshape(n, h * w, num_groups, c // num_groups).astype(jnp.float32)
    mean = jnp.mean(xg, axis=(1, 3), keepdims=True)
    var = jnp.mean(jnp.square(xg - mean), axis=(1, 3), keepdims=True)
    xhat = (xg - mean) * jax.lax.rsqrt(var + eps)
    y = xhat.reshape(n, h, w, c)
    y = y * gamma.astype(jnp.float32) + beta.astype(jnp.float32)
    if act == "silu" or act == "swish":
        y = y * jax.nn.sigmoid(y)
    elif act != "none":
        raise ValueError(f"unsupported act {act!r} (reference supports silu)")
    return y.astype(x.dtype)


class GroupNorm:
    """Drop-in for apex.contrib.group_norm.GroupNorm (NHWC, optional fused
    SiLU via ``act="silu"``)."""

    def __init__(self, num_groups: int, num_channels: int, eps: float = 1e-5,
                 affine: bool = True, act: str = "none",
                 dtype=jnp.float32):
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.eps = eps
        self.affine = affine
        self.act = act
        self.params = {
            "weight": jnp.ones((num_channels,), dtype),
            "bias": jnp.zeros((num_channels,), dtype),
        } if affine else {}

    def __call__(self, x, params=None):
        p = self.params if params is None else params
        gamma = p.get("weight", jnp.ones((self.num_channels,), x.dtype))
        beta = p.get("bias", jnp.zeros((self.num_channels,), x.dtype))
        return group_norm_nhwc(x, gamma, beta, self.num_groups, self.eps,
                               self.act)
