"""Fused sigmoid focal loss (ref: apex/contrib/focal_loss, ext
``focal_loss_cuda``) — the RetinaNet classification loss with label
smoothing, fwd+bwd in one pass.

The reference kernel fuses one-hot expansion + sigmoid + focal weighting +
normalization (and writes the gradient in the same pass). On TPU this is a
bandwidth-bound elementwise pipeline that XLA fuses into a single HBM pass;
the custom_vjp below mirrors the reference's precomputed-gradient structure
so the backward is one fused multiply instead of re-deriving the chain.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def focal_loss(
    cls_output,
    cls_targets,
    num_positives_sum,
    num_real_classes: int,
    alpha: float = 0.25,
    gamma: float = 2.0,
    label_smoothing: float = 0.0,
):
    """Sum of sigmoid focal loss over all anchors / classes.

    cls_output: [..., num_classes_padded] raw logits.
    cls_targets: [...] int class ids; -1 = negative anchor (all-zero
    one-hot, like the reference), -2 = ignored anchor (zero loss).
    num_positives_sum: scalar normalizer (the reference divides the loss
    and gradient by it); an integer count (the natural caller type, and
    what the reference kernel takes) is cast to float HERE so the
    custom_vjp's zero cotangent matches the primal dtype under grad.
    num_real_classes: ignore padded logit columns beyond this count.
    """
    nps = jnp.asarray(num_positives_sum)
    if not jnp.issubdtype(nps.dtype, jnp.floating):
        nps = nps.astype(jnp.float32)
    return _focal_loss(cls_output, cls_targets, nps,
                       num_real_classes, alpha, gamma, label_smoothing)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _focal_loss(
    cls_output,
    cls_targets,
    num_positives_sum,
    num_real_classes: int,
    alpha: float = 0.25,
    gamma: float = 2.0,
    label_smoothing: float = 0.0,
):
    return _focal_fwd(cls_output, cls_targets, num_positives_sum,
                      num_real_classes, alpha, gamma, label_smoothing)[0]


def _focal_pieces(x, targets, num_real_classes, alpha, gamma,
                  label_smoothing):
    x = x.astype(jnp.float32)
    ncls = x.shape[-1]
    # one-hot with -1 -> all zeros; label smoothing as in the reference:
    # t = t*(1-s) + s/2
    onehot = jax.nn.one_hot(targets, ncls, dtype=jnp.float32)
    t = onehot * (1.0 - label_smoothing) + 0.5 * label_smoothing
    p = jax.nn.sigmoid(x)
    # focal terms, numerically-stable BCE from logits
    bce = jnp.maximum(x, 0.0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * t + (1.0 - p) * (1.0 - t)
    alpha_t = alpha * t + (1.0 - alpha) * (1.0 - t)
    w = alpha_t * (1.0 - p_t) ** gamma
    loss = w * bce
    # gradient of (w * bce) wrt x, fused like the reference kernel:
    #   d/dx bce = p - t
    #   d/dx w   = alpha_t * gamma * (1-p_t)^(gamma-1) * -(dp_t/dx)
    #   dp_t/dx  = (2t - 1) * p * (1-p)
    dpt_dx = (2.0 * t - 1.0) * p * (1.0 - p)
    dw_dx = -alpha_t * gamma * (1.0 - p_t) ** (gamma - 1.0) * dpt_dx
    grad = w * (p - t) + dw_dx * bce
    # masks: ignored anchors (-2) and padded classes
    keep_anchor = (targets >= -1)[..., None]
    keep_class = (
        jax.lax.broadcasted_iota(jnp.int32, (ncls,), 0) < num_real_classes
    )
    keep = keep_anchor & keep_class
    loss = jnp.where(keep, loss, 0.0)
    grad = jnp.where(keep, grad, 0.0)
    return loss, grad


def _focal_fwd(x, targets, num_positives_sum, num_real_classes, alpha,
               gamma, label_smoothing):
    nps = jnp.maximum(jnp.asarray(num_positives_sum, jnp.float32), 1.0)
    loss, grad = _focal_pieces(x, targets, num_real_classes, alpha, gamma,
                               label_smoothing)
    total = loss.sum() / nps
    dtype_token = jnp.zeros((), x.dtype)  # carries the primal dtype
    return total, (grad, nps, dtype_token)


def _focal_bwd(num_real_classes, alpha, gamma, label_smoothing, res, g):
    grad, nps, dtype_token = res
    dx = (g * grad / nps).astype(dtype_token.dtype)
    # no gradient to integer targets; num_positives_sum treated as constant
    # (the reference's kernel also only emits d/d_logits)
    return dx, None, jnp.zeros_like(nps)


_focal_loss.defvjp(_focal_fwd, _focal_bwd)


class FocalLoss:
    """Module veneer matching the reference call shape."""

    def __init__(self, num_real_classes: int, alpha: float = 0.25,
                 gamma: float = 2.0, label_smoothing: float = 0.0):
        self.num_real_classes = num_real_classes
        self.alpha = alpha
        self.gamma = gamma
        self.label_smoothing = label_smoothing

    def __call__(self, cls_output, cls_targets, num_positives_sum):
        return focal_loss(
            cls_output, cls_targets, num_positives_sum,
            self.num_real_classes, self.alpha, self.gamma,
            self.label_smoothing,
        )
