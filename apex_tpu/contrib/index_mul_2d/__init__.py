"""index_mul_2d (ref: apex/contrib/index_mul_2d, ext ``index_mul_2d_cuda``
— the OpenFold fused gather-multiply).

Semantics: ``out[i] = in1[idx[i]] * in2[i]`` over 2-D feature rows. The
reference fuses gather + multiply fwd and the scatter-add backward; XLA
compiles ``take`` + multiply into a fused gather and the transpose into a
segment-sum scatter, so a hand kernel adds nothing on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp


def index_mul_2d(in1, in2, idx):
    """in1: [N, D]; in2: [M, D]; idx: [M] int -> [M, D]."""
    return jnp.take(in1, idx, axis=0) * in2
