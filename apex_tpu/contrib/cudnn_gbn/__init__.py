"""cuDNN group batch norm parity surface (ref: apex/contrib/cudnn_gbn).

Same capability as :mod:`apex_tpu.contrib.groupbn` (NHWC BN with group
statistics over a mesh axis); kept as a named module for reference-script
parity.
"""

from apex_tpu.contrib.groupbn import (  # noqa: F401
    BatchNorm2d_NHWC,
    GroupBatchNorm2d,
    batch_norm_nhwc,
)
