"""Fused-norm gradient clipping (ref: apex/contrib/clip_grad).

Implementation lives in :mod:`apex_tpu.optimizers.clip_grad`.
"""

from apex_tpu.optimizers.clip_grad import (  # noqa: F401
    clip_grad_norm,
    clip_grad_norm_,
)
