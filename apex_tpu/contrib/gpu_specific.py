"""GPU-specific reference modules with no TPU analog (documented stubs).

- apex/contrib/nccl_allocator — ``ncclMemAlloc``-backed CUDA allocator for
  NCCL user-buffer registration. On TPU, XLA owns HBM allocation and
  collective buffers; there is nothing to register. (SURVEY.md §3.13 #19)
- apex/contrib/gpu_direct_storage — cuFile/GDS direct disk<->VRAM IO. The
  TPU-stack analog is async checkpointing via orbax with host staging,
  which is provided by the checkpoint helpers, not a file API here.

Importing these names raises with this explanation, mirroring the
reference's behavior when an extension was not built.
"""


def _unavailable(name: str, why: str):
    def _raise(*args, **kwargs):
        raise NotImplementedError(
            f"{name} is GPU-specific and has no TPU analog: {why}"
        )

    return _raise


nccl_allocator_init = _unavailable(
    "nccl_allocator", "XLA owns device memory and collective buffers on TPU"
)
GDSFile = _unavailable(
    "gpu_direct_storage.GDSFile",
    "use orbax async checkpointing for high-throughput TPU IO",
)
