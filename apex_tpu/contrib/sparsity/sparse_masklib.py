"""m:n structured sparsity mask computation.

Ref: apex/contrib/sparsity/sparse_masklib.py::create_mask — computes 0/1
masks keeping the n largest-magnitude entries of every group of m along the
row dimension (pattern "m4n2_1d" = 2:4, the Ampere sparse-tensor-core
layout). TPU has no 2:4 hardware path, but the capability (mask search,
pruning workflow, mask maintenance across optimizer steps) is
hardware-agnostic; masks are computed with a vectorized top-k per group.
"""

from __future__ import annotations

import jax.numpy as jnp


def _mn_1d_mask(w2, m: int, n: int):
    """w2: [R, C] with C % m == 0. Keep the n largest |w| per group of m."""
    r, c = w2.shape
    groups = w2.reshape(r, c // m, m)
    mag = jnp.abs(groups)
    # rank entries within each group; keep the top n
    order = jnp.argsort(mag, axis=-1)  # ascending
    ranks = jnp.argsort(order, axis=-1)
    keep = ranks >= (m - n)
    return keep.reshape(r, c).astype(w2.dtype)


def create_mask(tensor, pattern: str = "m4n2_1d"):
    """Returns a 0/1 mask of ``tensor``'s shape for the given pattern.

    Supported patterns (reference names): "m4n2_1d" (2:4), "m8n2_1d",
    and the generic "m<M>n<N>_1d". 1-D/0-D tensors and tensors whose last
    dim is not divisible by m are left dense (mask of ones) — matching the
    reference's eligibility rule (it only prunes >=2-D weights with
    compatible shapes).
    """
    if not (pattern.startswith("m") and "_1d" in pattern and "n" in pattern):
        raise ValueError(f"unsupported sparsity pattern {pattern!r}")
    body = pattern[: pattern.index("_")]
    m_str, n_str = body[1:].split("n")
    m, n = int(m_str), int(n_str)
    if tensor.ndim < 2 or tensor.shape[-1] % m != 0:
        return jnp.ones_like(tensor)
    w2 = tensor.reshape(-1, tensor.shape[-1])
    return _mn_1d_mask(w2, m, n).reshape(tensor.shape)
