"""Channel-permutation search for 2:4 structured sparsity.

Ref: apex/contrib/sparsity/permutation_lib.py (+ the permutation_search_cuda
kernels): permuting a weight's INPUT channels before applying the m:n mask
can keep substantially more magnitude, because the mask operates on fixed
groups of ``m`` consecutive channels — the search moves "competing" large
channels into different groups.

TPU design: instead of the reference's CUDA exhaustive/bounded-regression
search, the search is a jit-compiled stochastic greedy over GROUP PAIRS:
each sweep randomly pairs the C/m channel groups, and every pair evaluates
all m*m single-channel exchanges (plus identity) in parallel (vmap), taking
the best. Each accepted exchange monotonically increases total retained
magnitude, all shapes are static, and the whole search is one ``lax.scan``
— no host round trips. This is the same hill-climbing move set as the
reference's `Exhaustive_Search` channel swaps, vectorized per sweep.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _retained(cols_abs: jax.Array, n: int) -> jax.Array:
    """cols_abs: (..., rows, m) -> retained magnitude (...,) keeping the
    top-``n`` of each row's m entries (what an m:n mask preserves)."""
    top = jnp.sort(cols_abs, axis=-1)[..., -n:]
    return jnp.sum(top, axis=(-2, -1))


def permutation_efficacy(weight: jax.Array, perm: jax.Array, m: int = 4,
                         n: int = 2) -> jax.Array:
    """Total |magnitude| an m:n mask keeps after permuting input channels."""
    w = jnp.abs(weight.reshape(-1, weight.shape[-1]).astype(jnp.float32))
    wp = w[:, perm]
    r, c = wp.shape
    groups = wp.reshape(r, c // m, m).transpose(1, 0, 2)  # (G, rows, m)
    return jnp.sum(_retained(groups, n))


@functools.partial(jax.jit, static_argnames=("m", "n", "sweeps"))
def search_channel_permutation(weight: jax.Array, *, m: int = 4, n: int = 2,
                               sweeps: int = 32,
                               key: jax.Array | None = None) -> jax.Array:
    """Find a permutation of the input channels (last axis) that increases
    the magnitude an m:n mask retains. Returns ``perm`` (int32 [C]); apply
    with ``weight[..., perm]`` (see apply_channel_permutation).
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    w = jnp.abs(weight.reshape(-1, weight.shape[-1]).astype(jnp.float32))
    rows, c = w.shape
    assert c % m == 0, f"channels {c} not a multiple of group size {m}"
    g = c // m
    npairs = g // 2

    def sweep(perm, key):
        # random disjoint group pairing for this sweep
        order = jax.random.permutation(key, g)
        pg = perm.reshape(g, m)[order]  # (G, m) channel ids, paired 2k/2k+1
        a = pg[0::2][:npairs]  # (P, m)
        b = pg[1::2][:npairs]

        def best_exchange(a_ids, b_ids):
            # candidates: identity + every single swap (i from a, j from b)
            ii, jj = jnp.meshgrid(jnp.arange(m), jnp.arange(m), indexing="ij")
            ii, jj = ii.reshape(-1), jj.reshape(-1)  # (m*m,)

            def cand(i, j):
                na = a_ids.at[i].set(b_ids[j])
                nb = b_ids.at[j].set(a_ids[i])
                return na, nb

            cas, cbs = jax.vmap(cand)(ii, jj)           # (m*m, m)
            cas = jnp.concatenate([a_ids[None], cas])    # (1+m*m, m)
            cbs = jnp.concatenate([b_ids[None], cbs])
            # w is already |weight| (function entry) — no abs here
            score = (_retained(w[:, cas].transpose(1, 0, 2), n)
                     + _retained(w[:, cbs].transpose(1, 0, 2), n))
            k = jnp.argmax(score)  # identity wins ties (index 0)
            return cas[k], cbs[k]

        na, nb = jax.vmap(best_exchange)(a, b)
        pg = pg.at[0::2].set(jnp.concatenate([na, pg[0::2][npairs:]])
                             if g % 2 else na)
        pg = pg.at[1::2].set(nb)
        # undo the pairing shuffle: scatter groups back to their slots
        out = jnp.zeros_like(pg).at[order].set(pg)
        return out.reshape(-1), None

    keys = jax.random.split(key, sweeps)
    perm, _ = jax.lax.scan(sweep, jnp.arange(c, dtype=jnp.int32), keys)
    return perm


def apply_channel_permutation(weight: jax.Array, perm: jax.Array) -> jax.Array:
    """Permute input channels (last axis). The producing layer upstream must
    permute its OUTPUT rows with the same perm to keep the network function
    identical — see invert_permutation for consumers."""
    return weight[..., perm]


def invert_permutation(perm: jax.Array) -> jax.Array:
    return jnp.zeros_like(perm).at[perm].set(jnp.arange(perm.shape[0], dtype=perm.dtype))
