"""ASP — the pruning workflow around the mask library.

Ref: apex/contrib/sparsity/asp.py::ASP (init_model_for_pruning /
init_optimizer_for_pruning / compute_sparse_masks / restore_pruned_weights).
The reference hooks torch optimizer.step to re-mask weights after every
update; the JAX equivalent is an ``optax`` wrapper that masks the updates
(params, once masked, then stay masked), plus functional helpers. A thin
class keeps the reference's classmethod workflow for script parity.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from apex_tpu.contrib.sparsity.sparse_masklib import create_mask


def _eligible(path, leaf, whitelist: Optional[Callable]) -> bool:
    if leaf.ndim < 2:
        return False
    if whitelist is None:
        return True
    return whitelist(path, leaf)


def compute_sparse_masks(params, pattern: str = "m4n2_1d",
                         whitelist: Optional[Callable] = None):
    """Returns a mask pytree (1.0 everywhere for ineligible leaves).

    ``whitelist(path, leaf) -> bool`` selects prunable leaves (the
    reference whitelists [nn.Linear, nn.Conv2d] module types; paths play
    that role here)."""
    def mask_leaf(path, leaf):
        if _eligible(path, leaf, whitelist):
            return create_mask(leaf, pattern)
        return jnp.ones_like(leaf)

    return jax.tree_util.tree_map_with_path(mask_leaf, params)


def apply_masks(params, masks):
    return jax.tree.map(lambda p, m: (p * m).astype(p.dtype), params, masks)


def masked_optimizer(tx: optax.GradientTransformation,
                     masks) -> optax.GradientTransformation:
    """Wrap an optax transform so updates (and hence params) stay sparse —
    the analog of the reference's optimizer step/state masking hooks."""

    def init_fn(params):
        return tx.init(params)

    def update_fn(grads, state, params=None):
        updates, new_state = tx.update(grads, state, params)
        updates = jax.tree.map(
            lambda u, m: (u * m).astype(u.dtype), updates, masks
        )
        return updates, new_state

    return optax.GradientTransformation(init_fn, update_fn)


class ASP:
    """Classmethod workflow mirroring the reference's ASP surface."""

    _masks = None
    _pattern = "m4n2_1d"
    _whitelist = None

    @classmethod
    def init_model_for_pruning(cls, params, mask_calculator: str = "m4n2_1d",
                               whitelist: Optional[Callable] = None,
                               allow_recompute_mask: bool = False):
        del allow_recompute_mask  # masks are cheap to recompute in JAX
        cls._pattern = mask_calculator
        cls._whitelist = whitelist
        cls._masks = compute_sparse_masks(params, mask_calculator, whitelist)
        return cls._masks

    @classmethod
    def init_optimizer_for_pruning(cls, tx: optax.GradientTransformation):
        if cls._masks is None:
            raise RuntimeError("call init_model_for_pruning first")
        return masked_optimizer(tx, cls._masks)

    @classmethod
    def compute_sparse_masks(cls, params):
        cls._masks = compute_sparse_masks(params, cls._pattern, cls._whitelist)
        return apply_masks(params, cls._masks), cls._masks

    @classmethod
    def restore_pruned_weights(cls, params):
        """Pruning is non-destructive here (masks live outside params);
        restoring = dropping the masks."""
        cls._masks = None
        return params
