"""FastLayerNorm (ref: apex/contrib/layer_norm, ext ``fast_layer_norm``).

The reference's persistent-CTA wide-hidden LN is a CUDA scheduling trick;
the Pallas LN kernel in :mod:`apex_tpu.ops.layer_norm` already blocks rows
in VMEM for any hidden size, so FastLayerNorm is the same kernel under the
contrib name (SURVEY.md §3.13 item 10).
"""

from __future__ import annotations

from apex_tpu.normalization import FusedLayerNorm
from apex_tpu.ops.layer_norm import layer_norm  # noqa: F401


class FastLayerNorm(FusedLayerNorm):
    """Drop-in for apex.contrib.layer_norm.FastLayerNorm."""
