"""Fused Conv+Bias(+Mask)(+ReLU) (ref: apex/contrib/conv_bias_relu, ext
``fused_conv_bias_relu`` over cudnn-frontend runtime fusion).

On TPU, XLA fuses the bias/ReLU epilogue into the convolution automatically;
these wrappers pin the reference's NHWC layout and epilogue set. All are
differentiable through JAX autodiff (the reference ships hand backward
passes for the same chains).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_DN = ("NHWC", "HWIO", "NHWC")


def _conv(x, w, stride, padding):
    strides = (stride, stride) if isinstance(stride, int) else stride
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    return jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding, dimension_numbers=_DN,
        preferred_element_type=jnp.float32,
    )


def conv_bias(x, weight, bias, stride=1, padding=0):
    """ConvBias: NHWC conv + channel bias."""
    y = _conv(x, weight, stride, padding) + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def conv_bias_relu(x, weight, bias, stride=1, padding=0):
    """ConvBiasReLU (ref: ConvBiasReLU_.apply)."""
    y = _conv(x, weight, stride, padding) + bias.astype(jnp.float32)
    return jax.nn.relu(y).astype(x.dtype)


def conv_bias_mask_relu(x, weight, bias, mask, stride=1, padding=0):
    """ConvBiasMaskReLU: multiply by a (0/1) mask before the ReLU."""
    y = _conv(x, weight, stride, padding) + bias.astype(jnp.float32)
    y = y * mask.astype(jnp.float32)
    return jax.nn.relu(y).astype(x.dtype)


def conv_frozen_scale_bias_relu(x, weight, scale, bias, stride=1, padding=0):
    """ConvFrozenScaleBiasReLU: conv, then y*scale + bias, then ReLU
    (frozen-BatchNorm inference folding)."""
    y = _conv(x, weight, stride, padding)
    y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return jax.nn.relu(y).astype(x.dtype)


# reference class-style aliases
ConvBias = conv_bias
ConvBiasReLU = conv_bias_relu
ConvBiasMaskReLU = conv_bias_mask_relu
ConvFrozenScaleBiasReLU = conv_frozen_scale_bias_relu
