"""Fused ResNet bottleneck + spatial-parallel variant (ref:
apex/contrib/bottleneck)."""

from apex_tpu.contrib.bottleneck.bottleneck import (  # noqa: F401
    Bottleneck,
    SpatialBottleneck,
    bottleneck_apply,
    bottleneck_init,
    spatial_bottleneck_apply,
)
