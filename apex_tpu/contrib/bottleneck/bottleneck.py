"""ResNet bottleneck with fused conv epilogues, plus the spatial-parallel
variant with halo exchange.

Ref: apex/contrib/bottleneck/bottleneck.py::Bottleneck/SpatialBottleneck +
csrc ``fast_bottleneck`` (cudnn runtime fusion of conv+frozen-BN scale/bias
+relu chains) and ``halo_exchangers``. The reference folds BatchNorm into
per-channel (scale, bias) — training keeps them frozen (the MLPerf
RetinaNet trick) — and fuses everything into three conv+epilogue calls.
XLA does the same fusion for the NHWC convs below.

SpatialBottleneck: the input is sharded along H over a named mesh axis;
only the 3x3 conv sees neighbor rows, so one ``halo_exchange_1d`` per
block supplies a 1-row halo and the conv runs VALID along H. Must be
called under ``shard_map``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.contrib.conv_bias_relu import (
    _conv,
    conv_frozen_scale_bias_relu,
)
from apex_tpu.contrib.peer_memory.halo_exchange import halo_exchange_1d


def bottleneck_init(key, in_ch: int, bottleneck_ch: int, out_ch: int,
                    *, stride: int = 1, dtype=jnp.float32):
    """Conv weights (HWIO) + folded-BN scale/bias per conv; a projection
    shortcut is created when shape changes (like torchvision/reference)."""
    ks = jax.random.split(key, 4)

    def he(k, shape):
        fan_in = shape[0] * shape[1] * shape[2]
        return (jax.random.normal(k, shape) * (2.0 / fan_in) ** 0.5).astype(dtype)

    params = {
        "conv1": {"w": he(ks[0], (1, 1, in_ch, bottleneck_ch)),
                  "scale": jnp.ones((bottleneck_ch,), dtype),
                  "bias": jnp.zeros((bottleneck_ch,), dtype)},
        "conv2": {"w": he(ks[1], (3, 3, bottleneck_ch, bottleneck_ch)),
                  "scale": jnp.ones((bottleneck_ch,), dtype),
                  "bias": jnp.zeros((bottleneck_ch,), dtype)},
        "conv3": {"w": he(ks[2], (1, 1, bottleneck_ch, out_ch)),
                  "scale": jnp.ones((out_ch,), dtype),
                  "bias": jnp.zeros((out_ch,), dtype)},
    }
    if stride != 1 or in_ch != out_ch:
        params["downsample"] = {
            "w": he(ks[3], (1, 1, in_ch, out_ch)),
            "scale": jnp.ones((out_ch,), dtype),
            "bias": jnp.zeros((out_ch,), dtype),
        }
    return params


def bottleneck_apply(params, x, *, stride: int = 1):
    """x: [N, H, W, C]. stride applies to the 3x3 (torchvision v1.5 / the
    reference's layout)."""
    c1 = params["conv1"]
    y = conv_frozen_scale_bias_relu(x, c1["w"], c1["scale"], c1["bias"],
                                    stride=1, padding=0)
    c2 = params["conv2"]
    y = conv_frozen_scale_bias_relu(y, c2["w"], c2["scale"], c2["bias"],
                                    stride=stride, padding=1)
    c3 = params["conv3"]
    y = _conv(y, c3["w"], 1, [(0, 0), (0, 0)])
    y = y * c3["scale"].astype(jnp.float32) + c3["bias"].astype(jnp.float32)
    if "downsample" in params:
        d = params["downsample"]
        sc = _conv(x, d["w"], stride, [(0, 0), (0, 0)])
        sc = sc * d["scale"].astype(jnp.float32) + d["bias"].astype(jnp.float32)
    else:
        sc = x.astype(jnp.float32)
    return jax.nn.relu(y + sc).astype(x.dtype)


def spatial_bottleneck_apply(params, x, axis_name: str, *,
                             halo_dim: int = 1):
    """Spatial-parallel bottleneck (stride 1): x is the local H-shard of an
    NHWC tensor sharded over ``axis_name``. One halo exchange feeds the 3x3
    conv; all 1x1 convs and the residual are purely local."""
    c1 = params["conv1"]
    y = conv_frozen_scale_bias_relu(x, c1["w"], c1["scale"], c1["bias"],
                                    stride=1, padding=0)
    # exchange 1-row halos, then conv VALID along H (the halo supplies the
    # padding interior ranks need; edge ranks see zeros = zero padding)
    y = halo_exchange_1d(y, axis_name, halo=1, dim=halo_dim)
    c2 = params["conv2"]
    y = _conv(y, c2["w"], 1, [(0, 0), (1, 1)])
    y = jax.nn.relu(
        y * c2["scale"].astype(jnp.float32) + c2["bias"].astype(jnp.float32)
    ).astype(x.dtype)
    c3 = params["conv3"]
    y = _conv(y, c3["w"], 1, [(0, 0), (0, 0)])
    y = y * c3["scale"].astype(jnp.float32) + c3["bias"].astype(jnp.float32)
    if "downsample" in params:
        d = params["downsample"]
        sc = _conv(x, d["w"], 1, [(0, 0), (0, 0)])
        sc = sc * d["scale"].astype(jnp.float32) + d["bias"].astype(jnp.float32)
    else:
        sc = x.astype(jnp.float32)
    return jax.nn.relu(y + sc).astype(x.dtype)


class Bottleneck:
    """Veneer holding params (ref constructor: in_channels, bottleneck_
    channels, out_channels, stride)."""

    def __init__(self, in_channels: int, bottleneck_channels: int,
                 out_channels: int, stride: int = 1, key=None,
                 dtype=jnp.float32):
        key = jax.random.PRNGKey(0) if key is None else key
        self.stride = stride
        self.params = bottleneck_init(
            key, in_channels, bottleneck_channels, out_channels,
            stride=stride, dtype=dtype,
        )

    def __call__(self, x, params=None):
        return bottleneck_apply(self.params if params is None else params,
                                x, stride=self.stride)


class SpatialBottleneck(Bottleneck):
    """Spatial-parallel veneer (ref: SpatialBottleneck; halo exchangers are
    replaced by the mesh axis)."""

    def __init__(self, in_channels: int, bottleneck_channels: int,
                 out_channels: int, axis_name: str = "spatial", key=None,
                 dtype=jnp.float32):
        super().__init__(in_channels, bottleneck_channels, out_channels,
                         stride=1, key=key, dtype=dtype)
        self.axis_name = axis_name

    def __call__(self, x, params=None):
        return spatial_bottleneck_apply(
            self.params if params is None else params, x, self.axis_name
        )
