"""FMHA — fused attention over packed variable-length batches.

Ref: apex/contrib/fmha/fmha.py::FMHAFun (ext ``fmhalib``): fixed-seqlen
(≤512) fused attention over a packed [total_tokens, 3, heads, d] qkv tensor
with ``cu_seqlens`` prefix offsets. TPU/XLA wants static shapes, so the
idiomatic equivalent takes the padded [batch, seq, 3, heads, d] layout plus
per-example lengths and masks padded keys inside the flash kernel; helpers
convert between the packed and padded layouts.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import flash_attention


def fmha(qkv, seqlens=None, *, causal: bool = False, scale: float | None = None,
         dropout_p: float = 0.0, dropout_rng=None, use_pallas=None):
    """qkv: [batch, seq, 3, heads, d]; seqlens: [batch] int32 valid lengths
    (None = all full). Returns [batch, seq, heads, d] with padded query rows
    zeroed (the reference writes nothing for padded tokens)."""
    b, s, three, h, d = qkv.shape
    if three != 3:
        raise ValueError("qkv must be [batch, seq, 3, heads, d]")
    q = qkv[:, :, 0].transpose(0, 2, 1, 3)  # [b, h, s, d]
    k = qkv[:, :, 1].transpose(0, 2, 1, 3)
    v = qkv[:, :, 2].transpose(0, 2, 1, 3)
    mask = None
    if seqlens is not None:
        valid = jnp.arange(s)[None, :] < seqlens[:, None]      # [b, s]
        mask = (~valid)[:, None, None, :]                      # key mask
    o = flash_attention(
        q, k, v, mask=mask, causal=causal, scale=scale,
        dropout_p=dropout_p, dropout_rng=dropout_rng, use_pallas=use_pallas,
    )
    o = o.transpose(0, 2, 1, 3)                                # [b, s, h, d]
    if seqlens is not None:
        o = jnp.where(valid[:, :, None, None], o, 0.0).astype(o.dtype)
    return o


def pack_qkv(qkv_padded, seqlens):
    """[batch, seq, 3, h, d] + lengths -> packed [total, 3, h, d] +
    cu_seqlens (host-side helper for reference-format interop)."""
    b, s = qkv_padded.shape[:2]
    valid = jnp.arange(s)[None, :] < seqlens[:, None]
    idx = jnp.nonzero(valid.reshape(-1))[0]
    packed = qkv_padded.reshape(b * s, *qkv_padded.shape[2:])[idx]
    cu = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                          jnp.cumsum(seqlens).astype(jnp.int32)])
    return packed, cu


def unpack_output(packed, cu_seqlens, seq: int):
    """Inverse of :func:`pack_qkv` for the output tensor."""
    b = cu_seqlens.shape[0] - 1
    out = jnp.zeros((b, seq) + packed.shape[1:], packed.dtype)
    for i in range(b):  # host-side helper; not jitted
        n = int(cu_seqlens[i + 1] - cu_seqlens[i])
        out = out.at[i, :n].set(packed[int(cu_seqlens[i]):int(cu_seqlens[i + 1])])
    return out


class FMHA:
    """Module veneer over :func:`fmha` (ref: apex/contrib/fmha)."""

    def __init__(self, *, causal: bool = False, dropout_p: float = 0.0):
        self.causal = causal
        self.dropout_p = dropout_p

    def __call__(self, qkv, seqlens=None, *, is_training=True,
                 dropout_rng=None):
        p = self.dropout_p if is_training else 0.0
        return fmha(qkv, seqlens, causal=self.causal, dropout_p=p,
                    dropout_rng=dropout_rng)
