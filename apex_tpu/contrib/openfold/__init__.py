"""OpenFold kernel surface — the analog of apex/contrib/openfold_triton.

Ref: apex/contrib/openfold_triton/* (SURVEY.md §3.10 row `openfold_triton`):
the reference's one non-CUDA kernel family — Triton LayerNorm fwd/bwd, the
fused evoformer MHA (additive pair bias + sigmoid gating), and the
swish/transition epilogues used by OpenFold's Evoformer blocks.

TPU mapping: every piece is backed by an existing apex_tpu kernel or an
XLA-fused jnp expression —
- LayerNorm       -> the Pallas LN family (ops/layer_norm.py)
- fused MHA       -> the Pallas flash kernel (ops/attention.py) with the
                     pair bias folded into its additive-bias input and the
                     boolean mask folded to -30000 (finite for bf16, the
                     reference's own mask fill convention)
- swish / swiglu  -> jnp expressions XLA fuses into the surrounding matmuls
- DAP             -> dynamic axial parallelism = shard the row/column axis
                     of the pair representation over a mesh axis; the
                     scatter/gather/transpose moves are custom-vjp
                     collectives like transformer/tensor_parallel/mappings
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.normalization.fused_layer_norm import (  # noqa: F401 — re-export
    FusedLayerNorm as LayerNorm,
    fused_layer_norm as layer_norm,
)
from apex_tpu.ops.attention import flash_attention

def swish(x):
    """SiLU. XLA fuses this into the producing matmul's epilogue."""
    return x * jax.nn.sigmoid(x)


def swiglu_transition(x, w_gate, w_up, w_down):
    """Gated transition block: (swish(x @ w_gate) * (x @ w_up)) @ w_down.
    One fused fwd pass under jit; fp32 MXU accumulation."""
    f32 = functools.partial(jnp.einsum, preferred_element_type=jnp.float32)
    gate = swish(f32("...h,hf->...f", x, w_gate))
    up = f32("...h,hf->...f", x, w_up)
    return f32("...f,fh->...h", (gate * up).astype(x.dtype), w_down).astype(x.dtype)


def mha(q, k, v, *, mask=None, bias=None, gate=None, use_pallas=None):
    """Fused evoformer attention (ref: openfold_triton mha):

        softmax(q·kᵀ/√d + bias + mask_bias) · v, optionally gated by
        sigmoid(gate) elementwise.

    Shapes: q/k/v ``(*batch, heads, seq, dim)`` (any number of leading batch
    dims — OpenFold passes [B, N_res] or [B, N_seq] there). ``mask`` is
    boolean ``(*batch, 1|heads, 1|seq_q, seq_k)`` (True = attend);
    ``bias`` is the additive pair bias broadcastable to
    ``(*batch, heads, seq_q, seq_k)``. ``gate`` matches q's shape.
    """
    # The boolean mask rides flash_attention's MASK path (True = MASKED
    # there, = attend here): no bias gradient is wanted for it, so the
    # backward stays O(block) — folding it into ``bias`` would force the
    # dense dbias pass and refuse streaming lengths. Only a real pair
    # bias is differentiable. A fully-masked query row returns 0 (the
    # flash kernel's gradient-safe convention) rather than the
    # reference's uniform -30000-fill attention; OpenFold never attends
    # from fully-masked rows, so the difference is unobservable there.
    o = flash_attention(
        q, k, v, bias=bias,
        mask=None if mask is None else ~jnp.asarray(mask, bool),
        causal=False, use_pallas=use_pallas,
    )
    if gate is not None:
        o = (o.astype(jnp.float32) * jax.nn.sigmoid(gate.astype(jnp.float32))).astype(o.dtype)
    return o


# --------------------------------------------------------------------------
# DAP — dynamic axial parallelism over a named mesh axis
# --------------------------------------------------------------------------

def dap_scatter(x, axis: str, dim: int):
    """Split ``dim`` across the mesh axis (enter DAP). Inside shard_map."""
    rank = jax.lax.axis_index(axis)
    n = jax.lax.axis_size(axis)
    assert x.shape[dim] % n == 0, (x.shape, dim, n)
    return jax.lax.dynamic_slice_in_dim(
        x, rank * (x.shape[dim] // n), x.shape[dim] // n, axis=dim
    )


def dap_gather(x, axis: str, dim: int):
    """All-gather ``dim`` from the mesh axis (leave DAP)."""
    return jax.lax.all_gather(x, axis, axis=dim, tiled=True)


def dap_row_to_col(x, axis: str, row_dim: int, col_dim: int):
    """Switch the sharded axis of the pair representation from rows to
    columns (the evoformer's transpose communication): all-to-all over ICI."""
    return jax.lax.all_to_all(
        x, axis, split_axis=col_dim, concat_axis=row_dim, tiled=True
    )


def dap_col_to_row(x, axis: str, row_dim: int, col_dim: int):
    return jax.lax.all_to_all(
        x, axis, split_axis=row_dim, concat_axis=col_dim, tiled=True
    )
