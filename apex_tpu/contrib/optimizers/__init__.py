"""Distributed (ZeRO-style) optimizers + deprecated contrib aliases
(ref: apex/contrib/optimizers)."""

from apex_tpu.contrib.optimizers.distributed_fused_adam import (  # noqa: F401
    DistributedFusedAdam,
)
from apex_tpu.contrib.optimizers.distributed_fused_lamb import (  # noqa: F401
    DistributedFusedLAMB,
)

# Deprecated reference names (apex/contrib/optimizers/fused_adam.py etc.)
# alias the core implementations, as SURVEY.md §3.13 #16 prescribes.
from apex_tpu.optimizers import (  # noqa: F401
    FusedAdam,
    FusedLAMB,
)
from apex_tpu.fp16_utils import FP16_Optimizer  # noqa: F401
