"""Flat-shard machinery for ZeRO-style optimizers.

Ref: apex/contrib/optimizers/distributed_fused_adam.py — the reference
flattens params into fixed-size buckets, reduce-scatters gradient buckets
as backward hooks fire, updates each rank's shard with fused kernels, and
all-gathers updated params. Under XLA the hook/stream choreography is
replaced by one reduce_scatter + one all_gather per step inside
``shard_map`` (XLA overlaps them with adjacent compute); what this module
keeps from the reference is the *flat-shard state layout* (fp32 master +
moments live only in 1/N of HBM per device — the actual ZeRO memory win)
and per-tensor bookkeeping via segment ids (the analog of the reference's
per-tensor chunk metadata, needed for LAMB trust ratios).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.observability import inc_counter
from apex_tpu.utils.profiling import trace_range


class FlatMeta(NamedTuple):
    treedef: object
    shapes: tuple
    dtypes: tuple
    sizes: tuple
    padded_total: int
    num_tensors: int      # total per-tensor segments (stacked leaves count L)
    sub_counts: tuple     # per leaf: 1, or L for a lax.scan-stacked [L, ...]


def flat_meta(params, n_shards: int,
              stacked_key: str | None = "layers") -> FlatMeta:
    """``stacked_key``: dict key marking scan-stacked [L, ...] collections
    (``testing.stack_layer_params``). Each such leaf contributes L segment
    ids — one per layer slice — so per-tensor bookkeeping (LAMB trust
    ratios) keeps the reference's per-layer-tensor granularity."""
    from apex_tpu.utils.pytree import stacked_flags

    leaves, treedef = jax.tree.flatten(params)
    shapes = tuple(l.shape for l in leaves)
    dtypes = tuple(l.dtype for l in leaves)
    sizes = tuple(int(l.size) for l in leaves)
    flags = stacked_flags(params, stacked_key)
    sub_counts = tuple(
        int(l.shape[0]) if f else 1 for f, l in zip(flags, leaves)
    )
    total = sum(sizes)
    padded_total = -(-total // n_shards) * n_shards
    return FlatMeta(treedef, shapes, dtypes, sizes, padded_total,
                    sum(sub_counts), sub_counts)


def flatten_fp32(tree, meta: FlatMeta):
    leaves = jax.tree.leaves(tree)
    flat = jnp.concatenate(
        [l.reshape(-1).astype(jnp.float32) for l in leaves]
    )
    pad = meta.padded_total - flat.shape[0]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    return flat


def unflatten(flat, meta: FlatMeta):
    out = []
    off = 0
    for shape, dtype, size in zip(meta.shapes, meta.dtypes, meta.sizes):
        out.append(flat[off:off + size].reshape(shape).astype(dtype))
        off += size
    return jax.tree.unflatten(meta.treedef, out)


def tensor_ids(meta: FlatMeta):
    """int32 [padded_total]: which per-tensor segment each flat element
    belongs to. A stacked [L, ...] leaf spans L consecutive ids (its flat
    layout is layer-major, so each layer slice is contiguous); padding gets
    id num_tensors — an extra dead segment."""
    ids = []
    nxt = 0
    for size, subs in zip(meta.sizes, meta.sub_counts):
        if subs == 1:
            ids.append(jnp.full((size,), nxt, jnp.int32))
        else:
            per = size // subs
            ids.append(jnp.repeat(
                jnp.arange(nxt, nxt + subs, dtype=jnp.int32), per))
        nxt += subs
    pad = meta.padded_total - sum(meta.sizes)
    if pad:
        ids.append(jnp.full((pad,), meta.num_tensors, jnp.int32))
    return jnp.concatenate(ids)


def my_shard(flat, axis_name: str):
    """Slice this device's contiguous shard of a flat [padded_total] array
    (call inside shard_map)."""
    n = lax.psum(1, axis_name)
    shard_size = flat.shape[0] // n
    idx = lax.axis_index(axis_name)
    return lax.dynamic_slice_in_dim(flat, idx * shard_size, shard_size)


def reduce_scatter_flat(flat, axis_name: str, *, mean: bool = True,
                        quantized: bool | None = None):
    """reduce_scatter a flat gradient so each device owns the reduced
    values of its shard (ref: the per-bucket reduce-scatter hooks).

    ``quantized=None`` follows ``APEX_TPU_QUANTIZED_COMMS``; True routes
    through the int8 per-chunk-scaled psum_scatter with error
    compensation (parallel/quantized_collectives.py — halves the wire
    bytes of the ZeRO-2 gradient reduce-scatter). False (or the gate off)
    is the exact path, bitwise-identical to the unquantized
    implementation."""
    n = lax.psum(1, axis_name)
    if quantized is None:
        from apex_tpu.parallel.overlap import quantized_comms_enabled

        quantized = quantized_comms_enabled()
    # profiling seam (ref: nvtx around the per-bucket reduce-scatter
    # hooks) + trace-time bytes-on-wire accounting (static sizes)
    with trace_range("zero_reduce_scatter_flat"):
        if quantized:
            from apex_tpu.parallel.quantized_collectives import (
                quantized_psum_scatter,
                quantized_scatter_wire_bytes,
            )

            inc_counter(
                "comms/bytes_on_wire",
                quantized_scatter_wire_bytes(flat.shape[0],
                                             lax.axis_size(axis_name)),
                path="zero", collective="psum_scatter", mode="int8")
            shard = quantized_psum_scatter(flat, axis_name)
        else:
            inc_counter(
                "comms/bytes_on_wire",
                flat.shape[0] * flat.dtype.itemsize,
                path="zero", collective="psum_scatter", mode="exact")
            shard = lax.psum_scatter(
                flat.reshape(n, flat.shape[0] // n), axis_name,
                scatter_dimension=0, tiled=False,
            )
    if mean:
        shard = shard / n
    return shard


def all_gather_flat(shard, axis_name: str, *, chunks: int = 1):
    """Inverse: gather every device's updated shard into the full flat
    array (ref: the all-gather of updated params).

    Implemented as place-in-zeros + psum rather than ``lax.all_gather``:
    JAX's varying-manual-axes checker cannot statically infer that an
    all_gather output is replicated (no all_gather_invariant in this JAX),
    and the optimizer's contract is that the returned params are replicated
    across the axis. XLA lowers this to one all-reduce over ICI.

    ``chunks > 1`` splits the shard into that many independently-psummed
    pieces. The full array is only assembled locally, so a consumer that
    needs early pieces (the ZeRO allgather-prefetch path: the embedding
    and first layers' params live at low flat offsets) can start compute
    as soon as its pieces land while later pieces are still in flight —
    the monolithic form serializes everything behind one collective.
    ``chunks=1`` is the original single-psum path, bit-for-bit.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    s = shard.shape[0]
    chunks = max(1, min(int(chunks), s)) if s else 1
    # the param-gather leg of the ZeRO bucket flush: one allreduce-sized
    # payload per step (place-in-zeros + psum, see docstring)
    inc_counter("comms/bytes_on_wire",
                lax.axis_size(axis_name) * s * shard.dtype.itemsize,
                path="zero", collective="allgather_params", mode="exact")
    if chunks == 1:
        with trace_range("zero_allgather_params"):
            full = jnp.zeros((n * s,), shard.dtype)
            full = lax.dynamic_update_slice_in_dim(full, shard, idx * s, 0)
            return lax.psum(full, axis_name)
    base = -(-s // chunks)  # ceil; ragged last piece
    full = jnp.zeros((n * s,), shard.dtype)
    with trace_range("zero_allgather_params_chunked"):
        for off in range(0, s, base):
            sz = min(base, s - off)
            piece = lax.dynamic_slice_in_dim(shard, off, sz, 0)
            buf = jnp.zeros((n * sz,), shard.dtype)
            buf = lax.dynamic_update_slice_in_dim(buf, piece, idx * sz, 0)
            buf = lax.psum(buf, axis_name)
            gathered = buf.reshape(-1, sz)  # row r = rank r's piece
            full = full.reshape(-1, s).at[:, off:off + sz].set(
                gathered).reshape(-1)
    return full


def per_tensor_sq_norms(x_shard, ids_shard, num_tensors: int,
                        axis_name: str):
    """Per-tensor sum-of-squares from flat shards: local segment-sum by
    tensor id, then psum over the axis (the analog of the reference's
    multi_tensor_l2norm over local chunks + allreduce)."""
    local = jax.ops.segment_sum(
        jnp.square(x_shard), ids_shard, num_segments=num_tensors + 1
    )
    return lax.psum(local, axis_name)[:num_tensors]


def finite_all(x, axis_name):
    """True iff every element of the sharded buffer is finite on every rank
    (per-element, the reference's multi_tensor chunk inf/nan flags). A
    naive ``isfinite(psum(sum(x)))`` also trips on a sum OVERFLOW of
    large-but-finite loss-scaled grads — a spurious step-skip."""
    return lax.pmin(jnp.all(jnp.isfinite(x)).astype(jnp.int32), axis_name) > 0


def clip_by_global_norm(x, max_norm, axis_name=None, scale=1.0, eps=1e-6):
    """``x * min(1, max_norm / (||x||/scale + eps))``; the square-sum runs
    over ``axis_name`` too when given (post-allreduce clip). Returns
    ``(clipped, norm_ok)`` — ``norm_ok`` False means the norm computation
    itself overflowed to inf on huge-but-finite grads; the clip is then a
    no-op and the caller must fold ``norm_ok`` into its step-skip (the
    loss-scaler overflow semantics) instead of letting factor=0 silently
    zero the gradient."""
    sq = jnp.sum(jnp.square(x))
    if axis_name is not None:
        sq = lax.psum(sq, axis_name)
    norm = jnp.sqrt(sq) / scale
    ok = jnp.isfinite(norm)
    factor = jnp.minimum(1.0, max_norm / (norm + eps))
    return x * jnp.where(ok, factor, 1.0), ok
