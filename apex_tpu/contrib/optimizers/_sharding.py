"""Flat-shard machinery for ZeRO-style optimizers.

Ref: apex/contrib/optimizers/distributed_fused_adam.py — the reference
flattens params into fixed-size buckets, reduce-scatters gradient buckets
as backward hooks fire, updates each rank's shard with fused kernels, and
all-gathers updated params. Under XLA the hook/stream choreography is
replaced by one reduce_scatter + one all_gather per step inside
``shard_map`` (XLA overlaps them with adjacent compute); what this module
keeps from the reference is the *flat-shard state layout* (fp32 master +
moments live only in 1/N of HBM per device — the actual ZeRO memory win)
and per-tensor bookkeeping via segment ids (the analog of the reference's
per-tensor chunk metadata, needed for LAMB trust ratios).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class FlatMeta(NamedTuple):
    treedef: object
    shapes: tuple
    dtypes: tuple
    sizes: tuple
    padded_total: int
    num_tensors: int      # total per-tensor segments (stacked leaves count L)
    sub_counts: tuple     # per leaf: 1, or L for a lax.scan-stacked [L, ...]


def flat_meta(params, n_shards: int,
              stacked_key: str | None = "layers") -> FlatMeta:
    """``stacked_key``: dict key marking scan-stacked [L, ...] collections
    (``testing.stack_layer_params``). Each such leaf contributes L segment
    ids — one per layer slice — so per-tensor bookkeeping (LAMB trust
    ratios) keeps the reference's per-layer-tensor granularity."""
    from apex_tpu.utils.pytree import stacked_flags

    leaves, treedef = jax.tree.flatten(params)
    shapes = tuple(l.shape for l in leaves)
    dtypes = tuple(l.dtype for l in leaves)
    sizes = tuple(int(l.size) for l in leaves)
    flags = stacked_flags(params, stacked_key)
    sub_counts = tuple(
        int(l.shape[0]) if f else 1 for f, l in zip(flags, leaves)
    )
    total = sum(sizes)
    padded_total = -(-total // n_shards) * n_shards
    return FlatMeta(treedef, shapes, dtypes, sizes, padded_total,
                    sum(sub_counts), sub_counts)


def flatten_fp32(tree, meta: FlatMeta):
    leaves = jax.tree.leaves(tree)
    flat = jnp.concatenate(
        [l.reshape(-1).astype(jnp.float32) for l in leaves]
    )
    pad = meta.padded_total - flat.shape[0]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    return flat


def unflatten(flat, meta: FlatMeta):
    out = []
    off = 0
    for shape, dtype, size in zip(meta.shapes, meta.dtypes, meta.sizes):
        out.append(flat[off:off + size].reshape(shape).astype(dtype))
        off += size
    return jax.tree.unflatten(meta.treedef, out)


def tensor_ids(meta: FlatMeta):
    """int32 [padded_total]: which per-tensor segment each flat element
    belongs to. A stacked [L, ...] leaf spans L consecutive ids (its flat
    layout is layer-major, so each layer slice is contiguous); padding gets
    id num_tensors — an extra dead segment."""
    ids = []
    nxt = 0
    for size, subs in zip(meta.sizes, meta.sub_counts):
        if subs == 1:
            ids.append(jnp.full((size,), nxt, jnp.int32))
        else:
            per = size // subs
            ids.append(jnp.repeat(
                jnp.arange(nxt, nxt + subs, dtype=jnp.int32), per))
        nxt += subs
    pad = meta.padded_total - sum(meta.sizes)
    if pad:
        ids.append(jnp.full((pad,), meta.num_tensors, jnp.int32))
    return jnp.concatenate(ids)


def my_shard(flat, axis_name: str):
    """Slice this device's contiguous shard of a flat [padded_total] array
    (call inside shard_map)."""
    n = lax.psum(1, axis_name)
    shard_size = flat.shape[0] // n
    idx = lax.axis_index(axis_name)
    return lax.dynamic_slice_in_dim(flat, idx * shard_size, shard_size)


def reduce_scatter_flat(flat, axis_name: str, *, mean: bool = True):
    """reduce_scatter a flat gradient so each device owns the reduced
    values of its shard (ref: the per-bucket reduce-scatter hooks)."""
    n = lax.psum(1, axis_name)
    shard = lax.psum_scatter(
        flat.reshape(n, flat.shape[0] // n), axis_name, scatter_dimension=0,
        tiled=False,
    )
    if mean:
        shard = shard / n
    return shard


def all_gather_flat(shard, axis_name: str):
    """Inverse: gather every device's updated shard into the full flat
    array (ref: the all-gather of updated params).

    Implemented as place-in-zeros + psum rather than ``lax.all_gather``:
    JAX's varying-manual-axes checker cannot statically infer that an
    all_gather output is replicated (no all_gather_invariant in this JAX),
    and the optimizer's contract is that the returned params are replicated
    across the axis. XLA lowers this to one all-reduce over ICI.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    full = jnp.zeros((n * shard.shape[0],), shard.dtype)
    full = lax.dynamic_update_slice_in_dim(full, shard, idx * shard.shape[0],
                                           0)
    return lax.psum(full, axis_name)


def per_tensor_sq_norms(x_shard, ids_shard, num_tensors: int,
                        axis_name: str):
    """Per-tensor sum-of-squares from flat shards: local segment-sum by
    tensor id, then psum over the axis (the analog of the reference's
    multi_tensor_l2norm over local chunks + allreduce)."""
    local = jax.ops.segment_sum(
        jnp.square(x_shard), ids_shard, num_segments=num_tensors + 1
    )
    return lax.psum(local, axis_name)[:num_tensors]


def finite_all(x, axis_name):
    """True iff every element of the sharded buffer is finite on every rank
    (per-element, the reference's multi_tensor chunk inf/nan flags). A
    naive ``isfinite(psum(sum(x)))`` also trips on a sum OVERFLOW of
    large-but-finite loss-scaled grads — a spurious step-skip."""
    return lax.pmin(jnp.all(jnp.isfinite(x)).astype(jnp.int32), axis_name) > 0


def clip_by_global_norm(x, max_norm, axis_name=None, scale=1.0, eps=1e-6):
    """``x * min(1, max_norm / (||x||/scale + eps))``; the square-sum runs
    over ``axis_name`` too when given (post-allreduce clip). Returns
    ``(clipped, norm_ok)`` — ``norm_ok`` False means the norm computation
    itself overflowed to inf on huge-but-finite grads; the clip is then a
    no-op and the caller must fold ``norm_ok`` into its step-skip (the
    loss-scaler overflow semantics) instead of letting factor=0 silently
    zero the gradient."""
    sq = jnp.sum(jnp.square(x))
    if axis_name is not None:
        sq = lax.psum(sq, axis_name)
    norm = jnp.sqrt(sq) / scale
    ok = jnp.isfinite(norm)
    factor = jnp.minimum(1.0, max_norm / (norm + eps))
    return x * jnp.where(ok, factor, 1.0), ok
