"""DistributedFusedAdam — ZeRO-2 Adam over the data axis.

Ref: apex/contrib/optimizers/distributed_fused_adam.py::DistributedFusedAdam
(the largest Python file in the reference): flat bucketed params, backward
hooks launching reduce-scatter per bucket on comm streams, per-rank fused
Adam on the owned shard with fp32 master weights, all-gather of updated
params overlapped with the next forward, fused grad-norm clipping.

TPU rewrite: one ``shard_map``-resident step —
    grads -> reduce_scatter (each device owns 1/N of the flat grads)
          -> fused Adam on the fp32 master shard (+ m/v shards)
          -> all_gather of updated flat params.
Optimizer state is 1/N per device (the ZeRO memory win); XLA schedules the
collectives asynchronously against neighboring compute, which replaces the
reference's stream/bucket choreography. Step-skipping on non-finite grads
(amp interop) uses the same ``lax.cond`` pattern as the core optimizers.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.contrib.optimizers._sharding import (
    FlatMeta,
    all_gather_flat,
    clip_by_global_norm,
    finite_all,
    flat_meta,
    flatten_fp32,
    my_shard,
    reduce_scatter_flat,
    unflatten,
)


class DistAdamState(NamedTuple):
    step: jnp.ndarray      # scalar int32
    master: jnp.ndarray    # [shard] fp32 master params
    m: jnp.ndarray         # [shard] fp32
    v: jnp.ndarray         # [shard] fp32


class DistributedFusedAdam:
    """Adam/AdamW with ZeRO-2 sharding over a named mesh axis.

    ``init_shard`` and ``step`` must run inside ``shard_map`` (or pmap)
    over ``axis_name``. Constructor args mirror the reference.
    """

    def __init__(self, learning_rate=1e-3, *, b1: float = 0.9,
                 b2: float = 0.999, eps: float = 1e-8,
                 weight_decay: float = 0.0, adam_w_mode: bool = True,
                 bias_correction: bool = True,
                 max_grad_norm: Optional[float] = None,
                 grad_averaging: bool = True, axis_name: str = "data",
                 use_pallas: Optional[bool] = None,
                 quantized_comms: Optional[bool] = None):
        self.lr = learning_rate
        self.b1, self.b2, self.eps = b1, b2, eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.bias_correction = bias_correction
        self.max_grad_norm = max_grad_norm
        self.grad_averaging = grad_averaging
        self.axis_name = axis_name
        # Pallas flat-shard update kernel (ops/pallas_optim.py, the analog
        # of csrc/multi_tensor_adam.cu over the reference's flat bucket
        # shards); None = platform default (TPU on, CPU oracle path off —
        # decided by benchmarks/bench_optim_kernels.py, see BASELINE.md).
        self.use_pallas = use_pallas
        # int8 gradient reduce-scatter (parallel/quantized_collectives.py);
        # None = follow APEX_TPU_QUANTIZED_COMMS, False = force exact
        self.quantized_comms = quantized_comms
        self._meta: Optional[FlatMeta] = None

    # -- metadata ----------------------------------------------------------
    def prepare(self, params, n_shards: int,
                stacked_key: str | None = "layers") -> FlatMeta:
        """Host-side: compute the flat layout (call once, outside jit).
        ``stacked_key``: dict key marking lax.scan-stacked [L, ...]
        collections (``testing.stack_layer_params``); their layer slices
        get separate per-tensor segments. Adam itself has no per-tensor
        statistics, but the segment ids feed diagnostics and keep the
        layout identical to DistributedFusedLAMB's. ``None`` disables."""
        self._meta = flat_meta(params, n_shards, stacked_key=stacked_key)
        return self._meta

    # -- inside shard_map --------------------------------------------------
    def init_shard(self, params) -> DistAdamState:
        """This device's optimizer-state shard (fp32 master copy of its
        1/N of the flattened params + zero moments)."""
        meta = self._require_meta()
        flat = flatten_fp32(params, meta)
        master = my_shard(flat, self.axis_name)
        return DistAdamState(
            step=jnp.zeros((), jnp.int32),
            master=master,
            m=jnp.zeros_like(master),
            v=jnp.zeros_like(master),
        )

    def step(self, params, grads, state: DistAdamState, *,
             scale=1.0):
        """One ZeRO-2 update. ``scale`` divides the gradients (loss-scale
        unscaling, amp interop). Returns (new_params, new_state)."""
        new_state = self.step_shard(params, grads, state, scale=scale)
        # chunks=1: the original single-collective gather, unchanged for
        # step() users; prefetch callers pick the chunked form explicitly
        return self.gather_params(new_state, chunks=1), new_state

    def gather_params(self, state: DistAdamState, *, chunks: int = 8):
        """Replicated params from the sharded fp32 master — the reference's
        post-step all-gather, callable separately so a train loop can
        PREFETCH: call this at the top of the next step (or pass it to
        ``parallel.grad_accum.accumulate_and_step_prefetch``) instead of
        consuming ``step``'s gathered output, and the gather lands in the
        same XLA program as the first microbatch's forward — chunked
        (``chunks`` independent psums), so early-offset leaves (embedding,
        first blocks) unblock compute while later chunks are in flight.
        Ref: distributed_fused_adam.py's all-gather-overlapped-with-next-
        forward; arxiv 2004.13336 motivates the same overlap for sharded
        weight updates."""
        meta = self._require_meta()
        flat_p = all_gather_flat(state.master, self.axis_name, chunks=chunks)
        return unflatten(flat_p, meta)

    def step_shard(self, params, grads, state: DistAdamState, *,
                   scale=1.0) -> DistAdamState:
        """The update WITHOUT the trailing params all-gather: reduce-scatter
        + per-shard Adam only, returning the new sharded state. Pair with
        :meth:`gather_params` (the allgather-prefetch split,
        ``APEX_TPU_ZERO_PREFETCH=1`` paths); ``step`` is exactly
        ``step_shard`` + ``gather_params``."""
        meta = self._require_meta()
        ax = self.axis_name
        flat_g = flatten_fp32(grads, meta)
        gshard = reduce_scatter_flat(flat_g, ax, mean=self.grad_averaging,
                                     quantized=self.quantized_comms)
        gshard = gshard / scale

        # fused global-norm clip (ref: multi_tensor_l2norm + allreduce)
        norm_ok = jnp.bool_(True)
        if self.max_grad_norm is not None:
            gshard, norm_ok = clip_by_global_norm(
                gshard, self.max_grad_norm, ax
            )

        if not self.adam_w_mode and self.weight_decay:
            # L2 mode: decay folds into the gradient before the moments
            gshard = gshard + self.weight_decay * state.master

        # a non-finite grad element OR a norm overflow skips the step
        finite = finite_all(gshard, ax) & norm_ok

        use_pallas = self.use_pallas
        if use_pallas is None:
            from apex_tpu.ops._utils import default_use_pallas

            use_pallas = default_use_pallas("optim_flat")

        def do_update(_):
            t = state.step + 1
            if use_pallas:
                from apex_tpu.ops import pallas_optim as PK

                master, m, v = PK.adam_flat(
                    gshard, state.master, state.m, state.v,
                    lr=self.lr, beta1=self.b1, beta2=self.b2, eps=self.eps,
                    step=t,
                    mode=(PK.ADAM_MODE_ADAMW if self.adam_w_mode
                          else PK.ADAM_MODE_ADAM),
                    bias_correction=self.bias_correction,
                    # ADAM (L2) mode decay was already folded into gshard
                    weight_decay=(self.weight_decay if self.adam_w_mode
                                  else 0.0),
                )
                return DistAdamState(t, master, m, v)
            m = self.b1 * state.m + (1 - self.b1) * gshard
            v = self.b2 * state.v + (1 - self.b2) * jnp.square(gshard)
            if self.bias_correction:
                mhat = m / (1 - self.b1 ** t.astype(jnp.float32))
                vhat = v / (1 - self.b2 ** t.astype(jnp.float32))
            else:
                mhat, vhat = m, v
            update = mhat / (jnp.sqrt(vhat) + self.eps)
            if self.adam_w_mode and self.weight_decay:
                update = update + self.weight_decay * state.master
            master = state.master - self.lr * update
            return DistAdamState(t, master, m, v)

        def skip(_):
            return DistAdamState(state.step, state.master, state.m, state.v)

        return lax.cond(finite, do_update, skip, None)

    def _require_meta(self) -> FlatMeta:
        if self._meta is None:
            raise RuntimeError("call prepare(params, n_shards) first")
        return self._meta
