"""DistributedFusedLAMB — the MLPerf-BERT ZeRO LAMB over the data axis.

Ref: apex/contrib/optimizers/distributed_fused_lamb.py::DistributedFusedLAMB
(+ multi_tensor_distopt_lamb kernels): overlapped reduce-scatter of flat
gradient buckets, fused L2 norms (global for clipping, per-tensor for the
trust ratio), sharded Adam-style moments, all-gather of updated params;
``set_global_scale`` feeds the loss scaler in, clipping can happen before
or after the allreduce (``clip_after_ar``).

TPU rewrite: same shard_map step shape as DistributedFusedAdam; the
per-tensor norms the reference computes with multi_tensor_l2norm over local
chunks + allreduce become one ``segment_sum`` over tensor ids on the flat
shard + ``psum`` (see _sharding.per_tensor_sq_norms), after which the
trust-ratio scaling is a flat gather by tensor id — fully fused by XLA.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.contrib.optimizers._sharding import (
    FlatMeta,
    all_gather_flat,
    clip_by_global_norm,
    finite_all,
    flat_meta,
    flatten_fp32,
    my_shard,
    per_tensor_sq_norms,
    reduce_scatter_flat,
    tensor_ids,
    unflatten,
)


class DistLAMBState(NamedTuple):
    step: jnp.ndarray
    master: jnp.ndarray
    m: jnp.ndarray
    v: jnp.ndarray
    ids: jnp.ndarray        # [shard] int32 tensor ids
    global_scale: jnp.ndarray


class DistributedFusedLAMB:
    """LAMB with ZeRO sharding over a named mesh axis (shard_map-resident,
    see DistributedFusedAdam)."""

    def __init__(self, learning_rate=1e-3, *, b1: float = 0.9,
                 b2: float = 0.999, eps: float = 1e-6,
                 weight_decay: float = 0.01, bias_correction: bool = True,
                 max_grad_norm: Optional[float] = 1.0,
                 clip_after_ar: bool = True, grad_averaging: bool = True,
                 use_nvlamb: bool = False, axis_name: str = "data"):
        self.lr = learning_rate
        self.b1, self.b2, self.eps = b1, b2, eps
        self.weight_decay = weight_decay
        self.bias_correction = bias_correction
        self.max_grad_norm = max_grad_norm
        self.clip_after_ar = clip_after_ar
        self.grad_averaging = grad_averaging
        self.use_nvlamb = use_nvlamb
        self.axis_name = axis_name
        self._meta: Optional[FlatMeta] = None

    def prepare(self, params, n_shards: int,
                stacked_key: str | None = "layers") -> FlatMeta:
        """``stacked_key``: dict key marking lax.scan-stacked [L, ...]
        collections (``testing.stack_layer_params``); their layer slices get
        separate per-tensor segments (LAMB trust ratios per layer, matching
        the reference's per-tensor chunk metadata). ``None`` disables."""
        self._meta = flat_meta(params, n_shards, stacked_key=stacked_key)
        return self._meta

    def init_shard(self, params) -> DistLAMBState:
        meta = self._require_meta()
        flat = flatten_fp32(params, meta)
        master = my_shard(flat, self.axis_name)
        ids = my_shard(tensor_ids(meta), self.axis_name)
        return DistLAMBState(
            step=jnp.zeros((), jnp.int32),
            master=master,
            m=jnp.zeros_like(master),
            v=jnp.zeros_like(master),
            ids=ids,
            global_scale=jnp.ones((), jnp.float32),
        )

    def set_global_scale(self, state: DistLAMBState, scale) -> DistLAMBState:
        """Loss-scale feed-in (ref: set_global_scale)."""
        return state._replace(
            global_scale=jnp.asarray(scale, jnp.float32)
        )

    def step(self, params, grads, state: DistLAMBState):
        meta = self._require_meta()
        ax = self.axis_name
        nt = meta.num_tensors

        flat_g = flatten_fp32(grads, meta)
        norm_ok = jnp.bool_(True)
        if not self.clip_after_ar and self.max_grad_norm is not None:
            # pre-allreduce clip (reference's fallback mode). The local
            # grads are still loss-scaled, so the norm is measured in
            # UNSCALED units to keep the threshold comparable to the
            # post-AR path; local norm_ok may differ per rank — pmin'd
            # into the skip below.
            flat_g, norm_ok = clip_by_global_norm(
                flat_g, self.max_grad_norm, scale=state.global_scale
            )
        gshard = reduce_scatter_flat(flat_g, ax, mean=self.grad_averaging)
        gshard = gshard / state.global_scale
        if self.clip_after_ar and self.max_grad_norm is not None:
            gshard, norm_ok = clip_by_global_norm(
                gshard, self.max_grad_norm, ax
            )

        # a non-finite grad element OR a norm overflow skips the step
        finite = finite_all(gshard, ax) & (
            lax.pmin(norm_ok.astype(jnp.int32), ax) > 0
        )

        def do_update(_):
            t = state.step + 1
            tf = t.astype(jnp.float32)
            m = self.b1 * state.m + (1 - self.b1) * gshard
            v = self.b2 * state.v + (1 - self.b2) * jnp.square(gshard)
            if self.bias_correction:
                mhat = m / (1 - self.b1 ** tf)
                vhat = v / (1 - self.b2 ** tf)
            else:
                mhat, vhat = m, v
            update = mhat / (jnp.sqrt(vhat) + self.eps)
            if self.weight_decay:
                update = update + self.weight_decay * state.master

            # per-tensor trust ratios from flat shards
            wsq = per_tensor_sq_norms(state.master, state.ids, nt, ax)
            usq = per_tensor_sq_norms(update, state.ids, nt, ax)
            wnorm = jnp.sqrt(wsq)
            unorm = jnp.sqrt(usq)
            if self.use_nvlamb:
                # NVLAMB applies the adaptive ratio unconditionally — a
                # zero-norm tensor gets ratio 0 (ref: multi_tensor_lamb's
                # use_nvlamb path has no zero guards)
                ratio = jnp.where(unorm > 0, wnorm / unorm, 1.0)
            else:
                # phase-2 LAMB skips the ratio for zero-norm tensors
                ratio = jnp.where(
                    (wnorm > 0) & (unorm > 0), wnorm / unorm, 1.0
                )
            # append neutral ratio for the padding segment
            ratio_full = jnp.concatenate([ratio, jnp.ones((1,), jnp.float32)])
            scale_elt = ratio_full[jnp.clip(state.ids, 0, nt)]
            master = state.master - self.lr * scale_elt * update
            return DistLAMBState(t, master, m, v, state.ids,
                                 state.global_scale)

        new_state = lax.cond(finite, do_update, lambda _: state, None)
        flat_p = all_gather_flat(new_state.master, ax)
        return unflatten(flat_p, meta), new_state

    def _require_meta(self) -> FlatMeta:
        if self._meta is None:
            raise RuntimeError("call prepare(params, n_shards) first")
        return self._meta
