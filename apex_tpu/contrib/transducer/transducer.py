"""RNN-T joint and loss.

Ref: apex/contrib/transducer/transducer.py::TransducerJoint/TransducerLoss
and apex/contrib/csrc/transducer/*. The reference fuses (a) the broadcast
add f[b,t]+g[b,u] with optional ReLU+dropout and optional packing (dropping
padded (t,u) cells via cu_seqlens), and (b) the RNN-T forward-backward loss
with analytic gradients.

TPU design: the joint is a fused broadcast-add epilogue (XLA emits one
pass; packing is replaced by masking since XLA wants static shapes — the
memory win of packing is delivered by masking before any downstream
reduction). The loss runs the alpha recursion with ``lax.scan`` over T and
a log-semiring ``lax.associative_scan`` over U (the u-recurrence
``a[u] = logaddexp(c[u], a[u-1] + w[u-1])`` is a first-order linear
recurrence, exactly parallelizable on the VPU), and gets exact gradients
via autodiff through the scan — the same alpha/beta math the reference
hand-writes, produced by transposition.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG = -1e30


# ------------------------------------------------------------------- joint

def transducer_joint(f, g, f_len=None, g_len=None, *, relu: bool = False,
                     dropout_p: float = 0.0, dropout_rng=None):
    """f: [B, T, H] (encoder); g: [B, U, H] (predictor) ->
    h: [B, T, U, H] = f[:, :, None] + g[:, None], with optional fused
    ReLU and dropout (ref: TransducerJoint(pack_output=False, relu,
    dropout)). Padded cells (t >= f_len or u >= g_len) are zeroed — the
    masking analog of the reference's packed output."""
    h = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        h = jax.nn.relu(h)
    if dropout_p > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_p > 0 requires dropout_rng")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_p, h.shape)
        h = jnp.where(keep, h / (1.0 - dropout_p), 0.0).astype(h.dtype)
    if f_len is not None:
        t_mask = jnp.arange(h.shape[1])[None, :] < f_len[:, None]
        h = jnp.where(t_mask[:, :, None, None], h, 0.0).astype(h.dtype)
    if g_len is not None:
        u_mask = jnp.arange(h.shape[2])[None, :] < g_len[:, None]
        h = jnp.where(u_mask[:, None, :, None], h, 0.0).astype(h.dtype)
    return h


class TransducerJoint:
    """Veneer with the reference constructor options."""

    def __init__(self, *, relu: bool = False, dropout: float = 0.0):
        self.relu = relu
        self.dropout = dropout

    def __call__(self, f, g, f_len=None, g_len=None, *, is_training=True,
                 dropout_rng=None):
        p = self.dropout if is_training else 0.0
        return transducer_joint(f, g, f_len, g_len, relu=self.relu,
                                dropout_p=p, dropout_rng=dropout_rng)


# -------------------------------------------------------------------- loss

def _logaddexp_linear_scan(c, w):
    """Solve a[u] = logaddexp(c[u], a[u-1] + w[u-1]) for u = 0..U-1
    (a[-1] = -inf) with an associative scan in the log semiring.

    Elements are pairs (W, C) representing the affine map
    a -> logaddexp(C, a + W); composition is associative:
    (W1,C1) then (W2,C2) = (W1+W2, logaddexp(C1+W2, C2)).
    """
    wshift = jnp.concatenate(
        [jnp.full_like(w[..., :1], _NEG), w], axis=-1
    )  # length U+1: map u uses w[u-1]; map 0 ignores the empty carry-in
    # NOTE wshift[0] = -inf makes the first map ignore the (empty) carry-in
    def combine(x, y):
        w1, c1 = x
        w2, c2 = y
        return w1 + w2, jnp.logaddexp(c1 + w2, c2)

    # we need the u-th prefix applied to a[-1] = -inf: result is just C of
    # the composed map
    _, a = jax.lax.associative_scan(combine, (wshift, c), axis=-1)
    return a


def transducer_loss(logits, labels, f_len, y_len, *, blank_idx: int = 0):
    """RNN-T loss (negative log posterior of the label sequence).

    logits: [B, T, U+1, V] joint outputs (log-unnormalized); labels:
    [B, U] int; f_len: [B] valid encoder lengths; y_len: [B] valid label
    lengths. Matches the reference's TransducerLoss (packed_input=False),
    one loss value per batch element.
    """
    b, t_max, u1, v = logits.shape
    u_max = u1 - 1
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    # blank and label emission log-probs
    blank = logp[..., blank_idx]                       # [B, T, U+1]
    labels_e = jnp.minimum(labels, v - 1)
    lab = jnp.take_along_axis(
        logp[:, :, :u_max, :], labels_e[:, None, :, None], axis=-1
    )[..., 0]                                          # [B, T, U]
    # mask invalid u transitions (u >= y_len): emitting a label beyond the
    # sequence is impossible
    u_valid = jnp.arange(u_max)[None, :] < y_len[:, None]
    lab = jnp.where(u_valid[:, None, :], lab, _NEG)

    # alpha recursion over t (scan), parallel over u (associative scan):
    # alpha[0, u] = sum_{i<u} lab[0, i] (prefix of label emissions)
    # alpha[t, u] = logaddexp(alpha[t-1, u] + blank[t-1, u],
    #                         alpha[t, u-1] + lab[t, u-1])
    alpha0 = jnp.concatenate(
        [jnp.zeros((b, 1), jnp.float32),
         jnp.cumsum(lab[:, 0, :], axis=-1)], axis=-1
    )                                                  # [B, U+1]

    def step(alpha_prev, xs):
        blank_prev, lab_t = xs                         # [B, U+1], [B, U]
        c = alpha_prev + blank_prev                    # horizontal moves
        a = _logaddexp_linear_scan(c, lab_t)           # vertical within row
        return a, a

    xs = (jnp.moveaxis(blank, 1, 0)[:-1], jnp.moveaxis(lab, 1, 0)[1:])
    _, alphas_rest = jax.lax.scan(step, alpha0, xs)    # [T-1, B, U+1]
    alphas = jnp.concatenate(
        [alpha0[None], alphas_rest], axis=0
    )                                                  # [T, B, U+1]
    alphas = jnp.moveaxis(alphas, 0, 1)                # [B, T, U+1]

    # loss = -(alpha[f_len-1, y_len] + blank[f_len-1, y_len])
    t_idx = jnp.maximum(f_len - 1, 0)
    batch = jnp.arange(b)
    final_alpha = alphas[batch, t_idx, y_len]
    final_blank = blank[batch, t_idx, y_len]
    return -(final_alpha + final_blank)


class TransducerLoss:
    """Veneer matching the reference call shape."""

    def __init__(self, *, blank_idx: int = 0, reduction: str = "mean"):
        self.blank_idx = blank_idx
        self.reduction = reduction

    def __call__(self, logits, labels, f_len, y_len):
        loss = transducer_loss(logits, labels, f_len, y_len,
                               blank_idx=self.blank_idx)
        if self.reduction == "mean":
            return loss.mean()
        if self.reduction == "sum":
            return loss.sum()
        return loss
