"""RNN-T transducer joint + loss (ref: apex/contrib/transducer, exts
``transducer_joint_cuda`` / ``transducer_loss_cuda``)."""

from apex_tpu.contrib.transducer.transducer import (  # noqa: F401
    TransducerJoint,
    TransducerLoss,
    transducer_joint,
    transducer_loss,
)
