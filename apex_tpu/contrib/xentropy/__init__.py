"""Fused softmax cross-entropy (ref: apex/contrib/xentropy).

The kernel lives in :mod:`apex_tpu.ops.xentropy` (ref: ext
``xentropy_cuda``); this package provides the reference's contrib surface.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.ops.xentropy import softmax_cross_entropy  # noqa: F401


class SoftmaxCrossEntropyLoss:
    """Drop-in for apex.contrib.xentropy.SoftmaxCrossEntropyLoss: callable
    loss with label smoothing; ``padding_idx`` entries contribute 0 loss
    (the reference's ignore behavior)."""

    def __init__(self, smoothing: float = 0.0, padding_idx: int = 0,
                 reduction: str = "mean"):
        self.smoothing = smoothing
        self.padding_idx = padding_idx
        self.reduction = reduction

    def __call__(self, logits, labels):
        loss = softmax_cross_entropy(logits, labels, self.smoothing)
        if self.padding_idx is not None:
            keep = labels != self.padding_idx
            loss = jnp.where(keep, loss, 0.0)
            denom = jnp.maximum(keep.sum(), 1)
        else:
            denom = loss.size
        if self.reduction == "mean":
            return loss.sum() / denom
        if self.reduction == "sum":
            return loss.sum()
        return loss
