"""Halo exchange over the ICI mesh (ref: apex/contrib/peer_memory +
apex/contrib/nccl_p2p).

The reference implements 1-D halo exchange two ways — CUDA-IPC peer memory
(``PeerMemoryPool`` / ``PeerHaloExchanger1d``) and raw NCCL send/recv
(``nccl_p2p_cuda``). On TPU both collapse to one idiom: a pair of
``lax.ppermute`` shifts along a named mesh axis, which XLA lowers to direct
ICI neighbor DMA — the hardware analog of peer memory. There is no pool to
manage (XLA owns buffers), so the pool class is a documented no-op shim.
"""

from apex_tpu.contrib.peer_memory.halo_exchange import (  # noqa: F401
    PeerHaloExchanger1d,
    halo_exchange_1d,
)


class PeerMemoryPool:
    """API shim (ref: peer_memory.PeerMemoryPool). On TPU, XLA manages
    cross-chip buffers; nothing to allocate."""

    def __init__(self, *args, **kwargs):
        pass
