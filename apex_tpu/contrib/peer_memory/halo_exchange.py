"""1-D halo exchange via paired ppermute shifts.

Ref: apex/contrib/peer_memory/peer_halo_exchanger_1d.py::PeerHaloExchanger1d
(and nccl_p2p's send/recv variant): each rank sends its top ``halo`` rows to
the previous neighbor and its bottom rows to the next, concatenating the
received halos around its local block of a spatially-partitioned tensor.

Must be called inside ``shard_map`` over a mesh with the named spatial
axis. Non-periodic boundaries (the reference's default: first/last rank
keep zero halos) are realized by zeroing the wrapped-around halo.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def halo_exchange_1d(x, axis_name: str, *, halo: int, dim: int = 1,
                     periodic: bool = False):
    """x: local shard; returns x with ``halo`` rows from each neighbor
    concatenated along ``dim`` (output grows by 2*halo).

    dim counts into the *local* array (reference splits H of NHWC, dim=1).
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)

    top = lax.slice_in_dim(x, 0, halo, axis=dim)            # my first rows
    bot = lax.slice_in_dim(x, x.shape[dim] - halo, x.shape[dim], axis=dim)

    fwd = [(i, (i + 1) % n) for i in range(n)]   # bottom rows go to next
    bwd = [(i, (i - 1) % n) for i in range(n)]   # top rows go to prev

    from_prev = lax.ppermute(bot, axis_name, fwd)  # received halo above
    from_next = lax.ppermute(top, axis_name, bwd)  # received halo below

    if not periodic:
        from_prev = jnp.where(idx == 0, jnp.zeros_like(from_prev), from_prev)
        from_next = jnp.where(idx == n - 1, jnp.zeros_like(from_next),
                              from_next)
    return jnp.concatenate([from_prev, x, from_next], axis=dim)


class PeerHaloExchanger1d:
    """Veneer with the reference's constructor shape (ranks/pool args are
    replaced by the mesh axis name)."""

    def __init__(self, axis_name: str, halo: int, dim: int = 1,
                 periodic: bool = False):
        self.axis_name = axis_name
        self.halo = halo
        self.dim = dim
        self.periodic = periodic

    def __call__(self, x):
        return halo_exchange_1d(x, self.axis_name, halo=self.halo,
                                dim=self.dim, periodic=self.periodic)
