"""FusedLayerNorm / FusedRMSNorm modules.

Ref: apex/normalization/fused_layer_norm.py — drop-in nn.LayerNorm/RMSNorm
replacements with elementwise-affine and no-affine paths, mixed-dtype
variants (params fp32 while activations are bf16/fp16 — the Megatron
pattern), and a ``memory_efficient`` flag.

On TPU the kernel is ``apex_tpu.ops.layer_norm`` (Pallas fwd/bwd, fp32
accumulation). ``memory_efficient=True`` maps to ``jax.checkpoint`` around
the op: residuals are dropped and recomputed in backward — the XLA-idiomatic
equivalent of the reference's recompute-free-bwd-from-output trick.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from apex_tpu.ops.layer_norm import layer_norm, rms_norm

try:
    import flax.linen as nn

    _HAVE_FLAX = True
except ImportError:  # pragma: no cover
    _HAVE_FLAX = False


def _norm_shape(normalized_shape) -> int:
    if isinstance(normalized_shape, int):
        return normalized_shape
    shape = tuple(normalized_shape)
    if len(shape) != 1:
        raise NotImplementedError(
            "apex_tpu normalizes over the last axis; pass the hidden size"
        )
    return shape[0]


def fused_layer_norm(
    x,
    weight=None,
    bias=None,
    eps: float = 1e-5,
    memory_efficient: bool = False,
):
    """Functional fused LayerNorm (ref: fused_layer_norm / FusedLayerNormFunction)."""
    fn = functools.partial(layer_norm, eps=eps)
    if memory_efficient:
        fn = jax.checkpoint(fn)
    return fn(x, weight, bias)


def fused_rms_norm(x, weight=None, eps: float = 1e-5, memory_efficient: bool = False):
    fn = functools.partial(rms_norm, eps=eps)
    if memory_efficient:
        fn = jax.checkpoint(fn)
    return fn(x, weight)


if _HAVE_FLAX:

    class FusedLayerNorm(nn.Module):
        """Drop-in LayerNorm over the last axis (ref: FusedLayerNorm).

        ``elementwise_affine=False`` gives the no-affine path. ``params_dtype``
        fp32 + bf16 inputs reproduces MixedFusedLayerNorm.
        """

        normalized_shape: Union[int, Sequence[int]]
        eps: float = 1e-5
        elementwise_affine: bool = True
        memory_efficient: bool = False
        params_dtype: object = jnp.float32

        @nn.compact
        def __call__(self, x):
            h = _norm_shape(self.normalized_shape)
            if self.elementwise_affine:
                weight = self.param(
                    "scale", nn.initializers.ones, (h,), self.params_dtype
                )
                bias = self.param(
                    "bias", nn.initializers.zeros, (h,), self.params_dtype
                )
            else:
                weight = bias = None
            return fused_layer_norm(
                x, weight, bias, self.eps, self.memory_efficient
            )

    class FusedRMSNorm(nn.Module):
        """Drop-in RMSNorm (ref: FusedRMSNorm)."""

        normalized_shape: Union[int, Sequence[int]]
        eps: float = 1e-5
        elementwise_affine: bool = True
        memory_efficient: bool = False
        params_dtype: object = jnp.float32

        @nn.compact
        def __call__(self, x):
            h = _norm_shape(self.normalized_shape)
            weight = (
                self.param("scale", nn.initializers.ones, (h,), self.params_dtype)
                if self.elementwise_affine
                else None
            )
            return fused_rms_norm(x, weight, self.eps, self.memory_efficient)

    class MixedFusedLayerNorm(FusedLayerNorm):
        """Params stay fp32 while activations are half (ref: MixedFusedLayerNorm).

        Identical to FusedLayerNorm with params_dtype=fp32 (the default) —
        kept as a named class for reference-script parity.
        """

    class MixedFusedRMSNorm(FusedRMSNorm):
        """fp32-params RMSNorm (ref: MixedFusedRMSNorm)."""
