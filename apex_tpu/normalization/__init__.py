from apex_tpu.normalization.fused_layer_norm import (  # noqa: F401
    FusedLayerNorm,
    FusedRMSNorm,
    MixedFusedLayerNorm,
    MixedFusedRMSNorm,
    fused_layer_norm,
    fused_rms_norm,
)
