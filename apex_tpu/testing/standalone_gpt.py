"""Standalone GPT (ref: apex/transformer/testing/standalone_gpt.py).

A causal LM assembled purely from apex_tpu.transformer parallel layers;
see standalone_transformer.py for the body.
"""

from __future__ import annotations

from apex_tpu.testing.standalone_transformer import (
    TransformerConfig,
    gpt_loss,
    param_specs,
    transformer_forward,
    transformer_init,
)


def gpt_config(**kw) -> TransformerConfig:
    return TransformerConfig(causal=True, **kw)


gpt_init = transformer_init
gpt_forward = transformer_forward
gpt_param_specs = param_specs
__all__ = ["gpt_config", "gpt_init", "gpt_forward", "gpt_loss",
           "gpt_param_specs"]
