"""Standalone BERT (ref: apex/transformer/testing/standalone_bert.py).

A bidirectional masked-LM assembled purely from apex_tpu.transformer
parallel layers; see standalone_transformer.py for the body.
"""

from __future__ import annotations

from apex_tpu.testing.standalone_transformer import (
    TransformerConfig,
    bert_loss,
    param_specs,
    transformer_forward,
    transformer_init,
)


def bert_config(**kw) -> TransformerConfig:
    return TransformerConfig(causal=False, **kw)


bert_init = transformer_init
bert_forward = transformer_forward
bert_param_specs = param_specs
__all__ = ["bert_config", "bert_init", "bert_forward", "bert_loss",
           "bert_param_specs"]
