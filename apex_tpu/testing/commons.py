"""Shared test/benchmark helpers (ref: apex/transformer/testing/commons.py)."""

from __future__ import annotations

import jax
import numpy as np


def set_random_seed(seed: int):
    """Ref: commons.py::set_random_seed — one seed for every stream. JAX
    PRNG is explicit, so this just returns the root key (numpy is seeded
    for host-side data generation)."""
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def smap(body, mesh, in_specs, out_specs):
    """shard_map with VMA checking off — model bodies mix collectives whose
    replication the static checker cannot always infer (see
    contrib/optimizers/_sharding.all_gather_flat for the long story)."""
    return jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )
