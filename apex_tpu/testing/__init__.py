"""apex_tpu.testing — standalone models + fixtures (ref:
apex/transformer/testing).

The reference ships ``standalone_gpt.py`` / ``standalone_bert.py`` (minimal
Megatron models built only from apex.transformer parts) and a spawn-based
``distributed_test_base``. Here the distributed base is the hermetic
N-device CPU mesh (see tests/conftest.py); the standalone models below are
the TP/SP-parallel flagships used by the model-level tests, the graft
entry, and the benchmark.
"""

from apex_tpu.testing.commons import set_random_seed, smap  # noqa: F401
from apex_tpu.testing.standalone_transformer import (  # noqa: F401
    TransformerConfig,
    bert_loss,
    gpt_loss,
    param_specs,
    sp_grad_sync,
    split_qkv,
    stack_layer_params,
    transformer_forward,
    transformer_init,
)
from apex_tpu.testing import standalone_gpt  # noqa: F401
from apex_tpu.testing import standalone_bert  # noqa: F401
