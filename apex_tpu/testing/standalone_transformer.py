"""Minimal Megatron-style transformer built ONLY from apex_tpu.transformer
parts (ref: apex/transformer/testing/standalone_gpt.py /
standalone_bert.py — the reference's parity models are likewise assembled
purely from the library's parallel layers).

Architecture (pre-LN GPT/BERT body):
  vocab-parallel embedding (+ learned positions)
  N x [ LN -> TP attention (column QKV, flash kernel, row proj) -> +res
        LN -> TP MLP (column h->4h, gelu, row 4h->h)           -> +res ]
  final LN -> vocab-parallel logits (tied embedding) -> vocab-parallel CE

Everything runs shard_map-local over a mesh with ("data", "model") axes:
the TP layers issue their own collectives, batch is sharded over "data",
and gradient reduction over "data" is the caller's choice (DDP bucketing
or plain psum). ``sequence_parallel`` switches the activations between TP
blocks to seq-sharded layout with the reduce-scatter/all-gather pairs
(Megatron SP) — the LN + dropout then run on 1/tp of the tokens.

GPT = causal attention, next-token loss. BERT = bidirectional attention,
masked-position loss. Dropout keys follow the frozen MP RNG spec
(random.py): TP-rank-varying for activation dropout.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.attention import flash_attention
from apex_tpu.ops.layer_norm import layer_norm
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
)
from apex_tpu.transformer.tensor_parallel.random import model_parallel_seed


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 512
    seq_len: int = 64
    hidden: int = 64
    layers: int = 2
    heads: int = 4
    kv_heads: int = 0              # 0 = dense MHA (kv_heads == heads).
                                   # > 0 enables grouped-query attention:
                                   # heads % kv_heads == 0, the flash
                                   # kernels share kv rows per group
                                   # (ops/attention.py GQA). QKV columns
                                   # are laid out KV-GROUP-major
                                   # ([q_g..., k_g, v_g] per kv head) so
                                   # a contiguous TP column split hands
                                   # each rank whole groups — requires
                                   # kv_heads % tp == 0.
    ffn_mult: float = 4            # ffn = int(hidden * ffn_mult)
    rope: bool = False             # rotary position embeddings on q/k
                                   # (ops/rope.py) INSTEAD of the learned
                                   # position table (no pos_embedding
                                   # param when set); CP offsets each
                                   # rank's table slice by its chunk.
    norm: str = "layernorm"        # "layernorm" | "rmsnorm" (rms blocks
                                   # carry gamma only)
    mlp_act: str = "gelu"          # "gelu" | "swiglu". SwiGLU pairs
                                   # gate/up INTERLEAVED per ffn unit
                                   # ([f0_gate, f0_up, f1_gate, ...]) so
                                   # TP column splits keep each pair on
                                   # one rank at any tp.
    causal: bool = True            # GPT; False = BERT
    sequence_parallel: bool = False
    dropout_p: float = 0.0
    attn_dropout_p: float = 0.0    # dropout on the attention PROBABILITIES,
                                   # fused into the flash kernel (counter
                                   # RNG — ops/attention.py). Key comes
                                   # from the rank-varying model-parallel
                                   # stream (each TP rank owns different
                                   # heads; Megatron forks the model-
                                   # parallel RNG for attention dropout).
    dtype: object = jnp.float32
    model_axis: str = "model"
    context_axis: object = None    # name of a mesh axis sharding the
                                   # SEQUENCE across chips (ring-attention
                                   # context parallelism). tokens/labels are
                                   # then the LOCAL s/cp chunk; params are
                                   # replicated over the axis, so grads need
                                   # a pmean over it (like a data axis).
                                   # Mutually exclusive with
                                   # sequence_parallel; dropout must be 0.
    remat: bool = False            # activation checkpointing per block
    remat_policy: str = "full"     # "full" = save only block boundaries;
                                   # "dots" = also save matmul outputs
                                   # (jax dots_with_no_batch_dims_saveable:
                                   # ~no recompute of MXU work in backward,
                                   # more activation memory) — only read
                                   # when remat=True. Measured on v5e
                                   # (BASELINE.md): "dots" needs ~1.15
                                   # GB/layer at BERT-large b>=32 and
                                   # fails to compile on a single 16 GB
                                   # chip; it is the right policy only
                                   # once state is ZeRO/TP-sharded.
                                   # "flash" = the mid-granularity policy
                                   # between those extremes: save ONLY the
                                   # flash-attention kernel's named
                                   # residuals ("flash_out"/"flash_lse",
                                   # ops/attention.py::_flash_core_fwd) —
                                   # [s,b,h] bf16 + [b,nh,s] fp32 per layer
                                   # (~1/9 of what "dots" pins) — so the
                                   # backward recompute skips the attention
                                   # forward kernel (the one op whose
                                   # recompute is NOT a plain MXU matmul)
                                   # but still recomputes the cheap linear
                                   # fwds. The reference's own selective
                                   # recompute (random.py::
                                   # CheckpointFunction) is the analogous
                                   # per-op choice.
                                   # "flash_offload" = same saved set, but
                                   # the flash residuals live in
                                   # pinned_host instead of HBM (device
                                   # memory of "flash" traded for d2h/h2d
                                   # transfers — an A/B candidate for
                                   # batch unlocking on 16 GB chips).
    fp32_logits: bool = False      # force fp32 INPUTS to the lm-head
                                   # matmul (3-pass MXU product + 2x
                                   # logits memory). Default follows
                                   # Megatron: logits in the compute
                                   # dtype, fp32 accumulation in the MXU,
                                   # cross-entropy upcasts per tile. Kept
                                   # as a flag so the decision stays
                                   # A/B-measurable (bench_step_variants).
    scan_layers: bool = False      # lax.scan over stacked layer params
                                   # (compile time O(1) in depth; pass
                                   # params through stack_layer_params)
    loss_chunk: object = None      # rows per chunk for the fused
                                   # linear+CE path (bert_loss AND
                                   # gpt_loss, incl. CP): lm-head
                                   # matmul + cross-entropy run chunked
                                   # under per-chunk remat, so the full
                                   # [s*b, v] logits never materialize.
                                   # None = dense (default). Exact same
                                   # math; decides peak memory at large
                                   # batch x vocab.
    moe_experts: int = 0           # > 0 replaces the dense MLP with the
                                   # MoE layer (transformer/moe.py):
                                   # experts sharded over the MODEL axis
                                   # (expert parallelism rides the TP
                                   # group; attention stays TP). Router
                                   # is replicated — without SP every
                                   # rank routes identical tokens, so
                                   # ep=tp output equals the tp=1 model
                                   # exactly; under SP router grads join
                                   # the sp_grad_sync psum class like
                                   # every replicated leaf. Aux losses
                                   # (Switch load-balance + router z)
                                   # are folded into gpt/bert_loss with
                                   # the coefficients below.
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coeff: float = 0.01    # load-balance loss weight
    moe_z_coeff: float = 1e-3      # router z-loss weight

    def __post_init__(self):
        assert self.remat_policy in (
            "full", "dots", "flash", "dots_flash", "flash_offload", "none"
        ), f"unknown remat_policy {self.remat_policy!r}"
        assert self.moe_experts >= 0
        assert self.norm in ("layernorm", "rmsnorm"), self.norm
        assert self.mlp_act in ("gelu", "swiglu"), self.mlp_act
        # mlp_act flows into the experts too (MoEConfig.act) — Mixtral-
        # style swiglu experts are supported, nothing silently downgrades
        if self.kv_heads:
            assert self.heads % self.kv_heads == 0, (
                f"heads={self.heads} not a multiple of "
                f"kv_heads={self.kv_heads}")
            # GQA + context_axis composes since round 5:
            # flash_attention_with_lse threads grouped KV through the
            # kernels' index maps, so the ring path needs no repeated KV
        assert self.loss_chunk is None or (
            isinstance(self.loss_chunk, int)
            and not isinstance(self.loss_chunk, bool)
            and self.loss_chunk > 0
        ), f"loss_chunk must be None or a positive int, got {self.loss_chunk!r}"
        if self.context_axis is not None:
            assert not self.sequence_parallel, (
                "context_axis and sequence_parallel both shard the sequence"
            )
            assert self.dropout_p == 0.0 and self.attn_dropout_p == 0.0, (
                "context parallelism does not thread per-chunk dropout keys"
            )

    @property
    def head_dim(self) -> int:
        return self.hidden // self.heads


def _ffn_width(cfg: TransformerConfig) -> int:
    return int(cfg.hidden * cfg.ffn_mult)


def _qkv_cols(cfg: TransformerConfig) -> int:
    if cfg.kv_heads:
        group = cfg.heads // cfg.kv_heads
        return cfg.kv_heads * (group + 2) * cfg.head_dim
    return 3 * cfg.hidden


def _ln_init(cfg: TransformerConfig):
    p = {"gamma": jnp.ones((cfg.hidden,), cfg.dtype)}
    if cfg.norm == "layernorm":
        p["beta"] = jnp.zeros((cfg.hidden,), cfg.dtype)
    return p


def transformer_init(key, cfg: TransformerConfig):
    """Full (unsharded) parameters; shard via ``param_specs`` in_specs."""
    h, ffn = cfg.hidden, _ffn_width(cfg)
    keys = iter(jax.random.split(key, 4 + 6 * cfg.layers))

    def norm(k, shape, scale):
        return (jax.random.normal(k, shape) * scale).astype(cfg.dtype)

    params = {
        "embedding": norm(next(keys), (cfg.vocab_size, h), 0.02),
        "final_ln": _ln_init(cfg),
        "layers": [],
    }
    if not cfg.rope:
        params["pos_embedding"] = norm(next(keys), (cfg.seq_len, h), 0.02)
    fc1_cols = ffn * (2 if cfg.mlp_act == "swiglu" else 1)
    for _ in range(cfg.layers):
        layer = {
            "ln1": _ln_init(cfg),
            "qkv": {"kernel": norm(next(keys), (h, _qkv_cols(cfg)), 0.02),
                    "bias": jnp.zeros((_qkv_cols(cfg),), cfg.dtype)},
            "proj": {"kernel": norm(next(keys), (h, h),
                                    0.02 / (2 * cfg.layers) ** 0.5),
                     "bias": jnp.zeros((h,), cfg.dtype)},
            "ln2": _ln_init(cfg),
        }
        if cfg.moe_experts:
            from apex_tpu.transformer.moe import moe_init

            layer["moe"] = moe_init(next(keys), _moe_cfg(cfg))
        else:
            layer.update({
                "fc1": {"kernel": norm(next(keys), (h, fc1_cols), 0.02),
                        "bias": jnp.zeros((fc1_cols,), cfg.dtype)},
                "fc2": {"kernel": norm(next(keys), (ffn, h),
                                       0.02 / (2 * cfg.layers) ** 0.5),
                        "bias": jnp.zeros((h,), cfg.dtype)},
            })
        params["layers"].append(layer)
    return params


def _moe_cfg(cfg: TransformerConfig):
    from apex_tpu.transformer.moe import MoEConfig

    return MoEConfig(
        hidden=cfg.hidden, ffn=_ffn_width(cfg),
        num_experts=cfg.moe_experts, top_k=cfg.moe_top_k,
        capacity_factor=cfg.moe_capacity_factor,
        expert_axis=cfg.model_axis, act=cfg.mlp_act, dtype=cfg.dtype,
    )


def stack_layer_params(params):
    """[{...}] * L -> one pytree of [L, ...] arrays (for scan_layers)."""
    return dict(params, layers=jax.tree.map(
        lambda *xs: jnp.stack(xs), *params["layers"]
    ))


def param_specs(cfg: TransformerConfig):
    """PartitionSpecs for shard_map in_specs (Megatron layout: QKV/fc1
    column-split on the out dim, proj/fc2 row-split on the in dim, embedding
    vocab-split). With ``scan_layers`` the per-layer specs gain the stacked
    leading dim."""
    ax = cfg.model_axis

    def lspec(*tail):
        return P(None, *tail) if cfg.scan_layers else P(*tail)

    def ln_spec():
        s = {"gamma": lspec()}
        if cfg.norm == "layernorm":
            s["beta"] = lspec()
        return s

    layer = {
        "ln1": ln_spec(),
        "qkv": {"kernel": lspec(None, ax), "bias": lspec(ax)},
        "proj": {"kernel": lspec(ax, None), "bias": lspec()},
        "ln2": ln_spec(),
    }
    if cfg.moe_experts:
        # experts shard over the model axis (EP rides the TP group);
        # the router is replicated like LN params
        layer["moe"] = {"router": lspec(),
                        "w1": lspec(ax, None, None),
                        "w2": lspec(ax, None, None)}
    else:
        layer.update({
            "fc1": {"kernel": lspec(None, ax), "bias": lspec(ax)},
            "fc2": {"kernel": lspec(ax, None), "bias": lspec()},
        })
    specs = {
        "embedding": P(ax, None),
        "final_ln": ({"gamma": P(), "beta": P()}
                     if cfg.norm == "layernorm" else {"gamma": P()}),
        "layers": layer if cfg.scan_layers
        else [dict(layer) for _ in range(cfg.layers)],
    }
    if not cfg.rope:
        specs["pos_embedding"] = P()
    return specs


def _output_dropout(y, cfg: TransformerConfig, dropout_key):
    """Inverted dropout on a sublayer output (one definition for the
    attention, dense-MLP, and MoE paths — key discipline is the caller's,
    see _forward_hidden)."""
    if cfg.dropout_p > 0.0:
        keep = jax.random.bernoulli(dropout_key, 1 - cfg.dropout_p, y.shape)
        y = jnp.where(keep, y / (1 - cfg.dropout_p), 0.0).astype(y.dtype)
    return y


def _norm(x, p, cfg: TransformerConfig):
    """ln1/ln2/final_ln dispatch: LayerNorm (gamma+beta) or RMSNorm
    (gamma only) per cfg.norm — both the Pallas-kernel ops."""
    if cfg.norm == "rmsnorm":
        from apex_tpu.ops.layer_norm import rms_norm

        return rms_norm(x, p["gamma"])
    return layer_norm(x, p["gamma"], p["beta"])


def _rope_tables(cfg: TransformerConfig, s: int):
    """cos/sin sliced to this rank's positions (CP chunks are offset)."""
    from apex_tpu.ops.rope import rope_frequencies

    cos, sin = rope_frequencies(cfg.head_dim, cfg.seq_len)
    if cfg.context_axis is not None:
        off = jax.lax.axis_index(cfg.context_axis) * s
        cos = jax.lax.dynamic_slice_in_dim(cos, off, s, 0)
        sin = jax.lax.dynamic_slice_in_dim(sin, off, s, 0)
    return cos, sin


def split_qkv(qkv, cfg: TransformerConfig):
    """Local QKV columns [s, b, cols/tp] -> (q, k, v) head tensors
    ([s, b, nh(_kv)_local, d]) under the Megatron column layouts. ONE
    definition shared by the training forward (_attention) and the
    serving engine (serving/engine.py) — the layouts must agree or a
    served checkpoint silently permutes heads.

    Dense MHA: columns ordered [heads, (q|k|v), d] so a contiguous TP
    column split hands each rank WHOLE heads — the same function at every
    tp (ref: attention.py reshapes local qkv to [s, b, nh_local, 3*hd]
    then split_tensor_along_last_dim; the round-1 [3, nh, hd] order
    silently changed with tp). GQA: KV-GROUP-major — per kv head
    [q_0..q_{g-1}, k, v] — the same invariance argument, requiring
    kv_heads % tp == 0 (each rank needs whole kv groups)."""
    s, b = qkv.shape[0], qkv.shape[1]
    dd = cfg.head_dim
    if cfg.kv_heads:
        group = cfg.heads // cfg.kv_heads
        assert qkv.shape[-1] % ((group + 2) * dd) == 0, (
            f"GQA column split landed mid-group: local qkv cols "
            f"{qkv.shape[-1]} vs group stride {(group + 2) * dd} — "
            f"kv_heads={cfg.kv_heads} must be divisible by the model-axis "
            "size (each TP rank needs whole kv groups)")
        n_kv = qkv.shape[-1] // ((group + 2) * dd)
        qkv = qkv.reshape(s, b, n_kv, group + 2, dd)
        q = qkv[:, :, :, :group].reshape(s, b, n_kv * group, dd)
        k = qkv[:, :, :, group]           # [s, b, n_kv, d]
        v = qkv[:, :, :, group + 1]
        return q, k, v
    n_local = qkv.shape[-1] // (3 * dd)
    qkv = qkv.reshape(s, b, n_local, 3, dd)
    q, k, v = (qkv[:, :, :, i] for i in range(3))      # [s, b, nh, d]
    return q, k, v


def _attention(lp, x, cfg: TransformerConfig, dropout_key, attn_key=None,
               rope_tables=None):
    """x: [s(, /tp if SP), b, h] -> same. Column QKV (no output gather) ->
    flash attention on the tp-local heads -> row projection.
    ``rope_tables``: (cos, sin) computed ONCE by the caller so the
    transcendentals don't re-emit per scan/remat body (None rebuilds —
    kept for direct callers like test_model_pipeline's blocks)."""
    ax = cfg.model_axis
    qkv = column_parallel_linear(
        x, lp["qkv"]["kernel"], lp["qkv"]["bias"], axis=ax,
        gather_output=False,
        sequence_parallel_enabled=cfg.sequence_parallel,
    )                                     # [s, b, 3h/tp]
    s, b = qkv.shape[0], qkv.shape[1]
    dd = cfg.head_dim
    q, k, v = split_qkv(qkv, cfg)
    if cfg.rope:
        from apex_tpu.ops.rope import apply_rope

        cos, sin = rope_tables if rope_tables is not None \
            else _rope_tables(cfg, s)
        # apply_rope wants [..., s, heads, d]
        q = apply_rope(q.transpose(1, 0, 2, 3), cos, sin).transpose(
            1, 0, 2, 3)
        k = apply_rope(k.transpose(1, 0, 2, 3), cos, sin).transpose(
            1, 0, 2, 3)
    # [s, b, nh, d] -> [b, nh, s, d]
    q, k, v = (t.transpose(1, 2, 0, 3) for t in (q, k, v))
    if cfg.context_axis is not None:
        from apex_tpu.transformer.context_parallel import ring_attention

        o = ring_attention(q, k, v, cfg.context_axis, causal=cfg.causal)
    elif cfg.attn_dropout_p > 0.0:
        # fused in-kernel probability dropout; the rank-varying attn_key
        # desyncs masks across TP ranks (each holds different heads)
        o = flash_attention(q, k, v, causal=cfg.causal,
                            dropout_p=cfg.attn_dropout_p,
                            dropout_rng=attn_key)
    else:
        o = flash_attention(q, k, v, causal=cfg.causal)
    o = o.transpose(2, 0, 1, 3).reshape(s, b, q.shape[1] * dd)
    o = row_parallel_linear(
        o, lp["proj"]["kernel"], lp["proj"]["bias"], axis=ax,
        input_is_parallel=True,
        sequence_parallel_enabled=cfg.sequence_parallel,
    )
    return _output_dropout(o, cfg, dropout_key)


def _mlp(lp, x, cfg: TransformerConfig, dropout_key):
    ax = cfg.model_axis
    y = column_parallel_linear(
        x, lp["fc1"]["kernel"], lp["fc1"]["bias"], axis=ax,
        gather_output=False,
        sequence_parallel_enabled=cfg.sequence_parallel,
    )
    if cfg.mlp_act == "swiglu":
        # interleaved [f0_gate, f0_up, f1_gate, ...] columns: the local
        # chunk is whole pairs at any tp
        y = y.reshape(y.shape[:-1] + (y.shape[-1] // 2, 2))
        y = jax.nn.silu(y[..., 0]) * y[..., 1]
    else:
        y = jax.nn.gelu(y)
    y = row_parallel_linear(
        y, lp["fc2"]["kernel"], lp["fc2"]["bias"], axis=ax,
        input_is_parallel=True,
        sequence_parallel_enabled=cfg.sequence_parallel,
    )
    return _output_dropout(y, cfg, dropout_key)


def _moe_mlp(lp, x, cfg: TransformerConfig, dropout_key):
    """MoE replacement for _mlp: x [s(,/tp under SP), b, h] -> (y, aux).
    Experts ride the model axis (expert parallelism inside the TP group);
    aux is the weighted Switch load-balance + router-z scalar for this
    layer. Without SP every rank routes identical tokens, so the output
    is TP-replicated exactly like _mlp's row-parallel output."""
    from apex_tpu.transformer.moe import moe_apply

    s_dim, b = x.shape[0], x.shape[1]
    # without SP the activations are TP-replicated: every model rank
    # routes the same tokens, so the expert-grad 1/p correction applies
    # (see moe_apply); under SP each rank holds its own s/tp tokens
    y, aux = moe_apply(
        lp["moe"], x.reshape(s_dim * b, cfg.hidden), _moe_cfg(cfg),
        tokens_replicated_over_axis=not cfg.sequence_parallel,
    )
    y = _output_dropout(y.reshape(s_dim, b, cfg.hidden), cfg, dropout_key)
    aux_total = (cfg.moe_aux_coeff * aux["load_balance"]
                 + cfg.moe_z_coeff * aux["router_z"])
    return y, aux_total


def _forward_hidden(params, tokens, cfg: TransformerConfig, *,
                    seed: int = 1234):
    """tokens: [b, s] int32 (shard_map-local batch shard). Returns the
    post-gather hidden states [s, b, h] — the tensor the lm head
    (_lm_logits) consumes; transformer_forward composes the two."""
    ax = cfg.model_axis
    if cfg.sequence_parallel:
        # Megatron SP entry: the vocab-parallel combine IS the seq scatter —
        # reduce_scatter of the partial lookups (bwd all_gather keeps the
        # vocab-shard grads complete) — and each rank adds only ITS slice
        # of the position table, so pos grads are seq-local and belong to
        # the sp_grad_sync psum class.
        emb = vocab_parallel_embedding(
            tokens, params["embedding"], axis=ax, reduce_output=False
        )
        x = emb.transpose(1, 0, 2)        # [s, b, h] partial sums
        x = reduce_scatter_to_sequence_parallel_region(x, ax)
        if cfg.rope:                       # positions live in q/k rotation
            x = x.astype(cfg.dtype)
        else:
            pos = jax.lax.dynamic_slice_in_dim(
                params["pos_embedding"][: tokens.shape[1]],
                jax.lax.axis_index(ax) * x.shape[0], x.shape[0], 0,
            )
            x = (x + pos[:, None, :]).astype(cfg.dtype)
    else:
        emb = vocab_parallel_embedding(tokens, params["embedding"], axis=ax)
        if cfg.rope:                       # positions live in q/k rotation
            x = emb.astype(cfg.dtype)
        elif cfg.context_axis is not None:
            # tokens are the LOCAL seq chunk: positions are globally offset
            s_local = tokens.shape[1]
            pos = jax.lax.dynamic_slice_in_dim(
                params["pos_embedding"],
                jax.lax.axis_index(cfg.context_axis) * s_local, s_local, 0,
            )
            x = (emb + pos[None]).astype(cfg.dtype)
        else:
            x = (emb + params["pos_embedding"][None, : tokens.shape[1]]).astype(
                cfg.dtype
            )
        x = x.transpose(1, 0, 2)          # [s, b, h] (Megatron layout)
    # Output dropout follows the reference's RNG discipline: the outputs of
    # row-parallel layers are TP-REPLICATED when SP is off, so their dropout
    # uses the *default* (TP-synced) stream — every rank must apply the same
    # mask or the residual stream desynchronizes. Under SP the activations
    # are seq-sharded (each rank holds different tokens), so the
    # rank-varying model-parallel stream is the right one.
    keys = model_parallel_seed(seed, ax)
    mp_key = keys.model_parallel if cfg.sequence_parallel else keys.default
    # attention-PROB dropout always draws from the rank-varying stream
    # (folded away from the 2i/2i+1 output-dropout folds above)
    attn_base = jax.random.fold_in(keys.model_parallel, 0x617474)
    # rope tables once, outside the scan/remat bodies
    rope_tbl = _rope_tables(cfg, x.shape[0]) if cfg.rope else None

    def block(x, lp, i):
        k1 = jax.random.fold_in(mp_key, 2 * i)
        k2 = jax.random.fold_in(mp_key, 2 * i + 1)
        ka = jax.random.fold_in(attn_base, i)
        x = x + _attention(lp, _norm(x, lp["ln1"], cfg), cfg, k1, ka,
                           rope_tables=rope_tbl)
        ln2 = _norm(x, lp["ln2"], cfg)
        if cfg.moe_experts:
            y, aux = _moe_mlp(lp, ln2, cfg, k2)
        else:
            y, aux = _mlp(lp, ln2, cfg, k2), jnp.float32(0.0)
        return x + y, aux

    if cfg.remat and cfg.remat_policy != "none":
        if cfg.remat_policy == "dots":
            block = jax.checkpoint(
                block,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            )
        elif cfg.remat_policy == "flash":
            block = jax.checkpoint(
                block,
                policy=jax.checkpoint_policies.save_only_these_names(
                    "flash_out", "flash_lse"
                ),
            )
        elif cfg.remat_policy == "dots_flash":
            # matmul outputs AND the flash kernel's (o, lse) residuals:
            # the backward recomputes only LN/elementwise — no MXU work
            # and no attention forward. Memory sits between "dots" and
            # "none"; measured v5e 2026-07-31: "dots" fits (and beats
            # full remat) at b32 with flash block 512, so this is the
            # next rung on the same ladder.
            block = jax.checkpoint(
                block,
                policy=jax.checkpoint_policies.save_from_both_policies(
                    jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                    jax.checkpoint_policies.save_only_these_names(
                        "flash_out", "flash_lse"
                    ),
                ),
            )
        elif cfg.remat_policy == "flash_offload":
            block = jax.checkpoint(
                block,
                policy=jax.checkpoint_policies
                .save_and_offload_only_these_names(
                    names_which_can_be_saved=[],
                    names_which_can_be_offloaded=["flash_out", "flash_lse"],
                    offload_src="device", offload_dst="pinned_host",
                ),
            )
        else:
            block = jax.checkpoint(block)
    aux_sum = jnp.float32(0.0)
    if cfg.scan_layers:
        def scan_body(carry, li):
            x, acc = carry
            x, aux = block(x, li[0], li[1])
            return (x, acc + aux), None

        (x, aux_sum), _ = jax.lax.scan(
            scan_body, (x, aux_sum),
            (params["layers"], jnp.arange(cfg.layers)),
        )
    else:
        for i, lp in enumerate(params["layers"]):
            x, aux = block(x, lp, i)
            aux_sum = aux_sum + aux
    # Final LN runs on the seq-sharded x under SP (Megatron keeps it inside
    # the SP region), so its grads are seq-local and sp_grad_sync's psum is
    # the correct completion.
    x = _norm(x, params["final_ln"], cfg)
    # Parallel-lm-head entry for the tied-embedding vocab-parallel logits
    # [s, b, h] @ [h, v/tp]: each rank's dx = dlogits_local @ emb_shard is a
    # PARTIAL sum, so the entry's backward must reduce it — without that,
    # every upstream grad is silently partial (round-1 bug caught by finite
    # differences; the loss-only parity tests missed it). Under SP the
    # gather's backward reduce_scatter does double duty (Megatron's
    # sequence_parallel ColumnParallelLinear); otherwise copy_to's psum.
    if cfg.sequence_parallel:
        x = gather_from_sequence_parallel_region(x, ax, True)
    else:
        x = copy_to_tensor_model_parallel_region(x, ax)
    # MoE aux must be a TP-consistent scalar: under SP each model rank
    # routed only its s/tp tokens (under CP its seq chunk) — average so
    # every rank adds the same aux to the loss
    if cfg.moe_experts and cfg.sequence_parallel:
        aux_sum = jax.lax.pmean(aux_sum, ax)
    if cfg.moe_experts and cfg.context_axis is not None:
        aux_sum = jax.lax.pmean(aux_sum, cfg.context_axis)
    return x, aux_sum


def _lm_logits(x, params, cfg: TransformerConfig):
    # Vocab logits stay in the compute dtype (Megatron computes
    # parallel_lm_logits in half precision; vocab_parallel_cross_entropy
    # upcasts to fp32 per-tile). The MXU accumulates bf16 x bf16 in fp32
    # regardless of the output dtype, so only the stored logits lose
    # mantissa — and forcing fp32 INPUTS here costs a 3-pass MXU matmul on
    # the h x vocab product (~9% of model MACs at BERT-large) plus a 2x
    # larger [s, b, v] intermediate. Measured on v5e via
    # benchmarks/bench_step_variants.py (see BASELINE.md).
    ldt = jnp.float32 if cfg.fp32_logits else cfg.dtype
    return jnp.matmul(
        x.astype(ldt),
        params["embedding"].astype(ldt).T,
        preferred_element_type=jnp.float32 if cfg.fp32_logits else None,
    )


def transformer_forward(params, tokens, cfg: TransformerConfig, *,
                        seed: int = 1234):
    """Full forward to vocab-parallel logits [s, b, v/tp]. (MoE aux
    losses are dropped here — use gpt_loss/bert_loss for training.)"""
    x, _ = _forward_hidden(params, tokens, cfg, seed=seed)
    return _lm_logits(x, params, cfg)


def _chunked_masked_ce(x, params, labels_sb, weight_sb, cfg):
    """Masked CE summed over rows WITHOUT materializing full [s*b, v]
    logits: row chunks of ``cfg.loss_chunk`` run lm-matmul + CE under
    jax.checkpoint inside lax.scan, so peak logits memory is
    O(chunk * v/tp) and the backward recomputes per chunk (the fused
    linear+cross-entropy pattern; enables batches whose dense logits
    would not fit). Exact same math as the dense path.

    x [s, b, h]; labels_sb / weight_sb [s, b] (weight 0 = ignore).
    Returns the weighted SUM of per-token losses (caller divides)."""
    n = x.shape[0] * x.shape[1]
    h = x.shape[-1]
    c = int(cfg.loss_chunk)
    xf = x.reshape(n, h)
    lf = labels_sb.reshape(n)
    wf = weight_sb.reshape(n).astype(jnp.float32)
    pad = (-n) % c
    if pad:
        xf = jnp.concatenate([xf, jnp.zeros((pad, h), xf.dtype)])
        lf = jnp.concatenate([lf, jnp.zeros((pad,), lf.dtype)])
        wf = jnp.concatenate([wf, jnp.zeros((pad,), jnp.float32)])

    def one(total, inp):
        x_c, l_c, w_c = inp
        logits = _lm_logits(x_c, params, cfg)
        losses = vocab_parallel_cross_entropy(
            logits, l_c, axis=cfg.model_axis
        )
        return total + jnp.sum(losses * w_c), None

    total, _ = jax.lax.scan(
        jax.checkpoint(one),
        jnp.float32(0.0),
        (xf.reshape(-1, c, h), lf.reshape(-1, c), wf.reshape(-1, c)),
    )
    return total


def gpt_loss(params, tokens, cfg: TransformerConfig, *, seed: int = 1234):
    """Next-token LM loss, mean over (s-1)*b tokens (shard_map-local; mean
    over the data axis is the caller's psum).

    Under context parallelism the target of a chunk's LAST token is the
    FIRST token of the next rank's chunk — fetched with one tiny ppermute —
    and the global final position is excluded; sum and count psum over the
    context axis so the mean matches the unsharded loss exactly."""
    if cfg.context_axis is not None:
        axc = cfg.context_axis
        c = jax.lax.axis_size(axc)
        r = jax.lax.axis_index(axc)
        s_local, b = tokens.shape[1], tokens.shape[0]
        nxt = jax.lax.ppermute(
            tokens[:, :1], axc, [((i + 1) % c, i) for i in range(c)]
        )                                            # next chunk's first token
        targets = jnp.concatenate([tokens[:, 1:], nxt], axis=1).transpose(1, 0)
        valid = jnp.where(
            r == c - 1,
            jnp.arange(s_local) < s_local - 1,
            jnp.ones((s_local,), bool),
        ).astype(jnp.float32)
        weights = jnp.broadcast_to(valid[:, None], (s_local, b))
        x, aux = _forward_hidden(params, tokens, cfg, seed=seed)
        if cfg.loss_chunk:
            total = _chunked_masked_ce(x, params, targets, weights, cfg)
        else:
            logits = _lm_logits(x, params, cfg)
            losses = vocab_parallel_cross_entropy(
                logits, targets, axis=cfg.model_axis
            )                                        # [s_local, b]
            total = (losses * weights).sum()
        total = jax.lax.psum(total, axc)
        count = jax.lax.psum(valid.sum() * b, axc)
        return total / count + aux
    s_len, b = tokens.shape[1], tokens.shape[0]
    x, aux = _forward_hidden(params, tokens, cfg, seed=seed)
    if cfg.loss_chunk:
        # weight 0 on the final position replaces the logits[:-1] slice
        targets = jnp.roll(tokens, -1, axis=1).transpose(1, 0)   # [s, b]
        weights = jnp.broadcast_to(
            (jnp.arange(s_len) < s_len - 1).astype(jnp.float32)[:, None],
            (s_len, b),
        )
        total = _chunked_masked_ce(x, params, targets, weights, cfg)
        return total / ((s_len - 1) * b) + aux
    logits = _lm_logits(x, params, cfg)
    targets = tokens[:, 1:].transpose(1, 0)          # [s-1, b]
    losses = vocab_parallel_cross_entropy(
        logits[:-1], targets, axis=cfg.model_axis
    )
    return losses.mean() + aux


def bert_loss(params, tokens, labels, loss_mask, cfg: TransformerConfig, *,
              seed: int = 1234, reduce_axes=()):
    """Masked-LM loss: CE at masked positions only (labels [b, s],
    loss_mask [b, s] with 1 = predict here).

    ``reduce_axes``: mesh axes holding batch shards (e.g. ``("data",)``).
    The masked-token count varies per shard, so the sum and count are
    psum'd over those axes BEFORE dividing — a naive pmean of per-shard
    means would weight shards with few masked tokens too heavily.
    """
    mask = loss_mask.transpose(1, 0).astype(jnp.float32)
    x, aux = _forward_hidden(params, tokens, cfg, seed=seed)
    if cfg.loss_chunk:
        total = _chunked_masked_ce(
            x, params, labels.transpose(1, 0), mask, cfg
        )
    else:
        logits = _lm_logits(x, params, cfg)
        losses = vocab_parallel_cross_entropy(
            logits, labels.transpose(1, 0), axis=cfg.model_axis
        )
        total = (losses * mask).sum()
    count = mask.sum()
    for axis in reduce_axes:
        total = jax.lax.psum(total, axis)
        count = jax.lax.psum(count, axis)
        aux = jax.lax.pmean(aux, axis)
    return total / jnp.maximum(count, 1.0) + aux


def sp_grad_sync(grads, cfg: TransformerConfig):
    """All-reduce over the model axis the gradients of TP-REPLICATED params
    computed in the sequence-sharded region (LN gammas/betas, row-parallel
    biases). Megatron does exactly this extra reduction when
    sequence_parallel is on (each TP rank only saw s/tp tokens); without SP
    those grads are already identical across ranks. No-op when SP is off.
    """
    if not cfg.sequence_parallel:
        return grads
    specs = param_specs(cfg)

    def sync(g, spec):
        if cfg.model_axis in jax.tree.leaves(tuple(spec)):
            return g  # TP-sharded leaf: grad is rank-local by design
        return jax.lax.psum(g, cfg.model_axis)

    return jax.tree.map(
        sync, grads, specs,
        is_leaf=lambda x: isinstance(x, P) or not isinstance(x, (dict, list)),
    )
