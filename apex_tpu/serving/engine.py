"""Serving engine — ONE fixed-shape jitted step over standalone_gpt.

A single device program, compiled ONCE, drives all traffic: every step
carries a PACKED batch of at most ``chunk_tokens`` query tokens — any
mix of prompt chunks (chunked prefill) and decode steps, one run per
slot — through the training layers (the SAME tensor-parallel layers as
testing/standalone_transformer.py — arxiv 2605.25645's argument for one
stack, not a separate serving port) with attention running through the
ragged multi-query paged-attention kernel (ops/paged_attention.py)
against the block-paged KV cache (serving/kv_cache.py). Each layer
writes the packed rows' K/V into the paged pool FIRST, then attends, so
causality within a chunk and across the resident prefix is uniform; the
greedy token of every packed row comes back and the host keeps the rows
it needs (a decode row's next token; a prompt-completing chunk's last
row = the request's FIRST token). Shapes never depend on the request
mix, so the jit cache sees exactly ONE step signature over any workload
— asserted by trace counters (``engine.trace_counts["step"]``; the tiny
admission/indexing helpers — share/retain/release/free — are separate
one-compile programs that never touch the transformer).

Prefix caching: the engine owns a persistent host-side
kv_cache.PrefixIndex. At admission the scheduler shares a prompt's
already-resident full blocks (device ``share_prefix``: refcount += 1,
only the suffix is prefilled or charged); when a request finishes, its
prompt's full blocks are inserted into the index and RETAINED (+1)
before the slot frees, so the pages survive for the next hit. Warm
requests are bitwise-identical to cold ones: the same single program
runs either way, only the run metadata differs, and every row's
attention reads the same K/V values whether this request or an earlier
identical prefix wrote them.

Continuous batching: the host loop (``ServingEngine.run``) interleaves
admission with planned steps under the scheduler's refcount-aware
free-block watermark (serving/scheduler.py) and evicts finished
sequences by returning non-shared blocks to the pool, so later arrivals
join mid-flight and long prompts prefill in chunks without stalling
running decodes.

Speculative decoding (``ServingConfig.spec``, serving/speculative.py):
decode is memory-bandwidth-bound, so a drafter proposes K tokens per
decode-ready slot and the SAME unified step verifies the whole window
as one ``query_len = K + 1`` ragged run — one weight-read per K + 1
candidate tokens instead of per token. Greedy longest-prefix acceptance
keeps the drafts the model itself would have emitted plus one bonus
token (every emitted token IS the model's greedy output at its
position, so speculative output is bitwise token-identical to
non-speculative decode at any accept rate); rejected tokens' cache
positions roll back through ``kv_cache.truncate_slots`` (refcount-aware
— over-allocated suffix pages return to the pool, prefix-shared pages
just drop this table's reference). Window block growth is pre-staged by
a ``grow_slots`` helper call so the step program stays byte-identical
spec-on vs spec-off, and the scheduler charges drafted tokens against
the same ``chunk_tokens`` budget while adapting each slot's depth to
its observed accept rate. ``spec`` off (the default) runs today's path
unchanged — no drafter, no helper calls, same compiled step.

Tensor parallelism is the training layout re-used verbatim: weights
shard via ``param_specs``, the cache's KV heads ride the model axis
(kv_cache.cache_pspecs), logits stay vocab-parallel and greedy sampling
argmaxes across shards with a pmax/pmin pair — token-identical to the
single-device argmax (first-max-wins tie-break in both).

Env knobs (docs/serving.md): ``APEX_TPU_PAGED_BLOCK_SIZE`` (cache page
size, default 16), ``APEX_TPU_SERVING_MAX_SLOTS`` (slot count, default
8), ``APEX_TPU_SERVING_CHUNK_TOKENS`` (per-step token budget),
``APEX_TPU_PREFIX_CACHE`` (0 disables prefix sharing),
``APEX_TPU_SERVING_SPEC`` (1 enables speculative decoding, default
off), ``APEX_TPU_SERVING_SPEC_K`` (max draft depth, default 4),
``APEX_TPU_SERVING_KV_INT8`` (1 quantizes the KV pool to int8 with
per-(token, head) fp32 scales — SAME pool bytes, more blocks
(``ServingConfig.pool_blocks``: ~2-4x vs an fp32 cache dtype, ~1.8x vs
bf16 — the sidecar's 4 B/row fixed cost bites harder against a 2 B
payload), greedy output token-matched against the full-width cache by
the quant leg/bench rung; default off = byte-for-byte today's cache
path) — defaults for ServingConfig, explicit arguments win.
"""

from __future__ import annotations

import dataclasses
import functools
import math
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.ops.paged_attention import (
    packed_row_slots,
    ragged_paged_attention,
)
from apex_tpu.serving import kv_cache as kc
from apex_tpu.serving.fleet import slo as slo_mod
from apex_tpu.serving.scheduler import Request, Scheduler
from apex_tpu.testing.commons import smap
from apex_tpu.testing.standalone_transformer import (
    TransformerConfig,
    _lm_logits,
    _mlp,
    _norm,
    param_specs,
    split_qkv,
    transformer_forward,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
)
from apex_tpu.observability import (
    default_registry,
    inc_counter,
    metrics_enabled,
    observe,
    set_gauge,
)
from apex_tpu.observability import events as obs_events
from apex_tpu.observability.tracing import trace_span
from apex_tpu.utils.envvars import env_flag, env_int
from apex_tpu.utils.profiling import trace_range

# serving/chunk_utilization histogram: fraction of the step budget
# actually carrying query tokens
UTIL_BUCKETS = (0.1, 0.25, 0.5, 0.75, 0.9, 1.0)
# serving/spec_accept_rate histogram: accepted / drafted per verify run
SPEC_BUCKETS = (0.1, 0.25, 0.5, 0.75, 0.9, 1.0)
_I32_MAX = 2**31 - 1


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Engine geometry. ``model`` is the training TransformerConfig the
    checkpoint was built with; serving supports its dense decode subset
    (no SP/CP/MoE/dropout — asserted at engine construction)."""

    model: TransformerConfig
    num_blocks: int = 128
    block_size: Optional[int] = None        # APEX_TPU_PAGED_BLOCK_SIZE | 16
    max_slots: Optional[int] = None         # APEX_TPU_SERVING_MAX_SLOTS | 8
    max_prefill_len: Optional[int] = None   # seeds the chunk budget default
    max_seq_len: Optional[int] = None       # context cap per sequence
    watermark: Optional[int] = None         # admission reserve (None=slots)
    eos_id: Optional[int] = None            # greedy stop token (None = off)
    dtype: object = None                    # cache dtype (None = model's)
    chunk_tokens: Optional[int] = None      # APEX_TPU_SERVING_CHUNK_TOKENS
    prefix_cache: Optional[bool] = None     # APEX_TPU_PREFIX_CACHE | on
    spec: Optional[bool] = None             # APEX_TPU_SERVING_SPEC | off
    spec_k: Optional[int] = None            # APEX_TPU_SERVING_SPEC_K | 4
    kv_int8: Optional[bool] = None          # APEX_TPU_SERVING_KV_INT8 | off

    def __post_init__(self):
        s = object.__setattr__
        if self.block_size is None:
            s(self, "block_size",
              env_int("APEX_TPU_PAGED_BLOCK_SIZE", default=16))
        if self.max_slots is None:
            s(self, "max_slots",
              env_int("APEX_TPU_SERVING_MAX_SLOTS", default=8))
        if self.max_seq_len is None:
            s(self, "max_seq_len", self.model.seq_len)
        if self.max_prefill_len is None:
            s(self, "max_prefill_len", min(self.max_seq_len, 64))
        if self.chunk_tokens is None:
            s(self, "chunk_tokens",
              env_int("APEX_TPU_SERVING_CHUNK_TOKENS",
                      default=max(self.max_slots, self.max_prefill_len)))
        if self.prefix_cache is None:
            env = env_flag("APEX_TPU_PREFIX_CACHE")
            s(self, "prefix_cache", True if env is None else env)
        if self.spec is None:
            # default OFF: unset leaves the engine byte-for-byte on the
            # non-speculative path (acceptance contract, docs/serving.md)
            s(self, "spec", bool(env_flag("APEX_TPU_SERVING_SPEC",
                                          default=False)))
        if self.spec_k is None:
            # the depth knob is read (and validated) only when
            # speculation is ON — a stray APEX_TPU_SERVING_SPEC_K must
            # not break plain non-speculative serving construction
            s(self, "spec_k",
              env_int("APEX_TPU_SERVING_SPEC_K", default=4)
              if self.spec else 4)
        if self.spec and self.spec_k < 1:
            raise ValueError(
                f"spec_k {self.spec_k} must be >= 1 (set spec=False to "
                f"disable speculation)")
        if self.kv_int8 is None:
            # default OFF: unset leaves the engine byte-for-byte on the
            # full-width cache path (docs/quantization.md)
            s(self, "kv_int8", bool(env_flag("APEX_TPU_SERVING_KV_INT8",
                                             default=False)))
        if self.dtype is None:
            s(self, "dtype", self.model.dtype)

    @property
    def max_blocks_per_seq(self) -> int:
        return int(math.ceil(self.max_seq_len / self.block_size))

    @property
    def pool_blocks(self) -> int:
        """The pool's ACTUAL block count: ``num_blocks`` full-width, or
        the int8 variant's count in the SAME byte budget
        (kv_cache.quantized_pool_blocks — the capacity doubling that is
        the point of ``APEX_TPU_SERVING_KV_INT8``). The scheduler's
        watermark, the occupancy gauges and the router's placement
        signals all see THIS count."""
        if not self.kv_int8:
            return self.num_blocks
        return kc.quantized_pool_blocks(self.num_blocks,
                                        self.model.head_dim, self.dtype)

    @property
    def n_kv_heads(self) -> int:
        return self.model.kv_heads or self.model.heads


def _vp_greedy(logits, axis: str, tp: int):
    """Greedy token from vocab-parallel logits [..., v/tp]: global max via
    pmax, global argmax as the SMALLEST winning index via pmin — the same
    first-max-wins tie-break as jnp.argmax on the gathered vocab (vocab
    shards are contiguous in rank order)."""
    vloc = logits.shape[-1]
    local_arg = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if tp == 1:
        return local_arg
    local_max = jnp.max(logits, axis=-1)
    gmax = jax.lax.pmax(local_max, axis)
    cand = jnp.where(local_max >= gmax,
                     local_arg + jax.lax.axis_index(axis) * vloc,
                     jnp.int32(2**30))
    return jax.lax.pmin(cand, axis)


def _rope_rows(cfg: TransformerConfig, pos):
    """Per-row RoPE table rows at positions ``pos`` [n] (fp32)."""
    from apex_tpu.ops.rope import rope_frequencies

    cos, sin = rope_frequencies(cfg.head_dim, cfg.seq_len)
    return cos[pos], sin[pos]


def _rope_at(x, cos_rows, sin_rows):
    """ops/rope._rotate at gathered per-row positions: x [n, nh, d],
    cos/sin_rows [n, d//2]. Same split-halves rotation, so the packed
    step matches the training apply_rope bit for bit."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    c = cos_rows[:, None, :]
    s = sin_rows[:, None, :]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s],
                           axis=-1).astype(x.dtype)


def _check_supported(cfg: TransformerConfig):
    for flag, msg in (
        (cfg.sequence_parallel, "sequence_parallel"),
        (cfg.context_axis is not None, "context parallelism"),
        (cfg.moe_experts > 0, "MoE layers"),
        (cfg.scan_layers, "scan_layers (pass unstacked layer params)"),
        (cfg.dropout_p > 0 or cfg.attn_dropout_p > 0, "dropout"),
        (not cfg.causal, "bidirectional (BERT) models"),
    ):
        if flag:
            raise NotImplementedError(
                f"serving engine does not support {msg}")


def counted_cache_op(counts, name, fn, mesh, cspec, n_scalar_args):
    """One-compile jitted wrapper for a pure cache op
    ``(cache, *scalars) -> cache``: shard over ``mesh`` with the cache
    donated, counting traces into ``counts[name]``. THE factory behind
    the engine's share/retain/release/free/grow/truncate helpers AND
    the draft runner's grow/truncate/free copies — one definition of
    the jit/smap/donation wiring, so the two paths cannot diverge."""

    def wrapped(*args):
        counts[name] += 1                  # trace-time side effect
        return fn(*args)

    return jax.jit(
        smap(wrapped, mesh, (cspec,) + (P(),) * n_scalar_args, cspec),
        donate_argnums=(0,))


# ---------------------------------------------------------------------------
# the unified device step (shard_map-local body)
# ---------------------------------------------------------------------------

def _step_body(params, cache, tokens, query_start, query_len, *, cfg, scfg):
    """tokens [chunk_tokens] packed input ids (prompt chunks + decode
    tokens, runs in slot order), query_start/query_len [max_slots]
    (query_len 0 = slot idle this step) -> (cache', greedy next token
    per packed row [chunk_tokens]). One fixed shape forever.

    Per step: COW-guard the append positions, advance seq_lens (decode
    rows grow a page where they cross a boundary), then per layer write
    the packed rows' K/V at their absolute positions and attend through
    the block table with the ragged multi-query kernel. Rows covered by
    no run compute masked garbage the host never reads."""
    ax = cfg.model_axis
    tq = tokens.shape[0]
    bs = cache.block_size
    qs = jnp.asarray(query_start, jnp.int32)
    ql = jnp.asarray(query_len, jnp.int32)
    active = ql > 0
    cache = kc.cow_append(cache, active)
    cache = kc.extend_slots(cache, active, ql)
    kl = jnp.where(active, cache.seq_lens, 0)                  # [S]

    # packed-row geometry: row r of slot sid[r] sits at absolute
    # sequence position pos[r] (its own token included in kl)
    r = jnp.arange(tq)
    sid, rvalid = packed_row_slots(qs, ql, tq)
    pos = kl[sid] - ql[sid] + (r - qs[sid])
    pos_c = jnp.clip(pos, 0, cfg.seq_len - 1)
    tbl_idx = jnp.clip(pos // bs, 0, cache.max_blocks_per_seq - 1)
    row_blk = jnp.where(rvalid, cache.block_tables[sid, tbl_idx],
                        cache.num_blocks).astype(jnp.int32)
    row_off = jnp.where(rvalid, pos % bs, 0).astype(jnp.int32)

    emb = vocab_parallel_embedding(tokens[:, None], params["embedding"],
                                   axis=ax)[:, 0]              # [Tq, h]
    if cfg.rope:
        x = emb.astype(cfg.dtype)
        rope_rows = _rope_rows(cfg, pos_c)
    else:
        x = (emb + params["pos_embedding"][pos_c]).astype(cfg.dtype)
    x = x[None]                                        # [s=1, b=Tq, h]
    for li, lp in enumerate(params["layers"]):
        qkv = column_parallel_linear(
            _norm(x, lp["ln1"], cfg),
            lp["qkv"]["kernel"], lp["qkv"]["bias"], axis=ax,
            gather_output=False)
        q, k, v = split_qkv(qkv, cfg)                  # [1, Tq, nh, d]
        q, k, v = q[0], k[0], v[0]                     # [Tq, nh(_kv), d]
        if cfg.rope:
            q = _rope_at(q, *rope_rows)
            k = _rope_at(k, *rope_rows)
        cache = kc.append_layer(cache, li, row_blk, row_off, k, v)
        # the int8 pool's per-(token, head) scale sidecars ride into the
        # kernel for fetch-time dequantization; a full-width cache is
        # byte-for-byte the pre-quantization program (the branch is
        # trace-time python on the cache's static pytree type)
        scales = ({"k_scale": cache.k_scale[li],
                   "v_scale": cache.v_scale[li]}
                  if kc.is_quantized(cache) else {})
        o = ragged_paged_attention(q, cache.k_pool[li], cache.v_pool[li],
                                   cache.block_tables, qs, ql, kl,
                                   **scales)
        o = o.reshape(1, tq, -1)                       # [1, Tq, nh*d]
        o = row_parallel_linear(
            o, lp["proj"]["kernel"], lp["proj"]["bias"], axis=ax,
            input_is_parallel=True)
        x = x + o
        x = x + _mlp(lp, _norm(x, lp["ln2"], cfg), cfg, None)
    x = _norm(x, params["final_ln"], cfg)
    x = copy_to_tensor_model_parallel_region(x, ax)
    logits = _lm_logits(x, params, cfg)[0]             # [Tq, v/tp]
    return cache, _vp_greedy(logits, ax, scfg["tp"])


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

class ServingEngine:
    """Continuous-batching driver. ``mesh`` is a Mesh with a "model" axis
    (size 1 = single chip); weights shard per param_specs, the KV cache
    per kv_cache.cache_pspecs. The prefix index and the KV cache persist
    across ``run`` calls (that persistence IS the warm-TTFT win); all
    other loop state is per-run host python."""

    def __init__(self, scfg: ServingConfig, params,
                 mesh: Optional[Mesh] = None, drafter=None,
                 replica: str = "0"):
        cfg = scfg.model
        _check_supported(cfg)
        if mesh is None:
            mesh = Mesh(jax.devices()[:1], ("model",))
        tp = mesh.shape.get("model", 1)
        if scfg.n_kv_heads % tp:
            raise ValueError(
                f"kv heads {scfg.n_kv_heads} not divisible by tp={tp}")
        if scfg.max_seq_len > cfg.seq_len:
            # holds for rope too: the engine's RoPE tables (and the
            # unpaged parity oracle) cover cfg.seq_len positions — serving
            # past them would silently clamp rotations, not extrapolate
            raise ValueError(
                f"max_seq_len {scfg.max_seq_len} exceeds the model's "
                f"position range ({cfg.seq_len})")
        if scfg.chunk_tokens < scfg.max_slots:
            raise ValueError(
                f"chunk_tokens {scfg.chunk_tokens} < max_slots "
                f"{scfg.max_slots}: a full decode round must fit one step")
        self.scfg = scfg
        self.cfg = cfg
        self.mesh = mesh
        self.params = params
        # which fleet replica this engine is (serving/fleet): the label
        # on every serving metric series it emits — "0" outside a fleet,
        # so single-engine dashboards and tests see one labeled series
        self.replica = str(replica)
        self.index: Optional[kc.PrefixIndex] = (
            kc.PrefixIndex(scfg.block_size) if scfg.prefix_cache else None)
        self._cache: Optional[kc.PagedKVCache] = None
        self.trace_counts = {"step": 0, "share": 0, "retain": 0,
                             "release": 0, "free": 0, "grow": 0,
                             "truncate": 0}
        # speculative decoding (docs/serving.md): the drafter proposes K
        # tokens per decode-ready slot and the SAME unified step verifies
        # them as one (K+1)-token ragged run — speculation changes run
        # metadata, never the compiled program
        self.drafter = None
        self._pending_drafter = drafter
        if not scfg.spec and drafter is not None:
            raise ValueError(
                "a drafter was supplied but ServingConfig.spec is off "
                "(set spec=True or APEX_TPU_SERVING_SPEC=1)")

        pspec = param_specs(cfg)
        cspec = (kc.quant_cache_pspecs(tp_axis="model") if scfg.kv_int8
                 else kc.cache_pspecs(tp_axis="model"))
        opts = {"cfg": cfg, "scfg": {"tp": tp}}
        counts = self.trace_counts

        def step(params, cache, tokens, qs, ql):
            counts["step"] += 1               # trace-time side effect
            with trace_range("serving.step"):
                return _step_body(params, cache, tokens, qs, ql, **opts)

        self._step = jax.jit(
            smap(step, mesh, (pspec, cspec, P(), P(), P()), (cspec, P())),
            donate_argnums=(1,))
        self._share = counted_cache_op(
            counts, "share", kc.share_prefix, mesh, cspec, 4)
        self._retain = counted_cache_op(
            counts, "retain", kc.retain_blocks, mesh, cspec, 2)
        self._release = counted_cache_op(
            counts, "release", kc.release_blocks, mesh, cspec, 2)
        self._free = counted_cache_op(
            counts, "free", kc.free_slot, mesh, cspec, 1)
        # speculation's pre-staged block growth (a verify window may
        # cross more than one page boundary) and post-verify rollback —
        # tiny one-compile programs like share/retain/release/free,
        # touched only when speculation is on
        self._max_grow = min(scfg.max_blocks_per_seq,
                             -(-scfg.chunk_tokens // scfg.block_size) + 1)
        self._grow = counted_cache_op(
            counts, "grow",
            functools.partial(kc.grow_slots, max_grow=self._max_grow),
            mesh, cspec, 1)
        self._truncate = counted_cache_op(
            counts, "truncate", kc.truncate_slots, mesh, cspec, 1)
        if scfg.spec:
            if self._pending_drafter is None:
                from apex_tpu.serving.speculative import NgramDrafter
                self._pending_drafter = NgramDrafter()
            self.set_drafter(self._pending_drafter)

    def set_drafter(self, drafter) -> None:
        """Install (and ``bind``) a drafter on a speculation-enabled
        engine — the supported way to swap drafting strategies between
        runs (the bench A/B swaps a StubDrafter profile per run; a
        DraftModelDrafter builds its device state here, so attribute
        assignment would skip it). The compiled step is untouched:
        drafters only change run metadata."""
        if not self.scfg.spec:
            raise ValueError(
                "set_drafter on a non-speculative engine (set spec=True "
                "or APEX_TPU_SERVING_SPEC=1)")
        drafter.bind(self)
        self.drafter = drafter

    def reset_state(self) -> None:
        """Forget the persistent KV cache and prefix index (the next run
        cold-starts) without touching the compiled step — the A/B lever
        benches use to re-measure cold TTFT on a warmed engine."""
        self._cache = None
        if self.index is not None:
            self.index = kc.PrefixIndex(self.scfg.block_size)
        if self.drafter is not None:
            self.drafter.reset()

    def fresh_cache(self) -> kc.PagedKVCache:
        s = self.scfg
        if s.kv_int8:
            # SAME pool bytes as the full-width cache, MORE blocks —
            # the concurrent-slot capacity lever (scfg.pool_blocks)
            return kc.quantized_kv_cache(
                layers=self.cfg.layers, num_blocks=s.pool_blocks,
                block_size=s.block_size, n_kv_heads=s.n_kv_heads,
                head_dim=self.cfg.head_dim, max_slots=s.max_slots,
                max_blocks_per_seq=s.max_blocks_per_seq)
        return kc.paged_kv_cache(
            layers=self.cfg.layers, num_blocks=s.num_blocks,
            block_size=s.block_size, n_kv_heads=s.n_kv_heads,
            head_dim=self.cfg.head_dim, max_slots=s.max_slots,
            max_blocks_per_seq=s.max_blocks_per_seq, dtype=s.dtype)

    @staticmethod
    def _table_row(cache: kc.PagedKVCache, slot: int, n: int) -> np.ndarray:
        """Fetch ONE slot's first ``n`` block-table entries: slice on
        DEVICE first, so the host transfer is the [n] row — not the
        whole [max_slots, max_blocks_per_seq] table per finished
        request (pinned by test: the fetched array has the row's
        shape)."""
        return np.asarray(cache.block_tables[slot, :n])

    def _ids_row(self, ids: List[int]) -> jax.Array:
        row = jnp.zeros((self.scfg.max_blocks_per_seq,), jnp.int32)
        if ids:
            row = row.at[: len(ids)].set(jnp.asarray(ids, jnp.int32))
        return row

    # -- the serving loop -------------------------------------------
    def session(self, *, cache: Optional[kc.PagedKVCache] = None
                ) -> "ServingSession":
        """Open an INCREMENTAL serving session: the same loop ``run``
        drives, one ``step_once`` at a time — the fleet Router's entry
        point (serving/fleet), so N replicas' steps interleave on one
        host with live load signals readable between them."""
        return ServingSession(self, cache=cache)

    def run(self, requests: List[Request], *, max_steps: int = 10_000,
            cache: Optional[kc.PagedKVCache] = None) -> Dict[object, dict]:
        """Serve ``requests`` (arrival-staggered) to completion. Returns
        {rid: {"tokens": [...], "ttft_step": int, "steps": int}} plus
        engine stats under the reserved key ``None``. With no explicit
        ``cache`` the engine's persistent cache (and prefix index) carry
        over from the previous run — the warm path; passing a cache
        resets the index (its block ids would dangle). Exactly
        open-session → step until idle → finalize (ServingSession is the
        loop; this is the one-engine driver of it)."""
        sess = ServingSession(self, cache=cache)
        # fail fast at intake, BEFORE the reset-on-failure guard: a bad
        # request must not surface as silent KV corruption mid-batch —
        # and since nothing has been donated yet, it must not cost the
        # engine its warm cache/index either
        for r in requests:
            sess.add(r)
        ok = False
        try:
            while sess.has_work() and sess.step < max_steps:
                sess.step_once()
            if sess.has_work():
                raise RuntimeError(
                    f"serving loop exceeded {max_steps} steps with work "
                    f"left")
            ok = True
        finally:
            if not ok:
                # the cache buffers were donated into the jitted step as
                # the loop ran and the index's holds refer to them — a
                # failed run must cold-start the next one instead of
                # serving from deleted arrays / desynced refcounts
                self.reset_state()
        return sess.finalize()

    def _batched(self, ids: List[int]):
        """Chunk a host id list into fixed-width release calls."""
        mb = self.scfg.max_blocks_per_seq
        for i in range(0, len(ids), mb):
            yield ids[i:i + mb]


# ---------------------------------------------------------------------------
# the incremental session (one "run", steppable — the fleet unit)
# ---------------------------------------------------------------------------

class ServingSession:
    """One serving run opened incrementally: admission, SLO preemption,
    step planning, ONE device step and finish handling per ``step_once``
    call. ``ServingEngine.run`` is a plain loop over this object; the
    fleet Router (serving/fleet/router.py) drives N of them round-robin,
    reads load signals between steps, and — on preemption or replica
    failure — moves unfinished work with its already-emitted tokens
    carried as ``prior`` so the final greedy output is bitwise the
    uninterrupted run's.

    Resume contract (preemption/fault requeue): a resumed request is
    reshaped to ``prompt = original prompt + emitted tokens`` with
    ``max_new_tokens`` reduced by the emitted count; the session records
    the emitted prefix in ``_prior`` and stitches it back onto the front
    of the tokens at finish. Greedy decode over the re-prefilled context
    regenerates exactly the continuation the uninterrupted run would
    have produced (the cold/warm bitwise-parity contract), so requeueing
    never changes output."""

    def __init__(self, engine: ServingEngine, *,
                 cache: Optional[kc.PagedKVCache] = None):
        eng = engine
        s = eng.scfg
        self.eng = eng
        if cache is None:
            cache = eng._cache if eng._cache is not None \
                else eng.fresh_cache()
        elif eng.index is not None:
            eng.index = kc.PrefixIndex(s.block_size)
        self.cache = cache
        held = len(eng.index) if eng.index is not None else 0
        self.sched = Scheduler(
            max_slots=s.max_slots, num_blocks=s.pool_blocks - held,
            block_size=s.block_size,
            max_blocks_per_seq=s.max_blocks_per_seq,
            watermark=s.watermark, chunk_tokens=s.chunk_tokens,
            prefix_index=eng.index,
            spec_k=s.spec_k if eng.drafter is not None else 0,
            replica=eng.replica)
        self.gen: Dict[int, List[int]] = {}            # slot -> tokens
        self.out: Dict[object, dict] = {}
        self.stats = {"steps": 0, "prefills": 0, "decode_steps": 0,
                      "decode_tokens": 0, "chunk_steps": 0,
                      "chunk_tokens": 0,
                      "prefix_hit_tokens": 0, "prefix_miss_tokens": 0,
                      "spec_drafted_tokens": 0, "spec_accepted_tokens": 0,
                      "preemptions": 0, "requeues": 0, "slo_violations": 0,
                      "prefill_s": 0.0, "decode_s": 0.0}
        self.waiting_since: Dict[object, float] = {}   # rid -> wall ts
        self._first_tok: Dict[object, float] = {}      # rid -> wall ts
        self._prior: Dict[object, List[int]] = {}      # rid -> resumed toks
        self.step = 0
        # host-side telemetry (docs/observability.md): everything this
        # session records happens OUTSIDE the jitted step, so the step
        # HLO and the one-compile contract are untouched with metrics on
        self.kv_free_min = self.sched.free_blocks
        # SLO-aligned histogram boundaries, frozen at the series' first
        # observation (registry contract): the latency-class targets are
        # bucket EDGES, so violation rates read straight off the
        # cumulative _bucket rows (docs/observability.md)
        targets = slo_mod.targets_for(slo_mod.LATENCY)
        self._ttft_buckets = slo_mod.slo_buckets(targets.ttft_s)
        self._tpot_buckets = slo_mod.slo_buckets(targets.tpot_s)
        if metrics_enabled():
            # materialize the event counters at 0 — with the SAME label
            # shape the real increments carry — so a quiet run still
            # exports the full per-replica serving series set
            # (preemptions stays 0 until an SLO-outranked victim is
            # actually evicted)
            reg = default_registry()
            names = ["serving/admissions", "serving/evictions",
                     "serving/preemptions",
                     "serving/admission_blocked",
                     "serving/prefix_hit_tokens",
                     "serving/prefix_miss_tokens"]
            if eng.drafter is not None:
                names += ["serving/spec_drafted_tokens",
                          "serving/spec_accepted_tokens"]
            for name in names:
                reg.counter(name).inc(0, replica=eng.replica)
            set_gauge("serving/kv_blocks_total", s.pool_blocks,
                      replica=eng.replica)
            set_gauge("serving/kv_watermark", self.sched.watermark,
                      replica=eng.replica)
            if s.kv_int8:
                # the quantized pool's capacity story, exported even on
                # a quiet run (docs/quantization.md): payload + sidecar
                # bytes per pool block x the doubled block count
                row = s.block_size * s.n_kv_heads
                blk = 2 * row * (self.eng.cfg.head_dim + 4)
                set_gauge("quant/kv_pool_bytes",
                          self.eng.cfg.layers * s.pool_blocks * blk,
                          replica=eng.replica)
                set_gauge("quant/kv_pool_blocks", s.pool_blocks,
                          replica=eng.replica)

    # -- intake ------------------------------------------------------
    def _intake(self, req: Request) -> None:
        """Validate + queue (shared by fresh and resumed intake, so a
        bad request raises before anything prefills)."""
        s = self.eng.scfg
        if len(req.prompt) + req.max_new_tokens > s.max_seq_len:
            raise ValueError(
                f"request {req.rid!r}: prompt + max_new_tokens = "
                f"{len(req.prompt) + req.max_new_tokens} exceeds "
                f"max_seq_len {s.max_seq_len}")
        self.sched.add(req)

    def add(self, req: Request) -> None:
        """Queue a fresh request into this session — the lifecycle's
        ``request.submit`` event."""
        self._intake(req)
        obs_events.request_event(obs_events.SUBMIT, req.rid,
                                 self.eng.replica,
                                 slo=slo_mod.resolve_class(req.slo))

    def add_resumed(self, req: Request, prior: List[int]) -> None:
        """Queue a RESUME-shaped request (its prompt already ends with
        the ``prior`` tokens an earlier placement emitted; its
        max_new_tokens counts only the remainder) — the fault-requeue
        entry the Router uses. The session stitches ``prior`` back onto
        the front of the tokens at finish, so the request's final output
        is the uninterrupted run's. Emits ``request.resume`` (NOT a
        second submit — the chain validator wants exactly one submit
        per rid across placements)."""
        if prior:
            self._prior[req.rid] = list(prior)
        self._intake(req)
        obs_events.request_event(obs_events.RESUME, req.rid,
                                 self.eng.replica, prior=len(prior))

    def has_work(self) -> bool:
        return self.sched.has_work()

    def signals(self) -> Dict[str, float]:
        """Live load snapshot — the same quantities the per-step gauges
        export, read directly off the host mirror (no device sync):
        the router's placement inputs."""
        s = self.eng.scfg
        idx = len(self.eng.index) if self.eng.index is not None else 0
        return {
            "queue_depth": self.sched.queue_depth(),
            "running": len(self.sched.running),
            "free_blocks": self.sched.free_blocks,
            "kv_occupancy":
                1.0 - (self.sched.free_blocks + idx) / s.pool_blocks,
            "est_work_tokens": self.sched.pending_work_tokens(),
        }

    def drain(self) -> List[tuple]:
        """Extract every UNFINISHED request as a ``(resume_request,
        prior_tokens)`` pair (host state only — the device cache is left
        alone; the caller resets the engine). The Router feeds these to
        surviving replicas via ``add_resumed`` after a replica fault.
        Each pair is the lifecycle's ``request.drain`` event."""
        items: List[tuple] = []
        for req in list(self.sched._future) + list(self.sched._waiting):
            items.append((req, self._prior.get(req.rid, [])))
        for slot in sorted(self.sched.running):
            st = self.sched.running[slot]
            emitted = self.gen.get(slot, [])
            prior = self._prior.get(st.req.rid, []) + list(emitted)
            items.append((Request(
                rid=st.req.rid,
                prompt=list(st.req.prompt) + list(emitted),
                max_new_tokens=st.req.max_new_tokens - len(emitted),
                arrival=0, slo=st.req.slo), prior))
        for req, prior in items:
            obs_events.request_event(obs_events.DRAIN, req.rid,
                                     self.eng.replica, emitted=len(prior))
        return items

    def state_summary(self) -> dict:
        """Host-mirror state snapshot for the flight recorder: slots
        with their seq_lens/prefill progress, queue depth, pool
        occupancy — every number read off the scheduler's python
        mirror, NEVER a device sync (the postmortem dump must be safe
        to take while the device is wedged)."""
        sched = self.sched
        sig = self.signals()
        return {
            "replica": self.eng.replica,
            "step": self.step,
            "queue_depth": int(sig["queue_depth"]),
            "free_blocks": int(sig["free_blocks"]),
            "kv_occupancy": round(float(sig["kv_occupancy"]), 6),
            "slots": {
                str(slot): {
                    "rid": str(st.req.rid),
                    "seq_len": st.tokens_in_cache,
                    "prefilled": st.prefilled,
                    "n_blocks": st.n_blocks,
                    "slo_rank": st.slo_rank,
                }
                for slot, st in sorted(sched.running.items())
            },
        }

    # -- preemption / finish ----------------------------------------
    def _preempt(self, slot: int) -> None:
        """Evict ``slot`` for a higher-class waiter: device table freed
        (shared pages survive via their other refcounts), scheduler
        mirror released (``serving/preemptions``), and the request
        requeued at the front of its class with its emitted tokens as
        ``prior`` — no token is lost or duplicated."""
        eng = self.eng
        st = self.sched.preempt(slot)
        self.cache = eng._free(self.cache, jnp.int32(slot))
        emitted = self.gen.pop(slot, [])
        prior = self._prior.pop(st.req.rid, []) + list(emitted)
        req = Request(rid=st.req.rid,
                      prompt=list(st.req.prompt) + list(emitted),
                      max_new_tokens=st.req.max_new_tokens - len(emitted),
                      arrival=0, slo=st.req.slo)
        if prior:
            self._prior[req.rid] = prior
        self.sched.requeue(req)
        if eng.drafter is not None:
            eng.drafter.on_finish(slot)
        self.stats["preemptions"] += 1
        self.stats["requeues"] += 1
        inc_counter("fleet/requeues", 1, reason="preemption",
                    replica=eng.replica)
        obs_events.request_event(obs_events.PREEMPT, req.rid,
                                 eng.replica, slot=slot,
                                 emitted=len(emitted))
        obs_events.request_event(obs_events.REQUEUE, req.rid,
                                 eng.replica, reason="preemption")

    def _finish(self, slot: int) -> None:
        eng = self.eng
        s = eng.scfg
        sched = self.sched
        st = sched.running[slot]
        rid = st.req.rid
        prior = self._prior.pop(rid, [])
        emitted = self.gen.pop(slot)
        tokens = prior + emitted
        self.out[rid]["tokens"] = tokens
        newly: List[int] = []
        if eng.index is not None:
            n_full = len(st.req.prompt) // s.block_size
            if n_full:
                # one small host fetch per FINISHED request — the
                # index needs the slot's concrete page ids
                row = eng._table_row(self.cache, slot, n_full)
                newly = eng.index.insert(st.req.prompt,
                                         [int(b) for b in row])
                if newly:
                    self.cache = eng._retain(
                        self.cache, eng._ids_row(newly),
                        jnp.int32(len(newly)))
        self.cache = eng._free(self.cache, jnp.int32(slot))
        sched.release(slot, newly)
        if eng.drafter is not None:
            eng.drafter.on_finish(slot)
        # SLO verdict (serving/fleet/slo.py): judged per finished
        # request against its class targets — batch has none. The pace
        # is measured over THIS placement's emissions only (``emitted``,
        # not the prior tokens a previous placement produced), so a
        # resumed request's tpot reflects real decode speed instead of
        # being deflated by work done elsewhere
        cls = slo_mod.resolve_class(st.req.slo)
        first = self._first_tok.pop(rid, None)
        tpot = None
        if first is not None and len(emitted) > 1:
            tpot = (time.perf_counter() - first) / (len(emitted) - 1)
        for kind in slo_mod.violations(cls, self.out[rid].get("ttft_s"),
                                       tpot):
            self.stats["slo_violations"] += 1
            inc_counter("fleet/slo_violations", 1, slo=cls, kind=kind,
                        replica=eng.replica)
        obs_events.request_event(obs_events.FINISH, rid, eng.replica,
                                 slot=slot, tokens=len(tokens))

    # -- one tick of the loop ---------------------------------------
    def step_once(self) -> None:
        """One continuous-batching tick: arrivals, SLO preemption,
        admission, draft/plan/pack, one fixed-shape device step, and
        emission/finish handling — the exact body ``run`` loops over."""
        eng = self.eng
        s = eng.scfg
        sched = self.sched
        rep = eng.replica
        gen, out, stats = self.gen, self.out, self.stats
        step = self.step
        sched.tick(step)
        for r in list(sched._waiting):
            self.waiting_since.setdefault(r.rid, time.perf_counter())
        set_gauge("serving/queue_depth", len(sched._waiting), replica=rep)
        admissions = sched.admit()
        # SLO preemption: while the next admission candidate outranks a
        # running slot and could not be admitted, evict the most recent
        # strictly-lower-class victim and retry (greedy — bounded by the
        # running-slot count; same-class work never preempts, so an
        # SLO-less workload can never enter this loop)
        while True:
            cand = sched.peek_next()
            if cand is None:
                break
            victim = sched.pick_victim(Scheduler._rank(cand))
            if victim is None:
                break
            self._preempt(victim)
            admissions += sched.admit()
        now_adm = time.perf_counter()
        for adm in admissions:
            observe("fleet/queue_wait_s",
                    now_adm - self.waiting_since.get(adm.req.rid, now_adm),
                    buckets=self._ttft_buckets, replica=rep,
                    slo=slo_mod.resolve_class(adm.req.slo))
            obs_events.request_event(
                obs_events.ADMIT, adm.req.rid, rep, slot=adm.slot,
                prefix="hit" if adm.shared_ids else "miss",
                shared_blocks=len(adm.shared_ids))
        for b in eng._batched(sched.drain_releases()):
            self.cache = eng._release(self.cache, eng._ids_row(b),
                                      jnp.int32(len(b)))
        for adm in admissions:
            hit = len(adm.shared_ids) * s.block_size
            stats["prefix_hit_tokens"] += hit
            stats["prefix_miss_tokens"] += len(adm.req.prompt) - hit
            self.cache = eng._share(
                self.cache, jnp.int32(adm.slot),
                eng._ids_row(adm.shared_ids),
                jnp.int32(len(adm.shared_ids)),
                jnp.int32(adm.n_blocks))
        drafts: Dict[int, List[int]] = {}
        if eng.drafter is not None:
            # draft BEFORE planning so the scheduler charges the
            # actual draft counts against the chunk budget
            want = [(slot, k) for slot, k
                    in sorted(sched.spec_quota().items()) if k > 0]
            if want:
                got = eng.drafter.draft_batch(
                    [(slot,
                      sched.running[slot].req.prompt + gen[slot],
                      k) for slot, k in want])
                drafts = {slot: list(got.get(slot) or [])[:k]
                          for slot, k in want if got.get(slot)}
        work = sorted(
            sched.plan_step({sl: len(d) for sl, d in drafts.items()}
                            if eng.drafter is not None else None),
            key=lambda w: w.slot)
        if eng.drafter is not None and any(w.grow for w in work):
            # pre-stage every page the verify windows touch, so
            # the in-step one-block growth stays a no-op and the
            # step program is byte-identical spec-on vs spec-off
            grow_row = np.zeros((s.max_slots,), np.int32)
            for w in work:
                grow_row[w.slot] = w.grow
            self.cache = eng._grow(self.cache, jnp.asarray(grow_row))
        if work:
            tokens = np.zeros((s.chunk_tokens,), np.int32)
            qs = np.zeros((s.max_slots,), np.int32)
            ql = np.zeros((s.max_slots,), np.int32)
            off = 0
            for w in work:                 # packed runs in slot order
                st = sched.running[w.slot]
                qs[w.slot] = off
                ql[w.slot] = w.n
                if w.kind == "chunk":
                    tokens[off:off + w.n] = st.req.prompt[
                        w.start:w.start + w.n]
                else:
                    # a decode row, or a verify window: the last
                    # generated token followed by the drafts
                    tokens[off] = gen[w.slot][-1]
                    if w.n > 1:
                        tokens[off + 1:off + w.n] = \
                            drafts[w.slot][:w.n - 1]
                off += w.n
            t0 = time.perf_counter()
            # tracer span over the dispatch+wait window — recorded in
            # the ring when APEX_TPU_TRACE=1 AND (through the
            # host_trace_range seam inside trace_span) marked in host
            # profiler traces when profiling is on; the compiled
            # program is untouched either way (HLO pinned)
            with trace_span("serving.unified_step", replica=rep, step=step,
                            tokens=off,
                            decodes=sum(1 for w in work
                                        if w.kind == "decode"),
                            chunks=sum(1 for w in work
                                       if w.kind == "chunk")):
                self.cache, nxt = eng._step(
                    eng.params, self.cache, jnp.asarray(tokens),
                    jnp.asarray(qs), jnp.asarray(ql))
            nxt = jax.device_get(nxt)         # host sync: timing honest
            now = time.perf_counter()
            dt = now - t0
            observe("serving/chunk_utilization", off / s.chunk_tokens,
                    buckets=UTIL_BUCKETS, replica=rep)
            n_dec = sum(1 for w in work if w.kind == "decode")
            if n_dec:
                stats["decode_steps"] += 1
                stats["decode_s"] += dt
            else:
                stats["prefill_s"] += dt
            dec_emitted = 0
            if any(w.kind == "chunk" for w in work):
                stats["chunk_steps"] += 1
                stats["chunk_tokens"] += sum(
                    w.n for w in work if w.kind == "chunk")
            trunc = None
            for w in work:
                st = sched.running[w.slot]
                rid = st.req.rid
                if w.kind == "chunk":
                    obs_events.request_event(
                        obs_events.PREFILL_CHUNK, rid, rep, slot=w.slot,
                        n=w.n, completes=int(w.completes_prompt))
                if w.kind == "decode" and w.n > 1:
                    # speculative verify: greedy longest-prefix
                    # acceptance — row j's output is the model's
                    # next token after [last, d1..dj], so every
                    # emitted token is EXACTLY the greedy
                    # continuation (the bitwise-identity
                    # contract), whatever the drafter proposed
                    nd = w.n - 1
                    d = drafts[w.slot][:nd]
                    base = qs[w.slot]
                    outs = [int(nxt[base + i]) for i in range(w.n)]
                    acc = 0
                    while acc < nd and outs[acc] == d[acc]:
                        acc += 1
                    emitted = outs[:acc + 1]
                    rem = st.req.max_new_tokens - len(gen[w.slot])
                    emitted = emitted[:rem]
                    if s.eos_id is not None and s.eos_id in emitted:
                        emitted = emitted[
                            :emitted.index(s.eos_id) + 1]
                    gen[w.slot].extend(emitted)
                    out[rid]["steps"] = step
                    stats["decode_tokens"] += len(emitted)
                    dec_emitted += len(emitted)
                    stats["spec_drafted_tokens"] += nd
                    stats["spec_accepted_tokens"] += acc
                    inc_counter("serving/spec_drafted_tokens", nd,
                                replica=rep)
                    inc_counter("serving/spec_accepted_tokens", acc,
                                replica=rep)
                    observe("serving/spec_accept_rate", acc / nd,
                            buckets=SPEC_BUCKETS, replica=rep)
                    obs_events.request_event(
                        obs_events.SPEC_VERIFY, rid, rep, slot=w.slot,
                        drafted=nd, accepted=acc,
                        emitted=len(emitted))
                    fin = (len(gen[w.slot])
                           >= st.req.max_new_tokens
                           or emitted[-1] == s.eos_id)
                    new_len = sched.note_spec(w.slot, nd, acc, fin)
                    if fin:
                        self._finish(w.slot)
                    elif acc < nd:
                        # rejected drafts: roll their K/V
                        # positions back and release the
                        # over-allocated suffix pages
                        if trunc is None:
                            trunc = np.full((s.max_slots,),
                                            _I32_MAX, np.int32)
                        trunc[w.slot] = new_len
                elif w.kind == "decode":
                    tok = int(nxt[qs[w.slot]])
                    gen[w.slot].append(tok)
                    out[rid]["steps"] = step
                    stats["decode_tokens"] += 1
                    dec_emitted += 1
                    obs_events.request_event(obs_events.DECODE, rid,
                                             rep, slot=w.slot)
                    if (len(gen[w.slot]) >= st.req.max_new_tokens
                            or tok == s.eos_id):
                        self._finish(w.slot)
                elif w.completes_prompt:
                    tok = int(nxt[qs[w.slot] + w.n - 1])
                    gen[w.slot] = [tok]
                    stats["prefills"] += 1
                    if rid in self._prior:
                        # a RESUMED request (preemption / replica
                        # fault): this placement's first row is just
                        # the next decode token — TTFT belongs to the
                        # placement that emitted the real first token
                        out.setdefault(rid, {})["steps"] = step
                    else:
                        ttft = now - self.waiting_since.get(rid, t0)
                        observe("serving/ttft_s", ttft,
                                buckets=self._ttft_buckets, replica=rep)
                        out[rid] = {"ttft_step": step, "steps": step,
                                    "ttft_s": ttft}
                        obs_events.request_event(
                            obs_events.FIRST_TOKEN, rid, rep,
                            slot=w.slot)
                    self._first_tok.setdefault(rid, now)
                    if st.req.max_new_tokens == 1 or tok == s.eos_id:
                        self._finish(w.slot)
            if trunc is not None:
                self.cache = eng._truncate(self.cache, jnp.asarray(trunc))
            if n_dec:
                # per-token decode latency: the step emitted
                # dec_emitted tokens across n_dec decode slots.
                # Without speculation dec_emitted == n_dec and
                # this is exactly the step latency; a verify
                # window emitting K+1 tokens divides its step
                # cost across them, keeping TPOT honest spec-on
                observe("serving/tpot_s",
                        dt * n_dec / max(dec_emitted, 1),
                        buckets=self._tpot_buckets, replica=rep)
        self.kv_free_min = min(self.kv_free_min, sched.free_blocks)
        set_gauge("serving/kv_blocks_free", sched.free_blocks, replica=rep)
        set_gauge("serving/kv_occupancy",
                  1.0 - (sched.free_blocks
                         + (len(eng.index) if eng.index else 0))
                  / s.pool_blocks, replica=rep)
        set_gauge("serving/active_slots", len(sched.running), replica=rep)
        self.step = step + 1

    # -- close -------------------------------------------------------
    def finalize(self) -> Dict[object, dict]:
        """Close the session: summary stats + gauges, and commit the
        cache back to the engine (the persistence that IS the warm-TTFT
        win). Returns the ``run``-shaped result dict."""
        eng = self.eng
        stats = self.stats
        stats["steps"] = self.step
        stats["trace_counts"] = dict(eng.trace_counts)
        stats["free_blocks"] = self.sched.free_blocks
        stats["index_blocks"] = len(eng.index) if eng.index else 0
        stats["cache"] = self.cache
        eng._cache = self.cache
        # low-watermark + throughput summary gauges for the whole run
        set_gauge("serving/kv_blocks_free_min", self.kv_free_min,
                  replica=eng.replica)
        if stats["decode_s"] > 0:
            set_gauge("serving/decode_steps_per_sec",
                      stats["decode_steps"] / stats["decode_s"],
                      replica=eng.replica)
            set_gauge("serving/decode_tokens_per_sec",
                      stats["decode_tokens"] / stats["decode_s"],
                      replica=eng.replica)
        out = self.out
        out[None] = stats
        return out


# ---------------------------------------------------------------------------
# unpaged reference (tests / parity legs)
# ---------------------------------------------------------------------------

def greedy_reference(params, cfg: TransformerConfig, prompt: List[int],
                     n_new: int, mesh: Optional[Mesh] = None,
                     pad_to: Optional[int] = None) -> List[int]:
    """The oracle loop: re-run the FULL training forward
    (standalone_transformer.transformer_forward — no cache, no paging)
    over the growing context and argmax the last position. O(n^2) in
    compute; exists to pin token-identical greedy parity. The context is
    padded to ``pad_to`` (default cfg.seq_len) so the loop compiles the
    forward ONCE — causality keeps the pad rows out of every valid row."""
    if mesh is None:
        mesh = Mesh(jax.devices()[:1], ("model",))
    pad_to = pad_to or cfg.seq_len
    if len(prompt) + n_new > pad_to:
        raise ValueError(
            f"{len(prompt)} prompt + {n_new} new tokens exceed pad_to="
            f"{pad_to}")
    toks = list(prompt)
    fwd = jax.jit(smap(lambda p, t: transformer_forward(p, t, cfg), mesh,
                       (param_specs(cfg), P()), P()))
    buf = jnp.zeros((1, pad_to), jnp.int32)
    for _ in range(n_new):
        logits = fwd(params,
                     buf.at[0, : len(toks)].set(jnp.asarray(toks,
                                                            jnp.int32)))
        toks.append(int(jnp.argmax(logits[len(toks) - 1, 0])))
    return toks[len(prompt):]
