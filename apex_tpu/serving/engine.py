"""Serving engine — fixed-shape jitted prefill/decode over standalone_gpt.

Two device programs, compiled ONCE each, drive all traffic:

- **prefill**: one request, prompt padded to ``max_prefill_len``. Runs
  the standard training forward (the SAME tensor-parallel layers and
  flash kernels as testing/standalone_transformer.py — arxiv 2605.25645's
  argument for one stack, not a separate serving port), captures each
  layer's K/V, scatters them into the paged cache
  (serving/kv_cache.py), and emits the first greedy token from the last
  prompt position.
- **decode**: ALL slots at once, one token per active slot (padded
  active-slot batch — inactive lanes compute masked garbage), each layer
  appending its K/V at the positions ``alloc_decode_blocks`` reserved
  and attending through the block table with the ragged paged-attention
  kernel (ops/paged_attention.py). Shapes never depend on the request
  mix, so the jit cache sees exactly two signatures over any workload —
  asserted by trace counters (``engine.trace_counts``).

Continuous batching: the host loop (``ServingEngine.run``) interleaves
admission->prefill with decode steps under the scheduler's free-block
watermark (serving/scheduler.py) and evicts finished sequences by
returning their blocks to the pool, so later arrivals join mid-flight.

Tensor parallelism is the training layout re-used verbatim: weights
shard via ``param_specs``, the cache's KV heads ride the model axis
(kv_cache.cache_pspecs), logits stay vocab-parallel and greedy sampling
argmaxes across shards with a pmax/pmin pair — token-identical to the
single-device argmax (first-max-wins tie-break in both).

Env knobs (docs/serving.md): ``APEX_TPU_PAGED_BLOCK_SIZE`` (cache page
size, default 16), ``APEX_TPU_SERVING_MAX_SLOTS`` (decode batch width,
default 8) — defaults for ServingConfig, explicit arguments win.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.ops.attention import flash_attention
from apex_tpu.ops.paged_attention import paged_attention
from apex_tpu.serving import kv_cache as kc
from apex_tpu.serving.scheduler import Request, Scheduler
from apex_tpu.testing.commons import smap
from apex_tpu.testing.standalone_transformer import (
    TransformerConfig,
    _lm_logits,
    _mlp,
    _norm,
    param_specs,
    split_qkv,
    transformer_forward,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    column_parallel_linear,
    row_parallel_linear,
    vocab_parallel_embedding,
)
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
)
from apex_tpu.observability import (
    TIME_BUCKETS,
    default_registry,
    inc_counter,
    metrics_enabled,
    observe,
    set_gauge,
)
from apex_tpu.utils.envvars import env_int
from apex_tpu.utils.profiling import host_trace_range, trace_range


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Engine geometry. ``model`` is the training TransformerConfig the
    checkpoint was built with; serving supports its dense decode subset
    (no SP/CP/MoE/dropout — asserted at engine construction)."""

    model: TransformerConfig
    num_blocks: int = 128
    block_size: Optional[int] = None        # APEX_TPU_PAGED_BLOCK_SIZE | 16
    max_slots: Optional[int] = None         # APEX_TPU_SERVING_MAX_SLOTS | 8
    max_prefill_len: Optional[int] = None   # prompt pad (compile shape)
    max_seq_len: Optional[int] = None       # context cap per sequence
    watermark: Optional[int] = None         # admission reserve (None=slots)
    eos_id: Optional[int] = None            # greedy stop token (None = off)
    dtype: object = None                    # cache dtype (None = model's)

    def __post_init__(self):
        s = object.__setattr__
        if self.block_size is None:
            s(self, "block_size",
              env_int("APEX_TPU_PAGED_BLOCK_SIZE", default=16))
        if self.max_slots is None:
            s(self, "max_slots",
              env_int("APEX_TPU_SERVING_MAX_SLOTS", default=8))
        if self.max_seq_len is None:
            s(self, "max_seq_len", self.model.seq_len)
        if self.max_prefill_len is None:
            s(self, "max_prefill_len", min(self.max_seq_len, 64))
        if self.dtype is None:
            s(self, "dtype", self.model.dtype)

    @property
    def max_blocks_per_seq(self) -> int:
        return int(math.ceil(self.max_seq_len / self.block_size))

    @property
    def n_kv_heads(self) -> int:
        return self.model.kv_heads or self.model.heads


def _vp_greedy(logits, axis: str, tp: int):
    """Greedy token from vocab-parallel logits [..., v/tp]: global max via
    pmax, global argmax as the SMALLEST winning index via pmin — the same
    first-max-wins tie-break as jnp.argmax on the gathered vocab (vocab
    shards are contiguous in rank order)."""
    vloc = logits.shape[-1]
    local_arg = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if tp == 1:
        return local_arg
    local_max = jnp.max(logits, axis=-1)
    gmax = jax.lax.pmax(local_max, axis)
    cand = jnp.where(local_max >= gmax,
                     local_arg + jax.lax.axis_index(axis) * vloc,
                     jnp.int32(2**30))
    return jax.lax.pmin(cand, axis)


def _rope_rows(cfg: TransformerConfig, pos):
    """Per-slot RoPE table rows at positions ``pos`` [S] (fp32)."""
    from apex_tpu.ops.rope import rope_frequencies

    cos, sin = rope_frequencies(cfg.head_dim, cfg.seq_len)
    return cos[pos], sin[pos]


def _rope_at(x, cos_rows, sin_rows):
    """ops/rope._rotate at gathered per-slot positions: x [S, nh, d],
    cos/sin_rows [S, d//2]. Same split-halves rotation, so decode matches
    the prefill/training apply_rope bit for bit."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    c = cos_rows[:, None, :]
    s = sin_rows[:, None, :]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s],
                           axis=-1).astype(x.dtype)


def _check_supported(cfg: TransformerConfig):
    for flag, msg in (
        (cfg.sequence_parallel, "sequence_parallel"),
        (cfg.context_axis is not None, "context parallelism"),
        (cfg.moe_experts > 0, "MoE layers"),
        (cfg.scan_layers, "scan_layers (pass unstacked layer params)"),
        (cfg.dropout_p > 0 or cfg.attn_dropout_p > 0, "dropout"),
        (not cfg.causal, "bidirectional (BERT) models"),
    ):
        if flag:
            raise NotImplementedError(
                f"serving engine does not support {msg}")


# ---------------------------------------------------------------------------
# device programs (shard_map-local bodies)
# ---------------------------------------------------------------------------

def _prefill_body(params, cache, tokens, slot, length, n_blocks, *, cfg,
                  scfg):
    """tokens [1, max_prefill_len] -> (cache', first greedy token).
    The training forward with per-layer K/V capture; pad rows are dropped
    by write_prefill and causality keeps them out of every valid row."""
    ax = cfg.model_axis
    cache = kc.allocate_slot(cache, slot, n_blocks)
    t_pad = tokens.shape[1]
    emb = vocab_parallel_embedding(tokens, params["embedding"], axis=ax)
    if cfg.rope:
        x = emb.astype(cfg.dtype)
    else:
        x = (emb + params["pos_embedding"][None, :t_pad]).astype(cfg.dtype)
    x = x.transpose(1, 0, 2)                           # [s, 1, h]
    if cfg.rope:
        from apex_tpu.ops.rope import apply_rope, rope_frequencies

        rope_tbl = rope_frequencies(cfg.head_dim, cfg.seq_len)
    ks, vs = [], []
    for lp in params["layers"]:
        qkv = column_parallel_linear(
            _norm(x, lp["ln1"], cfg),
            lp["qkv"]["kernel"], lp["qkv"]["bias"], axis=ax,
            gather_output=False)
        q, k, v = split_qkv(qkv, cfg)                  # [s, 1, nh, d]
        if cfg.rope:
            q = apply_rope(q.transpose(1, 0, 2, 3), *rope_tbl).transpose(
                1, 0, 2, 3)
            k = apply_rope(k.transpose(1, 0, 2, 3), *rope_tbl).transpose(
                1, 0, 2, 3)
        ks.append(k[:, 0])                             # [s, n_kv, d]
        vs.append(v[:, 0])
        qh, kh, vh = (t.transpose(1, 2, 0, 3) for t in (q, k, v))
        o = flash_attention(qh, kh, vh, causal=True)
        o = o.transpose(2, 0, 1, 3).reshape(t_pad, 1, -1)
        o = row_parallel_linear(
            o, lp["proj"]["kernel"], lp["proj"]["bias"], axis=ax,
            input_is_parallel=True)
        x = x + o
        x = x + _mlp(lp, _norm(x, lp["ln2"], cfg), cfg, None)
    cache = kc.write_prefill(cache, slot, jnp.stack(ks), jnp.stack(vs),
                             length)
    x = _norm(x, params["final_ln"], cfg)
    xl = jax.lax.dynamic_slice_in_dim(x, length - 1, 1, 0)   # [1, 1, h]
    xl = copy_to_tensor_model_parallel_region(xl, ax)
    logits = _lm_logits(xl, params, cfg)[0, 0]               # [v/tp]
    return cache, _vp_greedy(logits, ax, scfg["tp"])


def _decode_body(params, cache, tokens, active, *, cfg, scfg):
    """tokens [max_slots] (each slot's last token), active [max_slots]
    bool -> (cache', next tokens [max_slots]). One fixed shape forever."""
    ax = cfg.model_axis
    cache, block_ids, offsets = kc.alloc_decode_blocks(cache, active)
    lengths = jnp.where(active, cache.seq_lens, 0)
    pos = jnp.clip(cache.seq_lens - 1, 0, cfg.seq_len - 1)   # [S]
    emb = vocab_parallel_embedding(tokens[:, None], params["embedding"],
                                   axis=ax)[:, 0]            # [S, h]
    if cfg.rope:
        x = emb.astype(cfg.dtype)
        rope_rows = _rope_rows(cfg, pos)
    else:
        x = (emb + params["pos_embedding"][pos]).astype(cfg.dtype)
    x = x[None]                                        # [s=1, b=S, h]
    for li, lp in enumerate(params["layers"]):
        qkv = column_parallel_linear(
            _norm(x, lp["ln1"], cfg),
            lp["qkv"]["kernel"], lp["qkv"]["bias"], axis=ax,
            gather_output=False)
        q, k, v = split_qkv(qkv, cfg)                  # [1, S, nh, d]
        q, k, v = q[0], k[0], v[0]                     # [S, nh(_kv), d]
        if cfg.rope:
            q = _rope_at(q, *rope_rows)
            k = _rope_at(k, *rope_rows)
        cache = kc.append_layer(cache, li, block_ids, offsets, k, v)
        o = paged_attention(q, cache.k_pool[li], cache.v_pool[li],
                            cache.block_tables, lengths)
        o = o.reshape(1, o.shape[0], -1)               # [1, S, nh*d]
        o = row_parallel_linear(
            o, lp["proj"]["kernel"], lp["proj"]["bias"], axis=ax,
            input_is_parallel=True)
        x = x + o
        x = x + _mlp(lp, _norm(x, lp["ln2"], cfg), cfg, None)
    x = _norm(x, params["final_ln"], cfg)
    x = copy_to_tensor_model_parallel_region(x, ax)
    logits = _lm_logits(x, params, cfg)[0]             # [S, v/tp]
    return cache, _vp_greedy(logits, ax, scfg["tp"])


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------

class ServingEngine:
    """Continuous-batching driver. ``mesh`` is a Mesh with a "model" axis
    (size 1 = single chip); weights shard per param_specs, the KV cache
    per kv_cache.cache_pspecs. All loop state other than the cache is
    host-side python."""

    def __init__(self, scfg: ServingConfig, params,
                 mesh: Optional[Mesh] = None):
        cfg = scfg.model
        _check_supported(cfg)
        if mesh is None:
            mesh = Mesh(jax.devices()[:1], ("model",))
        tp = mesh.shape.get("model", 1)
        if scfg.n_kv_heads % tp:
            raise ValueError(
                f"kv heads {scfg.n_kv_heads} not divisible by tp={tp}")
        if scfg.max_seq_len > cfg.seq_len:
            # holds for rope too: the engine's RoPE tables (and the
            # unpaged parity oracle) cover cfg.seq_len positions — serving
            # past them would silently clamp rotations, not extrapolate
            raise ValueError(
                f"max_seq_len {scfg.max_seq_len} exceeds the model's "
                f"position range ({cfg.seq_len})")
        if scfg.max_prefill_len > scfg.max_seq_len:
            raise ValueError("max_prefill_len exceeds max_seq_len")
        self.scfg = scfg
        self.cfg = cfg
        self.mesh = mesh
        self.params = params
        self.trace_counts = {"prefill": 0, "decode": 0}

        pspec = param_specs(cfg)
        cspec = kc.cache_pspecs(tp_axis="model")
        opts = {"cfg": cfg, "scfg": {"tp": tp}}
        counts = self.trace_counts

        def prefill(params, cache, tokens, slot, length, n_blocks):
            counts["prefill"] += 1            # trace-time side effect
            with trace_range("serving.prefill"):
                return _prefill_body(params, cache, tokens, slot, length,
                                     n_blocks, **opts)

        def decode(params, cache, tokens, active):
            counts["decode"] += 1
            with trace_range("serving.decode"):
                return _decode_body(params, cache, tokens, active, **opts)

        self._prefill = jax.jit(
            smap(prefill, mesh,
                 (pspec, cspec, P(), P(), P(), P()), (cspec, P())),
            donate_argnums=(1,))
        self._decode = jax.jit(
            smap(decode, mesh, (pspec, cspec, P(), P()), (cspec, P())),
            donate_argnums=(1,))
        self._free = jax.jit(
            smap(lambda cache, slot: kc.free_slot(cache, slot), mesh,
                 (cspec, P()), cspec),
            donate_argnums=(0,))

    def fresh_cache(self) -> kc.PagedKVCache:
        s = self.scfg
        return kc.paged_kv_cache(
            layers=self.cfg.layers, num_blocks=s.num_blocks,
            block_size=s.block_size, n_kv_heads=s.n_kv_heads,
            head_dim=self.cfg.head_dim, max_slots=s.max_slots,
            max_blocks_per_seq=s.max_blocks_per_seq, dtype=s.dtype)

    # -- the serving loop -------------------------------------------
    def run(self, requests: List[Request], *, max_steps: int = 10_000,
            cache: Optional[kc.PagedKVCache] = None) -> Dict[object, dict]:
        """Serve ``requests`` (arrival-staggered) to completion. Returns
        {rid: {"tokens": [...], "ttft_step": int, "steps": int}} plus
        engine stats under the reserved key ``None``."""
        s = self.scfg
        sched = Scheduler(
            max_slots=s.max_slots, num_blocks=s.num_blocks,
            block_size=s.block_size,
            max_blocks_per_seq=s.max_blocks_per_seq,
            watermark=s.watermark)
        for r in requests:
            # fail fast at intake: a bad request must not surface as an
            # opaque shape error mid-batch, after other requests already
            # prefilled into the donated cache
            if len(r.prompt) > s.max_prefill_len:
                raise ValueError(
                    f"request {r.rid!r}: prompt length {len(r.prompt)} "
                    f"exceeds max_prefill_len {s.max_prefill_len}")
            if len(r.prompt) + r.max_new_tokens > s.max_seq_len:
                raise ValueError(
                    f"request {r.rid!r}: prompt + max_new_tokens = "
                    f"{len(r.prompt) + r.max_new_tokens} exceeds "
                    f"max_seq_len {s.max_seq_len}")
            sched.add(r)
        if cache is None:
            cache = self.fresh_cache()
        gen: Dict[int, List[int]] = {}                 # slot -> tokens
        out: Dict[object, dict] = {}
        stats = {"steps": 0, "prefills": 0, "decode_steps": 0,
                 "decode_tokens": 0, "prefill_s": 0.0, "decode_s": 0.0}
        waiting_since: Dict[object, float] = {}        # rid -> wall ts
        # host-side telemetry (docs/observability.md): everything below
        # records OUTSIDE the jitted programs, so the prefill/decode HLO
        # and the two-compile contract are untouched with metrics on
        kv_free_min = sched.free_blocks
        if metrics_enabled():
            # materialize the event counters at 0 so a quiet run still
            # exports the full serving series set (the scheduler never
            # preempts today; the counter is the dashboard's contract
            # for when it does)
            reg = default_registry()
            for name in ("serving/admissions", "serving/evictions",
                         "serving/preemptions",
                         "serving/admission_blocked"):
                reg.counter(name).inc(0)
            set_gauge("serving/kv_blocks_total", s.num_blocks)
            set_gauge("serving/kv_watermark", sched.watermark)

        def finish(slot):
            nonlocal cache
            st = sched.running[slot]
            out[st.req.rid]["tokens"] = gen.pop(slot)
            cache = self._free(cache, jnp.int32(slot))
            sched.release(slot)

        step = 0
        while sched.has_work() and step < max_steps:
            sched.tick(step)
            for r in list(sched._waiting):
                waiting_since.setdefault(r.rid, time.perf_counter())
            set_gauge("serving/queue_depth", len(sched._waiting))
            for slot, req, need in sched.admit():
                tokens = jnp.zeros((1, s.max_prefill_len), jnp.int32
                                   ).at[0, : len(req.prompt)].set(
                    jnp.asarray(req.prompt, jnp.int32))
                t0 = time.perf_counter()
                # host-side profiler seam: marks the dispatch+wait span
                # in host traces without touching the compiled program
                # (host_trace_range — a named_scope here would rename ops
                # if this call is the one that traces)
                with host_trace_range("serving.prefill_dispatch"):
                    cache, tok = self._prefill(
                        self.params, cache, tokens, jnp.int32(slot),
                        jnp.int32(len(req.prompt)), jnp.int32(need))
                stats["prefills"] += 1
                tok = int(tok)                # host sync: timing honest
                now = time.perf_counter()
                stats["prefill_s"] += now - t0
                gen[slot] = [tok]
                ttft = now - waiting_since.get(req.rid, t0)
                observe("serving/ttft_s", ttft, buckets=TIME_BUCKETS)
                observe("serving/prefill_s", now - t0,
                        buckets=TIME_BUCKETS)
                out[req.rid] = {
                    "ttft_step": step, "steps": step,
                    "ttft_s": ttft,
                }
                if req.max_new_tokens == 1 or tok == s.eos_id:
                    finish(slot)
            if sched.running:
                active = jnp.zeros((s.max_slots,), bool)
                tokens = jnp.zeros((s.max_slots,), jnp.int32)
                for slot in sched.running:
                    active = active.at[slot].set(True)
                    tokens = tokens.at[slot].set(gen[slot][-1])
                sched.grow_for_decode()       # host mirror of the device
                t0 = time.perf_counter()
                with host_trace_range("serving.paged_decode_step"):
                    cache, nxt = self._decode(self.params, cache, tokens,
                                              active)
                stats["decode_steps"] += 1
                stats["decode_tokens"] += len(sched.running)
                nxt = jax.device_get(nxt)     # host sync: timing honest
                dt = time.perf_counter() - t0
                stats["decode_s"] += dt
                # one decode step = one token per active slot, so the
                # step latency IS the per-token latency (TPOT)
                observe("serving/tpot_s", dt, buckets=TIME_BUCKETS)
                for slot in list(sched.running):
                    st = sched.running[slot]
                    tok = int(nxt[slot])
                    gen[slot].append(tok)
                    out[st.req.rid]["steps"] = step
                    if (len(gen[slot]) >= st.req.max_new_tokens
                            or tok == s.eos_id):
                        finish(slot)
            kv_free_min = min(kv_free_min, sched.free_blocks)
            set_gauge("serving/kv_blocks_free", sched.free_blocks)
            set_gauge("serving/kv_occupancy",
                      1.0 - sched.free_blocks / s.num_blocks)
            set_gauge("serving/active_slots", len(sched.running))
            step += 1
        if sched.has_work():
            raise RuntimeError(
                f"serving loop exceeded {max_steps} steps with work left")
        stats["steps"] = step
        stats["trace_counts"] = dict(self.trace_counts)
        stats["cache"] = cache
        # low-watermark + throughput summary gauges for the whole run
        set_gauge("serving/kv_blocks_free_min", kv_free_min)
        if stats["decode_s"] > 0:
            set_gauge("serving/decode_steps_per_sec",
                      stats["decode_steps"] / stats["decode_s"])
            set_gauge("serving/decode_tokens_per_sec",
                      stats["decode_tokens"] / stats["decode_s"])
        out[None] = stats
        return out


# ---------------------------------------------------------------------------
# unpaged reference (tests / parity legs)
# ---------------------------------------------------------------------------

def greedy_reference(params, cfg: TransformerConfig, prompt: List[int],
                     n_new: int, mesh: Optional[Mesh] = None,
                     pad_to: Optional[int] = None) -> List[int]:
    """The oracle loop: re-run the FULL training forward
    (standalone_transformer.transformer_forward — no cache, no paging)
    over the growing context and argmax the last position. O(n^2) in
    compute; exists to pin token-identical greedy parity. The context is
    padded to ``pad_to`` (default cfg.seq_len) so the loop compiles the
    forward ONCE — causality keeps the pad rows out of every valid row."""
    if mesh is None:
        mesh = Mesh(jax.devices()[:1], ("model",))
    pad_to = pad_to or cfg.seq_len
    if len(prompt) + n_new > pad_to:
        raise ValueError(
            f"{len(prompt)} prompt + {n_new} new tokens exceed pad_to="
            f"{pad_to}")
    toks = list(prompt)
    fwd = jax.jit(smap(lambda p, t: transformer_forward(p, t, cfg), mesh,
                       (param_specs(cfg), P()), P()))
    buf = jnp.zeros((1, pad_to), jnp.int32)
    for _ in range(n_new):
        logits = fwd(params,
                     buf.at[0, : len(toks)].set(jnp.asarray(toks,
                                                            jnp.int32)))
        toks.append(int(jnp.argmax(logits[len(toks) - 1, 0])))
    return toks[len(prompt):]
