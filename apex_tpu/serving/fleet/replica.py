"""One fleet replica: an engine, its incremental session, its signals,
and the deterministic fault-injection hook.

A replica owns a full ``ServingEngine`` — its jitted one-compile step,
its paged KV cache, its prefix index (the single-process multi-replica
pattern of the TP2 serving tests: N engines side by side on one host,
each a self-contained serving stack). The Router steps live replicas
round-robin through their ``ServingSession`` and reads
``Replica.signals()`` between steps for placement.

Fault tolerance contract: any exception escaping ``Replica.step`` kills
the replica for the rest of the drive — the Router harvests its
finished results, ``drain``s its unfinished requests as resume pairs
(prompt extended by the tokens already emitted, the emitted prefix
stitched back at finish), requeues them on survivors, and recovers the
engine with ``reset_state()`` (cold cache + index; the compiled step
survives, so a revived replica re-joins the NEXT drive without a
retrace). Greedy decode over the re-prefilled context regenerates
exactly the lost continuation, so a fault-interrupted fleet run's
output is bitwise the no-fault run's.

``FaultPlan`` is the deterministic injection hook the tests, the bench
and the dryrun leg use: replica r's step raises ``InjectedReplicaFault``
the moment its local step counter hits the planned value. The env form
``APEX_TPU_FLEET_FAULT_STEPS="1:3,0:7"`` (replica:step pairs) arms the
same plan from the outside (docs/performance.md).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Tuple

from apex_tpu.serving.engine import ServingEngine, ServingSession
from apex_tpu.serving.scheduler import Request
from apex_tpu.utils.envvars import env_str

__all__ = ["FaultPlan", "InjectedReplicaFault", "Replica",
           "ReplicaSignals"]

_FAULT_ENV = "APEX_TPU_FLEET_FAULT_STEPS"


class InjectedReplicaFault(RuntimeError):
    """The deterministic fault the FaultPlan hook raises — a stand-in
    for a real replica loss (device OOM, preempted VM, link flap)."""


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """replica id -> the LOCAL step index whose execution raises. A
    replica that finishes its work before reaching the step never
    faults — the plan is deterministic given the workload."""

    steps: Mapping[int, int]

    def fires(self, replica: int, local_step: int) -> bool:
        return self.steps.get(replica) == local_step

    @staticmethod
    def from_env() -> Optional["FaultPlan"]:
        """Parse ``APEX_TPU_FLEET_FAULT_STEPS`` ("r:step[,r:step...]")
        — None when unset. Malformed values raise naming the
        variable (the utils/envvars contract)."""
        raw = env_str(_FAULT_ENV)
        if raw is None:
            return None
        steps: Dict[int, int] = {}
        for part in raw.split(","):
            fields = part.split(":")
            try:
                if len(fields) != 2:
                    raise ValueError
                r, s = int(fields[0]), int(fields[1])
                if r < 0 or s < 0:
                    raise ValueError
            except ValueError:
                raise ValueError(
                    f"{_FAULT_ENV}={raw!r} must be comma-separated "
                    f"'replica:step' pairs of non-negative integers "
                    f"(e.g. '1:3,0:7')") from None
            steps[r] = s
        return FaultPlan(steps)


@dataclasses.dataclass(frozen=True)
class ReplicaSignals:
    """One replica's live load snapshot — the router's placement
    inputs, read off the scheduler's host mirror (the same quantities
    the per-step ``serving/*`` gauges export; no device sync)."""

    replica: int
    alive: bool
    queue_depth: int
    running: int
    free_blocks: int
    kv_occupancy: float
    est_work_tokens: int


class Replica:
    """One engine + its current session + its fault/liveness state."""

    def __init__(self, rid: int, engine: ServingEngine):
        self.rid = rid
        self.engine = engine
        self.session: Optional[ServingSession] = None
        self.alive = True
        self.local_step = 0
        self.fault_plan: Optional[FaultPlan] = None

    def begin(self, fault_plan: Optional[FaultPlan] = None) -> None:
        """Open a fresh session for one drive. A replica that died last
        drive re-joins here: its engine was reset_state()-recovered, so
        it cold-starts but does NOT retrace."""
        self.session = self.engine.session()
        self.alive = True
        self.local_step = 0
        self.fault_plan = fault_plan

    def submit(self, req: Request) -> None:
        self.session.add(req)

    def submit_resumed(self, req: Request, prior: List[int]) -> None:
        self.session.add_resumed(req, prior)

    def has_work(self) -> bool:
        return (self.alive and self.session is not None
                and self.session.has_work())

    def step(self) -> None:
        """One session tick; the fault hook fires BEFORE the device
        step, so the planned step's tokens are never emitted — they are
        regenerated bitwise on a survivor."""
        if (self.fault_plan is not None
                and self.fault_plan.fires(self.rid, self.local_step)):
            raise InjectedReplicaFault(
                f"replica {self.rid}: injected fault at local step "
                f"{self.local_step}")
        self.session.step_once()
        self.local_step += 1

    def signals(self) -> ReplicaSignals:
        if self.session is None:
            return ReplicaSignals(replica=self.rid, alive=self.alive,
                                  queue_depth=0, running=0, free_blocks=0,
                                  kv_occupancy=0.0, est_work_tokens=0)
        sig = self.session.signals()
        return ReplicaSignals(
            replica=self.rid, alive=self.alive,
            queue_depth=int(sig["queue_depth"]),
            running=int(sig["running"]),
            free_blocks=int(sig["free_blocks"]),
            kv_occupancy=float(sig["kv_occupancy"]),
            est_work_tokens=int(sig["est_work_tokens"]))

    def fail(self) -> List[Tuple[Request, List[int]]]:
        """Drain + recover after a fault: harvest nothing here (the
        Router copies finished results first), return the unfinished
        resume pairs, reset the engine (donated buffers and index holds
        are unrecoverable mid-run), and mark the replica dead for the
        rest of this drive."""
        items = self.session.drain()
        self.engine.reset_state()
        self.session = None
        self.alive = False
        return items

    def finalize(self) -> Dict[object, dict]:
        out = self.session.finalize()
        self.session = None
        return out
