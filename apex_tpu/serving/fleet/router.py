"""The fleet front end: SLO-aware, load-aware routing over N replicas.

Production TPU serving deployments run many engine replicas behind a
router (the Gemma-on-Cloud-TPU serving reference in PAPERS.md); this is
that layer for apex_tpu, single-process: every replica is a full
``ServingEngine`` (own KV pool, own prefix index, own one-compile jitted
step) and the Router is pure host python that

1. **places** each submitted request on the replica with the least
   estimated work, breaking ties by queue depth, then KV occupancy,
   then replica id (``ReplicaSignals`` — the same KV-occupancy /
   queue-depth quantities the PR-5 gauges export, read off the
   scheduler's host mirror with no device sync);
2. **drives** all live replicas round-robin, one ``ServingSession``
   step each (the fixed-shape jitted steps never retrace —
   ``trace_counts["step"] == 1`` per replica over any fleet workload);
3. **requeues**: preemption inside a replica (an SLO-outranked victim
   evicted for a latency request) is handled by its session; a replica
   FAULT (any exception escaping its step — deterministically
   injectable via ``FaultPlan`` / ``APEX_TPU_FLEET_FAULT_STEPS``) makes
   the Router harvest the dead replica's finished results, drain its
   unfinished requests as resume pairs and re-place them on survivors,
   and recover the engine with ``reset_state()``. Greedy decode over a
   re-prefilled context regenerates exactly the lost continuation, so
   fleet output — with or without faults, cold or prefix-warm — is
   bitwise the single-engine run's per request.

Conservation is enforced, not hoped for: ``drive`` raises if any
submitted rid is missing from (or duplicated in) the merged results.

Metrics (docs/observability.md): every replica's serving series carries
its ``replica`` label; the Router adds ``fleet/requeues`` (labeled by
reason: preemption | fault), ``fleet/slo_violations`` (judged per
finished request against its class targets, serving/fleet/slo.py) and
the ``fleet/queue_wait_s`` histogram (submit → admission, labeled
replica + slo class).

Env knobs: ``APEX_TPU_FLEET_REPLICAS`` (default fleet width, 2),
``APEX_TPU_FLEET_FAULT_STEPS`` (fault plan), plus the SLO knobs in
slo.py — all read at call time via utils/envvars.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from jax.sharding import Mesh

from apex_tpu.observability import (
    default_registry,
    inc_counter,
    metrics_enabled,
)
from apex_tpu.observability import events as obs_events
from apex_tpu.observability import tracing as obs_tracing
from apex_tpu.serving.engine import ServingConfig, ServingEngine
from apex_tpu.serving.fleet import slo
from apex_tpu.serving.fleet.replica import FaultPlan, Replica
from apex_tpu.serving.scheduler import Request
from apex_tpu.utils.envvars import env_int

__all__ = ["Router"]


class Router:
    """N-replica SLO-aware serving front end (single process).

    ``Router(scfg, params)`` builds ``n_replicas`` engines (default
    ``APEX_TPU_FLEET_REPLICAS`` | 2) sharing weights and mesh — each
    still owns its cache/index/jitted programs. ``submit`` places one
    request; ``drive`` serves everything queued; ``serve`` is
    submit-all + drive. Replicas persist across drives (their prefix
    indexes stay warm — the fleet-level warm-TTFT economy), and a
    replica that died in one drive re-joins the next, cold but without
    retracing."""

    def __init__(self, scfg: ServingConfig, params, *,
                 n_replicas: Optional[int] = None,
                 mesh: Optional[Mesh] = None,
                 fault_plan: Optional[FaultPlan] = None):
        n = (env_int("APEX_TPU_FLEET_REPLICAS", default=2)
             if n_replicas is None else n_replicas)
        if n < 1:
            raise ValueError(f"n_replicas {n} must be >= 1")
        self.replicas = [
            Replica(i, ServingEngine(scfg, params, mesh=mesh,
                                     replica=str(i)))
            for i in range(n)
        ]
        # explicit plan wins; None re-consults the env at each _begin
        self._fault_plan = fault_plan
        self._active = False
        self._rids: set = set()
        self._placements: Dict[object, int] = {}
        self._harvested: Dict[object, dict] = {}
        self._requeues = 0
        self._faults: List[dict] = []
        self._postmortems: List[str] = []

    # -- lifecycle ---------------------------------------------------
    def set_fault_plan(self, plan: Optional[FaultPlan]) -> None:
        """Arm (or clear, ``None`` = re-consult the env) the fault plan
        for subsequent drives — the supported way a test/bench swaps
        plans through one compiled fleet."""
        if self._active:
            raise RuntimeError(
                "set_fault_plan mid-drive: arm the plan before submit")
        self._fault_plan = plan

    def _begin(self) -> None:
        plan = (self._fault_plan if self._fault_plan is not None
                else FaultPlan.from_env())
        for rep in self.replicas:
            rep.begin(plan)
        self._active = True
        self._rids = set()
        self._placements = {}
        self._harvested = {}
        self._requeues = 0
        self._faults = []
        self._postmortems = []
        if metrics_enabled():
            # materialize the fleet series at 0 — one series per label
            # combination a drive can emit — so a quiet drive still
            # exports them (the dashboard contract)
            reg = default_registry()
            requeues = reg.counter("fleet/requeues")
            faults = reg.counter("fleet/replica_faults")
            viols = reg.counter("fleet/slo_violations")
            for rep in self.replicas:
                r = str(rep.rid)
                faults.inc(0, replica=r)
                for reason in ("preemption", "fault"):
                    requeues.inc(0, reason=reason, replica=r)
                for cls in (slo.LATENCY, slo.BATCH):
                    for kind in ("ttft", "tpot"):
                        viols.inc(0, slo=cls, kind=kind, replica=r)

    # -- placement ---------------------------------------------------
    def _place(self, req: Request) -> Replica:
        alive = [r for r in self.replicas if r.alive]
        if not alive:
            raise RuntimeError("fleet: no live replicas to place on")

        def score(rep: Replica):
            sig = rep.signals()
            return (sig.est_work_tokens, sig.queue_depth,
                    sig.kv_occupancy, rep.rid)

        return min(alive, key=score)

    def submit(self, request: Request,
               slo_class: Optional[str] = None) -> int:
        """Place ``request`` on the least-loaded live replica and queue
        it there. ``slo_class`` overrides the request's own ``slo``
        field. Returns the chosen replica id. Duplicate rids are
        rejected — conservation (every request emitted exactly once) is
        only checkable over unique ids."""
        if not self._active:
            self._begin()
        if request.rid in self._rids:
            raise ValueError(
                f"fleet: duplicate request id {request.rid!r}")
        if slo_class is not None:
            request = dataclasses.replace(request, slo=slo_class)
        rep = self._place(request)
        rep.submit(request)
        self._rids.add(request.rid)
        self._placements[request.rid] = rep.rid
        return rep.rid

    # -- fault handling ----------------------------------------------
    def _state_summary(self, failing: Optional[Replica] = None) -> dict:
        """Fleet-wide host-mirror snapshot for the flight recorder
        (slots, seq_lens, queue depths, pool occupancy — zero device
        syncs; ServingSession.state_summary). ``failing`` marks the
        replica whose step just raised."""
        out: Dict[str, object] = {"replicas": {}}
        for rep in self.replicas:
            if rep.session is None:
                out["replicas"][str(rep.rid)] = {"alive": rep.alive,
                                                 "session": None}
            else:
                s = rep.session.state_summary()
                s["alive"] = rep.alive
                out["replicas"][str(rep.rid)] = s
        if failing is not None:
            out["failed_replica"] = failing.rid
            out["failed_local_step"] = failing.local_step
        return out

    def _on_fault(self, rep: Replica, err: Exception) -> None:
        fault = {
            "replica": rep.rid, "local_step": rep.local_step,
            "error": f"{type(err).__name__}: {err}"}
        self._faults.append(fault)
        inc_counter("fleet/replica_faults", 1, replica=str(rep.rid))
        obs_tracing.trace_event("fleet.replica_fault",
                                replica=str(rep.rid),
                                step=rep.local_step,
                                error=type(err).__name__)
        # flight-recorder state is captured BEFORE the drain tears the
        # dying session down — this is the crash instant the postmortem
        # preserves
        state = (self._state_summary(failing=rep)
                 if obs_tracing.tracing_enabled() else None)
        # finished results survive the replica: harvest before drain
        for rid, v in rep.session.out.items():
            if rid is not None and "tokens" in v:
                self._harvested[rid] = v
        items = rep.fail()
        if state is not None:
            # dump ring + registry + state summary NOW (the drain/resume
            # events that follow land in the drive-end epilogue) — the
            # drained rids ride the state record so a replay knows which
            # chains must complete on the survivors
            state["drained"] = [str(req.rid) for req, _ in items]
            try:
                path = obs_events.dump_postmortem(
                    reason=f"replica {rep.rid} fault at local step "
                           f"{rep.local_step}: {fault['error']}",
                    state=state)
                fault["postmortem"] = str(path)
                self._postmortems.append(str(path))
            except OSError as e:  # a full disk must not kill recovery
                fault["postmortem_error"] = f"{type(e).__name__}: {e}"
        if not any(r.alive for r in self.replicas):
            raise RuntimeError(
                "fleet: every replica has faulted") from err
        for req, prior in items:
            target = self._place(req)
            target.submit_resumed(req, prior)
            self._placements[req.rid] = target.rid
            self._requeues += 1
            inc_counter("fleet/requeues", 1, reason="fault",
                        replica=str(rep.rid))

    # -- the drive loop ----------------------------------------------
    def drive(self, *, max_steps: int = 10_000) -> Dict[object, dict]:
        """Serve everything submitted since the last drive. Round-robin:
        every live replica with work takes one session step per fleet
        step; a replica that raises is drained onto survivors (see
        ``_on_fault``). Returns the merged ``{rid: result}`` dict with
        fleet stats (per-replica stats, placements, requeues, faults)
        under the reserved key ``None``."""
        if not self._active:
            self._begin()
        steps = 0
        ok = False
        try:
            while any(r.has_work() for r in self.replicas):
                if steps >= max_steps:
                    raise RuntimeError(
                        f"fleet drive exceeded {max_steps} steps with "
                        f"work left")
                for rep in list(self.replicas):
                    if not rep.has_work():
                        continue
                    try:
                        rep.step()
                    except Exception as e:  # noqa: BLE001 — any escape
                        # from a replica's step is a replica loss; the
                        # drain either recovers or re-raises (all dead)
                        self._on_fault(rep, e)
                steps += 1
            ok = True
        finally:
            if not ok:
                # mirror the single-engine economy: a failed drive
                # cold-starts every live replica instead of leaving
                # half-donated caches behind
                for rep in self.replicas:
                    if rep.alive and rep.session is not None:
                        rep.engine.reset_state()
                        rep.session = None
                self._active = False
        results: Dict[object, dict] = dict(self._harvested)
        stats_by_replica: Dict[int, dict] = {}
        for rep in self.replicas:
            if rep.session is None:
                continue
            out = rep.finalize()
            stats_by_replica[rep.rid] = out.pop(None)
            results.update(out)
        self._active = False
        missing = self._rids - set(results)
        extra = set(results) - self._rids
        if missing or extra:
            raise RuntimeError(
                f"fleet conservation violated: missing={sorted(map(str, missing))} "
                f"unexpected={sorted(map(str, extra))}")
        # close the flight-recorder loop: the drive completed, so every
        # crash dump gains an epilogue — the events recorded since the
        # dump (drain -> resume -> ... -> finish on the survivors) plus
        # the recovered state, making the postmortem's per-request
        # chains replayable end to end (tests + the graft trace leg)
        for path in self._postmortems:
            try:
                obs_events.append_epilogue(
                    path, state=self._state_summary())
            except OSError:
                pass
        results[None] = {
            "replicas": stats_by_replica,
            "fleet_steps": steps,
            "requests": len(self._rids),
            "requeues": self._requeues,
            "preemptions": sum(s["preemptions"]
                               for s in stats_by_replica.values()),
            "slo_violations": sum(s["slo_violations"]
                                  for s in stats_by_replica.values()),
            "faults": list(self._faults),
            "postmortems": list(self._postmortems),
            "dead_replicas": [r.rid for r in self.replicas
                              if not r.alive],
            "placements": dict(self._placements),
        }
        return results

    def serve(self, requests: List[Request], *,
              max_steps: int = 10_000) -> Dict[object, dict]:
        """submit() every request in order, then drive() to completion
        — the fleet analog of ``ServingEngine.run``."""
        for r in requests:
            self.submit(r)
        return self.drive(max_steps=max_steps)

    # -- introspection ------------------------------------------------
    def signals(self) -> List[dict]:
        """Per-replica load snapshot (dataclass -> dict) — what an
        operator polls, and what ``_place`` scores."""
        return [dataclasses.asdict(rep.signals())
                for rep in self.replicas]

    def trace_counts(self) -> Dict[int, Dict[str, int]]:
        """Per-replica engine trace counters — the fleet-level
        no-retrace pin (each replica's step compiles exactly once)."""
        return {rep.rid: dict(rep.engine.trace_counts)
                for rep in self.replicas}

    def reset_state(self) -> None:
        """Cold-start every replica (drop caches + prefix indexes)
        without touching the compiled steps — the fleet A/B lever."""
        for rep in self.replicas:
            rep.engine.reset_state()
