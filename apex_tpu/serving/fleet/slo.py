"""SLO classes — the request-priority vocabulary the fleet schedules by.

Production serving traffic is not uniform: an interactive chat turn is
LATENCY-bound (the user is watching the first token render), a batch
evaluation or synthetic-data job is THROUGHPUT-bound (only aggregate
tokens/s matters). The router and the scheduler treat the two
differently at every contention point:

* **step budget** (`Scheduler.plan_step`): latency-class slots are
  planned first in both the decode and the prompt-chunk phase, so under
  a tight ``chunk_tokens`` budget a latency prompt chunk displaces
  batch chunks (and a latency verify window outranks batch windows for
  speculative budget). With a single class the order degrades to the
  old sorted-slot order — SLO-less workloads plan byte-identical steps.
* **admission** (`Scheduler.admit`): the wait queue is FIFO *within* a
  class, but a latency request may be admitted past queued batch
  requests (class-aware head-of-line: the blocked head only blocks its
  own class and below).
* **preemption** (`ServingSession`): a latency request blocked at
  admission (no free slot / watermark) evicts the most recently
  admitted batch-class slot — its blocks return to the pool
  (``serving/preemptions``) and the request is REQUEUED at the front of
  its class with the tokens it already emitted carried as ``prior``, so
  its final greedy output is bitwise the uninterrupted run's.

Classes are ranked: numerically LOWER rank = higher priority. Unknown
class names raise at the first scheduling decision that consults them,
never silently schedule as batch.

Env knobs (docs/performance.md, all read at call time via
utils/envvars): ``APEX_TPU_SERVING_SLO_DEFAULT`` is the class a request
with ``slo=None`` resolves to (default ``batch`` — existing workloads
keep today's FIFO economy); ``APEX_TPU_SLO_LATENCY_TTFT_S`` /
``APEX_TPU_SLO_LATENCY_TPOT_S`` are the latency class's targets, judged
per finished request into the ``fleet/slo_violations`` counter. The
batch class has no targets (a violation-free class by definition).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from apex_tpu.utils.envvars import env_float, env_str

__all__ = [
    "BATCH",
    "LATENCY",
    "SLOTargets",
    "rank_of",
    "resolve_class",
    "slo_buckets",
    "targets_for",
    "violations",
]

LATENCY = "latency"
BATCH = "batch"

# rank 0 outranks rank 1 at every contention point (budget, admission,
# preemption); strictly-greater rank is the preemption-victim criterion
_RANKS = {LATENCY: 0, BATCH: 1}


def rank_of(name: str) -> int:
    """Priority rank of an SLO class name (lower = higher priority).
    Unknown names raise — a typo'd class must fail at the first
    scheduling decision, not silently serve as batch."""
    try:
        return _RANKS[name]
    except KeyError:
        raise ValueError(
            f"unknown SLO class {name!r} (expected one of "
            f"{sorted(_RANKS)})") from None


def resolve_class(name: Optional[str]) -> str:
    """A request's effective class: its own ``slo`` field, else the
    ``APEX_TPU_SERVING_SLO_DEFAULT`` env default (``batch`` when unset —
    SLO-less workloads keep today's pure-FIFO behavior)."""
    if name is None:
        name = env_str("APEX_TPU_SERVING_SLO_DEFAULT", default=BATCH)
    rank_of(name)  # validate
    return name


@dataclasses.dataclass(frozen=True)
class SLOTargets:
    """Per-class latency targets; ``None`` = no target (never violated).
    ``ttft_s`` is judged against the request's arrival→first-token wall
    time, ``tpot_s`` against its mean decode pace (first token →
    finish, per emitted token past the first)."""

    ttft_s: Optional[float] = None
    tpot_s: Optional[float] = None


def targets_for(name: str) -> SLOTargets:
    """The class's targets, env-resolved at call time. Only the latency
    class carries defaults; batch is target-free."""
    if name == LATENCY:
        return SLOTargets(
            ttft_s=env_float("APEX_TPU_SLO_LATENCY_TTFT_S", default=0.5),
            tpot_s=env_float("APEX_TPU_SLO_LATENCY_TPOT_S", default=0.1))
    rank_of(name)  # validate
    return SLOTargets()


# the fractions of a target the SLO-aligned histogram boundaries sit at:
# four buckets under the target (how much headroom), the target itself
# (the violation edge is a bucket EDGE, so violation counts read exactly
# off the cumulative histogram), and five over (how bad the misses are)
_BUCKET_FRACTIONS = (0.125, 0.25, 0.5, 0.75, 1.0, 1.5, 2.0, 4.0, 8.0,
                     16.0)


def slo_buckets(target_s: float) -> tuple:
    """Histogram upper bounds aligned to an SLO target: the target is
    one of the boundaries, with sub-target buckets below and escalating
    miss buckets above — ``serving/ttft_s``, ``serving/tpot_s`` and
    ``fleet/queue_wait_s`` declare these at first use
    (docs/observability.md), so a dashboard reads the violation rate
    straight off ``_bucket{le="<target>"}`` vs ``_count``. Registry
    bucket boundaries freeze at a series' first observation; changing
    the SLO env targets mid-process therefore raises on the next
    observation unless the registry was reset — the documented
    conflicting-redeclare contract."""
    if not target_s or target_s <= 0:
        raise ValueError(f"slo_buckets: target {target_s!r} must be > 0")
    return tuple(round(target_s * f, 9) for f in _BUCKET_FRACTIONS)


def violations(name: str, ttft_s: Optional[float],
               tpot_s: Optional[float]) -> List[str]:
    """Which targets a finished request missed (``["ttft", "tpot"]``
    subset) — the per-kind labels on ``fleet/slo_violations``. ``None``
    measurements (e.g. a fault-resumed request whose first token landed
    on the dead replica) are never judged."""
    t = targets_for(name)
    out: List[str] = []
    if t.ttft_s is not None and ttft_s is not None and ttft_s > t.ttft_s:
        out.append("ttft")
    if t.tpot_s is not None and tpot_s is not None and tpot_s > t.tpot_s:
        out.append("tpot")
    return out
