"""apex_tpu.serving.fleet — the multi-replica serving service layer.

One engine is not a service: heavy traffic hits N ``ServingEngine``
replicas behind a load- and SLO-aware front end. This package is that
front end, pure host python over the replicas' jitted fixed-shape steps
(docs/serving.md "Fleet"):

- ``slo``     — SLO classes (``latency`` vs ``batch``): priority ranks
                consumed by the scheduler's budget split / admission /
                preemption decisions, per-class latency targets judged
                into ``fleet/slo_violations``.
- ``replica`` — one engine + its incremental ``ServingSession`` +
                live load signals (queue depth, free blocks,
                KV occupancy, estimated work), plus the deterministic
                fault-injection hook (``FaultPlan`` /
                ``APEX_TPU_FLEET_FAULT_STEPS``).
- ``router``  — ``Router.submit(request, slo_class)`` load-aware
                placement over the replicas' signals, round-robin
                stepping of every live replica, preemption/requeue
                bookkeeping, and replica fault tolerance: a replica
                that raises mid-run is drained, its in-flight requests
                resume on survivors bitwise-identically (greedy
                decode), and its engine recovers via ``reset_state()``.

``slo`` is imported eagerly (the scheduler consults it); ``router`` /
``replica`` load lazily because they import the engine, which imports
the scheduler, which imports ``slo`` — the lazy hop keeps that chain
acyclic.
"""

from apex_tpu.serving.fleet.slo import (  # noqa: F401
    BATCH,
    LATENCY,
    SLOTargets,
    rank_of,
    resolve_class,
    targets_for,
    violations,
)

__all__ = [
    "BATCH", "FaultPlan", "InjectedReplicaFault", "LATENCY", "Replica",
    "ReplicaSignals", "Router", "SLOTargets", "rank_of", "resolve_class",
    "targets_for", "violations",
]

_LAZY = {
    "FaultPlan": "replica",
    "InjectedReplicaFault": "replica",
    "Replica": "replica",
    "ReplicaSignals": "replica",
    "Router": "router",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(
            f"module 'apex_tpu.serving.fleet' has no attribute {name!r}")
    import importlib

    m = importlib.import_module(f"apex_tpu.serving.fleet.{mod}")
    val = getattr(m, name)
    globals()[name] = val
    return val
