"""Speculative-decoding drafters — propose K tokens, let the unified
step verify them as one ragged run.

Decode is memory-bandwidth-bound at serving batch sizes (the TPU serving
comparison in PAPERS.md): every generated token re-reads the whole
weight set for one row of useful work. Speculative decoding turns that
into one weight-read per ``K + 1`` CANDIDATE tokens: a cheap drafter
proposes K continuations, the target model scores all of them in a
single call to the existing ragged multi-query paged-attention step
(``query_len = K + 1`` — exactly the run shape PR 7's kernel already
serves for prefill chunks), and greedy longest-prefix acceptance keeps
the verified prefix plus one bonus token. Because every emitted token is
the TARGET model's own greedy output at its position, speculative
output is bitwise token-identical to non-speculative greedy decode for
ANY drafter at ANY accept rate — the drafter only moves throughput,
never content (the acceptance contract tests/L0/test_speculative.py
pins).

Three drafters behind one interface:

- ``NgramDrafter`` — host-side self-drafting (prompt lookup): match the
  request's trailing n-gram against its own earlier prompt+generated
  tokens and propose what followed last time. Zero extra device work;
  shines on extractive/repetitive continuations.
- ``DraftModelDrafter`` — a small draft model with its OWN paged pool
  sharing the engine's block machinery (same ``kv_cache`` ops, same
  unified ``_step_body`` program, same mesh): the draft cache lazily
  re-syncs to each slot's accepted context as a ragged chunk, then
  autoregressively proposes K tokens, then rolls its lookahead back
  with ``truncate_slots``. All device work flows through ONE jitted
  draft step plus the grow/truncate/free helpers — one-compile, like
  the engine's own programs.
- ``StubDrafter`` — a forced-acceptance-profile oracle for tests and
  the bench A/B rung: drafts the true greedy continuation for a fixed
  fraction of each window and deliberately-wrong tokens for the rest,
  so throughput can be measured at a synthetic accept rate while the
  bitwise-output contract stays checkable.

Engine protocol (serving/engine.py): ``bind(engine)`` once at
construction; per step ``draft_batch([(slot, context, k), ...])`` with
``context = prompt + generated`` (the accepted stream — rejected drafts
never appear here); ``on_finish(slot)`` when a request retires;
``reset()`` alongside ``ServingEngine.reset_state``.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from apex_tpu.serving import kv_cache as kc

DraftItem = Tuple[int, List[int], int]         # (slot, context, max drafts)


class Drafter:
    """Interface every drafter implements. Drafts are PROPOSALS — the
    engine's verify step decides what survives, so a drafter may return
    fewer tokens than asked (or none) whenever it has no confident
    continuation; over-long returns are truncated by the engine."""

    def bind(self, engine) -> None:
        """One-time attach to the engine (geometry, mesh). Host-only
        drafters ignore it."""

    def draft_batch(self, items: List[DraftItem]) -> Dict[int, List[int]]:
        """Propose up to ``k`` tokens continuing ``context`` for every
        ``(slot, context, k)`` item. Default: loop over ``draft``."""
        return {slot: self.draft(slot, context, k)
                for slot, context, k in items}

    def draft(self, slot: int, context: List[int], k: int) -> List[int]:
        raise NotImplementedError

    def on_finish(self, slot: int) -> None:
        """The request in ``slot`` retired (per-slot state can drop)."""

    def reset(self) -> None:
        """Forget everything (the engine cold-started)."""


# ---------------------------------------------------------------------------
# n-gram self-drafting (prompt lookup)
# ---------------------------------------------------------------------------

class NgramDrafter(Drafter):
    """Prompt-lookup decoding: the continuation most likely to verify is
    the one that followed the SAME trailing n-gram earlier in this very
    request (system prompts quoted back, code identifiers, retrieved
    passages). Tries the longest suffix n-gram first (``max_ngram``
    down to ``min_ngram``), takes the MOST RECENT earlier occurrence,
    and proposes the tokens that followed it.

    Per-slot incremental index: a slot's context is append-only between
    ``on_finish`` calls (the engine feeds the accepted stream), so each
    n-gram length keeps a dict of ``n-gram -> position just after its
    latest occurrence``, extended only over the NEW tail each call —
    drafting is O(new tokens), not a rescan of the whole context on
    every decode step. A context that shrinks or is replaced (off the
    engine's contract, but legal through the public API) drops the
    slot's index and rebuilds."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"[{min_ngram}, {max_ngram}]")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        self._index: Dict[int, Dict[int, dict]] = {}  # slot -> n -> map
        self._seen: Dict[int, int] = {}               # slot -> indexed len
        self._tail: Dict[int, List[int]] = {}         # slot -> last tokens

    def on_finish(self, slot: int) -> None:
        self._index.pop(slot, None)
        self._seen.pop(slot, None)
        self._tail.pop(slot, None)

    def reset(self) -> None:
        self._index.clear()
        self._seen.clear()
        self._tail.clear()

    def _catch_up(self, slot: int, context: List[int]) -> Dict[int, dict]:
        seen = self._seen.get(slot, 0)
        tail = self._tail.get(slot, [])
        if seen > len(context) or context[seen - len(tail):seen] != tail:
            # context shrank or was replaced (off the engine's
            # append-only contract): drop the stale index and rebuild
            self.on_finish(slot)
            seen = 0
        maps = self._index.setdefault(
            slot, {n: {} for n in range(self.min_ngram,
                                        self.max_ngram + 1)})
        for n, m in maps.items():
            # windows ENDING strictly before the tail (i + n < len), so
            # the trailing n-gram never matches its own position; the
            # ones the last call excluded re-index now that the tail
            # moved. Later windows overwrite: the map always holds the
            # most recent occurrence.
            for i in range(max(0, seen - n), len(context) - n):
                m[tuple(context[i:i + n])] = i + n
        self._seen[slot] = len(context)
        self._tail[slot] = list(context[max(0, len(context)
                                            - self.max_ngram):])
        return maps

    def draft(self, slot: int, context: List[int], k: int) -> List[int]:
        maps = self._catch_up(slot, context)
        n_hi = min(self.max_ngram, len(context) - 1)
        for n in range(n_hi, self.min_ngram - 1, -1):
            pos = maps[n].get(tuple(context[-n:]))
            if pos is not None:
                return context[pos:pos + k]
        return []


# ---------------------------------------------------------------------------
# forced-acceptance-profile stub (tests / bench A/B)
# ---------------------------------------------------------------------------

class StubDrafter(Drafter):
    """Oracle drafter with a dialed-in accept rate: given each request's
    TRUE greedy continuation (``targets``: ``(prompt, continuation)``
    pairs — e.g. a spec-off run's outputs), drafts
    ``floor(accept_rate * k)`` correct tokens and deliberately-wrong
    ones for the rest of the window, so a bench rung measures
    tokens-per-step at a FIXED synthetic accept profile while the
    engine's bitwise-output contract stays fully exercised (wrong
    drafts must be rejected, right ones accepted). A context matching
    no target drafts nothing."""

    def __init__(self, targets: Sequence[Tuple[Sequence[int],
                                               Sequence[int]]],
                 accept_rate: float, vocab_size: int):
        if not 0.0 <= accept_rate <= 1.0:
            raise ValueError(f"accept_rate {accept_rate} not in [0, 1]")
        self.targets = [(list(p), list(c)) for p, c in targets]
        self.accept_rate = accept_rate
        self.vocab_size = int(vocab_size)

    def draft(self, slot: int, context: List[int], k: int) -> List[int]:
        for prompt, cont in self.targets:
            full = prompt + cont
            if (len(context) >= len(prompt)
                    and context == full[:len(context)]):
                true = full[len(context):len(context) + k]
                good = int(self.accept_rate * len(true))
                return (true[:good]
                        + [(t + 1) % self.vocab_size for t in true[good:]])
        return []


# ---------------------------------------------------------------------------
# draft-model path (its own paged pool, the engine's block machinery)
# ---------------------------------------------------------------------------

class DraftModelDrafter(Drafter):
    """A small target-architecture model drafts autoregressively against
    its OWN block-paged KV pool. Device work reuses the engine's exact
    machinery: the same ``_step_body`` (ragged multi-query attention
    over a ``PagedKVCache``) jitted ONCE on the engine's mesh, plus the
    grow / truncate / free cache helpers. Per ``draft_batch`` call the
    runner (1) pre-grows each slot's table to cover context + lookahead,
    (2) catches the draft cache up to the accepted context as ragged
    chunk runs (the last context row's greedy output IS the first
    draft), (3) runs ``k - 1`` single-token decode rounds for the rest,
    and (4) rolls the lookahead back with ``truncate_slots`` so the
    cache ends every call holding exactly the accepted context — the
    invariant that makes re-sync after the engine's own rollback free.

    The draft model must cover the engine's position range plus the
    draft window (``seq_len >= max_seq_len + spec_k``) and its KV heads
    must divide the mesh's model axis, checked at ``bind``."""

    def __init__(self, model_cfg, params, num_blocks: Optional[int] = None):
        self.cfg = model_cfg
        self.params = params
        self._num_blocks = num_blocks
        self._engine = None
        self.trace_counts: Dict[str, int] = {
            "draft_step": 0, "draft_grow": 0, "draft_truncate": 0,
            "draft_free": 0}

    # -- engine attach ----------------------------------------------
    def bind(self, engine) -> None:
        from apex_tpu.serving.engine import (
            _check_supported, _step_body, counted_cache_op)
        from apex_tpu.testing.commons import smap
        from apex_tpu.testing.standalone_transformer import param_specs

        cfg = self.cfg
        _check_supported(cfg)
        scfg = engine.scfg
        mesh = engine.mesh
        tp = mesh.shape.get("model", 1)
        n_kv = cfg.kv_heads or cfg.heads
        if n_kv % tp:
            raise ValueError(
                f"draft model kv heads {n_kv} not divisible by tp={tp}")
        if scfg.max_seq_len + scfg.spec_k > cfg.seq_len:
            raise ValueError(
                f"draft model position range ({cfg.seq_len}) cannot cover "
                f"max_seq_len {scfg.max_seq_len} + spec_k {scfg.spec_k} "
                f"of lookahead")
        self._engine = engine
        self._bs = scfg.block_size
        self._width = scfg.chunk_tokens
        self._max_slots = scfg.max_slots
        self._mbps = kc.blocks_needed(
            scfg.max_seq_len + scfg.spec_k, self._bs)
        self._pool = (self._num_blocks if self._num_blocks is not None
                      else scfg.num_blocks)
        self._layers = cfg.layers
        self._kv_heads = n_kv
        self._head_dim = cfg.head_dim
        self._dtype = cfg.dtype

        cspec = kc.cache_pspecs(tp_axis="model")
        counts = self.trace_counts
        opts = {"cfg": cfg, "scfg": {"tp": tp}}

        def step(params, cache, tokens, qs, ql):
            counts["draft_step"] += 1          # trace-time side effect
            return _step_body(params, cache, tokens, qs, ql, **opts)

        pspec = param_specs(cfg)
        self._step = jax.jit(
            smap(step, mesh, (pspec, cspec, P(), P(), P()), (cspec, P())),
            donate_argnums=(1,))
        self._grow = counted_cache_op(
            counts, "draft_grow",
            functools.partial(kc.grow_slots, max_grow=self._mbps),
            mesh, cspec, 1)
        self._truncate = counted_cache_op(
            counts, "draft_truncate", kc.truncate_slots, mesh, cspec, 1)
        self._free = counted_cache_op(
            counts, "draft_free", kc.free_slot, mesh, cspec, 1)
        self.reset()

    def _fresh_cache(self) -> kc.PagedKVCache:
        return kc.paged_kv_cache(
            layers=self._layers, num_blocks=self._pool,
            block_size=self._bs, n_kv_heads=self._kv_heads,
            head_dim=self._head_dim, max_slots=self._max_slots,
            max_blocks_per_seq=self._mbps, dtype=self._dtype)

    # -- host state --------------------------------------------------
    def reset(self) -> None:
        if self._engine is None:
            return
        self._cache = self._fresh_cache()
        self._synced: Dict[int, int] = {}      # slot -> resident tokens
        self._blocks: Dict[int, int] = {}      # slot -> table entries
        self._free_blocks = self._pool

    def on_finish(self, slot: int) -> None:
        if self._engine is None or slot not in self._synced:
            return
        self._cache = self._free(self._cache, jnp.int32(slot))
        self._free_blocks += self._blocks.pop(slot, 0)
        self._synced.pop(slot, None)

    # -- the drafting loop -------------------------------------------
    def _run(self, tokens: np.ndarray, qs: np.ndarray,
             ql: np.ndarray) -> np.ndarray:
        self._cache, nxt = self._step(
            self.params, self._cache, jnp.asarray(tokens),
            jnp.asarray(qs), jnp.asarray(ql))
        return jax.device_get(nxt)

    def draft_batch(self, items: List[DraftItem]) -> Dict[int, List[int]]:
        if self._engine is None:
            raise RuntimeError("DraftModelDrafter.bind was never called")
        items = [(slot, list(ctx), k) for slot, ctx, k in items if k > 0]
        if not items:
            return {}
        for slot, ctx, _k in items:
            if self._synced.get(slot, 0) >= len(ctx):
                raise RuntimeError(
                    f"slot {slot}: draft context did not advance past the "
                    f"synced length ({len(ctx)}) — the engine feeds the "
                    f"accepted stream, which grows every verify step")
        # 1. pre-grow every slot's table over context + lookahead (the
        #    catch-up chunk may cross many page boundaries; in-step
        #    growth then stays a no-op, as in the engine)
        grow_row = np.zeros((self._max_slots,), np.int32)
        total = 0
        budget = self._free_blocks
        kept: List[DraftItem] = []
        for slot, ctx, k in items:
            # the runner writes AT MOST len(ctx) + k - 1 positions (the
            # catch-up chunk plus k-1 draft rounds — the k-th draft is
            # returned, never appended), so grow for exactly that:
            # growing for an unwritten position would leave a page the
            # step-4 truncate cannot see (it derives the kept count from
            # seq_lens, which never covers the phantom position) and
            # desync the host mirror from the device refcounts.
            # A full draft pool DEGRADES speculation (shallower windows,
            # then no drafts for the slot) — drafts are proposals, so
            # running out of draft pages must never crash serving; the
            # engine pool prefix-shares and this one cannot, so it can
            # legitimately run out first
            have = self._blocks.get(slot, 0)
            while k >= 1:
                g = max(0, kc.blocks_needed(len(ctx) + k - 1, self._bs)
                        - have)
                if g <= budget:
                    break
                k -= 1
            if k < 1:
                continue           # not even the context fits: sit out
            g = max(0, kc.blocks_needed(len(ctx) + k - 1, self._bs) - have)
            budget -= g
            grow_row[slot] = g
            total += g
            kept.append((slot, ctx, k))
        items = kept
        if not items:
            return {}
        for slot, _ctx, _k in items:
            self._blocks[slot] = (self._blocks.get(slot, 0)
                                  + int(grow_row[slot]))
            self._synced.setdefault(slot, 0)
        if total:
            self._free_blocks -= total
            self._cache = self._grow(self._cache, jnp.asarray(grow_row))

        # 2. catch up to the accepted context (ragged chunks under the
        #    fixed width); a slot's LAST context row emits draft 1
        drafts: Dict[int, List[int]] = {slot: [] for slot, _, _ in items}
        pending = {slot: self._synced[slot] for slot, _, _ in items}
        while True:
            tokens = np.zeros((self._width,), np.int32)
            qs = np.zeros((self._max_slots,), np.int32)
            ql = np.zeros((self._max_slots,), np.int32)
            off = 0
            tail: List[Tuple[int, int]] = []   # (slot, its last-row index)
            for slot, ctx, _k in items:
                done = pending[slot]
                rem = len(ctx) - done
                if rem <= 0 or off >= self._width:
                    continue
                n = min(rem, self._width - off)
                tokens[off:off + n] = ctx[done:done + n]
                qs[slot] = off
                ql[slot] = n
                pending[slot] = done + n
                if done + n == len(ctx):
                    tail.append((slot, off + n - 1))
                off += n
            if off == 0:
                break
            nxt = self._run(tokens, qs, ql)
            for slot, row in tail:
                drafts[slot].append(int(nxt[row]))

        # 3. k-1 autoregressive rounds, all drafting slots packed ql=1
        rounds = max(k for _, _, k in items)
        for r in range(1, rounds):
            tokens = np.zeros((self._width,), np.int32)
            qs = np.zeros((self._max_slots,), np.int32)
            ql = np.zeros((self._max_slots,), np.int32)
            off = 0
            live = [slot for slot, _ctx, k in items
                    if k > r and len(drafts[slot]) == r]
            if not live:
                break
            for slot in live:
                tokens[off] = drafts[slot][-1]
                qs[slot] = off
                ql[slot] = 1
                off += 1
            nxt = self._run(tokens, qs, ql)
            for i, slot in enumerate(live):
                drafts[slot].append(int(nxt[qs[slot]]))

        # 4. roll the lookahead back: the cache ends the call holding
        #    exactly the accepted context (drafted rows' K/V dropped,
        #    over-grown pages released) — rejected drafts then cost the
        #    draft cache nothing next call
        trunc = np.full((self._max_slots,), 2**31 - 1, np.int32)
        for slot, ctx, _k in items:
            trunc[slot] = len(ctx)
            kept = kc.blocks_needed(len(ctx), self._bs)
            self._free_blocks += self._blocks[slot] - kept
            self._blocks[slot] = kept
            self._synced[slot] = len(ctx)
        self._cache = self._truncate(self._cache, jnp.asarray(trunc))
        return {slot: drafts[slot][:k] for slot, _ctx, k in items}
