"""Continuous-batching scheduler — host-side block/slot accounting.

The split of responsibilities mirrors production TPU serving stacks: the
DEVICE side (engine.py) is two fixed-shape jitted programs — prefill and
decode — that never recompile; the HOST side (this module) decides *what*
those programs run on each step: which waiting request is admitted into
which slot, and when a finished sequence's blocks return to the pool.

State machine per request::

    WAITING --admit--> RUNNING --(eos | max_new_tokens)--> FINISHED
      ^ arrival gate (requests carry an arrival step; continuous
        batching means later arrivals join mid-flight decodes)

Admission policy (free-block watermark): a request is admitted only when
a slot is free AND the pool would retain >= ``watermark`` free blocks
after its prompt allocation. The watermark reserves decode headroom for
the sequences already running — every active sequence needs at most one
new block per ``block_size`` decode steps, so ``watermark = max_slots``
(the default) guarantees a full round of block growth before the next
admission can be reconsidered; sizing the pool for the worst case
(``sum(ceil(max_ctx/bs))``) makes growth unconditionally safe.

The scheduler's counters are an exact host mirror of the device cache's
accounting (it sees every admit/grow/release), so steady-state decode
needs no device round-trip to make admission decisions. The engine
cross-checks the mirror against ``kv_cache.free_block_count`` in tests.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from apex_tpu.observability import inc_counter
from apex_tpu.serving.kv_cache import blocks_needed

WAITING = "WAITING"
RUNNING = "RUNNING"
FINISHED = "FINISHED"


@dataclasses.dataclass
class Request:
    """One generation request. ``arrival`` is the engine step index at
    which the request becomes visible (staggered-arrival workloads)."""

    rid: object
    prompt: List[int]
    max_new_tokens: int = 16
    arrival: int = 0

    def __post_init__(self):
        if not self.prompt:
            raise ValueError(f"request {self.rid!r}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"request {self.rid!r}: max_new_tokens must be >= 1")


@dataclasses.dataclass
class _Running:
    req: Request
    slot: int
    n_blocks: int          # blocks currently assigned to the slot
    tokens_in_cache: int   # prompt + generated tokens written so far


class Scheduler:
    """Slot/block bookkeeping + admission. Pure host state."""

    def __init__(self, *, max_slots: int, num_blocks: int, block_size: int,
                 max_blocks_per_seq: int,
                 watermark: Optional[int] = None):
        self.max_slots = max_slots
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.free_blocks = num_blocks
        self.watermark = max_slots if watermark is None else watermark
        self._future: List[Request] = []
        self._waiting: Deque[Request] = deque()
        self.running: Dict[int, _Running] = {}     # slot -> state
        self._free_slots = sorted(range(max_slots))

    # -- intake ------------------------------------------------------
    def add(self, req: Request) -> None:
        # capacity check covers the WHOLE lifetime (prompt + decode
        # budget), so grow_for_decode can never push a sequence past
        # max_blocks_per_seq — without this, decode past the last page
        # would silently overwrite live K/V on device while the host
        # mirror debits blocks the device never allocated
        need = blocks_needed(len(req.prompt) + req.max_new_tokens,
                             self.block_size)
        if need > self.max_blocks_per_seq:
            raise ValueError(
                f"request {req.rid!r}: {len(req.prompt)} prompt + "
                f"{req.max_new_tokens} new tokens need {need} blocks > "
                f"max_blocks_per_seq {self.max_blocks_per_seq} "
                f"(raise max_seq_len or split the request)")
        self._future.append(req)
        self._future.sort(key=lambda r: r.arrival)

    def tick(self, step: int) -> None:
        """Move requests whose arrival step has come into the wait queue."""
        while self._future and self._future[0].arrival <= step:
            self._waiting.append(self._future.pop(0))

    def has_work(self) -> bool:
        return bool(self._future or self._waiting or self.running)

    # -- admission ---------------------------------------------------
    def admit(self) -> List[Tuple[int, Request, int]]:
        """Admit FIFO from the wait queue while a slot is free and the
        pool keeps ``watermark`` blocks after each prompt allocation.
        Returns [(slot, request, prompt_blocks)]; the caller runs the
        prefills and reports the first decode tokens via started()."""
        admitted = []
        while self._waiting and self._free_slots:
            req = self._waiting[0]
            need = blocks_needed(len(req.prompt), self.block_size)
            if self.free_blocks - need < self.watermark:
                # the head-of-line request deferred by the watermark: the
                # KV-pressure signal an operator sizes the pool by
                inc_counter("serving/admission_blocked", 1)
                break                         # FIFO: no skip-ahead
            self._waiting.popleft()
            slot = self._free_slots.pop(0)
            self.free_blocks -= need
            self.running[slot] = _Running(
                req=req, slot=slot, n_blocks=need,
                tokens_in_cache=len(req.prompt))
            inc_counter("serving/admissions", 1)
            admitted.append((slot, req, need))
        return admitted

    # -- decode-step accounting -------------------------------------
    def grow_for_decode(self) -> int:
        """Account one token appended to every running slot (the engine's
        decode step does exactly that): slots whose new position opens a
        fresh page take a block from the pool. Returns the number of
        blocks taken; raises if the pool underflows — that is a watermark
        sizing bug, and corrupting block 0 on device would be worse."""
        grown = 0
        for st in self.running.values():
            pos = st.tokens_in_cache
            if pos // self.block_size >= st.n_blocks:
                st.n_blocks += 1
                grown += 1
            st.tokens_in_cache = pos + 1
        self.free_blocks -= grown
        if self.free_blocks < 0:
            raise RuntimeError(
                f"paged pool underflow: decode growth took {grown} blocks "
                f"with only {self.free_blocks + grown} free — the "
                f"admission watermark ({self.watermark}) is undersized "
                f"for this workload")
        return grown

    def release(self, slot: int) -> None:
        """Finished sequence: return its blocks, free its slot."""
        st = self.running.pop(slot)
        self.free_blocks += st.n_blocks
        self._free_slots.append(slot)
        self._free_slots.sort()
        inc_counter("serving/evictions", 1)
