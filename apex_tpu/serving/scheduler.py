"""Continuous-batching scheduler — host-side block/slot/chunk accounting.

The split of responsibilities mirrors production TPU serving stacks: the
DEVICE side (engine.py) is ONE fixed-shape jitted step that never
recompiles; the HOST side (this module) decides *what* that step runs on
each tick: which waiting request is admitted into which slot (and how
much of its prompt is already resident — the prefix cache), how this
step's fixed token budget (``chunk_tokens``) splits between decode steps
and prefill chunks, and when a finished sequence's blocks return to the
pool or are handed to the prefix index.

State machine per request::

    WAITING --admit--> RUNNING (chunk prefill -> decode)
                         --(eos | max_new_tokens)--> FINISHED
      ^ arrival gate (requests carry an arrival step; continuous
        batching means later arrivals join mid-flight decodes)

**Chunked prefill** (``plan_step``): every step carries at most
``chunk_tokens`` query tokens through the unified program. Decode steps
come first (one token per decode-ready slot — latency critical), then
prompt chunks FIFO in slot order fill the remaining budget, so a long
prompt is split across steps and never stalls running decodes behind a
monolithic prefill.

**Prefix-aware admission**: a request's prompt is matched against the
PrefixIndex (kv_cache.py) full block by full block; matched blocks are
SHARED (device refcount += 1 via share_prefix), and only the suffix
blocks are charged against the free-block watermark — a shared block is
already resident and is never double-counted against
``free_blocks``. At least one prompt token is always left to recompute:
its logits emit the first generated token. Under pool pressure the
scheduler evicts least-recently-matched index entries (their device
refcount release is drained by the engine via ``drain_releases``)
before blocking admission.

**Speculative decoding** (``spec_k > 0``): a decode-ready slot's step
item becomes a verify window of ``1 + K`` tokens (``spec_quota`` asks
the drafter, ``plan_step(spec_drafts=...)`` charges the drafts against
the SAME ``chunk_tokens`` budget — decodes first, chunks in what
remains; while prompt chunks are pending, speculation may take at most
HALF the leftover budget so prefill always progresses), and
``note_spec`` adapts each slot's depth to its observed accept rate
while reconciling the host mirror with the engine's device-side
rollback (``kv_cache.truncate_slots``).

**SLO classes** (serving/fleet/slo.py): every request carries an SLO
class (``latency`` outranks ``batch``; ``slo=None`` resolves via
``APEX_TPU_SERVING_SLO_DEFAULT``, default batch). The class shapes
three decisions: ``plan_step`` orders both its decode and its chunk
phase latency-class slots first (so under a tight budget a
latency-bound request's chunks displace throughput-bound ones — with a
single class this is exactly the old sorted-slot order), ``admit`` is
FIFO within a class but lets a latency request pass queued batch
requests (the blocked head only blocks its own class and below), and
the session's preemption path uses ``peek_next``/``pick_victim``/
``preempt``/``requeue``: a latency request blocked at admission evicts
the most recently admitted strictly-lower-class slot, returning its
blocks to the pool (the ``serving/preemptions`` counter — armed here)
and requeueing the victim at the front of its class section.

Admission policy (free-block watermark): a request is admitted only when
a slot is free AND the pool would retain >= ``watermark`` free blocks
after its suffix allocation. The watermark reserves decode headroom for
the sequences already running — every active sequence needs at most one
new block per ``block_size`` decode steps, so ``watermark = max_slots``
(the default) guarantees a full round of block growth before the next
admission can be reconsidered.

The scheduler's counters are an exact host mirror of the device cache's
refcount accounting (it sees every admit/share/grow/release/evict), so
steady-state serving needs no device round-trip to make admission
decisions. The engine cross-checks the mirror against
``kv_cache.free_block_count`` in tests.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Deque, Dict, Iterable, List, Optional

from apex_tpu.observability import inc_counter
from apex_tpu.observability import events as obs_events
from apex_tpu.serving.fleet import slo as slo_mod
from apex_tpu.serving.kv_cache import PrefixIndex, blocks_needed

WAITING = "WAITING"
RUNNING = "RUNNING"
FINISHED = "FINISHED"


@dataclasses.dataclass
class Request:
    """One generation request. ``arrival`` is the engine step index at
    which the request becomes visible (staggered-arrival workloads).
    ``slo`` is the request's SLO class (serving/fleet/slo.py:
    ``"latency"`` outranks ``"batch"``; ``None`` resolves through
    ``APEX_TPU_SERVING_SLO_DEFAULT`` at scheduling time)."""

    rid: object
    prompt: List[int]
    max_new_tokens: int = 16
    arrival: int = 0
    slo: Optional[str] = None

    def __post_init__(self):
        if not self.prompt:
            raise ValueError(f"request {self.rid!r}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"request {self.rid!r}: max_new_tokens must be >= 1")
        if self.slo is not None:
            slo_mod.rank_of(self.slo)       # typo'd class: fail at intake


@dataclasses.dataclass
class _Running:
    req: Request
    slot: int
    n_blocks: int          # blocks currently assigned to the slot
    tokens_in_cache: int   # prefix + chunk + decode tokens written so far
    prefilled: int         # prompt tokens resident (prefix hit + chunks)
    shared_ids: List[int]  # prefix blocks borrowed from the index
    spec_depth: int = 0    # current adaptive draft depth (speculation on)
    slo_rank: int = 1      # resolved class rank at admission (0 = latency)
    admit_seq: int = 0     # admission order — the preemption-victim key


@dataclasses.dataclass
class Admission:
    """One admitted request, ready for the engine's share_prefix call:
    point ``slot``'s table at ``shared_ids`` (the prefix-cache hit, may
    be empty) and allocate ``n_blocks - len(shared_ids)`` fresh suffix
    blocks."""

    slot: int
    req: Request
    shared_ids: List[int]
    n_blocks: int

    @property
    def prefix_tokens(self) -> int:
        return len(self.shared_ids)  # caller scales by block_size


@dataclasses.dataclass
class Work:
    """One slot's share of a step's token budget: a prompt chunk
    (``kind == "chunk"``, prompt[start : start+n]) or a decode step
    (``kind == "decode"``; n == 1 plain, n == 1 + K a speculative verify
    window of the slot's last generated token plus K drafts).
    ``completes_prompt`` marks the chunk whose last-row logits emit the
    request's FIRST generated token."""

    slot: int
    kind: str
    start: int
    n: int
    completes_prompt: bool = False
    # speculative verify runs only: blocks the engine's grow helper must
    # pre-stage before the step (a K+1-token window may cross more page
    # boundaries than the in-step one-block growth covers)
    grow: int = 0


class Scheduler:
    """Slot/block/chunk bookkeeping + admission. Pure host state."""

    def __init__(self, *, max_slots: int, num_blocks: int, block_size: int,
                 max_blocks_per_seq: int,
                 watermark: Optional[int] = None,
                 chunk_tokens: Optional[int] = None,
                 prefix_index: Optional[PrefixIndex] = None,
                 spec_k: int = 0,
                 replica: str = "0"):
        self.max_slots = max_slots
        # which fleet replica this scheduler serves — the label on every
        # counter it emits ("0" outside a fleet, docs/observability.md)
        self.replica = str(replica)
        # speculative decoding: spec_k is the MAX draft depth per slot
        # (0 = off); each running slot adapts its own depth within
        # [1, spec_k] to the accept rates note_spec observes
        self.spec_k = int(spec_k)
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.free_blocks = num_blocks
        self.watermark = max_slots if watermark is None else watermark
        self.chunk_tokens = (max(1, max_slots) if chunk_tokens is None
                             else chunk_tokens)
        if self.chunk_tokens < max_slots:
            raise ValueError(
                f"chunk_tokens {self.chunk_tokens} < max_slots "
                f"{max_slots}: a full decode round must fit one step")
        self.index = prefix_index
        self._future: List[Request] = []
        self._waiting: Deque[Request] = deque()
        self.running: Dict[int, _Running] = {}     # slot -> state
        self._free_slots = sorted(range(max_slots))
        # host mirror of index-held blocks currently shared by slots
        self._shared_in_use: Dict[int, int] = {}
        # index evictions awaiting their device refcount release
        self._pending_releases: List[int] = []
        self._admit_seq = 0    # admission order, the preemption-victim key

    # -- intake ------------------------------------------------------
    def add(self, req: Request) -> None:
        # capacity check covers the WHOLE lifetime (prompt + decode
        # budget), so decode growth can never push a sequence past
        # max_blocks_per_seq — without this, decode past the last page
        # would silently overwrite live K/V on device while the host
        # mirror debits blocks the device never allocated
        need = blocks_needed(len(req.prompt) + req.max_new_tokens,
                             self.block_size)
        if need > self.max_blocks_per_seq:
            raise ValueError(
                f"request {req.rid!r}: {len(req.prompt)} prompt + "
                f"{req.max_new_tokens} new tokens need {need} blocks > "
                f"max_blocks_per_seq {self.max_blocks_per_seq} "
                f"(raise max_seq_len or split the request)")
        self._future.append(req)
        self._future.sort(key=lambda r: r.arrival)

    def tick(self, step: int) -> None:
        """Move requests whose arrival step has come into the wait
        queue (each move is the ``request.queue`` lifecycle event —
        docs/serving.md's table; one flag check when tracing is off)."""
        while self._future and self._future[0].arrival <= step:
            req = self._future.pop(0)
            self._waiting.append(req)
            obs_events.request_event(obs_events.QUEUE, req.rid,
                                     self.replica, step=step)

    def has_work(self) -> bool:
        return bool(self._future or self._waiting or self.running)

    # -- SLO classes / fleet signals ---------------------------------
    @staticmethod
    def _rank(req: Request) -> int:
        """The request's resolved class rank (env default applied at
        CALL time — serving/fleet/slo.py)."""
        return slo_mod.rank_of(slo_mod.resolve_class(req.slo))

    def _next_index(self) -> Optional[int]:
        """Index into the wait queue of the next admission candidate:
        the FIRST request of the best (lowest-rank) class present —
        FIFO within a class, class-aware head-of-line across classes (a
        blocked latency head blocks everything; a blocked batch head
        never blocks a queued latency request)."""
        best_rank, best_i = None, None
        for i, r in enumerate(self._waiting):
            rk = self._rank(r)
            if best_rank is None or rk < best_rank:
                best_rank, best_i = rk, i
                if rk == 0:
                    break
        return best_i

    def peek_next(self) -> Optional[Request]:
        """The request ``admit`` would try next (None when the queue is
        empty) — the session's preemption check reads this."""
        i = self._next_index()
        return None if i is None else self._waiting[i]

    def queue_depth(self) -> int:
        """Waiting + not-yet-arrived requests — a router signal."""
        return len(self._waiting) + len(self._future)

    def pending_work_tokens(self) -> int:
        """Estimated tokens of work still owed: un-prefilled prompt
        tokens plus un-emitted decode budget across queued AND running
        requests — the router's estimated-work placement signal (a
        heuristic: eos may end a request early)."""
        total = sum(len(r.prompt) + r.max_new_tokens
                    for r in self._future)
        total += sum(len(r.prompt) + r.max_new_tokens
                     for r in self._waiting)
        for st in self.running.values():
            emitted = max(0, st.tokens_in_cache - len(st.req.prompt))
            total += max(0, len(st.req.prompt) - st.prefilled)
            total += max(0, st.req.max_new_tokens - emitted)
        return total

    # -- admission ---------------------------------------------------
    def _make_room(self, fresh: int, protect: set) -> None:
        """Evict least-recently-matched prefix-index entries until the
        watermark would pass (or the index runs dry). Evicting an entry
        drops the index's device refcount (drained by the engine); the
        block only becomes FREE if no running slot still shares it."""
        while (self.index is not None and len(self.index)
               and self.free_blocks - fresh < self.watermark):
            ids = self.index.evict(1, protect=protect)
            if not ids:
                break
            for b in ids:
                self._pending_releases.append(b)
                if self._shared_in_use.get(b, 0) == 0:
                    self.free_blocks += 1

    def drain_releases(self) -> List[int]:
        """Block ids whose index refcount release is due on device."""
        out, self._pending_releases = self._pending_releases, []
        return out

    def admit(self) -> List[Admission]:
        """Admit from the wait queue — class-aware FIFO (``_next_index``:
        FIFO within a class, a latency request passes queued batch
        requests) — while a slot is free and the pool keeps
        ``watermark`` blocks after each request's FRESH (non-shared)
        allocation. Prefix-matched blocks are borrowed from the index
        (refcount-aware: already resident, charged zero), so admission
        is not spuriously blocked when most resident blocks are shared
        prefixes."""
        admitted: List[Admission] = []
        while self._waiting and self._free_slots:
            i = self._next_index()
            req = self._waiting[i]
            prompt = req.prompt
            matched = self.index.match(prompt) if self.index else []
            # always leave >= 1 prompt token to recompute: its logits
            # emit the first generated token
            n_shared = min(len(matched),
                           (len(prompt) - 1) // self.block_size)
            shared_ids = matched[:n_shared]
            need = blocks_needed(len(prompt), self.block_size)
            fresh = need - n_shared
            protect = set(shared_ids) | set(self._shared_in_use)
            if self.free_blocks - fresh < self.watermark:
                self._make_room(fresh, protect)
            if self.free_blocks - fresh < self.watermark:
                # the head-of-line request deferred by the watermark: the
                # KV-pressure signal an operator sizes the pool by
                inc_counter("serving/admission_blocked", 1,
                            replica=self.replica)
                break               # FIFO within the best class: no skip
            del self._waiting[i]
            slot = self._free_slots.pop(0)
            self.free_blocks -= fresh
            for b in shared_ids:
                self._shared_in_use[b] = self._shared_in_use.get(b, 0) + 1
            prefix_tokens = n_shared * self.block_size
            self.running[slot] = _Running(
                req=req, slot=slot, n_blocks=need,
                tokens_in_cache=prefix_tokens, prefilled=prefix_tokens,
                shared_ids=list(shared_ids), spec_depth=self.spec_k,
                slo_rank=self._rank(req), admit_seq=self._admit_seq)
            self._admit_seq += 1
            inc_counter("serving/admissions", 1, replica=self.replica)
            inc_counter("serving/prefix_hit_tokens", prefix_tokens,
                        replica=self.replica)
            inc_counter("serving/prefix_miss_tokens",
                        len(prompt) - prefix_tokens, replica=self.replica)
            admitted.append(Admission(slot=slot, req=req,
                                      shared_ids=list(shared_ids),
                                      n_blocks=need))
        return admitted

    # -- preemption / requeue (SLO classes, serving/fleet) -----------
    def pick_victim(self, rank: int) -> Optional[int]:
        """The deterministic preemption victim for a blocked candidate
        of class rank ``rank``: the MOST RECENTLY ADMITTED running slot
        of a strictly lower-priority class (numerically greater rank) —
        the least sunk work among the outranked. None when nothing
        running is outranked (same-class work never preempts)."""
        cands = [(st.admit_seq, s) for s, st in self.running.items()
                 if st.slo_rank > rank]
        return max(cands)[1] if cands else None

    def preempt(self, slot: int) -> _Running:
        """Evict a running slot to make room for a higher-class request:
        its blocks return to the pool exactly as ``release`` would
        (shared prefix pages survive via their other references) but the
        request is NOT finished — the caller requeues it (the engine
        session stitches the tokens it already emitted back on as
        ``prior``). Arms the ``serving/preemptions`` counter. Returns
        the evicted running state."""
        st = self.running.pop(slot)
        self.free_blocks += self._return_blocks(st, set())
        self._free_slots.append(slot)
        self._free_slots.sort()
        inc_counter("serving/preemptions", 1, replica=self.replica)
        return st

    def requeue(self, req: Request) -> None:
        """Re-enter preempted / fault-drained work at the FRONT of its
        class section of the wait queue (after any higher classes): the
        victim was admitted before every still-waiting peer of its own
        class, so it keeps that seniority instead of starving behind
        later arrivals."""
        rk = self._rank(req)
        for i, r in enumerate(self._waiting):
            if self._rank(r) >= rk:
                self._waiting.insert(i, req)
                return
        self._waiting.append(req)

    # -- step planning ----------------------------------------------
    def _take_block(self) -> None:
        self.free_blocks -= 1
        if self.free_blocks < 0:
            raise RuntimeError(
                f"paged pool underflow: decode growth would need a block "
                f"with 0 free — the admission watermark "
                f"({self.watermark}) is undersized for this workload")

    def _decode_ready(self, st: _Running) -> bool:
        return st.prefilled >= len(st.req.prompt)

    def _emit_headroom(self, st: _Running) -> int:
        """Tokens the request may still EMIT (decode-ready slots only).
        The host's generated list runs one token ahead of the cache (the
        completing chunk emits the first token before any decode write),
        so generated-so-far = tokens_in_cache - prompt + 1."""
        return (st.req.max_new_tokens
                - (st.tokens_in_cache - len(st.req.prompt)) - 1)

    def _slot_order(self) -> List[int]:
        """Budget-allocation order: latency-class slots first, slot
        order within a class. With a single class this is exactly the
        old ``sorted(self.running)`` — SLO-less workloads plan
        byte-identical steps. (The ENGINE still packs rows in plain
        slot order; only who gets budget changes.)"""
        return sorted(self.running,
                      key=lambda s: (self.running[s].slo_rank, s))

    def spec_quota(self) -> Dict[int, int]:
        """Per decode-ready slot, the max draft tokens the engine should
        request from the drafter THIS step: the slot's adaptive depth,
        capped so the verify window never out-emits the request
        (accepting every draft plus the bonus token must not exceed
        max_new_tokens — that cap also keeps spec writes inside the
        lifetime block capacity checked at ``add``), so drafted tokens
        fit the step budget after every decode-ready slot's guaranteed
        one token, and so the windows' block growth fits the FREE pool —
        the admission watermark only reserves single-token growth, so
        speculation shrinks before it can underflow what plain decode is
        entitled to. Pure read — ``plan_step`` is then called with the
        draft counts the drafter actually produced."""
        ready = [s for s in self._slot_order()
                 if self._decode_ready(self.running[s])]
        spare = self.chunk_tokens - len(ready)
        # mid-prefill slots must keep making progress: speculation may
        # take at most HALF the leftover budget while prompt chunks are
        # pending (spec-off gave chunks the whole leftover; a sustained
        # high accept rate must not push queued prompts' TTFT out
        # indefinitely)
        pending = sum(len(self.running[s].req.prompt)
                      - self.running[s].prefilled
                      for s in self.running
                      if not self._decode_ready(self.running[s]))
        spare -= min(pending, (spare + 1) // 2)
        free = self.free_blocks
        quota: Dict[int, int] = {}
        for slot in ready:
            st = self.running[slot]
            k = max(0, min(st.spec_depth, self._emit_headroom(st), spare))

            def _growth(n_tok):
                return max(0, blocks_needed(st.tokens_in_cache + n_tok,
                                            self.block_size) - st.n_blocks)

            while k > 0 and _growth(1 + k) > free:
                k -= 1
            free -= _growth(1 + k)
            quota[slot] = k
            spare -= k
        return quota

    def note_spec(self, slot: int, drafted: int, accepted: int,
                  finished: bool) -> int:
        """Record one verify outcome: adapt the slot's draft depth to
        the observed accept rate (full acceptance probes one deeper,
        accepting under half backs off — bounded [1, spec_k]) and, for a
        slot that keeps running with rejected drafts in its cache, roll
        the host mirror back alongside the engine's device
        ``truncate_slots`` (tokens shrink to the accepted prefix, blocks
        past the kept span return to the pool — always fresh rc=1 spec
        growth, never prefix-shared pages, because rollback stops at
        this step's own writes). Returns the slot's post-rollback token
        count (the row the engine hands the device truncate). Finishing
        slots skip the rollback: ``free_slot``/``release`` retire the
        whole table, so mirror and device stay aligned without it."""
        st = self.running[slot]
        if drafted > 0:
            if accepted >= drafted:
                st.spec_depth = min(st.spec_depth + 1, self.spec_k)
            elif accepted * 2 < drafted:
                st.spec_depth = max(1, st.spec_depth - 1)
        new_len = st.tokens_in_cache - (drafted - accepted)
        if finished or accepted >= drafted:
            return st.tokens_in_cache
        kept = min(blocks_needed(new_len, self.block_size), st.n_blocks)
        self.free_blocks += st.n_blocks - kept
        st.n_blocks = kept
        st.tokens_in_cache = new_len
        return new_len

    def plan_step(self,
                  spec_drafts: Optional[Dict[int, int]] = None
                  ) -> List[Work]:
        """Split this step's ``chunk_tokens`` budget over the running
        slots: decode steps first (one token per decode-ready slot —
        guaranteed to fit, chunk_tokens >= max_slots), then prompt
        chunks FIFO with whatever budget remains. BOTH phases walk the
        slots in SLO order (``_slot_order``: latency class first, slot
        order within a class), so under a tight budget a latency-bound
        request's decode window and prompt chunks displace
        throughput-bound ones — with one class this is the old
        sorted-slot order, byte for byte. Advances the host mirror
        (prefilled / tokens_in_cache / decode block growth) — callers
        run every returned Work item this step.

        With ``spec_drafts`` (slot -> draft-token count, from the
        engine's drafter under ``spec_quota``) a decode-ready slot's
        item becomes a VERIFY run of ``1 + drafts`` tokens, charged
        against the same budget; its block growth (``Work.grow``) is
        whatever the whole window needs and is pre-staged by the
        engine's grow helper, so the in-step one-block growth stays a
        no-op.

        Note: chunk writes land in pages assigned at admission and a
        shared prefix is whole blocks (suffixes start page-aligned), so
        neither growth nor copy-on-write can trigger for chunks — only
        decode steps take pool blocks here."""
        budget = self.chunk_tokens
        work: List[Work] = []
        order = self._slot_order()
        for slot in order:
            st = self.running[slot]
            if self._decode_ready(st) and budget >= 1:
                pos = st.tokens_in_cache
                n = 1 + (spec_drafts.get(slot, 0) if spec_drafts else 0)
                n = min(n, budget)
                grow = 0
                need_blocks = blocks_needed(pos + n, self.block_size)
                while (st.n_blocks < need_blocks
                        and st.n_blocks < self.max_blocks_per_seq):
                    st.n_blocks += 1
                    self._take_block()
                    grow += 1
                work.append(Work(slot=slot, kind="decode", start=pos, n=n,
                                 grow=grow))
                st.tokens_in_cache = pos + n
                budget -= n
        for slot in order:
            st = self.running[slot]
            rem = len(st.req.prompt) - st.prefilled
            if rem > 0 and budget > 0:
                n = min(rem, budget)
                work.append(Work(slot=slot, kind="chunk",
                                 start=st.prefilled, n=n,
                                 completes_prompt=(n == rem)))
                st.prefilled += n
                st.tokens_in_cache += n
                budget -= n
        return work

    # -- legacy decode accounting (PR-3 API, kept for external callers)
    def grow_for_decode(self) -> int:
        """Account one token appended to every running slot: slots whose
        new position opens a fresh page take a block from the pool.
        Returns the number of blocks taken; raises on pool underflow.
        The unified engine uses ``plan_step`` (which does this per
        decode-ready slot); this whole-batch form remains for the PR-3
        decode loop shape."""
        grown = 0
        for st in self.running.values():
            pos = st.tokens_in_cache
            if pos // self.block_size >= st.n_blocks:
                st.n_blocks += 1
                grown += 1
            st.tokens_in_cache = pos + 1
        self.free_blocks -= grown
        if self.free_blocks < 0:
            raise RuntimeError(
                f"paged pool underflow: decode growth took {grown} blocks "
                f"with only {self.free_blocks + grown} free — the "
                f"admission watermark ({self.watermark}) is undersized "
                f"for this workload")
        return grown

    # -- release -----------------------------------------------------
    def _return_blocks(self, st: _Running, newly: set) -> int:
        """Blocks a departing slot returns to the pool: every block
        whose refcount reaches 0 — fresh blocks not handed to the
        prefix index (``newly``, which keep the index's refcount), plus
        shared prefix blocks nobody else references. The one accounting
        shared by ``release`` (finish) and ``preempt`` (eviction), so
        the two paths cannot diverge from the device's ``free_slot``."""
        freed = 0
        for b in st.shared_ids:
            cnt = self._shared_in_use.get(b, 1) - 1
            if cnt > 0:
                self._shared_in_use[b] = cnt
            else:
                self._shared_in_use.pop(b, None)
                if not (self.index is not None and self.index.holds(b)):
                    freed += 1
        fresh = st.n_blocks - len(st.shared_ids)
        freed += fresh - len(newly - set(st.shared_ids))
        return freed

    def release(self, slot: int, newly_indexed: Iterable[int] = ()) -> None:
        """Finished sequence: return its slot and its zero-refcount
        blocks (see ``_return_blocks``)."""
        st = self.running.pop(slot)
        self.free_blocks += self._return_blocks(
            st, {int(b) for b in newly_indexed})
        self._free_slots.append(slot)
        self._free_slots.sort()
        inc_counter("serving/evictions", 1, replica=self.replica)
