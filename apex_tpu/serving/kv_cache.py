"""Block-paged KV cache — a fixed-pool pytree with pure-functional ops,
per-block refcounts, and host-side hash-based prefix sharing.

The serving memory model of "Ragged Paged Attention" (arxiv 2604.15464)
and vLLM: K/V for all sequences live in ONE fixed pool of fixed-size
blocks ("pages"), and each sequence maps its logical positions to pool
blocks through a block table. Admission/eviction then move block IDS, not
KV bytes, and memory fragmentation is bounded by one partial block per
sequence.

Prefix caching (the millions-of-users lever: shared system prompts,
multi-turn chat) adds two pieces on top:

- **Per-block refcounts** (device side, part of the pytree): a block is
  free iff its refcount is 0. A block may be referenced by several block
  tables at once (a shared prompt prefix) and/or by the host-side prefix
  index; ``free_slot`` DECREMENTS instead of freeing, so a shared page
  outlives any one sequence. ``share_prefix`` admits a sequence by
  pointing its table at already-resident pages (+1 each) and allocating
  fresh pages only for the suffix; ``cow_append`` is the copy-on-write
  guard that gives a slot a private copy of a shared partial page before
  an append would write into it.
- **PrefixIndex** (host side, plain python): a chain hash of block-sized
  token runs -> the pool block id holding that run's K/V. The scheduler
  matches an incoming prompt against it block by block; every indexed
  block carries one refcount of its own (the engine retains newly
  indexed blocks before freeing their slot), so cached prefixes survive
  sequence eviction until the index itself evicts them under pool
  pressure (LRU).

Layout (the whole cache is a NamedTuple pytree — it jits, donates, and
shards like any train state):

    k_pool / v_pool  [layers, num_blocks, block_size, n_kv_heads, head_dim]
    block_tables     [max_slots, max_blocks_per_seq] int32 (pool block ids;
                     entries past n_blocks[slot] are meaningless and kept 0)
    n_blocks         [max_slots] int32  — blocks assigned per slot
    seq_lens         [max_slots] int32  — tokens written per slot
    refcount         [num_blocks] int32 — table references + prefix-index
                     holds (0 = free)

The per-layer pool slice ``k_pool[l]`` is exactly the
``[num_blocks, block_size, n_kv_heads, head_dim]`` operand
ops/paged_attention.py consumes. Sharding (cache_pspecs()): KV heads ride
the TP axis — the same head split as the training tensor-parallel layers,
so TP-sharded decode reuses the training weight layout — and the pool's
block axis can ride the data axis (each data rank serves its own
requests from its own pool shard; inside shard_map all ops here are
rank-local).

Every mutator is pure (returns a new cache) and built from lax/scatter
ops only, so the whole serving step — allocate, append, attend, free —
jits as one program. Out-of-range scatters use mode="drop" as the
masking mechanism for inactive slots (index ``num_blocks`` is the
designated drop target). Callers keep the pool from overflowing via the
scheduler's free-block watermark; allocation on an empty pool is a
documented invariant violation (it would corrupt block 0), so the
engine checks ``free_block_count`` before every step.

Env defaults (docs/serving.md): APEX_TPU_PAGED_BLOCK_SIZE (block_size,
default 16), APEX_TPU_SERVING_MAX_SLOTS (max_slots, default 8),
APEX_TPU_SERVING_CHUNK_TOKENS (engine step budget) — read by
serving/engine.py, not here; this module is explicit-arguments-only.
"""

from __future__ import annotations

import math
from collections import OrderedDict
from typing import List, Mapping, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class PagedKVCache(NamedTuple):
    k_pool: jax.Array       # [L, N, bs, Hkv, D]
    v_pool: jax.Array       # [L, N, bs, Hkv, D]
    block_tables: jax.Array  # [max_slots, max_blocks_per_seq] int32
    n_blocks: jax.Array     # [max_slots] int32
    seq_lens: jax.Array     # [max_slots] int32
    refcount: jax.Array     # [N] int32 (0 = free)

    # -- static views ------------------------------------------------
    @property
    def num_blocks(self) -> int:
        return self.k_pool.shape[1]

    @property
    def block_size(self) -> int:
        return self.k_pool.shape[2]

    @property
    def max_slots(self) -> int:
        return self.block_tables.shape[0]

    @property
    def max_blocks_per_seq(self) -> int:
        return self.block_tables.shape[1]


def paged_kv_cache(layers: int, num_blocks: int, block_size: int,
                   n_kv_heads: int, head_dim: int, max_slots: int,
                   max_blocks_per_seq: Optional[int] = None,
                   dtype=jnp.bfloat16) -> PagedKVCache:
    """A fresh cache: empty pool, zeroed tables, every refcount 0."""
    if max_blocks_per_seq is None:
        max_blocks_per_seq = num_blocks
    shape = (layers, num_blocks, block_size, n_kv_heads, head_dim)
    return PagedKVCache(
        k_pool=jnp.zeros(shape, dtype),
        v_pool=jnp.zeros(shape, dtype),
        block_tables=jnp.zeros((max_slots, max_blocks_per_seq), jnp.int32),
        n_blocks=jnp.zeros((max_slots,), jnp.int32),
        seq_lens=jnp.zeros((max_slots,), jnp.int32),
        refcount=jnp.zeros((num_blocks,), jnp.int32),
    )


# ---------------------------------------------------------------------------
# int8 quantized pool variant (docs/quantization.md "KV layout")
# ---------------------------------------------------------------------------

class QuantPagedKVCache(NamedTuple):
    """The int8 pool variant (``APEX_TPU_SERVING_KV_INT8=1``): K/V
    payloads are int8 with a PER-(token, head) fp32 absmax scale riding
    as a sidecar pool of the same block geometry — the
    quantization/qtensor.py scheme with the block axis = head_dim, so
    every write quantizes exactly the rows it lands (append stays a
    scatter) and ops/paged_attention.py dequantizes pages IN KERNEL at
    fetch time. All table/refcount machinery (share_prefix, cow_append,
    extend/grow/truncate_slots, free/retain/release, check_invariants,
    the PrefixIndex) is FIELD-NAME generic over this NamedTuple —
    quantization changes pool bytes, never the sharing semantics."""

    k_pool: jax.Array       # [L, N, bs, Hkv, D] int8
    v_pool: jax.Array       # [L, N, bs, Hkv, D] int8
    k_scale: jax.Array      # [L, N, bs, Hkv] fp32 absmax/127 per row
    v_scale: jax.Array      # [L, N, bs, Hkv] fp32
    block_tables: jax.Array  # [max_slots, max_blocks_per_seq] int32
    n_blocks: jax.Array     # [max_slots] int32
    seq_lens: jax.Array     # [max_slots] int32
    refcount: jax.Array     # [N] int32 (0 = free)

    # -- static views (same layout as PagedKVCache) ------------------
    @property
    def num_blocks(self) -> int:
        return self.k_pool.shape[1]

    @property
    def block_size(self) -> int:
        return self.k_pool.shape[2]

    @property
    def max_slots(self) -> int:
        return self.block_tables.shape[0]

    @property
    def max_blocks_per_seq(self) -> int:
        return self.block_tables.shape[1]


def quantized_kv_cache(layers: int, num_blocks: int, block_size: int,
                       n_kv_heads: int, head_dim: int, max_slots: int,
                       max_blocks_per_seq: Optional[int] = None
                       ) -> QuantPagedKVCache:
    """A fresh int8 cache: zero payloads AND zero scales (dequantized
    unwritten rows read as exact 0, matching the fp pool's zeros)."""
    if max_blocks_per_seq is None:
        max_blocks_per_seq = num_blocks
    shape = (layers, num_blocks, block_size, n_kv_heads, head_dim)
    return QuantPagedKVCache(
        k_pool=jnp.zeros(shape, jnp.int8),
        v_pool=jnp.zeros(shape, jnp.int8),
        k_scale=jnp.zeros(shape[:-1], jnp.float32),
        v_scale=jnp.zeros(shape[:-1], jnp.float32),
        block_tables=jnp.zeros((max_slots, max_blocks_per_seq), jnp.int32),
        n_blocks=jnp.zeros((max_slots,), jnp.int32),
        seq_lens=jnp.zeros((max_slots,), jnp.int32),
        refcount=jnp.zeros((num_blocks,), jnp.int32),
    )


def is_quantized(cache) -> bool:
    """Static (trace-time python) test for the int8 pool variant."""
    return isinstance(cache, QuantPagedKVCache)


def quant_cache_pspecs(tp_axis: Optional[str] = "model",
                       data_axis: Optional[str] = None) -> QuantPagedKVCache:
    """``cache_pspecs`` for the int8 variant: scale pools shard exactly
    like their payload pools minus the head_dim axis (KV heads on the
    TP axis, blocks optionally on data)."""
    base = cache_pspecs(tp_axis, data_axis)
    return QuantPagedKVCache(
        k_pool=base.k_pool,
        v_pool=base.v_pool,
        k_scale=P(None, data_axis, None, tp_axis),
        v_scale=P(None, data_axis, None, tp_axis),
        block_tables=base.block_tables,
        n_blocks=base.n_blocks,
        seq_lens=base.seq_lens,
        refcount=base.refcount,
    )


def quantized_pool_blocks(num_blocks: int, head_dim: int, dtype) -> int:
    """Blocks the int8 pool holds in the SAME byte budget as a
    ``num_blocks`` pool of ``dtype``: per (token, head) row the fp pool
    costs ``head_dim * itemsize`` bytes and the int8 pool costs
    ``head_dim + 4`` (payload + one fp32 scale); block_size, kv heads
    and layers scale both sides identically and cancel. This is the
    capacity lever behind ``APEX_TPU_SERVING_KV_INT8`` — an fp32 pool
    at head_dim 64 yields 3.7x the blocks, i.e. 3.7x the concurrent
    sequences the watermark admission path can hold resident."""
    fp_row = int(head_dim) * jnp.dtype(dtype).itemsize
    q_row = int(head_dim) + 4
    return max(int(num_blocks), (int(num_blocks) * fp_row) // q_row)


def kv_quantize(x):
    """Quantize K/V rows ``[..., D]`` to (int8 payload, fp32 scale) with
    one absmax scale per row — exactly ``quantization.quantize`` with
    block = head_dim (error <= absmax_row / 254 per element), THROUGH
    that one definition so the KV write path can never diverge from the
    library's error model. Shared by write_prefill and append_layer."""
    from apex_tpu.quantization import quantize

    qt = quantize(x, block=x.shape[-1], axis=-1)
    return qt.q, qt.scale[..., 0]


def cache_pspecs(tp_axis: Optional[str] = "model",
                 data_axis: Optional[str] = None) -> PagedKVCache:
    """PartitionSpecs for shard_map in/out specs: KV heads on the TP axis
    (kv_heads % tp == 0, same contract as the GQA column split in
    testing/standalone_transformer.py), and — when ``data_axis`` is given
    — pool blocks, tables and accounting over the data axis (per-rank
    request sets; block ids are rank-local)."""
    return PagedKVCache(
        k_pool=P(None, data_axis, None, tp_axis, None),
        v_pool=P(None, data_axis, None, tp_axis, None),
        block_tables=P(data_axis),
        n_blocks=P(data_axis),
        seq_lens=P(data_axis),
        refcount=P(data_axis),
    )


def blocks_needed(n_tokens: int, block_size: int) -> int:
    """Pool blocks covering ``n_tokens`` (host-side scheduler arithmetic)."""
    return int(math.ceil(max(int(n_tokens), 0) / block_size))


def free_block_count(cache: PagedKVCache):
    return jnp.sum((cache.refcount == 0).astype(jnp.int32))


# ---------------------------------------------------------------------------
# allocate / share / free
# ---------------------------------------------------------------------------

def share_prefix(cache: PagedKVCache, slot, shared_ids, n_shared,
                 n_total) -> PagedKVCache:
    """Admit ``slot`` with a resident prefix: its table's first
    ``n_shared`` entries point at ``shared_ids`` (already-resident pages,
    refcount += 1 each — the prefix-cache hit), entries
    ``[n_shared, n_total)`` take the first free pool blocks (refcount
    set to 1), and ``seq_lens`` starts at ``n_shared * block_size`` (the
    prefix tokens are already written; the engine prefills only the
    suffix). ``shared_ids`` is a fixed-shape [max_blocks_per_seq] int32
    row; entries past ``n_shared`` are ignored. ``n_shared``/``n_total``
    may be traced; the caller guarantees ``n_total - n_shared <=
    free_block_count`` and ``n_total <= max_blocks_per_seq`` (scheduler
    admission), and that the shared ids are distinct resident blocks."""
    mb = cache.max_blocks_per_seq
    nb_pool = cache.num_blocks
    lane = jnp.arange(mb)
    # free blocks first, in index order (stable sort of the "taken" flag)
    order = jnp.argsort(cache.refcount > 0, stable=True)
    take = order[:mb].astype(jnp.int32)
    if mb > nb_pool:  # tiny pools: pad with the drop target
        take = jnp.concatenate(
            [take, jnp.full((mb - nb_pool,), nb_pool, take.dtype)])
    shared_ids = jnp.asarray(shared_ids, jnp.int32)
    is_shared = lane < n_shared
    is_fresh = (lane >= n_shared) & (lane < n_total)
    fresh = take[jnp.clip(lane - n_shared, 0, mb - 1)]
    row = jnp.where(is_shared, shared_ids,
                    jnp.where(is_fresh, fresh, 0)).astype(jnp.int32)
    rc = cache.refcount.at[
        jnp.where(is_shared, shared_ids, nb_pool)].add(1, mode="drop")
    rc = rc.at[jnp.where(is_fresh, fresh, nb_pool)].set(1, mode="drop")
    return cache._replace(
        block_tables=cache.block_tables.at[slot].set(row),
        n_blocks=cache.n_blocks.at[slot].set(
            jnp.asarray(n_total, jnp.int32)),
        seq_lens=cache.seq_lens.at[slot].set(
            jnp.asarray(n_shared * cache.block_size, jnp.int32)),
        refcount=rc,
    )


def allocate_slot(cache: PagedKVCache, slot, n_blocks) -> PagedKVCache:
    """Assign the first ``n_blocks`` free pool blocks to ``slot`` (its
    whole table row is replaced; seq_len resets to 0) — the cold-path
    special case of ``share_prefix`` with an empty shared prefix."""
    return share_prefix(cache, slot,
                        jnp.zeros((cache.max_blocks_per_seq,), jnp.int32),
                        0, n_blocks)


def free_slot(cache: PagedKVCache, slot) -> PagedKVCache:
    """Release ``slot``: clear its row and DECREMENT its blocks'
    refcounts — blocks shared with another slot or held by the prefix
    index stay resident; only refcount 0 returns a block to the pool.
    Idempotent (a slot with n_blocks == 0 frees nothing)."""
    mb = cache.max_blocks_per_seq
    lane = jnp.arange(mb) < cache.n_blocks[slot]
    ids = jnp.where(lane, cache.block_tables[slot], cache.num_blocks)
    return cache._replace(
        block_tables=cache.block_tables.at[slot].set(
            jnp.zeros((mb,), jnp.int32)),
        n_blocks=cache.n_blocks.at[slot].set(0),
        seq_lens=cache.seq_lens.at[slot].set(0),
        refcount=cache.refcount.at[ids].add(-1, mode="drop"),
    )


def retain_blocks(cache: PagedKVCache, ids, n) -> PagedKVCache:
    """refcount += 1 for ``ids[:n]`` (fixed-shape [max_blocks_per_seq]
    row) — the engine's handoff of newly prefix-indexed blocks from a
    finishing slot to the index, called BEFORE free_slot so the pages
    never transit refcount 0."""
    lane = jnp.arange(ids.shape[0])
    tgt = jnp.where(lane < n, jnp.asarray(ids, jnp.int32),
                    cache.num_blocks)
    return cache._replace(
        refcount=cache.refcount.at[tgt].add(1, mode="drop"))


def release_blocks(cache: PagedKVCache, ids, n) -> PagedKVCache:
    """refcount -= 1 for ``ids[:n]`` — prefix-index eviction returning
    its hold on cached pages (a page still shared by a running slot
    stays resident)."""
    lane = jnp.arange(ids.shape[0])
    tgt = jnp.where(lane < n, jnp.asarray(ids, jnp.int32),
                    cache.num_blocks)
    return cache._replace(
        refcount=cache.refcount.at[tgt].add(-1, mode="drop"))


# ---------------------------------------------------------------------------
# prefill write
# ---------------------------------------------------------------------------

def write_prefill(cache: PagedKVCache, slot, k, v, length) -> PagedKVCache:
    """Scatter a prefill's K/V into ``slot``'s assigned pages and set its
    length. k/v: [layers, t_pad, n_kv_heads, head_dim] (a fixed padded
    prefill shape); rows at positions >= ``length`` are dropped. The slot
    must hold >= ceil(length / block_size) blocks (allocate_slot)."""
    t_pad = k.shape[1]
    bs = cache.block_size
    pos = jnp.arange(t_pad)
    tbl_idx = jnp.clip(pos // bs, 0, cache.max_blocks_per_seq - 1)
    blocks = cache.block_tables[slot][tbl_idx]                # [t_pad]
    valid = pos < length
    blocks = jnp.where(valid, blocks, cache.num_blocks)       # drop target
    offs = pos % bs
    new = {"seq_lens": cache.seq_lens.at[slot].set(
        jnp.asarray(length, jnp.int32))}
    if is_quantized(cache):
        kq, ks = kv_quantize(k)
        vq, vs = kv_quantize(v)
        new.update(
            k_pool=cache.k_pool.at[:, blocks, offs].set(kq, mode="drop"),
            v_pool=cache.v_pool.at[:, blocks, offs].set(vq, mode="drop"),
            k_scale=cache.k_scale.at[:, blocks, offs].set(ks, mode="drop"),
            v_scale=cache.v_scale.at[:, blocks, offs].set(vs, mode="drop"),
        )
    else:
        new.update(
            k_pool=cache.k_pool.at[:, blocks, offs].set(
                k.astype(cache.k_pool.dtype), mode="drop"),
            v_pool=cache.v_pool.at[:, blocks, offs].set(
                v.astype(cache.v_pool.dtype), mode="drop"),
        )
    return cache._replace(**new)


# ---------------------------------------------------------------------------
# append (decode steps and prefill chunks)
# ---------------------------------------------------------------------------

def cow_append(cache: PagedKVCache, active) -> PagedKVCache:
    """Copy-on-write guard before appending at each active slot's current
    position: if the page the next token would land in is partially
    filled AND shared (refcount > 1 — another slot or the prefix index
    also reads it), the slot gets a private copy first (fresh block,
    page contents copied, table repointed, shared refcount -= 1).

    With the engine's full-block-only prefix sharing a suffix always
    starts on a page boundary, so this never fires there — it is the
    safety net that makes partial-page sharing (forking, speculative
    branches) correct by construction. Callers keep one free block per
    potentially-COWed slot under the admission watermark."""
    bs = cache.block_size
    mb = cache.max_blocks_per_seq
    nb_pool = cache.num_blocks
    pos = cache.seq_lens                                       # [S]
    tbl_idx = jnp.clip(pos // bs, 0, mb - 1)
    blk = jnp.take_along_axis(cache.block_tables, tbl_idx[:, None],
                              1)[:, 0]
    inside = (jnp.asarray(active, bool) & (pos % bs != 0)
              & (pos // bs < cache.n_blocks))
    src_c = jnp.clip(blk, 0, nb_pool - 1)
    shared = inside & (cache.refcount[src_c] > 1)

    def body(carry, s):
        rc, tables = carry
        f = jnp.argmax(rc == 0).astype(jnp.int32)              # first free
        need = shared[s]
        rc = rc.at[f].set(jnp.where(need, 1, rc[f]))
        rc = rc.at[src_c[s]].add(jnp.where(need, -1, 0))
        tables = tables.at[s, tbl_idx[s]].set(
            jnp.where(need, f, tables[s, tbl_idx[s]]))
        return (rc, tables), jnp.where(need, f, nb_pool)

    (rc, tables), dst = jax.lax.scan(
        body, (cache.refcount, cache.block_tables),
        jnp.arange(cache.max_slots))

    # the quantized variant's scale sidecars are pools of the same block
    # geometry (axis 1 = pool block), so COW copies them alongside
    pool_fields = tuple(f for f in ("k_pool", "v_pool",
                                    "k_scale", "v_scale")
                        if f in cache._fields)

    def _copy(pools):
        return tuple(p.at[:, dst].set(p[:, src_c], mode="drop")
                     for p in pools)

    # the page gather+scatter is the expensive part and the common case
    # is "no COW anywhere" — gate it at RUNTIME so the steady-state step
    # pays one predicate, not [L, S, bs, Hkv, D] of HBM traffic
    pools = jax.lax.cond(
        jnp.any(shared), _copy, lambda pools: pools,
        tuple(getattr(cache, f) for f in pool_fields))
    return cache._replace(
        block_tables=tables,
        refcount=rc,
        **dict(zip(pool_fields, pools)),
    )


def extend_slots(cache: PagedKVCache, active, ql) -> PagedKVCache:
    """Advance each active slot's ``seq_lens`` by ``ql[s]`` tokens,
    allocating AT MOST ONE fresh pool block where the new span crosses
    into an unassigned page. Decode steps (ql == 1) grow across page
    boundaries here; prefill chunks land in pages assigned up front at
    admission (share_prefix), so they never need growth — a chunk that
    WOULD need more than one fresh page is a scheduler bug this op does
    not mask (the span past the one granted page scatters to the drop
    target and check_invariants flags the length).

    Growth walks slots with a scan (max_slots is small and static),
    handing each needy slot the first free block — callers keep
    ``free_block_count >= popcount(need)`` via the admission watermark.
    """
    ql = jnp.where(jnp.asarray(active, bool), jnp.asarray(ql, jnp.int32), 0)
    pos_end = cache.seq_lens + ql
    bs = cache.block_size
    need_blocks = (pos_end + bs - 1) // bs
    need = ((need_blocks > cache.n_blocks)
            & (cache.n_blocks < cache.max_blocks_per_seq))

    def body(carry, s):
        rc, tables, nblk = carry
        return _tail_alloc(rc, tables, nblk, s, need[s],
                           cache.max_blocks_per_seq), None

    (rc, tables, nblk), _ = jax.lax.scan(
        body, (cache.refcount, cache.block_tables, cache.n_blocks),
        jnp.arange(cache.max_slots))
    return cache._replace(
        block_tables=tables, n_blocks=nblk, refcount=rc,
        seq_lens=pos_end,
    )


def _tail_alloc(rc, tables, nblk, s, grow, max_blocks_per_seq: int):
    """One scan step of first-free tail allocation — THE shared body of
    ``extend_slots`` and ``grow_slots``: when ``grow``, hand slot ``s``
    the first free pool block (rc 0 -> 1) at its table tail. Callers
    guarantee a free block exists whenever ``grow`` is true (the
    admission watermark); with the pool full, argmax would return
    block 0 — the documented allocate-on-empty invariant violation."""
    blk = jnp.argmax(rc == 0).astype(jnp.int32)
    ti = jnp.clip(nblk[s], 0, max_blocks_per_seq - 1)
    rc = rc.at[blk].set(jnp.where(grow, 1, rc[blk]))
    tables = tables.at[s, ti].set(jnp.where(grow, blk, tables[s, ti]))
    nblk = nblk.at[s].add(jnp.where(grow, 1, 0))
    return rc, tables, nblk


def grow_slots(cache: PagedKVCache, counts, *, max_grow: int) -> PagedKVCache:
    """Assign ``counts[s]`` fresh pool blocks to each slot's table tail
    (refcount 1 each, ``n_blocks`` advanced; ``seq_lens`` untouched) —
    the engine's pre-staging call for runs that may cross MORE than one
    page boundary in a single step (a speculative verify window of
    ``K + 1`` tokens), which ``extend_slots``'s one-block-per-step
    growth cannot cover. Pre-grown slots make the in-step growth a
    no-op, so the unified step's program is byte-identical whether
    growth happened here or there.

    ``max_grow`` is the STATIC per-slot ceiling (callers jit one wrapper
    per engine); ``counts`` entries above it are a caller bug and are
    clamped. Callers keep ``free_block_count >= sum(counts)`` via the
    scheduler's watermark, and ``n_blocks + counts <=
    max_blocks_per_seq`` via the per-request capacity check."""
    counts = jnp.clip(jnp.asarray(counts, jnp.int32), 0, max_grow)

    def body(carry, sj):
        rc, tables, nblk = carry
        s = sj // max_grow
        j = sj % max_grow
        grow = (j < counts[s]) & (nblk[s] < cache.max_blocks_per_seq)
        return _tail_alloc(rc, tables, nblk, s, grow,
                           cache.max_blocks_per_seq), None

    (rc, tables, nblk), _ = jax.lax.scan(
        body, (cache.refcount, cache.block_tables, cache.n_blocks),
        jnp.arange(cache.max_slots * max_grow))
    return cache._replace(block_tables=tables, n_blocks=nblk, refcount=rc)


def truncate_slots(cache: PagedKVCache, new_lens) -> PagedKVCache:
    """Roll slots BACK to ``new_lens[s]`` tokens, releasing the
    over-allocated suffix: every table entry past
    ``ceil(new_len / block_size)`` has its refcount DECREMENTED (a page
    still shared by another table or held by the prefix index stays
    resident — rollback must never free pages the index holds) and is
    cleared from the table; ``n_blocks`` shrinks to the kept count.

    Only slots with ``new_lens[s] < seq_lens[s]`` change — pass the
    current length (or any value >= it, e.g. INT32_MAX) to leave a slot
    untouched. The engine calls this after speculative verification to
    drop rejected draft tokens' positions; callers must not truncate a
    slot holding pages assigned for UNWRITTEN future tokens (a
    mid-prefill slot's admitted suffix pages), because the kept count is
    derived from ``new_lens`` alone. Stale K/V past ``new_lens`` in
    kept pages is unreachable (the kernel masks columns >= kv_len) and
    is overwritten before the positions become visible again."""
    mb = cache.max_blocks_per_seq
    bs = cache.block_size
    nl = jnp.minimum(jnp.asarray(new_lens, jnp.int32), cache.seq_lens)
    do = nl < cache.seq_lens
    keep_n = jnp.minimum((nl + bs - 1) // bs, cache.n_blocks)
    keep_n = jnp.where(do, keep_n, cache.n_blocks)             # [S]
    lane = jnp.arange(mb)[None, :]
    drop = (lane >= keep_n[:, None]) & (lane < cache.n_blocks[:, None])
    ids = jnp.where(drop, cache.block_tables, cache.num_blocks)
    return cache._replace(
        block_tables=jnp.where(drop, 0, cache.block_tables),
        n_blocks=keep_n,
        seq_lens=jnp.where(do, nl, cache.seq_lens),
        refcount=cache.refcount.at[ids.reshape(-1)].add(-1, mode="drop"),
    )


def alloc_decode_blocks(cache: PagedKVCache, active):
    """Reserve this decode step's token position for every active slot,
    growing block tables where the position opens a new page (the PR-3
    decode entry — ``extend_slots`` with ql == 1 plus the per-slot write
    coordinates).

    active: [max_slots] bool. Returns (cache, block_ids, offsets) where
    block_ids/offsets [max_slots] locate each active slot's NEW token
    (inactive slots get the drop target ``num_blocks``); seq_lens of
    active slots are already incremented, so the lengths the paged
    kernel wants (current token included) are ``cache.seq_lens``.
    """
    pos = cache.seq_lens                                       # [S]
    active = jnp.asarray(active, bool)
    out = extend_slots(cache, active, jnp.ones((cache.max_slots,),
                                               jnp.int32))
    tbl_idx = jnp.clip(pos // cache.block_size, 0,
                       cache.max_blocks_per_seq - 1)
    block_ids = jnp.where(
        active,
        jnp.take_along_axis(out.block_tables, tbl_idx[:, None], 1)[:, 0],
        cache.num_blocks).astype(jnp.int32)
    offsets = (pos % cache.block_size).astype(jnp.int32)
    return out, block_ids, offsets


def append_layer(cache: PagedKVCache, layer: int, block_ids, offsets,
                 k_tok, v_tok) -> PagedKVCache:
    """Write K/V rows for ``layer`` at reserved positions. k_tok/v_tok:
    [n, n_kv_heads, head_dim] with block_ids/offsets [n] — one row per
    decode slot (alloc_decode_blocks) OR per packed ragged query row
    (the unified serving step); rows whose block_id is the drop target
    write nothing. On the int8 variant each row quantizes at its own
    per-(token, head) absmax scale (kv_quantize) and the scale sidecar
    scatters with the payload."""
    if is_quantized(cache):
        kq, ks = kv_quantize(k_tok)
        vq, vs = kv_quantize(v_tok)
        return cache._replace(
            k_pool=cache.k_pool.at[layer, block_ids, offsets].set(
                kq, mode="drop"),
            v_pool=cache.v_pool.at[layer, block_ids, offsets].set(
                vq, mode="drop"),
            k_scale=cache.k_scale.at[layer, block_ids, offsets].set(
                ks, mode="drop"),
            v_scale=cache.v_scale.at[layer, block_ids, offsets].set(
                vs, mode="drop"),
        )
    return cache._replace(
        k_pool=cache.k_pool.at[layer, block_ids, offsets].set(
            k_tok.astype(cache.k_pool.dtype), mode="drop"),
        v_pool=cache.v_pool.at[layer, block_ids, offsets].set(
            v_tok.astype(cache.v_pool.dtype), mode="drop"),
    )


# ---------------------------------------------------------------------------
# host-side prefix index (hash -> resident block id)
# ---------------------------------------------------------------------------

class PrefixIndex:
    """Content-addressed index of FULL resident pages: chain hash of
    block-sized token runs -> pool block id. Host-side plain python (the
    scheduler consults it at admission; no device work).

    The hash of block i covers the WHOLE prompt prefix through block i
    (h_i = hash(h_{i-1}, tokens of block i)), so a match is always a
    contiguous prefix and two different prefixes never alias onto the
    same chain entry. Only full blocks are indexed — a partial page's
    tail bytes belong to one sequence only (cow_append covers the day
    partial sharing is added).

    Refcount contract: every indexed block id carries ONE device
    refcount held by the index (the engine retains newly inserted ids
    before freeing their slot, and releases evicted ids). ``evict``
    drops least-recently-matched entries first; evicting a chain's
    parent strands its children (match() walks from the root), which is
    accepted — children age out by the same LRU.
    """

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self._chain: "OrderedDict[int, int]" = OrderedDict()  # hash -> id
        self._holds: dict = {}                                # id -> hash

    def __len__(self) -> int:
        return len(self._chain)

    def holds(self, block_id: int) -> bool:
        """True while the index carries a refcount on ``block_id``."""
        return int(block_id) in self._holds

    def held_ids(self) -> dict:
        """{block_id: 1} for every page the index holds — the
        ``index_refs`` argument check_invariants wants."""
        return {bid: 1 for bid in self._holds}

    def _hashes(self, tokens: Sequence[int]) -> List[int]:
        bs = self.block_size
        h = 0
        out = []
        for i in range(len(tokens) // bs):
            h = hash((h, tuple(tokens[i * bs:(i + 1) * bs])))
            out.append(h)
        return out

    def match(self, tokens: Sequence[int]) -> List[int]:
        """Longest indexed full-block prefix of ``tokens`` -> resident
        block ids (possibly empty). Touches matched entries (LRU)."""
        ids = []
        for h in self._hashes(tokens):
            bid = self._chain.get(h)
            if bid is None:
                break
            self._chain.move_to_end(h)
            ids.append(bid)
        return ids

    def insert(self, tokens: Sequence[int],
               block_ids: Sequence[int]) -> List[int]:
        """Index the full-block chain of ``tokens`` resident at
        ``block_ids`` (the sequence's table prefix, in order). Returns
        the ids NEWLY indexed — the caller must retain exactly these on
        device. Chains already present (a concurrent duplicate wrote the
        same content elsewhere) keep their existing block; the
        duplicate's pages simply free with its slot."""
        new = []
        for h, bid in zip(self._hashes(tokens), block_ids):
            if h in self._chain:
                self._chain.move_to_end(h)
                continue
            self._chain[h] = int(bid)
            self._holds[int(bid)] = h
            new.append(int(bid))
        return new

    def evict(self, n: int, protect=frozenset()) -> List[int]:
        """Drop up to ``n`` least-recently-matched entries whose block id
        is not in ``protect`` (blocks an in-flight admission is about to
        share must keep their hold until the device share lands);
        returns the evicted block ids — the caller must release exactly
        these on device."""
        out = []
        for h in list(self._chain):
            if len(out) >= n:
                break
            bid = self._chain[h]
            if bid in protect:
                continue
            del self._chain[h]
            self._holds.pop(bid, None)
            out.append(bid)
        return out


# ---------------------------------------------------------------------------
# invariant check (tests / debugging — host side)
# ---------------------------------------------------------------------------

def check_invariants(cache: PagedKVCache,
                     index_refs: Optional[Mapping[int, int]] = None) -> None:
    """Assert the pool accounting is consistent under sharing: every
    block reachable from a block table has refcount >= 1, freed
    (unreferenced) blocks have refcount exactly 0, and — with the
    prefix index's holds supplied as ``index_refs`` ({block_id: count},
    or any iterable of held ids) — every block's refcount EQUALS its
    table references plus index holds, so a refcount leak fails fast in
    tests instead of silently shrinking pool capacity. Host-side
    (concrete arrays) — test helper, not a jit citizen."""
    import numpy as np

    tables = np.asarray(cache.block_tables)
    nblk = np.asarray(cache.n_blocks)
    rc = np.asarray(cache.refcount)
    lens = np.asarray(cache.seq_lens)
    nb = cache.num_blocks
    table_refs = np.zeros(nb, np.int64)
    for s in range(cache.max_slots):
        row = tables[s, : nblk[s]]
        assert row.size == 0 or (0 <= row.min() and row.max() < nb), (
            f"slot {s}: table ids {row.tolist()} out of pool range {nb}")
        np.add.at(table_refs, row, 1)
        assert lens[s] <= nblk[s] * cache.block_size, (
            f"slot {s}: {lens[s]} tokens exceed {nblk[s]} blocks")
    expected = table_refs.copy()
    if index_refs is not None:
        items = (index_refs.items() if hasattr(index_refs, "items")
                 else ((b, 1) for b in index_refs))
        for b, n in items:
            expected[int(b)] += int(n)
    assert (rc >= 0).all(), f"negative refcounts: {np.flatnonzero(rc < 0)}"
    bad = np.flatnonzero((table_refs > 0) & (rc < 1))
    assert bad.size == 0, (
        f"blocks {bad.tolist()} reachable from a block table with "
        f"refcount 0")
    bad = np.flatnonzero(rc != expected)
    assert bad.size == 0, (
        "refcount leak: blocks "
        f"{[(int(b), int(rc[b]), int(expected[b])) for b in bad[:8]]} "
        "(id, refcount, table+index refs) disagree")
