"""Block-paged KV cache — a fixed-pool pytree with pure-functional ops.

The serving memory model of "Ragged Paged Attention" (arxiv 2604.15464)
and vLLM: K/V for all sequences live in ONE fixed pool of fixed-size
blocks ("pages"), and each sequence maps its logical positions to pool
blocks through a block table. Admission/eviction then move block IDS, not
KV bytes, and memory fragmentation is bounded by one partial block per
sequence.

Layout (the whole cache is a NamedTuple pytree — it jits, donates, and
shards like any train state):

    k_pool / v_pool  [layers, num_blocks, block_size, n_kv_heads, head_dim]
    block_tables     [max_slots, max_blocks_per_seq] int32 (pool block ids;
                     entries past n_blocks[slot] are meaningless and kept 0)
    n_blocks         [max_slots] int32  — blocks assigned per slot
    seq_lens         [max_slots] int32  — tokens written per slot
    free             [num_blocks] bool  — pool free map (True = free)

The per-layer pool slice ``k_pool[l]`` is exactly the
``[num_blocks, block_size, n_kv_heads, head_dim]`` operand
ops/paged_attention.py consumes. Sharding (pspecs()): KV heads ride the
TP axis — the same head split as the training tensor-parallel layers, so
TP-sharded decode reuses the training weight layout — and the pool's
block axis can ride the data axis (each data rank serves its own
requests from its own pool shard; inside shard_map all ops here are
rank-local).

Every mutator is pure (returns a new cache) and built from lax/scatter
ops only, so the whole serving step — allocate, append, attend, free —
jits as one program. Out-of-range scatters use mode="drop" as the
masking mechanism for inactive slots (index ``num_blocks`` is the
designated drop target). Callers keep the pool from overflowing via the
scheduler's free-block watermark; ``alloc_decode_blocks`` on an empty
pool is a documented invariant violation (it would corrupt block 0), so
the engine checks ``free_block_count`` before every decode step.

Env defaults (docs/serving.md): APEX_TPU_PAGED_BLOCK_SIZE (block_size,
default 16), APEX_TPU_SERVING_MAX_SLOTS (max_slots, default 8) — read by
serving/engine.py, not here; this module is explicit-arguments-only.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class PagedKVCache(NamedTuple):
    k_pool: jax.Array       # [L, N, bs, Hkv, D]
    v_pool: jax.Array       # [L, N, bs, Hkv, D]
    block_tables: jax.Array  # [max_slots, max_blocks_per_seq] int32
    n_blocks: jax.Array     # [max_slots] int32
    seq_lens: jax.Array     # [max_slots] int32
    free: jax.Array         # [N] bool

    # -- static views ------------------------------------------------
    @property
    def num_blocks(self) -> int:
        return self.k_pool.shape[1]

    @property
    def block_size(self) -> int:
        return self.k_pool.shape[2]

    @property
    def max_slots(self) -> int:
        return self.block_tables.shape[0]

    @property
    def max_blocks_per_seq(self) -> int:
        return self.block_tables.shape[1]


def paged_kv_cache(layers: int, num_blocks: int, block_size: int,
                   n_kv_heads: int, head_dim: int, max_slots: int,
                   max_blocks_per_seq: Optional[int] = None,
                   dtype=jnp.bfloat16) -> PagedKVCache:
    """A fresh cache: empty pool, zeroed tables, everything free."""
    if max_blocks_per_seq is None:
        max_blocks_per_seq = num_blocks
    shape = (layers, num_blocks, block_size, n_kv_heads, head_dim)
    return PagedKVCache(
        k_pool=jnp.zeros(shape, dtype),
        v_pool=jnp.zeros(shape, dtype),
        block_tables=jnp.zeros((max_slots, max_blocks_per_seq), jnp.int32),
        n_blocks=jnp.zeros((max_slots,), jnp.int32),
        seq_lens=jnp.zeros((max_slots,), jnp.int32),
        free=jnp.ones((num_blocks,), bool),
    )


def cache_pspecs(tp_axis: Optional[str] = "model",
                 data_axis: Optional[str] = None) -> PagedKVCache:
    """PartitionSpecs for shard_map in/out specs: KV heads on the TP axis
    (kv_heads % tp == 0, same contract as the GQA column split in
    testing/standalone_transformer.py), and — when ``data_axis`` is given
    — pool blocks, tables and accounting over the data axis (per-rank
    request sets; block ids are rank-local)."""
    return PagedKVCache(
        k_pool=P(None, data_axis, None, tp_axis, None),
        v_pool=P(None, data_axis, None, tp_axis, None),
        block_tables=P(data_axis),
        n_blocks=P(data_axis),
        seq_lens=P(data_axis),
        free=P(data_axis),
    )


def blocks_needed(n_tokens: int, block_size: int) -> int:
    """Pool blocks covering ``n_tokens`` (host-side scheduler arithmetic)."""
    return int(math.ceil(max(int(n_tokens), 0) / block_size))


def free_block_count(cache: PagedKVCache):
    return jnp.sum(cache.free.astype(jnp.int32))


# ---------------------------------------------------------------------------
# allocate / free
# ---------------------------------------------------------------------------

def allocate_slot(cache: PagedKVCache, slot, n_blocks) -> PagedKVCache:
    """Assign the first ``n_blocks`` free pool blocks to ``slot`` (its
    whole table row is replaced; seq_len resets to 0). ``n_blocks`` may be
    traced; the caller guarantees ``n_blocks <= free_block_count`` and
    ``n_blocks <= max_blocks_per_seq`` (scheduler admission)."""
    mb = cache.max_blocks_per_seq
    nb_pool = cache.num_blocks
    # free blocks first, in index order (stable sort of the "taken" flag)
    order = jnp.argsort(jnp.logical_not(cache.free), stable=True)
    take = order[:mb]
    if mb > nb_pool:  # tiny pools: pad with the drop target
        take = jnp.concatenate(
            [take, jnp.full((mb - nb_pool,), nb_pool, take.dtype)])
    lane = jnp.arange(mb) < n_blocks
    row = jnp.where(lane, take, 0).astype(jnp.int32)
    free = cache.free.at[jnp.where(lane, take, nb_pool)].set(
        False, mode="drop")
    return cache._replace(
        block_tables=cache.block_tables.at[slot].set(row),
        n_blocks=cache.n_blocks.at[slot].set(
            jnp.asarray(n_blocks, jnp.int32)),
        seq_lens=cache.seq_lens.at[slot].set(0),
        free=free,
    )


def free_slot(cache: PagedKVCache, slot) -> PagedKVCache:
    """Return ``slot``'s blocks to the pool and clear its row. Idempotent
    (a slot with n_blocks == 0 frees nothing)."""
    mb = cache.max_blocks_per_seq
    lane = jnp.arange(mb) < cache.n_blocks[slot]
    ids = jnp.where(lane, cache.block_tables[slot], cache.num_blocks)
    return cache._replace(
        block_tables=cache.block_tables.at[slot].set(
            jnp.zeros((mb,), jnp.int32)),
        n_blocks=cache.n_blocks.at[slot].set(0),
        seq_lens=cache.seq_lens.at[slot].set(0),
        free=cache.free.at[ids].set(True, mode="drop"),
    )


# ---------------------------------------------------------------------------
# prefill write
# ---------------------------------------------------------------------------

def write_prefill(cache: PagedKVCache, slot, k, v, length) -> PagedKVCache:
    """Scatter a prefill's K/V into ``slot``'s assigned pages and set its
    length. k/v: [layers, t_pad, n_kv_heads, head_dim] (the fixed padded
    prefill shape); rows at positions >= ``length`` are dropped. The slot
    must hold >= ceil(length / block_size) blocks (allocate_slot)."""
    t_pad = k.shape[1]
    bs = cache.block_size
    pos = jnp.arange(t_pad)
    tbl_idx = jnp.clip(pos // bs, 0, cache.max_blocks_per_seq - 1)
    blocks = cache.block_tables[slot][tbl_idx]                # [t_pad]
    valid = pos < length
    blocks = jnp.where(valid, blocks, cache.num_blocks)       # drop target
    offs = pos % bs
    return cache._replace(
        k_pool=cache.k_pool.at[:, blocks, offs].set(
            k.astype(cache.k_pool.dtype), mode="drop"),
        v_pool=cache.v_pool.at[:, blocks, offs].set(
            v.astype(cache.v_pool.dtype), mode="drop"),
        seq_lens=cache.seq_lens.at[slot].set(
            jnp.asarray(length, jnp.int32)),
    )


# ---------------------------------------------------------------------------
# decode append
# ---------------------------------------------------------------------------

def alloc_decode_blocks(cache: PagedKVCache, active):
    """Reserve this decode step's token position for every active slot,
    growing block tables where the position opens a new page.

    active: [max_slots] bool. Returns (cache, block_ids, offsets) where
    block_ids/offsets [max_slots] locate each active slot's NEW token
    (inactive slots get the drop target ``num_blocks``); seq_lens of
    active slots are already incremented, so the lengths the paged
    kernel wants (current token included) are ``cache.seq_lens``.

    Growth walks slots with a scan (max_slots is small and static),
    handing each needy slot the first free block — callers keep
    ``free_block_count >= popcount(need)`` via the admission watermark.
    """
    pos = cache.seq_lens                                       # [S]
    need = active & (pos // cache.block_size >= cache.n_blocks) \
        & (cache.n_blocks < cache.max_blocks_per_seq)

    def body(carry, s):
        free, tables, nblk = carry
        blk = jnp.argmax(free).astype(jnp.int32)               # first free
        grow = need[s]
        free = free.at[blk].set(jnp.where(grow, False, free[blk]))
        tables = tables.at[s, jnp.clip(nblk[s], 0,
                                       cache.max_blocks_per_seq - 1)].set(
            jnp.where(grow, blk, tables[s, jnp.clip(
                nblk[s], 0, cache.max_blocks_per_seq - 1)]))
        nblk = nblk.at[s].add(jnp.where(grow, 1, 0))
        return (free, tables, nblk), None

    (free, tables, nblk), _ = jax.lax.scan(
        body, (cache.free, cache.block_tables, cache.n_blocks),
        jnp.arange(cache.max_slots))
    tbl_idx = jnp.clip(pos // cache.block_size, 0,
                       cache.max_blocks_per_seq - 1)
    block_ids = jnp.where(
        active, jnp.take_along_axis(tables, tbl_idx[:, None], 1)[:, 0],
        cache.num_blocks).astype(jnp.int32)
    offsets = (pos % cache.block_size).astype(jnp.int32)
    return cache._replace(
        block_tables=tables, n_blocks=nblk, free=free,
        seq_lens=pos + active.astype(jnp.int32),
    ), block_ids, offsets


def append_layer(cache: PagedKVCache, layer: int, block_ids, offsets,
                 k_tok, v_tok) -> PagedKVCache:
    """Write one decode token's K/V for ``layer`` at the positions
    alloc_decode_blocks reserved. k_tok/v_tok: [max_slots, n_kv_heads,
    head_dim]; slots whose block_id is the drop target write nothing."""
    return cache._replace(
        k_pool=cache.k_pool.at[layer, block_ids, offsets].set(
            k_tok.astype(cache.k_pool.dtype), mode="drop"),
        v_pool=cache.v_pool.at[layer, block_ids, offsets].set(
            v_tok.astype(cache.v_pool.dtype), mode="drop"),
    )


# ---------------------------------------------------------------------------
# invariant check (tests / debugging — host side)
# ---------------------------------------------------------------------------

def check_invariants(cache: PagedKVCache) -> None:
    """Assert the pool accounting is consistent: assigned blocks are
    distinct, none of them is marked free, and every unassigned block is
    free. Host-side (concrete arrays) — test helper, not a jit citizen."""
    import numpy as np

    tables = np.asarray(cache.block_tables)
    nblk = np.asarray(cache.n_blocks)
    free = np.asarray(cache.free)
    lens = np.asarray(cache.seq_lens)
    assigned: list = []
    for s in range(cache.max_slots):
        row = tables[s, : nblk[s]]
        assigned.extend(row.tolist())
        assert lens[s] <= nblk[s] * cache.block_size, (
            f"slot {s}: {lens[s]} tokens exceed {nblk[s]} blocks")
    assert len(assigned) == len(set(assigned)), (
        f"double-assigned pool blocks: {sorted(assigned)}")
    for b in assigned:
        assert not free[b], f"assigned block {b} marked free"
    assert len(assigned) + int(free.sum()) == cache.num_blocks, (
        "pool accounting leak: "
        f"{len(assigned)} assigned + {int(free.sum())} free "
        f"!= {cache.num_blocks}")
