"""apex_tpu.serving — TPU-native inference serving.

Three layers (docs/serving.md):

- ``kv_cache``   — block-paged KV cache: one fixed pool of fixed-size
                   pages + per-sequence block tables, pure-functional
                   allocate/append/free (jits, donates, shards).
- ``scheduler``  — host-side continuous batching: free-block-watermark
                   admission, slot accounting, eviction.
- ``engine``     — two fixed-shape jitted programs (prefill + decode;
                   the decode path is the ragged paged-attention kernel,
                   ops/paged_attention.py) driven by the scheduler, with
                   optional tensor-parallel sharded weights reusing the
                   training layout.
"""

from apex_tpu.serving.engine import (  # noqa: F401
    ServingConfig,
    ServingEngine,
    greedy_reference,
)
from apex_tpu.serving.kv_cache import (  # noqa: F401
    PagedKVCache,
    alloc_decode_blocks,
    allocate_slot,
    append_layer,
    blocks_needed,
    cache_pspecs,
    check_invariants,
    free_block_count,
    free_slot,
    paged_kv_cache,
    write_prefill,
)
from apex_tpu.serving.scheduler import Request, Scheduler  # noqa: F401

__all__ = [
    "PagedKVCache", "Request", "Scheduler", "ServingConfig",
    "ServingEngine", "alloc_decode_blocks", "allocate_slot", "append_layer",
    "blocks_needed", "cache_pspecs", "check_invariants", "free_block_count",
    "free_slot", "greedy_reference", "paged_kv_cache", "write_prefill",
]
