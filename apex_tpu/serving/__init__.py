"""apex_tpu.serving — TPU-native inference serving.

Three layers (docs/serving.md):

- ``kv_cache``   — block-paged KV cache: one fixed pool of fixed-size
                   pages + per-sequence block tables + per-block
                   refcounts, pure-functional allocate/share/append/free
                   (jits, donates, shards), plus the host-side
                   PrefixIndex (block-content hash -> resident page).
- ``scheduler``  — host-side continuous batching: refcount-aware
                   free-block-watermark admission with prefix sharing,
                   chunked-prefill step planning under a fixed token
                   budget, slot accounting, eviction.
- ``engine``     — ONE fixed-shape jitted step (prefill chunks, decode
                   steps AND speculative verify windows packed through
                   the ragged multi-query paged-attention kernel,
                   ops/paged_attention.py) driven by the scheduler, with
                   optional tensor-parallel sharded weights reusing the
                   training layout.
- ``speculative`` — drafters for speculative decoding (host n-gram
                   prompt lookup, a small draft model over its own
                   paged pool, a forced-profile stub for benches):
                   propose K tokens, the unified step verifies them as
                   one ``query_len = K + 1`` run, greedy longest-prefix
                   acceptance keeps output bitwise identical to
                   non-speculative decode.
- ``fleet``      — the service layer over N engine replicas: SLO
                   classes (latency vs batch), a load-aware Router
                   (placement over live KV-occupancy / queue-depth /
                   estimated-work signals), preemption + requeue, and
                   replica fault tolerance with bitwise-identical
                   greedy recovery.
"""

from apex_tpu.serving.engine import (  # noqa: F401
    ServingConfig,
    ServingEngine,
    ServingSession,
    greedy_reference,
)
from apex_tpu.serving.fleet import (  # noqa: F401
    BATCH,
    LATENCY,
    FaultPlan,
    InjectedReplicaFault,
    Replica,
    ReplicaSignals,
    Router,
)
from apex_tpu.serving.kv_cache import (  # noqa: F401
    PagedKVCache,
    PrefixIndex,
    QuantPagedKVCache,
    alloc_decode_blocks,
    allocate_slot,
    append_layer,
    blocks_needed,
    cache_pspecs,
    check_invariants,
    cow_append,
    extend_slots,
    free_block_count,
    free_slot,
    grow_slots,
    is_quantized,
    kv_quantize,
    paged_kv_cache,
    quant_cache_pspecs,
    quantized_kv_cache,
    quantized_pool_blocks,
    release_blocks,
    retain_blocks,
    share_prefix,
    truncate_slots,
    write_prefill,
)
from apex_tpu.serving.scheduler import Request, Scheduler  # noqa: F401
from apex_tpu.serving.speculative import (  # noqa: F401
    Drafter,
    DraftModelDrafter,
    NgramDrafter,
    StubDrafter,
)

__all__ = [
    "BATCH", "Drafter", "DraftModelDrafter", "FaultPlan",
    "InjectedReplicaFault", "LATENCY", "NgramDrafter", "PagedKVCache",
    "PrefixIndex", "QuantPagedKVCache", "Replica", "ReplicaSignals",
    "Request", "Router", "Scheduler", "ServingConfig", "ServingEngine",
    "ServingSession", "StubDrafter", "alloc_decode_blocks",
    "allocate_slot", "append_layer", "blocks_needed", "cache_pspecs",
    "check_invariants", "cow_append", "extend_slots", "free_block_count",
    "free_slot", "greedy_reference", "grow_slots", "is_quantized",
    "kv_quantize", "paged_kv_cache", "quant_cache_pspecs",
    "quantized_kv_cache", "quantized_pool_blocks", "release_blocks",
    "retain_blocks", "share_prefix", "truncate_slots", "write_prefill",
]
