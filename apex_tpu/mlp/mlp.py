"""Fused MLP — ref: apex/mlp/mlp.py::MLP + csrc/mlp_cuda.cu.

The reference chains cuBLAS GEMM + bias + relu/sigmoid epilogues inside one
autograd Function to avoid per-layer kernel launches. On TPU, XLA fuses the
bias+activation epilogue into the MXU matmul automatically, so the idiomatic
implementation is a plain layer chain under jit — same API capability with
no hand scheduling.

Provided in two styles: a functional pair (`mlp_init`/`mlp_apply`) and a
flax module (:class:`MLP`).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

try:
    import flax.linen as nn

    _HAVE_FLAX = True
except ImportError:  # pragma: no cover
    _HAVE_FLAX = False


_ACTIVATIONS = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "gelu": jax.nn.gelu,
    "none": lambda x: x,
}


def mlp_init(key, sizes: Sequence[int], dtype=jnp.float32):
    """Init params for an MLP with layer widths ``sizes`` (in, h1, ..., out)."""
    params = {}
    keys = jax.random.split(key, len(sizes) - 1)
    for i, (k, din, dout) in enumerate(zip(keys, sizes[:-1], sizes[1:])):
        # match the reference's reset_parameters: uniform(-1/sqrt(fan_in), +)
        bound = 1.0 / jnp.sqrt(jnp.float32(din))
        params[f"layer_{i}"] = {
            "kernel": jax.random.uniform(k, (din, dout), dtype, -bound, bound),
            "bias": jnp.zeros((dout,), dtype),
        }
    return params


def mlp_apply(params, x, activation: str = "relu", use_bias: bool = True):
    """Forward through the layer chain; last layer has no activation
    (matching the reference MLP's semantics)."""
    act = _ACTIVATIONS[activation]
    n = len(params)
    for i in range(n):
        lp = params[f"layer_{i}"]
        x = x @ lp["kernel"]
        if use_bias:
            x = x + lp["bias"]
        if i < n - 1:
            x = act(x)
    return x


if _HAVE_FLAX:

    class MLP(nn.Module):
        """Flax module with the reference MLP's interface.

        ``mlp_sizes`` are layer widths including input; ``activation`` in
        {'relu', 'sigmoid', 'gelu', 'none'} (reference supports relu/sigmoid).
        """

        mlp_sizes: Sequence[int]
        bias: bool = True
        activation: str = "relu"
        dtype: object = jnp.float32

        @nn.compact
        def __call__(self, x):
            act = _ACTIVATIONS[self.activation]
            n = len(self.mlp_sizes) - 1
            for i, width in enumerate(self.mlp_sizes[1:]):
                x = nn.Dense(
                    width, use_bias=self.bias, dtype=self.dtype, name=f"layer_{i}"
                )(x)
                if i < n - 1:
                    x = act(x)
            return x
