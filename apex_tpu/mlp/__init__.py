from apex_tpu.mlp.mlp import MLP, mlp_apply, mlp_init  # noqa: F401
