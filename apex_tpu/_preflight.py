"""Per-kernel compile probes with automatic jnp fallback.

Every Pallas kernel family in apex_tpu has a numerically-equivalent jnp
path (the test oracle). ``preflight()`` compiles and runs a tiny instance
of each family ON THE ACTUAL DEVICE, checks it loosely against the oracle,
and pins any failing family to the jnp path via the registry in
``ops/_utils.py``. A single broken kernel then costs a log line and a few
percent of speed for that one op — never the whole train step (round-2
lesson: one bad LayerNorm block spec zeroed the only hardware benchmark
of the round).

Usage::

    import apex_tpu
    report = apex_tpu.preflight()          # probe all families
    # report = {"layer_norm": {"ok": True, "ms": 812.0}, ...}

The probes intentionally use small-but-aligned shapes (hidden a multiple
of 128, seq a multiple of the flash block) so compile time dominates and
the persistent compilation cache makes reruns cheap.
"""

from __future__ import annotations

import contextlib
import os
import time
import traceback
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops._utils import disable_kernel, enable_kernel


def _maxdiff(a, b) -> float:
    return float(
        jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))
    )


def _probe_layer_norm() -> None:
    from apex_tpu.ops.layer_norm import layer_norm_affine

    x = jax.random.normal(jax.random.PRNGKey(0), (4, 96, 256), jnp.bfloat16)
    g = jnp.ones((256,), jnp.float32)
    b = jnp.zeros((256,), jnp.float32)
    dy = jax.random.normal(jax.random.PRNGKey(1), x.shape, x.dtype)

    def f(x, g, b, use):
        y = layer_norm_affine(x, g, b, 1e-5, use)
        return jnp.vdot(y.astype(jnp.float32), dy.astype(jnp.float32))

    gp = jax.jit(jax.grad(lambda x, g, b: f(x, g, b, True), argnums=(0, 1, 2)))(x, g, b)
    gr = jax.jit(jax.grad(lambda x, g, b: f(x, g, b, False), argnums=(0, 1, 2)))(x, g, b)
    for a, c in zip(gp, gr):
        assert _maxdiff(a, c) < 0.1, "layer_norm grad mismatch vs oracle"


def _probe_rms_norm() -> None:
    from apex_tpu.ops.layer_norm import rms_norm_affine

    x = jax.random.normal(jax.random.PRNGKey(0), (4, 96, 256), jnp.bfloat16)
    g = jnp.ones((256,), jnp.float32)
    dy = jax.random.normal(jax.random.PRNGKey(1), x.shape, x.dtype)

    def f(x, g, use):
        y = rms_norm_affine(x, g, 1e-5, use)
        return jnp.vdot(y.astype(jnp.float32), dy.astype(jnp.float32))

    gp = jax.jit(jax.grad(lambda x, g: f(x, g, True), argnums=(0, 1)))(x, g)
    gr = jax.jit(jax.grad(lambda x, g: f(x, g, False), argnums=(0, 1)))(x, g)
    for a, c in zip(gp, gr):
        assert _maxdiff(a, c) < 0.1, "rms_norm grad mismatch vs oracle"


@contextlib.contextmanager
def _pinned_env(name: str, value):
    """Pin ``name`` to ``value`` for the probe's duration (``None`` =
    unset, so the probe sees the library DEFAULT, not an inherited
    operator override)."""
    old = os.environ.get(name)
    if value is None:
        os.environ.pop(name, None)
    else:
        os.environ[name] = value
    try:
        yield
    finally:
        if old is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = old


def _probe_flash_attention() -> None:
    # pin the RESIDENT kernels: an inherited APEX_TPU_FLASH_STREAM=1 would
    # route this probe through the streaming kernels, and their failure
    # must not pin off the (independent) short-seq family
    with _pinned_env("APEX_TPU_FLASH_STREAM", "0"):
        _probe_flash_attention_resident()


def _probe_flash_attention_resident() -> None:
    from apex_tpu.ops.attention import flash_attention

    q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 256, 64), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 256, 64), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 2, 256, 64), jnp.bfloat16)
    do = jax.random.normal(jax.random.PRNGKey(3), q.shape, q.dtype)
    bias = jax.random.normal(jax.random.PRNGKey(4), (1, 2, 256, 256), jnp.float32)

    for causal, bs in ((True, None), (False, bias)):
        def f(q, k, v, use):
            y = flash_attention(q, k, v, bias=bs, causal=causal, use_pallas=use)
            return jnp.vdot(y.astype(jnp.float32), do.astype(jnp.float32))

        gp = jax.jit(jax.grad(lambda q, k, v: f(q, k, v, True), argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(lambda q, k, v: f(q, k, v, False), argnums=(0, 1, 2)))(q, k, v)
        for a, c in zip(gp, gr):
            assert _maxdiff(a, c) < 0.1, "flash_attention grad mismatch vs oracle"

    # the production default block is sequence-dependent (512 at s<=2048);
    # probe it at a MULTI-block shape (s=1024 -> 2x2 grid of 512-blocks) so
    # the default path's cross-block machinery is validated, not just the
    # single-block degenerate case above. An inherited operator override
    # (e.g. APEX_TPU_FLASH_BLOCK=1024) would collapse this back to a 1x1
    # grid — unset it so the probe sees the true default.
    _probe_flash_default_block()


def _probe_flash_default_block() -> None:
    from apex_tpu.ops.attention import flash_attention

    q = jax.random.normal(jax.random.PRNGKey(5), (1, 2, 1024, 64), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(6), (1, 2, 1024, 64), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(7), (1, 2, 1024, 64), jnp.bfloat16)
    do = jax.random.normal(jax.random.PRNGKey(8), q.shape, q.dtype)

    def g(q, k, v, use):
        y = flash_attention(q, k, v, causal=True, use_pallas=use)
        return jnp.vdot(y.astype(jnp.float32), do.astype(jnp.float32))

    with _pinned_env("APEX_TPU_FLASH_BLOCK", None):
        gp = jax.jit(jax.grad(lambda q, k, v: g(q, k, v, True),
                              argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(lambda q, k, v: g(q, k, v, False),
                              argnums=(0, 1, 2)))(q, k, v)
    for a, c in zip(gp, gr):
        assert _maxdiff(a, c) < 0.1, \
            "flash_attention default-block grad mismatch vs oracle"


def _probe_optim_flat() -> None:
    from apex_tpu.ops.pallas_optim import adam_flat, l2norm_flat, lamb_phase1_flat

    n = 4099
    g = jax.random.normal(jax.random.PRNGKey(0), (n,), jnp.float32)
    p = jax.random.normal(jax.random.PRNGKey(1), (n,), jnp.float32)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)

    # jnp oracle for one Adam step (bias-corrected, decoupled decay)
    b1, b2, eps, lr, wd = 0.9, 0.999, 1e-8, 1e-3, 0.01
    m_r = (1 - b1) * g
    v_r = (1 - b2) * g * g
    u_r = (m_r / (1 - b1)) / (jnp.sqrt(v_r / (1 - b2)) + eps) + wd * p
    p_r = p - lr * u_r

    p_n, m_n, v_n = adam_flat(g, p, m, v, lr=lr, beta1=b1, beta2=b2,
                              eps=eps, step=1, weight_decay=wd)
    assert _maxdiff(p_n, p_r) < 1e-5, "adam_flat params mismatch vs oracle"
    assert _maxdiff(m_n, m_r) < 1e-6, "adam_flat exp_avg mismatch vs oracle"
    assert _maxdiff(v_n, v_r) < 1e-6, "adam_flat exp_avg_sq mismatch vs oracle"

    u, m_l, v_l = lamb_phase1_flat(g, p, m, v, beta1=b1, beta2=b2, eps=eps,
                                   step=1, weight_decay=wd)
    assert _maxdiff(u, u_r) < 1e-4, "lamb_phase1_flat update mismatch vs oracle"
    assert _maxdiff(m_l, m_r) < 1e-6, "lamb_phase1_flat exp_avg mismatch"

    nrm = l2norm_flat(g)
    ref = jnp.sqrt(jnp.sum(g * g))
    assert abs(float(nrm) - float(ref)) / float(ref) < 1e-5, "l2norm mismatch"


def _probe_flash_attention_stream() -> None:
    """The long-sequence streaming kernels (3-D grid + VMEM scratch).

    Probed at shapes with MULTIPLE blocks per grid axis (nq, nk >= 2), so
    the streaming-specific machinery — cross-step scratch accumulation,
    online-softmax rescale across revisits, causal block skip, revisited
    output copy-out, and the broadcast-bias (mask) spec branch — actually
    lowers and is value-checked. On failure only the streaming path is
    pinned off; short-seq flash keeps its kernels.

    Block size is pinned to 256 here: the production default is sequence-
    dependent (512 at these probe shapes), which would collapse the grids
    to a single block and let a regression in the multi-block machinery
    slip past the probe."""
    from apex_tpu.ops.attention import flash_attention

    with _pinned_env("APEX_TPU_FLASH_STREAM", "1"), \
            _pinned_env("APEX_TPU_FLASH_BLOCK", "256"):
        for (sq, sk), causal, masked in (
            ((512, 512), True, False),   # causal, 2x2 blocks, skip branch
            ((384, 640), False, True),   # ragged cross-attn + mask branch
        ):
            q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, sq, 64),
                                  jnp.bfloat16)
            k = jax.random.normal(jax.random.PRNGKey(1), (1, 2, sk, 64),
                                  jnp.bfloat16)
            v = jax.random.normal(jax.random.PRNGKey(2), (1, 2, sk, 64),
                                  jnp.bfloat16)
            do = jax.random.normal(jax.random.PRNGKey(3), q.shape, q.dtype)
            mask = (
                jnp.zeros((1, 1, 1, sk), bool).at[..., sk - 40:].set(True)
                if masked else None
            )

            def f(q, k, v, use, causal=causal, mask=mask, do=do):
                y = flash_attention(q, k, v, mask=mask, causal=causal,
                                    use_pallas=use)
                return jnp.vdot(y.astype(jnp.float32),
                                do.astype(jnp.float32))

            gp = jax.jit(jax.grad(
                lambda q, k, v: f(q, k, v, True), argnums=(0, 1, 2)))(q, k, v)
            gr = jax.jit(jax.grad(
                lambda q, k, v: f(q, k, v, False), argnums=(0, 1, 2)))(q, k, v)
            for a, c in zip(gp, gr):
                assert _maxdiff(a, c) < 0.1, \
                    "flash_attention_stream grad mismatch vs oracle"


def _probe_flash_attention_dropout() -> None:
    """Fused-dropout flash kernels (counter-RNG mask) — BOTH the resident
    fwd+fused-bwd pair and the streaming 3-D-grid family.

    The jnp fallback draws the SAME threefry bits (block_rng.keep_full),
    so this is an exact-mask grad parity check, not a statistical one. On
    failure only the dropout family pins to jnp — dropout-free flash
    keeps its kernels."""
    from apex_tpu.ops.attention import flash_attention

    rng = jax.random.PRNGKey(17)
    # 256 for the resident leg; 512 for the streaming leg so BOTH grid
    # axes have >= 2 blocks at the PINNED block 256 (the production
    # default is sequence-dependent and would make these single-block) —
    # nonzero keep_block coordinate offsets and scratch-revisit
    # interaction actually lower, same reasoning as
    # _probe_flash_attention_stream's shapes
    for stream, seq in (("0", 256), ("1", 512)):
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, seq, 64),
                              jnp.bfloat16)
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 2, seq, 64),
                              jnp.bfloat16)
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 2, seq, 64),
                              jnp.bfloat16)
        do = jax.random.normal(jax.random.PRNGKey(3), q.shape, q.dtype)

        def f(q, k, v, use, do=do):
            y = flash_attention(q, k, v, causal=True, dropout_p=0.2,
                                dropout_rng=rng, use_pallas=use)
            return jnp.vdot(y.astype(jnp.float32), do.astype(jnp.float32))

        with _pinned_env("APEX_TPU_FLASH_STREAM", stream), \
                _pinned_env("APEX_TPU_FLASH_BLOCK", "256"):
            gp = jax.jit(jax.grad(lambda q, k, v: f(q, k, v, True),
                                  argnums=(0, 1, 2)))(q, k, v)
            gr = jax.jit(jax.grad(lambda q, k, v: f(q, k, v, False),
                                  argnums=(0, 1, 2)))(q, k, v)
            for a, c in zip(gp, gr):
                assert _maxdiff(a, c) < 0.1, (
                    "flash_attention_dropout grad mismatch vs oracle "
                    f"(stream={stream})")


def _probe_paged_attention() -> None:
    """Decode kernel vs the gather oracle on a tiny ragged paged batch
    (GQA group 2, partial last pages, one empty slot)."""
    from apex_tpu.ops.paged_attention import (
        paged_attention,
        paged_attention_ref,
    )

    nb, bs, hkv, d, slots, maxb = 16, 8, 2, 128, 4, 3
    k_pool = jax.random.normal(jax.random.PRNGKey(0), (nb, bs, hkv, d),
                               jnp.bfloat16)
    v_pool = jax.random.normal(jax.random.PRNGKey(1), (nb, bs, hkv, d),
                               jnp.bfloat16)
    q = jax.random.normal(jax.random.PRNGKey(2), (slots, 2 * hkv, d),
                          jnp.bfloat16)
    tables = jax.random.permutation(
        jax.random.PRNGKey(3), nb)[: slots * maxb].reshape(slots, maxb)
    lengths = jnp.array([bs * maxb, 1, 0, bs + 3], jnp.int32)
    with _pinned_env("APEX_TPU_PAGED_BLOCK_ROWS", None), \
            _pinned_env("APEX_TPU_PAGED_KV_FETCH", None):
        got = jax.jit(lambda *a: paged_attention(*a, use_pallas=True))(
            q, k_pool, v_pool, tables, lengths)
        ref = paged_attention_ref(q, k_pool, v_pool, tables, lengths)
    assert _maxdiff(got, ref) < 0.1, "paged_attention mismatch vs oracle"


def _probe_grouped_matmul() -> None:
    """Ragged grouped matmul vs the segment oracle (skewed groups incl.
    an empty one), forward and custom_vjp grads — the dropless-MoE
    dispatch kernel (ops/grouped_matmul.py)."""
    from apex_tpu.ops.grouped_matmul import gmm

    t, e, h, f = 192, 4, 128, 256
    lhs = jax.random.normal(jax.random.PRNGKey(0), (t, h), jnp.bfloat16)
    rhs = jax.random.normal(jax.random.PRNGKey(1), (e, h, f), jnp.bfloat16)
    do = jax.random.normal(jax.random.PRNGKey(2), (t, f), jnp.bfloat16)
    group_sizes = jnp.array([100, 0, 57, 35], jnp.int32)

    def loss(lhs, rhs, use):
        y = gmm(lhs, rhs, group_sizes, use_pallas=use)
        return jnp.vdot(y.astype(jnp.float32), do.astype(jnp.float32))

    with _pinned_env("APEX_TPU_MOE_TILE_T", None), \
            _pinned_env("APEX_TPU_MOE_TILE_F", None):
        gp = jax.jit(jax.grad(lambda l, r: loss(l, r, True),
                              argnums=(0, 1)))(lhs, rhs)
        gr = jax.grad(lambda l, r: loss(l, r, False),
                      argnums=(0, 1))(lhs, rhs)
    for a, c in zip(gp, gr):
        assert _maxdiff(a, c) < 0.1, "grouped_matmul grad mismatch vs oracle"


def _probe_quant_matmul() -> None:
    """Blockwise-scaled quantized matmul vs the dequantize-einsum
    oracle over the SAME payloads (int8 + fp8 widths), forward and
    custom_vjp grads — the low-precision compute kernel
    (quantization/scaled_matmul.py)."""
    from apex_tpu.quantization import quant_matmul

    m, k, n = 192, 200, 160
    lhs = jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.float32)
    rhs = jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.float32)
    do = jax.random.normal(jax.random.PRNGKey(2), (m, n), jnp.float32)

    with _pinned_env("APEX_TPU_QUANT_TILE_M", None), \
            _pinned_env("APEX_TPU_QUANT_TILE_N", None), \
            _pinned_env("APEX_TPU_QUANT_TILE_K", None):
        for qdtype in ("int8", "fp8"):
            def loss(lhs, rhs, use, qdtype=qdtype):
                y = quant_matmul(lhs, rhs, dtype=qdtype, use_pallas=use)
                return jnp.vdot(y, do)

            gp = jax.jit(jax.grad(lambda l, r: loss(l, r, True),
                                  argnums=(0, 1)))(lhs, rhs)
            gr = jax.grad(lambda l, r: loss(l, r, False),
                          argnums=(0, 1))(lhs, rhs)
            for a, c in zip(gp, gr):
                assert _maxdiff(a, c) < 0.1, (
                    f"quant_matmul grad mismatch vs oracle ({qdtype})")


# family name (as consulted by default_use_pallas) -> probe
PROBES: Dict[str, Callable[[], None]] = {
    "layer_norm": _probe_layer_norm,
    "rms_norm": _probe_rms_norm,
    "flash_attention": _probe_flash_attention,
    "flash_attention_stream": _probe_flash_attention_stream,
    "flash_attention_dropout": _probe_flash_attention_dropout,
    "paged_attention": _probe_paged_attention,
    "grouped_matmul": _probe_grouped_matmul,
    "quant_matmul": _probe_quant_matmul,
    "optim_flat": _probe_optim_flat,
}


def preflight(
    kernels: Optional[list] = None,
    verbose: bool = True,
) -> Dict[str, dict]:
    """Compile-probe each Pallas kernel family; disable failures.

    Returns ``{family: {"ok": bool, "ms": float, "error": str|None}}``.
    Families that fail are pinned to their jnp fallback for the rest of the
    process (``use_pallas=None`` call sites); an explicit ``use_pallas=True``
    still forces the kernel.
    """
    # Pin the RESOLVED tune DB for the whole probe pass: each probe then
    # compile-checks exactly the kernel configs production will consult
    # (snapshot + user cache — or the empty DB when APEX_TPU_TUNE=0 has
    # disabled the cache, since pinning bypasses that check in lookup()),
    # and a concurrent autotune write or cache reload cannot shift configs
    # mid-probe. Probes that need the pure defaults additionally unset the
    # relevant env vars (_pinned_env).
    from apex_tpu import tuning

    db = tuning.active_db() if tuning.tuning_enabled() else tuning.TuneDB()
    report: Dict[str, dict] = {}
    with tuning.pinned(db):
        report.update(_preflight_inner(kernels, verbose))
    return report


def _preflight_inner(kernels, verbose) -> Dict[str, dict]:
    report: Dict[str, dict] = {}
    for name in kernels or list(PROBES):
        probe = PROBES.get(name)
        if probe is None:  # typo'd family name must not kill the harness
            report[name] = {
                "ok": False, "ms": 0.0,
                "error": f"unknown kernel family {name!r} "
                         f"(known: {sorted(PROBES)})",
            }
            continue
        t0 = time.perf_counter()
        try:
            # probes run whatever mode the platform dictates: compiled by
            # Mosaic on TPU, interpret on CPU (harmless, still checks parity)
            enable_kernel(name)
            probe()
            report[name] = {
                "ok": True,
                "ms": round((time.perf_counter() - t0) * 1e3, 1),
                "error": None,
            }
        except Exception as e:  # noqa: BLE001 — any failure means fallback
            disable_kernel(name)
            tb = traceback.format_exc().strip().splitlines()
            report[name] = {
                "ok": False,
                "ms": round((time.perf_counter() - t0) * 1e3, 1),
                "error": f"{type(e).__name__}: {str(e).splitlines()[0][:300]}",
                "traceback_tail": tb[-1][:300] if tb else "",
            }
            if verbose:
                print(
                    f"apex_tpu.preflight: kernel family {name!r} FAILED its "
                    f"compile probe and is pinned to the jnp fallback: "
                    f"{report[name]['error']}",
                    flush=True,
                )
    return report
