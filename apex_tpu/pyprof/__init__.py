"""apex.pyprof parity stub (ref: apex/pyprof/__init__.py — REMOVED upstream,
stub raising ImportError pointing at NVIDIA/PyProf).

The TPU profiling path is :mod:`apex_tpu.utils.profiling` (jax.profiler
traces viewable in TensorBoard/Perfetto).
"""


def __getattr__(name):
    raise ImportError(
        "apex_tpu.pyprof mirrors the reference's removed apex.pyprof stub. "
        "Use apex_tpu.utils.profiling (jax.profiler) instead."
    )
