"""DistributedDataParallel — bucketed gradient all-reduce over the ``data`` axis.

Ref: apex/parallel/distributed.py::DistributedDataParallel — flat-buffer,
bucketed, overlap-with-backward NCCL allreduce with options message_size,
delay_allreduce, allreduce_always_fp32, gradient_average,
gradient_predivide_factor, retain_allreduce_buffers.

TPU redesign: under SPMD autodiff there are no per-param backward hooks —
the whole backward is one XLA program and async collectives overlap with
compute automatically (the reference's hook/stream machinery exists to get
exactly this overlap, so it is not re-created). What still matters on ICI is
*bucketing*: many small psums waste link bandwidth; packing grads into a few
large flat buffers (the reference's flatten + 10MB buckets) is as valuable
on TPU as on NVLink. So:

  * grads are packed into flat fp32-or-native buckets of ``message_size``
    bytes (leaf order = tree order; the reference's grad-ready order is a
    scheduling detail XLA owns now),
  * one ``psum`` per bucket,
  * ``gradient_predivide_factor`` / ``allreduce_always_fp32`` /
    ``gradient_average`` semantics preserved exactly,
  * ``retain_allreduce_buffers`` returns the flat reduced buckets too (for
    fused optimizers consuming flat gradients, ref retain_allreduce_buffers).

``delay_allreduce`` is accepted for API parity; with one fused program there
is nothing to delay (documented no-op) — and it STAYS a no-op when
quantized comms is on (the quantization decision never keys off it).

Quantized bucket allreduce (EQuARX-style, arxiv 2506.17615): behind
``APEX_TPU_QUANTIZED_COMMS=1`` (or ``quantized_comms=True``) buckets at
least ``quantize_min_bytes`` on the wire go through
``parallel/quantized_collectives.quantized_psum`` — int8-range payload
on an int16 wire, per-chunk pmax-shared fp32 scales, plus an
error-compensation pass (2 B/element uncompensated — the bandwidth win —
or 4 B compensated near-exact; replica-consistent either way, bounds in
that module's doc). Small buckets stay exact: below the threshold the
latency is launch-bound, not bandwidth-bound, so quantization would cost
accuracy for nothing. ``retain_allreduce_buffers=True`` disables
quantization entirely — the retained flat buckets feed fused optimizers
that expect exact fp32 reduction semantics, so they must never silently
carry quantization error. With the gate off the collective path is
bitwise-identical to the unquantized implementation.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.observability import inc_counter
from apex_tpu.parallel.mesh import DATA_AXIS
from apex_tpu.utils.profiling import trace_range


def _leaf_bytes(x) -> int:
    return int(jnp.size(x)) * jnp.asarray(x).dtype.itemsize


@dataclasses.dataclass(frozen=True)
class DistributedDataParallel:
    """Gradient-averaging engine for the mesh ``data`` axis.

    Usage inside a shard_map'd train step::

        ddp = DistributedDataParallel(message_size=2**25)
        grads = jax.grad(loss)(params)          # local shard grads
        grads = ddp.allreduce_gradients(grads)  # bucketed psum over "data"

    Or at the jit level with GSPMD sharding, simply don't use this class —
    annotate the batch as sharded and XLA inserts the same collectives. This
    engine is for explicit shard_map training loops and for the option
    parity listed above.
    """

    axis_name: str = DATA_AXIS
    message_size: int = 2 ** 25          # ~33.5 MB, ref default 1e7 coalesced
    allreduce_always_fp32: bool = False
    gradient_average: bool = True
    gradient_predivide_factor: float = 1.0
    delay_allreduce: bool = False        # accepted for parity; no-op (see doc)
    retain_allreduce_buffers: bool = False
    # int8 bucket allreduce: None = follow APEX_TPU_QUANTIZED_COMMS (the
    # module-doc rules decide per bucket); True/False force it for tests
    quantized_comms: Optional[bool] = None
    quantize_min_bytes: int = 2 ** 16    # exact psum below this wire size
    quantize_chunk: int = 256            # elements per int8 scale group

    def _quantize_bucket(self, wire_bytes: int, dtype) -> bool:
        """Module-doc rules: gate on, float payload, big enough on the
        wire, and never when the reduced flat buckets are retained."""
        on = self.quantized_comms
        if on is None:
            from apex_tpu.parallel.overlap import quantized_comms_enabled

            on = quantized_comms_enabled()
        return (bool(on)
                and not self.retain_allreduce_buffers
                and jnp.issubdtype(jnp.dtype(dtype), jnp.floating)
                and wire_bytes >= self.quantize_min_bytes)

    def _buckets(self, leaves) -> Sequence[Sequence[int]]:
        """Greedy size-based bucketing by leaf index, segregated by dtype so
        concatenation never promotes (ref buckets are per-dtype too).
        Byte accounting uses the on-wire dtype (fp32 when
        ``allreduce_always_fp32``)."""
        by_dtype: dict = {}
        for i, leaf in enumerate(leaves):
            by_dtype.setdefault(jnp.asarray(leaf).dtype, []).append(i)
        buckets = []
        for idxs in by_dtype.values():
            cur, cur_bytes = [], 0
            for i in idxs:
                cur.append(i)
                if self.allreduce_always_fp32:
                    cur_bytes += int(jnp.size(leaves[i])) * 4
                else:
                    cur_bytes += _leaf_bytes(leaves[i])
                if cur_bytes >= self.message_size:
                    buckets.append(cur)
                    cur, cur_bytes = [], 0
            if cur:
                buckets.append(cur)
        return buckets

    def allreduce_gradients(self, grads, *, world_size: Optional[int] = None):
        """Bucketed psum over the data axis; returns averaged grads (and the
        flat reduced buckets when ``retain_allreduce_buffers``)."""
        leaves, treedef = jax.tree.flatten(grads)
        if not leaves:
            return grads
        n = world_size if world_size is not None else lax.psum(1, self.axis_name)

        # ref allreduce_bucket order: predivide unconditionally BEFORE the
        # all-reduce (overflow guard for low-precision sums), post-multiply
        # (predivide_factor / world) only when gradient_average
        pre = 1.0
        post = 1.0
        if self.gradient_predivide_factor != 1.0:
            pre = 1.0 / self.gradient_predivide_factor
        if self.gradient_average:
            post = self.gradient_predivide_factor / n

        flat_buckets = []
        reduced_leaves = [None] * len(leaves)
        for bi, bucket in enumerate(self._buckets(leaves)):
            # profiling seam (ref: DDP prof flag -> nvtx around bucket ops)
            with trace_range(f"ddp_bucket_allreduce_{bi}"):
                parts = []
                for i in bucket:
                    x = leaves[i]
                    x32 = x.astype(jnp.float32) if self.allreduce_always_fp32 else x
                    parts.append((x32 * pre).reshape(-1))
                flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
                if self._quantize_bucket(
                        flat.size * flat.dtype.itemsize, flat.dtype):
                    from apex_tpu.parallel.quantized_collectives import (
                        quantized_psum,
                        quantized_wire_bytes,
                    )

                    # bytes-on-wire, recorded at TRACE time (sizes are
                    # static): per traced step, not per execution — the
                    # fp32-vs-int8 wire delta the int8 path exists for
                    inc_counter(
                        "comms/bytes_on_wire",
                        quantized_wire_bytes(flat.size,
                                             self.quantize_chunk),
                        path="ddp", collective="psum", mode="int8")
                    flat = quantized_psum(flat, self.axis_name,
                                          chunk=self.quantize_chunk)
                else:
                    inc_counter(
                        "comms/bytes_on_wire",
                        flat.size * flat.dtype.itemsize,
                        path="ddp", collective="psum", mode="exact")
                    flat = lax.psum(flat, self.axis_name)
                flat = flat * post
            flat_buckets.append(flat)
            # unpack
            offset = 0
            for i in bucket:
                sz = int(jnp.size(leaves[i]))
                piece = flat[offset:offset + sz].reshape(jnp.shape(leaves[i]))
                reduced_leaves[i] = piece.astype(jnp.asarray(leaves[i]).dtype)
                offset += sz

        out = jax.tree.unflatten(treedef, reduced_leaves)
        if self.retain_allreduce_buffers:
            return out, flat_buckets
        return out

    # ref: module broadcast at __init__ via flat_dist_call
    def broadcast_params(self, params, src: int = 0):
        from apex_tpu.parallel.collectives import broadcast_tree

        return broadcast_tree(params, self.axis_name, src)

    def __call__(self, grads, **kw):
        return self.allreduce_gradients(grads, **kw)
