"""Named-mesh helpers — the SPMD replacement for the reference's process groups.

The reference builds NCCL process groups per parallel dimension
(apex/transformer/parallel_state.py::initialize_model_parallel creates
_TENSOR_MODEL_PARALLEL_GROUP, _PIPELINE_MODEL_PARALLEL_GROUP,
_DATA_PARALLEL_GROUP, ...). On TPU, a single ``jax.sharding.Mesh`` with named
axes replaces all of that: collectives take an axis name instead of a
communicator, and sub-groups are just sub-axes.

Canonical axis names used throughout apex_tpu:
  "data"   — data parallelism (reference: apex/parallel DDP, _DATA_PARALLEL_GROUP)
  "model"  — tensor model parallelism (reference: _TENSOR_MODEL_PARALLEL_GROUP)
  "stage"  — pipeline parallelism (reference: _PIPELINE_MODEL_PARALLEL_GROUP)

Axis ordering matters for the physical network: axes later in the mesh tuple
are "closer" (minor), so we order ("stage", "data", "model") by default —
tensor-parallel collectives (the chattiest) ride the fastest ICI links, DP
all-reduce amortizes over larger messages, and pipeline p2p (cheapest) can
span DCN on multi-slice deployments.
"""

from __future__ import annotations

import contextlib
import math
from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
MODEL_AXIS = "model"
STAGE_AXIS = "stage"

# Default major→minor ordering: pipeline outermost, tensor-parallel innermost.
DEFAULT_AXIS_ORDER = (STAGE_AXIS, DATA_AXIS, MODEL_AXIS)

_default_mesh: Optional[Mesh] = None


def _resolve_axes(axes, n_devices, axis_order):
    """Shared make_mesh/hybrid_mesh resolution: infer one -1 size from the
    device count and order axes major→minor per ``axis_order`` (unknown
    axes appended in insertion order). Returns (names, shape)."""
    axes = dict(axes)
    known = math.prod(s for s in axes.values() if s != -1)
    infer = [k for k, s in axes.items() if s == -1]
    if len(infer) > 1:
        raise ValueError("at most one axis size may be -1")
    if infer:
        if n_devices % known:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed axes "
                f"product {known}")
        axes[infer[0]] = n_devices // known
    names = [a for a in axis_order if a in axes]
    names += [a for a in axes if a not in names]
    return names, [axes[n] for n in names]


def make_mesh(
    axes: Mapping[str, int],
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_order: Sequence[str] = DEFAULT_AXIS_ORDER,
) -> Mesh:
    """Build a Mesh from ``{axis_name: size}``.

    Sizes of -1 (at most one) are inferred from the device count. Axes listed
    in ``axis_order`` are laid out in that major→minor order; unknown axes are
    appended in insertion order.
    """
    devices = list(devices if devices is not None else jax.devices())
    names, shape = _resolve_axes(axes, len(devices), axis_order)
    total = math.prod(shape)
    if total > len(devices):
        raise ValueError(f"mesh needs {total} devices, have {len(devices)}")
    devices = devices[:total]
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(names))


def hybrid_mesh(
    axes: Mapping[str, int],
    *,
    dcn_axes: Sequence[str] = (STAGE_AXIS, DATA_AXIS),
    devices: Optional[Sequence[jax.Device]] = None,
    axis_order: Sequence[str] = DEFAULT_AXIS_ORDER,
    slice_map: Optional[Sequence[int]] = None,
) -> Mesh:
    """Multi-slice mesh: axes named in ``dcn_axes`` span slices (DCN),
    everything else stays within a slice (ICI).

    The reference scales across hosts by giving every process group an
    NCCL communicator regardless of topology; on multi-slice TPU the
    topology is two-tier — fast ICI within a slice, slow DCN between —
    so the mesh must be laid out so that the chatty axes (tensor
    parallel) never cross DCN (SURVEY §6 "Distributed communication
    backend"; cf. the scaling-book recipe). ``hybrid_mesh`` walks
    ``dcn_axes`` major-to-minor, factoring the slice count into those
    axes (an axis may span BOTH tiers, e.g. dp=16 over 4 slices = 4 DCN
    x 4 ICI); the device array is ordered so each axis's DCN extent is
    major over its ICI extent.

    ``slice_map`` overrides slice assignment (one slice id per device) —
    used by tests and by CPU rehearsal of a pod layout. Without it,
    devices are grouped by ``slice_index`` when present (multi-slice TPU)
    falling back to ``process_index``, and a single group degenerates to
    ``make_mesh`` exactly.
    """
    devices = list(devices if devices is not None else jax.devices())
    names, shape = _resolve_axes(axes, len(devices), axis_order)
    axes = dict(zip(names, shape))
    if math.prod(shape) != len(devices):
        # unlike make_mesh, hybrid layout must use ALL devices — a surplus
        # would leave partial slices
        raise ValueError(
            f"mesh {dict(zip(names, shape))} needs {math.prod(shape)} "
            f"devices, have {len(devices)} (hybrid_mesh uses all devices)")

    if slice_map is not None and len(slice_map) != len(devices):
        raise ValueError(
            f"slice_map has {len(slice_map)} entries for "
            f"{len(devices)} devices")
    if slice_map is None:
        def _slice_of(d):
            s = getattr(d, "slice_index", None)
            return s if s is not None else d.process_index
        slice_map = [_slice_of(d) for d in devices]
    by_slice: dict = {}
    for d, s in zip(devices, slice_map):
        by_slice.setdefault(s, []).append(d)
    slice_groups = [by_slice[k] for k in sorted(by_slice)]
    n_slices = len(slice_groups)
    per_slice = len(devices) // n_slices
    if any(len(g) != per_slice for g in slice_groups):
        raise ValueError(
            f"uneven slices: {[len(g) for g in slice_groups]}")

    # factor n_slices into the dcn axes, major to minor
    dcn_part = {n: 1 for n in names}
    remaining = n_slices
    for a in dcn_axes:
        if a not in axes or remaining == 1:
            continue
        d = math.gcd(axes[a], remaining)
        dcn_part[a] = d
        remaining //= d
    if remaining != 1:
        raise ValueError(
            f"cannot factor {n_slices} slices into dcn_axes={dcn_axes} "
            f"sizes {[axes.get(a) for a in dcn_axes]}")
    # dcn_part[n] is 1 or gcd(axes[n], ...), so it always divides axes[n]
    ici_part = {n: axes[n] // dcn_part[n] for n in names}
    if math.prod(ici_part.values()) != per_slice:
        raise ValueError(
            f"ICI extents {ici_part} need {math.prod(ici_part.values())} "
            f"devices/slice, have {per_slice}")

    # [n_slices, per_slice] -> (dcn_0..dcn_k, ici_0..ici_k) ->
    # interleave (dcn_i, ici_i) pairs -> merge to the global shape
    arr = np.asarray(
        [d for g in slice_groups for d in g], dtype=object
    ).reshape([dcn_part[n] for n in names] + [ici_part[n] for n in names])
    k = len(names)
    arr = arr.transpose(
        [i for pair in zip(range(k), range(k, 2 * k)) for i in pair])
    return Mesh(arr.reshape(shape), tuple(names))


def data_parallel_mesh(n: Optional[int] = None, **kw) -> Mesh:
    return make_mesh({DATA_AXIS: -1 if n is None else n}, **kw)


def cpu_devices(n: int) -> Sequence[jax.Device]:
    """CPU devices for hermetic multi-device tests.

    Requires ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (set in
    tests/conftest.py) — the JAX analog of the reference's spawn-based
    MultiProcessTestCase harness (apex/transformer/testing/distributed_test_base.py).
    """
    devs = jax.devices("cpu")
    if len(devs) < n:
        raise RuntimeError(
            f"need {n} CPU devices, have {len(devs)}; set "
            "XLA_FLAGS=--xla_force_host_platform_device_count"
        )
    return devs[:n]


def cpu_mesh(axes: Mapping[str, int], **kw) -> Mesh:
    n = math.prod(s for s in axes.values())
    return make_mesh(axes, devices=cpu_devices(n), **kw)


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _default_mesh
    _default_mesh = mesh


def get_default_mesh() -> Optional[Mesh]:
    if _default_mesh is not None:
        return _default_mesh
    # Fall back to an ambient `with mesh:` context if one is active. There is
    # no public accessor for the *physical* ambient mesh, so this uses the
    # private thread_resources and degrades to None if jax moves it.
    try:
        from jax._src.mesh import thread_resources

        m = thread_resources.env.physical_mesh
        if m is not None and not m.empty:
            return m
    except Exception:
        pass
    return None


@contextlib.contextmanager
def default_mesh(mesh: Mesh):
    prev = _default_mesh
    set_default_mesh(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        set_default_mesh(prev)


def axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape[name] if name in mesh.axis_names else 1


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
