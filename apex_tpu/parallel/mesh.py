"""Named-mesh helpers — the SPMD replacement for the reference's process groups.

The reference builds NCCL process groups per parallel dimension
(apex/transformer/parallel_state.py::initialize_model_parallel creates
_TENSOR_MODEL_PARALLEL_GROUP, _PIPELINE_MODEL_PARALLEL_GROUP,
_DATA_PARALLEL_GROUP, ...). On TPU, a single ``jax.sharding.Mesh`` with named
axes replaces all of that: collectives take an axis name instead of a
communicator, and sub-groups are just sub-axes.

Canonical axis names used throughout apex_tpu:
  "data"   — data parallelism (reference: apex/parallel DDP, _DATA_PARALLEL_GROUP)
  "model"  — tensor model parallelism (reference: _TENSOR_MODEL_PARALLEL_GROUP)
  "stage"  — pipeline parallelism (reference: _PIPELINE_MODEL_PARALLEL_GROUP)

Axis ordering matters for the physical network: axes later in the mesh tuple
are "closer" (minor), so we order ("stage", "data", "model") by default —
tensor-parallel collectives (the chattiest) ride the fastest ICI links, DP
all-reduce amortizes over larger messages, and pipeline p2p (cheapest) can
span DCN on multi-slice deployments.
"""

from __future__ import annotations

import contextlib
import math
from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"
MODEL_AXIS = "model"
STAGE_AXIS = "stage"

# Default major→minor ordering: pipeline outermost, tensor-parallel innermost.
DEFAULT_AXIS_ORDER = (STAGE_AXIS, DATA_AXIS, MODEL_AXIS)

_default_mesh: Optional[Mesh] = None


def make_mesh(
    axes: Mapping[str, int],
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_order: Sequence[str] = DEFAULT_AXIS_ORDER,
) -> Mesh:
    """Build a Mesh from ``{axis_name: size}``.

    Sizes of -1 (at most one) are inferred from the device count. Axes listed
    in ``axis_order`` are laid out in that major→minor order; unknown axes are
    appended in insertion order.
    """
    devices = list(devices if devices is not None else jax.devices())
    axes = dict(axes)

    known = math.prod(s for s in axes.values() if s != -1)
    infer = [k for k, s in axes.items() if s == -1]
    if len(infer) > 1:
        raise ValueError("at most one axis size may be -1")
    if infer:
        if len(devices) % known:
            raise ValueError(
                f"{len(devices)} devices not divisible by fixed axes product {known}"
            )
        axes[infer[0]] = len(devices) // known

    total = math.prod(axes.values())
    if total > len(devices):
        raise ValueError(f"mesh needs {total} devices, have {len(devices)}")
    devices = devices[:total]

    names = [a for a in axis_order if a in axes]
    names += [a for a in axes if a not in names]
    shape = tuple(axes[n] for n in names)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(names))


def data_parallel_mesh(n: Optional[int] = None, **kw) -> Mesh:
    return make_mesh({DATA_AXIS: -1 if n is None else n}, **kw)


def cpu_devices(n: int) -> Sequence[jax.Device]:
    """CPU devices for hermetic multi-device tests.

    Requires ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (set in
    tests/conftest.py) — the JAX analog of the reference's spawn-based
    MultiProcessTestCase harness (apex/transformer/testing/distributed_test_base.py).
    """
    devs = jax.devices("cpu")
    if len(devs) < n:
        raise RuntimeError(
            f"need {n} CPU devices, have {len(devs)}; set "
            "XLA_FLAGS=--xla_force_host_platform_device_count"
        )
    return devs[:n]


def cpu_mesh(axes: Mapping[str, int], **kw) -> Mesh:
    n = math.prod(s for s in axes.values())
    return make_mesh(axes, devices=cpu_devices(n), **kw)


def set_default_mesh(mesh: Optional[Mesh]) -> None:
    global _default_mesh
    _default_mesh = mesh


def get_default_mesh() -> Optional[Mesh]:
    if _default_mesh is not None:
        return _default_mesh
    # Fall back to an ambient `with mesh:` context if one is active. There is
    # no public accessor for the *physical* ambient mesh, so this uses the
    # private thread_resources and degrades to None if jax moves it.
    try:
        from jax._src.mesh import thread_resources

        m = thread_resources.env.physical_mesh
        if m is not None and not m.empty:
            return m
    except Exception:
        pass
    return None


@contextlib.contextmanager
def default_mesh(mesh: Mesh):
    prev = _default_mesh
    set_default_mesh(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        set_default_mesh(prev)


def axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape[name] if name in mesh.axis_names else 1


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
