"""Quantized collectives: int8 per-chunk-scaled psum / psum_scatter.

Gradient all-reduce on the DDP path and the ZeRO-2 gradient
reduce-scatter move fp32 (or bf16) buckets whose information content is
far below 32 bits per element — EQuARX (arxiv 2506.17615) shows a
quantized allreduce recovering most of the exposed-collective gap on TPU
ICI at negligible quality cost. This module implements the scheme the
DDP/ZeRO paths opt into behind ``APEX_TPU_QUANTIZED_COMMS=1``:

1. **Per-chunk scaling.** The flat payload is viewed as fixed-size chunks
   (default 256 elements); each chunk gets its own fp32 scale so one
   outlier only costs its own chunk's resolution, not the bucket's.
2. **Shared scales.** Scales must agree across ranks for the integer sum
   to be exact, so per-chunk absmaxes are ``pmax``-ed over the axis
   first — a tiny fp32 collective (1/chunk_size of the payload).
3. **int8-range payload, int16 wire.** Values quantize to [-127, 127]
   (symmetric, round-to-nearest) and the wire collective runs on int16 —
   the narrowest dtype whose per-element sum (127 · world_size, world up
   to 250) cannot overflow, so each pass moves 2 bytes/element, half the
   fp32 psum's 4 (beyond 250 ranks the wire silently widens to int32 for
   correctness). Every rank dequantizes identically, so the result is
   replica-consistent — the property DDP needs to keep parameters
   bitwise-identical across data ranks.
4. **fp32 error compensation.** The local quantization residual
   ``e = x - dequant(quant(x))`` is computed in fp32, quantized at the
   residual's own (much finer) per-chunk scale, and summed in a second
   int16 pass that is added back after dequantization. The compensated
   error per element is bounded by ``amax_e / 254 <= amax_x / (2·254²)``
   per rank. Wire cost: **2 B/element uncompensated** (the 2× bandwidth
   win, worst-case relative error ~4e-3 of the chunk absmax) or
   **4 B/element compensated** (fp32-bandwidth parity, error ~1e-5 —
   the accuracy-first rollout mode the DDP/ZeRO paths default to; flip
   ``error_compensation=False`` once a workload's loss curve tolerates
   the single-pass error to collect the bandwidth win).

The documented error bounds (asserted by
``tests/L0/test_quantized_comms_fuzz.py`` across the dtype ladder,
bucket sizes, and ragged last chunks):

  relative error vs fp32 psum, measured against the max |sum| --
    compensated:   < 1e-4 · world_size
    uncompensated: < 1e-2 · world_size

All functions must run inside ``shard_map``/pmap over ``axis``. Payload
dtype is preserved: inputs are upcast to fp32 for scaling, outputs cast
back to the input dtype.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = [
    "quantized_psum",
    "quantized_psum_scatter",
    "quantized_scatter_wire_bytes",
    "quantized_wire_bytes",
]

DEFAULT_CHUNK = 256
_QMAX = 127.0
# int16 sums of int8-range values overflow past 32767/127 ranks; widen
# (and lose the bandwidth win) rather than corrupt beyond that
_INT16_MAX_WORLD = 250


def _wire_dtype(axis: str):
    return jnp.int16 if lax.axis_size(axis) <= _INT16_MAX_WORLD \
        else jnp.int32


def quantized_wire_bytes(n: int, chunk: int = DEFAULT_CHUNK, *,
                         error_compensation: bool = True,
                         wire_itemsize: int = 2) -> int:
    """Analytic payload bytes :func:`quantized_psum` moves for an
    ``n``-element input: per pass, the zero-padded chunk grid on the wire
    dtype plus one fp32 pmax-shared scale per chunk; two passes when
    error-compensated. The observability bytes-on-wire counters (ddp.py,
    contrib/optimizers/_sharding.py) and the analytic-match test both use
    this — one formula, no drift."""
    n = int(n)
    chunk = max(1, min(int(chunk), n))
    padded = -(-n // chunk) * chunk
    n_chunks = padded // chunk
    passes = 2 if error_compensation else 1
    return passes * (padded * wire_itemsize + n_chunks * 4)


def quantized_scatter_wire_bytes(n: int, world: int,
                                 chunk: int = DEFAULT_CHUNK, *,
                                 error_compensation: bool = True,
                                 wire_itemsize: int = 2) -> int:
    """Analytic payload bytes of :func:`quantized_psum_scatter` on a flat
    ``n``-element payload over a ``world``-rank axis: chunk padding is
    PER SHARD (chunk rows never straddle a shard boundary), scales are a
    full pmax per pass."""
    n, world = int(n), int(world)
    shard = n // world
    chunk = max(1, min(int(chunk), shard))
    padded_shard = -(-shard // chunk) * chunk
    n_chunks = world * (padded_shard // chunk)
    passes = 2 if error_compensation else 1
    return passes * (world * padded_shard * wire_itemsize + n_chunks * 4)


def _chunk_view(flat32, chunk: int):
    """[n] fp32 -> ([c, chunk] fp32, pad) with zero padding (zeros
    quantize exactly, so the ragged tail costs nothing)."""
    n = flat32.shape[0]
    chunk = max(1, min(int(chunk), n))
    pad = (-n) % chunk
    if pad:
        flat32 = jnp.concatenate([flat32, jnp.zeros((pad,), jnp.float32)])
    return flat32.reshape(-1, chunk), pad


def _shared_scales(rows, axis: str):
    """Per-chunk fp32 scales, pmax-shared over ``axis`` so the integer
    sum dequantizes identically on every rank."""
    amax = lax.pmax(jnp.max(jnp.abs(rows), axis=1), axis)
    # a zero chunk on every rank quantizes to zeros; scale 1 avoids 0/0
    return jnp.where(amax > 0, amax, 1.0) / _QMAX


def _quant(rows, scales):
    q = jnp.round(rows / scales[:, None])
    return jnp.clip(q, -_QMAX, _QMAX).astype(jnp.int8)


def _dequant(qrows, scales):
    return qrows.astype(jnp.float32) * scales[:, None]


def quantized_psum(x, axis: str, *, chunk: int = DEFAULT_CHUNK,
                   error_compensation: bool = True):
    """``lax.psum(x, axis)`` with an int8 wire format.

    ``x``: any shape/float dtype. Returns the quantized-allreduce sum in
    ``x``'s dtype; identical on every rank (replica-consistent). With
    ``error_compensation`` a second int8 pass carries the fp32
    quantization residual at its own finer scale (see module doc for the
    error bounds)."""
    shape, dtype = x.shape, x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    rows, pad = _chunk_view(flat, chunk)

    wire = _wire_dtype(axis)
    scales = _shared_scales(rows, axis)
    q = _quant(rows, scales)
    total = _dequant(lax.psum(q.astype(wire), axis), scales)

    if error_compensation:
        resid = rows - _dequant(q, scales)
        rscales = _shared_scales(resid, axis)
        rq = _quant(resid, rscales)
        total = total + _dequant(lax.psum(rq.astype(wire), axis),
                                 rscales)

    out = total.reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(shape).astype(dtype)


def quantized_psum_scatter(x, axis: str, *, chunk: int = DEFAULT_CHUNK,
                           error_compensation: bool = True):
    """``lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)`` on a
    flat [n] payload, with an int8 wire format.

    ``x``: 1-D, length divisible by the axis size. Each rank receives the
    reduced values of its own shard. Chunking is per shard-slice so the
    scale table scatters with the payload (rank r dequantizes with the
    scales of shard r); scales are pmax-shared over the axis exactly as
    in :func:`quantized_psum`."""
    if x.ndim != 1:
        raise ValueError(f"quantized_psum_scatter takes a flat payload, "
                         f"got shape {x.shape}")
    n = lax.axis_size(axis)
    if x.shape[0] % n:
        raise ValueError(
            f"payload length {x.shape[0]} not divisible by axis size {n}")
    dtype = x.dtype
    shard = x.shape[0] // n
    chunk = max(1, min(int(chunk), shard))
    pad = (-shard) % chunk  # ragged last chunk padded PER SHARD, so chunk
    # rows never straddle a shard boundary and the scale table scatters
    # cleanly with the payload
    xs = x.astype(jnp.float32).reshape(n, shard)
    if pad:
        xs = jnp.concatenate([xs, jnp.zeros((n, pad), jnp.float32)], axis=1)
    c = (shard + pad) // chunk  # chunk rows per shard
    rows2 = xs.reshape(n * c, chunk)

    wire = _wire_dtype(axis)

    def reduce_pass(rows):
        scales = _shared_scales(rows, axis)
        q = _quant(rows, scales)
        # scatter whole shard-blocks of chunk rows: [n, c, chunk]
        qs = lax.psum_scatter(
            q.astype(wire).reshape(n, c, chunk), axis,
            scatter_dimension=0, tiled=False)
        r = lax.axis_index(axis)
        my_scales = lax.dynamic_slice_in_dim(scales, r * c, c, 0)
        resid = rows - _dequant(q, scales)
        return _dequant(qs.reshape(c, chunk), my_scales), resid

    mine, resid = reduce_pass(rows2)
    if error_compensation:
        mine_r, _ = reduce_pass(resid)
        mine = mine + mine_r

    return mine.reshape(-1)[:shard].astype(dtype)
