"""SyncBatchNorm — batch statistics reduced across the data axis.

Ref: apex/parallel/optimized_sync_batchnorm.py + csrc/welford.cu — local
Welford mean/var, all_gather of per-rank stats, ``welford_parallel`` combine,
fused normalize fwd; backward reduces sum_dy / sum_dy_xmu across ranks.

TPU design: the parallel-combine is Chan's count/mean/M2 merge expressed
with two ``psum``s (count-weighted mean and raw second moment), which is
algebraically identical to the reference's welford_parallel for equal-size
shards and lowers to a single fused all-reduce pair on ICI. Backward comes
from autodiff through the psums (psum's transpose is psum), which reproduces
the reference's sum_dy/sum_dy_xmu cross-rank reductions without a hand
kernel. ``process_group`` maps to ``axis_name`` (a mesh sub-axis or tuple of
axes).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

try:
    import flax.linen as nn

    _HAVE_FLAX = True
except ImportError:  # pragma: no cover
    _HAVE_FLAX = False


Axis = Union[str, Sequence[str]]


def sync_batch_stats(x, axis_name: Optional[Axis], *, feature_axis: int = -1):
    """Global (mean, var) of x over all axes but ``feature_axis``, combined
    across ``axis_name`` ranks (count-weighted Chan merge)."""
    red = tuple(i for i in range(x.ndim) if i != (feature_axis % x.ndim))
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=red)
    if axis_name is not None:
        # equal shard sizes under SPMD -> unweighted pmean == Chan merge
        mean = lax.pmean(mean, axis_name)
    # two-pass variance around the GLOBAL mean: E[x^2]-E[x]^2 cancels
    # catastrophically in fp32 when |mean| >> std; centering first keeps the
    # numerics of the reference's Welford kernel at the cost of one more
    # local pass (collective count unchanged: one pmean for mean, one for var)
    shape = [1] * x.ndim
    shape[feature_axis % x.ndim] = mean.shape[0]
    var = jnp.mean(jnp.square(x32 - mean.reshape(shape)), axis=red)
    if axis_name is not None:
        var = lax.pmean(var, axis_name)
    return mean, var


if _HAVE_FLAX:

    class SyncBatchNorm(nn.Module):
        """Drop-in BatchNorm synchronizing statistics across ``axis_name``.

        Interface mirrors flax BatchNorm + the reference's extras:
        ``axis_name`` (ref: process_group), ``channel_last``-style via
        ``feature_axis``. Running stats live in the ``batch_stats``
        collection.
        """

        use_running_average: Optional[bool] = None
        axis_name: Optional[Axis] = None
        momentum: float = 0.9  # flax convention: ra = m*ra + (1-m)*batch
        epsilon: float = 1e-5
        dtype: Optional[object] = None
        param_dtype: object = jnp.float32
        use_bias: bool = True
        use_scale: bool = True
        bias_init: object = None
        scale_init: object = None
        feature_axis: int = -1

        @nn.compact
        def __call__(self, x, use_running_average: Optional[bool] = None):
            use_ra = nn.merge_param(
                "use_running_average",
                self.use_running_average,
                use_running_average,
            )
            feat = x.shape[self.feature_axis % x.ndim]
            ra_mean = self.variable(
                "batch_stats", "mean", lambda: jnp.zeros((feat,), jnp.float32)
            )
            ra_var = self.variable(
                "batch_stats", "var", lambda: jnp.ones((feat,), jnp.float32)
            )

            if use_ra:
                mean, var = ra_mean.value, ra_var.value
            else:
                # axis names are only bound inside shard_map/pmap; during
                # flax init (traced outside) reduce locally
                axis = None if self.is_initializing() else self.axis_name
                mean, var = sync_batch_stats(
                    x, axis, feature_axis=self.feature_axis
                )
                if not self.is_initializing():
                    ra_mean.value = (
                        self.momentum * ra_mean.value + (1 - self.momentum) * mean
                    )
                    ra_var.value = (
                        self.momentum * ra_var.value + (1 - self.momentum) * var
                    )

            shape = [1] * x.ndim
            shape[self.feature_axis % x.ndim] = feat
            y = (x.astype(jnp.float32) - mean.reshape(shape)) * jax.lax.rsqrt(
                var.reshape(shape) + self.epsilon
            )
            if self.use_scale:
                scale = self.param(
                    "scale",
                    self.scale_init or nn.initializers.ones,
                    (feat,),
                    self.param_dtype,
                )
                y = y * scale.reshape(shape).astype(jnp.float32)
            if self.use_bias:
                bias = self.param(
                    "bias",
                    self.bias_init or nn.initializers.zeros,
                    (feat,),
                    self.param_dtype,
                )
                y = y + bias.reshape(shape).astype(jnp.float32)
            return y.astype(self.dtype or x.dtype)

    def convert_syncbn_model(module, axis_name: Axis = "data"):
        """Recursively swap ``nn.BatchNorm`` sub-modules for SyncBatchNorm.

        Ref: apex/parallel/__init__.py::convert_syncbn_model. Works for
        modules whose BatchNorm layers are dataclass fields (explicit
        submodule style). ``@nn.compact`` modules construct children inline
        and cannot be rewritten from outside — use SyncBatchNorm directly
        there (documented limitation of the functional style).
        """
        import dataclasses as dc

        if isinstance(module, nn.BatchNorm):
            if not isinstance(module.axis, int):
                raise NotImplementedError(
                    "convert_syncbn_model: BatchNorm with multiple feature "
                    f"axes (axis={module.axis!r}) is not supported; use "
                    "SyncBatchNorm directly with a custom reduction"
                )
            return SyncBatchNorm(
                use_running_average=module.use_running_average,
                axis_name=axis_name,
                momentum=module.momentum,
                epsilon=module.epsilon,
                dtype=module.dtype,
                param_dtype=module.param_dtype,
                use_bias=module.use_bias,
                use_scale=module.use_scale,
                bias_init=module.bias_init,
                scale_init=module.scale_init,
                # flax BatchNorm(axis=k) names the feature axis directly
                feature_axis=module.axis if isinstance(module.axis, int) else -1,
            )

        def _convert_value(v):
            if isinstance(v, nn.Module):
                return convert_syncbn_model(v, axis_name)
            if isinstance(v, (list, tuple)):
                nv = [_convert_value(e) for e in v]
                changed = any(a is not b for a, b in zip(nv, v))
                return type(v)(nv) if changed else v
            if isinstance(v, dict):
                nv = {k: _convert_value(e) for k, e in v.items()}
                changed = any(nv[k] is not v[k] for k in v)
                return nv if changed else v
            return v

        if isinstance(module, nn.Module):
            changes = {}
            for f in dc.fields(module):
                try:
                    v = getattr(module, f.name)
                except AttributeError:
                    continue
                nv = _convert_value(v)
                if nv is not v:
                    changes[f.name] = nv
            if changes:
                return module.clone(**changes)
        return module
