"""Named-axis collectives — the distributed communication backend.

Ref: the reference's torch.distributed usage (SURVEY.md §6 "Distributed
communication backend"): NCCL/UCC process groups with all_reduce, all_gather,
reduce_scatter, broadcast, batch_isend_irecv. Under SPMD there are no
communicators: a collective names a mesh axis and XLA lowers it to ICI
(intra-slice) or DCN (inter-slice) transfers based on the mesh layout.

These wrappers exist to (a) give the rest of the library one vocabulary,
(b) centralize dtype-handling (fp32 accumulation options), and (c) document
the mapping for users porting reference code:

  dist.all_reduce(t, group=g)        -> all_reduce(t, axis)
  dist.all_gather(ts, t, group=g)    -> all_gather(t, axis)
  dist.reduce_scatter(out, ts)       -> reduce_scatter(t, axis)
  dist.broadcast(t, src, group=g)    -> broadcast(t, axis, src)
  batch_isend_irecv(P2POps)          -> permute(t, axis, perm) [ppermute]

All functions must run inside a ``shard_map``/``pmap`` body (a context where
``axis`` is bound).
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

Axis = Union[str, Sequence[str]]


def axis_index(axis: Axis):
    return lax.axis_index(axis)


def axis_size(axis: Axis) -> int:
    return lax.axis_size(axis) if hasattr(lax, "axis_size") else lax.psum(1, axis)


def all_reduce(x, axis: Axis, op: str = "sum"):
    """Ref: dist.all_reduce (SUM/MAX/MIN)."""
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unknown reduce op {op!r}")


def all_gather(x, axis: Axis, *, gather_axis: int = 0, tiled: bool = True):
    """Ref: dist.all_gather — concatenates shards along ``gather_axis``."""
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: Axis, *, scatter_axis: int = 0):
    """Ref: dist.reduce_scatter — sum then keep this rank's shard."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def broadcast(x, axis: Axis, src: int = 0):
    """Ref: dist.broadcast — every rank gets rank ``src``'s value.

    SPMD form: zero out non-src shards and psum (one collective, no
    control flow divergence).
    """
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def permute(x, axis: Axis, perm: Sequence[tuple]):
    """Ref: batch_isend_irecv p2p — (src, dst) pairs over the axis ring."""
    return lax.ppermute(x, axis, perm)


def shift_right(x, axis: Axis):
    """Send to the next rank on the ring (pipeline send_forward)."""
    n = axis_size(axis)
    return lax.ppermute(x, axis, [(i, (i + 1) % n) for i in range(n)])


def shift_left(x, axis: Axis):
    """Send to the previous rank on the ring (pipeline send_backward)."""
    n = axis_size(axis)
    return lax.ppermute(x, axis, [(i, (i - 1) % n) for i in range(n)])


def all_reduce_tree(tree, axis: Axis, op: str = "sum"):
    return jax.tree.map(lambda x: all_reduce(x, axis, op), tree)


def broadcast_tree(tree, axis: Axis, src: int = 0):
    return jax.tree.map(lambda x: broadcast(x, axis, src), tree)
