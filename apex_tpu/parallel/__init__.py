"""apex_tpu.parallel — data parallelism over the mesh ``data`` axis
(ref: apex/parallel)."""

from apex_tpu.parallel import (  # noqa: F401
    collectives,
    mesh,
    overlap,
    quantized_collectives,
)
from apex_tpu.parallel.ddp import DistributedDataParallel  # noqa: F401
from apex_tpu.parallel.grad_accum import (  # noqa: F401
    accumulate_and_step,
    accumulate_and_step_prefetch,
    accumulate_gradients,
    split_microbatches,
)
# the reference exposes LARC under apex.parallel as well as its module
from apex_tpu.optimizers.larc import LARC  # noqa: F401
from apex_tpu.parallel.sync_batchnorm import sync_batch_stats  # noqa: F401

try:  # flax-only pieces; DDP/collectives/mesh stay importable without flax
    from apex_tpu.parallel.sync_batchnorm import (  # noqa: F401
        SyncBatchNorm,
        convert_syncbn_model,
    )
except ImportError:  # pragma: no cover
    pass
from apex_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    STAGE_AXIS,
    cpu_mesh,
    data_parallel_mesh,
    default_mesh,
    get_default_mesh,
    hybrid_mesh,
    make_mesh,
    set_default_mesh,
)
