"""apex_tpu.parallel — data parallelism over the mesh ``data`` axis
(ref: apex/parallel)."""

from apex_tpu.parallel import mesh  # noqa: F401
from apex_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    STAGE_AXIS,
    cpu_mesh,
    data_parallel_mesh,
    default_mesh,
    get_default_mesh,
    make_mesh,
    set_default_mesh,
)
