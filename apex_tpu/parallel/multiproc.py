"""Multi-host launcher helper (ref: apex/parallel/multiproc.py).

The reference's launcher spawns one process per GPU and sets RANK/WORLD_SIZE
for ``torch.distributed``. On TPU pods the runtime launches one process per
host; what remains is coordinator discovery — ``jax.distributed.initialize``
— after which every chip appears in ``jax.devices()`` and SPMD takes over
(no per-chip processes, no process groups).
"""

from __future__ import annotations

import os
import warnings

import jax


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Bring up multi-host JAX (ref capability: multiproc launcher + torch
    init_process_group rendezvous). On Cloud TPU the arguments are
    auto-detected; pass them explicitly elsewhere."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def main():  # pragma: no cover - host-environment dependent
    """CLI shim: the reference's ``python -m apex.parallel.multiproc`` is a
    GPU process spawner; on TPU it reduces to an env sanity check."""
    warnings.warn(
        "apex_tpu.parallel.multiproc: TPU runtimes launch one process per "
        "host; call apex_tpu.parallel.multiproc.initialize() (or rely on "
        "auto-init) instead of spawning per-chip processes.",
        stacklevel=1,
    )
    print(f"process {os.environ.get('CLOUD_TPU_TASK_ID', '?')}: "
          f"{jax.device_count()} devices visible")


if __name__ == "__main__":  # pragma: no cover
    main()
