"""Gradient accumulation over microbatches with fp32 accumulation.

The reference exposes this capability twice: DistributedDataParallel's
``delay_allreduce`` lets users run several backwards before the bucketed
allreduce fires (apex/parallel/distributed.py::DistributedDataParallel),
and the Megatron path accumulates weight gradients into an fp32
``main_grad`` buffer across microbatches
(csrc/megatron/fused_weight_gradient_dense.cpp, SURVEY §3.13 #7; the
pipeline schedules drive one backward per microbatch). The TPU analog is
a ``lax.scan`` over microbatches whose carry is the fp32 grad
accumulator — one compiled program, no per-microbatch dispatch.

Why it is a *performance* feature here and not just a memory one: the
activation-memory footprint is set by the MICRO batch, so a remat policy
that only fits at small batch (measured on v5e: ``dots`` fits BERT-large
only at b <= 32, where it beats full remat — BASELINE.md remat ladder)
can be combined with a large effective batch. b128 as 4 x b32(dots)
executes ~1/3 fewer matmul FLOPs than b128 full remat (no forward
replay in the backward), trading them for one fp32 accumulator
(params-sized, ~1.3 GB at BERT-large) and a few grad-add passes.

Loss-scaling composition: scaling is linear, so accumulating SCALED
grads and unscaling the mean once (``amp.apply_gradients``) is exact;
any microbatch overflow survives into the mean and still trips the
scaler's found_inf check.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def split_microbatches(batch, n_micro: int):
    """Reshape every leaf's leading dim ``B`` to ``[n_micro, B/n_micro]``.

    Raises if any leaf's leading dim is not divisible — silent padding
    would change the loss mean.
    """
    def _split(x):
        x = jnp.asarray(x)
        if x.ndim == 0:
            raise ValueError(
                "batch pytree contains a 0-d (scalar) leaf; every leaf "
                "must carry a leading batch dimension to split into "
                "microbatches (hoist per-batch constants out of the "
                "batch pytree, e.g. close over them in loss_fn)")
        if x.shape[0] % n_micro:
            raise ValueError(
                f"leading dim {x.shape[0]} not divisible by "
                f"n_micro={n_micro}")
        return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])

    return jax.tree.map(_split, batch)


def accumulate_gradients(loss_fn, params, batch, n_micro: int,
                         accum_dtype=jnp.float32, with_index: bool = False):
    """Mean loss and mean gradients of ``loss_fn`` over ``n_micro``
    microbatches, accumulated in ``accum_dtype``.

    ``loss_fn(params, microbatch) -> scalar`` where ``microbatch`` has
    the same pytree structure as ``batch`` with leading dim
    ``B / n_micro``. Because every microbatch is the same size and
    ``loss_fn`` returns a per-microbatch mean, the mean of the per-micro
    gradients equals the full-batch gradient exactly (up to summation
    order in ``accum_dtype``).

    ``with_index=True`` calls ``loss_fn(params, microbatch, i)`` with the
    traced microbatch index instead. A loss with dropout MUST use this
    (fold ``i`` into its PRNG key): a key closed over in ``loss_fn`` is
    constant across the scan, so all microbatches would draw the SAME
    dropout mask — correlated in exactly the way accumulation is meant
    to average away.

    jit/shard_map-compatible: the microbatch loop is a ``lax.scan`` whose
    carry is the fp32 accumulator, so XLA compiles ONE microbatch body.
    ``n_micro=1`` degenerates to a plain ``value_and_grad`` call (plus a
    dtype cast of the grads).
    """
    batches, vg, zeros, inv = _accum_prologue(
        loss_fn, params, batch, n_micro, accum_dtype, with_index)

    def body(carry, micro_i):
        loss_acc, g_acc = carry
        micro, i = micro_i
        loss, g = vg(params, micro, i)
        g_acc = jax.tree.map(
            lambda a, x: a + x.astype(accum_dtype), g_acc, g)
        return (loss_acc + loss.astype(jnp.float32), g_acc), None

    (loss_sum, g_sum), _ = lax.scan(
        body, (jnp.float32(0.0), zeros),
        (batches, jnp.arange(n_micro, dtype=jnp.int32)))
    return loss_sum * inv, jax.tree.map(lambda g: g * inv, g_sum)


def _accum_prologue(loss_fn, params, batch, n_micro, accum_dtype,
                    with_index):
    """Shared setup for both accumulation forms: split the batch, wrap the
    loss, and build the fp32 accumulator skeleton from an eval_shape."""
    batches = split_microbatches(batch, n_micro)
    fn = loss_fn if with_index else (lambda p, mb, i: loss_fn(p, mb))
    vg = jax.value_and_grad(fn)
    first = jax.tree.map(lambda x: x[0], batches)
    g_shape = jax.eval_shape(vg, params, first, jnp.int32(0))[1]
    zeros = jax.tree.map(
        lambda s: jnp.zeros(s.shape, accum_dtype), g_shape)
    return batches, vg, zeros, 1.0 / n_micro


def accumulate_and_step(loss_fn, params, state, batch, n_micro: int,
                        apply_fn, accum_dtype=jnp.float32,
                        with_index: bool = False):
    """``accumulate_gradients`` with the optimizer update executed INSIDE
    the scan's final iteration (``lax.cond`` on the microbatch index).

    Why: with the plain form, the fp32 accumulator (params-sized, ~1.3 GB
    at BERT-large) leaves the scan, crosses an XLA region boundary, and
    re-enters the optimizer epilogue — an HBM round-trip between two
    separately-scheduled programs. Folding the update into the loop body
    lets XLA schedule the last microbatch's backward and the parameter
    update as one region. A/B'd against the plain form in
    benchmarks/bench_step_variants.py (``*_optscanN`` variants).

    ``apply_fn(mean_grads, state, params) -> (params, state)`` — the
    optimizer/amp apply_gradients signature. ``loss_fn`` as in
    ``accumulate_gradients`` (use ``with_index=True`` for dropout).
    Returns ``(mean_loss, new_params, new_state)``; every microbatch's
    gradient is taken at the PRE-update parameters, so the result is
    step-equivalent to accumulate-then-apply (up to fusion/scheduling).
    """
    batches, vg, zeros, inv = _accum_prologue(
        loss_fn, params, batch, n_micro, accum_dtype, with_index)

    def body(carry, micro_i):
        params_c, state_c, loss_acc, g_acc = carry
        micro, i = micro_i
        loss, g = vg(params_c, micro, i)
        g_acc = jax.tree.map(
            lambda a, x: a + x.astype(accum_dtype), g_acc, g)

        def update(_):
            mean = jax.tree.map(lambda g: g * inv, g_acc)
            return apply_fn(mean, state_c, params_c)

        params_n, state_n = lax.cond(
            i == n_micro - 1, update, lambda _: (params_c, state_c), None)
        return (params_n, state_n,
                loss_acc + loss.astype(jnp.float32), g_acc), None

    (params, state, loss_sum, _), _ = lax.scan(
        body, (params, state, jnp.float32(0.0), zeros),
        (batches, jnp.arange(n_micro, dtype=jnp.int32)))
    return loss_sum * inv, params, state


def accumulate_and_step_prefetch(loss_fn, state, batch, n_micro: int,
                                 apply_fn, gather_fn,
                                 accum_dtype=jnp.float32,
                                 with_index: bool = False):
    """ZeRO allgather-prefetch form: the parameters are NOT an input —
    they are materialized from the sharded optimizer ``state`` by
    ``gather_fn`` INSIDE the compiled step, immediately before the first
    microbatch's forward.

    Why (arxiv 2004.13336, the weight-update-sharding overlap): a ZeRO
    optimizer whose ``step`` ends with the parameter all-gather serializes
    that collective at the step boundary — it finishes in one XLA program,
    and the next program's first forward waits on all of it. Moving the
    gather here puts it in the SAME program as the forward it feeds, and
    with a chunked gather (``DistributedFusedAdam.gather_params``: one
    independent psum per chunk) the scheduler starts the embedding/early-
    block compute as soon as their low-offset chunks land while later
    chunks are still on the wire. Behind ``APEX_TPU_ZERO_PREFETCH=1`` in
    the bench/dryrun harnesses; call signature:

      ``gather_fn(state) -> params``          (e.g. ``opt.gather_params``)
      ``apply_fn(mean_grads, state, params) -> new_state``  (sharded; e.g.
      ``opt.step_shard`` — NO trailing gather)

    Returns ``(mean_loss, new_state)`` — the params never round-trip
    through the caller, so the next step gathers from the fresh shards.
    Numerically identical to gather-at-step-end (same collectives, same
    summands, different program placement)."""
    params = gather_fn(state)
    loss, mean = accumulate_gradients(
        loss_fn, params, batch, n_micro, accum_dtype, with_index)
    return loss, apply_fn(mean, state, params)
