"""Communication-overlap subsystem: decomposed collective matmul.

The MFU gap left after the kernel-autotuning PR is exposed *collective
latency*: the TP hot paths issue one monolithic ``all_gather`` /
``psum_scatter`` per matmul and depend on XLA's latency-hiding scheduler
to find overlap — which it cannot, because the collective and the matmul
are data-dependent end to end. The classic fix (XLA's own "collective
matmul" rewrite; Wang et al., "Overlap Communication with Dependent
Computation via Decomposition", ASPLOS 2023) is to DECOMPOSE the pair:

  all-gather -> matmul      becomes   N partial matmuls, one per ring
                                      chunk, each overlapped with the
                                      ``ppermute`` that fetches the next
                                      chunk;
  matmul -> reduce-scatter  becomes   N partial matmuls feeding a ring of
                                      shifted partial-sum accumulators.

Each hop's ``ppermute`` is a neighbor DMA on ICI with no data dependence
on the *current* chunk's matmul, so the scheduler genuinely overlaps
them; the exposed time drops from one full collective to one chunk hop.

Both fused ops carry a ``jax.custom_vjp`` whose backward decomposes
symmetrically:

  y = all_gather(x) @ A : dx = decomposed reduce_scatter(dy @ A^T)
                          dA = ring-accumulated  x_chunk^T @ dy_slice
  y = reduce_scatter(x @ A) : dx = decomposed all_gather(dy) @ A^T
                              dA = ring-accumulated x_slice^T @ dy_chunk

so neither direction ever materializes the gathered operand while still
issuing only neighbor DMAs.

Chunking: the local block is split into ``chunks`` pieces which alternate
ring direction (even pieces travel +1, odd pieces -1) — ``chunks=2`` is
the classic bidirectional ring (both ICI link directions busy, per-hop
latency halved), larger values pipeline finer. The count is a registered
tunable (``tuning/registry.py::overlap_tp``) resolved env >
tune-cache > cost-model default, like every other kernel knob. Ragged
splits (chunk count not dividing the local rows) are supported — the last
piece is simply shorter.

Everything here must run inside ``shard_map``/pmap over ``axis``. All
partial matmuls accumulate in fp32 on the MXU (``preferred_element_type``)
exactly like the monolithic path, so decomposed == monolithic to fp32
summation-order tolerance.

Env gates (all off by default; each lever independent):

  APEX_TPU_OVERLAP_TP=1        decomposed collective matmul in the TP/SP
                               hot paths (tensor_parallel/layers.py +
                               mappings.py sequence-parallel region ops)
  APEX_TPU_OVERLAP_TP_CHUNKS=N chunk-count override (beats the tune cache)
  APEX_TPU_QUANTIZED_COMMS=1   int8 quantized DDP/ZeRO collectives
                               (parallel/quantized_collectives.py)
  APEX_TPU_ZERO_PREFETCH=1     ZeRO param allgather overlapped with the
                               first microbatch forward (grad_accum.py +
                               contrib DistributedFusedAdam)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.utils.envvars import env_flag, env_int

__all__ = [
    "all_gather_matmul",
    "matmul_reduce_scatter",
    "overlap_tp_enabled",
    "quantized_comms_enabled",
    "resolve_chunks",
    "ring_all_gather",
    "ring_reduce_scatter",
    "zero_prefetch_enabled",
]


# -- env gates -------------------------------------------------------------

def overlap_tp_enabled() -> bool:
    """Decomposed-collective-matmul gate; read at trace time."""
    return env_flag("APEX_TPU_OVERLAP_TP", default=False)


def quantized_comms_enabled() -> bool:
    """Quantized DDP/ZeRO collectives gate; read at trace time."""
    return env_flag("APEX_TPU_QUANTIZED_COMMS", default=False)


def zero_prefetch_enabled() -> bool:
    """ZeRO allgather-prefetch gate; read at trace time."""
    return env_flag("APEX_TPU_ZERO_PREFETCH", default=False)


# -- chunk-count resolution (env > tune cache > cost model) ---------------

def resolve_chunks(rows_local: int, n_ranks: int, dtype,
                   chunks: int | None = None) -> int:
    """Ring chunk count for a decomposed collective over ``rows_local``
    local rows and an ``n_ranks`` ring. Explicit argument wins (tests /
    direct callers), then ``APEX_TPU_OVERLAP_TP_CHUNKS``, then the tuned
    cache entry for this shape class, then the cost-model default. The
    result is always clamped to [1, rows_local] so a stale cache entry
    degrades instead of crashing."""
    if chunks is None:
        chunks = env_int("APEX_TPU_OVERLAP_TP_CHUNKS")
    if chunks is None:
        from apex_tpu.tuning import cache, shape_class

        entry = cache.lookup(
            shape_class.overlap_key(rows_local, n_ranks, dtype))
        if entry is not None:
            try:
                chunks = int(entry.get("chunks"))
            except (TypeError, ValueError):
                chunks = None
    if chunks is None:
        from apex_tpu.tuning import cost_model

        chunks = cost_model.overlap_chunks_default(rows_local, n_ranks)
    return max(1, min(int(chunks), max(1, rows_local)))


# -- internals -------------------------------------------------------------

def _mm(x, kernel, transpose_kernel: bool = False):
    """Shard-local GEMM, fp32 MXU accumulation, result in operand dtype —
    the same contraction the monolithic layers issue."""
    k = kernel.T if transpose_kernel else kernel
    return jnp.matmul(x, k, preferred_element_type=jnp.float32).astype(
        jnp.result_type(x, kernel))


def _split_points(rows: int, chunks: int):
    """Static piece boundaries: ``chunks`` near-equal pieces, ragged last
    piece when ``chunks`` does not divide ``rows``."""
    chunks = max(1, min(chunks, rows)) if rows else 1
    base = -(-rows // chunks)  # ceil
    offs = list(range(0, rows, base))
    return [(o, min(base, rows - o)) for o in offs]


def _perm(n: int, direction: int):
    return [(i, (i + direction) % n) for i in range(n)]


def _take(x, dim: int, start, size: int):
    return lax.dynamic_slice_in_dim(x, start, size, dim)


def _put(buf, piece, dim: int, start):
    return lax.dynamic_update_slice_in_dim(buf, piece, start, dim)


def _ring_schedule(x, axis: str, dim: int, chunks: int):
    """Yield ``(piece, src_rank, offset)`` for every (hop, piece) of a
    bidirectional ring over ``x``'s rank-local block: the local pieces
    first (src = this rank), then, hop by hop, each remote rank's pieces
    as their ppermutes deliver them. Even pieces travel +1 (arrive from
    rank r-t at hop t), odd pieces travel -1 — per-hop transfers split
    across both ICI link directions. Pure generator of traced values; the
    caller decides what to do with each delivered piece."""
    n = lax.axis_size(axis)
    r = lax.axis_index(axis)
    pieces = [(_take(x, dim, off, size), off)
              for off, size in _split_points(x.shape[dim], chunks)]
    for piece, off in pieces:
        yield piece, r, off
    if n == 1:
        return
    state = [(piece, off, 1 if i % 2 == 0 else -1)
             for i, (piece, off) in enumerate(pieces)]
    for t in range(1, n):
        nxt = []
        for piece, off, d in state:
            piece = lax.ppermute(piece, axis, _perm(n, d))
            yield piece, (r - d * t) % n, off
            nxt.append((piece, off, d))
        state = nxt


# -- decomposed plain collectives (no matmul) ------------------------------

def ring_all_gather(x, axis: str, *, dim: int = 0, chunks: int | None = None):
    """``lax.all_gather(x, axis, axis=dim, tiled=True)`` decomposed into
    chunked ``ppermute`` neighbor hops, so each chunk transfer is an
    independently schedulable DMA instead of one fused collective."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    s_loc = x.shape[dim]
    chunks = resolve_chunks(s_loc, n, x.dtype, chunks)
    shape = list(x.shape)
    shape[dim] = n * s_loc
    out = jnp.zeros(shape, x.dtype)
    for piece, src, off in _ring_schedule(x, axis, dim, chunks):
        out = _put(out, piece, dim, src * s_loc + off)
    return out


def ring_reduce_scatter(x, axis: str, *, dim: int = 0,
                        chunks: int | None = None):
    """``lax.psum_scatter(x, axis, scatter_dimension=dim, tiled=True)``
    decomposed: per-destination partial sums circulate the ring, each hop
    adding the local contribution — the sum arrives fully reduced at its
    owner after n-1 neighbor hops."""
    n = lax.axis_size(axis)
    if n == 1:
        return x
    r = lax.axis_index(axis)
    if x.shape[dim] % n:
        raise ValueError(
            f"dim {dim} size {x.shape[dim]} not divisible by ring size {n}")
    s_out = x.shape[dim] // n
    chunks = resolve_chunks(s_out, n, x.dtype, chunks)

    out = None
    for i, (off, size) in enumerate(_split_points(s_out, chunks)):
        d = 1 if i % 2 == 0 else -1
        # an accumulator starting at rank r lands on rank r + d*(n-1)
        # = r - d after n-1 hops, so it must carry destination r - d's
        # piece; every rank it passes adds its own contribution.
        acc = _take(x, dim, ((r - d) % n) * s_out + off, size)
        for t in range(1, n):
            acc = lax.ppermute(acc, axis, _perm(n, d))
            dest = (r + d * (n - 1 - t)) % n
            acc = acc + _take(x, dim, dest * s_out + off, size)
        piece_out = acc
        if out is None:
            shape = list(x.shape)
            shape[dim] = s_out
            out = jnp.zeros(shape, x.dtype)
        out = _put(out, piece_out, dim, off)
    return out


# -- decomposed all_gather -> matmul --------------------------------------

def _ag_mm_fwd_impl(x, kernel, axis, dim, chunks, transpose_kernel=False):
    n = lax.axis_size(axis)
    s_loc = x.shape[dim]
    out_cols = kernel.shape[0] if transpose_kernel else kernel.shape[1]
    shape = list(x.shape)
    shape[dim] = n * s_loc
    shape[-1] = out_cols
    y = jnp.zeros(shape, jnp.result_type(x, kernel))
    chunks = resolve_chunks(s_loc, n, x.dtype, chunks)
    for piece, src, off in _ring_schedule(x, axis, dim, chunks):
        y = _put(y, _mm(piece, kernel, transpose_kernel), dim,
                 src * s_loc + off)
    return y


def _mm_rs_fwd_impl(x, kernel, axis, dim, chunks, transpose_kernel=False):
    n = lax.axis_size(axis)
    r = lax.axis_index(axis)
    if x.shape[dim] % n:
        raise ValueError(
            f"dim {dim} size {x.shape[dim]} not divisible by ring size {n}")
    s_out = x.shape[dim] // n
    chunks = resolve_chunks(s_out, n, x.dtype, chunks)
    out = None
    for i, (off, size) in enumerate(_split_points(s_out, chunks)):
        d = 1 if i % 2 == 0 else -1
        acc = _mm(_take(x, dim, ((r - d) % n) * s_out + off, size),
                  kernel, transpose_kernel)
        for t in range(1, n):
            acc = lax.ppermute(acc, axis, _perm(n, d))
            dest = (r + d * (n - 1 - t)) % n
            acc = acc + _mm(_take(x, dim, dest * s_out + off, size),
                            kernel, transpose_kernel)
        if out is None:
            shape = list(acc.shape)
            shape[dim] = s_out
            out = jnp.zeros(shape, acc.dtype)
        out = _put(out, acc, dim, off)
    return out


def _ring_weight_grad(circ, indexed, axis, dim, chunks, *, circ_is_lhs,
                      out_dtype):
    """dA accumulated over the ring without materializing the gathered
    operand. ``circ`` is this rank's local block (it circulates);
    ``indexed`` holds full-length rows addressed by the source rank of
    each delivered piece. circ_is_lhs=True computes
    sum_src piece^T @ indexed[src]; False computes
    sum_src indexed[src]^T @ piece. Accumulation is fp32."""
    s_loc = circ.shape[dim]
    n = lax.axis_size(axis)
    chunks = resolve_chunks(s_loc, n, circ.dtype, chunks)

    def flat2d(a):
        # fold every non-contracted dim into rows; contraction dim last
        return a.reshape(-1, a.shape[-1])

    acc = None
    for piece, src, off in _ring_schedule(circ, axis, dim, chunks):
        other = _take(indexed, dim, src * s_loc + off, piece.shape[dim])
        lhs, rhs = (piece, other) if circ_is_lhs else (other, piece)
        part = jnp.matmul(flat2d(lhs).T, flat2d(rhs),
                          preferred_element_type=jnp.float32)
        acc = part if acc is None else acc + part
    return acc.astype(out_dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def all_gather_matmul(x, kernel, axis: str, dim: int = 0,
                      chunks: int | None = None):
    """``all_gather(x, dim) @ kernel`` as one decomposed, overlappable op.

    x: [..., s_loc, ..., k] local block (gather dim ``dim``), kernel:
    [k, m] shard-local weights. Equals
    ``lax.all_gather(x, axis, axis=dim, tiled=True) @ kernel`` to fp32
    summation-order tolerance; the custom backward decomposes into the
    conjugate matmul->reduce-scatter plus a ring-accumulated weight grad
    (never materializing the gathered x)."""
    return _ag_mm_fwd_impl(x, kernel, axis, dim, chunks)


def _ag_mm_fwd(x, kernel, axis, dim, chunks):
    return _ag_mm_fwd_impl(x, kernel, axis, dim, chunks), (x, kernel)


def _ag_mm_bwd(axis, dim, chunks, res, dy):
    x, kernel = res
    # dx = reduce_scatter(dy @ A^T) — the conjugate decomposed pair
    dx = _mm_rs_fwd_impl(dy, kernel, axis, dim, chunks,
                         transpose_kernel=True)
    # dA = gathered(x)^T @ dy, ring-accumulated while x circulates
    dk = _ring_weight_grad(x, dy, axis, dim, chunks, circ_is_lhs=True,
                           out_dtype=kernel.dtype)
    return dx.astype(x.dtype), dk


all_gather_matmul.defvjp(_ag_mm_fwd, _ag_mm_bwd)


# -- decomposed matmul -> reduce-scatter ----------------------------------

@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def matmul_reduce_scatter(x, kernel, axis: str, dim: int = 0,
                          chunks: int | None = None):
    """``reduce_scatter(x @ kernel, dim)`` as one decomposed op.

    x: [..., s, ..., k] with the scatter dim divisible by the ring size,
    kernel: [k, m]. Equals ``lax.psum_scatter(x @ kernel, axis,
    scatter_dimension=dim, tiled=True)`` to fp32 summation-order
    tolerance: each destination's partial sum circulates the ring,
    gaining one locally-computed partial matmul per hop — only the
    destination slice of the product is ever computed per step, so the
    matmul itself is pipelined against the neighbor DMAs."""
    return _mm_rs_fwd_impl(x, kernel, axis, dim, chunks)


def _mm_rs_fwd(x, kernel, axis, dim, chunks):
    return _mm_rs_fwd_impl(x, kernel, axis, dim, chunks), (x, kernel)


def _mm_rs_bwd(axis, dim, chunks, res, dy):
    x, kernel = res
    # d(x@A) = all_gather(dy); dx = all_gather(dy) @ A^T — conjugate pair
    dx = _ag_mm_fwd_impl(dy, kernel, axis, dim, chunks,
                         transpose_kernel=True)
    # dA = x^T @ all_gather(dy), ring-accumulated while dy circulates
    dk = _ring_weight_grad(dy, x, axis, dim, chunks, circ_is_lhs=False,
                           out_dtype=kernel.dtype)
    return dx.astype(x.dtype), dk


matmul_reduce_scatter.defvjp(_mm_rs_fwd, _mm_rs_bwd)
